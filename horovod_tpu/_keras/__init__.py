"""Shared Keras implementation (parity: horovod/_keras/__init__.py).

``create_distributed_optimizer`` uses the reference's dynamic-subclass
trick: build a subclass of the user's optimizer class that allreduces
gradients in ``apply`` before delegating to the original math, then
rebuild the instance ``from_config``.  Works for Keras 3 (``apply`` is
the single funnel ``apply_gradients`` and ``model.fit`` go through).
``make_distributed_class`` exposes the subclass itself for
``hvd.load_model``, which wraps a freshly-loaded optimizer in place
(plain-optimizer checkpoints) and registers ``Distributed*`` names as
custom objects so checkpoints saved from an already-wrapped optimizer
deserialize (the role of the reference's ``horovod_objects`` dict).

``backward_passes_per_step > 1`` implements local gradient aggregation
(parity: horovod/tensorflow/aggregation_helper.py
LocalGradientAggregationHelper): gradients accumulate in tf.Variables
for N micro-steps; every N-th step the accumulated (optionally
averaged) gradient is allreduced and applied, other steps apply zeros
so optimizer bookkeeping (iterations) still advances.  Sparse
IndexedSlices gradients are densified when aggregating.
"""

from __future__ import annotations


def make_distributed_class(base_cls, compression=None, op=None,
                           gradient_predivide_factor=1.0,
                           backward_passes_per_step=1,
                           average_aggregated_gradients=True,
                           process_set=None):
    """Build the allreduce-wrapping subclass of ``base_cls`` (parity:
    the class the reference's create_distributed_optimizer generates,
    factored out so load_model can register it as a custom object)."""
    import tensorflow as tf

    from ..tensorflow import Average, allreduce
    from ..tensorflow.compression import Compression
    from ..tensorflow.mpi_ops import predivide_scaling

    compression = compression or Compression.none
    op = op if op is not None else Average
    bpps = int(backward_passes_per_step)
    if bpps < 1:
        raise ValueError(
            f"backward_passes_per_step must be >= 1, got {bpps}"
        )

    class _DistributedOptimizer(base_cls):
        """Allreduce-averaging subclass (parity: _keras
        create_distributed_optimizer's generated class)."""

        _hvtpu_distributed = True
        _hvtpu_backward_passes_per_step = bpps

        def apply(self, grads, trainable_variables=None, **kwargs):
            grads = list(grads)
            if bpps == 1:
                grads = self._hvtpu_allreduce_grads(grads)
                return super().apply(grads, trainable_variables, **kwargs)
            return self._hvtpu_aggregate_apply(
                grads, trainable_variables, **kwargs
            )

        def _hvtpu_allreduce_grads(self, grads):
            eff_op, prescale, postscale = predivide_scaling(
                op, gradient_predivide_factor, process_set
            )
            out = []
            for g in grads:
                if g is None:
                    out.append(None)
                    continue
                out.append(allreduce(
                    g, op=eff_op, compression=compression,
                    prescale_factor=prescale, postscale_factor=postscale,
                    process_set=process_set,
                ))
            return out

        def _hvtpu_aggregate_apply(self, grads, trainable_variables,
                                   **kwargs):
            """Accumulate for bpps micro-steps; every bpps-th step
            allreduce the (optionally averaged) aggregate and run the
            REAL apply — other steps skip the base apply entirely, so
            stateful optimizers (Adam m/v, momentum) and
            ``iterations`` only advance on aggregate steps (parity:
            LocalGradientAggregationHelper skipping non-sync applies).
            """
            import tensorflow as tf

            if trainable_variables is not None and not self.built:
                self.build(trainable_variables)
            if not hasattr(self, "_hvtpu_acc"):
                self._hvtpu_counter = tf.Variable(
                    0, dtype=tf.int64, trainable=False,
                    name="hvtpu_agg_counter",
                )
                self._hvtpu_acc = [
                    None if g is None else tf.Variable(
                        tf.zeros_like(tf.convert_to_tensor(g)),
                        trainable=False, name=f"hvtpu_agg_{i}",
                    )
                    for i, g in enumerate(grads)
                ]
            self._hvtpu_counter.assign_add(1)
            for acc, g in zip(self._hvtpu_acc, grads):
                if acc is not None and g is not None:
                    acc.assign_add(tf.convert_to_tensor(g))
            is_sync = tf.equal(self._hvtpu_counter % bpps, 0)
            live_acc = [a for a in self._hvtpu_acc if a is not None]

            def do_sync():
                gs = [a.read_value() for a in live_acc]
                if average_aggregated_gradients:
                    gs = [g / float(bpps) for g in gs]
                gs = self._hvtpu_allreduce_grads(gs)
                full, it = [], iter(gs)
                for a in self._hvtpu_acc:
                    full.append(None if a is None else next(it))
                base_cls.apply(self, full, trainable_variables, **kwargs)
                for a in live_acc:
                    a.assign(tf.zeros_like(a))
                return tf.constant(True)

            def no_sync():
                return tf.constant(False)

            tf.cond(is_sync, do_sync, no_sync)
            return None

    _DistributedOptimizer.__name__ = "Distributed" + base_cls.__name__
    return _DistributedOptimizer


def create_distributed_optimizer(optimizer, name=None, compression=None,
                                 op=None, gradient_predivide_factor=1.0,
                                 backward_passes_per_step=1,
                                 average_aggregated_gradients=True,
                                 process_set=None):
    cls = make_distributed_class(
        optimizer.__class__, compression=compression, op=op,
        gradient_predivide_factor=gradient_predivide_factor,
        backward_passes_per_step=backward_passes_per_step,
        average_aggregated_gradients=average_aggregated_gradients,
        process_set=process_set,
    )
    config = optimizer.get_config()
    if name is not None:
        config["name"] = name
    return cls.from_config(config)


def load_model_impl(keras_module, filepath, custom_optimizers=None,
                    custom_objects=None, compression=None):
    """Parity: horovod/_keras/__init__.py ``_load_model`` — load a
    saved keras model and wrap its optimizer in the distributed
    subclass, preserving the saved optimizer state (iterations, slot
    variables).

    Keras 3 resolves BUILT-IN optimizer classes by module path and
    never consults custom_objects for them, so a plain-optimizer
    checkpoint is wrapped AFTER load: swap the live optimizer's class
    to the generated subclass in place (same instance, all restored
    variables untouched), falling back to rebuild-from-config +
    variable copy for optimizers whose layout rejects the swap.  A
    checkpoint saved from an ALREADY-wrapped optimizer records
    ``Distributed<Base>`` under this module — those names ARE looked
    up in custom_objects, so they're pre-registered here (the
    reference's horovod_objects role); ``custom_optimizers`` extends
    that registry with user optimizer classes."""
    horovod_objects = {}
    base = keras_module.optimizers.Optimizer
    opt_classes = [
        cls for name in dir(keras_module.optimizers)
        if isinstance(cls := getattr(keras_module.optimizers, name),
                      type) and issubclass(cls, base) and cls is not base
    ]
    # user classes LAST so a name collision resolves to the user's
    # optimizer (reference horovod_objects.update order)
    opt_classes.extend(custom_optimizers or [])
    for cls in opt_classes:
        horovod_objects["Distributed" + cls.__name__] = \
            make_distributed_class(cls, compression=compression)
    horovod_objects.update(custom_objects or {})
    model = keras_module.models.load_model(
        filepath, custom_objects=horovod_objects)
    opt = getattr(model, "optimizer", None)
    if opt is None or getattr(opt, "_hvtpu_distributed", False):
        return model
    cls = make_distributed_class(opt.__class__,
                                 compression=compression)
    try:
        opt.__class__ = cls
    except TypeError:
        new_opt = cls.from_config(opt.get_config())
        if getattr(opt, "built", False):
            new_opt.build(model.trainable_variables)
            if len(new_opt.variables) != len(opt.variables):
                raise ValueError(
                    f"optimizer rebuild produced "
                    f"{len(new_opt.variables)} variables vs "
                    f"{len(opt.variables)} loaded — refusing a "
                    "partial state copy")
            for dst, src in zip(new_opt.variables, opt.variables):
                dst.assign(src)
        model.optimizer = new_opt
    return model
