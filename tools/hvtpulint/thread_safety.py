"""thread-safety pass: guarded-by lock discipline for annotated classes.

Opt-in via source annotations (grammar in docs/static-analysis.md):

  self._payloads = {}        # hvtpulint: guarded-by(_lock)
  self._undrained = 0        # hvtpulint: guarded-by(_lock, racy-read-ok)
  def _take_payloads(self):  # hvtpulint: requires(_lock)

For every class that declares at least one guarded attribute the pass
computes the set of methods reachable from a *thread entry point* —
a method handed to ``threading.Thread(target=self.X)`` or any public
method (callable from user threads) — by following ``self.m()`` call
edges.  Within reachable methods, every access to a guarded attribute
must be lexically inside ``with self.<lock>:`` or inside a method
annotated ``requires(<lock>)``; calls to requires-methods must
themselves hold the lock.  ``racy-read-ok`` permits bare unlocked
reads (intentional racy fast-path checks) but still flags writes.

``__init__`` is exempt: the object is not yet shared.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from . import Finding, Project

PASS = "thread-safety"

SCAN_DIRS = ("horovod_tpu",)
MARKER = "hvtpulint:"

_GUARDED_RE = re.compile(
    r"self\.(\w+)\s*[:=].*#\s*hvtpulint:\s*guarded-by\(([^)]*)\)")
_REQUIRES_RE = re.compile(r"#\s*hvtpulint:\s*requires\((\w+)\)")
_ANY_ANNOT_RE = re.compile(r"#\s*hvtpulint:\s*(guarded-by|requires)\b")


class _Guard:
    def __init__(self, lock: str, racy_read_ok: bool, line: int):
        self.lock = lock
        self.racy_read_ok = racy_read_ok
        self.line = line


def _parse_guard(args: str, line: int) -> Optional[_Guard]:
    parts = [p.strip() for p in args.split(",") if p.strip()]
    if not parts:
        return None
    lock = parts[0]
    flags = set(parts[1:])
    return _Guard(lock, "racy-read-ok" in flags, line)


def _method_requires(lines: List[str], fn: ast.FunctionDef) -> Optional[str]:
    """requires(<lock>) on the def line(s) or the line just above."""
    start = max(fn.lineno - 2, 0)
    end = fn.body[0].lineno - 1 if fn.body else fn.lineno
    for raw in lines[start:end]:
        m = _REQUIRES_RE.search(raw)
        if m:
            return m.group(1)
    return None


def _self_attr(node: ast.expr) -> Optional[str]:
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


class _MethodScan(ast.NodeVisitor):
    """Collects guarded-attribute accesses with the lock-held context,
    self-call edges, and thread targets for one method body."""

    def __init__(self, guards: Dict[str, _Guard], held: Set[str]):
        self.guards = guards
        self.base_held = held
        self.held: Set[str] = set(held)
        # (attr, lineno, is_write, held-locks-at-site)
        self.accesses: List[Tuple[str, int, bool, Set[str]]] = []
        # (callee, lineno, held-locks-at-site)
        self.calls: List[Tuple[str, int, Set[str]]] = []
        self.thread_targets: Set[str] = set()

    def visit_With(self, node: ast.With):
        saved = set(self.held)
        for item in node.items:
            self.visit(item.context_expr)
            attr = _self_attr(item.context_expr)
            if attr is not None:
                self.held.add(attr)
        for stmt in node.body:
            self.visit(stmt)
        self.held = saved

    def visit_Attribute(self, node: ast.Attribute):
        attr = _self_attr(node)
        if attr is not None and attr in self.guards:
            is_write = isinstance(node.ctx, (ast.Store, ast.Del))
            self.accesses.append((attr, node.lineno, is_write,
                                  set(self.held)))
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        attr = _self_attr(node.func)
        if attr is not None:
            self.calls.append((attr, node.lineno, set(self.held)))
        # threading.Thread(target=self._loop, ...)
        fname = node.func.attr if isinstance(node.func, ast.Attribute) \
            else (node.func.id if isinstance(node.func, ast.Name) else None)
        if fname == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    tgt = _self_attr(kw.value)
                    if tgt is not None:
                        self.thread_targets.add(tgt)
        self.generic_visit(node)

    # Nested defs/lambdas may run on yet another thread (callbacks);
    # keep visiting them but with no locks assumed held.
    def _visit_nested(self, node):
        saved, saved_base = self.held, self.base_held
        self.held, self.base_held = set(), set()
        self.generic_visit(node)
        self.held, self.base_held = saved, saved_base

    def visit_FunctionDef(self, node: ast.FunctionDef):
        self._visit_nested(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef):
        self._visit_nested(node)

    def visit_Lambda(self, node: ast.Lambda):
        self._visit_nested(node)


def _check_class(project: Project, rel: str, src: str,
                 cls: ast.ClassDef) -> List[Finding]:
    lines = src.splitlines()
    findings: List[Finding] = []

    # Guarded-attribute declarations anywhere in the class source span.
    guards: Dict[str, _Guard] = {}
    end = cls.end_lineno or len(lines)
    for lineno in range(cls.lineno, min(end, len(lines)) + 1):
        m = _GUARDED_RE.search(lines[lineno - 1])
        if not m:
            continue
        guard = _parse_guard(m.group(2), lineno)
        if guard is None:
            findings.append(Finding(
                PASS, rel, lineno, f"{cls.name}:bad-annotation:{m.group(1)}",
                "guarded-by() needs a lock attribute name"))
            continue
        guards[m.group(1)] = guard
    if not guards:
        return findings

    methods = {n.name: n for n in cls.body
               if isinstance(n, ast.FunctionDef)}
    requires: Dict[str, str] = {}
    for name, fn in methods.items():
        lock = _method_requires(lines, fn)
        if lock is not None:
            requires[name] = lock

    # Scan every method once.
    scans: Dict[str, _MethodScan] = {}
    thread_targets: Set[str] = set()
    for name, fn in methods.items():
        held = {requires[name]} if name in requires else set()
        scan = _MethodScan(guards, held)
        for stmt in fn.body:
            scan.visit(stmt)
        scans[name] = scan
        thread_targets |= scan.thread_targets

    # Reachability from thread entry points over self-call edges.
    entries = set(thread_targets)
    entries |= {n for n in methods
                if not n.startswith("_") or n in thread_targets}
    entries.discard("__init__")
    reachable: Set[str] = set()
    frontier = [e for e in entries if e in methods]
    while frontier:
        name = frontier.pop()
        if name in reachable:
            continue
        reachable.add(name)
        for callee, _, _ in scans[name].calls:
            if callee in methods and callee not in reachable:
                frontier.append(callee)
    reachable.discard("__init__")

    for name in sorted(reachable):
        scan = scans[name]
        for attr, lineno, is_write, held in scan.accesses:
            guard = guards[attr]
            if guard.lock in held:
                continue
            if guard.racy_read_ok and not is_write:
                continue
            kind = "write to" if is_write else "read of"
            findings.append(Finding(
                PASS, rel, lineno, f"{cls.name}.{name}:{attr}",
                f"{kind} self.{attr} without holding self.{guard.lock} "
                f"(declared guarded-by({guard.lock}) at line {guard.line}; "
                f"reachable from a thread entry point via {name}())"))
        for callee, lineno, held in scan.calls:
            lock = requires.get(callee)
            if lock is not None and lock not in held:
                findings.append(Finding(
                    PASS, rel, lineno, f"{cls.name}.{name}:call:{callee}",
                    f"call to self.{callee}() which requires({lock}) "
                    f"without holding self.{lock}"))
    return findings


def scan_file(project: Project, path) -> List[Finding]:
    src = project.read(path)
    if src is None or MARKER not in src:
        return []
    tree = project.parse(path)
    if tree is None:
        return []
    findings: List[Finding] = []
    rel = project.rel(path)
    # Annotations outside any class would be silently dead — flag them.
    class_spans = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            class_spans.append((node.lineno, node.end_lineno or node.lineno))
            findings.extend(_check_class(project, rel, src, node))
    for lineno, line in enumerate(src.splitlines(), 1):
        if _ANY_ANNOT_RE.search(line) and not any(
                a <= lineno <= b for a, b in class_spans):
            findings.append(Finding(
                PASS, rel, lineno, f"orphan-annotation:{lineno}",
                "hvtpulint annotation outside a class body has no effect"))
    return findings


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for path in project.py_files(*SCAN_DIRS):
        findings.extend(scan_file(project, path))
    return findings
