// Gaussian-process regression + Expected Improvement for the autotuner.
//
// Parity surface: horovod/common/optim/gaussian_process.cc
// (GaussianProcessRegressor: RBF kernel, Cholesky solve, posterior
// mean/std) and the EI acquisition of bayesian_optimization.cc
// (BayesianOptimization::NextSample) — the reference keeps this math
// in native code (Eigen); here it is a dependency-free C++17
// implementation with the same structure: y standardisation, RBF Gram
// matrix with jitter, Cholesky factorisation, two triangular solves
// for alpha, posterior variance via the factor solve, and the
// closed-form EI with the z = imp/sigma split.
//
// The Python twin (obs/gaussian_process.py) remains the executable
// spec; tests/test_native.py cross-checks the two to ~1e-10.

#include <cmath>
#include <cstdint>
#include <vector>

namespace {

// Dense column-ordered lower-triangular Cholesky: A = L L^T, in place
// on a row-major n*n buffer.  Returns false if A is not positive
// definite.
bool cholesky(std::vector<double>& a, int64_t n) {
  for (int64_t j = 0; j < n; ++j) {
    double d = a[j * n + j];
    for (int64_t k = 0; k < j; ++k) d -= a[j * n + k] * a[j * n + k];
    if (d <= 0.0) return false;
    const double l = std::sqrt(d);
    a[j * n + j] = l;
    for (int64_t i = j + 1; i < n; ++i) {
      double s = a[i * n + j];
      for (int64_t k = 0; k < j; ++k) s -= a[i * n + k] * a[j * n + k];
      a[i * n + j] = s / l;
    }
    for (int64_t k = j + 1; k < n; ++k) a[j * n + k] = 0.0;
  }
  return true;
}

// Solve L x = b (forward) in place.
void solve_lower(const std::vector<double>& l, int64_t n,
                 std::vector<double>& b) {
  for (int64_t i = 0; i < n; ++i) {
    double s = b[i];
    for (int64_t k = 0; k < i; ++k) s -= l[i * n + k] * b[k];
    b[i] = s / l[i * n + i];
  }
}

// Solve L^T x = b (backward) in place.
void solve_upper_t(const std::vector<double>& l, int64_t n,
                   std::vector<double>& b) {
  for (int64_t i = n - 1; i >= 0; --i) {
    double s = b[i];
    for (int64_t k = i + 1; k < n; ++k) s -= l[k * n + i] * b[k];
    b[i] = s / l[i * n + i];
  }
}

double rbf(const double* a, const double* b, int64_t d,
           double length_scale, double signal_variance) {
  double d2 = 0.0;
  for (int64_t k = 0; k < d; ++k) {
    const double diff = a[k] - b[k];
    d2 += diff * diff;
  }
  return signal_variance *
         std::exp(-0.5 * d2 / (length_scale * length_scale));
}

double norm_pdf(double z) {
  return std::exp(-0.5 * z * z) / std::sqrt(2.0 * M_PI);
}

double norm_cdf(double z) { return 0.5 * (1.0 + std::erf(z / std::sqrt(2.0))); }

}  // namespace

extern "C" {

// Fit a GP on (xs: n x d, ys: n) and write the posterior (mean, std)
// at (cand: m x d) into out_mu / out_sigma (each m).  Mirrors
// GaussianProcess.fit + .predict in obs/gaussian_process.py: y is
// standardised, the kernel gets `noise` jitter on the diagonal, and
// the posterior is de-standardised.  Returns 0 on success, -1 if the
// Gram matrix is not positive definite.
int hvt_gp_predict(const double* xs, const double* ys, int64_t n, int64_t d,
                   const double* cand, int64_t m, double length_scale,
                   double noise, double signal_variance, double* out_mu,
                   double* out_sigma) {
  // standardise y
  double mean = 0.0;
  for (int64_t i = 0; i < n; ++i) mean += ys[i];
  mean /= static_cast<double>(n);
  double var = 0.0;
  for (int64_t i = 0; i < n; ++i) {
    const double c = ys[i] - mean;
    var += c * c;
  }
  double std_ = std::sqrt(var / static_cast<double>(n));
  if (std_ == 0.0) std_ = 1.0;

  // K + noise I, factor
  std::vector<double> k(static_cast<size_t>(n) * n);
  for (int64_t i = 0; i < n; ++i)
    for (int64_t j = 0; j < n; ++j)
      k[i * n + j] = rbf(xs + i * d, xs + j * d, d, length_scale,
                         signal_variance) +
                     (i == j ? noise : 0.0);
  if (!cholesky(k, n)) return -1;

  // alpha = K^-1 yn via two triangular solves
  std::vector<double> alpha(n);
  for (int64_t i = 0; i < n; ++i) alpha[i] = (ys[i] - mean) / std_;
  solve_lower(k, n, alpha);
  solve_upper_t(k, n, alpha);

  std::vector<double> ks(n);
  for (int64_t c = 0; c < m; ++c) {
    for (int64_t i = 0; i < n; ++i)
      ks[i] = rbf(cand + c * d, xs + i * d, d, length_scale,
                  signal_variance);
    double mu = 0.0;
    for (int64_t i = 0; i < n; ++i) mu += ks[i] * alpha[i];
    // v = L^-1 ks ; var = prior_diag - v.v
    solve_lower(k, n, ks);
    double vv = 0.0;
    for (int64_t i = 0; i < n; ++i) vv += ks[i] * ks[i];
    double v = signal_variance - vv;
    if (v < 1e-12) v = 1e-12;
    out_mu[c] = mu * std_ + mean;
    out_sigma[c] = std::sqrt(v) * std_;
  }
  return 0;
}

// Expected Improvement over candidates given observations; the
// fit+predict+EI pipeline of BayesianOptimizer.suggest in one call.
// Returns 0 on success, -1 on a non-PD Gram matrix.
int hvt_gp_expected_improvement(const double* xs, const double* ys,
                                int64_t n, int64_t d, const double* cand,
                                int64_t m, double length_scale, double noise,
                                double signal_variance, double best_y,
                                double xi, double* out_ei) {
  std::vector<double> mu(m), sigma(m);
  const int rc = hvt_gp_predict(xs, ys, n, d, cand, m, length_scale, noise,
                                signal_variance, mu.data(), sigma.data());
  if (rc != 0) return rc;
  for (int64_t c = 0; c < m; ++c) {
    const double imp = mu[c] - best_y - xi;
    if (sigma[c] < 1e-12) {
      out_ei[c] = 0.0;
      continue;
    }
    const double z = imp / sigma[c];
    out_ei[c] = imp * norm_cdf(z) + sigma[c] * norm_pdf(z);
  }
  return 0;
}

}  // extern "C"
