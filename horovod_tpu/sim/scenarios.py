"""Named chaos scenarios: the real control plane at virtual scale.

Each scenario builds a :class:`~.kernel.SimKernel` + \
:class:`~.fabric.SimFabric`, spawns one task per virtual rank running
REAL framework code (``State.commit`` / ``_DrainCoordinator`` /
``core.audit.verify`` / ``AmortizedStallInspector`` /
``EagerController`` over ``KVTransport``), injects chaos through
``core/faults.py`` clauses bound to virtual ranks, runs to quiescence,
asserts the protocol's invariants, and returns
``{"scenario", "ranks", "seed", "stats", "events"}`` where ``stats``
carries per-phase virtual-time numbers and ``events`` is the
deterministic replay log (same seed ⇒ byte-identical).

Catalog (also ``python -m tools.hvtpusim list``):

========================  =============================================
steady-drain              one rank preempted mid-run (fault action
                          ``preempt``); full notice → plan → agreed
                          drain commit → exit-79/DrainInterrupt cycle,
                          with a per-commit audit allgather as the
                          lockstep barrier.  Asserts exactly-once
                          drain-commit accounting on every rank.
thundering-rendezvous     every rank calls the audit digest-allgather
                          simultaneously — the post-restart rendezvous
                          verification storm.  Asserts zero divergence
                          (or pinpointed divergence for a planted one).
rolling-preemption        repeated waves: preempt → drain → survivor
                          re-election (dense rank renumbering over the
                          KV) → next generation, shrinking the world
                          each wave.
kill-blacklist            a rank dies hard (fault action ``kill``);
                          a virtual driver records the failure in the
                          REAL HostManager: strike, cooldown exclusion,
                          then cooldown-expiry readmission on the
                          virtual clock.
kv-brownout               a window of injected ``kv.get``/``kv.put``
                          UNAVAILABLE faults plus dropped heartbeats
                          under live audits and heartbeat evaluation.
                          Asserts the retry plane absorbs the brownout:
                          no false stall failure, all audits complete.
straggler-tail            lockstep negotiation (manual controllers over
                          KVTransport) with one rank's link 20× slower;
                          the cycle-time distribution shows the tail.
stream-matrix             the streamed (barrier-free) plane with
                          schedule prediction warmed up, then the
                          split-burst × mispredict-recovery ×
                          membership-change (staggered shutdown)
                          interleavings.  Asserts every future
                          resolves and post-recovery cycles are clean.
multi-job-arbiter         the REAL FleetArbiter sharing one pool
                          between a low- and a high-priority job:
                          injected preemption, then a priority
                          preemption via the graceful-drain channel
                          (exit-79 victims, zero charged restarts),
                          gang start of the high job, and per-job
                          exactly-once sample accounting.
checkpoint-storm          every rank runs the real durable commit
                          protocol (core/durable.py) with injected
                          ``ckpt.write`` torn/bitflip damage on two
                          victims' final commit, then storms the
                          restore path: manifest verification + the
                          KV restore quorum.  Asserts the agreed
                          restore point is the min over per-rank
                          maxima, durable everywhere, and damage only
                          ever lowers the pick.
anomaly-detection         one rank's link degraded mid-run via
                          ``set_link``; the real AnomalyEngine, fed
                          per-cycle arrival skew, must raise a
                          straggler incident naming exactly that rank.
                          Measures detection latency (virtual s).
coordinator-loss          the coordination service's host dies: every
                          rank's KV lease expires (real FencedKV
                          self-fencing, virtual exit 89), the virtual
                          driver blacklists the host and re-elects the
                          coordinator over surviving slots, and gen 1
                          replays each rank's journaled durable keys
                          into the fresh fabric.  Measures detect and
                          fence-to-recover latency.
partition-storm           a burst of ``partition(MS)`` fault windows
                          silences several ranks' coordination
                          traffic; peers classify them partition
                          SUSPECTS (stall blame held), most recover,
                          and the one leased victim self-fences.
                          Asserts no false stall failure.
fleet-service             the production front door: a seeded
                          multi-tenant submission storm through the
                          REAL indexed journal into the REAL arbiter
                          (quotas, fair share, starvation guard,
                          torus placement, truthful backpressure)
                          with an injected arbiter crash that rolls
                          the intake cursor back mid-storm.  Asserts
                          exactly-once intake, budget-bounded per-tick
                          cost, named quota rejections, and a bounded
                          post-aging wait for the starved probe gang.
lossy-link                the wire plane under seeded per-send loss,
                          deterministic ``wire.send:drop`` faults and
                          a link flap window: every failed ring
                          collective runs the REAL consensus
                          abort-and-retry (comm/wirefault.py) under
                          attempt-tagged wire keys, and the link-
                          health map reroutes the ring around the
                          flapping rank.  Asserts zero restarts, zero
                          torn collectives, and bitwise-clean retried
                          results; ``baseline=True`` disables retries
                          and must poison the job instead.
compression-negotiation   mixed-precision negotiation through the
                          real controller: a dense fp32 allreduce
                          plus an int8-compressed sidecar per cycle.
                          Asserts every rank sees the identical
                          negotiated schedule with the sidecar at the
                          int8 wire dtype, never fused into the fp32
                          burst.
========================  =============================================
"""

from __future__ import annotations

import contextlib
import json
import os
from typing import Dict, Iterator, List, Optional

from .context import RankContext
from .fabric import SimFabric
from .kernel import SimKernel, VirtualExit
from .workers import (SimElasticState, WorldView, elect_and_assign,
                      patch_data_plane)

__all__ = ["SCENARIOS", "run_scenario"]

_DEF_BUDGET_S = 36000.0  # virtual-time ceiling: livelock tripwire


@contextlib.contextmanager
def _env(**overrides: Optional[str]) -> Iterator[None]:
    """Scoped os.environ overrides (None deletes)."""
    saved = {k: os.environ.get(k) for k in overrides}
    for k, v in overrides.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    try:
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _fresh(ranks: int, seed: int) -> tuple:
    from ..core import audit as core_audit

    core_audit.reset_sequences()
    kernel = SimKernel(seed=seed)
    fabric = SimFabric(kernel)
    return kernel, fabric


def _pct(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1)))
    return sorted_vals[idx]


def _result(name: str, ranks: int, seed: int, kernel: SimKernel,
            stats: Dict) -> Dict:
    return {"scenario": name, "ranks": ranks, "seed": seed,
            "stats": stats, "events": kernel.events}


# ---------------------------------------------------------------------------
# thundering-rendezvous
# ---------------------------------------------------------------------------

def thundering_rendezvous(ranks: int, seed: int = 0, *,
                          diverge_rank: Optional[int] = None) -> Dict:
    """Every rank runs the REAL audit digest-allgather at once — the
    restart-rendezvous verification storm.  ``diverge_rank`` plants one
    divergent payload and asserts the audit names exactly that rank."""
    from ..core import audit as core_audit

    kernel, fabric = _fresh(ranks, seed)
    done_t: Dict[int, float] = {}
    reports: Dict[int, dict] = {}

    def make(rank: int):
        def body():
            world = WorldView(rank, ranks, 0)
            client = fabric.client(rank, caps="str")
            value = 41.0 if rank == diverge_rank else 7.0
            tree = {"epoch": 3, "w": [value, float(ranks)]}
            reports[rank] = core_audit.verify(
                tree, label="rendezvous", action="warn",
                timeout_s=600.0, client=client, world=world)
            done_t[rank] = kernel.now
            kernel.log("rendezvous_done", rank=rank)
        return body

    for r in range(ranks):
        kernel.spawn(f"rank{r}", make(r))
    kernel.run(max_virtual_s=_DEF_BUDGET_S)

    assert len(done_t) == ranks, "some ranks never finished rendezvous"
    for rank, report in reports.items():
        if diverge_rank is None:
            assert not report["divergent"], (
                f"false divergence on rank {rank}: {report}")
        else:
            assert report["ranks"] == [diverge_rank], (
                f"rank {rank} blamed {report['ranks']}, "
                f"expected [{diverge_rank}]")
    times = sorted(done_t.values())
    stats = {"phases": {"rendezvous": {
        "virtual_s": times[-1],
        "p50_s": _pct(times, 0.50),
        "p99_s": _pct(times, 0.99),
    }}, "kv_ops": dict(fabric.ops)}
    return _result("thundering-rendezvous", ranks, seed, kernel, stats)


# ---------------------------------------------------------------------------
# steady-drain
# ---------------------------------------------------------------------------

def steady_drain(ranks: int, seed: int = 0, *, steps: int = 6,
                 depart_rank: Optional[int] = None,
                 grace_s: float = 60.0, compute_s: float = 0.05,
                 durable_every: int = 3) -> Dict:
    """One rank preempted (fault action ``preempt`` at the
    ``worker.step`` site): the full drain protocol over the simulated
    KV, with the per-commit audit allgather acting as the lockstep
    barrier real training gets from its collectives."""
    from ..core.exceptions import DrainInterrupt
    from ..core.preempt import DRAIN_EXIT_CODE
    from ..core import retry as core_retry

    if depart_rank is None:
        depart_rank = max(1, ranks // 3)
    spec = f"worker.step:preempt@rank={depart_rank},times=1"
    kernel, fabric = _fresh(ranks, seed)
    records: Dict[int, dict] = {}
    notice_t: List[float] = []
    drain_t: List[float] = []

    def make(rank: int):
        def body():
            client = fabric.client(rank, caps="dir")
            kv = core_retry.resilient_kv(client, rank=rank)
            ctx = RankContext(
                kernel, rank, ranks, fault_spec=spec, generation=0,
                drain_client=kv, drain_grace_s=grace_s, with_drain=True)
            state = SimElasticState(
                client=client, world=WorldView(rank, ranks, 0), step=0)
            state.set_commit_policy(durable_every)
            rec = records[rank] = {"outcome": "finished"}
            with ctx.activate():
                try:
                    for _ in range(steps):
                        ctx.check_exit()
                        ctx.coordinator._poll_once()
                        kernel.sleep(compute_s)
                        state.step += 1
                        state.commit()
                except DrainInterrupt as e:
                    rec["outcome"] = "drain_interrupt"
                    rec["peer"] = e.rank
                    drain_t.append(kernel.now)
                    kernel.log("drain_interrupt", rank=rank,
                               commit=state._commit_count)
                except VirtualExit:
                    rec["outcome"] = "virtual_exit"
                    drain_t.append(kernel.now)
                    kernel.log("drain_exit", rank=rank,
                               commit=state._commit_count)
                    raise
                finally:
                    rec["commits"] = state._commit_count
                    rec["durable"] = state.durable_commits
                    if rank == depart_rank and ctx.coordinator._notice_t:
                        notice_t.append(ctx.coordinator._notice_t)
        return body

    with _env(HVTPU_AUDIT_EVERY="1", HVTPU_AUDIT_ACTION="abort",
              HVTPU_ELASTIC_STATE_DIR=None):
        tasks = {r: kernel.spawn(f"rank{r}", make(r))
                 for r in range(ranks)}
        kernel.run(max_virtual_s=_DEF_BUDGET_S)

    departed = tasks[depart_rank]
    assert departed.exit_code == DRAIN_EXIT_CODE, (
        f"departing rank exited {departed.exit_code}, "
        f"expected {DRAIN_EXIT_CODE}")
    survivor_commits = {records[r]["commits"]
                        for r in range(ranks) if r != depart_rank}
    assert len(survivor_commits) == 1, (
        f"survivors drained at different commits: {survivor_commits}")
    drain_commit = survivor_commits.pop()
    assert records[depart_rank]["commits"] == drain_commit, (
        "departing rank's drain commit disagrees with the survivors'")
    # exactly-once durable accounting: every rank wrote the periodic
    # durable commits PLUS the promoted drain commit, exactly once
    expected_durable = sum(
        1 for c in range(1, drain_commit + 1)
        if c % durable_every == 0 or c == drain_commit)
    for r in range(ranks):
        assert records[r]["durable"] == expected_durable, (
            f"rank {r} wrote {records[r]['durable']} durable commits, "
            f"expected {expected_durable}")
        assert records[r]["outcome"] == (
            "virtual_exit" if r == depart_rank else "drain_interrupt")
    latency = (max(drain_t) - notice_t[0]) if notice_t and drain_t else 0.0
    stats = {"phases": {
        "steady": {"virtual_s": round(notice_t[0], 6) if notice_t else 0.0},
        "drain": {
            "drain_commit": drain_commit,
            "notice_to_commit_s": round(latency, 6),
            "grace_s": grace_s,
            "virtual_s": round(max(drain_t) if drain_t else 0.0, 6),
        }}, "kv_ops": dict(fabric.ops)}
    return _result("steady-drain", ranks, seed, kernel, stats)


# ---------------------------------------------------------------------------
# rolling-preemption
# ---------------------------------------------------------------------------

def rolling_preemption(ranks: int, seed: int = 0, *, waves: int = 2,
                       steps_per_gen: int = 4, grace_s: float = 60.0,
                       compute_s: float = 0.02) -> Dict:
    """``waves`` preemption waves: each generation one rank is
    preempted at its first commit, the world drains, survivors
    re-elect dense ranks over the KV, and the next generation resumes
    from the drain commit — the restart-based elastic resize at
    protocol level."""
    from ..core.exceptions import DrainInterrupt
    from ..core.preempt import DRAIN_EXIT_CODE
    from ..core import retry as core_retry

    kernel, fabric = _fresh(ranks, seed)
    # deterministic victim schedule over PHYSICAL ids (never phys 0 —
    # keeping one stable observer makes the log easier to read)
    pool = list(range(1, ranks))
    rng = kernel.rng("victims")
    victims = [pool.pop(rng.randrange(len(pool))) for _ in range(waves)]
    records: Dict[int, dict] = {}
    wave_stats: List[dict] = []
    gen_members: Dict[int, set] = {0: set(range(ranks))}

    def make(phys: int):
        def body():
            rec = records[phys] = {"gens": 0, "final_rank": phys,
                                   "resumed_step": 0}
            rank, size = phys, ranks
            step_base = 0
            for gen in range(waves + 1):
                victim_here = gen < waves and phys == victims[gen]
                spec = (f"worker.step:preempt@rank={rank},times=1"
                        if victim_here else "")
                client = fabric.client(phys, caps="dir")
                kv = core_retry.resilient_kv(client, rank=rank)
                ctx = RankContext(
                    kernel, rank, size, fault_spec=spec, generation=gen,
                    drain_client=kv, drain_grace_s=grace_s,
                    with_drain=True)
                state = SimElasticState(
                    client=client, world=WorldView(rank, size, gen),
                    step=step_base)
                state.set_commit_policy(2)
                drained_peer = None
                with ctx.activate():
                    try:
                        for _ in range(steps_per_gen):
                            ctx.check_exit()
                            ctx.coordinator._poll_once()
                            kernel.sleep(compute_s)
                            state.step += 1
                            state.commit()
                    except DrainInterrupt as e:
                        drained_peer = e.rank
                    except VirtualExit:
                        kernel.log("departed", gen=gen, phys=phys,
                                   rank=rank,
                                   commit=state._commit_count)
                        raise
                rec["gens"] = gen + 1
                step_base = state._saved["step"]
                rec["resumed_step"] = step_base
                if drained_peer is None:
                    # final generation ran to completion
                    rec["final_rank"] = rank
                    continue
                kernel.log("drain_observed", gen=gen, phys=phys,
                           rank=rank, peer=drained_peer,
                           commit=state._commit_count)
                survivors = [r for r in range(size) if r != drained_peer]
                assignment = elect_and_assign(
                    kv, rank, survivors, generation=gen + 1)
                rank = assignment[rank]
                size = len(survivors)
                rec["final_rank"] = rank
        return body

    with _env(HVTPU_AUDIT_EVERY="1", HVTPU_AUDIT_ACTION="abort",
              HVTPU_ELASTIC_STATE_DIR=None):
        tasks = {p: kernel.spawn(f"phys{p}", make(p))
                 for p in range(ranks)}
        kernel.run(max_virtual_s=_DEF_BUDGET_S)

    for w, victim in enumerate(victims):
        assert tasks[victim].exit_code == DRAIN_EXIT_CODE, (
            f"wave-{w} victim phys{victim} exited "
            f"{tasks[victim].exit_code}, expected {DRAIN_EXIT_CODE}")
    survivors_phys = [p for p in range(ranks) if p not in victims]
    final_size = ranks - waves
    final_ranks = sorted(records[p]["final_rank"] for p in survivors_phys)
    assert final_ranks == list(range(final_size)), (
        f"survivor renumbering not dense: {final_ranks}")
    for p in survivors_phys:
        assert records[p]["gens"] == waves + 1, (
            f"phys{p} completed {records[p]['gens']} generations, "
            f"expected {waves + 1}")
    resumed = {records[p]["resumed_step"] for p in survivors_phys}
    assert len(resumed) == 1, (
        f"survivors resumed from different steps: {resumed}")
    stats = {"phases": {
        "waves": {"count": waves, "victims_phys": victims},
        "final": {"world_size": final_size,
                  "virtual_s": round(kernel.now, 6),
                  "resumed_step": resumed.pop()},
    }, "kv_ops": dict(fabric.ops)}
    return _result("rolling-preemption", ranks, seed, kernel, stats)


# ---------------------------------------------------------------------------
# kill-blacklist
# ---------------------------------------------------------------------------

class _StaticDiscovery:
    """Discovery stub for the virtual driver: a fixed host->slots map
    (the HostManager under test is real; only the shell-out is fake)."""

    def __init__(self, hosts: Dict[str, int]):
        self.hosts = dict(hosts)

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        return dict(self.hosts)


def kill_blacklist(ranks: int, seed: int = 0, *, steps: int = 6,
                   kill_rank: Optional[int] = None,
                   slots_per_host: int = 8,
                   cooldown_s: float = 30.0,
                   compute_s: float = 0.02) -> Dict:
    """A rank dies hard (``kill`` fault): a virtual driver task feeds
    the failure to the REAL HostManager and asserts strike → cooldown
    exclusion → cooldown-expiry readmission, all on the virtual
    clock."""
    from ..elastic.discovery import HostManager

    if kill_rank is None:
        kill_rank = max(1, ranks // 2)
    spec = f"worker.step:kill@rank={kill_rank},times=1"
    kernel, fabric = _fresh(ranks, seed)
    hosts = {f"host{h}": slots_per_host
             for h in range((ranks + slots_per_host - 1)
                            // slots_per_host)}
    kill_host = f"host{kill_rank // slots_per_host}"
    records: Dict[int, dict] = {}
    driver_log: List[dict] = []

    def make(rank: int):
        def body():
            client = fabric.client(rank, caps="dir")
            ctx = RankContext(kernel, rank, ranks, fault_spec=spec,
                              generation=0)
            state = SimElasticState(
                client=client, world=WorldView(rank, ranks, 0), step=0)
            rec = records[rank] = {}
            with ctx.activate():
                try:
                    for _ in range(steps):
                        ctx.check_exit()
                        kernel.sleep(compute_s)
                        state.step += 1
                        state.commit()
                finally:
                    rec["commits"] = state._commit_count
                    rec["durable"] = state.durable_commits
        return body

    def driver():
        hm = HostManager(_StaticDiscovery(hosts),
                         cooldown_base_s=cooldown_s,
                         cooldown_max_s=8 * cooldown_s)
        hm.refresh()
        full_slots = hm.available_slots()
        # wait for the kill
        while not (tasks[kill_rank].done
                   and tasks[kill_rank].exit_code == 1):
            kernel.sleep(0.5)
        cooldown = hm.blacklist_host(kill_host)
        hm.refresh()
        driver_log.append({
            "t": kernel.now, "event": "blacklisted",
            "host": kill_host, "cooldown_s": cooldown,
            "strikes": hm.strikes(kill_host),
            "slots": hm.available_slots(),
        })
        assert kill_host in hm.blacklisted_now()
        assert hm.available_slots() == full_slots - hosts[kill_host]
        if len(hosts) > 1:
            assert not hm.exhausted(min_np=1)
        # cooldown expiry on the virtual clock: the host is probed and
        # readmitted
        wait = hm.next_readmission_s()
        assert wait is not None and wait <= cooldown
        kernel.sleep(wait + 0.001)
        changed = hm.refresh()
        driver_log.append({
            "t": kernel.now, "event": "readmitted", "host": kill_host,
            "changed": changed, "slots": hm.available_slots(),
        })
        assert changed and hm.available_slots() == full_slots
        assert hm.blacklisted_now() == []
        assert hm.strikes(kill_host) == 1  # strike persists past cooldown
        hm.record_success(kill_host)
        assert hm.strikes(kill_host) == 0
        kernel.log("driver_done", blacklist_events=len(driver_log))

    with _env(HVTPU_AUDIT_EVERY="0", HVTPU_ELASTIC_STATE_DIR=None):
        tasks = {r: kernel.spawn(f"rank{r}", make(r))
                 for r in range(ranks)}
        kernel.spawn("driver", driver)
        kernel.run(max_virtual_s=_DEF_BUDGET_S)

    assert tasks[kill_rank].exit_code == 1, (
        f"killed rank exited {tasks[kill_rank].exit_code}, expected 1")
    for r in range(ranks):
        if r == kill_rank:
            continue
        assert records[r]["commits"] == steps, (
            f"survivor rank {r} committed {records[r]['commits']}, "
            f"expected {steps}")
        assert records[r]["durable"] == steps  # default policy: every commit
    stats = {"phases": {
        "kill": {"rank": kill_rank, "host": kill_host},
        "blacklist": driver_log[0] if driver_log else {},
        "readmission": driver_log[1] if len(driver_log) > 1 else {},
    }, "kv_ops": dict(fabric.ops)}
    return _result("kill-blacklist", ranks, seed, kernel, stats)


# ---------------------------------------------------------------------------
# kv-brownout
# ---------------------------------------------------------------------------

def kv_brownout(ranks: int, seed: int = 0, *, steps: int = 5,
                error_prob: float = 0.25, error_budget: int = 60,
                heartbeat_s: float = 0.5,
                compute_s: float = 0.1) -> Dict:
    """A coordination-service brownout: every rank's ``kv.get`` /
    ``kv.put`` ops fail with UNAVAILABLE at ``error_prob`` for a
    bounded budget, and heartbeats are dropped too — while audits and
    the heartbeat stall inspector keep running.  Asserts the retry
    plane absorbs it: every audit completes, no rank latches a stall
    failure, and retries actually happened."""
    from ..comm.stall import AmortizedStallInspector
    from ..core import audit as core_audit
    from ..core import retry as core_retry
    from ..obs import metrics as obs_metrics

    spec = (f"kv.put:error@prob={error_prob},times={error_budget};"
            f"kv.get:error@prob={error_prob},times={error_budget};"
            f"heartbeat:drop@prob=0.3,times={error_budget}")
    kernel, fabric = _fresh(ranks, seed)
    # The invariant under test is "the retry plane absorbs the
    # brownout", not "4 attempts always suffice": at prob=0.25 a
    # single op exhausts the default 4-attempt budget with p~0.4%,
    # which at 256 ranks x ~1e5 KV ops is a near-certainty.  Give the
    # policy enough headroom that exhaustion probability is
    # negligible (0.25^16 ~ 2e-10 per op) so the assertion holds at
    # every world size.
    retry_env = _env(HVTPU_KV_RETRY_ATTEMPTS="16",
                     HVTPU_KV_RETRY_DEADLINE_S="600")
    inspectors: Dict[int, AmortizedStallInspector] = {}
    audits_done: Dict[int, int] = {}
    retries_before = obs_metrics.counter("hvtpu_kv_retries_total").value()

    def make(rank: int):
        def body():
            client = fabric.client(rank, caps="dir")
            kv = core_retry.resilient_kv(client, rank=rank)
            ctx = RankContext(kernel, rank, ranks, fault_spec=spec,
                              generation=0)
            insp = AmortizedStallInspector(
                kv, rank, warn_s=60.0, abort_s=600.0,
                heartbeat_s=heartbeat_s, generation=0,
                start_heartbeat=False)
            inspectors[rank] = insp
            world = WorldView(rank, ranks, 0)
            audits_done[rank] = 0
            with ctx.activate():
                for step in range(steps):
                    desc = insp.pre_op(0, range(ranks), f"step{step}")
                    # virtual compute, heartbeats pumped on cadence
                    beats = max(1, int(compute_s / heartbeat_s))
                    for _ in range(beats):
                        kernel.sleep(compute_s / beats)
                        insp._beat_once()
                    insp._clear_inflight(0)
                    report = core_audit.verify(
                        {"step": step, "w": [1.0, 2.0]},
                        label="brownout", action="abort",
                        timeout_s=1200.0, client=client, world=world)
                    assert not report["divergent"]
                    audits_done[rank] += 1
                insp.stop()
                kernel.log("brownout.rank_done", rank=rank,
                           audits=audits_done[rank],
                           t=round(kernel.now, 9))
        return body

    with retry_env:
        for r in range(ranks):
            kernel.spawn(f"rank{r}", make(r))
        kernel.run(max_virtual_s=_DEF_BUDGET_S)

    for rank, insp in inspectors.items():
        assert insp.failure is None, (
            f"rank {rank} latched a false stall failure during the "
            f"brownout: {insp.failure}")
        assert audits_done[rank] == steps
    retries = (obs_metrics.counter("hvtpu_kv_retries_total").value()
               - retries_before)
    assert retries > 0, "brownout injected no retried KV op"
    stats = {"phases": {"brownout": {
        "virtual_s": round(kernel.now, 6),
        "kv_retries": retries,
        "audits": steps * ranks,
    }}, "kv_ops": dict(fabric.ops)}
    return _result("kv-brownout", ranks, seed, kernel, stats)


# ---------------------------------------------------------------------------
# straggler-tail / lockstep negotiation bench
# ---------------------------------------------------------------------------

def _lockstep_world(kernel: SimKernel, fabric: SimFabric, ranks: int,
                    cycles: int, cycle_times: Dict[int, List[float]],
                    fault_spec: str = ""):
    """Task bodies for a manual lockstep EagerController world over
    the simulated KVTransport; every rank enqueues one allreduce per
    cycle and drives run_cycle_once (a real all-rank barrier)."""
    from ..eager.controller import EagerController, KVTransport

    def make(rank: int):
        def body():
            ctx = RankContext(kernel, rank, ranks, fault_spec=fault_spec,
                              generation=0)
            transport = KVTransport(
                rank, ranks, client=fabric.client(rank, caps="bytes"),
                timeout_s=600.0, poll_s=1.0)
            ctrl = EagerController(rank, ranks, transport=transport,
                                   cycle_time_ms=1.0, manual=True)
            times = cycle_times.setdefault(rank, [])
            with ctx.activate():
                for cycle in range(cycles):
                    t0 = kernel.now
                    fut = ctrl.enqueue(
                        "allreduce", [1.0, float(rank)],
                        name=f"grad.{cycle}")
                    ctrl.run_cycle_once()
                    assert fut.done(), (
                        f"rank {rank} cycle {cycle}: future unresolved "
                        "after the lockstep cycle")
                    fut.result(timeout=0)
                    times.append(kernel.now - t0)
                ctrl.request_shutdown()
                while not ctrl._shutdown_seen.is_set():
                    ctrl.run_cycle_once()
                ctrl.stop()
        return body

    return make


def straggler_tail(ranks: int, seed: int = 0, *, cycles: int = 8,
                   straggler: Optional[int] = None,
                   slowdown: float = 20.0) -> Dict:
    """Lockstep negotiation with one rank's KV link ``slowdown``×
    slower: the per-cycle barrier makes every rank pay the straggler's
    latency — the distribution's tail IS the diagnosis."""
    kernel, fabric = _fresh(ranks, seed)
    if straggler is None:
        straggler = max(1, ranks - 1)
    base = fabric.link(straggler)
    fabric.set_link(straggler,
                    latency_s=base.latency_s * slowdown,
                    bandwidth_bps=base.bandwidth_bps / slowdown)
    cycle_times: Dict[int, List[float]] = {}
    make = _lockstep_world(kernel, fabric, ranks, cycles, cycle_times)
    with patch_data_plane(), _env(HVTPU_EAGER_STREAM=None):
        for r in range(ranks):
            kernel.spawn(f"rank{r}", make(r))
        kernel.run(max_virtual_s=_DEF_BUDGET_S)

    all_times = sorted(t for ts in cycle_times.values() for t in ts)
    stats = {"phases": {"negotiate": {
        "cycles": cycles,
        "straggler_rank": straggler,
        "slowdown": slowdown,
        "cycle_p50_s": round(_pct(all_times, 0.50), 9),
        "cycle_p99_s": round(_pct(all_times, 0.99), 9),
        "cycle_max_s": round(all_times[-1], 9) if all_times else 0.0,
        "virtual_s": round(kernel.now, 6),
    }}, "kv_ops": dict(fabric.ops)}
    return _result("straggler-tail", ranks, seed, kernel, stats)


def bench_negotiation(ranks: int, seed: int = 0, *, cycles: int = 6,
                      warmup: int = 2) -> Dict:
    """Healthy-network lockstep negotiation: the measured
    negotiation-cycle time vs world size (BENCH_SCALING rows)."""
    kernel, fabric = _fresh(ranks, seed)
    cycle_times: Dict[int, List[float]] = {}
    make = _lockstep_world(kernel, fabric, ranks, warmup + cycles,
                           cycle_times)
    with patch_data_plane(), _env(HVTPU_EAGER_STREAM=None):
        for r in range(ranks):
            kernel.spawn(f"rank{r}", make(r))
        kernel.run(max_virtual_s=_DEF_BUDGET_S)
    steady = sorted(t for ts in cycle_times.values()
                    for t in ts[warmup:])
    stats = {"phases": {"negotiate": {
        "cycles": cycles,
        "cycle_p50_s": round(_pct(steady, 0.50), 9),
        "cycle_mean_s": round(sum(steady) / max(1, len(steady)), 9),
        "cycle_max_s": round(steady[-1], 9) if steady else 0.0,
        "virtual_s": round(kernel.now, 6),
    }}, "kv_ops": dict(fabric.ops)}
    return _result("bench-negotiation", ranks, seed, kernel, stats)


# ---------------------------------------------------------------------------
# stream-matrix: streamed plane under split-burst / mispredict /
# membership-change interleavings
# ---------------------------------------------------------------------------

def stream_matrix(ranks: int, seed: int = 0, *, burst: int = 2,
                  warmup_steps: int = 4, post_steps: int = 2) -> Dict:
    """The streamed (barrier-free) control plane with schedule
    prediction warmed up, then the interleavings that historically
    break prediction-class protocols:

    - **split-burst**: one rank drains its burst in two halves (a
      virtual-time gap wider than the gate deadline) — atomic burst
      units must HOLD the release, never diverge it;
    - **mispredict-recovery**: a rank is forced through
      ``_on_mispredict`` mid-stream — the resync re-anchor must
      converge and subsequent cycles run clean;
    - **membership-change**: every rank announces shutdown at a
      different virtual time — agreement must still be reached with
      request blobs carrying flags mid-flight.

    Asserts every enqueued future resolves, the predictor actually
    engaged during warmup, and all ranks observe shutdown agreement.
    """
    from ..eager.controller import EagerController, KVTransport
    from ..obs import metrics as obs_metrics

    kernel, fabric = _fresh(ranks, seed)
    split_rank = max(1, ranks // 3)
    mispredict_rank = max(1, (2 * ranks) // 3)
    predicted_before = obs_metrics.counter(
        "hvtpu_controller_predicted_cycles_total").value()
    steps_total = warmup_steps + 2 + post_steps
    resolved: Dict[int, int] = {}
    shutdown_seen: Dict[int, bool] = {}

    def pump(ctrl, rank: int) -> bool:
        """One round of the real drainer/servicer/fetcher work, inline
        on this rank's task (mirrors _drain_loop/_fetch_loop without
        their threads)."""
        active = False
        if ctrl._undrained or ctrl._post_needed:
            active = ctrl._drain_once() or active
        if rank == 0:
            active = ctrl._service_once() or active
            while ctrl._local_resp:
                ctrl._fetch_once(wait_s=0)
                active = True
        else:
            active = ctrl._fetch_once(wait_s=0) or active
        return active

    def make(rank: int):
        def body():
            transport = KVTransport(
                rank, ranks, client=fabric.client(rank, caps="bytes"),
                timeout_s=600.0, poll_s=0.02)
            ctrl = EagerController(rank, ranks, transport=transport,
                                   cycle_time_ms=1.0, manual=True)
            ctrl._stream = True  # streamed plane, scenario-pumped
            resolved[rank] = 0
            # training-loop shape: the SAME named collectives re-issued
            # every step (what lets the bit-set verify and the
            # predictor engage)
            names = [f"g{i}" for i in range(burst)]
            for step in range(steps_total):
                if step == warmup_steps and rank == split_rank:
                    # split-burst: half now, half after a gap wider
                    # than the steady-state gate deadline
                    half = burst // 2 or 1
                    futs = [ctrl.enqueue("allreduce", [1.0], name=n)
                            for n in names[:half]]
                    deadline = kernel.now + 0.3
                    while kernel.now < deadline:
                        if not pump(ctrl, rank):
                            kernel.sleep(0.01)
                    futs += [ctrl.enqueue("allreduce", [1.0], name=n)
                             for n in names[half:]]
                else:
                    futs = [ctrl.enqueue("allreduce", [1.0], name=n)
                            for n in names]
                if (step == warmup_steps + 1
                        and rank == mispredict_rank):
                    with ctrl._lock:
                        ctrl._on_mispredict("sim-forced divergence")
                while not all(f.done() for f in futs):
                    if not pump(ctrl, rank):
                        kernel.sleep(0.005)
                for f in futs:
                    f.result(timeout=0)
                resolved[rank] += len(futs)
                kernel.log("step_done", rank=rank, step=step)
            # membership-change: staggered shutdown announcements
            kernel.sleep(0.001 * rank)
            ctrl.request_shutdown()
            while not ctrl._shutdown_seen.is_set():
                if not pump(ctrl, rank):
                    kernel.sleep(0.005)
            shutdown_seen[rank] = True
            # drain any post-agreement confirmations so quiesce needn't
            # spin; leftovers roll back inside quiesce (its contract)
            tail = kernel.now + 1.0
            while ctrl._predicted and kernel.now < tail:
                if not pump(ctrl, rank):
                    kernel.sleep(0.005)
            quiesced = ctrl.quiesce(timeout=10.0)
            assert quiesced, f"rank {rank} did not quiesce post-shutdown"
            ctrl.stop()
        return body

    with patch_data_plane(), _env(HVTPU_EAGER_PREDICT="auto",
                                  HVTPU_EAGER_BURST_CAP="1"):
        for r in range(ranks):
            kernel.spawn(f"rank{r}", make(r))
        kernel.run(max_virtual_s=_DEF_BUDGET_S)

    for r in range(ranks):
        assert resolved[r] == steps_total * burst, (
            f"rank {r} resolved {resolved[r]} futures, expected "
            f"{steps_total * burst}")
        assert shutdown_seen.get(r), f"rank {r} missed shutdown agreement"
    predicted = (obs_metrics.counter(
        "hvtpu_controller_predicted_cycles_total").value()
                 - predicted_before)
    assert predicted > 0, (
        "the schedule predictor never engaged — the warmup phase is "
        "not exercising the fast path")
    stats = {"phases": {
        "warmup": {"steps": warmup_steps,
                   "predicted_bursts": predicted},
        "perturb": {"split_rank": split_rank,
                    "mispredict_rank": mispredict_rank},
        "shutdown": {"virtual_s": round(kernel.now, 6)},
    }, "kv_ops": dict(fabric.ops)}
    return _result("stream-matrix", ranks, seed, kernel, stats)


# ---------------------------------------------------------------------------
# multi-job-arbiter: the fleet arbiter sharing one pool between jobs
# ---------------------------------------------------------------------------

class _SimJobRunner:
    """Handle-protocol job runner over virtual rank tasks: the sim
    counterpart of ``fleet/runner.py``'s ElasticJobRunner.  Each
    generation spawns ``np`` rank tasks running the REAL elastic-commit
    + drain-coordination code over a job-prefixed KV namespace; a
    supervisor task classifies each incarnation (done / drain /
    restart) exactly like the production driver and relaunches.

    All mutation happens on kernel task threads serialised by the run
    token, so no locks are needed (the sim invariant)."""

    def __init__(self, job, kernel, fabric, *, steps: int,
                 compute_s: float, durable_every: int, grace_s: float,
                 perm, account: Dict[int, int],
                 fault_spec: str = "", launch_hook=None):
        self.job = job
        self.name = job.spec.name
        self.kernel = kernel
        self.fabric = fabric
        self.steps = steps
        self.compute_s = compute_s
        self.durable_every = durable_every
        self.grace_s = grace_s
        self.perm = perm
        self.account = account
        self.fault_spec = fault_spec  # generation 0 only
        self.launch_hook = launch_hook
        # disjoint generation space per job: audit sequence counters
        # are keyed (generation, rank, label) process-wide, and two
        # jobs sharing rank ids at different commit cadences would
        # otherwise desynchronise each other's audit rounds
        self.ns_base = job.submit_seq * 1000
        self.charged_restarts = 0
        self.drains = 0
        self.np_history: List[int] = []
        self.exit79_per_gen: List[int] = []
        self.shrink_req_t: Optional[float] = None
        self.notice_to_commit_s: Optional[float] = None
        self.resize_s: Optional[float] = None
        self._alloc: Dict[str, int] = {}
        self._pending_alloc: Optional[Dict[str, int]] = None
        self._victims: set = set()
        self._notices: set = set()
        self._kills: set = set()
        self._target_np: Optional[int] = None
        self._phase = "pending"
        self._np = 0
        self._exit: Optional[int] = None
        self._gen = 0
        self._resume = {"step": 0, "cursor": 0}
        self._drain_t = 0.0
        self._measure_resize = False

    # -- handle protocol (called from the arbiter's task) ---------------
    def start(self, allocation: Dict[str, int]) -> None:
        self._alloc = dict(allocation)
        self._phase = "running"
        self.kernel.spawn(f"{self.name}.driver", self._supervise)

    def poll(self) -> Optional[int]:
        return self._exit

    def stop(self) -> None:
        self._kills.update(range(self._np))

    def request_shrink(self, new_np: int) -> bool:
        if self._phase != "running" or self._np <= new_np:
            return False
        keep: Dict[str, int] = {}
        remaining = new_np
        for h in sorted(self._alloc):
            take = min(self._alloc[h], remaining)
            if take > 0:
                keep[h] = take
                remaining -= take
        self._pending_alloc = keep
        self._victims = set(range(new_np, self._np))
        self._target_np = new_np
        self._phase = "draining"
        self.shrink_req_t = self.kernel.now
        self._measure_resize = True
        self._notices.update(self._victims)
        self.kernel.log("fleet_sim.shrink_notice", job=self.name,
                        to_np=new_np, victims=self._np - new_np)
        return True

    def escalate(self) -> int:
        victims = set(self._victims)
        self._kills |= victims
        return len(victims)

    def update_allocation(self, allocation: Dict[str, int]) -> None:
        self._alloc = dict(allocation)

    def phase(self) -> str:
        return self._phase

    def current_np(self) -> int:
        return self._np

    def target_np(self) -> Optional[int]:
        return self._target_np

    def allocation(self) -> Dict[str, int]:
        return dict(self._alloc)

    # -- supervisor (one kernel task per job) ---------------------------
    def _supervise(self) -> None:
        from ..core.preempt import DRAIN_EXIT_CODE

        while True:
            gen = self._gen
            size = sum(self._alloc.values())
            self._np = size
            self._phase = "running"
            self.np_history.append(size)
            if self._measure_resize and len(self.np_history) > 1 \
                    and self.shrink_req_t is not None \
                    and self._pending_alloc is None:
                self._measure_resize = False
                self.resize_s = self.kernel.now - self.shrink_req_t
            self.kernel.log("fleet_sim.launch", job=self.name, gen=gen,
                            np=size)
            if self.launch_hook is not None:
                self.launch_hook(self, gen, size)
            outcomes: Dict[int, str] = {}
            tasks = [self.kernel.spawn(
                f"{self.name}.g{gen}.r{r}",
                self._make_rank(r, size, gen, outcomes))
                for r in range(size)]
            while not all(t.done for t in tasks):
                self.kernel.sleep(0.05)
            drained = [r for r, t in enumerate(tasks)
                       if t.exit_code == DRAIN_EXIT_CODE]
            crashed = [r for r, t in enumerate(tasks)
                       if t.exit_code not in (None, DRAIN_EXIT_CODE)]
            if all(outcomes.get(r) == "finished" for r in range(size)):
                self._phase = "done"
                self._exit = 0
                self.kernel.log("fleet_sim.done", job=self.name,
                                gen=gen, np=size,
                                step=self._resume["step"])
                return
            if drained and not crashed:
                self.drains += 1
                self.exit79_per_gen.append(len(drained))
                if self._victims and self.shrink_req_t is not None \
                        and self.notice_to_commit_s is None:
                    self.notice_to_commit_s = (self._drain_t
                                               - self.shrink_req_t)
            else:
                self.charged_restarts += 1
            # the incarnation_end moment: apply the pending grant
            # BEFORE the relaunch (the anti-race contract the real
            # driver gets from its synchronous listener)
            if self._pending_alloc is not None:
                self._alloc = self._pending_alloc
                self._pending_alloc = None
                self._victims = set()
                self._notices = set()
                self._kills = set()
                self._target_np = None
            self._phase = "resizing"
            self._gen += 1
            self.kernel.log("fleet_sim.incarnation_end", job=self.name,
                            gen=gen, drained=len(drained),
                            crashed=len(crashed))
            self.kernel.sleep(0.1)  # modelled relaunch latency

    def _make_rank(self, rank: int, size: int, gen: int,
                   outcomes: Dict[int, str]):
        job_gen = self.ns_base + gen

        def body():
            from ..core.exceptions import DrainInterrupt
            from ..core.preempt import DRAIN_EXIT_CODE
            from ..core import retry as core_retry
            from ..data import sharder
            from ..fleet.job import prefixed_client

            client = prefixed_client(
                self.fabric.client(self.ns_base + rank, caps="dir"),
                self.name)
            kv = core_retry.resilient_kv(client, rank=rank)
            ctx = RankContext(
                self.kernel, rank, size,
                fault_spec=(self.fault_spec if gen == 0 else ""),
                generation=job_gen, drain_client=kv,
                drain_grace_s=self.grace_s, with_drain=True)
            state = SimElasticState(
                client=client, world=WorldView(rank, size, job_gen),
                step=self._resume["step"],
                cursor=self._resume["cursor"])
            state.set_commit_policy(self.durable_every)
            pending: List[int] = []
            flushed = 0
            outcomes[rank] = "running"

            def flush_durable():
                nonlocal flushed
                if state.durable_commits > flushed:
                    flushed = state.durable_commits
                    for i in pending:
                        self.account[i] = self.account.get(i, 0) + 1
                    del pending[:]

            with ctx.activate():
                try:
                    while state.step < self.steps:
                        ctx.check_exit()
                        if rank in self._kills:
                            raise VirtualExit(1)
                        if rank in self._notices:
                            self._notices.discard(rank)
                            ctx.coordinator.notice("fleet")
                        ctx.coordinator._poll_once()
                        self.kernel.sleep(self.compute_s)
                        idx, new_cursor = sharder.shard_window(
                            self.perm, state.cursor, rank, size, 1)
                        pending.extend(int(i) for i in idx)
                        state.step += 1
                        state.cursor = int(new_cursor)
                        try:
                            state.commit()
                        finally:
                            # deliveries become accountable only when
                            # a DURABLE commit captured their cursor —
                            # uncommitted batches are re-fetched by the
                            # next incarnation (exactly-once contract)
                            flush_durable()
                    # end-of-job durable save (what a real training
                    # loop does before exiting clean), so the final
                    # partial window is accounted too
                    state.save()
                    flush_durable()
                    outcomes[rank] = "finished"
                except DrainInterrupt:
                    outcomes[rank] = "drain_peer"
                except VirtualExit as e:
                    outcomes[rank] = ("drain_exit"
                                      if e.code == DRAIN_EXIT_CODE
                                      else "killed")
                    if e.code == DRAIN_EXIT_CODE:
                        self._drain_t = self.kernel.now
                    raise
                finally:
                    self._resume = {
                        "step": int(state._saved.get("step", 0)),
                        "cursor": int(state._saved.get("cursor", 0))}

        return body


def multi_job_arbiter(ranks: int, seed: int = 0, *, lo_steps: int = 8,
                      hi_steps: int = 4, slots_per_host: int = 8,
                      tick_s: float = 0.25, hi_arrival_s: float = 1.0,
                      grace_s: float = 120.0, compute_s: float = 0.4,
                      durable_every: int = 2) -> Dict:
    """Two jobs, one pool, under the REAL FleetArbiter: a low-priority
    job expands to the whole pool, survives an injected mid-run
    preemption (planned drain, relaunch at full size), then a
    high-priority job arrives and the arbiter reclaims half the pool
    through the graceful-drain channel — the victims exit
    DRAIN_EXIT_CODE at an agreed commit, the low job relaunches
    smaller with ZERO charged restarts, and the high job gang-starts
    only once its full min-world allocation is free.  Both jobs finish
    with per-job exactly-once sample accounting."""
    from ..core.preempt import DRAIN_EXIT_CODE
    from ..data import sharder
    from ..fleet import FleetArbiter, JobSpec

    kernel, fabric = _fresh(ranks, seed)
    n_hosts = (ranks + slots_per_host - 1) // slots_per_host
    hosts = {f"host{h:04d}": slots_per_host for h in range(n_hosts)}
    pool_slots = n_hosts * slots_per_host
    hi_min = ranks // 2
    lo_min = max(1, ranks // 4)
    fault_rank = max(1, ranks // 3)
    num_samples = (lo_steps + hi_steps) * pool_slots
    perms = {
        "lo": sharder.epoch_permutation(num_samples, seed * 131 + 1, 0),
        "hi": sharder.epoch_permutation(num_samples, seed * 131 + 2, 0),
    }
    accounts: Dict[str, Dict[int, int]] = {"lo": {}, "hi": {}}
    runners: Dict[str, _SimJobRunner] = {}
    gang_snapshots: List[dict] = []

    def launch_hook(runner, gen, size):
        # gang-disjointness evidence: at every launch, per-host usage
        # across ALL live jobs must fit the host
        usage: Dict[str, int] = {}
        for r in runners.values():
            if r._exit is None:
                for h, n in r._alloc.items():
                    usage[h] = usage.get(h, 0) + n
        gang_snapshots.append(
            {"t": kernel.now, "job": runner.name, "gen": gen,
             "np": size, "usage": usage})

    def make_runner(job):
        name = job.spec.name
        cfg = {
            "lo": dict(steps=lo_steps,
                       fault_spec=(f"worker.step:preempt@"
                                   f"rank={fault_rank},times=1")),
            "hi": dict(steps=hi_steps, fault_spec=""),
        }[name]
        runner = _SimJobRunner(
            job, kernel, fabric, compute_s=compute_s,
            durable_every=durable_every, grace_s=grace_s,
            perm=perms[name], account=accounts[name],
            launch_hook=launch_hook, **cfg)
        runners[name] = runner
        return runner

    arb = FleetArbiter(
        _StaticDiscovery(hosts), fleet_dir=None, tick_s=tick_s,
        drain_grace_s=grace_s, runner_factory=make_runner,
        event_fn=kernel.log, register_debug=False)

    def arbiter_task():
        arb.submit(JobSpec("lo", ["sim"], priority=0,
                           min_np=lo_min, max_np=pool_slots))
        while not arb.all_terminal():
            arb.tick()
            kernel.sleep(tick_s)
        arb.tick()  # final reap/publish
        kernel.log("fleet_sim.arbiter_done",
                   states={n: arb.jobs[n].state
                           for n in sorted(arb.jobs)})

    def hi_submitter():
        kernel.sleep(hi_arrival_s)
        # the arrival must preempt a healthy post-drain world, not
        # merge into the injected gen-0 drain (whose commit lands
        # after hi_arrival_s at large rank counts): wait for lo's
        # second incarnation to be running
        lo_runner = runners["lo"]
        while not (lo_runner._gen >= 1
                   and lo_runner.phase() == "running"):
            kernel.sleep(tick_s)
        arb.submit(JobSpec("hi", ["sim"], priority=10, min_np=hi_min))

    with _env(HVTPU_AUDIT_EVERY="1", HVTPU_AUDIT_ACTION="abort",
              HVTPU_ELASTIC_STATE_DIR=None, HVTPU_FLEET_DIR=None):
        kernel.spawn("arbiter", arbiter_task)
        kernel.spawn("hi-submitter", hi_submitter)
        kernel.run(max_virtual_s=_DEF_BUDGET_S)

    lo_r, hi_r = runners["lo"], runners["hi"]
    lo_job, hi_job = arb.jobs["lo"], arb.jobs["hi"]
    # both jobs finished clean under the arbiter
    assert lo_job.state == "DONE" and lo_r._exit == 0, (
        f"lo ended {lo_job.state} (exit {lo_r._exit}): {lo_job.reason}")
    assert hi_job.state == "DONE" and hi_r._exit == 0, (
        f"hi ended {hi_job.state} (exit {hi_r._exit}): {hi_job.reason}")
    # incarnation history: full pool → full pool (after the injected
    # preemption's planned drain) → shrunk for the high-priority gang
    assert lo_r.np_history[0] == pool_slots, (
        f"lo did not expand to the pool at start: {lo_r.np_history}")
    assert lo_r.np_history[-1] == pool_slots - hi_min, (
        f"lo final size {lo_r.np_history[-1]}, expected "
        f"{pool_slots - hi_min}: {lo_r.np_history}")
    assert hi_r.np_history == [hi_min], (
        f"hi must gang-launch exactly once at min_np: {hi_r.np_history}")
    # planned drains only: exit-79 departures, zero charged restarts
    assert lo_r.drains == 2 and lo_r.exit79_per_gen == [1, hi_min], (
        f"drains={lo_r.drains} exit79={lo_r.exit79_per_gen}")
    assert lo_r.charged_restarts == 0 and hi_r.charged_restarts == 0, (
        f"planned preemption charged the restart budget: "
        f"lo={lo_r.charged_restarts} hi={hi_r.charged_restarts}")
    assert lo_job.preemptions == 1 and lo_job.charged_restarts == 0
    # gang scheduling: at every launch the per-host usage fits
    for snap in gang_snapshots:
        for h, used in snap["usage"].items():
            assert used <= hosts[h], (
                f"host {h} over-committed ({used}/{hosts[h]}) at "
                f"{snap}")
    # the high job waited for the drain, then got its FULL gang
    assert hi_job.queue_wait_s is not None and hi_job.queue_wait_s > 0
    assert gang_snapshots[-1]["job"] in ("hi", "lo")
    # per-job exactly-once accounting against the committed cursor
    for name in ("lo", "hi"):
        acct = accounts[name]
        cursor = runners[name]._resume["cursor"]
        assert cursor > 0, f"{name} committed no data progress"
        dupes = {i: c for i, c in acct.items() if c != 1}
        assert not dupes, (
            f"{name}: samples delivered more than once: "
            f"{sorted(dupes)[:10]}")
        expect = sorted(int(i) for i in perms[name][:cursor])
        assert sorted(acct) == expect, (
            f"{name}: delivered set != committed window "
            f"({len(acct)} vs {cursor})")
    assert lo_r.notice_to_commit_s is not None
    assert 0 < lo_r.notice_to_commit_s < grace_s
    assert lo_r.resize_s is not None and lo_r.resize_s > 0
    stats = {"phases": {
        "pool": {"hosts": n_hosts, "slots": pool_slots},
        "inject": {"fault_rank": fault_rank,
                   "lo_incarnations": lo_r.np_history},
        "preempt": {
            "victims": hi_min,
            "queue_wait_s": round(hi_job.queue_wait_s, 6),
            "notice_to_commit_s": round(lo_r.notice_to_commit_s, 6),
            "resize_s": round(lo_r.resize_s, 6),
        },
        "done": {
            "lo_final_np": lo_r.np_history[-1],
            "hi_np": hi_min,
            "lo_samples": len(accounts["lo"]),
            "hi_samples": len(accounts["hi"]),
            "virtual_s": round(kernel.now, 6),
        }}, "kv_ops": dict(fabric.ops)}
    _ = DRAIN_EXIT_CODE
    return _result("multi-job-arbiter", ranks, seed, kernel, stats)


# ---------------------------------------------------------------------------
# checkpoint-storm: the durable state plane under storage chaos
# ---------------------------------------------------------------------------

def checkpoint_storm(ranks: int, seed: int = 0, *, commits: int = 4,
                     payload_kb: int = 8, compute_s: float = 0.05,
                     disk_base_s: float = 0.002,
                     disk_bps: float = 200e6,
                     torn_rank: Optional[int] = None,
                     bitflip_rank: Optional[int] = None) -> Dict:
    """Every rank runs the REAL durable commit protocol
    (core/durable.py) against its own state directory, with injected
    storage damage on two victims' FINAL commit: one torn write (the
    commit never lands — its manifest is truncated) and one bit flip
    (the commit LOOKS landed and only hash verification can reject
    it).  Then all ranks storm the restore path at once: verify local
    snapshots, publish the highest verified seq, and run the
    restore quorum over the simulated KV.  Asserts the agreed restore
    point is the min over per-rank maxima, is durable on EVERY rank,
    and that neither damaged snapshot is ever picked — a victim's
    damage lowers the pick, never diverges it."""
    import shutil as _shutil
    import tempfile

    from ..core import durable as core_durable

    if torn_rank is None:
        torn_rank = max(1, ranks // 4)
    if bitflip_rank is None:
        bitflip_rank = max(2, ranks // 2)
    assert torn_rank != bitflip_rank, "victims must differ"
    kernel, fabric = _fresh(ranks, seed)
    # each commit is two atomic_writes (payload, then manifest); the
    # final commit's payload is ckpt.write invocation 2*commits-1
    last_payload = 2 * commits - 1
    root = tempfile.mkdtemp(prefix="hvtpu-ckpt-storm-")
    commit_t: List[float] = []
    quorum_t: List[float] = []
    best: Dict[int, Optional[int]] = {}
    agreed: Dict[int, Optional[int]] = {}

    def make(rank: int):
        if rank == torn_rank:
            # torn payload AND (via unlimited times) torn manifest of
            # the final commit: the commit point is never reached
            spec = f"ckpt.write:torn@rank={rank},count={last_payload}"
        elif rank == bitflip_rank:
            # one flipped bit in the final payload, manifest intact:
            # the snapshot parses as committed, verification rejects it
            spec = (f"ckpt.write:bitflip@rank={rank},"
                    f"count={last_payload},times=1")
        else:
            spec = ""

        def body():
            d = os.path.join(root, f"rank{rank}")
            ctx = RankContext(kernel, rank, ranks, fault_spec=spec)
            size_b = payload_kb * 1024
            with ctx.activate():
                for seq in range(1, commits + 1):
                    kernel.sleep(compute_s)
                    stamp = f"{seed}/{rank}/{seq}/".encode()
                    data = (stamp * (size_b // len(stamp) + 1))[:size_b]
                    t0 = kernel.now
                    # modeled disk latency (real writes land on tmpfs
                    # in zero virtual time)
                    kernel.sleep(disk_base_s + len(data) / disk_bps)
                    core_durable.write_snapshot(
                        d, seq, {"state.pkl": data}, fsync=False)
                    commit_t.append(kernel.now - t0)
                    kernel.log("ckpt_commit", rank=rank, seq=seq)
                # the restore storm: every rank verifies its local
                # snapshots and votes; min over votes is the pick
                lb = core_durable.latest_verified(d)
                best[rank] = lb
                kernel.log("ckpt_local_best", rank=rank,
                           best=-1 if lb is None else lb)
                t1 = kernel.now
                a = core_durable.restore_quorum(
                    fabric.client(rank, caps="str"), rank=rank,
                    size=ranks, local_best=lb,
                    namespace="hvtpu/ckpt/quorum/0/0", timeout_s=600.0)
                agreed[rank] = a
                quorum_t.append(kernel.now - t1)
                kernel.log("ckpt_quorum", rank=rank,
                           agreed=-1 if a is None else a)
        return body

    try:
        with _env(HVTPU_CKPT_KEEP="2", HVTPU_CKPT_FSYNC="0"):
            for r in range(ranks):
                kernel.spawn(f"rank{r}", make(r))
            kernel.run(max_virtual_s=_DEF_BUDGET_S)

        assert len(agreed) == ranks, "some ranks never finished"
        # undamaged ranks verified their final commit; both victims
        # fell back to the previous one
        for r in range(ranks):
            want = commits - 1 if r in (torn_rank, bitflip_rank) \
                else commits
            assert best[r] == want, (
                f"rank {r} local best {best[r]}, expected {want}")
        # the torn victim's final attempt is visibly UNcommitted; the
        # bitflip victim's is committed-but-rejected (hash mismatch)
        torn_d = core_durable.snapshot_path(
            os.path.join(root, f"rank{torn_rank}"), commits)
        assert core_durable._committed(torn_d) is None, (
            "torn final commit must not reach the commit point")
        flip_d = core_durable.snapshot_path(
            os.path.join(root, f"rank{bitflip_rank}"), commits)
        assert core_durable._committed(flip_d) is not None, (
            "bitflip leaves the manifest intact")
        assert not core_durable.verify_snapshot(flip_d), (
            "bit-flipped payload must fail hash verification")
        # agreement: one value, the min over per-rank maxima, durable
        # (verified) on every rank — the damage delayed the pick, it
        # never diverged it
        picks = set(agreed.values())
        assert picks == {commits - 1}, (
            f"ranks disagree on the restore point: {sorted(picks)}")
        for r in range(ranks):
            p = core_durable.snapshot_path(
                os.path.join(root, f"rank{r}"), commits - 1)
            assert core_durable.verify_snapshot(p), (
                f"agreed commit {commits - 1} not durable on rank {r}")
    finally:
        _shutil.rmtree(root, ignore_errors=True)

    commit_s = sorted(commit_t)
    quorum_s = sorted(quorum_t)
    stats = {"phases": {
        "commit": {
            "commits": len(commit_t),
            "payload_kb": payload_kb,
            "commit_p50_s": round(_pct(commit_s, 0.50), 9),
            "commit_p99_s": round(_pct(commit_s, 0.99), 9),
            "commit_max_s": round(commit_s[-1], 9) if commit_s else 0.0,
        },
        "restore_quorum": {
            "agreed_seq": commits - 1,
            "torn_rank": torn_rank,
            "bitflip_rank": bitflip_rank,
            "quorum_p50_s": round(_pct(quorum_s, 0.50), 9),
            "quorum_max_s": round(quorum_s[-1], 9) if quorum_s else 0.0,
            "virtual_s": round(kernel.now, 6),
        }}, "kv_ops": dict(fabric.ops)}
    return _result("checkpoint-storm", ranks, seed, kernel, stats)


# ---------------------------------------------------------------------------
# compression-negotiation: int8-sidecar agreement through the real
# controller
# ---------------------------------------------------------------------------

def compression_negotiation(ranks: int, seed: int = 0, *,
                            cycles: int = 4) -> Dict:
    """Mixed-precision negotiation through the REAL EagerController
    over the simulated KVTransport: every rank enqueues a dense fp32
    allreduce AND an int8-compressed sidecar (EQuARX-style) each
    cycle.  The wire dtype is the fusion/caching signature, so the
    coordinator must keep the two streams apart and every rank must
    see the SAME negotiated response schedule — int8 ops at the int8
    wire dtype, never fused into the fp32 burst.  Asserts identical
    per-rank schedules and that every future resolves."""
    from ..comm.compression import Int8Compressor
    from ..eager.controller import EagerController, KVTransport
    from ..native import wire

    kernel, fabric = _fresh(ranks, seed)
    int8_id = wire.DTYPE_IDS["int8"]
    schedules: Dict[int, List] = {}
    cycle_times: Dict[int, List[float]] = {}

    def make(rank: int):
        def body():
            ctx = RankContext(kernel, rank, ranks)
            transport = KVTransport(
                rank, ranks, client=fabric.client(rank, caps="bytes"),
                timeout_s=600.0, poll_s=1.0)
            ctrl = EagerController(rank, ranks, transport=transport,
                                   cycle_time_ms=1.0, manual=True)
            sched = schedules[rank] = []
            times = cycle_times.setdefault(rank, [])
            # spy on the execution dispatch: the one choke point every
            # released ResponseList passes through on BOTH the manual
            # lockstep and the streamed plane — what lands here IS the
            # schedule this rank will execute
            orig = ctrl._dispatch_execution

            def spy(rl, finished):
                for rs in rl.responses:
                    if rs.type == wire.ALLREDUCE:
                        sched.append((tuple(rs.tensor_names), rs.dtype))
                return orig(rl, finished)

            ctrl._dispatch_execution = spy
            with ctx.activate():
                for cycle in range(cycles):
                    t0 = kernel.now
                    dense = ctrl.enqueue(
                        "allreduce", [1.0, float(rank)],
                        name=f"dense.{cycle}")
                    sidecar = ctrl.enqueue(
                        "allreduce", [0.5, float(rank), -1.0, 2.0],
                        name=f"sidecar.{cycle}",
                        compression=Int8Compressor)
                    ctrl.run_cycle_once()
                    for fut in (dense, sidecar):
                        assert fut.done(), (
                            f"rank {rank} cycle {cycle}: future "
                            "unresolved after the lockstep cycle")
                        fut.result(timeout=0)
                    times.append(kernel.now - t0)
                    kernel.log("negotiated", rank=rank, cycle=cycle)
                ctrl.request_shutdown()
                while not ctrl._shutdown_seen.is_set():
                    ctrl.run_cycle_once()
                ctrl.stop()
        return body

    with patch_data_plane(), _env(HVTPU_EAGER_STREAM=None):
        for r in range(ranks):
            kernel.spawn(f"rank{r}", make(r))
        kernel.run(max_virtual_s=_DEF_BUDGET_S)

    assert len(schedules) == ranks, "some ranks never negotiated"
    # agreement: byte-identical negotiated schedule on every rank
    base = schedules[0]
    for r in range(1, ranks):
        assert schedules[r] == base, (
            f"rank {r} negotiated a different schedule:\n"
            f"  rank 0: {base}\n  rank {r}: {schedules[r]}")
    # the int8 sidecars crossed the wire at the int8 dtype, one
    # response per cycle, and never shared a response with fp32 ops
    sidecars = [s for s in base
                if any(n.startswith("sidecar.") for n in s[0])]
    assert len(sidecars) == cycles, (
        f"expected {cycles} sidecar responses, got {sidecars}")
    for names, dtype in sidecars:
        assert dtype == int8_id, (
            f"sidecar response {names} at wire dtype {dtype}, "
            f"expected int8 ({int8_id})")
        assert all(n.startswith("sidecar.") for n in names), (
            f"int8 sidecar fused with non-int8 ops: {names}")
    dense = [s for s in base
             if any(n.startswith("dense.") for n in s[0])]
    assert len(dense) == cycles and all(
        d != int8_id for _, d in dense), (
        f"dense fp32 stream polluted by the sidecar: {dense}")

    all_times = sorted(t for ts in cycle_times.values() for t in ts)
    stats = {"phases": {"negotiate": {
        "cycles": cycles,
        "sidecar_responses": len(sidecars),
        "cycle_p50_s": round(_pct(all_times, 0.50), 9),
        "cycle_max_s": round(all_times[-1], 9) if all_times else 0.0,
        "virtual_s": round(kernel.now, 6),
    }}, "kv_ops": dict(fabric.ops)}
    return _result("compression-negotiation", ranks, seed, kernel,
                   stats)


# ---------------------------------------------------------------------------
# anomaly-detection
# ---------------------------------------------------------------------------

def anomaly_detection(ranks: int, seed: int = 0, *, cycles: int = 32,
                      degrade_after: int = 12,
                      straggler: Optional[int] = None,
                      slowdown: float = 400.0) -> Dict:
    """One virtual rank's link degrades mid-run; the REAL
    :class:`~..obs.anomaly.AnomalyEngine`, fed per-cycle arrival skew
    exactly as rank 0's controller drain feeds it, must raise a
    ``straggler`` incident *naming that rank*.  Detection latency =
    virtual seconds from the ``set_link`` degradation to the first
    incident.

    Mechanics: every rank runs ``cycles`` barrier-ish steps — sleep
    (compute), one KV round trip (paying its own link), then posting
    its arrival time.  An aggregator task (the rank-0 role) gathers
    each cycle's arrivals with a single ``dir_get``, computes
    skew/last-arriver, and feeds the engine.  After the aggregator has
    scored ``degrade_after`` healthy cycles it degrades ``straggler``'s
    link ``slowdown``× (latency and bandwidth) via ``set_link``."""
    from ..obs.anomaly import AnomalyConfig, AnomalyEngine

    kernel, fabric = _fresh(ranks, seed)
    if straggler is None:
        straggler = max(1, ranks // 2)
    step_s = 0.25
    engine = AnomalyEngine(
        rank=0, size=ranks,
        config=AnomalyConfig(window=16, warmup=8, threshold=6.0,
                             min_rel=0.5, cooldown_s=0.0))
    degrade_t: List[float] = []
    detect_t: List[float] = []
    skews: List[float] = []

    def worker(rank: int):
        client = fabric.client(rank, caps="str")

        def body():
            ctx = RankContext(kernel, rank, ranks, generation=0)
            with ctx.activate():
                for c in range(cycles):
                    kernel.sleep(step_s)
                    # one round trip on this rank's own link — the
                    # degraded straggler pays its inflated latency
                    # here, so its posted arrival time drifts late.
                    try:
                        client.key_value_try_get("go")
                    except KeyError:
                        pass
                    client.key_value_set(
                        f"arr/{c}/{rank:05d}", repr(kernel.now))
        return body

    def aggregator():
        client = fabric.client(0, caps="dir")
        ctx = RankContext(kernel, 0, ranks, generation=0)
        with ctx.activate():
            for c in range(cycles):
                while True:
                    items = client.key_value_dir_get(f"arr/{c}/")
                    if len(items) >= ranks:
                        break
                    kernel.sleep(0.01)
                arrivals = {int(k.rsplit("/", 1)[1]): float(v)
                            for k, v in items}
                last = max(arrivals, key=lambda r: arrivals[r])
                skew = max(arrivals.values()) - min(arrivals.values())
                skews.append(skew)
                fired = engine.on_arrival_skew(
                    f"grad.{c}", skew, last)
                if fired and not detect_t and any(
                        i["kind"] == "straggler" for i in fired):
                    detect_t.append(kernel.now)
                    kernel.log("straggler_detected", cycle=c,
                               ranks=fired[0]["ranks"])
                client.key_value_delete(f"arr/{c}/")
                if c + 1 == degrade_after:
                    base = fabric.link(straggler)
                    fabric.set_link(
                        straggler,
                        latency_s=base.latency_s * slowdown,
                        bandwidth_bps=base.bandwidth_bps / slowdown)
                    degrade_t.append(kernel.now)
                    kernel.log("link_degraded", rank=straggler,
                               slowdown=slowdown)

    for r in range(ranks):
        kernel.spawn(f"rank{r}", worker(r))
    kernel.spawn("aggregator", aggregator)
    kernel.run(max_virtual_s=_DEF_BUDGET_S)

    incidents = [i for i in engine.incidents()
                 if i["kind"] == "straggler"]
    assert degrade_t, "degradation never happened"
    assert incidents, (
        f"no straggler incident after a {slowdown}x link degradation "
        f"of rank {straggler}")
    first = incidents[0]
    assert first["ranks"] == [straggler], (
        f"incident blamed ranks {first['ranks']}, expected "
        f"[{straggler}]")
    assert detect_t and detect_t[0] >= degrade_t[0], (
        "incident fired before the degradation")
    healthy = sorted(skews[:degrade_after])
    stats = {"phases": {"detect": {
        "cycles": cycles,
        "straggler_rank": straggler,
        "blamed_ranks": first["ranks"],
        "slowdown": slowdown,
        "incidents": len(incidents),
        "first_zscore": first["zscore"],
        "healthy_skew_p50_s": round(_pct(healthy, 0.50), 9),
        "degrade_t_s": round(degrade_t[0], 6),
        "detect_t_s": round(detect_t[0], 6),
        "detection_latency_s": round(detect_t[0] - degrade_t[0], 6),
        "virtual_s": round(kernel.now, 6),
    }}, "kv_ops": dict(fabric.ops)}
    return _result("anomaly-detection", ranks, seed, kernel, stats)


# ---------------------------------------------------------------------------
# coordinator-loss
# ---------------------------------------------------------------------------

def coordinator_loss(ranks: int, seed: int = 0, *, steps_before: int = 2,
                     steps_after: int = 2, lease_s: float = 2.0,
                     hb_s: float = 0.25, slots_per_host: int = 8,
                     cooldown_s: float = 60.0) -> Dict:
    """The coordination service's HOST dies mid-run: every rank's KV
    lease expires (real FencedKV self-fencing over a downed fabric →
    virtual exit 89), the virtual driver blacklists the coordinator
    host in the REAL HostManager and re-elects the coordinator address
    via the REAL ``_default_coordinator_addr`` over the surviving
    slots, and the relaunched generation replays each rank's journaled
    durable keys (real KeyJournal) into the fresh, EMPTY fabric.
    Asserts: every rank fences (no zombies), the re-elected address
    moves off the dead host, every journaled key is visible to every
    gen-1 rank, and per-rank commit accounting is exactly-once across
    the restart."""
    import shutil
    import tempfile

    from ..core.journal import KeyJournal
    from ..core.retry import FENCE_EXIT_CODE, FencedKV
    from ..elastic.discovery import HostManager
    from ..obs import metrics as obs_metrics
    from ..runner.hosts import HostSlots, get_host_assignments
    from ..runner.launch import _default_coordinator_addr

    kernel, fabric0 = _fresh(ranks, seed)
    # one SPARE host beyond what the ranks need: losing the coordinator
    # host must leave enough slots to re-place the world
    n_hosts = (ranks + slots_per_host - 1) // slots_per_host + 1
    hosts = {f"host{h}": slots_per_host for h in range(n_hosts)}
    down_at_s = steps_before * hb_s + 0.2
    jdir = tempfile.mkdtemp(prefix="hvtsim-kvjournal-")
    fabrics: Dict[str, SimFabric] = {"gen0": fabric0}
    down_t: List[float] = []
    fence_t: Dict[int, float] = {}
    recover_t: Dict[int, float] = {}
    committed0: Dict[int, int] = {}
    committed1: Dict[int, int] = {}
    replayed: Dict[int, int] = {}
    votes_seen: Dict[int, int] = {}
    gen1_tasks: Dict[int, object] = {}
    election: Dict[str, str] = {}
    fence_exits_before = obs_metrics.counter(
        "hvtpu_fence_exits_total").value()

    def make_gen0(rank: int):
        def body():
            ctx = RankContext(kernel, rank, ranks, generation=0)
            client = fabric0.client(rank, caps="dir")

            def exit_fn(code):
                fence_t[rank] = kernel.now
                ctx.request_exit(code)

            with ctx.activate():
                kv = FencedKV(client, rank=rank, job_epoch=0,
                              generation=0, lease_s=lease_s,
                              check_every=10_000, exit_fn=exit_fn,
                              journal=KeyJournal(jdir, rank=rank))
                kv.add_journal_prefix("hvtdur/")
                # one durable key per rank (a restore-quorum-style
                # vote) — the history the fresh coordinator cannot
                # recompute
                kv.key_value_set(f"hvtdur/vote/{rank}", str(100 + rank))
                committed0[rank] = 0
                for step in range(steps_before):
                    kernel.sleep(hb_s)
                    kv.key_value_set(f"hb/{rank}", str(step))
                    committed0[rank] += 1
                # the outage begins: keep heartbeating until the lease
                # fences us (retry exhaustion raises; the lease check
                # in FencedKV._guarded eventually calls exit_fn)
                while True:
                    kernel.sleep(hb_s)
                    try:
                        kv.key_value_set(f"hb/{rank}", "outage")
                        committed0[rank] += 1
                    except Exception:
                        pass
        return body

    def chaos():
        kernel.sleep(down_at_s)
        fabric0.set_down(True)
        down_t.append(kernel.now)
        kernel.log("coordinator_down", host="host0",
                   t=round(kernel.now, 9))

    def make_gen1(rank: int):
        def body():
            ctx = RankContext(kernel, rank, ranks, generation=1)
            client = fabrics["gen1"].client(rank, caps="dir")
            with ctx.activate():
                journal = KeyJournal(jdir, rank=rank)
                kv = FencedKV(client, rank=rank, job_epoch=0,
                              generation=1, lease_s=lease_s,
                              exit_fn=ctx.request_exit,
                              journal=journal)
                kv.add_journal_prefix("hvtdur/")
                replayed[rank] = journal.replay(kv)
                committed1[rank] = 0
                for step in range(steps_after):
                    kernel.sleep(hb_s)
                    kv.key_value_set(f"hb/{rank}", str(step))
                    committed1[rank] += 1
                # every rank's journaled vote must be visible again
                while len(kv.key_value_dir_get("hvtdur/vote/")) < ranks:
                    kernel.sleep(0.1)
                votes_seen[rank] = len(
                    kv.key_value_dir_get("hvtdur/vote/"))
                recover_t[rank] = kernel.now
        return body

    def driver():
        hm = HostManager(_StaticDiscovery(hosts),
                         cooldown_base_s=cooldown_s,
                         cooldown_max_s=8 * cooldown_s)
        hm.refresh()
        all_slots = [HostSlots(h, s) for h, s in sorted(hosts.items())]
        election["old"] = _default_coordinator_addr(
            get_host_assignments(all_slots, ranks))
        # wait for every gen-0 rank to fence itself
        while not all(t.done for t in gen0_tasks.values()):
            kernel.sleep(0.2)
        hm.blacklist_host("host0")
        hm.refresh()
        surviving = [HostSlots(h, s)
                     for h, s in sorted(hm.current.items())]
        election["new"] = _default_coordinator_addr(
            get_host_assignments(surviving, ranks))
        kernel.log("coordinator_reelected", old=election["old"],
                   new=election["new"], t=round(kernel.now, 9))
        # relaunch everyone against a FRESH fabric (the relaunched
        # coordination service starts empty — the split this scenario
        # measures journal replay against)
        fabrics["gen1"] = SimFabric(kernel)
        for r in range(ranks):
            gen1_tasks[r] = kernel.spawn(f"gen1-rank{r}", make_gen1(r))
        kernel.log("relaunched", generation=1, ranks=ranks)

    try:
        with _env(HVTPU_AUDIT_EVERY="0", HVTPU_ELASTIC_STATE_DIR=None,
                  HVTPU_KV_FENCE_DISABLE=None, HVTPU_JOB_EPOCH=None):
            gen0_tasks = {r: kernel.spawn(f"rank{r}", make_gen0(r))
                          for r in range(ranks)}
            kernel.spawn("chaos", chaos)
            kernel.spawn("driver", driver)
            kernel.run(max_virtual_s=_DEF_BUDGET_S)
    finally:
        shutil.rmtree(jdir, ignore_errors=True)

    # every gen-0 rank self-fenced (closed split brain: no zombies)
    for r, t in gen0_tasks.items():
        assert t.exit_code == FENCE_EXIT_CODE, (
            f"gen-0 rank {r} exited {t.exit_code}, expected "
            f"{FENCE_EXIT_CODE}")
    fence_exits = (obs_metrics.counter("hvtpu_fence_exits_total").value()
                   - fence_exits_before)
    assert fence_exits >= ranks
    assert election["new"] != election["old"], election
    assert election["new"] != "host0"
    detect = sorted(fence_t[r] - down_t[0] for r in range(ranks))
    assert detect[0] >= 0.0
    assert detect[-1] <= lease_s + 10.0, (
        f"slowest fence took {detect[-1]}s past the outage")
    for r in range(ranks):
        assert committed0[r] == steps_before, (
            f"rank {r} gen-0 committed {committed0[r]} (outage writes "
            f"must not count)")
        assert committed1[r] == steps_after
        assert replayed[r] == 1, (
            f"rank {r} replayed {replayed[r]} keys, expected its vote")
        assert votes_seen[r] == ranks
    fence_to_recover = max(recover_t.values()) - max(fence_t.values())
    stats = {"phases": {"coordinator_loss": {
        "hosts": n_hosts,
        "down_t_s": round(down_t[0], 6),
        "detect_p50_s": round(_pct(detect, 0.50), 6),
        "detect_max_s": round(detect[-1], 6),
        "fence_exits": ranks,
        "old_coordinator": election["old"],
        "new_coordinator": election["new"],
        "replayed_keys": sum(replayed.values()),
        "fence_to_recover_s": round(fence_to_recover, 6),
        "virtual_s": round(kernel.now, 6),
    }}, "kv_ops": {"gen0": dict(fabric0.ops),
                   "gen1": dict(fabrics["gen1"].ops)}}
    return _result("coordinator-loss", ranks, seed, kernel, stats)


# ---------------------------------------------------------------------------
# partition-storm
# ---------------------------------------------------------------------------

def partition_storm(ranks: int, seed: int = 0, *,
                    window_ms: float = 3000.0, hb_s: float = 0.5,
                    stale_s: float = 2.0, suspect_s: float = 3.0,
                    lease_s: float = 1.5, n_victims: int = 3,
                    total_s: float = 10.0) -> Dict:
    """A burst of network partitions under live heartbeat evaluation:
    ``n_victims`` ranks each get a first-class ``partition(MS)`` fault
    clause (core/faults.py) that silently drops their kv.get/kv.put/
    heartbeat traffic for a seeded window.  Peers running the REAL
    AmortizedStallInspector classify them as partition SUSPECTS (blame
    held) and then see them either recover (window < stale+suspect) or
    — for the one victim carrying a KV lease — self-fence via the real
    FencedKV lease check (virtual exit 89).  Asserts: suspects are
    detected and resolved, no surviving rank latches a false stall
    failure, the leased victim fences, and the suspect-seconds
    histogram observed the episode."""
    from ..comm.stall import AmortizedStallInspector
    from ..core import faults as core_faults
    from ..core.retry import FENCE_EXIT_CODE, FencedKV
    from ..obs import metrics as obs_metrics

    n_victims = max(1, min(n_victims, max(1, ranks // 2)))
    # victims spread across the world; the LAST one carries the lease
    victims = [1 + i * max(1, (ranks - 1) // (n_victims + 1))
               for i in range(n_victims)]
    victims = sorted(set(min(v, ranks - 1) for v in victims))
    fence_victim = victims[-1]
    # the leased victim's window outlasts stale+suspect (it would be
    # classified dead) — but its lease fences it first
    fence_window_ms = (stale_s + suspect_s + 4.0) * 1000.0
    kernel, fabric = _fresh(ranks, seed)
    observer_rank = 0
    assert observer_rank not in victims
    window_open_t: Dict[int, float] = {}
    fence_t: List[float] = []
    suspect_seen_t: Dict[int, float] = {}
    suspect_gone_t: Dict[int, float] = {}
    inspectors: Dict[int, AmortizedStallInspector] = {}
    steps_done: Dict[int, int] = {}
    hist = obs_metrics.histogram("hvtpu_partition_suspect_seconds")

    def _hist_count() -> int:
        return sum(cell[2] for cell in hist._values.values())

    hist_before = _hist_count()
    fence_exits_before = obs_metrics.counter(
        "hvtpu_fence_exits_total").value()

    def make(rank: int):
        # window opens at this victim's 4th beat (count=4): peers have
        # a healthy baseline before the silence starts
        if rank == fence_victim:
            spec = (f"heartbeat:partition({fence_window_ms:g})"
                    f"@rank={rank},count=4,times=1")
        elif rank in victims:
            spec = (f"heartbeat:partition({window_ms:g})"
                    f"@rank={rank},count=4,times=1")
        else:
            spec = ""

        def body():
            ctx = RankContext(kernel, rank, ranks, fault_spec=spec,
                              generation=0)
            client = fabric.client(rank, caps="dir")

            def exit_fn(code):
                fence_t.append(kernel.now)
                ctx.request_exit(code)

            with ctx.activate():
                kv = FencedKV(
                    client, rank=rank, job_epoch=0, generation=0,
                    lease_s=(lease_s if rank == fence_victim else 0.0),
                    check_every=10_000, exit_fn=exit_fn)
                insp = AmortizedStallInspector(
                    kv, rank, warn_s=60.0, abort_s=600.0,
                    heartbeat_s=hb_s, generation=0, stale_s=stale_s,
                    suspect_s=suspect_s, start_heartbeat=False)
                inspectors[rank] = insp
                steps_done[rank] = 0
                beats = int(total_s / hb_s)
                for step in range(beats):
                    ctx.check_exit()
                    kernel.sleep(hb_s)
                    insp._beat_once()
                    # work-plane KV op: dropped inside the victim's
                    # partition window — what starves the lease
                    kv.key_value_set(f"work/{rank}", str(step))
                    steps_done[rank] += 1
                    if (rank in victims and rank not in window_open_t
                            and core_faults.partition_remaining() > 0):
                        window_open_t[rank] = kernel.now
                        kernel.log("partition_window_open", rank=rank)
                insp.stop()
        return body

    def observer():
        # watch the observer rank's inspector classify the silence
        while observer_rank not in inspectors:
            kernel.sleep(0.05)
        insp = inspectors[observer_rank]
        end = total_s + 5.0
        while kernel.now < end:
            suspects = set(insp.debug_state()["partition_suspects"])
            for v in victims:
                if v in suspects and v not in suspect_seen_t:
                    suspect_seen_t[v] = kernel.now
                if (v in suspect_seen_t and v not in suspects
                        and v not in suspect_gone_t):
                    suspect_gone_t[v] = kernel.now
            kernel.sleep(0.1)

    with _env(HVTPU_AUDIT_EVERY="0", HVTPU_PARTITION_SUSPECT_S=None,
              HVTPU_KV_FENCE_DISABLE=None, HVTPU_JOB_EPOCH=None):
        tasks = {r: kernel.spawn(f"rank{r}", make(r))
                 for r in range(ranks)}
        kernel.spawn("observer", observer)
        kernel.run(max_virtual_s=_DEF_BUDGET_S)

    assert tasks[fence_victim].exit_code == FENCE_EXIT_CODE, (
        f"leased victim exited {tasks[fence_victim].exit_code}, "
        f"expected {FENCE_EXIT_CODE}")
    fence_exits = (obs_metrics.counter("hvtpu_fence_exits_total").value()
                   - fence_exits_before)
    assert fence_exits >= 1
    for r, insp in inspectors.items():
        if r == fence_victim:
            continue
        assert insp.failure is None, (
            f"rank {r} latched a false stall failure during the "
            f"partition storm: {insp.failure}")
    recovered = [v for v in victims if v != fence_victim]
    for v in recovered:
        assert steps_done[v] == int(total_s / hb_s), (
            f"recovered victim {v} finished {steps_done[v]} steps")
        assert v in suspect_seen_t, (
            f"victim {v} was never classified a partition suspect")
        assert v in suspect_gone_t, (
            f"victim {v} never left the suspect state")
    assert _hist_count() - hist_before >= 1, (
        "the suspect-seconds histogram observed nothing")
    detect = sorted(suspect_seen_t[v] - window_open_t[v]
                    for v in victims if v in suspect_seen_t
                    and v in window_open_t)
    fence_latency = (fence_t[0] - window_open_t[fence_victim]
                     if fence_t and fence_victim in window_open_t
                     else 0.0)
    stats = {"phases": {"partition_storm": {
        "victims": victims,
        "fence_victim": fence_victim,
        "window_ms": window_ms,
        "detect_p50_s": round(_pct(detect, 0.50), 6),
        "detect_max_s": round(detect[-1], 6) if detect else 0.0,
        "fence_latency_s": round(fence_latency, 6),
        "recovered": len(recovered),
        "suspect_observations": _hist_count() - hist_before,
        "virtual_s": round(kernel.now, 6),
    }}, "kv_ops": dict(fabric.ops)}
    return _result("partition-storm", ranks, seed, kernel, stats)


# ---------------------------------------------------------------------------
# fleet-service: the production front door under a submission storm
# ---------------------------------------------------------------------------

class _ServiceJobRunner:
    """Fleet-service job handle in pure virtual time: no per-job
    kernel task, so a 5000-job storm costs O(jobs) small objects, not
    O(jobs) threads.  The REAL arbiter drives it entirely through the
    runner protocol (start/poll/phase/request_shrink/escalate/stop);
    progress, drain landings and whole-job stops are lazy functions of
    ``kernel.now`` evaluated at each reap."""

    def __init__(self, job, kernel: SimKernel, duration_s: float,
                 drain_s: float, on_start=None):
        self.name = job.spec.name
        self.kernel = kernel
        self.duration_s = duration_s
        self.drain_s = drain_s
        self.charged_restarts = 0
        self.health_dir = None
        self._on_start = on_start
        self._alloc: Dict[str, int] = {}
        self._start_t: Optional[float] = None
        self._stop_t: Optional[float] = None  # whole-job drain lands
        self._shrink: Optional[tuple] = None  # (new_np, land_t)
        self._exit: Optional[int] = None

    def start(self, alloc: Dict[str, int]) -> None:
        self._alloc = dict(alloc)
        self._start_t = self.kernel.now
        if self._on_start is not None:
            self._on_start(self)

    def _land_shrink(self) -> None:
        new_np, _land_t = self._shrink
        self._shrink = None
        # the drained gang leaves name-largest hosts first: a unique,
        # replayable trim order
        cur = sum(self._alloc.values())
        for h in sorted(self._alloc, reverse=True):
            if cur <= new_np:
                break
            drop = min(self._alloc[h], cur - new_np)
            self._alloc[h] -= drop
            cur -= drop
            if self._alloc[h] <= 0:
                del self._alloc[h]

    def _advance(self) -> None:
        now = self.kernel.now
        if self._exit is not None:
            return
        if self._shrink is not None and now >= self._shrink[1]:
            self._land_shrink()
        if self._stop_t is not None:
            if now >= self._stop_t:
                self._exit = 0
        elif (self._start_t is not None
              and now >= self._start_t + self.duration_s):
            self._exit = 0

    def poll(self) -> Optional[int]:
        self._advance()
        return self._exit

    def phase(self) -> str:
        self._advance()
        return "resizing" if self._shrink is not None else "running"

    def target_np(self) -> Optional[int]:
        return self._shrink[0] if self._shrink is not None else None

    def current_np(self) -> int:
        return sum(self._alloc.values())

    def allocation(self) -> Dict[str, int]:
        return dict(self._alloc)

    def update_allocation(self, alloc: Dict[str, int]) -> None:
        self._alloc = dict(alloc)

    def request_shrink(self, new_np: int) -> bool:
        if self._start_t is None or self._exit is not None:
            return False
        self._shrink = (new_np, self.kernel.now + self.drain_s)
        return True

    def escalate(self) -> int:
        if self._shrink is None:
            return 0
        lag = max(0, self.current_np() - self._shrink[0])
        self._land_shrink()
        return lag

    def stop(self) -> None:
        if self._exit is None and self._stop_t is None:
            self._stop_t = self.kernel.now + self.drain_s


def fleet_service(ranks: int, seed: int = 0, *,
                  n_jobs: Optional[int] = None,
                  slots_per_host: int = 8, tick_s: float = 0.5,
                  grace_s: float = 20.0, intake_budget: int = 256,
                  queue_limit: Optional[int] = None,
                  starvation_s: float = 60.0,
                  aging_slack_s: float = 150.0,
                  window_s: Optional[float] = None,
                  restart_delay_s: float = 3.0) -> Dict:
    """The production front door end to end: a seeded storm of mixed
    tenants/tiers/sizes submitted through the REAL indexed journal
    (``fleet/intake.py``) into the REAL arbiter, with per-tenant
    quotas from a real ``tenants.json``, the weighted fair-share and
    starvation-guard scheduling order, torus-aware placement, truthful
    queue-full backpressure (clients retry after the advertised
    delay), random cancels, and an injected arbiter crash that rolls
    the intake cursor back several batches mid-storm.  Asserts
    exactly-once intake across the crash (replays dedupe, nothing runs
    twice, nothing is lost), a per-tick intake cost bounded by the
    budget (O(new-entries), zero on quiet ticks), quota rejections
    that name tenant and limit, a bounded post-aging wait for the
    starved min-priority probe gang, and gang placements that never
    overcommit a host."""
    import shutil as _shutil
    import tempfile

    from ..fleet import (FleetArbiter, JobSpec, QueueFullError,
                         SubmitJournal)

    kernel, fabric = _fresh(ranks, seed)
    n_hosts = max(1, (ranks + slots_per_host - 1) // slots_per_host)
    hosts = {f"host{h:04d}": slots_per_host for h in range(n_hosts)}
    pool_slots = n_hosts * slots_per_host
    if n_jobs is None:
        # ~2.5 jobs per pool slot keeps utilisation (and therefore
        # contention) scale-invariant, capped at the 5000-submission
        # storm the intake protocol is sized for
        n_jobs = max(120, min(5000, pool_slots * 5 // 2))
    if queue_limit is None:
        queue_limit = max(64, n_jobs // 3)
    if window_s is None:
        # sized for ~0.85 pool utilisation at the mean job (3 slots x
        # 30 virtual s), floored so small pools still see a real storm
        window_s = max(240.0, n_jobs * 90.0 / (0.85 * pool_slots))
    # the journal/cursor/state.json are REAL files with real fsyncs;
    # tmpfs keeps the per-tick fsync from dominating the run (the
    # protocol under test is unchanged — same checkpoint-storm trick)
    fleet_dir = tempfile.mkdtemp(
        prefix="hvtpu-fleet-service-",
        dir="/dev/shm" if os.path.isdir("/dev/shm") else None)
    with open(os.path.join(fleet_dir, "tenants.json"), "w") as f:
        json.dump({
            "prod": {"weight": 3.0},
            "batch": {"weight": 1.0},
            "guest": {"weight": 1.0,
                      "max_ranks": max(8, pool_slots // 8),
                      "max_queued": 4},
            "*": {"weight": 1.0},
        }, f)

    # -- the seeded arrival plan (all randomness drawn up front) -------
    r = kernel.rng("fleet-service")
    tier_of = {"prod": 10, "guest": 5, "batch": 0}
    storm_t = round(window_s * 0.4, 3)
    n_storm = int(n_jobs * 0.4)
    plan: List[tuple] = []  # (t, op, payload)
    meta: Dict[str, dict] = {}  # name -> tenant/priority/duration
    for i in range(n_jobs):
        name = f"job{i:05d}"
        tenant = r.choice(("prod", "prod", "batch", "batch", "batch",
                           "guest"))
        size = r.choice((1, 1, 2, 2, 2, 4, 4, 8))
        elastic = r.random() < 0.3
        dur = round(r.uniform(15.0, 45.0), 3)
        t = (storm_t if i < n_storm
             else round(r.uniform(0.0, window_s), 3))
        spec = JobSpec(name, ["sim"], priority=tier_of[tenant],
                       min_np=size,
                       max_np=(2 * size if elastic else size),
                       tenant=tenant).to_dict()
        meta[name] = {"tenant": tenant, "priority": tier_of[tenant],
                      "duration": dur}
        plan.append((t, "submit", spec))
        if r.random() < 0.06:
            plan.append((round(t + r.uniform(0.05, 20.0), 3),
                         "cancel", name))
    # the starvation probe: a min-priority HALF-POOL gang submitted
    # right into the storm — backfill keeps eating its capacity until
    # the aging guard boosts it over every tier
    probe_np = max(2, pool_slots // 2)
    probe = JobSpec("probe-batch", ["sim"], priority=0,
                    min_np=probe_np, tenant="batch").to_dict()
    meta["probe-batch"] = {"tenant": "batch", "priority": 0,
                           "duration": 30.0}
    plan.append((storm_t, "submit", probe))
    plan.sort(key=lambda e: e[0])

    journal = SubmitJournal(fleet_dir)
    submit_t: Dict[str, float] = {}
    seq_name: Dict[int, str] = {}
    intake_c = {"queue_full": 0, "max_attempts": 0}
    submit_done: List[bool] = []
    runners: Dict[str, _ServiceJobRunner] = {}
    overcommit: List[str] = []
    gang_spread: List[int] = []

    def on_start(runner: _ServiceJobRunner) -> None:
        usage: Dict[str, int] = {}
        for rn in runners.values():
            if rn._exit is None:
                for h, n in rn._alloc.items():
                    usage[h] = usage.get(h, 0) + n
        for h, used in usage.items():
            if used > hosts[h]:
                overcommit.append(
                    f"{h}: {used}/{hosts[h]} at {runner.name}")
        gang_spread.append(len(runner._alloc))

    def hash0(name: str) -> int:
        # a tiny deterministic per-name hash (builtin hash() is
        # salted per process and would break replay)
        v = 0
        for ch in name:
            v = (v * 131 + ord(ch)) % 100003
        return v

    def make_runner(job):
        rn = _ServiceJobRunner(
            job, kernel,
            duration_s=meta[job.spec.name]["duration"],
            drain_s=round(2.0 + (hash0(job.spec.name) % 60) / 10.0, 1),
            on_start=on_start)
        runners[job.spec.name] = rn
        return rn

    def submitter():
        for t, op, payload in plan:
            if kernel.now < t:
                kernel.sleep(t - kernel.now)
            if op == "cancel":
                journal.append_cancel(payload)
                continue
            attempts = 0
            while True:
                attempts += 1
                try:
                    seq = journal.append_submit(payload)
                    break
                except QueueFullError as e:
                    # the advertised retry-after is truthful: wait it
                    # out (plus one tick of margin) and try again
                    intake_c["queue_full"] += 1
                    kernel.sleep(e.retry_after_s + tick_s)
            intake_c["max_attempts"] = max(intake_c["max_attempts"],
                                           attempts)
            submit_t[payload["name"]] = kernel.now
            seq_name[seq] = payload["name"]
        submit_done.append(True)
        kernel.log("fleet_service.submitted", jobs=len(submit_t),
                   queue_full=intake_c["queue_full"])

    arbiters: List[FleetArbiter] = []
    cursor_hist: List[bytes] = []
    crashed: List[bool] = []
    batch_sizes: List[int] = []
    frag_samples: List[float] = []
    crash_t = storm_t + 4 * tick_s

    def make_arbiter() -> FleetArbiter:
        return FleetArbiter(
            _StaticDiscovery(hosts), fleet_dir=fleet_dir,
            tick_s=tick_s, drain_grace_s=grace_s,
            runner_factory=make_runner, event_fn=kernel.log,
            register_debug=False)

    def arbiter_task():
        arb = make_arbiter()
        arbiters.append(arb)
        last_seq = 0
        ticks = 0
        while True:
            if not crashed and kernel.now >= crash_t:
                crashed.append(True)
                # injected crash BETWEEN batch-apply and cursor
                # commit: the dead incarnation's runners vanish with
                # it and the cursor wakes up several batches stale —
                # the replay must dedupe, not double-run
                for rn in runners.values():
                    if rn._exit is None:
                        rn._exit = -1
                if len(cursor_hist) >= 3:
                    with open(journal.cursor_path, "wb") as f:
                        f.write(cursor_hist[-3])
                kernel.log("fleet_service.crash",
                           live=sum(1 for j in arb.jobs.values()
                                    if not j.terminal))
                kernel.sleep(restart_delay_s)
                arb = make_arbiter()
                arbiters.append(arb)
                n = arb.recover()
                kernel.log("fleet_service.recover", jobs=n)
                last_seq = int(
                    journal.read_cursor().get("seq", 0) or 0)
            arb.tick()
            ticks += 1
            cur_seq = int(journal.read_cursor().get("seq", 0) or 0)
            batch_sizes.append(cur_seq - last_seq)
            last_seq = cur_seq
            try:
                with open(journal.cursor_path, "rb") as f:
                    cursor_hist.append(f.read())
            except OSError:
                cursor_hist.append(b"")
            del cursor_hist[:-8]
            if ticks % 16 == 0:
                with arb._lock:
                    frag_samples.append(arb._placement.fragmentation(
                        arb._free_map(), arb.hosts.current))
            if (submit_done and journal.depth() == 0
                    and arb.all_terminal()):
                break
            kernel.sleep(tick_s)
        kernel.log("fleet_service.arbiter_done", ticks=ticks,
                   jobs=len(arb.jobs))

    with _env(HVTPU_FLEET_INTAKE_BUDGET=str(intake_budget),
              HVTPU_FLEET_QUEUE_LIMIT=str(queue_limit),
              HVTPU_FLEET_STARVATION_SECONDS=str(starvation_s),
              HVTPU_ELASTIC_STATE_DIR=None, HVTPU_FLEET_DIR=None):
        kernel.spawn("submitter", submitter)
        kernel.spawn("arbiter", arbiter_task)
        try:
            kernel.run(max_virtual_s=_DEF_BUDGET_S)
        finally:
            _shutil.rmtree(fleet_dir, ignore_errors=True)

    # -- fold the event log --------------------------------------------
    first_submit: Dict[str, float] = {}
    waits: Dict[int, List[float]] = {0: [], 5: [], 10: []}
    done_counts: Dict[str, int] = {}
    rejected: Dict[str, List[str]] = {}
    aged_t: Dict[str, float] = {}
    start_t: Dict[str, float] = {}
    quota_waited: set = set()
    dup_replays = 0
    preempts = 0
    for ev in kernel.events:
        kind = ev["kind"]
        if kind == "fleet.submit":
            first_submit.setdefault(ev["job"], ev["t"])
        elif kind == "fleet.job_start":
            start_t.setdefault(ev["job"], ev["t"])
            m = meta.get(ev["job"])
            if m is not None:
                waits[m["priority"]].append(ev["queue_wait_s"])
            # the aging guard's contract: NOTHING waits past the
            # threshold without being boosted (a job_aged event)
            if ev["queue_wait_s"] > starvation_s + 2 * tick_s:
                assert ev["job"] in aged_t, (
                    f"{ev['job']} waited {ev['queue_wait_s']:.1f}s "
                    f"(> {starvation_s}s guard) without aging")
        elif kind == "fleet.job_end":
            if ev["state"] == "DONE":
                done_counts[ev["job"]] = (
                    done_counts.get(ev["job"], 0) + 1)
        elif kind == "fleet.submit_rejected":
            sq = ev["spool"]
            if sq.startswith("journal-"):
                nm = seq_name.get(int(sq[len("journal-"):]), sq)
                rejected.setdefault(nm, []).append(ev["error"])
        elif kind == "fleet.journal_duplicate":
            dup_replays += 1
        elif kind == "fleet.job_aged":
            aged_t.setdefault(ev["job"], ev["t"])
        elif kind == "fleet.quota_wait":
            quota_waited.add(ev["job"])
        elif kind == "fleet.preempt":
            preempts += 1

    # exactly-once across the crash: every accepted submission is
    # terminal in exactly one incarnation's ledger, nothing ran twice,
    # nothing was lost
    arb1 = arbiters[0]
    arb2 = arbiters[-1]
    lost = []
    for name in submit_t:
        j = arb2.jobs.get(name) or arb1.jobs.get(name)
        if j is not None and j.terminal:
            continue
        if name in rejected:
            continue  # refused with a durable, named error
        lost.append(name)
    assert not lost, f"{len(lost)} submissions lost: {lost[:5]}"
    twice = {n: c for n, c in done_counts.items() if c > 1}
    assert not twice, f"jobs completed more than once: {twice}"
    dup_rejects = [e for msgs in rejected.values() for e in msgs
                   if "already exists" in e]
    assert not dup_rejects, (
        f"replay surfaced as duplicate-name rejection: "
        f"{dup_rejects[:3]}")
    assert len(arbiters) == 2 and crashed, "crash was never injected"
    assert dup_replays >= 1, (
        "the rolled-back cursor replayed no batch — the crash window "
        "closed without exercising dedupe")
    # intake is O(new-entries): every tick applies at most the budget,
    # and quiet ticks touch zero records
    assert batch_sizes and max(batch_sizes) <= intake_budget, (
        f"a tick applied {max(batch_sizes)} records "
        f"(budget {intake_budget})")
    assert intake_c["queue_full"] >= 1, (
        "the storm never hit the queue limit — backpressure untested")
    # quota rejections are actionable: tenant and limit named
    guest_rejects = [e for msgs in rejected.values() for e in msgs
                     if "tenant 'guest'" in e and "max_queued" in e]
    assert guest_rejects, "no quota rejection named tenant 'guest'"
    # the starvation guard bounds the probe's post-aging wait: boosted
    # over every tier, it starts within the aging slack + one drain
    probe_j = (arb2.jobs.get("probe-batch")
               or arb1.jobs.get("probe-batch"))
    assert probe_j is not None and probe_j.state == "DONE", (
        f"probe ended {probe_j and probe_j.state}")
    assert ("probe-batch" in aged_t
            or (probe_j.queue_wait_s or 0.0)
            <= starvation_s + 2 * tick_s), (
        f"probe waited {probe_j.queue_wait_s}s without aging")
    if "probe-batch" in aged_t:
        gap = start_t["probe-batch"] - aged_t["probe-batch"]
        assert gap <= aging_slack_s + grace_s, (
            f"aged probe waited {gap:.1f}s past the guard "
            f"(slack {aging_slack_s}+{grace_s})")
    assert not overcommit, f"host overcommit: {overcommit[:3]}"

    lat = sorted(first_submit[n] - submit_t[n]
                 for n in submit_t if n in first_submit)
    aged_gaps = sorted(start_t[n] - aged_t[n] for n in aged_t
                       if n in start_t and n not in quota_waited)
    for w in waits.values():
        w.sort()
    frag_samples.sort()
    n_cancelled = sum(
        1 for n in submit_t
        if ((arb2.jobs.get(n) or arb1.jobs.get(n)) is not None
            and (arb2.jobs.get(n) or arb1.jobs.get(n)).cancelled))
    stats = {"phases": {
        "pool": {"hosts": n_hosts, "slots": pool_slots,
                 "jobs": n_jobs, "storm": n_storm,
                 "queue_limit": queue_limit},
        "intake": {
            "appended": len(submit_t),
            "queue_full_rejections": intake_c["queue_full"],
            "max_attempts": intake_c["max_attempts"],
            "max_batch": max(batch_sizes),
            "budget": intake_budget,
            "idle_ticks": sum(1 for b in batch_sizes if b == 0),
            "intake_p50_s": round(_pct(lat, 0.50), 6),
            "intake_p99_s": round(_pct(lat, 0.99), 6),
        },
        "admission": {
            "rejected": len(rejected),
            "quota_waits": len(quota_waited),
        },
        "crash": {"incarnations": len(arbiters),
                  "recovered": sum(
                      1 for e in kernel.events
                      if e["kind"] == "fleet.recover"),
                  "replayed_duplicates": dup_replays},
        "service": {
            "queue_wait_p50_s": {
                str(p): round(_pct(w, 0.50), 6)
                for p, w in sorted(waits.items())},
            "queue_wait_p99_s": {
                str(p): round(_pct(w, 0.99), 6)
                for p, w in sorted(waits.items())},
            "preemptions": preempts,
            "aged_jobs": len(aged_t),
            "aged_gap_max_s": (round(aged_gaps[-1], 6)
                               if aged_gaps else 0.0),
            "probe_wait_s": round(
                probe_j.queue_wait_s or 0.0, 6),
        },
        "placement": {
            "frag_mean": (round(sum(frag_samples)
                                / len(frag_samples), 6)
                          if frag_samples else 0.0),
            "frag_max": (round(frag_samples[-1], 6)
                         if frag_samples else 0.0),
            "single_host_gangs": (
                round(sum(1 for g in gang_spread if g == 1)
                      / len(gang_spread), 6) if gang_spread else 0.0),
        },
        "done": {
            "done": sum(done_counts.values()),
            "cancelled": n_cancelled,
            "virtual_s": round(kernel.now, 6),
        },
    }, "kv_ops": dict(fabric.ops)}
    return _result("fleet-service", ranks, seed, kernel, stats)


# ---------------------------------------------------------------------------
# lossy-link: wire-plane consensus abort-and-retry + route-around
# ---------------------------------------------------------------------------

def lossy_link(ranks: int, seed: int = 0, *, steps: int = 10,
               retries: int = 3, loss_prob: float = 0.2,
               flap_down_s: float = 3.0, hop_timeout_s: float = 2.0,
               consensus_s: float = 30.0, baseline: bool = False) -> Dict:
    """The wire plane under a lossy fabric: each step is one ring
    exchange (every rank sends a deterministic payload to its ring
    successor over a :class:`~.fabric.EdgeModel` data edge and blocks
    on its predecessor's, then a done-gather + commit barrier seals the
    step).  Three victims shape the chaos: ``va`` gets two
    deterministic ``wire.send:drop`` fault firings (core/faults.py,
    the new parser-gated wire site), ``vb``'s outgoing edge FLAPS for
    ``flap_down_s`` mid-run (``SimFabric.flap``), and ``vc``'s
    outgoing edge drops each send with seeded probability
    ``loss_prob``.

    Every failure runs the REAL :class:`~..comm.wirefault.WireConsensus`
    over the fabric KV — all member ranks vote attempt *k* dead before
    anyone reissues attempt *k+1* under attempt-tagged keys
    (``native/wire.py::attempt_tag``) — and rank 0 folds per-hop loss
    reports into the REAL :class:`~..comm.wirefault.LinkHealth` map,
    re-ordering the ring to demote a degraded rank to the tail.
    Asserts: zero restarts, zero torn steps (every rank delivers the
    SAME attempt), every delivered value bitwise-equal to the clean
    result for the ring in effect, ≥2 consensus retries and ≥1
    reroute.  ``baseline=True`` disables retries: the same seed must
    then poison the job, and the result records the steps lost to the
    restart-the-world recovery."""
    from ..comm import wirefault
    from ..core import faults as core_faults
    from ..native.wire import attempt_tag
    from ..obs import metrics as obs_metrics

    assert ranks >= 8, "lossy-link needs >= 8 ranks for distinct victims"
    va, vb, vc = ranks // 4, ranks // 2, (3 * ranks) // 4
    budget = 0 if baseline else max(0, retries)
    drop_step, flap_step = 1, max(3, steps // 2)
    kernel, fabric = _fresh(ranks, seed)
    fabric.set_edge(vc, (vc + 1) % ranks, loss_prob=loss_prob)
    # rank 0's view of link health, fed from the steps' loss reports
    lh = wirefault.LinkHealth(expect_s=0.5, alpha=0.3)

    retries_before = obs_metrics.counter(
        "hvtpu_collective_retries_total").value()
    reroutes_before = obs_metrics.counter(
        "hvtpu_ring_reroutes_total").value()

    members = list(range(ranks))
    delivered: Dict[int, Dict[int, tuple]] = {}  # rank -> step -> (att, v)
    orders: List[List[int]] = []                 # ring in effect per step
    retry_rounds: Dict[int, int] = {}            # step -> consensus rounds
    cons_lat: List[float] = []
    poison_box: List[dict] = []
    completed: Dict[int, int] = {}

    def value(r: int, s: int) -> int:
        # attempt-independent: a retried delivery is bitwise-equal to
        # the clean one by construction, so equality PROVES the job
        # never consumed bytes from an aborted attempt
        return (r * 1315423911 + s * 2654435761) % (2 ** 31)

    class _Lost(Exception):
        def __init__(self, why: str, frm: Optional[int] = None):
            super().__init__(why)
            self.frm = frm  # rank whose link dropped it, when known

    def make(rank: int):
        spec = (f"wire.send:drop@rank={va},count={drop_step + 1},times=2"
                if rank == va else "")

        def body():
            ctx = RankContext(kernel, rank, ranks, fault_spec=spec,
                              generation=0)
            client = fabric.client(rank, caps="dir")
            wc = wirefault.WireConsensus(
                client, rank, generation=0, deadline_s=consensus_s)
            delivered[rank] = {}
            completed[rank] = 0

            def ring_hop(step: int, attempt: int, order: List[int]):
                i = order.index(rank)
                succ = order[(i + 1) % ranks]
                pred = order[(i - 1) % ranks]
                if core_faults.ACTIVE and core_faults.inject("wire.send"):
                    raise _Lost("wire.send dropped", frm=rank)
                if fabric.edge_lost(rank, succ):
                    raise _Lost("edge dropped the send", frm=rank)
                kernel.sleep(fabric.edge_delay(rank, succ, 64))
                client.key_value_set(
                    attempt_tag(f"ll/{step}/{rank}", attempt),
                    str(value(rank, step)))
                try:
                    got = client.blocking_key_value_get(
                        attempt_tag(f"ll/{step}/{pred}", attempt),
                        int(hop_timeout_s * 1000))
                except TimeoutError:
                    raise _Lost("recv timed out", frm=pred) from None
                if core_faults.ACTIVE and core_faults.inject("wire.recv"):
                    raise _Lost("wire.recv dropped", frm=pred)
                return int(got)

            def commit(step: int, attempt: int, order: List[int],
                       got: int, lost_from: List[int]) -> None:
                client.key_value_set(
                    attempt_tag(f"ll/done/{step}", attempt) + f"/{rank}",
                    json.dumps({"v": got, "lost": lost_from}))
                if rank != 0:
                    try:
                        client.blocking_key_value_get(
                            attempt_tag(f"ll/commit/{step}", attempt),
                            int((hop_timeout_s + 2.0) * 1000))
                    except TimeoutError:
                        raise _Lost("commit timed out") from None
                    return
                prefix = attempt_tag(f"ll/done/{step}", attempt) + "/"
                deadline = kernel.now + hop_timeout_s + 1.0
                while True:
                    entries = client.key_value_dir_get(prefix)
                    if len(entries) >= ranks:
                        break
                    if kernel.now >= deadline:
                        raise _Lost("done gather timed out")
                    kernel.sleep(0.05)
                # fold the step's loss reports into the health map; a
                # demoted (>= threshold) rank gets no healthy decay —
                # its sick edge is unused, so nothing proves it healed
                for _k, v in entries:
                    for frm in json.loads(v).get("lost", []):
                        lh.observe(frm, lost=True)
                for r2 in order:
                    if lh.score(r2) < lh.degraded_score:
                        lh.observe(r2, gap_s=0.5)
                client.key_value_set(
                    attempt_tag(f"ll/commit/{step}", attempt), "ok")
                if step + 1 < steps:
                    client.key_value_set(
                        f"ll/order/{step + 1}",
                        json.dumps(lh.ring_order(order)))

            def poison(step: int, why: str) -> None:
                if not poison_box:
                    poison_box.append(
                        {"rank": rank, "step": step, "why": why})
                kernel.log("wire_poison", rank=rank, step=step)

            with ctx.activate():
                for step in range(steps):
                    if poison_box:
                        break
                    if step == 0:
                        order = list(members)
                    else:
                        order = json.loads(client.blocking_key_value_get(
                            f"ll/order/{step}", 60_000))
                    if rank == 0:
                        orders.append(list(order))
                        if step == flap_step:
                            i0 = order.index(vb)
                            fabric.flap(vb, order[(i0 + 1) % ranks],
                                        period_s=1e9, down_s=flap_down_s,
                                        start_s=kernel.now)
                            kernel.log("flap_window_open", rank=vb)
                    attempt, fails = 0, 0
                    lost_from: List[int] = []
                    while True:
                        try:
                            got = ring_hop(step, attempt, order)
                            commit(step, attempt, order, got, lost_from)
                        except _Lost as lost:
                            if lost.frm is not None:
                                lost_from.append(lost.frm)
                            fails += 1
                            if fails > budget:
                                poison(step, str(lost))
                                break
                            t0 = kernel.now
                            decision = wc.vote_and_decide(
                                "ll", step, attempt, members,
                                f"ring:{step}", False)
                            if rank == 0:
                                cons_lat.append(kernel.now - t0)
                            if decision != wirefault.RETRY:
                                poison(step, f"consensus={decision}")
                                break
                            if rank == 0:
                                wirefault.record_retry(
                                    rank, "ll", step, attempt, decision)
                                retry_rounds[step] = (
                                    retry_rounds.get(step, 0) + 1)
                            attempt += 1
                            continue
                        delivered[rank][step] = (attempt, got)
                        completed[rank] = step + 1
                        wc.cleanup("ll", step, attempt)
                        for a in range(attempt + 1):
                            client.key_value_delete(
                                attempt_tag(f"ll/{step}/{rank}", a))
                            client.key_value_delete(
                                attempt_tag(f"ll/done/{step}", a)
                                + f"/{rank}")
                        break
        return body

    with _env(HVTPU_AUDIT_EVERY="0"):
        for r in range(ranks):
            kernel.spawn(f"rank{r}", make(r))
        kernel.run(max_virtual_s=_DEF_BUDGET_S)

    retries_total = int(obs_metrics.counter(
        "hvtpu_collective_retries_total").value() - retries_before)
    reroutes = int(obs_metrics.counter(
        "hvtpu_ring_reroutes_total").value() - reroutes_before)

    if baseline:
        assert poison_box, (
            "retries disabled: the first wire loss must poison the job")
        first_lost = min(completed.values())
        steps_lost = steps - first_lost
        assert steps_lost > 0
        stats = {"phases": {"lossy_link": {
            "mode": "baseline",
            "steps": steps,
            "restarts": 1,
            "steps_completed": first_lost,
            "steps_lost": steps_lost,
            "retry_rounds": 0,
            "reroutes": reroutes,
            "torn": 0,
            "virtual_s": round(kernel.now, 6),
        }}, "kv_ops": dict(fabric.ops)}
        return _result("lossy-link", ranks, seed, kernel, stats)

    assert not poison_box, (
        f"job poisoned despite retry budget {budget}: {poison_box}")
    for r in range(ranks):
        assert completed.get(r) == steps, (
            f"rank {r} finished {completed.get(r)}/{steps} steps")
    torn = 0
    for s in range(steps):
        if len({delivered[r][s][0] for r in range(ranks)}) != 1:
            torn += 1
    assert torn == 0, f"{torn} steps delivered a torn mix of attempts"
    # bitwise equality with the clean run: values depend only on
    # (predecessor, step) for the deterministic ring in effect
    assert len(orders) == steps
    for s in range(steps):
        order = orders[s]
        for i, r in enumerate(order):
            expect = value(order[(i - 1) % ranks], s)
            assert delivered[r][s][1] == expect, (
                f"rank {r} step {s}: delivered {delivered[r][s][1]}, "
                f"clean result is {expect}")
    assert retries_total >= 2, (
        f"expected >= 2 consensus retries, saw {retries_total}")
    assert reroutes >= 1, "the flapping rank was never rerouted around"
    cons_sorted = sorted(cons_lat)
    stats = {"phases": {"lossy_link": {
        "mode": "retries",
        "steps": steps,
        "restarts": 0,
        "steps_lost": 0,
        "recovered_collectives": len(retry_rounds),
        "retry_rounds": retries_total,
        "consensus_p50_s": round(_pct(cons_sorted, 0.50), 6),
        "consensus_max_s": (round(cons_sorted[-1], 6)
                            if cons_sorted else 0.0),
        "reroutes": reroutes,
        "torn": torn,
        "edge_losses": int(fabric.ops.get("edge_lost", 0)),
        "virtual_s": round(kernel.now, 6),
    }}, "kv_ops": dict(fabric.ops)}
    return _result("lossy-link", ranks, seed, kernel, stats)


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

SCENARIOS = {
    "thundering-rendezvous": thundering_rendezvous,
    "steady-drain": steady_drain,
    "rolling-preemption": rolling_preemption,
    "kill-blacklist": kill_blacklist,
    "kv-brownout": kv_brownout,
    "straggler-tail": straggler_tail,
    "stream-matrix": stream_matrix,
    "multi-job-arbiter": multi_job_arbiter,
    "checkpoint-storm": checkpoint_storm,
    "compression-negotiation": compression_negotiation,
    "anomaly-detection": anomaly_detection,
    "coordinator-loss": coordinator_loss,
    "partition-storm": partition_storm,
    "fleet-service": fleet_service,
    "lossy-link": lossy_link,
}


def run_scenario(name: str, ranks: int, seed: int = 0, **kwargs) -> Dict:
    """Run one named scenario; raises KeyError with the catalog on an
    unknown name."""
    fn = SCENARIOS.get(name)
    if fn is None:
        raise KeyError(
            f"unknown scenario {name!r}; available: "
            f"{', '.join(sorted(SCENARIOS))}")
    return fn(ranks, seed, **kwargs)
