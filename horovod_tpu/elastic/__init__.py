"""Elastic training: dynamic world membership with commit/restore state.

Parity surface: ``hvd.elastic`` — ``horovod/common/elastic.py`` (State,
ObjectState, run), ``horovod/runner/elastic/`` (ElasticDriver,
discovery, worker notification).  See state.py / worker.py / driver.py
for the TPU-native restart-based design (SURVEY.md §7.2 hard part 3:
elasticity at slice granularity with checkpoint-based resync).

Worker-side usage (same shape as the reference)::

    import horovod_tpu as hvt
    import horovod_tpu.elastic as elastic

    hvt.init()
    state = elastic.JaxState(params=params, opt_state=opt_state,
                             epoch=0, batch=0)

    @elastic.run
    def train(state):
        while state.epoch < EPOCHS:
            ...train one epoch from state.batch...
            state.epoch += 1
            state.commit()

    train(state)

Launcher-side: ``hvtpurun --host-discovery-script ./discover.sh
--min-np 2 --max-np 8 python train.py``.
"""

from ..core.exceptions import (  # noqa: F401
    HorovodInternalError,
    HostsUpdatedInterrupt,
)
from .state import (  # noqa: F401
    JaxState,
    ObjectState,
    ShardedJaxState,
    State,
)
from .worker import RESET_EXIT_CODE, run  # noqa: F401

__all__ = [
    "State", "ObjectState", "JaxState", "ShardedJaxState", "run", "RESET_EXIT_CODE",
    "HorovodInternalError", "HostsUpdatedInterrupt",
]
