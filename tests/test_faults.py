"""core/faults.py: the deterministic fault-injection harness.

Unit tests drive the registry directly (grammar, selectors, seeded
probability, persistence); the acceptance smokes launch REAL 2-process
elastic jobs under ``HVTPU_FAULT_SPEC`` and assert (a) an injected
rank-kill at step 3 recovers within the restart budget, and (b) the
same failure under ``--max-restarts=0`` fails fast with the
restart-budget diagnostic.  The heavier matrix is marked ``chaos`` and
stays out of tier-1.
"""

import os
import subprocess
import sys
import time

import pytest

import horovod_tpu
from horovod_tpu.core import faults

pytestmark = []

_REPO = os.path.dirname(os.path.dirname(horovod_tpu.__file__))
_SCRIPT = os.path.join(_REPO, "tests", "elastic_train_script.py")


@pytest.fixture(autouse=True)
def _clean_registry():
    yield
    faults.uninstall()


class TestParse:
    def test_full_grammar(self):
        cs = faults.parse_spec(
            "worker.step:kill@rank=1,count=3; "
            "kv.put:error@prob=0.25,times=2; "
            "heartbeat:drop@rank=0|2; "
            "collective.pre:delay(250)@pset=1")
        assert [c.site for c in cs] == [
            "worker.step", "kv.put", "heartbeat", "collective.pre"]
        assert cs[0].action == "kill" and cs[0].times == 1  # kill: 1-shot
        assert cs[0].ranks == frozenset({1}) and cs[0].count == 3
        assert cs[1].prob == 0.25 and cs[1].times == 2
        assert cs[2].ranks == frozenset({0, 2}) and cs[2].times == 0
        assert cs[3].action == "delay" and cs[3].delay_ms == 250.0
        assert cs[3].pset == 1

    def test_corrupt_grammar(self):
        cs = faults.parse_spec(
            "collective.pre:corrupt@rank=1; "
            "collective.post:corrupt(bitflip)@count=2; "
            "collective.post:corrupt(nan)")
        assert [c.action for c in cs] == ["corrupt"] * 3
        assert cs[0].corrupt_mode == "nan"  # default
        assert cs[1].corrupt_mode == "bitflip" and cs[1].count == 2
        assert cs[2].corrupt_mode == "nan"
        assert cs[1].site == "collective.post"

    def test_storage_grammar(self):
        cs = faults.parse_spec(
            "ckpt.write:torn@rank=1,count=3; "
            "ckpt.write:bitflip@count=5,times=1; "
            "ckpt.fsync:drop; "
            "ckpt.rename:kill@rank=0,count=2")
        assert [c.site for c in cs] == [
            "ckpt.write", "ckpt.write", "ckpt.fsync", "ckpt.rename"]
        assert cs[0].action == "torn" and cs[0].times == 0  # unlimited
        assert cs[1].action == "bitflip" and cs[1].times == 1
        assert cs[3].action == "kill" and cs[3].times == 1

    @pytest.mark.parametrize("bad", [
        "kv.put:torn",              # torn only means something on bytes
        "worker.step:bitflip",
        "collective.pre:torn@rank=1",
    ])
    def test_storage_damage_limited_to_storage_sites(self, bad):
        with pytest.raises(faults.FaultSpecError):
            faults.parse_spec(bad)

    def test_partition_grammar(self):
        cs = faults.parse_spec(
            "kv.put:partition(3000)@rank=3; "
            "heartbeat:partition(250.5)@count=4,times=2; "
            "kv.get:partition(10)")
        assert [c.action for c in cs] == ["partition"] * 3
        assert cs[0].partition_ms == 3000.0
        assert cs[0].times == 1   # partition: 1-shot by default
        assert cs[1].partition_ms == 250.5
        assert cs[1].count == 4 and cs[1].times == 2
        assert cs[2].site == "kv.get" and cs[2].partition_ms == 10.0

    @pytest.mark.parametrize("bad", [
        "worker.step:partition(3000)",     # not a coordination site
        "collective.pre:partition(100)",
        "ckpt.write:partition(100)",
        "kv.put:partition()",              # missing window
        "kv.put:partition(abc)",
        "kv.put:partition(-5)",
    ])
    def test_partition_limited_to_coordination_sites(self, bad):
        with pytest.raises(faults.FaultSpecError):
            faults.parse_spec(bad)

    def test_wire_grammar(self):
        cs = faults.parse_spec(
            "wire.send:drop@rank=1,count=2,times=2; "
            "wire.recv:slow(250)@prob=0.5; "
            "collective.exec:flap(1500)")
        assert [c.site for c in cs] == [
            "wire.send", "wire.recv", "collective.exec"]
        assert cs[0].action == "drop" and cs[0].times == 2
        assert cs[0].ranks == frozenset({1}) and cs[0].count == 2
        assert cs[1].action == "slow" and cs[1].delay_ms == 250.0
        assert cs[1].prob == 0.5
        assert cs[2].action == "flap" and cs[2].flap_ms == 1500.0
        assert cs[2].times == 1  # flap: 1-shot by default

    @pytest.mark.parametrize("bad,msg", [
        ("wire.send:torn", "no durable bytes to tear"),
        ("wire.recv:bitflip", "no durable bytes to tear"),
        ("collective.exec:torn@rank=1", "no durable bytes to tear"),
        ("wire.send:corrupt", "no tensor to poison"),
        ("wire.recv:corrupt(bitflip)", "no tensor to poison"),
        ("wire.send:partition(100)", "coordination sites"),
    ])
    def test_wire_sites_reject_foreign_damage(self, bad, msg):
        """The wire sites carry no durable bytes and no tensor: the
        parser must name WHY the action is wrong and what to use."""
        with pytest.raises(faults.FaultSpecError, match=msg):
            faults.parse_spec(bad)

    @pytest.mark.parametrize("bad", [
        "kv.put:slow(100)",
        "worker.step:flap(500)",
        "ckpt.write:slow(10)",
        "heartbeat:flap(100)",
        "collective.pre:slow(50)",
    ])
    def test_slow_flap_limited_to_wire_sites(self, bad):
        with pytest.raises(faults.FaultSpecError,
                           match="only applies at wire sites"):
            faults.parse_spec(bad)

    @pytest.mark.parametrize("bad", [
        "wire.send:slow()",
        "wire.send:slow(abc)",
        "wire.recv:flap()",
        "wire.recv:flap(-5)",
    ])
    def test_malformed_wire_windows_fail_loudly(self, bad):
        with pytest.raises(faults.FaultSpecError):
            faults.parse_spec(bad)

    def test_empty_spec_yields_nothing(self):
        assert faults.parse_spec("") == []
        assert faults.parse_spec(" ; ; ") == []

    @pytest.mark.parametrize("bad", [
        "nosuchsite:drop",
        "kv.put:explode",
        "kv.put",
        "kv.put:drop@rank",
        "kv.put:drop@color=red",
        "kv.put:drop@prob=1.5",
        "kv.put:drop@count=0",
        "worker.step:delay(x)",
        "collective.pre:corrupt(weird)",
    ])
    def test_malformed_specs_fail_loudly(self, bad):
        with pytest.raises(faults.FaultSpecError):
            faults.parse_spec(bad)


class TestRegistry:
    def test_inactive_module_is_noop(self):
        assert faults.ACTIVE is False
        assert faults.inject("kv.put") is False

    def test_install_empty_uninstalls(self):
        faults.install("kv.put:drop")
        assert faults.ACTIVE is True
        faults.install("")
        assert faults.ACTIVE is False

    def test_rank_selector(self):
        faults.install("kv.put:drop@rank=1", rank=0)
        assert faults.inject("kv.put") is False  # rank 0: no match
        faults.install("kv.put:drop@rank=1", rank=1)
        assert faults.inject("kv.put") is True

    def test_count_fires_from_nth_invocation(self):
        faults.install("kv.put:drop@count=3", rank=0)
        assert [faults.inject("kv.put") for _ in range(5)] == [
            False, False, True, True, True]

    def test_times_caps_firings(self):
        faults.install("kv.put:drop@times=2", rank=0)
        assert [faults.inject("kv.put") for _ in range(4)] == [
            True, True, False, False]

    def test_pset_selector(self):
        faults.install("collective.pre:drop@pset=7", rank=0)
        assert faults.inject("collective.pre", pset=3) is False
        assert faults.inject("collective.pre") is False  # no pset info
        assert faults.inject("collective.pre", pset=7) is True

    def test_error_action_raises_retryable_marker(self):
        faults.install("kv.get:error", rank=0)
        with pytest.raises(faults.InjectedFault, match="UNAVAILABLE"):
            faults.inject("kv.get")

    def test_delay_action_sleeps(self):
        faults.install("worker.step:delay(80)", rank=0)
        t0 = time.monotonic()
        assert faults.inject("worker.step") is False
        assert time.monotonic() - t0 >= 0.07

    def test_prob_is_seeded_and_reproducible(self):
        def draws(seed, rank, n=64):
            faults.install("kv.put:drop@prob=0.5", rank=rank, seed=seed)
            return [faults.inject("kv.put") for _ in range(n)]

        a = draws(seed=7, rank=0)
        b = draws(seed=7, rank=0)
        c = draws(seed=8, rank=0)
        d = draws(seed=7, rank=1)
        assert a == b                      # same seed+rank: identical
        assert a != c or a != d            # different stream somewhere
        assert 5 < sum(a) < 59             # actually probabilistic

    def test_persistence_across_incarnations(self, tmp_path):
        spec = "worker.step:kill@count=3"
        # incarnation 1 "fires" (we can't os._exit in-test; simulate by
        # writing the marker the way the registry does)
        reg = faults.FaultRegistry(
            faults.parse_spec(spec), rank=1, state_dir=str(tmp_path))
        clause = reg._by_site["worker.step"][0]
        clause._fired = 1
        reg._persist_fired(clause)
        # incarnation 2 loads the spent budget: never fires again
        faults.install(spec, rank=1, state_dir=str(tmp_path))
        assert all(not faults.inject("worker.step") for _ in range(10))

    def test_unlimited_clause_ignores_state_dir(self, tmp_path):
        faults.install("kv.put:drop", rank=0, state_dir=str(tmp_path))
        assert faults.inject("kv.put") is True
        assert not (tmp_path / "faults_fired").exists()


class TestPartitionWindow:
    """A fired ``partition(MS)`` clause opens a WINDOW: unlike ``drop``
    (one lost operation), every coordination site — kv.get, kv.put,
    heartbeat — is silenced as a unit until the window expires, which
    is what a real network partition looks like to one rank."""

    @pytest.fixture()
    def tick(self):
        from horovod_tpu.core import clock as core_clock

        class _TickClock(core_clock.Clock):
            def __init__(self):
                self.t = 100.0

            def monotonic(self):
                return self.t

            def wall(self):
                return self.t

            def sleep(self, seconds):
                self.t += max(0.0, seconds)

            def call_later(self, seconds, fn):
                fn()

        fake = _TickClock()
        core_clock.install(fake)
        yield fake
        core_clock.install(None)

    def test_window_silences_all_coordination_sites(self, tick):
        faults.install("kv.put:partition(3000)", rank=0)
        assert faults.partition_remaining() == 0.0
        assert faults.inject("kv.get") is False  # window not yet open
        assert faults.inject("kv.put") is True   # trigger: opens window
        # every coordination site now drops, not just the trigger site
        assert faults.inject("kv.get") is True
        assert faults.inject("heartbeat") is True
        assert faults.inject("kv.put") is True
        assert 0.0 < faults.partition_remaining() <= 3.0

    def test_window_expires_on_clock(self, tick):
        faults.install("heartbeat:partition(500)", rank=0)
        assert faults.inject("heartbeat") is True
        tick.t += 0.4
        assert faults.inject("kv.put") is True   # still inside window
        tick.t += 0.2                            # past 500ms total
        assert faults.partition_remaining() == 0.0
        assert faults.inject("kv.put") is False
        assert faults.inject("heartbeat") is False  # times=1: spent

    def test_window_spares_non_coordination_sites(self, tick):
        faults.install("kv.put:partition(3000)", rank=0)
        assert faults.inject("kv.put") is True
        # compute/storage planes keep flowing during the partition
        assert faults.inject("worker.step") is False
        assert faults.inject("collective.pre") is False
        assert faults.inject_storage("ckpt.write") is None

    def test_count_delays_window_open(self, tick):
        faults.install("kv.get:partition(1000)@count=3", rank=0)
        assert faults.inject("kv.get") is False
        assert faults.inject("kv.get") is False
        assert faults.partition_remaining() == 0.0
        assert faults.inject("kv.get") is True   # 3rd hit opens it
        assert faults.inject("heartbeat") is True

    def test_rank_selector_scopes_window(self, tick):
        faults.install("kv.put:partition(1000)@rank=1", rank=0)
        assert faults.inject("kv.put") is False
        assert faults.partition_remaining() == 0.0


class TestFlapWindow:
    """A fired ``flap(MS)`` clause takes the WHOLE wire link down for a
    window: every wire-site operation on this rank drops until it
    expires — the link-level analog of ``partition(MS)``."""

    @pytest.fixture()
    def tick(self):
        from horovod_tpu.core import clock as core_clock

        class _TickClock(core_clock.Clock):
            def __init__(self):
                self.t = 100.0

            def monotonic(self):
                return self.t

            def wall(self):
                return self.t

            def sleep(self, seconds):
                self.t += max(0.0, seconds)

            def call_later(self, seconds, fn):
                fn()

        fake = _TickClock()
        core_clock.install(fake)
        yield fake
        core_clock.install(None)

    def test_window_drops_every_wire_site(self, tick):
        faults.install("wire.send:flap(1500)", rank=0)
        assert faults.flap_remaining() == 0.0
        assert faults.inject("wire.recv") is False  # window not open
        assert faults.inject("wire.send") is True   # trigger: opens it
        assert faults.inject("wire.recv") is True
        assert faults.inject("collective.exec") is True
        assert 0.0 < faults.flap_remaining() <= 1.5

    def test_window_spares_other_planes(self, tick):
        faults.install("wire.send:flap(1500)", rank=0)
        assert faults.inject("wire.send") is True
        # coordination/compute/storage keep flowing: the LINK is down,
        # not the rank
        assert faults.inject("kv.put") is False
        assert faults.inject("heartbeat") is False
        assert faults.inject("worker.step") is False
        assert faults.inject_storage("ckpt.write") is None

    def test_window_expires_on_clock(self, tick):
        faults.install("collective.exec:flap(500)", rank=0)
        assert faults.inject("collective.exec") is True
        tick.t += 0.4
        assert faults.inject("wire.send") is True   # inside the window
        tick.t += 0.2                               # past 500ms total
        assert faults.flap_remaining() == 0.0
        assert faults.inject("wire.send") is False
        assert faults.inject("collective.exec") is False  # times=1 spent

    def test_slow_adds_latency_without_dropping(self, tick):
        faults.install("wire.recv:slow(80)", rank=0)
        t0 = tick.t
        assert faults.inject("wire.recv") is False  # delivered, late
        assert tick.t - t0 >= 0.079

    def test_rank_selector_scopes_window(self, tick):
        faults.install("wire.send:flap(1000)@rank=1", rank=0)
        assert faults.inject("wire.send") is False
        assert faults.flap_remaining() == 0.0


def test_inactive_guard_is_zero_overhead():
    """Acceptance: with an empty fault spec the hot-path hook is one
    module-attribute read — bound it at far under a microsecond per op
    so the bench wire-bytes/latency numbers cannot regress."""
    import timeit

    assert faults.ACTIVE is False
    n = 100_000
    t = timeit.timeit(
        lambda: faults.ACTIVE and faults.inject("collective.pre"),
        number=n)
    assert t / n < 5e-6, f"{t / n * 1e9:.0f} ns/op"


class TestInjectionSites:
    """The sites are actually threaded through the framework."""

    def test_collective_pre_site(self, hvt):
        import jax.numpy as jnp

        faults.install("collective.pre:error@count=2", rank=0)
        hvt.allreduce(jnp.ones(2))  # op 1: below count
        with pytest.raises(faults.InjectedFault):
            hvt.allreduce(jnp.ones(2))

    def test_collective_pre_corrupt_poisons_input(self, hvt):
        import jax.numpy as jnp
        import numpy as np

        faults.install("collective.pre:corrupt", rank=0)
        out = hvt.allreduce(jnp.ones(4))
        assert not np.isfinite(np.asarray(out)).all()

    def test_collective_post_corrupt_poisons_result(self, hvt):
        import jax.numpy as jnp
        import numpy as np

        faults.install("collective.post:corrupt", rank=0)
        out = hvt.allreduce(jnp.ones(4))
        assert not np.isfinite(np.asarray(out)).all()
        faults.uninstall()
        clean = hvt.allreduce(jnp.ones(4))
        assert np.isfinite(np.asarray(clean)).all()

    def test_corrupt_clause_never_fires_at_non_tensor_sites(self):
        """A corrupt clause at a KV site has nothing to poison; plain
        inject() must neither fire nor consume its budget."""
        faults.install("kv.put:corrupt@times=1", rank=0)
        assert faults.inject("kv.put") is False
        assert faults.inject("kv.put") is False

    def test_storage_clause_never_fires_at_plain_inject(self):
        """A torn clause outside inject_storage has no byte stream to
        damage; plain inject() must neither fire nor spend budget
        (same argument as corrupt at non-tensor sites)."""
        faults.install("ckpt.write:torn@times=1", rank=0)
        assert faults.inject("ckpt.write") is False
        assert faults.inject_storage("ckpt.write") == "torn"

    def test_inject_storage_damage_modes(self):
        faults.install(
            "ckpt.write:bitflip@times=1; ckpt.fsync:drop@times=1",
            rank=0)
        assert faults.inject_storage("ckpt.write") == "bitflip"
        assert faults.inject_storage("ckpt.write") is None  # spent
        assert faults.inject_storage("ckpt.fsync") == "drop"

    def test_inject_storage_error_raises(self):
        faults.install("ckpt.write:error@times=1", rank=0)
        with pytest.raises(faults.InjectedFault):
            faults.inject_storage("ckpt.write")

    def test_bitflip_corrupts_non_float_dtypes(self):
        import jax.numpy as jnp
        import numpy as np

        faults.install("collective.post:corrupt(bitflip)", rank=0)
        out = faults.inject_tensor(
            "collective.post", jnp.zeros((3,), jnp.int32))
        assert int(np.asarray(out)[0]) != 0

    def test_worker_step_site_fires_at_commit(self):
        import horovod_tpu.elastic as elastic

        state = elastic.ObjectState(epoch=0)
        faults.install("worker.step:error@count=2", rank=0)
        state.commit()
        with pytest.raises(faults.InjectedFault):
            state.commit()

    def test_heartbeat_site_drops_beats(self):
        from test_stall import FakeKV

        from horovod_tpu.comm.stall import AmortizedStallInspector

        faults.install("heartbeat:drop", rank=0)
        insp = AmortizedStallInspector(
            FakeKV(), rank=0, warn_s=10, abort_s=0, heartbeat_s=0.05)
        try:
            time.sleep(0.3)
            assert insp._kv.d == {}  # every beat suppressed
            faults.uninstall()
            deadline = time.monotonic() + 2.0
            while not insp._kv.d and time.monotonic() < deadline:
                time.sleep(0.02)
            assert insp._kv.d  # beats resume once the fault clears
        finally:
            insp.stop()


# ---------------------------------------------------------------------------
# acceptance: real 2-process elastic runs under an injected rank-kill
# ---------------------------------------------------------------------------


def _launch_elastic(tmp_path, extra_args=(), epochs=5, timeout=240):
    from conftest import make_discovery_script

    _hosts, disc = make_discovery_script(tmp_path, "localhost:2")
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["ELASTIC_EPOCHS"] = str(epochs)
    env["EPOCH_SLEEP"] = "0.2"
    env["HVTPU_ELASTIC_DISCOVERY_INTERVAL"] = "0.2"
    cmd = [
        sys.executable, "-m", "horovod_tpu.runner",
        "--host-discovery-script", disc,
        "--min-np", "2", "--cpu-devices", "1", "--verbose",
        "--fault-spec", "worker.step:kill@rank=1,count=3",
        *extra_args,
        "--", sys.executable, _SCRIPT,
    ]
    res = subprocess.run(cmd, env=env, cwd=_REPO, timeout=timeout,
                         capture_output=True, text=True)
    return res, res.stdout + res.stderr


@pytest.mark.multiprocess
def test_injected_rank_kill_recovers_within_budget(tmp_path):
    """Tier-1 chaos smoke (ISSUE-2 acceptance): rank 1 is killed by the
    harness at its 3rd step; the elastic driver must relaunch within
    the restart budget and the job must reach the target epoch."""
    res, out = _launch_elastic(tmp_path, extra_args=("--max-restarts",
                                                     "3"))
    assert res.returncode == 0, out[-3000:]
    assert "fault injection: killing rank 1" in out, out[-3000:]
    assert "DONE size=2 epoch=5" in out, out[-3000:]
    # exactly one relaunch: the kill clause is one-shot (persisted
    # across incarnations through the driver's state dir)
    assert out.count("launching 2 workers") == 2, out[-3000:]


@pytest.mark.multiprocess
@pytest.mark.slow  # tier-1 runtime diet: heaviest in the --durations audit; full matrix via -m slow
def test_injected_kill_with_zero_budget_fails_fast(tmp_path):
    """The same injected death with --max-restarts=0 must NOT relaunch:
    the driver exits non-zero with the restart-budget diagnostic."""
    res, out = _launch_elastic(tmp_path, extra_args=("--max-restarts",
                                                     "0"))
    assert res.returncode != 0, out[-3000:]
    assert "restart budget exhausted" in out, out[-3000:]
    assert "DONE" not in out, out[-3000:]
    assert out.count("launching 2 workers") == 1, out[-3000:]


@pytest.mark.multiprocess
def test_coordinator_rank_kill_replays_journal(tmp_path):
    """ISSUE-17 acceptance: rank 0 — the rank on the coordinator host
    — is killed mid-run.  The startup restore quorum's votes rode the
    durable key journal (core/journal.py via the fenced quorum KV), so
    the relaunched incarnation must REPLAY them into its fresh
    coordination KV and still finish with exactly-once accounting."""
    from conftest import make_discovery_script

    _hosts, disc = make_discovery_script(tmp_path, "localhost:2")
    state_dir = tmp_path / "state"
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["ELASTIC_EPOCHS"] = "5"
    env["EPOCH_SLEEP"] = "0.2"
    env["HVTPU_ELASTIC_DISCOVERY_INTERVAL"] = "0.2"
    env["HVTPU_ELASTIC_STATE_DIR"] = str(state_dir)
    env["HVTPU_LOG_LEVEL"] = "info"  # surfaces the replay line
    cmd = [
        sys.executable, "-m", "horovod_tpu.runner",
        "--host-discovery-script", disc,
        "--min-np", "2", "--cpu-devices", "1", "--verbose",
        "--max-restarts", "3",
        "--fault-spec", "worker.step:kill@rank=0,count=3",
        "--", sys.executable, _SCRIPT,
    ]
    res = subprocess.run(cmd, env=env, cwd=_REPO, timeout=240,
                         capture_output=True, text=True)
    out = res.stdout + res.stderr
    assert res.returncode == 0, out[-4000:]
    assert "fault injection: killing rank 0" in out, out[-4000:]
    assert "DONE size=2 epoch=5" in out, out[-4000:]
    assert out.count("launching 2 workers") == 2, out[-4000:]
    # gen 0's restore-quorum votes rode the journal; the relaunch
    # replayed them into the fresh coordinator
    assert "kv journal: rank 0 replayed" in out, out[-4000:]
    journal = state_dir / "kvjournal" / "rank0.jsonl"
    assert journal.exists() and journal.read_text().strip(), (
        "quorum votes never reached the durable key journal")


@pytest.mark.multiprocess
def test_partition_lease_expiry_self_fences_no_strike(tmp_path):
    """ISSUE-17 acceptance: a partition(MS) window starves rank 1's KV
    lease mid-run; the rank must SELF-FENCE (exit FENCE_EXIT_CODE)
    rather than zombie on, and the driver must relaunch WITHOUT
    charging its host a blacklist strike."""
    from conftest import make_discovery_script

    _hosts, disc = make_discovery_script(tmp_path, "localhost:2")
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["ELASTIC_EPOCHS"] = "8"
    env["EPOCH_SLEEP"] = "0.5"  # long enough for the lease to starve
    env["HVTPU_ELASTIC_DISCOVERY_INTERVAL"] = "0.2"
    env["HVTPU_KV_LEASE_S"] = "1"
    cmd = [
        sys.executable, "-m", "horovod_tpu.runner",
        "--host-discovery-script", disc,
        "--min-np", "2", "--cpu-devices", "1", "--verbose",
        "--max-restarts", "3",
        "--fault-spec", "kv.put:partition(8000)@rank=1,count=2",
        "--", sys.executable, _SCRIPT,
    ]
    res = subprocess.run(cmd, env=env, cwd=_REPO, timeout=240,
                         capture_output=True, text=True)
    out = res.stdout + res.stderr
    assert res.returncode == 0, out[-4000:]
    assert "self-fenced (exit 89)" in out, out[-4000:]
    assert "without a blacklist strike" in out, out[-4000:]
    assert "blacklisting host" not in out, out[-4000:]
    assert "DONE size=2 epoch=8" in out, out[-4000:]
    assert out.count("launching 2 workers") == 2, out[-4000:]


@pytest.mark.multiprocess
@pytest.mark.chaos
@pytest.mark.slow  # tier-1 keeps the two smokes above; -m chaos runs this
def test_chaos_kv_error_burst_job_survives(tmp_path):
    """Chaos matrix (opt-in): a burst of injected coordination-KV
    failures must be absorbed by the retry layer — the job completes
    with no restart at all."""
    from conftest import make_discovery_script

    _hosts, disc = make_discovery_script(tmp_path, "localhost:2")
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["ELASTIC_EPOCHS"] = "4"
    env["EPOCH_SLEEP"] = "0.2"
    env["HVTPU_ELASTIC_DISCOVERY_INTERVAL"] = "0.2"
    cmd = [
        sys.executable, "-m", "horovod_tpu.runner",
        "--host-discovery-script", disc,
        "--min-np", "2", "--cpu-devices", "1", "--verbose",
        "--fault-spec", "kv.put:error@prob=0.05,times=6",
        "--", sys.executable, _SCRIPT,
    ]
    res = subprocess.run(cmd, env=env, cwd=_REPO, timeout=240,
                         capture_output=True, text=True)
    out = res.stdout + res.stderr
    assert res.returncode == 0, out[-3000:]
    assert "DONE size=2 epoch=4" in out, out[-3000:]


# ---------------------------------------------------------------------------
# acceptance (PR 15): kill mid-commit at each storage site — every rank
# recovers to the last FULLY-durable commit, never a torn/corrupt one
# ---------------------------------------------------------------------------


def _launch_storage_chaos(tmp_path, fault_spec, epochs=5, timeout=240):
    """2-proc elastic run with the durable commit protocol under the
    given storage fault spec.  Epoch N's snapshot is commit N, and each
    commit is exactly two ckpt.write/fsync/rename invocations (payload,
    then manifest), so count=3 targets commit 2's payload op."""
    from conftest import make_discovery_script

    _hosts, disc = make_discovery_script(tmp_path, "localhost:2")
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["ELASTIC_EPOCHS"] = str(epochs)
    env["EPOCH_SLEEP"] = "0.2"
    env["HVTPU_ELASTIC_DISCOVERY_INTERVAL"] = "0.2"
    cmd = [
        sys.executable, "-m", "horovod_tpu.runner",
        "--host-discovery-script", disc,
        "--min-np", "2", "--cpu-devices", "1", "--verbose",
        "--max-restarts", "3",
        "--fault-spec", fault_spec,
        "--", sys.executable, _SCRIPT,
    ]
    res = subprocess.run(cmd, env=env, cwd=_REPO, timeout=timeout,
                         capture_output=True, text=True)
    return res, res.stdout + res.stderr


def _assert_rolled_back_to_last_durable(res, out):
    assert res.returncode == 0, out[-4000:]
    assert "fault injection: killing rank 0" in out, out[-4000:]
    # rank 0 (the ObjectState writer rank) died mid-commit of epoch
    # 2's snapshot, so the last fully-durable commit is epoch 1.  The
    # restore quorum must land every rank there — never on the torn
    # attempt — which replays epoch 1: the epoch-1 line prints twice.
    assert out.count("EPOCH epoch=1 ") == 2, out[-4000:]
    assert "DONE size=2 epoch=5" in out, out[-4000:]
    assert out.count("launching 2 workers") == 2, out[-4000:]


@pytest.mark.multiprocess
def test_kill_mid_commit_at_ckpt_write_recovers(tmp_path):
    """Tier-1 storage-chaos smoke: rank 0 dies inside the payload
    write of commit 2 (ckpt.write invocation 3).  The torn attempt has
    no manifest, so it never existed as far as restore is concerned."""
    res, out = _launch_storage_chaos(
        tmp_path, "ckpt.write:kill@rank=0,count=3")
    _assert_rolled_back_to_last_durable(res, out)


@pytest.mark.multiprocess
@pytest.mark.chaos
@pytest.mark.slow  # tier-1 keeps the ckpt.write smoke; -m chaos runs all 3
def test_kill_mid_commit_at_ckpt_fsync_recovers(tmp_path):
    res, out = _launch_storage_chaos(
        tmp_path, "ckpt.fsync:kill@rank=0,count=3")
    _assert_rolled_back_to_last_durable(res, out)


@pytest.mark.multiprocess
@pytest.mark.chaos
@pytest.mark.slow  # tier-1 keeps the ckpt.write smoke; -m chaos runs all 3
def test_kill_mid_commit_at_ckpt_rename_recovers(tmp_path):
    res, out = _launch_storage_chaos(
        tmp_path, "ckpt.rename:kill@rank=0,count=3")
    _assert_rolled_back_to_last_durable(res, out)


@pytest.mark.multiprocess
def test_bitflip_snapshot_rejected_with_fallback(tmp_path):
    """Acceptance: a bitflip-corrupted snapshot (commit 2's payload)
    parses as committed but fails sha256 verification at restore; the
    restore falls back to the previous retained snapshot and the
    quorum lands every rank on epoch 1."""
    res, out = _launch_storage_chaos(
        tmp_path,
        "ckpt.write:bitflip@rank=0,count=3,times=1; "
        "worker.step:kill@rank=0,count=3")
    assert res.returncode == 0, out[-4000:]
    assert "bitflip storage damage" in out, out[-4000:]
    # the corrupt commit 2 must be SKIPPED: both ranks replay epoch 1
    assert out.count("EPOCH epoch=1 ") == 2, out[-4000:]
    assert "DONE size=2 epoch=5" in out, out[-4000:]
