"""Fabric simulator: the real control plane at virtual scale, under
chaos, deterministically (horovod_tpu/sim + tools/hvtpusim).

Tier-1 runs the fast matrix — every scenario at 256 virtual ranks in
well under a minute each — covering rendezvous, a coordinated drain
with exactly-once durable-commit accounting, a kill + HostManager
blacklist round-trip, and a KV error burst absorbed by the retry
plane.  The 1024/4096-rank versions of the same scenarios (including
the acceptance command, ``rolling-preemption --ranks 1024 --seed 7``)
are ``slow``-marked: same code, more wall-clock.

Every scenario asserts its own protocol invariants internally (the
scenario *is* the test harness); the tests here additionally pin the
reported stats and the determinism/replay contract: same seed ⇒
byte-identical event log, different seed ⇒ different log.
"""

import pytest

from horovod_tpu.sim import (DeadlockError, SimKernel,
                             SimTimeBudgetExceeded, run_scenario)
from horovod_tpu.sim.scenarios import SCENARIOS, thundering_rendezvous

pytestmark = pytest.mark.sim


# ---------------------------------------------------------------------------
# kernel contract
# ---------------------------------------------------------------------------


class TestKernel:
    def test_deadlock_detection_names_parked_tasks(self):
        from horovod_tpu.sim.kernel import WaitToken

        k = SimKernel(seed=0)

        def body():
            k.block(WaitToken(), None, "waiting for a put that never comes")

        k.spawn("stuck", body)
        with pytest.raises(DeadlockError, match="stuck.*never comes"):
            k.run()

    def test_virtual_time_budget(self):
        k = SimKernel(seed=0)
        k.spawn("sleeper", lambda: k.sleep(1e6))
        with pytest.raises(SimTimeBudgetExceeded):
            k.run(max_virtual_s=10.0)

    def test_cancelled_timeout_does_not_advance_virtual_time(self):
        # A blocking get with a 600s timeout that resolves in 1ms must
        # leave the clock at ~1ms, not drag it to the timeout horizon.
        from horovod_tpu.sim.kernel import WaitToken

        k = SimKernel(seed=0)
        token = WaitToken()
        k.spawn("getter", lambda: k.block(token, 600.0, "get"))
        k.spawn("putter", lambda: (k.sleep(0.001), k.notify(token)))
        k.run()
        assert k.now < 1.0, f"stale timeout advanced the clock: {k.now}"

    def test_task_error_propagates(self):
        k = SimKernel(seed=0)

        def boom():
            raise ValueError("protocol bug")

        k.spawn("bad", boom)
        with pytest.raises(ValueError, match="protocol bug"):
            k.run()

    def test_named_rng_streams_are_seed_deterministic(self):
        a = SimKernel(seed=7).rng("victims").random()
        b = SimKernel(seed=7).rng("victims").random()
        c = SimKernel(seed=8).rng("victims").random()
        d = SimKernel(seed=7).rng("other").random()
        assert a == b
        assert a != c
        assert a != d


# ---------------------------------------------------------------------------
# fast chaos matrix: 256 virtual ranks in tier-1
# ---------------------------------------------------------------------------


class TestFastChaosMatrix:
    def test_thundering_rendezvous_256(self):
        r = run_scenario("thundering-rendezvous", 256, seed=7)
        stats = r["stats"]["phases"]["rendezvous"]
        assert stats["virtual_s"] > 0
        assert stats["p50_s"] <= stats["p99_s"] <= stats["virtual_s"]
        # the audit allgather is all-to-all: P*(P-1) reads + P posts
        assert r["stats"]["kv_ops"]["put"] == 256
        assert r["stats"]["kv_ops"]["get"] == 256 * 255

    def test_rendezvous_pinpoints_divergent_rank_256(self):
        # one rank hashes a different tree; the REAL audit plane must
        # name exactly that rank (asserted inside the scenario)
        r = thundering_rendezvous(256, seed=7, diverge_rank=81)
        assert r["stats"]["phases"]["rendezvous"]["virtual_s"] > 0

    def test_steady_drain_exactly_once_256(self):
        # scenario asserts: all survivors land on the SAME drain
        # commit, the departing rank exits DRAIN_EXIT_CODE, and the
        # durable-commit count matches the exactly-once expectation
        # (every policy boundary plus the forced drain commit, no
        # double-commit) — here we pin the reported latency contract
        r = run_scenario("steady-drain", 256, seed=7, steps=4,
                         durable_every=2)
        drain = r["stats"]["phases"]["drain"]
        assert drain["drain_commit"] >= 1
        assert 0 < drain["notice_to_commit_s"] < drain["grace_s"]

    def test_kill_blacklist_256(self):
        r = run_scenario("kill-blacklist", 256, seed=7)
        blk = r["stats"]["phases"]["blacklist"]
        adm = r["stats"]["phases"]["readmission"]
        assert blk["host"] == r["stats"]["phases"]["kill"]["host"]
        assert blk["cooldown_s"] > 0
        assert blk["strikes"] == 1
        # cooldown expiry on the virtual clock readmitted the host
        # (strike persistence across readmission is asserted inside
        # the scenario's driver task)
        assert adm["event"] == "readmitted"
        assert adm["changed"] is True

    def test_kv_brownout_256(self):
        r = run_scenario("kv-brownout", 256, seed=7, steps=3)
        brown = r["stats"]["phases"]["brownout"]
        assert brown["kv_retries"] > 0, "no injected error was retried"
        assert brown["audits"] == 3 * 256

    def test_multi_job_arbiter_256(self):
        # two jobs, one pool: the scenario itself asserts gang
        # placement (never partial), per-job exactly-once accounting,
        # and that every victim left through the drain channel (zero
        # charged restarts).  Here we pin the external contract: both
        # finish, the preemption shows up as planned exits, and the
        # measured arbiter latencies are sane.
        r = run_scenario("multi-job-arbiter", 256, seed=7)
        pre = r["stats"]["phases"]["preempt"]
        done = r["stats"]["phases"]["done"]
        assert pre["victims"] == 128
        assert pre["queue_wait_s"] > 0
        assert 0 < pre["notice_to_commit_s"] < pre["resize_s"]
        assert done["lo_final_np"] == 128 and done["hi_np"] == 128
        assert r["stats"]["phases"]["inject"]["lo_incarnations"] == [
            256, 256, 128]

    def test_fleet_service_256(self):
        # the scenario itself asserts the front-door contract
        # (exactly-once intake across the injected crash,
        # budget-bounded per-tick cost, named quota rejections, the
        # starvation guard's bounded wait, no host overcommit); here
        # we pin the external shape of the measured rows
        r = run_scenario("fleet-service", 256, seed=7)
        ph = r["stats"]["phases"]
        assert ph["pool"]["jobs"] == 640
        intake = ph["intake"]
        assert 0 < intake["max_batch"] <= intake["budget"]
        assert intake["queue_full_rejections"] >= 1
        assert intake["idle_ticks"] > 0, (
            "no quiet tick — the O(new-entries) claim is unobserved")
        assert intake["intake_p99_s"] >= intake["intake_p50_s"] > 0
        assert ph["crash"]["incarnations"] == 2
        assert ph["crash"]["recovered"] > 0
        assert ph["crash"]["replayed_duplicates"] >= 1
        assert ph["admission"]["rejected"] > 0
        assert ph["service"]["aged_jobs"] >= 1
        assert ph["service"]["preemptions"] >= 1
        assert 0.0 <= ph["placement"]["frag_mean"] <= 1.0
        assert ph["done"]["done"] > 0.7 * 640

    def test_checkpoint_storm_256(self):
        # the scenario itself asserts the durable-plane contract
        # (torn commit never lands, bitflip rejected by hashes, one
        # agreed restore point verified on every rank); here we pin
        # the measured latency rows the bench embeds
        r = run_scenario("checkpoint-storm", 256, seed=7)
        commit = r["stats"]["phases"]["commit"]
        quorum = r["stats"]["phases"]["restore_quorum"]
        assert commit["commits"] == 256 * 4
        assert 0 < commit["commit_p50_s"] <= commit["commit_p99_s"]
        assert quorum["agreed_seq"] == 3
        assert quorum["torn_rank"] != quorum["bitflip_rank"]
        assert 0 < quorum["quorum_p50_s"] <= quorum["quorum_max_s"]

    def test_compression_negotiation_256(self):
        # int8 sidecar agreement through the real controller: the
        # scenario asserts identical per-rank schedules and dtype
        # separation; pin the external shape here
        r = run_scenario("compression-negotiation", 256, seed=7)
        neg = r["stats"]["phases"]["negotiate"]
        assert neg["cycles"] == 4
        assert neg["sidecar_responses"] == 4
        assert 0 < neg["cycle_p50_s"] <= neg["cycle_max_s"]

    def test_coordinator_loss_256(self):
        # the scenario itself asserts the recovery contract (every
        # gen-0 rank exits FENCE_EXIT_CODE once the coordinator host
        # dies, a DIFFERENT host wins the re-election, and journal
        # replay republishes every durable vote into the fresh KV);
        # here we pin the measured detect/recover rows the bench embeds
        r = run_scenario("coordinator-loss", 256, seed=7)
        loss = r["stats"]["phases"]["coordinator_loss"]
        assert loss["fence_exits"] == 256
        assert loss["new_coordinator"] != loss["old_coordinator"]
        assert loss["replayed_keys"] == 256
        assert 0 < loss["detect_p50_s"] <= loss["detect_max_s"]
        assert loss["fence_to_recover_s"] > 0

    def test_partition_storm_256(self):
        # scenario asserts: partitioned-but-thawed victims are held as
        # SUSPECT (not blamed dead) and recover, while the victim whose
        # lease expires self-fences with zero post-thaw writes accepted
        r = run_scenario("partition-storm", 256, seed=7)
        storm = r["stats"]["phases"]["partition_storm"]
        assert len(storm["victims"]) == 3
        assert storm["recovered"] == 2
        assert storm["suspect_observations"] >= 1
        assert 0 < storm["detect_p50_s"] <= storm["detect_max_s"]
        assert storm["fence_latency_s"] > 0

    def test_lossy_link_8(self):
        # the scenario itself asserts the wire-plane contract (zero
        # restarts, zero torn steps, every delivered value bitwise-
        # equal to the clean ring result, >= 2 consensus retries and
        # >= 1 reroute around the flapping link); here we pin the
        # external shape of the recovery rows the bench embeds.  8
        # ranks keeps the tier-1 smoke sub-second; 64/1024 run below.
        r = run_scenario("lossy-link", 8, seed=3)
        ll = r["stats"]["phases"]["lossy_link"]
        assert ll["mode"] == "retries"
        assert ll["restarts"] == 0 and ll["steps_lost"] == 0
        assert ll["torn"] == 0
        assert ll["retry_rounds"] >= 2
        assert ll["recovered_collectives"] >= 1
        assert ll["reroutes"] >= 1
        assert 0 < ll["consensus_p50_s"] <= ll["consensus_max_s"]
        assert ll["edge_losses"] >= 1

    def test_lossy_link_baseline_restart_cost_8(self):
        # same seed, retries disabled: the FIRST wire loss poisons the
        # job (the pre-PR-20 fail-stop behavior) and the steps after it
        # are lost to the restart — the recovery-vs-restart comparison
        # the BENCH_SCALING rows quantify
        r = run_scenario("lossy-link", 8, seed=3, baseline=True)
        ll = r["stats"]["phases"]["lossy_link"]
        assert ll["mode"] == "baseline"
        assert ll["restarts"] == 1
        assert ll["steps_lost"] > 0
        assert ll["retry_rounds"] == 0

    def test_stream_matrix_64(self):
        # split-burst + forced mispredict + membership-change-free
        # shutdown interleavings on the streamed plane; 256-rank and
        # up run in the slow tier
        r = run_scenario("stream-matrix", 64, seed=7)
        assert r["stats"]["phases"]["warmup"]["predicted_bursts"] > 0


# ---------------------------------------------------------------------------
# determinism / replay contract
# ---------------------------------------------------------------------------


def _dump(result):
    import json

    return "".join(
        json.dumps(rec, sort_keys=True) + "\n" for rec in result["events"])


class TestDeterminism:
    @pytest.mark.parametrize(
        "name", ["steady-drain", "kill-blacklist", "multi-job-arbiter",
                 "checkpoint-storm", "compression-negotiation",
                 "coordinator-loss", "partition-storm",
                 "fleet-service", "lossy-link"])
    def test_same_seed_byte_identical(self, name):
        a = _dump(run_scenario(name, 64, seed=7))
        b = _dump(run_scenario(name, 64, seed=7))
        assert a == b
        assert a, "scenario produced an empty event log"

    def test_different_seed_diverges(self):
        a = _dump(run_scenario("kv-brownout", 32, seed=7, steps=2))
        b = _dump(run_scenario("kv-brownout", 32, seed=8, steps=2))
        assert a != b, "chaos timing ignores the seed"

    def test_catalog_is_complete(self):
        assert set(SCENARIOS) == {
            "thundering-rendezvous", "steady-drain", "rolling-preemption",
            "kill-blacklist", "kv-brownout", "straggler-tail",
            "stream-matrix", "multi-job-arbiter", "checkpoint-storm",
            "compression-negotiation", "anomaly-detection",
            "coordinator-loss", "partition-storm", "fleet-service",
            "lossy-link"}
        with pytest.raises(KeyError, match="steady-drain"):
            run_scenario("no-such-scenario", 8)


# ---------------------------------------------------------------------------
# scale tier (slow): 1024 / 4096 virtual ranks
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestScale:
    def test_rolling_preemption_1024_acceptance(self):
        # the acceptance command: python -m tools.hvtpusim run
        # rolling-preemption --ranks 1024 --seed 7
        r = run_scenario("rolling-preemption", 1024, seed=7)
        final = r["stats"]["phases"]["final"]
        assert final["world_size"] == 1024 - 2  # one departure per wave
        assert final["resumed_step"] > 0

    def test_rolling_preemption_256(self):
        r = run_scenario("rolling-preemption", 256, seed=7)
        assert r["stats"]["phases"]["final"]["world_size"] == 254

    def test_stream_matrix_256(self):
        r = run_scenario("stream-matrix", 256, seed=7)
        assert r["stats"]["phases"]["warmup"]["predicted_bursts"] > 0

    def test_multi_job_arbiter_1024(self):
        # acceptance scale: python -m tools.hvtpusim run
        # multi-job-arbiter --ranks 1024 --seed 7
        r = run_scenario("multi-job-arbiter", 1024, seed=7)
        pre = r["stats"]["phases"]["preempt"]
        assert pre["victims"] == 512
        assert r["stats"]["phases"]["done"]["hi_np"] == 512

    def test_checkpoint_storm_1024(self):
        r = run_scenario("checkpoint-storm", 1024, seed=7)
        quorum = r["stats"]["phases"]["restore_quorum"]
        assert quorum["agreed_seq"] == 3
        assert quorum["quorum_max_s"] > 0

    def test_coordinator_loss_1024(self):
        r = run_scenario("coordinator-loss", 1024, seed=7)
        loss = r["stats"]["phases"]["coordinator_loss"]
        assert loss["fence_exits"] == 1024
        assert loss["replayed_keys"] == 1024
        assert loss["fence_to_recover_s"] > 0

    def test_partition_storm_1024(self):
        r = run_scenario("partition-storm", 1024, seed=7)
        storm = r["stats"]["phases"]["partition_storm"]
        assert storm["recovered"] == len(storm["victims"]) - 1
        assert storm["detect_max_s"] > 0

    def test_lossy_link_1024_acceptance(self):
        # the PR-20 acceptance command: python -m tools.hvtpusim run
        # lossy-link --ranks 1024 --seed 7 — seeded drops + a flap
        # window at 1024 virtual ranks completes with ZERO restarts
        # and ZERO torn collectives (asserted inside the scenario,
        # with every retried delivery bitwise-equal to the clean run)
        r = run_scenario("lossy-link", 1024, seed=7)
        ll = r["stats"]["phases"]["lossy_link"]
        assert ll["restarts"] == 0 and ll["torn"] == 0
        assert ll["retry_rounds"] >= 2
        assert ll["reroutes"] >= 1

    def test_thundering_rendezvous_4096(self):
        r = run_scenario("thundering-rendezvous", 4096, seed=7)
        assert r["stats"]["kv_ops"]["put"] == 4096

    def test_fleet_service_4096(self):
        # the 5000-submission storm: the full front door at fleet
        # scale (intake stays budget-bounded, the crash replay
        # dedupes, quotas reject by name)
        r = run_scenario("fleet-service", 4096, seed=7)
        ph = r["stats"]["phases"]
        assert ph["pool"]["jobs"] == 5000
        assert 0 < ph["intake"]["max_batch"] <= 256
        assert ph["intake"]["queue_full_rejections"] >= 1
        assert ph["crash"]["replayed_duplicates"] >= 1
        assert ph["admission"]["rejected"] > 0

    def test_fleet_service_16384(self):
        r = run_scenario("fleet-service", 16384, seed=7)
        ph = r["stats"]["phases"]
        assert ph["pool"]["slots"] == 16384
        assert ph["pool"]["jobs"] == 5000
        assert 0 < ph["intake"]["max_batch"] <= 256
        assert ph["crash"]["incarnations"] == 2
        assert ph["done"]["done"] > 0.8 * 5000
