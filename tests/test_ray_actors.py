"""RayExecutor's actor path (VERDICT r4 #5), driven by a MOCKED ray
module — the same pattern the reference uses to unit-test its launcher
with mocked ssh (SURVEY §4.3).  Asserts actors are created with the
requested resources, each rank's env carries the launcher-equivalent
topology, results come back rank-ordered, and shutdown kills actors.
"""

import sys
import types

import pytest


class _FakeRef:
    """Stands in for a Ray ObjectRef."""

    def __init__(self, value):
        self.value = value


class _FakeMethod:
    def __init__(self, bound):
        self._bound = bound

    def remote(self, *args, **kwargs):
        return _FakeRef(self._bound(*args, **kwargs))


class _FakeActorHandle:
    def __init__(self, instance):
        self._instance = instance
        self.killed = False

    def __getattr__(self, name):
        return _FakeMethod(getattr(self._instance, name))


def _make_fake_ray(node_ips):
    """A minimal in-process ray: remote() records resource opts and
    wraps the class so .remote() constructs instances synchronously;
    node_info is overridden to walk the scripted node ip list."""
    ray = types.ModuleType("ray")
    state = {
        "remote_opts": [], "actors": [], "killed": [],
        "ips": list(node_ips), "next_ip": 0, "next_port": 29600,
    }
    ray._state = state

    def is_initialized():
        return True

    def remote(**opts):
        state["remote_opts"].append(opts)

        class _Factory:
            def __init__(self, cls):
                self._cls = cls

            def remote(self):
                inst = self._cls()
                ip = state["ips"][state["next_ip"] % len(state["ips"])]
                state["next_ip"] += 1
                state["next_port"] += 1
                port = state["next_port"]

                def node_info():
                    return ip, port

                inst.node_info = node_info
                h = _FakeActorHandle(inst)
                state["actors"].append(h)
                return h

        return _Factory

    def get(refs):
        if isinstance(refs, list):
            return [r.value for r in refs]
        return refs.value

    def kill(handle):
        handle.killed = True
        state["killed"].append(handle)

    ray.is_initialized = is_initialized
    ray.remote = remote
    ray.get = get
    ray.kill = kill
    return ray


@pytest.fixture
def fake_ray(monkeypatch):
    ray = _make_fake_ray(["10.0.0.1", "10.0.0.1", "10.0.0.2", "10.0.0.2"])
    monkeypatch.setitem(sys.modules, "ray", ray)
    import horovod_tpu.ray as ray_mod

    class _IsolatedWorker(ray_mod._ActorWorker):
        """The fake actors run IN-PROCESS: setup must not leak
        HVTPU_* into the test process's os.environ."""

        def setup(self, env):
            self.env = dict(env)
            return True

    monkeypatch.setattr(ray_mod, "_ActorWorker", _IsolatedWorker)
    return ray


class TestRayActorPath:
    def test_actors_created_with_resources(self, fake_ray):
        import horovod_tpu.ray as ray_mod

        ex = ray_mod.RayExecutor(num_workers=4, cpus_per_worker=3)
        ex.start()
        st = fake_ray._state
        # resource request reached ray.remote; one actor per rank
        assert st["remote_opts"] == [{"num_cpus": 3}]
        assert len(st["actors"]) == 4
        ex.shutdown()

    def test_env_assignment_and_rank_order(self, fake_ray):
        import horovod_tpu.ray as ray_mod

        recorded = []

        class RecordingWorker(ray_mod._ActorWorker):
            def setup(self, env):
                recorded.append(dict(env))
                self.env = dict(env)
                return True

            def execute(self, fn, args=(), kwargs=None):
                return (int(self.env["HVTPU_RANK"]),
                        fn(*args, **(kwargs or {})))

        orig = ray_mod._ActorWorker
        ray_mod._ActorWorker = RecordingWorker
        try:
            ex = ray_mod.RayExecutor(num_workers=4)
            ex.start()
            results = ex.run(lambda a: a * 2, args=(21,))
        finally:
            ray_mod._ActorWorker = orig
        assert [int(e["HVTPU_RANK"]) for e in recorded] == [0, 1, 2, 3]
        assert all(e["HVTPU_SIZE"] == "4" for e in recorded)
        # two ranks per fake node: local/cross topology per host
        assert [e["HVTPU_LOCAL_RANK"] for e in recorded] == \
            ["0", "1", "0", "1"]
        assert all(e["HVTPU_LOCAL_SIZE"] == "2" for e in recorded)
        assert [e["HVTPU_CROSS_RANK"] for e in recorded] == \
            ["0", "0", "1", "1"]
        assert all(e["HVTPU_CROSS_SIZE"] == "2" for e in recorded)
        assert all(e["HVTPU_UNIFORM_LOCAL_SIZE"] == "2" for e in recorded)
        # every rank points at rank 0's node for coordination
        addr0 = recorded[0]["HVTPU_COORDINATOR_ADDR"]
        port0 = recorded[0]["HVTPU_COORDINATOR_PORT"]
        assert addr0 == "10.0.0.1"
        assert all(e["HVTPU_COORDINATOR_ADDR"] == addr0 for e in recorded)
        assert all(e["HVTPU_COORDINATOR_PORT"] == port0 for e in recorded)
        # results come back rank-ordered
        assert results == [(0, 42), (1, 42), (2, 42), (3, 42)]

    def test_run_remote_returns_refs_execute_resolves(self, fake_ray):
        import horovod_tpu.ray as ray_mod

        ex = ray_mod.RayExecutor(num_workers=2)
        ex.start()
        refs = ex.run_remote(lambda: "x")
        assert all(isinstance(r, _FakeRef) for r in refs)
        assert ex.execute(refs) == ["x", "x"]
        ex.shutdown()

    def test_shutdown_kills_actors(self, fake_ray):
        import horovod_tpu.ray as ray_mod

        ex = ray_mod.RayExecutor(num_workers=3)
        ex.start()
        ex.shutdown()
        st = fake_ray._state
        assert len(st["killed"]) == 3
        assert ex._actors is None

    def test_env_vars_forwarded(self, fake_ray):
        import horovod_tpu.ray as ray_mod

        recorded = []

        class RecordingWorker(ray_mod._ActorWorker):
            def setup(self, env):
                recorded.append(dict(env))
                return True

        orig = ray_mod._ActorWorker
        ray_mod._ActorWorker = RecordingWorker
        try:
            ex = ray_mod.RayExecutor(
                num_workers=2, env_vars={"MY_FLAG": "7"})
            ex.start()
        finally:
            ray_mod._ActorWorker = orig
        assert all(e["MY_FLAG"] == "7" for e in recorded)

    def test_gpu_request_forwarded(self, fake_ray):
        import horovod_tpu.ray as ray_mod

        ex = ray_mod.RayExecutor(num_workers=1, use_gpu=True,
                                 gpus_per_worker=2)
        ex.start()
        assert fake_ray._state["remote_opts"][-1] == {
            "num_cpus": 1, "num_gpus": 2}
        ex.shutdown()


class TestLocalFallback:
    def test_no_ray_module_falls_back(self, monkeypatch):
        """ray not importable: start() arms the local path and run()
        still goes through the launcher machinery."""
        monkeypatch.setitem(sys.modules, "ray", None)
        import horovod_tpu.ray as ray_mod

        assert ray_mod._probe_ray() is None
        ex = ray_mod.RayExecutor(num_workers=2)
        ex.start()
        assert ex._actors is None

    def test_uninitialized_ray_falls_back(self, monkeypatch):
        ray = types.ModuleType("ray")
        ray.is_initialized = lambda: False
        monkeypatch.setitem(sys.modules, "ray", ray)
        import horovod_tpu.ray as ray_mod

        assert ray_mod._probe_ray() is None
