"""Torch-frontend MNIST example — the horovod_tpu port surface of the
reference's examples/pytorch/pytorch_mnist.py: only the import line
changes (``import horovod.torch as hvd`` -> ``import horovod_tpu.torch
as hvd``).  Synthetic MNIST-shaped data keeps it hermetic.

Run:  hvtpurun -np 2 --cpu-devices 1 python examples/pytorch_mnist.py
"""

import argparse

import numpy as np
import torch
import torch.nn as nn
import torch.nn.functional as F

import horovod_tpu.torch as hvd


class Net(nn.Module):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(784, 128)
        self.fc2 = nn.Linear(128, 10)

    def forward(self, x):
        x = F.relu(self.fc1(x))
        return F.log_softmax(self.fc2(x), dim=1)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--train-size", type=int, default=2048)
    args = p.parse_args()

    hvd.init()
    torch.manual_seed(42 + hvd.rank())

    rng = np.random.RandomState(0)
    x = rng.rand(args.train_size, 784).astype(np.float32)
    w = rng.randn(784, 10).astype(np.float32)
    y = (x @ w).argmax(axis=1)

    # DistributedSampler analog: shard by rank.
    n = len(x) // hvd.size()
    lo = hvd.rank() * n
    data = torch.from_numpy(x[lo:lo + n])
    target = torch.from_numpy(y[lo:lo + n])

    model = Net()
    optimizer = torch.optim.SGD(model.parameters(), lr=args.lr)

    # Horovod idiom: broadcast start state, wrap the optimizer.
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(optimizer, root_rank=0)
    optimizer = hvd.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters()
    )

    for epoch in range(args.epochs):
        perm = torch.randperm(
            n, generator=torch.Generator().manual_seed(epoch)
        )
        for i in range(0, n - args.batch_size + 1, args.batch_size):
            idx = perm[i:i + args.batch_size]
            optimizer.zero_grad()
            loss = F.nll_loss(model(data[idx]), target[idx])
            loss.backward()
            optimizer.step()
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss={loss.item():.4f}", flush=True)

    # Ranks must stay in lockstep under averaged gradients.
    csum = torch.cat([p.detach().reshape(-1) for p in model.parameters()])
    sums = hvd.allgather(csum.sum().reshape(1))
    assert torch.allclose(sums, sums[0]), sums
    if hvd.rank() == 0:
        print(f"final loss {loss.item():.4f}; ranks consistent "
              f"({hvd.size()} ranks)", flush=True)


if __name__ == "__main__":
    main()
