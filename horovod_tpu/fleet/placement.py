"""Topology-aware placement: the pool as a torus grid, contiguity-
scored carving, fragmentation-minimizing backfill, and a measured
fragmentation metric.

TPU pods wire hosts into an ICI torus; a gang spread across distant
hosts pays cross-slice hops on every ring step.  ``core/topology.py``
models the *device* mesh inside one job — this module models the
*host* grid the arbiter carves jobs from.  Host names carry no
coordinates, so the grid is virtual but stable: the sorted host-name
list is folded row-major onto a near-square 2-D torus, giving every
pool the same deterministic geometry on every arbiter (and every
simulator) incarnation.

Carving policy, replacing the PR 14 name-order greedy:

1. **Tightest single-host fit** — a gang that fits on one host takes
   the host with the LEAST free capacity that still fits (classic
   best-fit), keeping big contiguous hosts whole for big gangs.
2. **Anchored torus walk** — a multi-host gang anchors on the host
   with the most free slots (ties: name order) and grows outward in
   (torus-distance, name) order, so allocations stay contiguous and
   the leftover free space stays clustered rather than checkerboarded.
3. **Near-set preference** — expansion / autoscale-grow passes the
   job's current hosts as ``near``; slots on or adjacent to them win.

The **fragmentation metric** is external fragmentation over the torus:
``1 - largest connected free region / total free slots`` (hosts with
free capacity, 4-neighbour torus adjacency).  0.0 means all free
capacity is one contiguous region (any fitting gang can be placed
contiguously); values near 1.0 mean the free space is confetti.

Thread safety: a :class:`PlacementPolicy` is owned by the arbiter and
only touched under its ``_lock``; the grid cache is plain state.
"""

from __future__ import annotations

import math
from typing import Dict, Iterable, List, Optional, Tuple

from ..obs import metrics as obs_metrics

__all__ = ["TorusGrid", "PlacementPolicy"]

_M_FRAG = obs_metrics.gauge(
    "hvtpu_fleet_fragmentation",
    "External fragmentation of the fleet pool's free capacity on the "
    "virtual host torus: 1 - largest contiguous free region / total "
    "free slots (0 = one contiguous region).")


class TorusGrid:
    """Sorted host names folded row-major onto a near-square 2-D
    torus; distances are wrap-around Manhattan.

    Non-square pools leave the last row partial (``last_w`` cells
    wide), so the torus is irregular: wraps fold within the VALID
    extent of the row/column in question — the last row wraps at its
    own width, a column over the missing tail wraps one row short —
    rather than landing on missing cells.  :meth:`neighbors` and
    :meth:`distance` use the same folded geometry, so every neighbor
    is at distance 1 and connectivity (the fragmentation metric's
    view) never disagrees with proximity (the carver's view)."""

    def __init__(self, hosts: Iterable[str]):
        self.names: List[str] = sorted(hosts)
        n = len(self.names)
        self.cols = max(1, int(math.ceil(math.sqrt(n))))
        self.rows = max(1, int(math.ceil(n / self.cols)))
        # width of the (possibly partial) last row: == cols when the
        # grid is a full rectangle
        self.last_w = n - (self.rows - 1) * self.cols
        self.coord: Dict[str, Tuple[int, int]] = {
            h: (i // self.cols, i % self.cols)
            for i, h in enumerate(self.names)}

    def _row_w(self, r: int) -> int:
        return self.last_w if r == self.rows - 1 else self.cols

    def _col_h(self, c: int) -> int:
        return self.rows if c < self.last_w else self.rows - 1

    def distance(self, a: str, b: str) -> int:
        (ra, ca), (rb, cb) = self.coord[a], self.coord[b]
        dr = abs(ra - rb)
        dc = abs(ca - cb)
        # wrap extents match neighbors(): a same-row pair wraps at
        # that row's width, a same-column pair at that column's
        # height; mixed pairs can route through full rows/columns
        w = self._row_w(ra) if ra == rb else self.cols
        hgt = self._col_h(ca) if ca == cb else self.rows
        return min(dr, max(0, hgt - dr)) + min(dc, max(0, w - dc))

    def neighbors(self, h: str) -> List[str]:
        r, c = self.coord[h]
        w = self._row_w(r)
        hgt = self._col_h(c)
        cand = []
        if hgt > 1:
            cand += [((r - 1) % hgt, c), ((r + 1) % hgt, c)]
        if w > 1:
            cand += [(r, (c - 1) % w), (r, (c + 1) % w)]
        out: List[str] = []
        for nr, nc in cand:
            n = self.names[nr * self.cols + nc]
            if n != h and n not in out:
                out.append(n)
        return out


class PlacementPolicy:
    """Deterministic, fragmentation-minimizing slot carving over a
    cached :class:`TorusGrid` of the current pool."""

    def __init__(self):
        self._grid: Optional[TorusGrid] = None
        self._grid_key: Optional[Tuple[str, ...]] = None

    def grid_for(self, hosts: Iterable[str]) -> TorusGrid:
        key = tuple(sorted(hosts))
        if key != self._grid_key:
            self._grid = TorusGrid(key)
            self._grid_key = key
        return self._grid

    # -- carving ---------------------------------------------------------
    def carve(self, free: Dict[str, int], n: int,
              pool_hosts: Iterable[str],
              near: Optional[Iterable[str]] = None) -> Dict[str, int]:
        """Carve ``n`` slots out of ``free`` (mutated in place, like
        the old ``_take``), preferring a tight single-host fit, else a
        contiguous torus walk from the best anchor (or from ``near``,
        the job's existing hosts, when expanding)."""
        out: Dict[str, int] = {}
        if n <= 0:
            return out
        grid = self.grid_for(pool_hosts)
        avail = {h: c for h, c in free.items() if c > 0}
        near_set = set(near or ()) & set(grid.coord)
        if not near_set:
            # best-fit: smallest host that holds the whole gang
            fits = sorted((c, h) for h, c in avail.items() if c >= n)
            if fits:
                _, h = fits[0]
                out[h] = n
                free[h] -= n
                return out
        anchor = self._anchor(avail, grid, near_set)
        if anchor is None:
            return out
        order = sorted(
            avail,
            key=lambda h: (min((grid.distance(h, a)
                                for a in (near_set or {anchor})),
                               default=0), h))
        for h in order:
            if n <= 0:
                break
            got = min(avail[h], n)
            if got > 0:
                out[h] = out.get(h, 0) + got
                free[h] -= got
                n -= got
        return out

    @staticmethod
    def _anchor(avail: Dict[str, int], grid: TorusGrid,
                near_set) -> Optional[str]:
        if not avail:
            return None
        if near_set:
            # expanding: anchor on an existing host
            return sorted(near_set)[0]
        # fresh gang: anchor where the most capacity lives
        return sorted(avail, key=lambda h: (-avail[h], h))[0]

    # -- fragmentation ---------------------------------------------------
    def fragmentation(self, free: Dict[str, int],
                      pool_hosts: Iterable[str]) -> float:
        """External fragmentation of the free capacity (see module
        docstring); publishes the ``hvtpu_fleet_fragmentation``
        gauge."""
        grid = self.grid_for(pool_hosts)
        avail = {h: c for h, c in free.items()
                 if c > 0 and h in grid.coord}
        total = sum(avail.values())
        if total <= 0:
            _M_FRAG.set(0.0)
            return 0.0
        seen = set()
        largest = 0
        for h in sorted(avail):
            if h in seen:
                continue
            stack, comp = [h], 0
            seen.add(h)
            while stack:
                cur = stack.pop()
                comp += avail[cur]
                for nb in grid.neighbors(cur):
                    if nb in avail and nb not in seen:
                        seen.add(nb)
                        stack.append(nb)
            largest = max(largest, comp)
        frag = 1.0 - largest / total
        _M_FRAG.set(frag)
        return frag
