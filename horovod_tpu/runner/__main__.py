"""``python -m horovod_tpu.runner`` == ``hvtpurun``."""

import sys

from .launch import main

sys.exit(main())
