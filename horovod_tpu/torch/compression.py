"""Gradient compression for the torch frontend (parity:
horovod/torch/compression.py ``Compression.none`` / ``Compression.fp16``).

Wire compression for the torch eager path casts before the collective
and casts back after; on TPU the cast itself runs as an XLA fusion once
the tensor crosses into the engine, so these classes only carry the
*intent* (wire dtype) — the math lives in horovod_tpu.comm.compression.
"""

from __future__ import annotations

import torch


class Compressor:
    """Interface: compress(tensor) -> (tensor, ctx); decompress(tensor, ctx)."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class NoneCompressor(Compressor):
    pass


class FP16Compressor(Compressor):
    """Cast fp32/fp64 gradients to fp16 on the wire, cast back after."""

    @staticmethod
    def compress(tensor: torch.Tensor):
        if tensor.dtype in (torch.float32, torch.float64):
            return tensor.to(torch.float16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor: torch.Tensor, ctx):
        if ctx is not None:
            return tensor.to(ctx)
        return tensor


class BF16Compressor(Compressor):
    """bfloat16 wire format — the TPU-native choice (same exponent range
    as fp32, so no overflow risk on un-normalized gradient sums)."""

    @staticmethod
    def compress(tensor: torch.Tensor):
        if tensor.dtype in (torch.float32, torch.float64):
            return tensor.to(torch.bfloat16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor: torch.Tensor, ctx):
        if ctx is not None:
            return tensor.to(ctx)
        return tensor


class Compression:
    """Namespace matching ``hvd.Compression``."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
