"""Elastic fault-injection integration tests (reference pattern:
test/integration/elastic_common.py — launch real `horovodrun
--host-discovery-script` jobs on localhost, kill workers / mutate the
discovery output mid-run, assert recovery).

Here: `hvtpurun --host-discovery-script` with CPU workers.  World
reconfiguration is restart-based (see horovod_tpu/elastic/): workers
exit RESET_EXIT_CODE at commit boundaries and the driver relaunches
them; progress resumes from the durable commit.
"""

import os
import subprocess
import sys
import time

import pytest

import horovod_tpu

pytestmark = pytest.mark.multiprocess

_REPO = os.path.dirname(os.path.dirname(horovod_tpu.__file__))
_SCRIPT = os.path.join(_REPO, "tests", "elastic_train_script.py")


def _make_discovery(tmp_path, spec: str):
    hosts_file = tmp_path / "hosts.txt"
    hosts_file.write_text(spec + "\n")
    script = tmp_path / "discover.sh"
    script.write_text(f'#!/bin/sh\ncat "{hosts_file}"\n')
    script.chmod(0o755)
    return hosts_file, str(script)


def _launch(discovery_script, extra_env=None, min_np=2, max_np=None,
            epochs=6, sleep_s=0.3):
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["ELASTIC_EPOCHS"] = str(epochs)
    env["EPOCH_SLEEP"] = str(sleep_s)
    env["HVTPU_ELASTIC_DISCOVERY_INTERVAL"] = "0.2"
    env.update(extra_env or {})
    cmd = [
        sys.executable, "-m", "horovod_tpu.runner",
        "--host-discovery-script", discovery_script,
        "--min-np", str(min_np),
        "--cpu-devices", "1", "--verbose",
    ]
    if max_np:
        cmd += ["--max-np", str(max_np)]
    cmd += ["--", sys.executable, _SCRIPT]
    return subprocess.Popen(
        cmd, env=env, cwd=_REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )


def test_worker_crash_recovers_from_commit(tmp_path):
    """Kill one worker mid-run (self-crash, one incarnation only): the
    driver must relaunch and training must RESUME from the committed
    epoch, not restart from zero."""
    _, disc = _make_discovery(tmp_path, "localhost:2")
    marker = tmp_path / "crashed.marker"
    proc = _launch(
        disc,
        extra_env={
            "CRASH_MARKER": str(marker),
            "CRASH_RANK": "1",
            "CRASH_EPOCH": "2",
        },
        min_np=2, epochs=5,
    )
    out, _ = proc.communicate(timeout=240)
    assert proc.returncode == 0, out[-3000:]
    assert marker.exists(), "crash injection never fired"
    # worker output arrives rank-prefixed ("[0]<stdout>:EPOCH ...")
    epochs_seen = [
        int(ln.split("epoch=")[1].split()[0])
        for ln in out.splitlines() if "EPOCH epoch=" in ln
    ]
    # the crash happened at epoch 2; the relaunched incarnation must
    # resume from the commit (>= 2), never replay epochs 0/1
    crash_at = epochs_seen.index(2)
    assert all(e >= 2 for e in epochs_seen[crash_at:]), out[-3000:]
    assert epochs_seen[0] == 0, out[-3000:]  # first incarnation from 0
    assert "DONE size=2 epoch=5" in out, out[-3000:]


def test_discovery_shrink_resizes_world(tmp_path):
    """Rewrite the discovery output mid-run (3 -> 2 slots): the driver
    must notify workers (SIGUSR1), relaunch at the new size, and the
    job must finish with size=2 while keeping committed progress."""
    hosts_file, disc = _make_discovery(tmp_path, "localhost:3")
    proc = _launch(disc, min_np=2, epochs=10, sleep_s=0.4)
    shrunk = False
    lines = []
    start = time.monotonic()
    for line in proc.stdout:
        lines.append(line.rstrip())
        if not shrunk and "EPOCH epoch=1 " in line:
            hosts_file.write_text("localhost:2\n")
            shrunk = True
        if time.monotonic() - start > 240:
            proc.kill()
            pytest.fail("timeout:\n" + "\n".join(lines[-40:]))
    proc.wait(timeout=30)
    out = "\n".join(lines)
    assert proc.returncode == 0, out[-3000:]
    assert shrunk, out[-2000:]
    assert any("size=3" in ln for ln in lines), out[-3000:]
    assert "DONE size=2 epoch=10" in out, out[-3000:]
