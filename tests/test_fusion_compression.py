"""Fusion bucketing + wire compression tests (parity targets:
FusionBufferManager semantics, Compression.fp16, EQuARX-style int8)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.comm import Compression, ReduceOp
from horovod_tpu.comm.fusion import (
    fused_tree_allreduce,
    plan_buckets,
    plan_for_tree,
)

AXIS = "world"


def mesh8():
    return Mesh(np.asarray(jax.devices(), dtype=object), (AXIS,))


class TestBucketPlan:
    def _leaves(self, sizes):
        return [np.zeros((s,), np.float32) for s in sizes]

    def test_deterministic_sorted_order(self):
        names = ["b", "a", "c"]
        plan = plan_buckets(names, self._leaves([4, 4, 4]), 1 << 30)
        flat = [e.name for b in plan.buckets for e in b]
        assert flat == ["a", "b", "c"]

    def test_threshold_splits(self):
        # 4 tensors of 256B with a 512B threshold → 2 buckets of 2.
        names = list("abcd")
        plan = plan_buckets(names, self._leaves([64] * 4), 512)
        assert plan.num_buckets == 2
        assert all(len(b) == 2 for b in plan.buckets)

    def test_oversized_tensor_gets_own_bucket(self):
        names = ["big", "s1", "s2"]
        plan = plan_buckets(names, self._leaves([1024, 2, 2]), 128)
        sizes_per_bucket = [[e.name for e in b] for b in plan.buckets]
        assert ["big"] in sizes_per_bucket

    def test_plan_for_tree_names_are_paths(self):
        tree = {"layer1": {"w": np.zeros((2, 2), np.float32)},
                "layer0": np.zeros((3,), np.float32)}
        plan, _ = plan_for_tree(tree, 1 << 30)
        names = [e.name for b in plan.buckets for e in b]
        assert names == sorted(names)
        assert any("layer1" in n and "w" in n for n in names)


class TestFusedTreeAllreduce:
    def _run(self, tree, **kw):
        def body(t):
            return fused_tree_allreduce(
                t, axis_name=AXIS, threshold_bytes=kw.pop("threshold", 64),
                **kw,
            )

        return jax.jit(
            jax.shard_map(
                body, mesh=mesh8(), in_specs=(P(),), out_specs=P(),
                check_vma=False,
            )
        )(tree)

    def test_sum_across_replicas(self):
        tree = {"a": jnp.ones((3, 3)), "b": {"c": jnp.full((5,), 2.0)}}
        out = self._run(tree, op=ReduceOp.SUM)
        np.testing.assert_allclose(np.asarray(out["a"]), np.full((3, 3), 8.0))
        np.testing.assert_allclose(np.asarray(out["b"]["c"]), np.full((5,), 16.0))

    def test_average(self):
        tree = {"a": jnp.full((4,), 3.0)}
        out = self._run(tree, op=ReduceOp.AVERAGE)
        np.testing.assert_allclose(np.asarray(out["a"]), np.full((4,), 3.0))

    def test_mixed_dtypes_roundtrip(self):
        tree = {"w": jnp.ones((4,), jnp.bfloat16), "b": jnp.ones((2,), jnp.float32)}
        out = self._run(tree, op=ReduceOp.SUM)
        assert out["w"].dtype == jnp.bfloat16
        assert out["b"].dtype == jnp.float32

    def test_compressed_bucket(self):
        tree = {"a": jnp.full((64,), 0.125), "b": jnp.full((32,), 0.25)}
        out = self._run(tree, op=ReduceOp.SUM, compression=Compression.bf16)
        np.testing.assert_allclose(np.asarray(out["a"]), np.full((64,), 1.0))

    def test_adasum_fused(self):
        tree = {"a": jnp.ones((8,))}
        out = self._run(tree, op=ReduceOp.ADASUM)
        # identical inputs → adasum keeps the gradient
        np.testing.assert_allclose(np.asarray(out["a"]), np.ones((8,)), rtol=1e-4)


class TestCompressionRoundtrip:
    @pytest.mark.parametrize("comp,tol", [
        (Compression.fp16, 1e-3), (Compression.bf16, 1e-2),
        (Compression.int8, 2e-2),
    ])
    def test_roundtrip_error(self, comp, tol):
        rng = np.random.RandomState(11)
        x = jnp.asarray(rng.randn(1000).astype(np.float32))
        wire, ctx = comp.compress(x)
        back = comp.decompress(wire, ctx)
        assert back.dtype == x.dtype
        assert back.shape == x.shape
        err = np.abs(np.asarray(back) - np.asarray(x)).max()
        assert err < tol * np.abs(np.asarray(x)).max() + 1e-6

    def test_none_is_identity(self):
        x = jnp.arange(5.0)
        wire, ctx = Compression.none.compress(x)
        assert wire is x
        assert Compression.none.decompress(wire, ctx) is x

    def test_int_tensors_pass_through(self):
        x = jnp.arange(5, dtype=jnp.int32)
        wire, ctx = Compression.fp16.compress(x)
        assert wire.dtype == jnp.int32

    def test_int8_nonmultiple_block(self):
        x = jnp.asarray(np.random.RandomState(0).randn(1000).astype(np.float32))
        wire, ctx = Compression.int8.compress(x)
        back = Compression.int8.decompress(wire, ctx)
        assert back.shape == (1000,)

    def test_from_name(self):
        assert Compression.from_name("fp16") is Compression.fp16
        with pytest.raises(ValueError):
            Compression.from_name("zstd")


class TestStochasticInt8Wire:
    """The int8_stochastic compressor must actually dither the
    allreduce wire (regression: spmd routed every int8 compressor to
    the deterministic quantized path, leaving stochastic inert) with a
    TRACED per-(rank, payload) key (regression: a Python-side seed
    baked into the jit program at trace time — same dither every step
    and on every rank)."""

    def _allreduce(self, x, compression):
        from horovod_tpu.comm import spmd

        def body(xs):
            return spmd.allreduce(
                xs[0], axis_name=AXIS, op=ReduceOp.SUM,
                compression=compression,
            )

        return jax.jit(
            jax.shard_map(
                body, mesh=mesh8(), in_specs=(P(AXIS),), out_specs=P(),
                check_vma=False,
            )
        )(x)

    def test_dither_decorrelates_identical_ranks(self):
        # The phase-1 mechanics in isolation (the end-to-end error is
        # dominated by the phase-2 requantization of the 8x-larger
        # reduced values, which is common to both rounding modes):
        # 8 ranks quantizing IDENTICAL data deterministically produce
        # bit-equal errors, so the summed error is 8x the per-rank
        # error (mean ~ 2*scale); independent per-rank dither is
        # unbiased and cancels ~sqrt(8)-style (mean ~ 0.9*scale).
        from horovod_tpu.comm.quantized import _quantize

        rng = np.random.RandomState(21)
        row = rng.randn(1, 8192).astype(np.float32)
        want = row[0] * 8.0

        def summed(keys):
            total = np.zeros(8192, np.float64)
            for r in range(8):
                q, s = _quantize(jnp.asarray(row),
                                 key=None if keys is None else keys[r])
                deq = (np.asarray(q, np.float64)
                       * np.asarray(s, np.float64)).reshape(-1)
                total += deq
            return total

        det = np.abs(summed(None) - want).mean()
        keys = [jax.random.fold_in(jax.random.key(7), r) for r in range(8)]
        stoch = np.abs(summed(keys) - want).mean()
        assert stoch < 0.7 * det, (stoch, det)

    def test_stochastic_error_bound(self):
        rng = np.random.RandomState(22)
        x = jnp.asarray(rng.randn(8, 4096).astype(np.float32))
        out = np.asarray(self._allreduce(x, Compression.int8_stochastic))
        want = np.asarray(x).sum(0)
        # floor(x+u) errors are <= 1 scale-unit per rank per phase
        amax = np.abs(np.asarray(x)).max()
        assert np.abs(out - want).max() <= (8 + 1) * 2 * amax / 127

    def test_dither_varies_with_payload(self):
        # the traced key folds the payload bits, so two different
        # inputs see different dither patterns under ONE jit trace
        rng = np.random.RandomState(23)
        a = rng.randn(8, 2048).astype(np.float32)
        b = a + np.float32(1e-6)
        err_a = np.asarray(self._allreduce(jnp.asarray(a),
                                           Compression.int8_stochastic))
        err_b = np.asarray(self._allreduce(jnp.asarray(b),
                                           Compression.int8_stochastic))
        # same values to fp32-block-scale precision, different dither
        assert not np.array_equal(err_a, err_b)

    def test_stochastic_skips_ring_kernel(self, monkeypatch):
        # HVTPU_QUANTIZED_RING routes int8 through the deterministic
        # per-hop ring; the stochastic compressor must keep the XLA
        # dithered path (documented semantics win over the ring opt-in)
        monkeypatch.setenv("HVTPU_QUANTIZED_RING", "1")
        monkeypatch.setenv("HVTPU_PALLAS_INTERPRET", "1")
        from horovod_tpu.ops import ring as ring_mod

        calls = []
        real = ring_mod.ring_allreduce
        monkeypatch.setattr(
            ring_mod, "ring_allreduce",
            lambda *a, **kw: (calls.append(kw), real(*a, **kw))[1],
        )
        rng = np.random.RandomState(24)
        x = jnp.asarray(rng.randn(8, 2048).astype(np.float32))
        self._allreduce(x, Compression.int8_stochastic)
        assert not calls
