"""Elastic training script driving the ASYNC eager controller with
schedule prediction on (the default): used by the preempt-vs-prediction
chaos test.  Each epoch runs a steady same-shape burst of async
allreduces through the controller — so by the time the injected
preemption notice lands on rank 1, predicted cycles are in flight —
then commits.  The drain commit quiesces the controller: in-flight
predictions must confirm (or roll back to full negotiation) before the
emergency commit persists anything they produced.
"""

import os
import sys
import time

import horovod_tpu as hvt
import horovod_tpu.elastic as elastic


def main():
    hvt.init()
    epochs = int(os.environ.get("ELASTIC_EPOCHS", "6"))
    sleep_s = float(os.environ.get("EPOCH_SLEEP", "0.3"))
    state = elastic.ObjectState(epoch=0, total=0.0)

    @elastic.run
    def train(state):
        import jax.numpy as jnp
        import numpy as np

        while state.epoch < epochs:
            hs = [
                hvt.allreduce_async(jnp.full((8,), 1.0), name=f"pd/{i}",
                                    op=hvt.Sum)
                for i in range(3)
            ]
            for h in hs:
                out = hvt.synchronize(h)
                got = float(np.asarray(out)[0])
                want = float(hvt.size())
                assert got == want, (got, want, state.epoch)
            state.epoch += 1
            time.sleep(sleep_s)
            state.commit()
        if hvt.rank() == 0:
            from horovod_tpu.obs import metrics as obs_metrics

            pred = obs_metrics.counter(
                "hvtpu_controller_predicted_cycles_total").value()
            misp = obs_metrics.counter(
                "hvtpu_controller_mispredicts_total").value()
            print(
                f"DONE size={hvt.size()} epoch={state.epoch} "
                f"predicted={pred:.0f} mispredicts={misp:.0f}",
                flush=True,
            )

    train(state)


if __name__ == "__main__":
    main()
