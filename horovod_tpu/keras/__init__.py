"""Keras frontend (parity: ``horovod/keras/__init__.py`` +
``horovod/tensorflow/keras/``): ``hvd.DistributedOptimizer`` for keras
optimizers, callbacks, and the shared engine surface.

Usage (only the import changes vs. the reference)::

    import horovod_tpu.keras as hvd

    hvd.init()
    opt = keras.optimizers.SGD(0.01 * hvd.size())
    opt = hvd.DistributedOptimizer(opt)
    model.compile(optimizer=opt, ...)
    model.fit(..., callbacks=[
        hvd.callbacks.BroadcastGlobalVariablesCallback(0),
        hvd.callbacks.MetricAverageCallback(),
    ])
"""

from __future__ import annotations

import horovod_tpu as _hvt

from ..tensorflow import (  # noqa: F401
    Adasum,
    Average,
    Compression,
    HorovodInternalError,
    HostsUpdatedInterrupt,
    Max,
    Min,
    Product,
    ProcessSet,
    Sum,
    add_process_set,
    allgather,
    allgather_object,
    allreduce,
    alltoall,
    barrier,
    broadcast,
    broadcast_object,
    broadcast_variables,
    ccl_built,
    cross_rank,
    cross_size,
    cuda_built,
    ddl_built,
    gloo_built,
    gloo_enabled,
    grouped_allgather,
    grouped_allreduce,
    grouped_reducescatter,
    init,
    is_initialized,
    join,
    local_rank,
    local_size,
    mpi_built,
    mpi_enabled,
    mpi_threads_supported,
    nccl_built,
    rank,
    remove_process_set,
    rocm_built,
    shutdown,
    size,
    start_timeline,
    stop_timeline,
    xla_built,
)
from . import callbacks  # noqa: F401
from . import elastic  # noqa: F401  (hvd.elastic.KerasState parity)


def DistributedOptimizer(optimizer, name=None,
                         device_dense="", device_sparse="",
                         compression=Compression.none,
                         sparse_as_dense=False, op=Average,
                         gradient_predivide_factor: float = 1.0,
                         backward_passes_per_step: int = 1,
                         average_aggregated_gradients: bool = True,
                         process_set=None):
    """Wrap a keras optimizer with gradient allreduce (parity:
    horovod.keras.DistributedOptimizer)."""
    from .._keras import create_distributed_optimizer

    return create_distributed_optimizer(
        optimizer, name=name, compression=compression, op=op,
        gradient_predivide_factor=gradient_predivide_factor,
        backward_passes_per_step=backward_passes_per_step,
        average_aggregated_gradients=average_aggregated_gradients,
        process_set=process_set,
    )


def load_model(filepath, custom_optimizers=None, custom_objects=None,
               compression=Compression.none):
    """Load a saved keras model with its optimizer wrapped in
    ``DistributedOptimizer`` (parity: horovod.keras.load_model /
    horovod.tensorflow.keras.load_model).  The optimizer deserializes
    INTO the wrapped class, so saved optimizer state (iterations,
    Adam m/v slots) restores and subsequent fits allreduce gradients
    — resuming a single-rank checkpoint distributed is the
    reference's canonical use."""
    import keras

    from .._keras import load_model_impl

    return load_model_impl(
        keras, filepath, custom_optimizers=custom_optimizers,
        custom_objects=custom_objects, compression=compression)
