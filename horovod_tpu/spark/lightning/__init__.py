"""Lightning estimator: param-compatible N/A shim.

Parity surface: ``horovod/spark/lightning/ (LightningEstimator)``.
pytorch-lightning is not a dependency of this build, so a TESTED port
is impossible here; this shim keeps the reference's import path and
constructor signature importable and fails fast with guidance instead
of an AttributeError deep inside user code.  The supported migration
is ``horovod_tpu.spark.TorchEstimator`` with a plain ``nn.Module`` —
or install lightning and drive your ``LightningModule``'s
``training_step`` yourself (see docs/migration.md, "Estimator
surface").
"""

from __future__ import annotations

_GUIDANCE = (
    "LightningEstimator is not available in this build: "
    "pytorch-lightning is not a dependency. Migrate to "
    "horovod_tpu.spark.TorchEstimator with a plain nn.Module "
    "(same fit(df)->Model->transform lifecycle over a Store), or "
    "install pytorch-lightning and invoke your LightningModule's "
    "training_step from a TorchEstimator loss callable. See "
    "docs/migration.md section 'Estimator surface: edges and scope'."
)


class LightningEstimator:
    """Reference-shaped constructor that raises with migration
    guidance (parity: horovod/spark/lightning/estimator.py)."""

    def __init__(self, model=None, *, num_proc=None, backend=None,
                 store=None, loader_num_epochs=None, input_shapes=None,
                 feature_cols=None, label_cols=None, validation=None,
                 batch_size=None, epochs=None, verbose=None,
                 callbacks=None, random_seed=None, run_id=None,
                 train_steps_per_epoch=None,
                 validation_steps_per_epoch=None,
                 transformation_fn=None, **kwargs):
        raise ImportError(_GUIDANCE)


class LightningModel:
    def __init__(self, *args, **kwargs):
        raise ImportError(_GUIDANCE)


# The reference exports the lightning estimator under this name
# (horovod/spark/lightning/__init__.py: `from ...estimator import
# TorchEstimator`) — keep the upstream import path working.
TorchEstimator = LightningEstimator
TorchModel = LightningModel
