"""obs/profile.py — xplane device-trace op summarizer.

The wire-format parser is validated against a hand-encoded xplane
buffer (exact bytes, no TF/protobuf dependency) and against a live
jax.profiler capture (host plane on this CPU test platform; the
device-plane path is the same code, validated on real TPU hardware in
the perf work this module productizes).
"""

import os

import pytest

from horovod_tpu.obs import profile


def _varint(v: int) -> bytes:
    out = b""
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


def _ld(field: int, payload: bytes) -> bytes:
    return _varint((field << 3) | 2) + _varint(len(payload)) + payload


def _vi(field: int, value: int) -> bytes:
    return _varint(field << 3) + _varint(value)


def _make_xplane(tmpdir) -> str:
    # XEventMetadata {id:7, name:"multiply_reduce_fusion.3"}
    meta = _vi(1, 7) + _ld(2, b"multiply_reduce_fusion.3")
    meta_entry = _vi(1, 7) + _ld(2, meta)          # map key=1, value=2
    meta2 = _vi(1, 8) + _ld(2, b"convolution.1")
    meta2_entry = _vi(1, 8) + _ld(2, meta2)
    # events: two of metadata 7 (1ms + 2ms), one of metadata 8 (5ms)
    ev1 = _vi(1, 7) + _vi(3, int(1e9))
    ev2 = _vi(1, 7) + _vi(3, int(2e9))
    ev3 = _vi(1, 8) + _vi(3, int(5e9))
    line = _ld(2, b"XLA Ops") + _ld(4, ev1) + _ld(4, ev2) + _ld(4, ev3)
    plane = (_ld(2, b"/device:TPU:0") + _ld(3, line)
             + _ld(4, meta_entry) + _ld(4, meta2_entry))
    space = _ld(1, plane)
    d = os.path.join(str(tmpdir), "plugins", "profile", "run1")
    os.makedirs(d)
    path = os.path.join(d, "host.xplane.pb")
    with open(path, "wb") as f:
        f.write(space)
    return str(tmpdir)


class TestParser:
    def test_synthetic_xplane_summary(self, tmp_path):
        logdir = _make_xplane(tmp_path)
        rows = profile.op_summary(logdir)
        assert rows == [
            {"op": "convolution", "total_ms": 5.0, "count": 1},
            {"op": "multiply_reduce_fusion", "total_ms": 3.0,
             "count": 2},
        ]
        assert profile.device_time_ms(logdir) == 8.0

    def test_ungrouped_keeps_instance_names(self, tmp_path):
        logdir = _make_xplane(tmp_path)
        rows = profile.op_summary(logdir, group=False)
        names = {r["op"] for r in rows}
        assert names == {"multiply_reduce_fusion.3", "convolution.1"}

    def test_plane_names(self, tmp_path):
        logdir = _make_xplane(tmp_path)
        assert profile.plane_names(logdir) == ["/device:TPU:0"]

    def test_missing_trace_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            profile.op_summary(str(tmp_path))


class TestLiveCapture:
    def test_capture_and_parse_host_plane(self, tmp_path, hvt):
        import jax
        import jax.numpy as jnp

        x = jnp.ones((256, 256))

        @jax.jit
        def f(a):
            return (a @ a).sum()

        float(f(x))
        with profile.trace(str(tmp_path)):
            float(f(x))
        # CPU traces carry host planes; the parser must read them
        names = profile.plane_names(str(tmp_path))
        assert any("/host:CPU" in n for n in names)
        rows = profile.op_summary(
            str(tmp_path), plane_substr="/host:CPU", line_name="python",
            group=False,
        )
        assert isinstance(rows, list)
