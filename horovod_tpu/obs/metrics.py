"""Unified metrics registry: always-on numeric telemetry for every layer.

The reference Horovod's observability stops at the Timeline (trace
files, opt-in) and the stall inspector (log lines); neither answers
"how many bytes crossed the wire this minute" for a live job.  This
module is the single sink the hot layers report into:

- a dependency-free, thread-safe registry of **Counter** / **Gauge** /
  **Histogram** (fixed log-scale buckets) families, with optional
  Prometheus-style labels;
- a **Prometheus text-format exposition endpoint** served from a
  background ``http.server`` thread — enabled by ``HVTPU_METRICS_PORT``
  (or ``hvtpurun --metrics-port``); each worker binds
  ``port + local_rank`` so multi-slot hosts don't collide;
- ``snapshot()`` (JSON-serializable dump of every family) and
  ``aggregate(process_set)`` — an allgather of per-rank snapshots over
  the JAX coordination KV (the same store the eager controller and the
  stall heartbeat ride), so rank 0 can export a cluster-wide view.

Instrumented producers (metric catalog in docs/observability.md):
``comm/eager.py`` (per-collective counts, wire bytes pre/post
compression, allreduce latency), ``eager/controller.py`` (cycle
duration, queue depth, negotiation latency, cache hits),
``comm/stall.py`` (heartbeat age, warnings/aborts), ``elastic/*``
(rendezvous duration, restarts, live worker gauge),
``api/optimizer.py`` (steps, skipped steps, examples/sec), and
``data/loader.py`` (input wait time, prefetch queue depth,
samples/batches delivered, resize re-shards).

Cost model: a counter increment is a lock + dict add (~1 µs) — two
orders of magnitude under the cheapest eager collective — so the
registry always counts; only the HTTP endpoint is opt-in.
"""

from __future__ import annotations

import bisect
import http.server
import json
import logging
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

logger = logging.getLogger("horovod_tpu")

# ---------------------------------------------------------------------------
# buckets
# ---------------------------------------------------------------------------


def log_buckets(start: float, factor: float, count: int) -> Tuple[float, ...]:
    """``count`` log-scale bucket upper bounds: start * factor**k."""
    return tuple(start * factor ** k for k in range(count))


# 10 µs .. ~42 s in 4x steps — spans a sub-ms CPU op to a stalled pod.
DEFAULT_TIME_BUCKETS = log_buckets(1e-5, 4.0, 12)
# 256 B .. ~1 GiB in 4x steps — a scalar barrier to a fused VGG bucket.
DEFAULT_BYTE_BUCKETS = log_buckets(256.0, 4.0, 12)


def _labelstr(labels: Dict[str, str]) -> str:
    """Canonical (sorted) Prometheus label block, '' when unlabeled."""
    if not labels:
        return ""
    parts = []
    for k in sorted(labels):
        v = str(labels[k]).replace("\\", r"\\").replace('"', r"\"") \
            .replace("\n", r"\n")
        parts.append(f'{k}="{v}"')
    return "{" + ",".join(parts) + "}"


def _fmt(v: float) -> str:
    """Prometheus sample value: integral counters render without '.0'."""
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


# ---------------------------------------------------------------------------
# metric families
# ---------------------------------------------------------------------------


class _Family:
    kind = "untyped"

    def __init__(self, name: str, help: str, lock: threading.Lock):
        self.name = name
        self.help = help
        self._lock = lock
        self._values: Dict[str, float] = {}

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(_labelstr(labels), 0.0)

    def _reset(self):
        self._values.clear()

    # -- snapshot / exposition ------------------------------------------
    def _snapshot_values(self):
        return dict(self._values)

    def _expo_lines(self) -> List[str]:
        return [f"{self.name}{k} {_fmt(v)}"
                for k, v in sorted(self._values.items())]


class Counter(_Family):
    """Monotonically increasing count (Prometheus counter)."""

    kind = "counter"

    def inc(self, amount: float = 1.0, **labels):
        if amount < 0:
            raise ValueError("counters only go up")
        key = _labelstr(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount


class Gauge(_Family):
    """Point-in-time value (Prometheus gauge)."""

    kind = "gauge"

    def set(self, value: float, **labels):
        with self._lock:
            self._values[_labelstr(labels)] = float(value)

    def inc(self, amount: float = 1.0, **labels):
        key = _labelstr(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels):
        self.inc(-amount, **labels)


class Histogram(_Family):
    """Distribution over fixed log-scale buckets (Prometheus histogram).

    Internally stores per-bucket (non-cumulative) counts plus an
    overflow slot; exposition emits the cumulative ``_bucket{le=...}``
    series, ``_sum`` and ``_count``.
    """

    kind = "histogram"

    def __init__(self, name, help, lock, buckets=None):
        super().__init__(name, help, lock)
        self.buckets: Tuple[float, ...] = tuple(
            sorted(buckets if buckets is not None else DEFAULT_TIME_BUCKETS)
        )
        # label key -> [counts (len buckets + 1 overflow), sum, count]
        self._values: Dict[str, list] = {}

    def observe(self, value: float, **labels):
        key = _labelstr(labels)
        i = bisect.bisect_left(self.buckets, value)
        with self._lock:
            cell = self._values.get(key)
            if cell is None:
                cell = self._values[key] = [
                    [0] * (len(self.buckets) + 1), 0.0, 0]
            cell[0][i] += 1
            cell[1] += float(value)
            cell[2] += 1

    def observe_many(self, values, **labels):
        """Record a batch of observations under ONE lock acquisition —
        the per-fused-group bookkeeping path of the eager controller
        (one metrics update per group instead of per op)."""
        values = [float(v) for v in values]
        if not values:
            return
        key = _labelstr(labels)
        idxs = [bisect.bisect_left(self.buckets, v) for v in values]
        with self._lock:
            cell = self._values.get(key)
            if cell is None:
                cell = self._values[key] = [
                    [0] * (len(self.buckets) + 1), 0.0, 0]
            for i in idxs:
                cell[0][i] += 1
            cell[1] += sum(values)
            cell[2] += len(values)

    def value(self, **labels):
        with self._lock:
            cell = self._values.get(_labelstr(labels))
            return 0 if cell is None else cell[2]

    def _snapshot_values(self):
        return {
            k: {"counts": list(c[0]), "sum": c[1], "count": c[2]}
            for k, c in self._values.items()
        }

    def _expo_lines(self) -> List[str]:
        lines = []
        for key, (counts, total, n) in sorted(self._values.items()):
            base = key[1:-1] if key else ""  # strip {} to splice 'le' in

            def lbl(le: str) -> str:
                return "{" + (base + "," if base else "") + \
                    f'le="{le}"' + "}"

            cum = 0
            for b, c in zip(self.buckets, counts):
                cum += c
                lines.append(
                    f"{self.name}_bucket{lbl('{:g}'.format(b))} {cum}")
            lines.append(f"{self.name}_bucket{lbl('+Inf')} {n}")
            lines.append(f"{self.name}_sum{key} {repr(float(total))}")
            lines.append(f"{self.name}_count{key} {n}")
        return lines


class MetricsRegistry:
    """Named families, created idempotently; one coarse lock (metric
    updates are far off any sub-microsecond path)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}

    def _family(self, cls, name, help, **kw) -> _Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if not isinstance(fam, cls):
                    raise TypeError(
                        f"metric {name!r} already registered as "
                        f"{fam.kind}, not {cls.kind}")
                return fam
            fam = cls(name, help, self._lock, **kw)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "") -> Counter:
        return self._family(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._family(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets=None) -> Histogram:
        return self._family(Histogram, name, help, buckets=buckets)

    def reset(self):
        """Zero every family's samples (families stay registered so
        cached accessor objects remain valid) — test hook."""
        with self._lock:
            for fam in self._families.values():
                fam._reset()

    # -- export ----------------------------------------------------------
    def snapshot(self) -> Dict[str, dict]:
        """JSON-serializable dump of every family (the unit that rides
        the coordination KV in ``aggregate`` and embeds in bench.py's
        report)."""
        with self._lock:
            return {
                name: {
                    "type": fam.kind,
                    "help": fam.help,
                    **({"buckets": list(fam.buckets)}
                       if isinstance(fam, Histogram) else {}),
                    "values": fam._snapshot_values(),
                }
                for name, fam in sorted(self._families.items())
            }

    def exposition(self) -> str:
        """Prometheus text format 0.0.4."""
        out = []
        with self._lock:
            for name, fam in sorted(self._families.items()):
                help_ = fam.help.replace("\\", r"\\").replace("\n", r"\n")
                out.append(f"# HELP {name} {help_}")
                out.append(f"# TYPE {name} {fam.kind}")
                out.extend(fam._expo_lines())
        return "\n".join(out) + "\n"


REGISTRY = MetricsRegistry()


def counter(name: str, help: str = "") -> Counter:
    return REGISTRY.counter(name, help)


def gauge(name: str, help: str = "") -> Gauge:
    return REGISTRY.gauge(name, help)


def histogram(name: str, help: str = "", buckets=None) -> Histogram:
    return REGISTRY.histogram(name, help, buckets=buckets)


def snapshot() -> Dict[str, dict]:
    return REGISTRY.snapshot()


# ---------------------------------------------------------------------------
# hot-path accessors (pre-registered so call sites are one cached lookup)
# ---------------------------------------------------------------------------

_OP_COUNTERS: Dict[str, Counter] = {}
_OP_LOCK = threading.Lock()


def op_counter(kind: str) -> Counter:
    """Per-collective-kind counter, e.g. ``hvtpu_allreduce_total``."""
    c = _OP_COUNTERS.get(kind)
    if c is None:
        with _OP_LOCK:
            c = _OP_COUNTERS.setdefault(kind, REGISTRY.counter(
                f"hvtpu_{kind}_total",
                f"Eager {kind} collectives executed by this rank."))
    return c


TENSOR_BYTES = REGISTRY.counter(
    "hvtpu_tensor_bytes_total",
    "Collective payload bytes BEFORE wire compression/quantization.")
WIRE_BYTES = REGISTRY.counter(
    "hvtpu_wire_bytes_total",
    "Bytes actually moved on the wire (after compression/quantization, "
    "including quantization scale sidecars).")
ALLREDUCE_LATENCY = REGISTRY.histogram(
    "hvtpu_allreduce_latency_seconds",
    "Eager allreduce dispatch-to-ready latency as seen by the caller.",
    buckets=DEFAULT_TIME_BUCKETS)

_STEP_STATE = {"t": None}
_STEP_LOCK = threading.Lock()
# EWMA weight for the steps/examples-per-second gauges: ~last 10 steps.
_RATE_ALPHA = 0.2


def note_step(examples: float = 0.0, steps: float = 1.0):
    """Record optimizer/training progress.  Increments the step and
    example counters and maintains EWMA ``*_per_second`` gauges from
    inter-call time.  Called by the eager ``allreduce_gradients`` path
    once per step; jit training loops (whose update is traced once)
    call it from the host loop, passing the steps and examples per
    dispatch (see bench.py's lax.scan dispatches)."""
    REGISTRY.counter(
        "hvtpu_optimizer_steps_total", "Optimizer steps applied."
    ).inc(steps)
    if examples:
        REGISTRY.counter(
            "hvtpu_examples_total", "Training examples processed."
        ).inc(examples)
    # Step-boundary hook for the overlap profiler (import deferred:
    # stepprof imports this module for its registry).  The returned
    # step record feeds the flight ring and the anomaly detectors —
    # both behind single module-attribute guards when disabled.
    from . import stepprof as _stepprof
    if _stepprof.ACTIVE:
        rec = _stepprof.note_step_boundary(steps=steps)
        if rec is not None:
            from . import anomaly as _anomaly
            from . import flight as _flight
            if _flight.ACTIVE:
                _flight.note("step", **rec)
            if _anomaly.ACTIVE:
                _anomaly.on_step(rec)
    now = time.monotonic()
    with _STEP_LOCK:
        prev = _STEP_STATE["t"]
        _STEP_STATE["t"] = now
    if prev is None or now <= prev:
        return
    dt = now - prev
    sps = REGISTRY.gauge(
        "hvtpu_steps_per_second", "EWMA optimizer steps per second.")
    old = sps.value()
    rate = steps / dt
    sps.set((1 - _RATE_ALPHA) * old + _RATE_ALPHA * rate
            if old else rate)
    if examples:
        eps = REGISTRY.gauge(
            "hvtpu_examples_per_second", "EWMA training examples per "
            "second (requires callers to pass examples to note_step).")
        old = eps.value()
        rate = examples / dt
        eps.set((1 - _RATE_ALPHA) * old + _RATE_ALPHA * rate
                if old else rate)


# ---------------------------------------------------------------------------
# live /debug introspection plane
# ---------------------------------------------------------------------------
# Subsystems (eager controller, stall inspector, core state) register a
# zero-argument callable returning a JSON-serializable dict; the HTTP
# server's /debug route snapshots all of them so "what is my job doing"
# is one curl away.  A provider that raises is reported in place as an
# {"error": ...} entry — introspection never takes the endpoint down.

_debug_providers: Dict[str, Callable[[], dict]] = {}
_debug_lock = threading.Lock()


def register_debug_provider(name: str, fn: Callable[[], dict]) -> None:
    with _debug_lock:
        _debug_providers[name] = fn


def unregister_debug_provider(name: str) -> None:
    with _debug_lock:
        _debug_providers.pop(name, None)


def debug_snapshot() -> dict:
    """One coherent-ish dump of every registered provider (each
    provider snapshots under its own lock; cross-provider skew is the
    wall time between calls)."""
    with _debug_lock:
        items = list(_debug_providers.items())
    out: dict = {"time_unix": time.time()}
    for name, fn in items:
        try:
            out[name] = fn()
        except Exception as e:  # noqa: BLE001 — isolate provider faults
            out[name] = {"error": str(e)}
    return out


# ---------------------------------------------------------------------------
# Prometheus exposition endpoint
# ---------------------------------------------------------------------------

_server: Optional[http.server.ThreadingHTTPServer] = None
_server_thread: Optional[threading.Thread] = None
_server_lock = threading.Lock()


def _make_handler(registry: MetricsRegistry):
    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 — http.server API
            path = self.path.split("?", 1)[0]
            if path == "/debug":
                body = json.dumps(
                    debug_snapshot(), indent=2, default=str,
                ).encode("utf-8")
                ctype = "application/json"
            elif path in ("/", "/metrics"):
                body = registry.exposition().encode("utf-8")
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            else:
                self.send_error(404)
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # silence per-scrape stderr noise
            pass

    return Handler


def start_http_server(port: int, addr: str = "",
                      registry: Optional[MetricsRegistry] = None) -> int:
    """Serve ``registry`` (default: the global one) at
    ``http://<addr>:<port>/metrics`` from a daemon thread.  ``port=0``
    binds an ephemeral port.  Returns the bound port.  Idempotent per
    process: a second call while a server is live returns its port."""
    global _server, _server_thread
    with _server_lock:
        if _server is not None:
            return _server.server_address[1]
        srv = http.server.ThreadingHTTPServer(
            (addr, port), _make_handler(registry or REGISTRY))
        srv.daemon_threads = True
        t = threading.Thread(target=srv.serve_forever,
                             name="hvt-metrics-http", daemon=True)
        t.start()
        _server, _server_thread = srv, t
        return srv.server_address[1]


def stop_http_server():
    global _server, _server_thread
    with _server_lock:
        srv, t = _server, _server_thread
        _server = _server_thread = None
    if srv is not None:
        srv.shutdown()
        srv.server_close()
    if t is not None:
        t.join(timeout=5)


def serve_from_env(local_rank: int = 0) -> Optional[int]:
    """Start the endpoint when ``HVTPU_METRICS_PORT`` (reference
    spelling ``HOROVOD_METRICS_PORT`` honored too) is set: each worker
    binds ``port + local_rank`` so multi-slot hosts don't collide.  A
    bind failure logs a warning and returns None — telemetry must never
    take a healthy job down."""
    raw = (os.environ.get("HVTPU_METRICS_PORT")
           or os.environ.get("HOROVOD_METRICS_PORT"))
    if not raw:
        return None
    try:
        base = int(raw)
    except ValueError:
        logger.warning("HVTPU_METRICS_PORT=%r is not an integer; "
                       "metrics endpoint disabled", raw)
        return None
    if base <= 0:
        return None
    try:
        return start_http_server(base + local_rank)
    except OSError as e:
        logger.warning(
            "metrics endpoint disabled: could not bind port %d: %s",
            base + local_rank, e)
        return None


# ---------------------------------------------------------------------------
# cross-rank aggregation over the coordination KV
# ---------------------------------------------------------------------------

_agg_seq: Dict[Tuple[int, int], int] = {}
_agg_lock = threading.Lock()
_AGG_NS = "hvtmetrics"


def merge_snapshots(snaps: List[Dict[str, dict]]) -> Dict[str, dict]:
    """Element-wise merge of per-rank snapshots: counters, gauges and
    histogram cells SUM across ranks (a summed gauge is the natural
    cluster view for worker counts and rates; per-rank values stay
    available in ``aggregate``'s per_rank map)."""
    merged: Dict[str, dict] = {}
    for snap in snaps:
        for name, fam in snap.items():
            m = merged.get(name)
            if m is None:
                merged[name] = json.loads(json.dumps(fam))  # deep copy
                continue
            if fam["type"] == "histogram":
                if fam.get("buckets") != m.get("buckets"):
                    raise ValueError(
                        f"histogram {name!r} bucket mismatch across ranks")
                for key, cell in fam["values"].items():
                    mc = m["values"].get(key)
                    if mc is None:
                        m["values"][key] = json.loads(json.dumps(cell))
                    else:
                        mc["counts"] = [a + b for a, b in
                                        zip(mc["counts"], cell["counts"])]
                        mc["sum"] += cell["sum"]
                        mc["count"] += cell["count"]
            else:
                for key, v in fam["values"].items():
                    m["values"][key] = m["values"].get(key, 0.0) + v
    return merged


def aggregate(process_set=None, timeout_s: float = 60.0,
              registry: Optional[MetricsRegistry] = None) -> dict:
    """Allgather every member rank's ``snapshot()`` through the JAX
    coordination KV and return ``{"per_rank": {rank: snap},
    "merged": snap}``.

    COLLECTIVE contract: every member rank of the process set must call
    ``aggregate`` the same number of times (each call uses a fresh
    per-set sequence number, like a controller cycle).  Single-process
    worlds — or processes without a coordination client — degrade to the
    local snapshot.
    """
    registry = registry or REGISTRY
    snap = registry.snapshot()

    try:
        from ..core import state as core_state

        st = core_state.global_state()
    except Exception:
        st = None
    if st is None or not st.initialized or st.size <= 1:
        rank = st.rank if st is not None else 0
        return {"per_rank": {rank: snap}, "merged": snap}

    try:
        from jax._src import distributed as _jd

        client = _jd.global_state.client
    except Exception:
        client = None
    if client is None:
        return {"per_rank": {st.rank: snap}, "merged": snap}

    if process_set is None:
        ps = st.process_set_table.global_process_set
    elif isinstance(process_set, int):
        ps = st.process_set_table.get(process_set)
    else:
        ps = process_set
    members = list(ps.ranks) if ps.ranks is not None else list(
        range(st.size))
    if st.rank not in members:
        raise ValueError(
            f"rank {st.rank} is not a member of process set "
            f"{ps.process_set_id}")

    # One shared retry engine (core/retry.py) instead of the ad-hoc
    # loop this function used to carry: the KV wrapper retries
    # transient put failures with backoff (counted in
    # hvtpu_kv_retries_total), and the per-peer blocking poll rides a
    # deadline-bounded policy where NOT_FOUND/timeout just means "the
    # peer hasn't posted yet".
    from ..core import retry as core_retry

    kv = core_retry.resilient_kv(client, rank=st.rank)

    with _agg_lock:
        key = (st.init_generation, ps.process_set_id)
        seq = _agg_seq.get(key, 0)
        _agg_seq[key] = seq + 1
    prefix = (f"{_AGG_NS}/{st.init_generation}/{ps.process_set_id}/"
              f"{seq}/")
    kv.key_value_set(prefix + str(st.rank), json.dumps(snap))

    per_rank: Dict[int, dict] = {st.rank: snap}
    deadline = time.monotonic() + timeout_s
    poll_policy = core_retry.RetryPolicy(
        name="metrics-aggregate",
        max_attempts=1_000_000,  # the deadline is the real bound
        base_delay_s=0.02, max_delay_s=0.25,
        deadline_s=timeout_s,
        retryable=core_retry.kv_blocking_retryable)

    def _fetch(r: int) -> dict:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            # non-retryable by design: the GLOBAL deadline bounds the
            # whole aggregate, not each peer's poll loop
            raise RuntimeError("aggregate budget spent")
        budget_ms = max(1, int(remaining * 1000))
        return json.loads(kv.blocking_key_value_get(
            prefix + str(r), min(budget_ms, 2000)))

    for r in sorted(members):
        if r == st.rank:
            continue
        try:
            per_rank[r] = core_retry.call(poll_policy, _fetch, r)
        except Exception:
            raise TimeoutError(
                f"metrics snapshot from rank {r} not posted "
                f"within {timeout_s:.0f}s") from None
    # rolling cleanup: every member posted seq, so nobody still needs
    # this rank's previous round (each rank deletes only its own key)
    if seq > 0:
        try:
            kv.key_value_delete(
                f"{_AGG_NS}/{st.init_generation}/{ps.process_set_id}/"
                f"{seq - 1}/{st.rank}")
        except Exception:
            pass
    return {"per_rank": per_rank, "merged": merge_snapshots(
        [per_rank[r] for r in sorted(per_rank)])}
