"""Core runtime tests: config, topology, process sets, lifecycle.

Modeled on the reference's single-process unit tests (SURVEY.md §4,
test/single/) — no cluster, pure logic.
"""

import jax
import numpy as np
import pytest

import horovod_tpu as hvt
from horovod_tpu.core import Config, ProcessSet
from horovod_tpu.core.topology import Topology


class TestConfig:
    def test_defaults(self):
        cfg = Config.from_env()
        assert cfg.fusion_threshold_bytes == 64 * 1024 * 1024
        assert cfg.cycle_time_ms == 1.0
        assert cfg.cache_capacity == 1024

    def test_hvtpu_env(self, monkeypatch):
        monkeypatch.setenv("HVTPU_FUSION_THRESHOLD", "1048576")
        monkeypatch.setenv("HVTPU_CYCLE_TIME", "5")
        monkeypatch.setenv("HVTPU_COMPRESSION", "bf16")
        cfg = Config.from_env()
        assert cfg.fusion_threshold_bytes == 1048576
        assert cfg.cycle_time_ms == 5.0
        assert cfg.compression == "bf16"

    def test_horovod_env_fallback(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_FUSION_THRESHOLD", "2097152")
        monkeypatch.setenv("HOROVOD_STALL_CHECK_TIME_SECONDS", "10")
        cfg = Config.from_env()
        assert cfg.fusion_threshold_bytes == 2097152
        assert cfg.stall_check_time_seconds == 10.0

    def test_hvtpu_wins_over_horovod(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_FUSION_THRESHOLD", "111")
        monkeypatch.setenv("HVTPU_FUSION_THRESHOLD", "222")
        assert Config.from_env().fusion_threshold_bytes == 222

    def test_fusion_threshold_mb_flag_form(self, monkeypatch):
        monkeypatch.setenv("HVTPU_FUSION_THRESHOLD_MB", "2")
        assert Config.from_env().fusion_threshold_bytes == 2 * 1024 * 1024


class TestLifecycle:
    def test_init_idempotent(self):
        hvt.init()
        try:
            s1 = hvt.core.global_state()
            hvt.init()
            assert hvt.core.global_state() is s1
            assert hvt.is_initialized()
            assert hvt.rank() == 0
            assert hvt.size() == 1
            assert hvt.num_devices() == 8
        finally:
            hvt.shutdown()
        assert not hvt.is_initialized()

    def test_require_init_raises(self):
        assert not hvt.is_initialized()
        with pytest.raises(hvt.HorovodTpuError):
            hvt.rank()

    def test_feature_probes(self, hvt):
        assert hvt.xla_built()
        assert not hvt.nccl_built()
        assert not hvt.mpi_built()


class TestTopology:
    def test_world_mesh(self):
        topo = Topology()
        mesh = topo.world_mesh()
        assert mesh.devices.size == 8
        assert mesh.axis_names == ("world",)
        assert topo.world_mesh() is mesh  # cached

    def test_hierarchical_mesh_single_host(self):
        topo = Topology()
        mesh = topo.hierarchical_mesh()
        assert mesh.axis_names == ("dcn", "ici")
        assert mesh.devices.shape == (1, 8)

    def test_nd_mesh(self):
        topo = Topology()
        mesh = topo.nd_mesh(("dp", "tp"), (2, 4))
        assert mesh.devices.shape == (2, 4)
        with pytest.raises(ValueError):
            topo.nd_mesh(("dp",), (3,))

    def test_proc_mesh(self):
        topo = Topology()
        mesh = topo.proc_mesh()
        assert mesh.devices.size == 1  # single process
        assert mesh.axis_names == ("proc",)


class TestProcessSets:
    def test_global_set(self, hvt):
        table = hvt.core.global_state().process_set_table
        g = table.global_process_set
        assert g.process_set_id == 0
        assert g.ranks == [0]
        assert g.included(0)
        assert g.size == 1

    def test_reference_method_call_syntax(self, hvt):
        # upstream ProcessSet exposes size()/rank()/included() as
        # no-arg METHODS; the engine reads size as a value — both
        # spellings must work on the same object
        g = hvt.global_process_set
        assert g.size == 1 and g.size() == 1
        assert g.rank == 0 and g.rank() == 0
        assert g.included() is True
        assert g.included(0) is True
        # a set this process is NOT in: rank is None, included False
        ns = ProcessSet([0])
        ns.ranks = [7]  # simulate membership elsewhere (1-proc world)
        ns._topology = g._topology
        assert ns.rank is None
        assert ns.included() is False

    def test_rank_and_included_require_init(self):
        import horovod_tpu as mod
        from horovod_tpu.core.exceptions import NotInitializedError

        if mod.is_initialized():
            mod.shutdown()
        ps = ProcessSet([0])
        ps.ranks = [0]
        with pytest.raises(NotInitializedError):
            _ = ps.rank
        with pytest.raises(NotInitializedError):
            ps.included()
        assert ps.included(0)  # explicit-rank query needs no init

    def test_duplicate_set_rejected(self, hvt):
        # [0] duplicates the global set's ranks in a 1-process world.
        with pytest.raises(ValueError):
            hvt.add_process_set(ProcessSet([0]))

    def test_out_of_range_ranks_rejected(self, hvt):
        table = hvt.core.global_state().process_set_table
        with pytest.raises(ValueError):
            table.add(ProcessSet([0, 5]))

    def test_cannot_remove_global(self, hvt):
        table = hvt.core.global_state().process_set_table
        with pytest.raises(ValueError):
            table.remove(0)

    def test_device_groups_partition(self):
        # Simulate a 4-process world by faking process indices is not
        # possible with real devices; exercise the partition math via
        # explicit groups on the SPMD API instead (test_spmd_collectives).
        pass


class TestSmallParitySurface:
    def test_is_homogeneous_single_process(self, hvt):
        assert hvt.is_homogeneous() is True

    def test_global_process_set_attribute(self, hvt):
        gps = hvt.global_process_set
        assert gps.process_set_id == 0

    def test_global_process_set_requires_init(self):
        import horovod_tpu as mod

        if mod.is_initialized():
            mod.shutdown()
        # AttributeError (not NotInitializedError): hasattr/getattr
        # probes must keep their contract pre-init
        assert not hasattr(mod, "global_process_set")
        assert getattr(mod, "global_process_set", None) is None
