"""Host-spec parsing and rank/slot assignment for the launcher.

Parity surface: ``horovod/runner/common/util/hosts.py``
(``parse_hosts``, ``get_host_assignments``) — the ``-H h1:2,h2:4``
syntax and the rank → (host, local_rank, cross_rank) assignment the
reference launcher computes before exporting ``HOROVOD_RANK/LOCAL_RANK/
CROSS_RANK`` to each worker.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional


@dataclasses.dataclass
class HostSlots:
    hostname: str
    slots: int


@dataclasses.dataclass
class SlotInfo:
    """One rank's placement (parity: horovod.runner.common.util.hosts
    SlotInfo: rank/size/local_rank/local_size/cross_rank/cross_size)."""

    hostname: str
    rank: int
    size: int
    local_rank: int
    local_size: int
    cross_rank: int
    cross_size: int


def parse_host_spec(spec: str) -> List[HostSlots]:
    """Parse ``h1:2,h2:4`` (slots default to 1 when omitted)."""
    out: List[HostSlots] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            name, slots_s = part.rsplit(":", 1)
            slots = int(slots_s)
        else:
            name, slots = part, 1
        if slots <= 0:
            raise ValueError(f"host {name!r} has non-positive slots {slots}")
        out.append(HostSlots(name, slots))
    if not out:
        raise ValueError(f"empty host spec {spec!r}")
    return out


def get_host_assignments(hosts: List[HostSlots], np: int) -> List[SlotInfo]:
    """Assign ``np`` ranks to hosts in order, filling each host's slots.

    Rank numbering is host-major (all of host 0's slots, then host 1's),
    matching the reference.  ``cross_rank`` is the index of the rank's
    host among hosts that have a worker at the same ``local_rank`` —
    the communicator layout hierarchical collectives use.
    """
    total = sum(h.slots for h in hosts)
    if np > total:
        raise ValueError(
            f"requested -np {np} exceeds available slots {total} "
            f"({','.join(f'{h.hostname}:{h.slots}' for h in hosts)})"
        )
    placements: List[tuple] = []  # (hostname, local_rank)
    remaining = np
    for h in hosts:
        take = min(h.slots, remaining)
        for lr in range(take):
            placements.append((h.hostname, lr))
        remaining -= take
        if remaining == 0:
            break

    # local_size per host, cross layout per local_rank
    local_sizes: dict = {}
    for hn, _ in placements:
        local_sizes[hn] = local_sizes.get(hn, 0) + 1
    by_local_rank: dict = {}
    for hn, lr in placements:
        by_local_rank.setdefault(lr, []).append(hn)

    out: List[SlotInfo] = []
    for rank, (hn, lr) in enumerate(placements):
        cross_hosts = by_local_rank[lr]
        out.append(
            SlotInfo(
                hostname=hn,
                rank=rank,
                size=np,
                local_rank=lr,
                local_size=local_sizes[hn],
                cross_rank=cross_hosts.index(hn),
                cross_size=len(cross_hosts),
            )
        )
    return out


def is_local_host(hostname: str) -> bool:
    return hostname in ("localhost", "127.0.0.1", "::1")
