"""Adasum training example — the horovod_tpu analog of the reference's
examples/pytorch/pytorch_mnist.py run with ``--use-adasum``: the
DistributedOptimizer combines gradients with the scale-invariant
Adasum operator instead of averaging, so the effective step stays
stable as the world grows and the reference's lr×size scaling rule is
NOT applied (Adasum's combine already accounts for parallelism).

Adasum needs a power-of-two participant count.  With
``HVTPU_HIERARCHICAL_ALLREDUCE=1`` and a uniform host layout it runs
hierarchically (intra-host sum over ici, scale-invariant combine
across hosts) — then scale the lr by local_size, matching the
reference's GPU guidance.

Run:  hvtpurun -np 2 --cpu-devices 1 python examples/pytorch_mnist_adasum.py
"""

import argparse

import numpy as np
import torch
import torch.nn as nn
import torch.nn.functional as F

import horovod_tpu.torch as hvd


class Net(nn.Module):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(784, 128)
        self.fc2 = nn.Linear(128, 10)

    def forward(self, x):
        x = F.relu(self.fc1(x))
        return F.log_softmax(self.fc2(x), dim=1)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--train-size", type=int, default=2048)
    args = p.parse_args()

    hvd.init()
    torch.manual_seed(42 + hvd.rank())

    rng = np.random.RandomState(0)
    x = rng.rand(args.train_size, 784).astype(np.float32)
    w = rng.randn(784, 10).astype(np.float32)
    y = (x @ w).argmax(axis=1)

    n = len(x) // hvd.size()
    lo = hvd.rank() * n
    data = torch.from_numpy(x[lo:lo + n])
    target = torch.from_numpy(y[lo:lo + n])

    model = Net()
    # Adasum: no lr × size scaling (contrast pytorch_mnist.py)
    opt = torch.optim.SGD(model.parameters(), lr=args.lr)
    opt = hvd.DistributedOptimizer(
        opt, named_parameters=model.named_parameters(), op=hvd.Adasum)

    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(opt, root_rank=0)

    steps = max(len(data) // args.batch_size, 1)
    for epoch in range(args.epochs):
        perm = torch.randperm(len(data))
        total = 0.0
        for s in range(steps):
            idx = perm[s * args.batch_size:(s + 1) * args.batch_size]
            opt.zero_grad()
            loss = F.nll_loss(model(data[idx]), target[idx])
            loss.backward()
            opt.step()
            total += float(loss)
        avg = hvd.allreduce(
            torch.tensor(total / steps), op=hvd.Average)
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss={float(avg):.4f}", flush=True)

    if hvd.rank() == 0:
        print(f"done; ranks consistent ({hvd.size()} ranks)",
              flush=True)


if __name__ == "__main__":
    main()
