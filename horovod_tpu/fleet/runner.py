"""The production job runner: one ElasticDriver per fleet job.

The arbiter (:mod:`.arbiter`) talks to jobs through a small handle
protocol — ``start`` / ``poll`` / ``request_shrink`` / ``escalate`` /
``update_allocation`` / ``stop`` plus ``phase()`` / ``current_np()`` /
``allocation()`` — so the scheduling core stays pure logic and the
fabric simulator can substitute a virtual-rank runner.  This module is
the real one: each job wraps its OWN :class:`ElasticDriver` on a
daemon thread, with a job-scoped state dir (durable commits), a
job-scoped notice dir (per-rank ``core/preempt.py`` notice files), and
the job's env overlay.

The planned-shrink dance, in driver terms:

1. ``request_shrink(new_np)`` touches the notice files of the
   incarnation's highest ranks (``rank >= new_np``).  Each victim's
   drain watcher fires with source ``file``; the world performs the
   coordinated emergency commit; victims exit ``DRAIN_EXIT_CODE``
   while peers reset — the driver classifies the incarnation as a
   planned ``drain`` (no restart-budget or blacklist strike).
2. The driver's ``listener`` seam delivers ``incarnation_end``
   SYNCHRONOUSLY on the driver thread, BEFORE it re-polls discovery —
   the handle flips its allocation view to the shrunk grant there, so
   the relaunch can never race back up to the old size.
3. If the drain grace expires first, the arbiter calls
   :meth:`escalate`: the shrunk allocation is applied immediately and
   the victims get a bare SIGTERM, which the driver classifies as a
   crash — a **charged** restart, by design (the job burned its grace).

Grow is the existing scale-up path untouched: the allocation view
widens, the driver's discovery poll notices, SIGUSR1s the workers, and
relaunches at the new size (budget semantics unchanged).
"""

from __future__ import annotations

import os
import signal
import threading
from typing import Dict, List, Optional

from ..core import clock
from ..elastic.driver import ElasticDriver

__all__ = ["AllocationDiscovery", "ElasticJobRunner"]


class AllocationDiscovery:
    """The job driver's host 'discovery': the arbiter's current grant.
    Duck-types HostDiscoveryScript (find_available_hosts_and_slots)."""

    def __init__(self, allocation: Optional[Dict[str, int]] = None):
        self._lock = threading.Lock()
        self._alloc: Dict[str, int] = dict(  # hvtpulint: guarded-by(_lock)
            allocation or {})

    def set(self, allocation: Dict[str, int]) -> None:
        with self._lock:
            self._alloc = dict(allocation)

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._alloc)


class ElasticJobRunner:
    """Handle protocol implementation over a real ElasticDriver."""

    def __init__(self, job, base_dir: str, *,
                 discovery_interval: float = 0.5,
                 elastic_timeout: float = 600.0,
                 verbose: bool = False):
        spec = job.spec
        self.name = spec.name
        self._dir = os.path.join(base_dir, spec.name)
        self.state_dir = os.path.join(self._dir, "state")
        self.notice_dir = os.path.join(self._dir, "notice")
        # File channel for the fleet health rollup: rank 0's reporter
        # mirrors summaries here (fleet/health.py) and the arbiter's
        # _poll_health reads them off this handle attribute — the
        # arbiter is not a member of the job's coordination world, so
        # the job's KV alone cannot carry health to it.
        self.health_dir = os.path.join(self._dir, "health")
        os.makedirs(self.state_dir, exist_ok=True)
        os.makedirs(self.notice_dir, exist_ok=True)
        os.makedirs(self.health_dir, exist_ok=True)
        self._discovery = AllocationDiscovery()
        self._driver = ElasticDriver(
            command=list(spec.command),
            discovery=self._discovery,
            min_np=spec.min_np,
            max_np=spec.max_np,
            discovery_interval=discovery_interval,
            elastic_timeout=elastic_timeout,
            state_dir=self.state_dir,
            verbose=verbose,
            max_restarts=spec.max_restarts,
            restart_window=spec.restart_window,
            drain_grace=spec.drain_grace,
            notice_dir=self.notice_dir,
            extra_env=self._job_env(spec),
        )
        self._driver.listener = self._on_driver_event
        self._lock = threading.Lock()
        self._alloc: Dict[str, int] = {}  # hvtpulint: guarded-by(_lock)
        self._pending_alloc: Optional[Dict[str, int]] = None  # hvtpulint: guarded-by(_lock)
        self._victims: List[int] = []  # hvtpulint: guarded-by(_lock)
        self._phase = "pending"  # hvtpulint: guarded-by(_lock)
        self._np = 0  # hvtpulint: guarded-by(_lock)
        self._target_np: Optional[int] = None  # hvtpulint: guarded-by(_lock)
        self.charged_restarts = 0
        self.drains = 0
        self._exit: Optional[int] = None
        self._thread: Optional[threading.Thread] = None

    def _job_env(self, spec) -> Dict[str, str]:
        # Workers learn their fleet job name so rank 0's HealthReporter
        # (installed by core/state.init) publishes under the right
        # fleet/<job>/ KV prefix, and the health-file directory the
        # arbiter polls for the rollup.  Explicit spec.env entries win.
        env = dict(spec.env or {})
        env.setdefault("HVTPU_FLEET_JOB", spec.name)
        env.setdefault("HVTPU_FLEET_HEALTH_DIR", self.health_dir)
        return env

    # -- lifecycle ------------------------------------------------------
    def start(self, allocation: Dict[str, int]) -> None:
        with self._lock:
            self._alloc = dict(allocation)
            self._phase = "running"
        self._discovery.set(allocation)
        self._thread = threading.Thread(
            target=self._run, name=f"fleet-job-{self.name}", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        try:
            self._exit = self._driver.run()
        except Exception:  # noqa: BLE001 — a driver crash fails the job
            self._exit = 1

    def poll(self) -> Optional[int]:
        if self._thread is None or self._thread.is_alive():
            return None
        return self._exit if self._exit is not None else 1

    def stop(self) -> None:
        """Graceful cancel: the whole-job drain path (the driver's own
        SIGTERM handling) — workers reach a commit boundary, then the
        driver escalates through terminate()."""
        self._driver._drain_requested = True

    # -- driver listener (driver thread) --------------------------------
    def _on_driver_event(self, event: str, info: dict) -> None:
        with self._lock:
            if event == "launch":
                self._np = int(info["size"])
                self._phase = "running"
                return
            if event != "incarnation_end":
                return
            outcome = info.get("outcome")
            if outcome == "restart":
                self.charged_restarts += 1
            if outcome == "drain":
                self.drains += 1
            apply_pending = (self._pending_alloc is not None
                             and outcome in ("drain", "restart"))
            if apply_pending:
                alloc = self._apply_pending_locked()
            if outcome in ("drain", "restart"):
                self._phase = "resizing"
        if apply_pending:
            self._discovery.set(alloc)
            self._clear_notices()

    def _apply_pending_locked(self) -> Dict[str, int]:  # hvtpulint: requires(_lock)
        alloc = dict(self._pending_alloc)
        self._alloc = alloc
        self._pending_alloc = None
        self._victims = []
        self._target_np = None
        return alloc

    def _clear_notices(self) -> None:
        try:
            for f in os.listdir(self.notice_dir):
                try:
                    os.unlink(os.path.join(self.notice_dir, f))
                except OSError:
                    pass
        except OSError:
            pass

    # -- arbiter-driven resize ------------------------------------------
    def request_shrink(self, new_np: int) -> bool:
        """Start a planned shrink to ``new_np`` ranks via the per-rank
        notice files.  Returns False when there is nothing to shrink
        (already at/below target, or between incarnations — the caller
        retries next tick)."""
        with self._lock:
            slots = list(self._driver.current_slots)
            live = sorted({s.rank for s in slots})
            if not live or len(live) <= new_np:
                return False
            victims = [r for r in live if r >= new_np]
            keep: Dict[str, int] = {}
            for s in slots:
                if s.rank < new_np:
                    keep[s.hostname] = keep.get(s.hostname, 0) + 1
            self._pending_alloc = keep
            self._victims = victims
            self._target_np = new_np
            self._phase = "draining"
        for r in victims:
            path = os.path.join(self.notice_dir, f"rank{r}")
            try:
                with open(path, "w") as f:
                    f.write(f"drain requested at {clock.wall():.3f}\n")
            except OSError:
                pass
        return True

    def escalate(self) -> int:
        """Drain-grace expiry: apply the shrunk allocation NOW and
        SIGTERM the victims.  The driver classifies a bare SIGTERM as a
        crash, so this relaunch is charged to the restart budget — the
        documented cost of blowing the grace window."""
        with self._lock:
            if self._pending_alloc is None:
                return 0
            victims = list(self._victims)
            alloc = self._apply_pending_locked()
        self._discovery.set(alloc)
        self._clear_notices()
        return self._driver.signal_ranks(victims, signal.SIGTERM)

    def update_allocation(self, allocation: Dict[str, int]) -> None:
        """Grow (or administratively retarget) the job's allocation;
        the driver's discovery poll picks it up and resets the world at
        the next commit boundary (existing scale-up semantics)."""
        with self._lock:
            self._alloc = dict(allocation)
        self._discovery.set(allocation)

    # -- read side ------------------------------------------------------
    def phase(self) -> str:
        with self._lock:
            return self._phase

    def current_np(self) -> int:
        with self._lock:
            return self._np

    def target_np(self) -> Optional[int]:
        with self._lock:
            return self._target_np

    def allocation(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._alloc)

    def info(self) -> dict:
        with self._lock:
            return {
                "phase": self._phase,
                "np": self._np,
                "target_np": self._target_np,
                "allocation": dict(self._alloc),
                "charged_restarts": self.charged_restarts,
                "drains": self.drains,
                "state_dir": self.state_dir,
            }
