"""Eager mini-controller package (SURVEY.md §7.0 "eager/").

Restores the reference's out-of-order enqueue tolerance for the async
eager API: ranks may submit collectives in any order; the controller
negotiates a globally-agreed, deterministically-fused execution schedule
each cycle (parity: BackgroundThreadLoop + Controller::
ComputeResponseList), then executes it on the XLA data plane.
"""

from __future__ import annotations

import threading
from typing import Optional

from ..core import state as core_state
from .controller import (
    EagerController,
    KVTransport,
    LocalTransport,
    OpFuture,
)

_init_lock = threading.Lock()


def get_controller() -> EagerController:
    """The process-wide controller, started lazily on first use
    (parity: InitializeHorovodOnce starting the background thread).
    Thread-safe: concurrent first calls create exactly one controller."""
    st = core_state.require_init("async eager collectives")
    if st.controller is not None:
        return st.controller
    with _init_lock:
        if st.controller is None:
            cfg = st.config
            process_sets = {
                psid: list(ps.ranks)
                for psid, ps in st.process_set_table._table.items()
                if ps.ranks is not None
            }
            controller = EagerController(
                st.rank,
                st.size,
                cycle_time_ms=cfg.cycle_time_ms,
                fusion_threshold=cfg.fusion_threshold_bytes,
                cache_capacity=cfg.cache_capacity,
                stall_warn_s=(float("inf") if cfg.stall_check_disable
                              else cfg.stall_check_time_seconds),
                stall_abort_s=cfg.stall_shutdown_time_seconds,
                timeline=st.timeline,
                autotuner=st.autotuner,
                process_sets=process_sets,
            )
            controller.start()
            st.controller = controller
    return st.controller


__all__ = [
    "EagerController", "OpFuture", "KVTransport", "LocalTransport",
    "get_controller",
]
