"""Device topology and mesh construction.

TPU-native replacement for the reference's communicator plumbing
(horovod/common/mpi/mpi_context.cc ``MPIContext::Initialize`` building
global/local/cross MPI communicators; NCCL comm creation in
horovod/common/ops/nccl_operations.cc ``NCCLOpContext::InitNCCLComm``).

On TPU there is no NCCL ring setup: collectives lower to XLA ops over the
ICI torus, and "communicator creation" becomes "mesh construction".  This
module builds and caches the meshes everything else shards over:

* the **world mesh** — one axis over every device in the job (ICI order,
  with DCN-aware ordering for multi-host slices);
* the **hierarchical mesh** — ``("dcn", "ici")`` axes separating
  cross-host (slow) from intra-slice (fast) links, the analog of the
  reference's hierarchical allreduce (``NCCLHierarchicalAllreduce``);
* the **process mesh** — one device per participating process, which is
  the data plane for eager Horovod-style collectives (one process = one
  Horovod rank).

Like NCCL comms in the reference, meshes are created lazily and cached.
"""

from __future__ import annotations

import threading
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

# Canonical axis names.
WORLD_AXIS = "world"   # flat axis over all devices
DCN_AXIS = "dcn"       # cross-host / cross-slice (data-center network)
ICI_AXIS = "ici"       # intra-slice interconnect
PROC_AXIS = "proc"     # one device per process (eager data plane)
LDEV_AXIS = "ldev"     # local devices of a process (eager multi-lane)


class Topology:
    """Lazily-built, cached mesh factory over a fixed device set."""

    def __init__(self, devices: Optional[Sequence[jax.Device]] = None):
        self._devices = list(devices) if devices is not None else None
        self._lock = threading.Lock()
        self._world_mesh: Optional[Mesh] = None
        self._proc_mesh: Optional[Mesh] = None
        self._hier_mesh: Optional[Mesh] = None

    # -- device sets ---------------------------------------------------

    @property
    def devices(self):
        if self._devices is None:
            self._devices = list(jax.devices())
        return self._devices

    @property
    def num_devices(self) -> int:
        return len(self.devices)

    @property
    def num_local_devices(self) -> int:
        pid = jax.process_index()
        return sum(1 for d in self.devices if d.process_index == pid)

    def process_device(self, process_index: int) -> jax.Device:
        """The representative (first) device owned by a process."""
        for d in self.devices:
            if d.process_index == process_index:
                return d
        raise ValueError(f"no device owned by process {process_index}")

    # -- meshes --------------------------------------------------------

    def world_mesh(self) -> Mesh:
        """1-D mesh with axis ``world`` over every device.

        Device order follows ``jax.devices()`` which XLA already orders
        for ICI locality within a slice.
        """
        with self._lock:
            if self._world_mesh is None:
                self._world_mesh = Mesh(
                    np.asarray(self.devices, dtype=object), (WORLD_AXIS,)
                )
            return self._world_mesh

    def hierarchical_mesh(self) -> Mesh:
        """2-D ``(dcn, ici)`` mesh: processes × local devices.

        The ``ici`` axis stays inside a host/slice (fast links); the
        ``dcn`` axis crosses hosts.  Collectives that reduce over ``ici``
        first and ``dcn`` second get the reference's hierarchical
        allreduce for free from XLA.
        """
        with self._lock:
            if self._hier_mesh is None:
                devs = self.devices
                procs = sorted({d.process_index for d in devs})
                per_proc = {}
                for d in devs:
                    per_proc.setdefault(d.process_index, []).append(d)
                counts = {len(v) for v in per_proc.values()}
                if len(counts) != 1:
                    raise ValueError(
                        "hierarchical mesh requires equal device counts per "
                        f"process; got {sorted(counts)}"
                    )
                grid = np.asarray(
                    [per_proc[p] for p in procs], dtype=object
                )
                self._hier_mesh = Mesh(grid, (DCN_AXIS, ICI_AXIS))
            return self._hier_mesh

    def proc_mesh(self) -> Mesh:
        """1-D mesh with one device per process, axis ``proc``.

        This is the eager data plane: Horovod rank r ↔ process r ↔ its
        first device.  Eager collectives stack per-rank tensors along
        this axis and reduce with a jitted ``shard_map``.
        """
        with self._lock:
            if self._proc_mesh is None:
                procs = sorted({d.process_index for d in self.devices})
                reps = [self.process_device(p) for p in procs]
                self._proc_mesh = Mesh(
                    np.asarray(reps, dtype=object), (PROC_AXIS,)
                )
            return self._proc_mesh

    def nd_mesh(self, axis_names: Tuple[str, ...], shape: Tuple[int, ...]) -> Mesh:
        """Arbitrary N-D mesh (e.g. ``("dp","tp","sp")``) over all devices.

        Uses ``mesh_utils.create_device_mesh`` so the trailing axes land
        on physically adjacent ICI neighbors (bandwidth-heavy axes should
        come last).
        """
        if int(np.prod(shape)) != self.num_devices:
            raise ValueError(
                f"mesh shape {shape} does not cover {self.num_devices} devices"
            )
        from jax.experimental import mesh_utils

        try:
            grid = mesh_utils.create_device_mesh(shape, devices=self.devices)
        except Exception:
            grid = np.asarray(self.devices, dtype=object).reshape(shape)
        return Mesh(grid, axis_names)
