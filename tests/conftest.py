"""Test harness: 8 virtual CPU devices, one process.

This replicates the reference's localhost-as-cluster pattern
(SURVEY.md §4: all "multi-node" CI is N processes on loopback): here the
world is N=8 XLA CPU devices in one process, and test bodies are SPMD
(rank-oblivious shard_map bodies), the analog of tests running under
``horovodrun -np 8``.

NOTE: this sandbox pre-imports jax via sitecustomize with the TPU
platform pinned in env, so the CPU override must use jax.config.update
(env vars are read too early to take effect here).
"""

import os

# Older jax (< 0.5) has no jax_num_cpu_devices config option; the
# XLA flag below is its spelling of the same request and is read at
# backend init (first device query), which is still ahead of us.
_FLAG = "--xla_force_host_platform_device_count=8"
if _FLAG not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " " + _FLAG).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass  # XLA_FLAGS fallback above covers it

import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _ensure_cpu_devices():
    assert jax.device_count() == 8, (
        "test harness expected 8 virtual CPU devices, got "
        f"{jax.device_count()}"
    )
    yield


@pytest.fixture()
def hvt(tmp_path, monkeypatch):
    """Fresh-initialized horovod_tpu for a test, shut down afterwards.

    The flight recorder is pointed at a tmp dir so a test that trips a
    fatal path (stall abort, audit abort) dumps its postmortem there
    instead of littering the repo root."""
    import horovod_tpu as hvt_mod

    monkeypatch.setenv("HVTPU_FLIGHT_DIR", str(tmp_path))
    hvt_mod.init()
    yield hvt_mod
    hvt_mod.shutdown()


@pytest.fixture(scope="session")
def world_axis():
    return "world"


def make_discovery_script(tmp_path, spec: str):
    """Shared elastic-driver discovery fixture: a script printing the
    (rewritable) hosts file — used by the elastic integration tests
    (which mutate the file mid-run) and the CLI example smokes."""
    hosts_file = tmp_path / "hosts.txt"
    hosts_file.write_text(spec + "\n")
    script = tmp_path / "discover.sh"
    script.write_text(f'#!/bin/sh\ncat "{hosts_file}"\n')
    script.chmod(0o755)
    return hosts_file, str(script)
