"""hvtpu.data — elastic-aware sharded input pipeline.

Checkpointable iterators over deterministic sample-space shards:
``ElasticDataLoader`` prefetches on a background thread, registers its
``LoaderState`` with the elastic state machinery for exactly-once
sample delivery across preemptions and resizes, and agrees epoch
boundaries across ranks.  See docs/data.md.
"""

from .loader import ElasticDataLoader, LoaderState, quiesce_all
from .sharder import (Sharder, epoch_permutation, shard_window,
                      steps_remaining)
from .sources import (ArraySource, DataSource, FileListSource,
                      SyntheticSource, map_structure)

__all__ = [
    "ElasticDataLoader",
    "LoaderState",
    "quiesce_all",
    "Sharder",
    "epoch_permutation",
    "shard_window",
    "steps_remaining",
    "DataSource",
    "ArraySource",
    "FileListSource",
    "SyntheticSource",
    "map_structure",
]
