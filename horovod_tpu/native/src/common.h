// horovod_tpu native core — shared types.
//
// TPU-native re-implementation of the reference's C++ core vocabulary
// (reference: horovod/common/common.h — TensorTableEntry, Status,
// DataType, and horovod/common/message.h — RequestType/ResponseType).
// The data plane (actual collectives) lives in XLA; this library is the
// *control plane* for the eager path: queueing, readiness coordination,
// fusion planning, caching, stall detection.
#pragma once

#include <cstdint>
#include <string>
#include <vector>

namespace hvt {

// Parity: horovod/common/common.h DataType (wire dtype ids are part of
// the request signature, so keep a stable numbering).
enum class DataType : uint8_t {
  kUint8 = 0,
  kInt8 = 1,
  kInt32 = 2,
  kInt64 = 3,
  kFloat16 = 4,
  kBFloat16 = 5,
  kFloat32 = 6,
  kFloat64 = 7,
  kBool = 8,
};

inline int64_t DataTypeSize(DataType t) {
  switch (t) {
    case DataType::kUint8:
    case DataType::kInt8:
    case DataType::kBool:
      return 1;
    case DataType::kFloat16:
    case DataType::kBFloat16:
      return 2;
    case DataType::kInt32:
    case DataType::kFloat32:
      return 4;
    default:
      return 8;
  }
}

// Parity: horovod/common/message.h Request::RequestType (+ our BARRIER,
// which the reference spells as a zero-byte allreduce).
enum class OpType : uint8_t {
  kAllreduce = 0,
  kAllgather = 1,
  kBroadcast = 2,
  kAlltoall = 3,
  kReducescatter = 4,
  kAdasum = 5,
  kBarrier = 6,
  kJoin = 7,
};

// Reduction semantics rider for allreduce-family ops.
enum class RedOp : uint8_t {
  kSum = 0,
  kAverage = 1,
  kMin = 2,
  kMax = 3,
  kProduct = 4,
  kAdasum = 5,
};

struct Status {
  bool ok = true;
  std::string message;
  static Status OK() { return {}; }
  static Status Error(std::string msg) { return {false, std::move(msg)}; }
};

// One pending eager operation. Parity: horovod/common/common.h
// TensorTableEntry minus the framework tensor pointers — payloads stay
// on the Python/JAX side keyed by `seq`; the control plane only needs
// metadata.
struct Entry {
  uint64_t seq = 0;       // process-local enqueue sequence id (handle)
  std::string name;       // globally-meaningful tensor name
  OpType type = OpType::kAllreduce;
  RedOp red_op = RedOp::kSum;
  DataType dtype = DataType::kFloat32;
  std::vector<int64_t> shape;
  int32_t process_set_id = 0;
  int64_t group_id = -1;  // -1: ungrouped (parity: group_table.cc NULL_GROUP_ID)
  int32_t root_rank = -1; // broadcast only
  double enqueue_time_s = 0.0;  // steady-clock seconds, for stall checks

  int64_t num_elements() const {
    int64_t n = 1;
    for (int64_t d : shape) n *= d;
    return n;
  }
  int64_t nbytes() const { return num_elements() * DataTypeSize(dtype); }
};

}  // namespace hvt
