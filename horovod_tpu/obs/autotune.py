"""Online autotuning of fusion threshold / cycle time.

Parity surface: ``horovod/common/parameter_manager.cc``
(``ParameterManager``) + ``horovod/common/optim/bayesian_optimization.cc``
— enabled by ``HVTPU_AUTOTUNE=1``, scoring each sampled configuration by
observed throughput and converging on the best, optionally logging every
sample to ``HVTPU_AUTOTUNE_LOG`` as CSV.

The reference fits a Gaussian process over (fusion threshold, cycle
time).  Here the search space is the discrete log-grid below and the
tuner is successive sampling with exploitation after warmup: each
candidate gets ``autotune_steps_per_sample`` steps, scores are
bytes/sec, and after one sweep the best candidate is pinned.  On TPU
the eager path is the only consumer (the jit path fuses at compile
time), so cheap-and-robust beats a GP fit; the scoring/pinning API
matches the reference so a GP can be dropped in later.
"""

from __future__ import annotations

import csv
import time
from typing import List, Optional, Tuple

# (fusion_threshold_bytes, cycle_time_ms) candidates — log grid around
# the reference defaults (64 MB, 1-5 ms).
_DEFAULT_GRID: List[Tuple[int, float]] = [
    (2 * 1024 * 1024, 1.0),
    (8 * 1024 * 1024, 1.0),
    (32 * 1024 * 1024, 1.0),
    (64 * 1024 * 1024, 1.0),
    (64 * 1024 * 1024, 2.5),
    (128 * 1024 * 1024, 2.5),
    (128 * 1024 * 1024, 5.0),
]


class Autotuner:
    def __init__(self, config, grid: Optional[List[Tuple[int, float]]] = None):
        self._grid = list(grid or _DEFAULT_GRID)
        self._steps_per_sample = max(1, config.autotune_steps_per_sample)
        self._warmup = max(0, config.autotune_warmup_samples)
        self._log_path = config.autotune_log
        self._scores: List[float] = []
        self._candidate = 0
        self._steps = 0
        self._bytes = 0
        self._t_start = time.monotonic()
        self._pinned: Optional[Tuple[int, float]] = None
        self._warmup_left = self._warmup
        if self._log_path:
            with open(self._log_path, "w", newline="") as f:
                csv.writer(f).writerow(
                    ["fusion_threshold", "cycle_time_ms", "bytes_per_sec"]
                )

    @property
    def current(self) -> Tuple[int, float]:
        """Active (fusion_threshold_bytes, cycle_time_ms)."""
        if self._pinned is not None:
            return self._pinned
        return self._grid[self._candidate]

    @property
    def done(self) -> bool:
        return self._pinned is not None

    def record_step(self, nbytes: int):
        """Report one training/communication step of ``nbytes`` reduced.

        Drives the sampling schedule; call once per step from the eager
        controller cycle (or a training loop).
        """
        if self._pinned is not None:
            return
        if self._warmup_left > 0:
            self._warmup_left -= 1
            self._t_start = time.monotonic()
            return
        self._steps += 1
        self._bytes += nbytes
        if self._steps < self._steps_per_sample:
            return
        elapsed = max(time.monotonic() - self._t_start, 1e-9)
        score = self._bytes / elapsed
        self._scores.append(score)
        if self._log_path:
            thr, cyc = self._grid[self._candidate]
            with open(self._log_path, "a", newline="") as f:
                csv.writer(f).writerow([thr, cyc, f"{score:.1f}"])
        self._candidate += 1
        self._steps = 0
        self._bytes = 0
        self._t_start = time.monotonic()
        if self._candidate >= len(self._grid):
            best = max(range(len(self._scores)), key=self._scores.__getitem__)
            self._pinned = self._grid[best]
