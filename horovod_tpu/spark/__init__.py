"""Spark integration hook (out of scope for the TPU build; SURVEY.md
§7.3).  The reference's ``horovod.spark.run(fn)`` launches ranks on
Spark executors; TPU jobs are launched by ``hvtpurun`` / GKE instead.
The API hook is kept so code probing for it degrades clearly.
"""

from __future__ import annotations

_MSG = (
    "horovod_tpu does not ship a Spark integration: TPU workers are "
    "launched by hvtpurun (see horovod_tpu.runner) or your cluster "
    "scheduler. The horovod.spark surface is documented out of scope "
    "in SURVEY.md §7.3."
)


def run(*args, **kwargs):
    raise NotImplementedError(_MSG)


def run_elastic(*args, **kwargs):
    raise NotImplementedError(_MSG)


class KerasEstimator:  # pragma: no cover - stub surface
    def __init__(self, *args, **kwargs):
        raise NotImplementedError(_MSG)


class TorchEstimator:  # pragma: no cover - stub surface
    def __init__(self, *args, **kwargs):
        raise NotImplementedError(_MSG)
