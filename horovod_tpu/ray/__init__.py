"""Ray integration surface, local-mode functional.

Parity surface: ``horovod.ray.RayExecutor`` (horovod/ray/runner.py) —
``start()`` / ``run(fn)`` / ``run_remote``+``execute`` / ``shutdown``
driving one Horovod rank per Ray worker.  Ray placement-group
scheduling is out of scope for the TPU build (SURVEY.md §7.3: pods are
launched by hvtpurun / the cluster scheduler); the same API is provided
in **local mode**, launching ranks as local worker processes through
the hvtpurun machinery — the reference's own CI exercises RayExecutor
on a local Ray cluster the same way.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional


class RayExecutor:
    """Local-mode executor with the reference's lifecycle shape.

    >>> ex = RayExecutor(num_workers=2)
    >>> ex.start()
    >>> results = ex.run(train_fn, args=(cfg,))
    >>> ex.shutdown()
    """

    def __init__(self, settings=None, *, num_workers: Optional[int] = None,
                 num_hosts: Optional[int] = None,
                 num_workers_per_host: Optional[int] = None,
                 cpu_devices: Optional[int] = 1,
                 env_vars: Optional[Dict[str, str]] = None,
                 use_gpu: bool = False, cpus_per_worker: int = 1,
                 gpus_per_worker: Optional[int] = None):
        # reference world-size arithmetic: either num_workers directly
        # or num_hosts x num_workers_per_host — silently running a
        # different world size than asked would corrupt training
        if num_workers is None and num_hosts is not None:
            num_workers = num_hosts * (num_workers_per_host or 1)
        elif (num_workers is not None and num_hosts is not None
              and num_workers != num_hosts * (num_workers_per_host or 1)):
            raise ValueError(
                "specify num_workers OR num_hosts*num_workers_per_host, "
                "not conflicting values of both"
            )
        self.num_workers = num_workers or 2
        self.cpu_devices = cpu_devices
        self.env_vars = env_vars
        self._started = False

    def start(self):
        """No cluster to warm up in local mode; validates state."""
        self._started = True

    def run(self, fn: Callable, args: tuple = (),
            kwargs: Optional[Dict[str, Any]] = None) -> List[Any]:
        """Run ``fn`` on every rank, return per-rank results ordered by
        rank (parity: RayExecutor.run)."""
        if not self._started:
            raise RuntimeError("RayExecutor.start() must be called first")
        from .. import runner

        return runner.run(
            fn, args=args, kwargs=kwargs, np=self.num_workers,
            cpu_devices=self.cpu_devices, env=self.env_vars,
        )

    # reference API aliases
    def run_remote(self, fn: Callable, args: tuple = (),
                   kwargs: Optional[Dict[str, Any]] = None):
        """Local mode executes eagerly; returns the results list (the
        reference returns Ray ObjectRefs to pass to ``execute``)."""
        return self.run(fn, args=args, kwargs=kwargs)

    def execute(self, fn_or_results):
        """Reference shape: ``execute(fn)`` runs fn on every worker.
        Also accepts the output of :meth:`run_remote` (already a
        results list in local mode) and returns it unchanged."""
        if callable(fn_or_results):
            return self.run(fn_or_results)
        return fn_or_results

    def shutdown(self):
        self._started = False


class ElasticRayExecutor:
    """Elastic executor with the reference's lifecycle shape (parity:
    ``horovod.ray.ElasticRayExecutor``): ``start()`` then ``run(fn)``
    where ``fn`` follows the elastic contract (``hvd.elastic.State`` +
    ``@hvd.elastic.run``).  Local-mode: ranks are launched under the
    elastic DRIVER (restart-based reconfiguration, durable commits),
    not Ray actors — placement-group scheduling stays out of scope
    (SURVEY.md §7.3).  A ``host_discovery_script`` makes the world
    resize live, the reference's Ray-autoscaler discovery analog."""

    def __init__(self, settings=None, *,
                 min_workers: Optional[int] = None,
                 max_workers: Optional[int] = None,
                 num_workers: Optional[int] = None,
                 cpu_devices: Optional[int] = 1,
                 env_vars: Optional[Dict[str, str]] = None,
                 host_discovery_script: Optional[str] = None,
                 override_discovery: bool = True,  # source compat
                 use_gpu: bool = False, cpus_per_worker: int = 1,
                 gpus_per_worker: Optional[int] = None):
        # reference source compat: ElasticRayExecutor carries its
        # elastic bounds in a settings object (create_settings(min_np,
        # max_np, ...)); honor those rather than silently dropping them
        if settings is not None:
            min_workers = min_workers or getattr(
                settings, "min_np", None) or getattr(
                settings, "min_workers", None)
            max_workers = max_workers or getattr(
                settings, "max_np", None) or getattr(
                settings, "max_workers", None)
            host_discovery_script = host_discovery_script or getattr(
                settings, "discovery_script", None)
        self.num_workers = num_workers or max_workers or min_workers or 2
        self.min_workers = min_workers
        self.max_workers = max_workers
        if min_workers is not None and min_workers > self.num_workers:
            raise ValueError(
                f"min_workers={min_workers} exceeds the world size "
                f"{self.num_workers} (num_workers/max_workers): the "
                "static local discovery could never satisfy it")
        self.cpu_devices = cpu_devices
        self.env_vars = env_vars
        self.host_discovery_script = host_discovery_script
        self._started = False

    @staticmethod
    def create_settings(min_np: Optional[int] = None,
                        max_np: Optional[int] = None,
                        discovery_script: Optional[str] = None,
                        **_ignored):
        """Reference-shaped settings factory (parity:
        ElasticRayExecutor.create_settings): a plain namespace the
        constructor reads its elastic bounds from."""
        from types import SimpleNamespace

        return SimpleNamespace(min_np=min_np, max_np=max_np,
                               discovery_script=discovery_script)

    def start(self):
        self._started = True

    def run(self, fn: Callable, args: tuple = (),
            kwargs: Optional[Dict[str, Any]] = None) -> List[Any]:
        """Run ``fn`` under the elastic driver; per-rank results of the
        final world, ordered by rank."""
        if not self._started:
            raise RuntimeError(
                "ElasticRayExecutor.start() must be called first")
        from .. import runner

        return runner.run_elastic(
            fn, args=args, kwargs=kwargs,
            num_proc=self.num_workers,
            min_np=self.min_workers, max_np=self.max_workers,
            cpu_devices=self.cpu_devices, env=self.env_vars,
            host_discovery_script=self.host_discovery_script,
        )

    def shutdown(self):
        self._started = False
