"""``hvtpurun`` — the launcher CLI.

Parity surface: ``horovod/runner/launch.py`` (``parse_args``, ``_run``)
and ``horovod/runner/gloo_run.py`` (``launch_gloo``): compute rank
assignments from the host spec, build each worker's environment
(``HVTPU_RANK/SIZE/LOCAL_RANK/...`` — the HOROVOD_RANK/SIZE analog),
spawn workers with rank-prefixed output piping, and propagate the first
non-zero exit code after terminating survivors.

TPU-native departure: there is no launcher-hosted HTTP rendezvous
server (``runner/http/http_server.py``).  Rank 0's worker process hosts
the JAX coordination service (a KV store + barrier over DCN); the
launcher only picks the port and points every worker at it via
``HVTPU_COORDINATOR_ADDR/PORT``.
"""

from __future__ import annotations

import argparse
import os
import shlex
import signal
import socket
import sys
import threading
from typing import Dict, List, Optional, Sequence

from . import hosts as hosts_mod
from . import safe_shell_exec
from .hosts import SlotInfo


def find_free_port(bind_addr: str = "127.0.0.1") -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((bind_addr, 0))
        return s.getsockname()[1]


def _default_coordinator_addr(slots: List[SlotInfo]) -> str:
    """Address workers use to reach rank 0's coordination service.

    Loopback is only usable when EVERY worker is local; a mixed spec
    probes this host's NICs for a routable address (parity:
    driver_service.py's interface discovery), with --network-interface
    as the explicit override when the probe picks a wrong one.
    """
    host0 = slots[0].hostname
    if hosts_mod.is_local_host(host0):
        remotes = [s.hostname for s in slots
                   if not hosts_mod.is_local_host(s.hostname)]
        if remotes:
            from . import nic

            addr = nic.probe_coordinator_addr(remote_host=remotes[0])
            # always announce the auto-chosen address: a wrong guess is
            # otherwise a silent rendezvous hang with nothing to debug
            print(f"hvtpurun: coordinator address auto-selected: {addr} "
                  "(override with --network-interface)", file=sys.stderr)
            return addr
        return "127.0.0.1"
    return host0


def parse_args(argv: Optional[Sequence[str]] = None) -> argparse.Namespace:
    """Parity: horovod/runner/launch.py parse_args — flags mirror the
    HVTPU_*/HOROVOD_* env namespace (SURVEY.md §5.6 layer 2)."""
    p = argparse.ArgumentParser(
        prog="hvtpurun",
        description="Launch a horovod_tpu job on N worker processes.",
    )
    p.add_argument("-v", "--version", action="store_true",
                   dest="show_version",
                   help="print the horovod_tpu version and exit")
    p.add_argument("-cb", "--check-build", action="store_true",
                   help="print build capabilities (frameworks, "
                        "collectives, native core) and exit")
    p.add_argument("-np", "--num-proc", type=int, dest="np", default=None,
                   help="number of worker processes (ranks)")
    p.add_argument("-H", "--hosts", dest="hosts", default=None,
                   help='host spec "h1:2,h2:2" (default: localhost:np)')
    p.add_argument("-hostfile", "--hostfile", dest="hostfile",
                   default=None,
                   help="file of hosts, one per line: 'host slots=N' "
                        "(reference format) or 'host:N'")
    p.add_argument("-p", "--ssh-port", type=int, dest="ssh_port",
                   default=None,
                   help="ssh port for remote workers (parity: "
                        "horovodrun -p)")
    p.add_argument("-i", "--ssh-identity-file", dest="ssh_identity_file",
                   default=None,
                   help="ssh identity (private key) file for remote "
                        "workers (parity: horovodrun -i)")
    p.add_argument("-x", dest="env_passthrough", action="append",
                   default=[], metavar="VAR[=VAL]",
                   help="pass an environment variable to every worker "
                        "(repeatable); VAR alone copies the launcher's "
                        "value, VAR=VAL sets it explicitly")
    p.add_argument("--network-interface", dest="nic", default=None,
                   help="address workers use to reach the coordinator "
                        "(default: first host, or 127.0.0.1 if local)")
    p.add_argument("--coordinator-port", type=int, default=0,
                   help="coordination-service port (0 = pick a free one)")
    p.add_argument("--start-timeout", type=float, default=600.0,
                   help="seconds workers get to rendezvous at startup "
                        "(exported as HVTPU_START_TIMEOUT; does NOT "
                        "bound job duration)")
    p.add_argument("--job-timeout", type=float, default=None,
                   help="optional hard deadline for the WHOLE job; "
                        "default: unlimited")
    p.add_argument("--output-filename", default=None,
                   help="directory for per-rank output files instead of "
                        "prefixed piping (parity: horovodrun flag)")
    p.add_argument("--verbose", action="store_true")
    # engine knobs mirrored into env (layer-2 of the config scheme)
    p.add_argument("--fusion-threshold-mb", type=float, default=None)
    p.add_argument("--cycle-time-ms", type=float, default=None)
    p.add_argument("--cache-capacity", type=int, default=None)
    p.add_argument("--disable-cache", action="store_true",
                   help="disable the response cache (parity: "
                        "horovodrun --disable-cache; equals "
                        "--cache-capacity 0)")
    p.add_argument("--hierarchical-allreduce", action="store_true",
                   help="force two-stage (intra-host, cross-host) "
                        "allreduce on uniform layouts")
    p.add_argument("--autotune-warmup-samples", type=int, default=None)
    p.add_argument("--autotune-steps-per-sample", type=int, default=None)
    p.add_argument("--autotune-bayes-opt-max-samples", type=int,
                   default=None,
                   help="max Bayesian-optimization samples (maps to "
                        "HVTPU_AUTOTUNE_GP_SAMPLES)")
    p.add_argument("--timeline-filename", default=None)
    p.add_argument("--timeline-mark-cycles", action="store_true")
    p.add_argument("--trace-dir", default=None,
                   help="enable cross-rank distributed tracing: each "
                        "worker writes DIR/rank<N>.trace.json (exported "
                        "as HVTPU_TRACE; merge/report with "
                        "python -m tools.hvtputrace)")
    p.add_argument("--metrics-port", type=int, default=None,
                   help="serve Prometheus text-format metrics from each "
                        "worker at http://host:(PORT+local_rank)/metrics "
                        "(exported as HVTPU_METRICS_PORT)")
    p.add_argument("--flight-dir", default=None,
                   help="directory for flight-recorder postmortem dumps "
                        "(postmortem-<rank>-<gen>.json, written on fatal "
                        "paths or SIGUSR2; exported as HVTPU_FLIGHT_DIR; "
                        "merge with python -m tools.hvtputrace "
                        "postmortem)")
    p.add_argument("--flight-window", type=int, default=None,
                   help="flight-recorder ring capacity in events "
                        "(exported as HVTPU_FLIGHT_WINDOW; default 2048)")
    p.add_argument("--autotune", action="store_true")
    p.add_argument("--autotune-log", default=None)
    p.add_argument("--compression", default=None,
                   choices=["none", "fp16", "bf16", "int8"])
    p.add_argument("--stall-check-time", type=float, default=None,
                   help="seconds before warning about a stalled collective")
    p.add_argument("--stall-shutdown-time", type=float, default=None,
                   help="seconds before aborting a stalled collective")
    p.add_argument("--no-stall-check", action="store_true",
                   help="disable stall detection entirely (parity: "
                        "horovodrun --no-stall-check)")
    p.add_argument("--stall-check-mode", default=None,
                   choices=["amortized", "strict"],
                   help="amortized (default: local bookkeeping + KV "
                        "heartbeat, ~zero per-op cost) or strict "
                        "(per-op pre-dispatch rendezvous: nothing "
                        "dispatches until all members confirm)")
    p.add_argument("--stall-heartbeat", type=float, default=None,
                   help="amortized-mode heartbeat interval seconds "
                        "(default 0.5; detection latency is one beat)")
    p.add_argument("--log-level", default=None,
                   choices=["trace", "debug", "info", "warning", "error",
                            "fatal"])
    # elastic (driven by runner.elastic once --host-discovery-script set)
    p.add_argument("--host-discovery-script", default=None,
                   help="script printing current 'host:slots' lines; "
                        "enables elastic mode")
    p.add_argument("--min-np", type=int, default=None)
    p.add_argument("--max-np", type=int, default=None)
    p.add_argument("--elastic-timeout", type=float, default=None)
    p.add_argument("--max-restarts", type=int, default=None,
                   help="elastic restart budget: relaunches allowed "
                        "before the driver declares the workload "
                        "crash-looping and exits with a diagnostic "
                        "(default: unlimited; HVTPU_MAX_RESTARTS)")
    p.add_argument("--restart-window", type=float, default=None,
                   help="seconds: apply --max-restarts to a sliding "
                        "window instead of the whole job "
                        "(HVTPU_RESTART_WINDOW_SECONDS)")
    p.add_argument("--blacklist-cooldown", type=float, default=None,
                   help="seconds a host stays blacklisted after its "
                        "first strike; doubles per strike "
                        "(HVTPU_BLACKLIST_COOLDOWN_SECONDS, default 300)")
    # graceful preemption / drain (core/preempt.py; docs/robustness.md)
    p.add_argument("--drain-grace", type=float, default=None,
                   dest="drain_grace",
                   help="seconds a preempted worker may spend reaching "
                        "a drain commit before it force-exits; also how "
                        "long the driver waits after forwarding a drain "
                        "(HVTPU_DRAIN_GRACE_SECONDS, default 30)")
    p.add_argument("--preempt-notice-file", default=None,
                   dest="preempt_notice_file",
                   help="path workers poll for a preemption notice; "
                        "creating it triggers a coordinated drain, for "
                        "platforms that announce preemption via files "
                        "or metadata probes instead of signals "
                        "(HVTPU_PREEMPT_NOTICE_FILE)")
    # fault injection (core/faults.py; docs/robustness.md)
    p.add_argument("--fault-spec", default=None,
                   help="deterministic fault-injection spec exported "
                        "to workers as HVTPU_FAULT_SPEC, e.g. "
                        "'worker.step:kill@rank=1,count=3' "
                        "(docs/robustness.md for the grammar)")
    p.add_argument("--fault-seed", type=int, default=None,
                   help="seed for prob= fault selectors "
                        "(HVTPU_FAULT_SEED; per-rank streams derive "
                        "from it, so a seed reproduces a schedule)")
    # data-plane integrity (core/audit.py + api/optimizer.py;
    # docs/robustness.md "Integrity")
    p.add_argument("--audit-every", type=int, default=None,
                   help="run the parameter divergence audit every N "
                        "steps (0 = off; HVTPU_AUDIT_EVERY)")
    p.add_argument("--audit-action", default=None,
                   choices=["abort", "warn"],
                   help="what to do when the audit finds divergent "
                        "replicas (HVTPU_AUDIT_ACTION, default abort: "
                        "elastic jobs roll back to the last commit "
                        "and relaunch verified-identical)")
    p.add_argument("--nonfinite-action", default=None,
                   choices=["skip", "zero", "abort", "off"],
                   help="coordinated optimizer action when the reduced "
                        "gradients carry NaN/inf — every rank acts "
                        "together (HVTPU_NONFINITE_ACTION, default "
                        "skip)")
    # CPU-simulation mode (this sandbox / CI: N ranks on localhost CPU)
    p.add_argument("--cpu-devices", type=int, default=None,
                   help="force the CPU platform with this many XLA "
                        "devices per worker (testing / CI)")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="worker command, e.g. python train.py")
    args = p.parse_args(argv)
    if args.command and args.command[0] == "--":
        args.command = args.command[1:]
    if args.show_version or args.check_build:
        return args  # informational modes need no command/np
    if args.hostfile:
        if args.hosts:
            p.error("--hosts and --hostfile are mutually exclusive")
        try:
            args.hosts = parse_hostfile(args.hostfile)
        except (OSError, ValueError) as e:
            p.error(f"--hostfile {args.hostfile}: {e}")
    if not args.host_discovery_script:
        if args.np is None:
            p.error("-np is required (unless --host-discovery-script)")
    elif args.np is None:
        args.np = args.min_np or 1
    if not args.command:
        p.error("no worker command given")
    return args


def parse_hostfile(path: str) -> str:
    """Hostfile → host-spec string.  Accepts the reference's format
    ('hostname slots=N', horovod/runner/launch.py parse_host_files)
    and the compact 'hostname:N'; blank lines and # comments skipped."""
    specs = []
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            if ":" in parts[0]:
                # compact 'host:N' — one entry per line, no mixing
                # with slots= (a 'node1:4 slots=8' line is ambiguous)
                if len(parts) > 1:
                    raise ValueError(
                        f"malformed hostfile line {line!r}: compact "
                        "'host:N' lines take one entry per line")
                specs.append(parts[0])
                continue
            host = parts[0]
            slots = 1
            for tok in parts[1:]:
                if tok.startswith("slots="):
                    slots = int(tok[len("slots="):])
            specs.append(f"{host}:{slots}")
    if not specs:
        raise ValueError(f"hostfile {path!r} contains no hosts")
    return ",".join(specs)


def uniform_local_size(slots: List[SlotInfo]) -> int:
    """The common per-host slot count when the layout is uniform (every
    host has the same local_size), else 0.  Hierarchical collectives
    require a uniform grid; the launcher is the one place that can see
    the whole layout, so it certifies uniformity to the workers."""
    sizes = {s.local_size for s in slots}
    return slots[0].local_size if len(sizes) == 1 else 0


def build_worker_env(
    base_env: Dict[str, str],
    slot: SlotInfo,
    coordinator_addr: str,
    coordinator_port: int,
    args: Optional[argparse.Namespace] = None,
    uniform_local: Optional[int] = None,
) -> Dict[str, str]:
    """Per-rank environment (parity: the env block launch_gloo exports —
    HOROVOD_RANK/SIZE/LOCAL_RANK/LOCAL_SIZE/CROSS_RANK/CROSS_SIZE plus
    rendezvous address/port)."""
    env = dict(base_env)
    env.update(
        HVTPU_RANK=str(slot.rank),
        HVTPU_SIZE=str(slot.size),
        HVTPU_LOCAL_RANK=str(slot.local_rank),
        HVTPU_LOCAL_SIZE=str(slot.local_size),
        HVTPU_CROSS_RANK=str(slot.cross_rank),
        HVTPU_CROSS_SIZE=str(slot.cross_size),
        HVTPU_COORDINATOR_ADDR=coordinator_addr,
        HVTPU_COORDINATOR_PORT=str(coordinator_port),
    )
    if uniform_local is not None:
        env["HVTPU_UNIFORM_LOCAL_SIZE"] = str(uniform_local)
    # Source-checkout robustness: make the horovod_tpu package the
    # launcher itself is running from importable in workers even when
    # it is not pip-installed and the script lives elsewhere (the
    # reference assumes an installed horovod; worker scripts here are
    # run by absolute path, so cwd is not on sys.path).
    pkg_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    parts = [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p]
    if pkg_root not in parts:
        env["PYTHONPATH"] = os.pathsep.join([pkg_root] + parts)
    if args is not None:
        flag_env = {
            "HVTPU_FUSION_THRESHOLD_MB": args.fusion_threshold_mb,
            "HVTPU_CYCLE_TIME": args.cycle_time_ms,
            "HVTPU_CACHE_CAPACITY": args.cache_capacity,
            "HVTPU_TIMELINE": args.timeline_filename,
            "HVTPU_TRACE": args.trace_dir,
            "HVTPU_METRICS_PORT": args.metrics_port,
            "HVTPU_FLIGHT_DIR": getattr(args, "flight_dir", None),
            "HVTPU_FLIGHT_WINDOW": getattr(args, "flight_window", None),
            "HVTPU_AUTOTUNE_LOG": args.autotune_log,
            "HVTPU_COMPRESSION": args.compression,
            "HVTPU_STALL_CHECK_TIME_SECONDS": args.stall_check_time,
            "HVTPU_STALL_SHUTDOWN_TIME_SECONDS": args.stall_shutdown_time,
            "HVTPU_STALL_CHECK_MODE": args.stall_check_mode,
            "HVTPU_STALL_HEARTBEAT_SECONDS": args.stall_heartbeat,
            "HVTPU_LOG_LEVEL": args.log_level,
            "HVTPU_CPU_DEVICES": args.cpu_devices,
            "HVTPU_FAULT_SPEC": getattr(args, "fault_spec", None),
            "HVTPU_FAULT_SEED": getattr(args, "fault_seed", None),
            "HVTPU_AUDIT_EVERY": getattr(args, "audit_every", None),
            "HVTPU_AUDIT_ACTION": getattr(args, "audit_action", None),
            "HVTPU_NONFINITE_ACTION":
                getattr(args, "nonfinite_action", None),
            "HVTPU_ELASTIC_TIMEOUT": args.elastic_timeout,
            "HVTPU_DRAIN_GRACE_SECONDS": getattr(args, "drain_grace", None),
            "HVTPU_PREEMPT_NOTICE_FILE":
                getattr(args, "preempt_notice_file", None),
            "HVTPU_START_TIMEOUT": args.start_timeout,
            "HVTPU_AUTOTUNE_WARMUP_SAMPLES": args.autotune_warmup_samples,
            "HVTPU_AUTOTUNE_STEPS_PER_SAMPLE":
                args.autotune_steps_per_sample,
            "HVTPU_AUTOTUNE_GP_SAMPLES":
                args.autotune_bayes_opt_max_samples,
        }
        for k, v in flag_env.items():
            if v is not None:
                env[k] = str(v)
        if args.autotune:
            env["HVTPU_AUTOTUNE"] = "1"
        if args.timeline_mark_cycles:
            env["HVTPU_TIMELINE_MARK_CYCLES"] = "1"
        if args.disable_cache:
            env["HVTPU_CACHE_CAPACITY"] = "0"
        if args.no_stall_check:
            env["HVTPU_STALL_CHECK_DISABLE"] = "1"
        if args.hierarchical_allreduce:
            env["HVTPU_HIERARCHICAL_ALLREDUCE"] = "1"
        # -x VAR[=VAL]: explicit per-worker env passthrough (parity:
        # mpirun -x, which horovodrun users reach via --mpi-args; the
        # ssh path forwards only the HVTPU_/JAX_/... namespace, so -x
        # is how arbitrary app variables cross hosts)
        for spec in args.env_passthrough:
            if "=" in spec:
                k, v = spec.split("=", 1)
                env[k] = v
            elif spec in base_env:
                env[spec] = base_env[spec]
            else:
                # mpirun parity: -x of an unset variable warns instead
                # of silently launching workers without it
                print(f"hvtpurun: warning: -x {spec}: variable not "
                      "found in the launcher environment",
                      file=sys.stderr)
    return env


def ssh_options_from_args(args: Optional[argparse.Namespace]) -> Dict:
    """The launcher-flag subset build_ssh_command consumes — one
    derivation shared by the static and elastic spawn paths so `-p`,
    `-i`, and `-x` can never apply in one mode and not the other."""
    if args is None:
        return {}
    return {
        "ssh_port": args.ssh_port,
        "ssh_identity_file": args.ssh_identity_file,
        "extra_env_keys": [s.split("=", 1)[0]
                           for s in args.env_passthrough],
    }


def build_ssh_command(
    hostname: str,
    command: Sequence[str],
    env: Dict[str, str],
    cwd: Optional[str] = None,
    ssh_port: Optional[int] = None,
    ssh_identity_file: Optional[str] = None,
    extra_env_keys: Sequence[str] = (),
) -> List[str]:
    """Remote worker command line (parity: get_remote_command /
    get_ssh_command in horovod/runner/util/remote.py).  Only the
    HVTPU_*/JAX_*/XLA_* env subset is forwarded — plus any ``-x``
    passthrough names in ``extra_env_keys`` — like the reference
    forwarding its own namespace with ``env`` on the remote shell.
    """
    extra = set(extra_env_keys)
    exports = " ".join(
        f"{k}={shlex.quote(v)}"
        for k, v in sorted(env.items())
        if (k.startswith(("HVTPU_", "HOROVOD_", "JAX_", "XLA_", "TPU_",
                          "PYTHONPATH")) or k in extra)
        # never serialize the HMAC key itself into argv — it would be
        # world-readable via /proc/*/cmdline on both ends; the key
        # rides a 0600 file (HVTPU_SECRET_FILE) instead
        and k != "HVTPU_SECRET_KEY"
    )
    inner = " ".join(shlex.quote(c) for c in command)
    if cwd:
        inner = f"cd {shlex.quote(cwd)} && env {exports} {inner}"
    else:
        inner = f"env {exports} {inner}"
    # HVTPU_SSH_COMMAND swaps the transport binary (integration tests
    # use a local shim so the REAL remote code path — env export
    # serialization, quoting, cwd, piping, exit propagation — executes
    # on machines without sshd; parity: the reference's ssh command is
    # also centrally constructed and test-substituted).
    override = os.environ.get("HVTPU_SSH_COMMAND")
    if override:
        ssh = shlex.split(override)
    else:
        ssh = ["ssh", "-o", "PasswordAuthentication=no",
               "-o", "StrictHostKeyChecking=no"]
        if ssh_port:
            ssh += ["-p", str(ssh_port)]
        if ssh_identity_file:
            ssh += ["-i", ssh_identity_file]
    return ssh + [hostname, inner]


def launch_workers(
    command: Sequence[str],
    slots: List[SlotInfo],
    coordinator_addr: str,
    coordinator_port: int,
    args: Optional[argparse.Namespace] = None,
    base_env: Optional[Dict[str, str]] = None,
    job_timeout: Optional[float] = None,
    output_dir: Optional[str] = None,
) -> int:
    """Spawn one worker per slot and wait (parity: launch_gloo).

    ``job_timeout`` is an optional hard deadline for the whole job;
    startup/rendezvous timeouts are the workers' business
    (HVTPU_START_TIMEOUT -> jax.distributed initialization_timeout).
    """
    base_env = dict(base_env if base_env is not None else os.environ)
    stdout_lock = threading.Lock()
    uniform = uniform_local_size(slots)
    ssh_opts = ssh_options_from_args(args)
    workers: List[safe_shell_exec.WorkerProcess] = []
    try:
        for slot in slots:
            env = build_worker_env(
                base_env, slot, coordinator_addr, coordinator_port, args,
                uniform_local=uniform,
            )
            if hosts_mod.is_local_host(slot.hostname):
                cmd = list(command)
            else:
                cmd = build_ssh_command(
                    slot.hostname, command, env, cwd=os.getcwd(),
                    **ssh_opts,
                )
            workers.append(
                safe_shell_exec.WorkerProcess(
                    slot.rank, cmd, env,
                    output_dir=output_dir,
                    stdout_lock=stdout_lock,
                )
            )
    except Exception:
        for w in workers:
            w.terminate()
        raise

    def _on_failure(w, code):
        print(
            f"hvtpurun: rank {w.rank} exited with code {code}; "
            "terminating remaining workers",
            file=sys.stderr,
        )

    # Launcher SIGTERM (scheduler preemption of hvtpurun itself)
    # forwards the configured preemption signal to every live worker
    # so they run the coordinated drain protocol (core/preempt.py)
    # instead of dying to the escalation path's killpg — the workers'
    # own SIGTERM handler publishes the drain notice; the escalation
    # timer only starts after this wait returns.
    def _forward_preempt(signum, frame):
        from ..core.preempt import configured_signal

        fwd = configured_signal()
        for w in workers:
            if w.poll() is None and fwd is not None:
                try:
                    os.kill(w.proc.pid, fwd)
                except (ProcessLookupError, OSError):
                    pass
        print("hvtpurun: SIGTERM received; forwarded preemption "
              "notice to workers (coordinated drain)", file=sys.stderr)

    prev_term = None
    try:
        prev_term = signal.signal(signal.SIGTERM, _forward_preempt)
    except ValueError:
        pass  # non-main thread: no forwarding, escalation path only
    try:
        return safe_shell_exec.wait_for_any_failure_or_all_done(
            workers, timeout=job_timeout, on_failure=_on_failure
        )
    finally:
        if prev_term is not None:
            try:
                signal.signal(signal.SIGTERM, prev_term)
            except ValueError:
                pass


def _check_build() -> int:
    """Parity: horovodrun -cb (check_build in the reference's
    launch.py): print version + available capabilities and exit."""
    from .. import version as _version

    print(f"hvtpurun (horovod_tpu) v{_version.__version__}")
    import horovod_tpu as hvt

    def mark(flag):
        return "[X]" if flag else "[ ]"

    def probe(name):
        try:
            __import__(name)
            return True
        except ImportError:
            return False

    try:
        from ..native import core as native_core
        native = bool(native_core.available())
    except Exception:
        native = False
    print("Available frameworks:")
    print(f"    {mark(True)} JAX")
    print(f"    {mark(probe('tensorflow'))} TensorFlow")
    print(f"    {mark(probe('torch'))} PyTorch")
    print(f"    {mark(probe('keras'))} Keras")
    print("Available controllers:")
    print(f"    {mark(native)} native C++ core")
    print(f"    {mark(True)} Python twin")
    print("Available tensor operations:")
    print(f"    {mark(hvt.xla_built())} XLA collectives (ICI/DCN)")
    print(f"    {mark(bool(hvt.nccl_built()))} NCCL")
    print(f"    {mark(hvt.mpi_built())} MPI")
    return 0


def _run(args: argparse.Namespace) -> int:
    """Parity: horovod/runner/launch.py _run — static vs elastic split."""
    if args.show_version:
        from .. import version as _version

        print(_version.__version__)
        return 0
    if args.check_build:
        return _check_build()
    # Workers inherit a fault spec from either the flag or a
    # pre-existing HVTPU_FAULT_SPEC in the launcher's environment
    # (launch_workers forwards both).  Validate every source here,
    # before any spawn: a malformed clause would otherwise kill each
    # worker at fault-registry init, which at scale reads as a
    # mysterious whole-job crash instead of one launcher-side error
    # naming the bad clause.
    for origin, spec in (("--fault-spec", args.fault_spec),
                         ("HVTPU_FAULT_SPEC",
                          os.environ.get("HVTPU_FAULT_SPEC"))):
        if not spec:
            continue
        from ..core.faults import FaultSpecError, parse_spec

        try:
            parse_spec(spec)  # fail fast, before any spawn
        except FaultSpecError as e:
            print(f"hvtpurun: {origin}: {e}", file=sys.stderr)
            return 2
    if args.host_discovery_script:
        from ..elastic.driver import run_elastic

        return run_elastic(args)
    host_spec = args.hosts or f"localhost:{args.np}"
    slots = hosts_mod.get_host_assignments(
        hosts_mod.parse_host_spec(host_spec), args.np
    )
    if args.nic:
        from . import nic as nic_mod

        coordinator_addr = nic_mod.resolve_interface(args.nic)
    else:
        coordinator_addr = _default_coordinator_addr(slots)
    port = args.coordinator_port or find_free_port()
    if args.verbose:
        print(
            f"hvtpurun: {args.np} ranks on {host_spec}, "
            f"coordinator {coordinator_addr}:{port}",
            file=sys.stderr,
        )
    return launch_workers(
        args.command,
        slots,
        coordinator_addr,
        port,
        args=args,
        job_timeout=args.job_timeout,
        output_dir=args.output_filename,
    )


def main(argv: Optional[Sequence[str]] = None) -> int:
    return _run(parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
