"""Python mirror of the native wire format (native/src/message.cc).

Parity surface: ``horovod/common/message.cc`` (+ ``wire/message.fbs``)
— Request/RequestList/Response/ResponseList.  The byte layout here is
bit-identical to the C++ implementation so native and pure-Python
controllers interoperate on the same coordination channel (mixed
deployments, and the fallback when no C++ toolchain is present).
"""

from __future__ import annotations

import dataclasses
import struct
from typing import List, Tuple

REQUEST_MAGIC = 0x52545648  # "HVTR"
RESPONSE_MAGIC = 0x50545648  # "HVTP"
# v2: ResponseList carries coordinator-tuned (fusion threshold, cycle
# time) so every rank applies identical autotuned parameters (parity:
# ParameterManager broadcasting tuned params from the coordinator).
# v3: RequestList grows the steady-state `cache_bits` frame (bypass
# cycles negotiate via a per-rank cache-bit vector instead of
# serialized requests; parity: the coordinated cache bitvector of
# Controller::CoordinateCacheAndState) plus bypass/resync flags, and
# ResponseList carries `cache_resync_needed` so the coordinator can
# force every rank back to a full-request cycle.
# v5 (v4 was an ABI-only bump): RequestList carries the atomic
# burst-unit delimiter (`burst_id`/`burst_len` right after the flags
# byte, covering the leading requests or cache bits of this drain) and
# a `predicted` flag (bit 4) marking the blob as a post-hoc
# confirmation of a locally predicted schedule; ResponseList carries
# `confirm_hashes` (FNV-1a 64 of each suppressed fully-predicted
# component's would-be response bytes) so predictors verify without a
# response round trip.
WIRE_VERSION = 5

# OpType (native/src/common.h)
ALLREDUCE, ALLGATHER, BROADCAST, ALLTOALL, REDUCESCATTER, ADASUM, BARRIER, JOIN = range(8)
# RedOp
RED_SUM, RED_AVERAGE, RED_MIN, RED_MAX, RED_PRODUCT, RED_ADASUM = range(6)
# DataType
DTYPE_IDS = {
    "uint8": 0, "int8": 1, "int32": 2, "int64": 3,
    "float16": 4, "bfloat16": 5, "float32": 6, "float64": 7, "bool": 8,
}
DTYPE_NAMES = {v: k for k, v in DTYPE_IDS.items()}
DTYPE_SIZES = {0: 1, 1: 1, 2: 4, 3: 8, 4: 2, 5: 2, 6: 4, 7: 8, 8: 1}


@dataclasses.dataclass
class Entry:
    seq: int = 0
    name: str = ""
    type: int = ALLREDUCE
    red_op: int = RED_SUM
    dtype: int = 6
    shape: Tuple[int, ...] = ()
    process_set_id: int = 0
    group_id: int = -1
    root_rank: int = -1

    @property
    def num_elements(self) -> int:
        n = 1
        for d in self.shape:
            n *= d
        return n

    @property
    def nbytes(self) -> int:
        return self.num_elements * DTYPE_SIZES[self.dtype]

    def signature(self) -> str:
        """Must match ResponseCache::Signature (controller.cc)."""
        dims = "".join(f"{d}," for d in self.shape)
        return (f"{self.name}|{self.type}|{self.red_op}|{self.dtype}|"
                f"{self.process_set_id}|{self.root_rank}|{dims}")


@dataclasses.dataclass
class Request:
    rank: int = 0
    entry: Entry = dataclasses.field(default_factory=Entry)
    cached: bool = False
    cache_bit: int = 0


@dataclasses.dataclass
class RequestList:
    rank: int = 0
    requests: List[Request] = dataclasses.field(default_factory=list)
    cache_hits: List[int] = dataclasses.field(default_factory=list)
    joined: bool = False
    shutdown: bool = False
    # Steady-state bypass cycle: ``requests`` is empty and the drained
    # ops travel as set bits in ``cache_bits`` (u64 words, bit b set =>
    # this rank drained a request whose signature holds cache bit b).
    cache_bypass: bool = False
    # This blob is a periodic full resync: requests carry FULL entries
    # (no per-request bit compression) so the coordinator's message
    # table and stall inspector re-anchor on ground truth.
    cache_resync: bool = False
    cache_bits: List[int] = dataclasses.field(default_factory=list)
    # Post-hoc confirmation of a locally predicted schedule: the rank
    # already executed predict_responses(cache_bits) and is not waiting
    # for a ResponseList (it only expects a confirm hash).
    predicted: bool = False
    # Atomic burst unit: this drain's first `burst_len` requests (or,
    # on a bypass blob, its first `burst_len` cache bits in ascending
    # order) form one indivisible unit — the coordinator releases and
    # fuses them together, never across the unit boundary.  0 = no
    # unit (empty drains, membership frames, resync re-announcements).
    burst_id: int = 0
    burst_len: int = 0


# Confirm-hash function for suppressed predicted components.  Must
# match Fnv1a64() in native/src/message.cc byte-for-byte.
_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3


def fnv1a64(data: bytes) -> int:
    h = _FNV_OFFSET
    for b in data:
        h = ((h ^ b) * _FNV_PRIME) & 0xFFFFFFFFFFFFFFFF
    return h


# --- retry attempt tags ----------------------------------------------
# The consensus abort-and-retry plane (comm/wirefault.py) reissues a
# dead collective under ATTEMPT-TAGGED wire keys so a late packet from
# an aborted attempt can never be mistaken for the live one.  The tag
# rides INSIDE the existing variable-length name/key strings — entry
# names, KV exchange keys — so the wire format itself is unchanged
# (same WIRE_VERSION, byte-identical twins).  Attempt 0 is untagged:
# the healthy path serializes exactly the bytes it always did.
_ATTEMPT_SEP = "#a"


def attempt_tag(name: str, attempt: int) -> str:
    """Tag a wire key / tensor name with a retry attempt number
    (attempt 0 → the name unchanged)."""
    if attempt <= 0:
        return name
    return f"{name}{_ATTEMPT_SEP}{attempt}"


def split_attempt(name: str) -> Tuple[str, int]:
    """Inverse of :func:`attempt_tag`: ``(base_name, attempt)``."""
    base, sep, tail = name.rpartition(_ATTEMPT_SEP)
    if sep and tail.isdigit():
        return base, int(tail)
    return name, 0


# Byte offset of the RequestList flags byte: magic u32 + version u32 +
# rank i32 + joined u8 + shutdown u8.
_FLAGS_OFFSET = 4 + 4 + 4 + 1 + 1


def mark_predicted(blob: bytes) -> bytes:
    """Flip the `predicted` flag on an already-serialized RequestList.

    Turns a drained bypass blob into the compact post-hoc confirmation
    the drainer posts after executing a locally predicted schedule
    (byte-identical to serializing with predicted=True)."""
    return (blob[:_FLAGS_OFFSET]
            + bytes([blob[_FLAGS_OFFSET] | 4])
            + blob[_FLAGS_OFFSET + 1:])


def bits_to_words(bits: List[int]) -> List[int]:
    """Pack bit ids into a little-endian u64-word bitvector."""
    words: List[int] = []
    for b in bits:
        w, o = b >> 6, b & 63
        while len(words) <= w:
            words.append(0)
        words[w] |= 1 << o
    return words


def words_to_bits(words: List[int]) -> List[int]:
    """Unpack a u64-word bitvector into ascending bit ids."""
    bits: List[int] = []
    for w, word in enumerate(words):
        base = w << 6
        while word:
            o = (word & -word).bit_length() - 1
            bits.append(base + o)
            word &= word - 1
    return bits


@dataclasses.dataclass
class Response:
    type: int = ALLREDUCE
    red_op: int = RED_SUM
    dtype: int = 6
    process_set_id: int = 0
    root_rank: int = -1
    tensor_names: List[str] = dataclasses.field(default_factory=list)
    tensor_shapes: List[Tuple[int, ...]] = dataclasses.field(default_factory=list)
    total_bytes: int = 0
    error: str = ""


@dataclasses.dataclass
class ResponseList:
    responses: List[Response] = dataclasses.field(default_factory=list)
    join_last_rank: int = -1
    shutdown: bool = False
    # Coordinator could not expand a bypass cache bit (cache divergence,
    # e.g. an elastic restart mixing generations): every rank must send
    # a full-resync request blob next cycle, re-announcing in-flight ops.
    cache_resync_needed: bool = False
    # coordinator-tuned parameters (-1 = unset)
    tuned_fusion_threshold: int = -1
    tuned_cycle_time_us: int = -1
    # One FNV-1a 64 hash per suppressed fully-predicted burst
    # component (in release order): every announcing rank predicted the
    # identical schedule, so the coordinator emits the hash of the
    # would-be response bytes instead of the responses themselves.
    confirm_hashes: List[int] = dataclasses.field(default_factory=list)


class _W:
    def __init__(self):
        self.parts: List[bytes] = []

    def u8(self, v): self.parts.append(struct.pack("<B", v))
    def u32(self, v): self.parts.append(struct.pack("<I", v))
    def i32(self, v): self.parts.append(struct.pack("<i", v))
    def i64(self, v): self.parts.append(struct.pack("<q", v))
    def u64(self, v): self.parts.append(struct.pack("<Q", v))

    def s(self, v: str):
        b = v.encode("utf-8")
        self.u32(len(b))
        self.parts.append(b)

    def bytes(self) -> bytes:
        return b"".join(self.parts)


class _R:
    def __init__(self, data: bytes):
        self.data = data
        self.off = 0

    def _take(self, fmt: str, n: int):
        v = struct.unpack_from(fmt, self.data, self.off)[0]
        self.off += n
        return v

    def u8(self): return self._take("<B", 1)
    def u32(self): return self._take("<I", 4)
    def i32(self): return self._take("<i", 4)
    def i64(self): return self._take("<q", 8)
    def u64(self): return self._take("<Q", 8)

    def s(self) -> str:
        n = self.u32()
        v = self.data[self.off:self.off + n].decode("utf-8")
        self.off += n
        return v


def _write_entry(w: _W, e: Entry):
    w.u64(e.seq)
    w.s(e.name)
    w.u8(e.type)
    w.u8(e.red_op)
    w.u8(e.dtype)
    w.u8(len(e.shape))
    for d in e.shape:
        w.i64(d)
    w.i32(e.process_set_id)
    w.i64(e.group_id)
    w.i32(e.root_rank)


def _read_entry(r: _R) -> Entry:
    e = Entry()
    e.seq = r.u64()
    e.name = r.s()
    e.type = r.u8()
    e.red_op = r.u8()
    e.dtype = r.u8()
    ndim = r.u8()
    e.shape = tuple(r.i64() for _ in range(ndim))
    e.process_set_id = r.i32()
    e.group_id = r.i64()
    e.root_rank = r.i32()
    return e


def serialize_request_list(rl: RequestList) -> bytes:
    w = _W()
    w.u32(REQUEST_MAGIC)
    w.u32(WIRE_VERSION)
    w.i32(rl.rank)
    w.u8(1 if rl.joined else 0)
    w.u8(1 if rl.shutdown else 0)
    w.u8((1 if rl.cache_bypass else 0) | (2 if rl.cache_resync else 0)
         | (4 if rl.predicted else 0))
    w.u32(rl.burst_id)
    w.u32(rl.burst_len)
    w.u32(len(rl.cache_bits))
    for word in rl.cache_bits:
        w.u64(word)
    w.u32(len(rl.cache_hits))
    for b in rl.cache_hits:
        w.u32(b)
    w.u32(len(rl.requests))
    for rq in rl.requests:
        w.i32(rq.rank)
        w.u8(1 if rq.cached else 0)
        w.u32(rq.cache_bit)
        _write_entry(w, rq.entry)
    return w.bytes()


def parse_request_list(data: bytes) -> RequestList:
    r = _R(data)
    if r.u32() != REQUEST_MAGIC:
        raise ValueError("bad request magic")
    if r.u32() != WIRE_VERSION:
        raise ValueError("bad wire version")
    rl = RequestList()
    rl.rank = r.i32()
    rl.joined = r.u8() != 0
    rl.shutdown = r.u8() != 0
    flags = r.u8()
    rl.cache_bypass = bool(flags & 1)
    rl.cache_resync = bool(flags & 2)
    rl.predicted = bool(flags & 4)
    rl.burst_id = r.u32()
    rl.burst_len = r.u32()
    rl.cache_bits = [r.u64() for _ in range(r.u32())]
    rl.cache_hits = [r.u32() for _ in range(r.u32())]
    n = r.u32()
    for _ in range(n):
        rq = Request()
        rq.rank = r.i32()
        rq.cached = r.u8() != 0
        rq.cache_bit = r.u32()
        rq.entry = _read_entry(r)
        rl.requests.append(rq)
    return rl


def serialize_response_list(rl: ResponseList) -> bytes:
    w = _W()
    w.u32(RESPONSE_MAGIC)
    w.u32(WIRE_VERSION)
    w.i32(rl.join_last_rank)
    w.u8(1 if rl.shutdown else 0)
    w.u8(1 if rl.cache_resync_needed else 0)
    w.i64(rl.tuned_fusion_threshold)
    w.i32(rl.tuned_cycle_time_us)
    w.u32(len(rl.confirm_hashes))
    for h in rl.confirm_hashes:
        w.u64(h)
    w.u32(len(rl.responses))
    for rs in rl.responses:
        w.u8(rs.type)
        w.u8(rs.red_op)
        w.u8(rs.dtype)
        w.i32(rs.process_set_id)
        w.i32(rs.root_rank)
        w.i64(rs.total_bytes)
        w.s(rs.error)
        w.u32(len(rs.tensor_names))
        for n in rs.tensor_names:
            w.s(n)
        for shape in rs.tensor_shapes:
            w.u8(len(shape))
            for d in shape:
                w.i64(d)
    return w.bytes()


def parse_response_list(data: bytes) -> ResponseList:
    r = _R(data)
    if r.u32() != RESPONSE_MAGIC:
        raise ValueError("bad response magic")
    if r.u32() != WIRE_VERSION:
        raise ValueError("bad wire version")
    rl = ResponseList()
    rl.join_last_rank = r.i32()
    rl.shutdown = r.u8() != 0
    rl.cache_resync_needed = r.u8() != 0
    rl.tuned_fusion_threshold = r.i64()
    rl.tuned_cycle_time_us = r.i32()
    rl.confirm_hashes = [r.u64() for _ in range(r.u32())]
    n = r.u32()
    for _ in range(n):
        rs = Response()
        rs.type = r.u8()
        rs.red_op = r.u8()
        rs.dtype = r.u8()
        rs.process_set_id = r.i32()
        rs.root_rank = r.i32()
        rs.total_bytes = r.i64()
        rs.error = r.s()
        nt = r.u32()
        rs.tensor_names = [r.s() for _ in range(nt)]
        rs.tensor_shapes = [
            tuple(r.i64() for _ in range(r.u8())) for _ in range(nt)
        ]
        rl.responses.append(rs)
    return rl
