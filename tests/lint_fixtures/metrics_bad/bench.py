"""bench fixture (bad): requires a metric nobody registers or catalogs."""

REQUIRED_METRIC_KEYS = [
    "hvtpu_fixture_missing_total",
]
