"""Elastic fault-injection integration tests (reference pattern:
test/integration/elastic_common.py — launch real `horovodrun
--host-discovery-script` jobs on localhost, kill workers / mutate the
discovery output mid-run, assert recovery).

Here: `hvtpurun --host-discovery-script` with CPU workers.  World
reconfiguration is restart-based (see horovod_tpu/elastic/): workers
exit RESET_EXIT_CODE at commit boundaries and the driver relaunches
them; progress resumes from the durable commit.
"""

import os
import subprocess
import sys
import time

import pytest

import horovod_tpu

pytestmark = pytest.mark.multiprocess

_REPO = os.path.dirname(os.path.dirname(horovod_tpu.__file__))
_SCRIPT = os.path.join(_REPO, "tests", "elastic_train_script.py")


from conftest import make_discovery_script as _make_discovery  # noqa: E402


def _launch(discovery_script, extra_env=None, min_np=2, max_np=None,
            epochs=6, sleep_s=0.3, cpu_devices=1, script=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["ELASTIC_EPOCHS"] = str(epochs)
    env["EPOCH_SLEEP"] = str(sleep_s)
    env["HVTPU_ELASTIC_DISCOVERY_INTERVAL"] = "0.2"
    env.update(extra_env or {})
    cmd = [
        sys.executable, "-m", "horovod_tpu.runner",
        "--host-discovery-script", discovery_script,
        "--min-np", str(min_np),
        "--cpu-devices", str(cpu_devices), "--verbose",
    ]
    if max_np:
        cmd += ["--max-np", str(max_np)]
    cmd += ["--", sys.executable, script or _SCRIPT]
    return subprocess.Popen(
        cmd, env=env, cwd=_REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )


def test_worker_crash_recovers_from_commit(tmp_path):
    """Kill one worker mid-run (self-crash, one incarnation only): the
    driver must relaunch and training must RESUME from the committed
    epoch, not restart from zero."""
    _, disc = _make_discovery(tmp_path, "localhost:2")
    marker = tmp_path / "crashed.marker"
    proc = _launch(
        disc,
        extra_env={
            "CRASH_MARKER": str(marker),
            "CRASH_RANK": "1",
            "CRASH_EPOCH": "2",
        },
        min_np=2, epochs=5,
    )
    out, _ = proc.communicate(timeout=240)
    assert proc.returncode == 0, out[-3000:]
    assert marker.exists(), "crash injection never fired"
    # worker output arrives rank-prefixed ("[0]<stdout>:EPOCH ...")
    epochs_seen = [
        int(ln.split("epoch=")[1].split()[0])
        for ln in out.splitlines() if "EPOCH epoch=" in ln
    ]
    # the crash happened at epoch 2; the relaunched incarnation must
    # resume from the commit (>= 2), never replay epochs 0/1
    crash_at = epochs_seen.index(2)
    assert all(e >= 2 for e in epochs_seen[crash_at:]), out[-3000:]
    assert epochs_seen[0] == 0, out[-3000:]  # first incarnation from 0
    assert "DONE size=2 epoch=5" in out, out[-3000:]


def _stream_until_exit(proc, on_line, deadline_s=240.0):
    """Read rank-prefixed output until the job exits, firing
    ``on_line`` per line.  The deadline is enforced with select() so a
    job that hangs WITHOUT producing output still fails the test
    instead of blocking readline() forever."""
    import select

    lines = []
    start = time.monotonic()
    fd = proc.stdout
    while True:
        remaining = deadline_s - (time.monotonic() - start)
        if remaining <= 0:
            proc.kill()
            pytest.fail("timeout:\n" + "\n".join(lines[-40:]))
        ready, _, _ = select.select([fd], [], [], min(remaining, 5.0))
        if not ready:
            if proc.poll() is not None:
                break
            continue
        line = fd.readline()
        if not line:
            break
        lines.append(line.rstrip())
        on_line(line)
    proc.wait(timeout=30)
    return lines


def test_discovery_shrink_resizes_world(tmp_path):
    """Rewrite the discovery output mid-run (3 -> 2 slots): the driver
    must notify workers (SIGUSR1), relaunch at the new size, and the
    job must finish with size=2 while keeping committed progress."""
    hosts_file, disc = _make_discovery(tmp_path, "localhost:3")
    proc = _launch(disc, min_np=2, epochs=10, sleep_s=0.4)
    state = {"shrunk": False}

    def on_line(line):
        if not state["shrunk"] and "EPOCH epoch=1 " in line:
            hosts_file.write_text("localhost:2\n")
            state["shrunk"] = True

    lines = _stream_until_exit(proc, on_line)
    shrunk = state["shrunk"]
    out = "\n".join(lines)
    assert proc.returncode == 0, out[-3000:]
    assert shrunk, out[-2000:]
    assert any("size=3" in ln for ln in lines), out[-3000:]
    assert "DONE size=2 epoch=10" in out, out[-3000:]


def test_discovery_grow_resizes_world(tmp_path):
    """Grow path (reference: ElasticDriver host-add): rewrite discovery
    2 -> 3 slots mid-run; the driver must notify, relaunch at size 3,
    and resume from the commit rather than restarting at epoch 0."""
    hosts_file, disc = _make_discovery(tmp_path, "localhost:2")
    proc = _launch(disc, min_np=2, epochs=10, sleep_s=0.4)
    state = {"grown": False}

    def on_line(line):
        if not state["grown"] and "EPOCH epoch=1 " in line:
            hosts_file.write_text("localhost:3\n")
            state["grown"] = True

    lines = _stream_until_exit(proc, on_line)
    grown = state["grown"]
    out = "\n".join(lines)
    assert proc.returncode == 0, out[-3000:]
    assert grown, out[-2000:]
    assert any("size=2" in ln for ln in lines), out[-3000:]
    assert "DONE size=3 epoch=10" in out, out[-3000:]
    # reset callbacks fire in the relaunched incarnation, seeing the
    # NEW world size (generation-stamped by the driver)
    assert any("RESET_CB" in ln and "size=3" in ln for ln in lines), \
        out[-3000:]
    # resume-from-commit: the size-3 incarnation must not replay epoch 0
    sizes_by_epoch = [
        (int(ln.split("epoch=")[1].split()[0]), "size=3" in ln)
        for ln in lines if "EPOCH epoch=" in ln
    ]
    first3 = next(i for i, (_, is3) in enumerate(sizes_by_epoch) if is3)
    assert sizes_by_epoch[first3][0] >= 1, out[-3000:]


def test_max_np_caps_growth(tmp_path):
    """--max-np must cap the world when discovery grows past it, and
    the driver must NOT restart-thrash chasing uncappable slots
    (regression: _supervise compared raw discovered slots to the
    running world instead of the max_np-capped effective world)."""
    hosts_file, disc = _make_discovery(tmp_path, "localhost:2")
    proc = _launch(disc, min_np=2, max_np=2, epochs=8, sleep_s=0.3)
    state = {"grown": False}

    def on_line(line):
        if not state["grown"] and "EPOCH epoch=1 " in line:
            hosts_file.write_text("localhost:4\n")
            state["grown"] = True

    lines = _stream_until_exit(proc, on_line)
    grown = state["grown"]
    out = "\n".join(lines)
    assert proc.returncode == 0, out[-3000:]
    assert grown, out[-2000:]
    assert "DONE size=2 epoch=8" in out, out[-3000:]
    assert not any("size=3" in ln or "size=4" in ln for ln in lines), \
        out[-3000:]
    # no restart-thrash: the job must complete in ONE incarnation
    # (epoch sequence strictly increasing, no replay)
    epochs_seen = [
        int(ln.split("epoch=")[1].split()[0])
        for ln in lines if "EPOCH epoch=" in ln
    ]
    assert epochs_seen == sorted(epochs_seen), out[-3000:]


@pytest.mark.slow  # tier-1 runtime diet: heaviest in the --durations audit; full matrix via -m slow
def test_blacklist_after_three_strikes(tmp_path):
    """A host whose workers crash BLACKLIST_THRESHOLD times must be
    excluded from subsequent incarnations (parity: registration.py
    blacklist); the job then finishes on the surviving host."""
    _, disc = _make_discovery(tmp_path, "localhost:1\n127.0.0.1:1")
    marker = tmp_path / "strikes.txt"
    proc = _launch(
        disc,
        extra_env={
            "CRASH_MARKER": str(marker),
            "CRASH_RANK": "1",       # rank 1 lands on 127.0.0.1
            "CRASH_EPOCH": "2",
            "CRASH_COUNT": "3",
        },
        min_np=1, epochs=5,
    )
    out, _ = proc.communicate(timeout=240)
    assert proc.returncode == 0, out[-3000:]
    assert marker.exists() and marker.read_text().strip() == "3", \
        out[-3000:]
    # hosts are launched in sorted order (127.0.0.1 first), so rank 1
    # — the crasher — lands on "localhost"
    assert "blacklisting localhost" in out, out[-3000:]
    assert "launching 1 workers on 127.0.0.1:1" in out, out[-3000:]
    assert "DONE size=1 epoch=5" in out, out[-3000:]


_SHARDED_SCRIPT = os.path.join(_REPO, "tests", "elastic_sharded_script.py")


def test_elastic_resize_with_sharded_global_arrays(tmp_path):
    """The full pod resize-resume loop: workers hold GLOBAL
    world-sharded arrays (ShardedJaxState, 2 devices per worker);
    discovery grows 2 -> 3 workers mid-run, the driver relaunches, and
    the committed params reshard onto the LARGER global mesh (4 -> 6
    devices) with progress exactly preserved (w0 counts epochs run —
    any replay or loss shows up in the final value)."""
    hosts_file, disc = _make_discovery(tmp_path, "localhost:2")
    proc = _launch(disc, min_np=2, epochs=8, sleep_s=0.4,
                   cpu_devices=2, script=_SHARDED_SCRIPT)
    state = {"grown": False}

    def on_line(line):
        if not state["grown"] and "EPOCH epoch=2 " in line:
            hosts_file.write_text("localhost:3\n")
            state["grown"] = True

    lines = _stream_until_exit(proc, on_line)
    out = "\n".join(lines)
    assert proc.returncode == 0, out[-3000:]
    assert state["grown"], out[-2000:]
    assert any("size=2 ndev=4" in ln for ln in lines), out[-3000:]
    assert any("size=3 ndev=6" in ln for ln in lines), out[-3000:]
    # progress exactly preserved: w0 == epochs run, monotone epochs
    assert "DONE size=3 epoch=8 w0=8.0" in out, out[-3000:]
    epochs_seen = [int(ln.split("epoch=")[1].split()[0])
                   for ln in lines if "EPOCH epoch=" in ln]
    assert epochs_seen == sorted(epochs_seen), out[-3000:]


@pytest.mark.slow  # tier-1 runtime diet: heaviest in the --durations audit; full matrix via -m slow
def test_functional_run_elastic_api(tmp_path):
    """The function-mode elastic API (parity: horovod.spark.run_elastic):
    fn rides the signed pickle channel, runs under the elastic driver,
    and per-rank results come back — including across a mid-run crash
    recovered from the durable commit."""
    import horovod_tpu.spark as spark

    marker = str(tmp_path / "crash.marker")
    state_dir = str(tmp_path / "state")

    def train_body(epochs, marker):
        import os

        import jax.numpy as jnp

        import horovod_tpu as hvt
        import horovod_tpu.elastic as elastic

        hvt.init()
        state = elastic.ObjectState(epoch=0, total=0.0)

        @elastic.run
        def train(state):
            while state.epoch < epochs:
                state.total += float(
                    hvt.allreduce(jnp.ones(2), op=hvt.Sum)[0])
                state.epoch += 1
                state.commit()
                # one injected crash on rank 1 at epoch 2
                if (hvt.rank() == 1 and state.epoch == 2
                        and not os.path.exists(marker)):
                    open(marker, "w").write("x")
                    os._exit(1)
            return state.total

        total = train(state)
        hvt.shutdown()
        return (total, state.epoch)

    results = spark.run_elastic(
        train_body, args=(4, marker), num_proc=2, min_np=1,
        env={"HVTPU_ELASTIC_STATE_DIR": state_dir,
             "HVTPU_ELASTIC_DISCOVERY_INTERVAL": "0.2"})
    assert os.path.exists(marker)  # the crash actually happened
    # both ranks finish all 4 epochs; totals equal world-size sums
    # resumed from the commit, never replayed past it
    assert [e for _, e in results] == [4, 4]
    totals = [t for t, _ in results]
    assert totals[0] == totals[1] == 8.0, results
