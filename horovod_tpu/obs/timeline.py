"""Per-tensor lifecycle tracing to Chrome trace-event JSON.

Parity surface: ``horovod/common/timeline.cc`` (``Timeline``,
``TimelineController``) — enabled by ``HVTPU_TIMELINE=/path.json``
(or the reference spelling ``HOROVOD_TIMELINE``), loadable in
``chrome://tracing`` / Perfetto.  Phases mirror the reference's
per-tensor states (NEGOTIATE_* → QUEUE → MEMCPY_IN_FUSION_BUFFER →
<collective> → MEMCPY_OUT_FUSION_BUFFER), with TPU-native phase names
where the mechanism differs (e.g. ``ICI_ALLREDUCE`` instead of
``NCCL_ALLREDUCE``; ``TRACE``/``COMPILE`` for XLA compilation, which
has no reference analog).

For on-device detail (per-op HLO timing) ``start_jax_profiler`` wraps
``jax.profiler`` — the TPU analog of the reference's NVTX ranges
(horovod/common/nvtx_op_range.cc).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

# Reference-parity phase names (timeline.cc writes these as event names).
NEGOTIATE = "NEGOTIATE"
QUEUE = "QUEUE"
MEMCPY_IN_FUSION_BUFFER = "MEMCPY_IN_FUSION_BUFFER"
ICI_ALLREDUCE = "ICI_ALLREDUCE"
MEMCPY_OUT_FUSION_BUFFER = "MEMCPY_OUT_FUSION_BUFFER"
COMPILE = "COMPILE"
CYCLE = "CYCLE"


class Timeline:
    """Thread-safe incremental Chrome-trace writer.

    Events: ``begin(name, phase)`` / ``end(name)`` duration pairs on a
    per-tensor track, plus ``instant`` marks and ``mark_cycle`` (the
    reference's HOROVOD_TIMELINE_MARK_CYCLES).
    """

    def __init__(self, filename: str, rank: int = 0, mark_cycles: bool = False):
        self._filename = filename
        self._rank = rank
        self._mark_cycles = mark_cycles
        self._lock = threading.Lock()
        self._file = open(filename, "w")
        self._file.write("[\n")
        self._first = True
        # Monotonic epoch for event timestamps plus the wall-clock
        # reading taken at the same instant: merge tooling
        # (tools/hvtputrace) rebases per-rank relative timestamps onto
        # a shared wall clock via this anchor and the clock offsets.
        self._t0 = time.monotonic()
        self._wall_t0 = time.time()
        self._open_spans = {}
        self._closed = False
        self._emit(
            {
                "name": "process_name",
                "ph": "M",
                "pid": rank,
                "args": {"name": f"hvtpu rank {rank}"},
            }
        )

    @property
    def wall_t0(self) -> float:
        """time.time() captured at the trace's ts=0 instant."""
        return self._wall_t0

    @property
    def mark_cycles(self) -> bool:
        """Whether CYCLE instants are enabled (the controller consults
        this before paying for a mark_cycle call each cycle)."""
        return self._mark_cycles

    def _now_us(self) -> float:
        return (time.monotonic() - self._t0) * 1e6

    def _emit(self, event: dict):
        with self._lock:
            if self._closed:
                return
            if not self._first:
                self._file.write(",\n")
            self._first = False
            json.dump(event, self._file)
            self._file.flush()

    def begin(self, tensor_name: str, phase: str, **args):
        # A tensor entering its next phase before end() closes the
        # previous one (NEGOTIATE -> QUEUE -> ICI_ALLREDUCE) must end
        # that span first — silently overwriting the open-span entry
        # leaves an unmatched 'B' event in the trace.
        if tensor_name in self._open_spans:
            self.end(tensor_name)
        self._open_spans[tensor_name] = phase
        self._emit(
            {
                "name": phase,
                "cat": "tensor",
                "ph": "B",
                "ts": self._now_us(),
                "pid": self._rank,
                "tid": hash(tensor_name) % (1 << 31),
                "args": {"tensor": tensor_name, **args},
            }
        )

    def end(self, tensor_name: str):
        phase = self._open_spans.pop(tensor_name, None)
        if phase is None:
            return
        self._emit(
            {
                "name": phase,
                "cat": "tensor",
                "ph": "E",
                "ts": self._now_us(),
                "pid": self._rank,
                "tid": hash(tensor_name) % (1 << 31),
            }
        )

    def instant(self, name: str, **args):
        self._emit(
            {
                "name": name,
                "ph": "i",
                "s": "p",
                "ts": self._now_us(),
                "pid": self._rank,
                "tid": 0,
                "args": args,
            }
        )

    def mark_cycle(self, cycle_index: int):
        """Mark a controller cycle; no-op unless mark_cycles was enabled
        (parity: HOROVOD_TIMELINE_MARK_CYCLES)."""
        if self._mark_cycles:
            self.instant(CYCLE, index=cycle_index)

    def close(self):
        # End dangling spans first so the trace has no unmatched 'B'
        # events (e.g. ops still negotiating when the file is swapped
        # by start_timeline).
        for name in list(self._open_spans):
            self.end(name)
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._file.write("\n]\n")
            self._file.close()


# --- jax.profiler integration (NVTX analog) -----------------------------

_profiler_dir: Optional[str] = None


def start_jax_profiler(log_dir: str):
    """Start an on-device XLA trace (view in TensorBoard/Perfetto)."""
    global _profiler_dir
    import jax

    jax.profiler.start_trace(log_dir)
    _profiler_dir = log_dir


def stop_jax_profiler():
    global _profiler_dir
    import jax

    jax.profiler.stop_trace()
    _profiler_dir = None
