"""Generic bounded retry with exponential backoff + full jitter.

One policy engine for every "transiently unreachable" surface in the
stack, replacing ad-hoc loops:

- **coordination KV** (``ResilientKV``): the stall inspector's
  heartbeat reads/writes and ``obs/metrics.aggregate``'s snapshot
  exchange ride the JAX coordination service, whose gRPC channel can
  blip (coordinator restart, DCN hiccup, injected fault).  Before this
  module a single ``UNAVAILABLE`` turned into an instant
  ``HorovodInternalError``/hang; now it retries with backoff and only
  an exhausted budget surfaces.  Retries and exhaustions are counted in
  the metrics registry (``hvtpu_kv_retries_total``,
  ``hvtpu_kv_retry_exhausted_total``).
- **gloo teardown races** (``GLOO_TEARDOWN``): jaxlib's gloo CPU
  transport occasionally drops a connection under parallel localhost
  load (a rank SIGSEGVs; peers report "Connection closed by peer").
  That race lives below this framework; the bounded retry the tests
  carried inline is now this named policy, reused from
  ``tests/test_multiprocess.py`` and ``tests/test_launch_cli.py``.

Backoff follows the AWS "full jitter" scheme: sleep is uniform in
``[0, min(max_delay, base * 2**attempt)]`` — decorrelated retries so P
ranks hammering a recovering coordinator don't re-collide in lockstep.

Env knobs (docs/robustness.md):

- ``HVTPU_KV_RETRY_ATTEMPTS``   (default 4)  total attempts per KV op
- ``HVTPU_KV_RETRY_BASE_MS``    (default 50) first-retry backoff cap
- ``HVTPU_KV_RETRY_MAX_MS``     (default 2000) per-sleep cap
- ``HVTPU_KV_RETRY_DEADLINE_S`` (default 30) wall-clock budget per op
"""

from __future__ import annotations

import dataclasses
import os
import random
from typing import Any, Callable, Optional, Tuple

from ..obs import flight
from ..obs import metrics as obs_metrics
from . import clock
from . import faults

_M_KV_RETRIES = obs_metrics.counter(
    "hvtpu_kv_retries_total",
    "Coordination-KV operations retried after a transient failure.")
_M_KV_EXHAUSTED = obs_metrics.counter(
    "hvtpu_kv_retry_exhausted_total",
    "Coordination-KV operations that failed even after exhausting the "
    "retry budget (the error then surfaces to the caller).")


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Immutable retry schedule + classification.

    ``retryable`` classifies exceptions; ``retry_result`` (optional)
    classifies RETURN VALUES that should be retried (subprocess results
    carrying an infra-crash signature, say).  ``max_attempts`` counts
    total attempts including the first; ``deadline_s`` bounds the whole
    call in wall-clock time.  ``base_delay_s`` of 0 retries immediately
    (the gloo policy: the race is gone on re-run, waiting buys nothing).
    """

    name: str
    max_attempts: int = 3
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    deadline_s: Optional[float] = None
    retryable: Callable[[BaseException], bool] = lambda e: True
    retry_result: Optional[Callable[[Any], bool]] = None

    def backoff_s(self, attempt: int, rng: random.Random) -> float:
        """Full-jitter sleep before retry ``attempt`` (1-based)."""
        if self.base_delay_s <= 0:
            return 0.0
        cap = min(self.max_delay_s,
                  self.base_delay_s * (2.0 ** (attempt - 1)))
        return rng.uniform(0.0, cap)


class RetryExhausted(Exception):
    """Raised only for result-based exhaustion when the caller asked
    for it; exception-based exhaustion re-raises the original error so
    existing ``except`` clauses keep matching."""


def call(policy: RetryPolicy, fn: Callable, *args,
         on_retry: Optional[Callable[[int, Optional[BaseException]],
                                     None]] = None,
         rng: Optional[random.Random] = None, **kwargs):
    """Run ``fn(*args, **kwargs)`` under ``policy``.

    On a retryable exception: sleep (full jitter) and re-attempt until
    ``max_attempts`` or ``deadline_s`` runs out, then re-raise the
    LAST exception (no wrapper type — callers' handlers keep working).
    With ``retry_result``, a True-classified return value is retried
    the same way and the final value is returned once the budget is
    spent.  ``on_retry(attempt, exc_or_None)`` fires before each sleep.
    """
    rng = rng or random.Random()
    start = clock.monotonic()
    attempt = 0
    while True:
        attempt += 1
        try:
            result = fn(*args, **kwargs)
        except Exception as e:
            budget_left = (
                attempt < policy.max_attempts
                and (policy.deadline_s is None
                     or clock.monotonic() - start < policy.deadline_s))
            if not policy.retryable(e) or not budget_left:
                raise
            if on_retry is not None:
                on_retry(attempt, e)
            clock.sleep(policy.backoff_s(attempt, rng))
            continue
        if (policy.retry_result is not None
                and policy.retry_result(result)
                and attempt < policy.max_attempts
                and (policy.deadline_s is None
                     or clock.monotonic() - start < policy.deadline_s)):
            if on_retry is not None:
                on_retry(attempt, None)
            clock.sleep(policy.backoff_s(attempt, rng))
            continue
        return result


def retrying(policy: RetryPolicy):
    """Decorator form of :func:`call`."""
    def deco(fn):
        import functools

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            return call(policy, fn, *args, **kwargs)
        return wrapped
    return deco


# ---------------------------------------------------------------------------
# named policies
# ---------------------------------------------------------------------------

# Transient coordination-service failure signatures (grpc status names
# + socket-level shapes).  NOT_FOUND is deliberately absent: a missing
# key is a legitimate answer for try_get, not a failure to retry.
_KV_TRANSIENT_MARKERS = (
    "UNAVAILABLE", "DEADLINE_EXCEEDED", "RESOURCE_EXHAUSTED",
    "failed to connect", "Connection reset", "connection reset",
    "Broken pipe", "Socket closed", "coordination service",
)


def kv_retryable(e: BaseException) -> bool:
    if isinstance(e, TimeoutError):
        return True
    msg = str(e)
    return any(m in msg for m in _KV_TRANSIENT_MARKERS)


def kv_blocking_retryable(e: BaseException) -> bool:
    """Blocking-get variant: a NOT_FOUND/timeout just means the peer
    hasn't posted yet — poll again until the caller's deadline."""
    return kv_retryable(e) or "NOT_FOUND" in str(e)


def kv_policy(deadline_s: Optional[float] = None) -> RetryPolicy:
    """The coordination-KV policy, env-tunable (module docstring)."""
    return RetryPolicy(
        name="kv",
        max_attempts=int(os.environ.get("HVTPU_KV_RETRY_ATTEMPTS", "4")),
        base_delay_s=float(
            os.environ.get("HVTPU_KV_RETRY_BASE_MS", "50")) / 1000.0,
        max_delay_s=float(
            os.environ.get("HVTPU_KV_RETRY_MAX_MS", "2000")) / 1000.0,
        deadline_s=(float(os.environ.get("HVTPU_KV_RETRY_DEADLINE_S",
                                         "30"))
                    if deadline_s is None else deadline_s),
        retryable=kv_retryable,
    )


#: jaxlib/gloo CPU-transport teardown-race signatures (a rank SIGSEGVs
#: mid-collective; peers see the torn socket).  Shared by the policy
#: below and the test-suite launch retries.
GLOO_INFRA_MARKERS: Tuple[str, ...] = (
    "Connection closed by peer", "Socket closed",
    "collective transport failure", "connection reset by peer",
)


def is_gloo_infra_error(text: str) -> bool:
    """True when ``text`` (an exception string or a process's combined
    output) carries a gloo teardown-race signature rather than a
    framework failure."""
    return any(m in text for m in GLOO_INFRA_MARKERS)


def gloo_teardown_policy(max_attempts: int = 5,
                         retry_result: Optional[Callable[[Any], bool]]
                         = None) -> RetryPolicy:
    """Bounded relaunch for the gloo CPU teardown race: immediate
    re-run (the race is load-timing, not state), exception-classified
    by :func:`is_gloo_infra_error`; pass ``retry_result`` to also
    classify completed-subprocess results (rc + output blob)."""
    return RetryPolicy(
        name="gloo-teardown",
        max_attempts=max_attempts,
        base_delay_s=0.0,
        retryable=lambda e: is_gloo_infra_error(str(e)),
        retry_result=retry_result,
    )


GLOO_TEARDOWN = gloo_teardown_policy()


# ---------------------------------------------------------------------------
# resilient coordination-KV wrapper
# ---------------------------------------------------------------------------


class ResilientKV:
    """Coordination-service client wrapper: fault injection (sites
    ``kv.get`` / ``kv.put``) + bounded retry with backoff on transient
    failures, counting into the metrics registry.

    Dropped-op semantics (the ``drop`` fault action): a dropped read is
    a miss (``KeyError`` for try_get — the same "no such key" contract
    the raw client's error has, which every caller already treats as
    absent; ``[]`` for dir_get; ``TimeoutError`` for blocking_get), a
    dropped write/delete silently does nothing.  ``blocking_key_value_get``
    is NOT retried here — its callers own a deadline loop already.

    Attributes the wrapped client lacks stay missing (``key_value_dir_get``
    presence is how comm/stall.py picks amortized vs strict mode), and
    unknown attributes delegate, so the wrapper is drop-in.
    """

    def __init__(self, client, rank: int = 0,
                 policy: Optional[RetryPolicy] = None):
        self._kv = client
        self._rank = rank
        self._policy = policy or kv_policy()
        self._rng = random.Random(0x6B76 + rank)
        if hasattr(client, "key_value_dir_get"):
            # instance attribute, so ``getattr(kv, "key_value_dir_get",
            # None)`` stays None for clients without a dir get
            self.key_value_dir_get = self._dir_get

    def _on_retry(self, attempt: int, exc) -> None:
        _M_KV_RETRIES.inc()
        if flight.ACTIVE:
            flight.note("kv_retry", rank=self._rank, attempt=attempt,
                        error=type(exc).__name__)

    def _call(self, fn, *args):
        try:
            return call(self._policy, fn, *args,
                        on_retry=self._on_retry, rng=self._rng)
        except Exception as e:
            if kv_retryable(e):
                _M_KV_EXHAUSTED.inc()
                if flight.ACTIVE:
                    flight.note("kv_retry_exhausted", rank=self._rank,
                                error=str(e)[:200])
            raise

    # Fault injection happens INSIDE the retried closures below, so an
    # ``error``-injected op (whose message carries UNAVAILABLE) is
    # retried exactly like a real coordinator blip — and heals once the
    # clause's budget is spent.  ``drop`` never raises, so it is never
    # retried: a dropped write stays dropped.

    # -- mutations (site kv.put) ---------------------------------------
    def key_value_set(self, key: str, value: str):
        def _put():
            if faults.ACTIVE and faults.inject("kv.put", detail=key):
                return None
            return self._kv.key_value_set(key, value)

        return self._call(_put)

    def key_value_delete(self, key: str):
        if faults.ACTIVE and faults.inject("kv.put", detail=key):
            return None
        # best-effort by contract (callers swallow failures); one shot
        return self._kv.key_value_delete(key)

    # -- reads (site kv.get) -------------------------------------------
    def key_value_try_get(self, key: str):
        def _get():
            if faults.ACTIVE and faults.inject("kv.get", detail=key):
                raise KeyError(f"{key} (dropped by fault injection)")
            return self._kv.key_value_try_get(key)

        return self._call(_get)

    def _dir_get(self, prefix: str):
        def _get():
            if faults.ACTIVE and faults.inject("kv.get", detail=prefix):
                return []
            return self._kv.key_value_dir_get(prefix)

        return self._call(_get)

    def blocking_key_value_get(self, key: str, timeout_ms: int):
        if faults.ACTIVE and faults.inject("kv.get", detail=key):
            raise TimeoutError(f"{key} (dropped by fault injection)")
        return self._kv.blocking_key_value_get(key, timeout_ms)

    def __getattr__(self, name):
        return getattr(self._kv, name)


def resilient_kv(client, rank: int = 0,
                 policy: Optional[RetryPolicy] = None):
    """Wrap ``client`` (idempotently) in :class:`ResilientKV`."""
    if client is None or isinstance(client, ResilientKV):
        return client
    return ResilientKV(client, rank=rank, policy=policy)


# ---------------------------------------------------------------------------
# generation-fenced coordination-KV wrapper
# ---------------------------------------------------------------------------

#: Distinct worker exit status for "this rank fenced itself": its
#: generation was superseded (a newer incarnation owns the keyspace) or
#: its KV lease expired (it could not prove liveness to the
#: coordination plane).  The elastic driver classifies this separately
#: from a crash — a fenced rank did the RIGHT thing by dying, so it
#: costs no restart-budget strike and no host-blacklist strike
#: (docs/robustness.md exit-code table).
FENCE_EXIT_CODE = 89

_M_FENCED_WRITES = obs_metrics.counter(
    "hvtpu_kv_fenced_writes_total",
    "Stale KV values from fenced (superseded-generation) writers "
    "rejected by readers.")
_M_FENCE_EXITS = obs_metrics.counter(
    "hvtpu_fence_exits_total",
    "Ranks that self-fenced (generation superseded or KV lease "
    "expired) and exited with FENCE_EXIT_CODE.")

#: Fencing-token stamp framing: ``\\x1fF<epoch>.<generation>\\x1f<payload>``.
#: \\x1f (ASCII unit separator) never occurs in the JSON/ascii payloads
#: the control protocols exchange, so unstamped values pass through
#: untouched and stamped ones are unambiguous.
_STAMP_LEAD = "\x1fF"
_STAMP_SEP = "\x1f"

#: Raw beacon key carrying the highest fencing token any writer has
#: advertised.  Deliberately OUTSIDE every protocol namespace and never
#: itself stamped: its value IS a token.
FENCE_BEACON_KEY = "hvtfence/beacon"


def _parse_token(text) -> Optional[Tuple[int, int]]:
    """``"epoch.generation"`` -> (epoch, generation), else None."""
    if not isinstance(text, str):
        return None
    epoch, _, gen = text.partition(".")
    try:
        return int(epoch), int(gen)
    except ValueError:
        return None


def unstamp(value):
    """Split a possibly-stamped KV value into ``(token, payload)``.

    ``token`` is ``(job_epoch, generation)`` or None for unstamped
    values (pre-fencing writers, non-string payloads).  Total: never
    raises — malformed stamps are treated as unstamped payloads.
    Readers outside the fenced seams (e.g. the fleet arbiter's health
    poll) call this to stay stamp-tolerant.
    """
    if not isinstance(value, str) or not value.startswith(_STAMP_LEAD):
        return None, value
    end = value.find(_STAMP_SEP, len(_STAMP_LEAD))
    if end < 0:
        return None, value
    token = _parse_token(value[len(_STAMP_LEAD):end])
    if token is None:
        return None, value
    return token, value[end + len(_STAMP_SEP):]


class FencedError(RuntimeError):
    """Raised by a fenced :class:`FencedKV` whose ``exit_fn`` returned
    (tests, sim ranks): no operation may proceed past a fence."""


class FencedKV(ResilientKV):
    """Generation-fenced :class:`ResilientKV`: every write is stamped
    with this writer's ``(job_epoch, generation)`` fencing token, and
    reads reject values stamped by a SUPERSEDED token — closing the
    split-brain window where a rank that exhausted its KV retries (or
    thawed after a partition) keeps writing stale heartbeats, drain
    plans, or quorum votes into the live keyspace.

    Three fence triggers, all terminal for this rank:

    - **supersession observed on read**: a value or the beacon key
      carries a HIGHER token than ours — a newer incarnation owns the
      keyspace, we are the zombie;
    - **lease expiry**: ``lease_s > 0`` and no KV operation has
      actually reached the server for longer than the lease — we
      cannot prove liveness, so we must assume we were given up on
      (peers hold a ``partition_suspect`` grace first: comm/stall.py);
    - **explicit** :meth:`fence` from the owner (tests, drain logic).

    Fencing exits via ``exit_fn`` (default ``os._exit``) with
    :data:`FENCE_EXIT_CODE`; if ``exit_fn`` returns (unit tests, sim
    virtual ranks whose exit_fn raises), every subsequent operation
    raises :class:`FencedError` so a fenced client can never write.

    Equal tokens — the only case in a healthy single-generation job —
    cost one string startswith per read and one prefix concat per
    write.  ``HVTPU_KV_FENCE_DISABLE=1`` removes even that (the
    factory returns a plain ResilientKV).
    """

    def __init__(self, client, rank: int = 0,
                 policy: Optional[RetryPolicy] = None, *,
                 job_epoch: Optional[int] = None,
                 generation: Optional[int] = None,
                 lease_s: Optional[float] = None,
                 check_every: Optional[int] = None,
                 exit_fn=None, journal=None):
        super().__init__(client, rank=rank, policy=policy)
        if job_epoch is None:
            job_epoch = int(os.environ.get("HVTPU_JOB_EPOCH", "0") or 0)
        if generation is None:
            generation = int(
                os.environ.get("HVTPU_ELASTIC_GENERATION", "0") or 0)
        if lease_s is None:
            lease_s = float(os.environ.get("HVTPU_KV_LEASE_S", "0") or 0)
        if check_every is None:
            check_every = int(
                os.environ.get("HVTPU_KV_FENCE_CHECK_EVERY", "32") or 32)
        self._token: Tuple[int, int] = (job_epoch, generation)
        self._lease_s = lease_s
        self._check_every = max(1, check_every)
        self._exit_fn = exit_fn
        self._journal = journal
        self._journal_prefixes: Tuple[str, ...] = ()
        self._fenced = False
        self._fence_reason = ""
        # "last proven reachable": bumped on every op that the server
        # actually answered (including NOT_FOUND — an answer).  seq
        # disambiguates refresh-vs-not without clock comparisons.
        self._lease_ok = clock.monotonic()
        self._lease_seq = 0
        self._ops_since_check = 0
        self._recheck = False
        self._advertise()

    # -- token plumbing -------------------------------------------------
    @property
    def token(self) -> Tuple[int, int]:
        return self._token

    def token_str(self) -> str:
        return f"{self._token[0]}.{self._token[1]}"

    def _stamp(self, value):
        if not isinstance(value, str):
            return value
        return f"{_STAMP_LEAD}{self.token_str()}{_STAMP_SEP}{value}"

    # -- journal of self-authored durable keys --------------------------
    def add_journal_prefix(self, prefix: str) -> None:
        """Record future writes under ``prefix`` in this rank's key
        journal (core/journal.py) for replay into a fresh KV after
        coordinator loss."""
        if prefix not in self._journal_prefixes:
            self._journal_prefixes = self._journal_prefixes + (prefix,)

    def _journal_write(self, key: str, value) -> None:
        if self._journal is None or not isinstance(value, str):
            return
        if any(key.startswith(p) for p in self._journal_prefixes):
            self._journal.record(key, value)

    # -- fencing --------------------------------------------------------
    def fence(self, reason: str):
        """Terminal: this rank may no longer touch the keyspace."""
        if not self._fenced:
            self._fenced = True
            self._fence_reason = reason
            _M_FENCE_EXITS.inc()
            if flight.ACTIVE:
                flight.note("fence_exit", rank=self._rank,
                            token=self.token_str(), reason=reason)
            flight.dump_postmortem("fenced", rank=self._rank,
                                   token=self.token_str(),
                                   detail=reason)
            import sys

            print(f"hvtpu fence: rank {self._rank} token "
                  f"{self.token_str()} self-fencing ({reason}); "
                  f"exiting {FENCE_EXIT_CODE}",
                  file=sys.stderr, flush=True)
            if self._exit_fn is not None:
                self._exit_fn(FENCE_EXIT_CODE)
            else:
                os._exit(FENCE_EXIT_CODE)
        # exit_fn returned (unit test / already-exiting sim rank):
        # refuse the operation that discovered the fence.
        raise FencedError(
            f"KV client fenced ({self._fence_reason}): rank {self._rank} "
            f"token {self.token_str()}")

    def _observe(self, token: Optional[Tuple[int, int]]) -> bool:
        """Classify a read value's token: True means REJECT the value
        (stale writer); a newer token fences US."""
        if token is None or token == self._token:
            return False
        if token > self._token:
            self.fence(f"generation superseded (saw token "
                       f"{token[0]}.{token[1]})")
        _M_FENCED_WRITES.inc()
        if flight.ACTIVE:
            flight.note("fenced_write_rejected", rank=self._rank,
                        stale=f"{token[0]}.{token[1]}",
                        token=self.token_str())
        return True

    # -- lease ----------------------------------------------------------
    def _touch_lease(self) -> None:
        self._lease_ok = clock.monotonic()
        self._lease_seq += 1

    def lease_remaining(self) -> float:
        """Seconds until the lease expires (inf with no lease armed)."""
        if self._lease_s <= 0:
            return float("inf")
        return self._lease_s - (clock.monotonic() - self._lease_ok)

    def _lease_check(self) -> None:
        if self._lease_s <= 0:
            return
        age = clock.monotonic() - self._lease_ok
        if age > self._lease_s:
            self.fence(f"kv lease expired (unreachable {age:.3f}s > "
                       f"lease {self._lease_s:.3f}s)")

    # -- beacon ---------------------------------------------------------
    def _raw_beacon_get(self):
        # through fault injection (a partitioned rank cannot read the
        # beacon) but NOT through retry: the beacon is advisory.
        if faults.ACTIVE and faults.inject("kv.get",
                                           detail=FENCE_BEACON_KEY):
            raise KeyError(FENCE_BEACON_KEY)
        return self._kv.key_value_try_get(FENCE_BEACON_KEY)

    def _check_beacon(self) -> None:
        try:
            seen = _parse_token(self._raw_beacon_get())
        except KeyError:
            # NOT_FOUND: the server answered "no beacon yet" — claim
            # it.  (A partition-dropped read lands here too; the
            # publish below is then dropped the same way, harmlessly.)
            seen = None
        except Exception:
            return
        if seen is None:
            self._publish_beacon()
        elif seen > self._token:
            self.fence(f"generation superseded (beacon "
                       f"{seen[0]}.{seen[1]})")
        elif seen < self._token:
            self._publish_beacon()

    def _publish_beacon(self) -> None:
        try:
            if faults.ACTIVE and faults.inject("kv.put",
                                               detail=FENCE_BEACON_KEY):
                return
            self._kv.key_value_set(FENCE_BEACON_KEY, self.token_str())
        except Exception:
            pass

    def _advertise(self) -> None:
        """Init-time beacon handshake: fence immediately if a newer
        incarnation already advertised, else advertise ourselves."""
        self._check_beacon()

    # -- op shells -------------------------------------------------------
    def _pre_op(self) -> None:
        if self._fenced:
            raise FencedError(
                f"KV client fenced ({self._fence_reason}): rank "
                f"{self._rank} token {self.token_str()}")
        self._ops_since_check += 1
        if self._recheck or self._ops_since_check >= self._check_every:
            self._ops_since_check = 0
            self._recheck = False
            self._check_beacon()

    def _guarded(self, fn):
        """Run one retried op; when it never reached the server
        (dropped by a partition window / transport failure), evaluate
        the lease and schedule a beacon re-check for the next op (a
        thawed zombie fences BEFORE its first post-thaw write)."""
        before = self._lease_seq
        try:
            return self._call(fn)
        finally:
            if self._lease_seq == before:
                self._recheck = True
                self._lease_check()

    # -- mutations (site kv.put) ----------------------------------------
    def key_value_set(self, key: str, value: str):
        self._pre_op()
        stamped = self._stamp(value)
        self._journal_write(key, value)

        def _put():
            if faults.ACTIVE and faults.inject("kv.put", detail=key):
                return None
            r = self._kv.key_value_set(key, stamped)
            self._touch_lease()
            return r

        return self._guarded(_put)

    def key_value_delete(self, key: str):
        self._pre_op()
        if self._journal is not None:
            self._journal.forget(key)
        if faults.ACTIVE and faults.inject("kv.put", detail=key):
            return None
        r = self._kv.key_value_delete(key)
        self._touch_lease()
        return r

    # -- reads (site kv.get) --------------------------------------------
    def key_value_try_get(self, key: str):
        self._pre_op()

        def _get():
            if faults.ACTIVE and faults.inject("kv.get", detail=key):
                raise KeyError(f"{key} (dropped by fault injection)")
            try:
                r = self._kv.key_value_try_get(key)
            except Exception as e:
                if not kv_retryable(e):
                    self._touch_lease()  # NOT_FOUND is an answer
                raise
            self._touch_lease()
            return r

        raw = self._guarded(_get)
        token, payload = unstamp(raw)
        if self._observe(token):
            raise KeyError(f"{key} (fenced stale write rejected)")
        return payload

    def _dir_get(self, prefix: str):
        self._pre_op()

        def _get():
            if faults.ACTIVE and faults.inject("kv.get", detail=prefix):
                return None
            r = self._kv.key_value_dir_get(prefix)
            self._touch_lease()
            return r

        raw = self._guarded(_get)
        if raw is None:  # dropped
            return []
        out = []
        for k, v in raw:
            token, payload = unstamp(v)
            if self._observe(token):
                continue  # stale entry: invisible, like a miss
            out.append((k, payload))
        return out

    def blocking_key_value_get(self, key: str, timeout_ms: int):
        self._pre_op()
        if faults.ACTIVE and faults.inject("kv.get", detail=key):
            self._recheck = True
            self._lease_check()
            raise TimeoutError(f"{key} (dropped by fault injection)")
        try:
            raw = self._kv.blocking_key_value_get(key, timeout_ms)
        except Exception as e:
            if kv_retryable(e):
                self._recheck = True
                self._lease_check()
            else:
                self._touch_lease()
            raise
        self._touch_lease()
        token, payload = unstamp(raw)
        if self._observe(token):
            raise TimeoutError(f"{key} (fenced stale write rejected)")
        return payload


def fenced_kv(client, rank: int = 0,
              policy: Optional[RetryPolicy] = None, **kwargs):
    """Wrap ``client`` (idempotently) in :class:`FencedKV`.

    A plain :class:`ResilientKV` is re-wrapped around its inner client
    (fencing subsumes resilience); ``HVTPU_KV_FENCE_DISABLE=1`` falls
    back to :func:`resilient_kv` for bisection/escape-hatch use.
    """
    if client is None or isinstance(client, FencedKV):
        return client
    if os.environ.get("HVTPU_KV_FENCE_DISABLE", "").lower() in (
            "1", "true", "on"):
        return resilient_kv(client, rank=rank, policy=policy)
    if isinstance(client, ResilientKV):
        client = client._kv
    return FencedKV(client, rank=rank, policy=policy, **kwargs)
