"""SPMD collective correctness matrix.

The analog of the reference's test/parallel/test_torch.py op × dtype ×
path matrix (SURVEY.md §4): test bodies are rank-oblivious shard_map
functions run over an 8-device mesh — the TPU-native equivalent of
"every rank runs the same asserts under horovodrun -np 8".
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.comm import Compression, ReduceOp, spmd
from horovod_tpu.comm.adasum import adasum_reduce_reference

AXIS = "world"


def mesh8():
    return Mesh(np.asarray(jax.devices(), dtype=object), (AXIS,))


def run_spmd(body, args, in_specs, out_specs):
    return jax.jit(
        jax.shard_map(
            body, mesh=mesh8(), in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    )(*args)


DTYPES = [jnp.float32, jnp.bfloat16, jnp.float16, jnp.int32]


class TestAllreduce:
    @pytest.mark.parametrize("dtype", DTYPES)
    def test_sum(self, dtype):
        x = jnp.arange(8 * 4, dtype=dtype).reshape(8, 4)

        def body(s):
            return spmd.allreduce(s[0], axis_name=AXIS, op=ReduceOp.SUM)[None]

        out = run_spmd(body, (x,), (P(AXIS),), P(AXIS))
        expect = np.asarray(x, np.float64).sum(0)
        np.testing.assert_allclose(
            np.asarray(out[3], np.float64), expect, rtol=1e-2
        )

    def test_average(self):
        x = jnp.arange(8.0).reshape(8, 1)

        def body(s):
            return spmd.allreduce(s[0], axis_name=AXIS, op=ReduceOp.AVERAGE)[None]

        out = run_spmd(body, (x,), (P(AXIS),), P(AXIS))
        np.testing.assert_allclose(np.asarray(out).ravel(), [3.5] * 8)

    def test_average_int_floordiv(self):
        x = jnp.arange(8, dtype=jnp.int32).reshape(8, 1)

        def body(s):
            return spmd.allreduce(s[0], axis_name=AXIS, op=ReduceOp.AVERAGE)[None]

        out = run_spmd(body, (x,), (P(AXIS),), P(AXIS))
        assert np.asarray(out).ravel().tolist() == [28 // 8] * 8

    @pytest.mark.parametrize("op,npop", [
        (ReduceOp.MIN, np.min), (ReduceOp.MAX, np.max),
        (ReduceOp.PRODUCT, np.prod),
    ])
    def test_min_max_product(self, op, npop):
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.uniform(0.5, 1.5, (8, 3)).astype(np.float32))

        def body(s):
            return spmd.allreduce(s[0], axis_name=AXIS, op=op)[None]

        out = run_spmd(body, (x,), (P(AXIS),), P(AXIS))
        np.testing.assert_allclose(
            np.asarray(out[0]), npop(np.asarray(x), axis=0), rtol=1e-5
        )

    def test_prescale_postscale(self):
        x = jnp.ones((8, 2))

        def body(s):
            return spmd.allreduce(
                s[0], axis_name=AXIS, op=ReduceOp.SUM,
                prescale_factor=0.5, postscale_factor=3.0,
            )[None]

        out = run_spmd(body, (x,), (P(AXIS),), P(AXIS))
        np.testing.assert_allclose(np.asarray(out[0]), np.full((2,), 12.0))

    def test_legacy_average_kwarg(self):
        x = jnp.arange(8.0).reshape(8, 1)

        def body(s):
            return spmd.allreduce(s[0], axis_name=AXIS, average=False)[None]

        out = run_spmd(body, (x,), (P(AXIS),), P(AXIS))
        np.testing.assert_allclose(np.asarray(out).ravel(), [28.0] * 8)

    @pytest.mark.parametrize("comp", [Compression.fp16, Compression.bf16])
    def test_compressed_wire(self, comp):
        rng = np.random.RandomState(2)
        x = jnp.asarray(rng.randn(8, 16).astype(np.float32))

        def body(s):
            return spmd.allreduce(
                s[0], axis_name=AXIS, op=ReduceOp.SUM, compression=comp
            )[None]

        out = run_spmd(body, (x,), (P(AXIS),), P(AXIS))
        assert out.dtype == jnp.float32  # decompressed back
        np.testing.assert_allclose(
            np.asarray(out[0]), np.asarray(x).sum(0), rtol=5e-2, atol=5e-2
        )

    def test_explicit_groups(self):
        # Two groups of 4: the analog of a process-set-scoped allreduce.
        x = jnp.arange(8.0).reshape(8, 1)
        groups = [[0, 1, 2, 3], [4, 5, 6, 7]]

        def body(s):
            return spmd.allreduce(
                s[0], axis_name=AXIS, op=ReduceOp.SUM, groups=groups
            )[None]

        out = np.asarray(run_spmd(body, (x,), (P(AXIS),), P(AXIS))).ravel()
        np.testing.assert_allclose(out[:4], [6.0] * 4)
        np.testing.assert_allclose(out[4:], [22.0] * 4)


class TestGroupedAllreduce:
    def test_matches_individual(self):
        rng = np.random.RandomState(3)
        a = jnp.asarray(rng.randn(8, 3).astype(np.float32))
        b = jnp.asarray(rng.randn(8, 5, 2).astype(np.float32))

        def body(sa, sb):
            ra, rb = spmd.grouped_allreduce(
                [sa[0], sb[0]], axis_name=AXIS, op=ReduceOp.AVERAGE
            )
            return ra[None], rb[None]

        oa, ob = run_spmd(
            body, (a, b), (P(AXIS), P(AXIS)), (P(AXIS), P(AXIS))
        )
        np.testing.assert_allclose(
            np.asarray(oa[0]), np.asarray(a).mean(0), rtol=1e-5, atol=1e-6
        )
        np.testing.assert_allclose(
            np.asarray(ob[0]), np.asarray(b).mean(0), rtol=1e-5, atol=1e-6
        )


class TestAllgather:
    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.int32])
    def test_gather_dim0(self, dtype):
        x = jnp.arange(8 * 2 * 3, dtype=dtype).reshape(8, 2, 3)

        def body(s):
            return spmd.allgather(s[0], axis_name=AXIS)[None]

        out = run_spmd(body, (x,), (P(AXIS),), P(AXIS))
        # every participant sees the full concatenation
        np.testing.assert_array_equal(
            np.asarray(out[0]), np.asarray(x).reshape(16, 3)
        )


class TestBroadcast:
    @pytest.mark.parametrize("root", [0, 3, 7])
    def test_root(self, root):
        x = jnp.arange(8.0).reshape(8, 1) + 1.0

        def body(s):
            return spmd.broadcast(s[0], root_rank=root, axis_name=AXIS)[None]

        out = run_spmd(body, (x,), (P(AXIS),), P(AXIS))
        np.testing.assert_allclose(np.asarray(out).ravel(), [root + 1.0] * 8)

    def test_bool(self):
        x = jnp.asarray([i == 2 for i in range(8)]).reshape(8, 1)

        def body(s):
            return spmd.broadcast(s[0], root_rank=2, axis_name=AXIS)[None]

        out = run_spmd(body, (x,), (P(AXIS),), P(AXIS))
        assert out.dtype == jnp.bool_
        assert np.asarray(out).all()


class TestAlltoall:
    def test_exchange(self):
        # participant i sends row j of its shard to participant j
        x = jnp.arange(8 * 8, dtype=jnp.float32).reshape(8, 8, 1)

        def body(s):
            return spmd.alltoall(s[0], axis_name=AXIS)[None]

        out = run_spmd(body, (x,), (P(AXIS),), P(AXIS))
        full = np.asarray(x)[..., 0]
        got = np.asarray(out)[..., 0]
        np.testing.assert_array_equal(got, full.T)

    def test_indivisible_raises(self):
        x = jnp.ones((8, 7))
        with pytest.raises(ValueError):
            def body(s):
                return spmd.alltoall(s[0], axis_name=AXIS)[None]
            run_spmd(body, (x,), (P(AXIS),), P(AXIS))


class TestReducescatter:
    def test_sum(self):
        x = jnp.ones((8, 2))

        def body(s):
            # replicated input: every participant holds the full (8,2)
            return spmd.reducescatter(s, axis_name=AXIS, op=ReduceOp.SUM)

        out = run_spmd(body, (x,), (P(None),), P(AXIS))
        np.testing.assert_allclose(np.asarray(out), np.full((8, 2), 8.0))

    def test_average(self):
        x = jnp.full((8, 2), 4.0)

        def body(s):
            return spmd.reducescatter(s, axis_name=AXIS, op=ReduceOp.AVERAGE)

        out = run_spmd(body, (x,), (P(None),), P(AXIS))
        np.testing.assert_allclose(np.asarray(out), np.full((8, 2), 4.0))


class TestAdasum:
    def test_matches_reference_recursion(self):
        rng = np.random.RandomState(7)
        vecs = rng.randn(8, 16).astype(np.float32)

        def body(s):
            return spmd.allreduce(s[0], axis_name=AXIS, op=ReduceOp.ADASUM)[None]

        out = run_spmd(body, (jnp.asarray(vecs),), (P(AXIS),), P(AXIS))
        ref = adasum_reduce_reference([vecs[i] for i in range(8)])
        np.testing.assert_allclose(np.asarray(out[0]), ref, rtol=1e-3, atol=1e-4)

    def test_orthogonal_sums(self):
        # Adasum of orthogonal gradients reduces to their sum.
        vecs = np.zeros((8, 8), np.float32)
        for i in range(8):
            vecs[i, i] = 2.0

        def body(s):
            return spmd.allreduce(s[0], axis_name=AXIS, op=ReduceOp.ADASUM)[None]

        out = run_spmd(body, (jnp.asarray(vecs),), (P(AXIS),), P(AXIS))
        np.testing.assert_allclose(
            np.asarray(out[0]), np.full((8,), 2.0), rtol=1e-5
        )

    def test_identical_inputs_stay_put(self):
        # Adasum of n identical gradients returns that gradient
        # (scale-invariance: parallel components are averaged).
        vecs = np.tile(np.arange(1, 5, dtype=np.float32), (8, 1))

        def body(s):
            return spmd.allreduce(s[0], axis_name=AXIS, op=ReduceOp.ADASUM)[None]

        out = run_spmd(body, (jnp.asarray(vecs),), (P(AXIS),), P(AXIS))
        np.testing.assert_allclose(
            np.asarray(out[0]), vecs[0], rtol=1e-4
        )


class TestRankSize:
    def test_axis_introspection(self):
        def body(x):
            r = spmd.rank(AXIS)
            n = spmd.axis_size(AXIS)
            return (x[0] * 0 + r)[None], (x[0] * 0 + n)[None]

        x = jnp.zeros((8, 1), jnp.int32)
        ranks, sizes = run_spmd(body, (x,), (P(AXIS),), (P(AXIS), P(AXIS)))
        assert np.asarray(ranks).ravel().tolist() == list(range(8))
        assert np.asarray(sizes).ravel().tolist() == [8] * 8
