"""Cross-rank integrity layer (PR 4): named-rank mismatch diagnostics,
the coordinated non-finite guard, and the parameter divergence audit.

Unit coverage drives the controllers and the optimizer directly;
acceptance coverage launches REAL 2-process jobs (the reference's
`horovodrun -np 2` pattern) and proves a mismatched shape produces a
typed error naming the offending rank on every rank — no hang — across
both controller implementations and both control-plane modes, and that
a NaN-poisoned gradient results in a coordinated skip with replicas
proven digest-identical by the audit afterward.
"""

import os

import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu
from horovod_tpu.native import core as ncore
from horovod_tpu.native import fallback, wire
from horovod_tpu.runner import run

NATIVE = ncore.available()

_REPO_ROOT = os.path.dirname(os.path.dirname(horovod_tpu.__file__))
_ENV = {"PYTHONPATH": _REPO_ROOT + os.pathsep
        + os.environ.get("PYTHONPATH", "")}

CONTROLLER_IMPLS = [fallback.PyController] + (
    [ncore.NativeController] if NATIVE else []
)


def _pair(cls, size=2):
    return [cls(r, size, 1 << 20) for r in range(size)]


def _cycle(controllers):
    blobs = [c.drain_requests() for c in controllers]
    for b in blobs:
        controllers[0].ingest(b)
    resp = controllers[0].compute_responses()
    fins = [c.apply_responses(resp) for c in controllers]
    return wire.parse_response_list(resp), fins


# --------------------------------------------------------------------------
# controller mismatch diagnostics (unit, both impls)
# --------------------------------------------------------------------------

@pytest.mark.parametrize("impl", CONTROLLER_IMPLS)
class TestMismatchDiagnostics:
    def test_shape_mismatch_names_offending_rank(self, impl):
        c0, c1 = _pair(impl)
        c0.enqueue(1, "g", wire.ALLREDUCE, wire.RED_SUM, 6, (4, 4))
        c1.enqueue(1, "g", wire.ALLREDUCE, wire.RED_SUM, 6, (4, 8))
        rl, _ = _cycle([c0, c1])
        assert len(rl.responses) == 1
        err = rl.responses[0].error
        assert err.startswith("cross-rank tensor mismatch for 'g'")
        assert "rank 1 submitted" in err and "shape=[4,8]" in err
        # the error broadcast forces a full resync so the bypass plane
        # re-anchors
        assert rl.cache_resync_needed

    def test_red_op_and_dtype_mismatch(self, impl):
        c0, c1 = _pair(impl)
        c0.enqueue(1, "g", wire.ALLREDUCE, wire.RED_SUM, 6, (4,))
        c1.enqueue(1, "g", wire.ALLREDUCE, wire.RED_AVERAGE, 4, (4,))
        rl, _ = _cycle([c0, c1])
        err = rl.responses[0].error
        assert "red_op=0" in err and "red_op=1" in err
        assert "dtype=6" in err and "dtype=4" in err

    def test_group_id_is_not_part_of_the_agreement_surface(self, impl):
        """Grouping is rank-local bookkeeping: ranks may number groups
        differently without tripping the diagnostics."""
        c0, c1 = _pair(impl)
        for c in (c0, c1):
            c.declare_group(c.rank + 1, 1)
        c0.enqueue(1, "g", wire.ALLREDUCE, wire.RED_SUM, 6, (4,),
                   0, 1, -1)
        c1.enqueue(1, "g", wire.ALLREDUCE, wire.RED_SUM, 6, (4,),
                   0, 2, -1)
        rl, fins = _cycle([c0, c1])
        assert rl.responses[0].error == ""
        assert fins == [[1], [1]]

    def test_ragged_allgather_and_alltoall_are_legitimate(self, impl):
        """Per-rank DIM 0 is the allgather/alltoall contract (ragged
        gathers, variable splits) — it must NOT trip the diagnostics;
        trailing-dim disagreement still must."""
        c0, c1 = _pair(impl)
        c0.enqueue(1, "ag", wire.ALLGATHER, wire.RED_SUM, 6, (2, 5))
        c1.enqueue(1, "ag", wire.ALLGATHER, wire.RED_SUM, 6, (3, 5))
        c0.enqueue(2, "a2a", wire.ALLTOALL, wire.RED_SUM, 6, (4,))
        c1.enqueue(2, "a2a", wire.ALLTOALL, wire.RED_SUM, 6, (6,))
        rl, _ = _cycle([c0, c1])
        assert [rs.error for rs in rl.responses] == ["", ""]
        # trailing dims must still agree
        c0.enqueue(3, "bad", wire.ALLGATHER, wire.RED_SUM, 6, (2, 5))
        c1.enqueue(3, "bad", wire.ALLGATHER, wire.RED_SUM, 6, (2, 7))
        rl, _ = _cycle([c0, c1])
        err = rl.responses[0].error
        assert "cross-rank tensor mismatch for 'bad'" in err
        assert "shape=[2,7]" in err
        # so must the number of dims
        c0.enqueue(4, "nd", wire.ALLGATHER, wire.RED_SUM, 6, (2, 5))
        c1.enqueue(4, "nd", wire.ALLGATHER, wire.RED_SUM, 6, (2,))
        rl, _ = _cycle([c0, c1])
        assert "cross-rank tensor mismatch for 'nd'" in \
            rl.responses[0].error

    def test_matching_resubmission_recovers(self, impl):
        """After a mismatch error, a correctly-matched re-enqueue of
        the same name completes normally (the table entry was
        consumed by the error response)."""
        c0, c1 = _pair(impl)
        c0.enqueue(1, "g", wire.ALLREDUCE, wire.RED_SUM, 6, (4,))
        c1.enqueue(1, "g", wire.ALLREDUCE, wire.RED_SUM, 6, (8,))
        rl, _ = _cycle([c0, c1])
        assert rl.responses[0].error
        c0.enqueue(2, "g", wire.ALLREDUCE, wire.RED_SUM, 6, (4,))
        c1.enqueue(2, "g", wire.ALLREDUCE, wire.RED_SUM, 6, (4,))
        rl, fins = _cycle([c0, c1])
        assert rl.responses[0].error == ""
        assert fins == [[2], [2]]

    def test_bypass_bit_vs_full_entry_mismatch(self, impl):
        """A steady-state rank negotiating via the cache-bit bypass
        must still be diagnosed against a peer's conflicting full
        submission (the bit expands through the coordinator's cache)."""
        c0, c1 = _pair(impl)
        # cycle 1: both agree -> signature cached on every rank
        c0.enqueue(1, "g", wire.ALLREDUCE, wire.RED_SUM, 6, (4,))
        c1.enqueue(1, "g", wire.ALLREDUCE, wire.RED_SUM, 6, (4,))
        rl, _ = _cycle([c0, c1])
        assert rl.responses[0].error == ""
        # cycle 2: rank 0 re-announces (pure cache hit -> bypass blob),
        # rank 1 submits a DIFFERENT shape (cache miss -> full entry)
        c0.enqueue(2, "g", wire.ALLREDUCE, wire.RED_SUM, 6, (4,))
        c1.enqueue(2, "g", wire.ALLREDUCE, wire.RED_SUM, 6, (5,))
        b0, b1 = c0.drain_requests(), c1.drain_requests()
        assert wire.parse_request_list(b0).cache_bypass
        assert not wire.parse_request_list(b1).cache_bypass
        c0.ingest(b0)
        c0.ingest(b1)
        rl = wire.parse_response_list(c0.compute_responses())
        err = rl.responses[0].error
        assert err.startswith("cross-rank tensor mismatch")
        assert "rank 1 submitted" in err and "shape=[5]" in err


# --------------------------------------------------------------------------
# coordinated non-finite guard (unit, eager path)
# --------------------------------------------------------------------------

class TestNonfiniteGuard:
    @pytest.fixture(autouse=True)
    def _init(self):
        import optax  # noqa: F401  (import check before init cost)

        horovod_tpu.init()
        yield

    def _tx(self, monkeypatch, action):
        import optax

        monkeypatch.setenv("HVTPU_NONFINITE_ACTION", action)
        return horovod_tpu.DistributedOptimizer(optax.adam(0.1))

    def test_skip_leaves_state_untouched(self, monkeypatch):
        from horovod_tpu.obs import metrics as obs_metrics

        tx = self._tx(monkeypatch, "skip")
        params = {"w": jnp.ones((3,))}
        st = tx.init(params)
        before = obs_metrics.counter(
            "hvtpu_optimizer_nonfinite_skips_total").value()
        upd, st2 = tx.update(
            {"w": jnp.array([1.0, float("nan"), 1.0])}, st, params)
        assert np.all(np.asarray(upd["w"]) == 0.0)
        import jax

        # adam state (count, mu, nu) byte-identical to the pre-step one
        for a, b in zip(jax.tree_util.tree_leaves(st),
                        jax.tree_util.tree_leaves(st2)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        after = obs_metrics.counter(
            "hvtpu_optimizer_nonfinite_skips_total").value()
        assert after == before + 1

    def test_zero_applies_with_poison_zeroed(self, monkeypatch):
        import optax

        monkeypatch.setenv("HVTPU_NONFINITE_ACTION", "zero")
        tx = horovod_tpu.DistributedOptimizer(optax.sgd(0.1))
        params = {"w": jnp.ones((3,))}
        st = tx.init(params)
        upd, _ = tx.update(
            {"w": jnp.array([1.0, float("inf"), 1.0])}, st, params)
        got = np.asarray(upd["w"])
        assert np.isfinite(got).all()
        assert got[1] == 0.0 and got[0] != 0.0

    def test_abort_raises(self, monkeypatch):
        tx = self._tx(monkeypatch, "abort")
        st = tx.init({"w": jnp.ones((2,))})
        with pytest.raises(horovod_tpu.HorovodInternalError):
            tx.update({"w": jnp.array([float("nan"), 0.0])}, st, None)

    def test_off_disables_the_check(self, monkeypatch):
        tx = self._tx(monkeypatch, "off")
        st = tx.init({"w": jnp.ones((2,))})
        upd, _ = tx.update({"w": jnp.array([float("nan"), 1.0])}, st,
                           None)
        assert not np.isfinite(np.asarray(upd["w"])).all()

    def test_bad_action_is_loud(self, monkeypatch):
        import optax

        monkeypatch.setenv("HVTPU_NONFINITE_ACTION", "explode")
        with pytest.raises(ValueError, match="HVTPU_NONFINITE_ACTION"):
            horovod_tpu.DistributedOptimizer(optax.sgd(0.1))

    def test_finite_step_applies_normally(self, monkeypatch):
        tx = self._tx(monkeypatch, "skip")
        params = {"w": jnp.ones((3,))}
        st = tx.init(params)
        upd, _ = tx.update({"w": jnp.full((3,), 2.0)}, st, params)
        assert np.asarray(upd["w"]).std() >= 0  # produced real updates
        assert np.any(np.asarray(upd["w"]) != 0.0)


# --------------------------------------------------------------------------
# parameter divergence audit (unit, single process)
# --------------------------------------------------------------------------

class TestAuditUnit:
    def test_digest_is_stable_and_content_sensitive(self):
        from horovod_tpu.core import audit

        t1 = {"a": jnp.arange(4.0), "b": {"c": jnp.ones((2, 2))}}
        t2 = {"a": jnp.arange(4.0), "b": {"c": jnp.ones((2, 2))}}
        d1, d2 = audit.digest_tree(t1), audit.digest_tree(t2)
        assert d1 == d2
        t3 = {"a": jnp.arange(4.0).at[0].set(9.0),
              "b": {"c": jnp.ones((2, 2))}}
        d3 = audit.digest_tree(t3)
        assert d1.keys() == d3.keys()
        assert any(d1[k] != d3[k] for k in d1)
        # dtype/shape are part of the digest, not just bytes
        assert (audit.digest_tree({"x": jnp.zeros((4,), jnp.float32)})
                != audit.digest_tree({"x": jnp.zeros((2, 2),
                                                     jnp.float32)}))

    def test_single_process_verify_is_clean(self):
        from horovod_tpu.core import audit

        horovod_tpu.init()
        report = audit.verify({"w": jnp.ones((3,))}, "unit")
        assert report["divergent"] == {} and report["ranks"] == []

    def test_maybe_audit_gating(self, monkeypatch):
        from horovod_tpu.core import audit

        monkeypatch.delenv("HVTPU_AUDIT_EVERY", raising=False)
        assert audit.maybe_audit({"w": jnp.ones(2)}, 10) is None
        monkeypatch.setenv("HVTPU_AUDIT_EVERY", "5")
        assert audit.maybe_audit({"w": jnp.ones(2)}, 7) is None
        assert audit.maybe_audit({"w": jnp.ones(2)}, 10) is not None

    def test_outlier_attribution_prefers_majority(self):
        from horovod_tpu.core import audit

        divergent = audit._find_divergence({
            0: {"w": "aaaa"}, 1: {"w": "bbbb"}, 2: {"w": "aaaa"},
        })
        assert audit._majority_outliers(divergent["w"]) == [1]
        # 2-rank tie: the lowest rank's digest is the reference
        divergent = audit._find_divergence({0: {"w": "aaaa"},
                                            1: {"w": "bbbb"}})
        assert audit._majority_outliers(divergent["w"]) == [1]
        # missing tensor on one rank is divergence too
        divergent = audit._find_divergence({0: {"w": "aaaa", "x": "cc"},
                                            1: {"w": "aaaa"}})
        assert list(divergent) == ["x"]

    def test_elastic_state_audit_gating(self, monkeypatch):
        """ObjectState.audit is a no-op until HVTPU_AUDIT_EVERY > 0,
        then digests exactly the tracked attributes (the elastic run
        wrapper calls it after every sync so incarnations start
        verified-identical)."""
        import horovod_tpu.elastic as elastic

        horovod_tpu.init()
        state = elastic.ObjectState(epoch=3, w=jnp.ones((2,)))
        monkeypatch.delenv("HVTPU_AUDIT_EVERY", raising=False)
        assert state.audit() is None
        monkeypatch.setenv("HVTPU_AUDIT_EVERY", "1")
        report = state.audit("unit.sync")
        assert report is not None and report["divergent"] == {}

    def test_commit_runs_periodic_audit(self, monkeypatch):
        """State.commit() drives the periodic audit at the
        HVTPU_AUDIT_EVERY cadence (the commit counter, identical on
        every rank, is the step clock)."""
        import horovod_tpu.elastic as elastic
        from horovod_tpu.obs import metrics as obs_metrics

        horovod_tpu.init()
        monkeypatch.setenv("HVTPU_AUDIT_EVERY", "2")
        state = elastic.ObjectState(epoch=0, w=jnp.ones((2,)))
        runs = obs_metrics.counter("hvtpu_audit_runs_total")
        before = runs.value()
        state.commit()   # count 1: not due
        assert runs.value() == before
        state.commit()   # count 2: audit fires
        assert runs.value() == before + 1

    def test_bad_knobs_are_loud(self, monkeypatch):
        from horovod_tpu.core import audit

        monkeypatch.setenv("HVTPU_AUDIT_EVERY", "soon")
        with pytest.raises(ValueError, match="HVTPU_AUDIT_EVERY"):
            audit.audit_every()
        monkeypatch.setenv("HVTPU_AUDIT_ACTION", "panic")
        with pytest.raises(ValueError, match="HVTPU_AUDIT_ACTION"):
            audit.audit_action()


# --------------------------------------------------------------------------
# 2-process acceptance
# --------------------------------------------------------------------------

def _run(body, np_=2, env=None, **kw):
    merged = dict(_ENV)
    if env:
        merged.update(env)
    return run(body, np=np_, cpu_devices=1, env=merged,
               start_timeout=300.0, **kw)


@pytest.mark.multiprocess
@pytest.mark.parametrize("force_py", ["0", "1"]
                         if NATIVE else ["1"])
@pytest.mark.parametrize("stream", ["0", "1"])
def test_mismatch_acceptance_2proc(force_py, stream):
    """An injected shape mismatch produces HvtpuMismatchError naming
    rank 1 on EVERY rank — no hang — in both controller impls and both
    control-plane modes (streamed / lockstep)."""

    def body():
        import numpy as np

        import horovod_tpu as hvt
        import jax.numpy as jnp

        hvt.init()
        r = hvt.rank()
        # a matched op first proves the controller works in this mode
        ok = hvt.synchronize(hvt.allreduce_async(
            jnp.full((4,), float(r + 1)), name="warm", op=hvt.Sum))
        assert float(np.asarray(ok)[0]) == 3.0
        # rank 1 submits a mismatched shape under the same name
        shape = (4,) if r == 0 else (6,)
        h = hvt.allreduce_async(jnp.ones(shape), name="conflicted",
                                op=hvt.Sum)
        try:
            hvt.synchronize(h)
        except hvt.HvtpuMismatchError as e:
            msg = str(e)
            assert "cross-rank tensor mismatch for 'conflicted'" in msg
            assert "rank 1 submitted" in msg
            assert "shape=[6]" in msg
        else:
            raise AssertionError(
                f"rank {r}: mismatched collective did not raise")
        # the channel survives: a matched op still completes afterwards
        again = hvt.synchronize(hvt.allreduce_async(
            jnp.full((4,), 1.0), name="recovered", op=hvt.Sum))
        assert float(np.asarray(again)[0]) == 2.0
        return r

    results = _run(body, env={
        "HVTPU_FORCE_PY_CONTROLLER": force_py,
        "HVTPU_EAGER_STREAM": stream,
    }, timeout=300.0)
    assert sorted(results) == [0, 1]


@pytest.mark.multiprocess
def test_nan_skip_and_audit_2proc():
    """A NaN-poisoned gradient on ONE rank results in a coordinated
    skip on BOTH (the NaN rides the allreduce), leaving optimizer
    state digest-identical — proven by the divergence audit — and a
    post-collective corruption on one rank is then caught by the same
    audit naming that rank."""

    def body():
        import jax
        import numpy as np

        import horovod_tpu as hvt
        import jax.numpy as jnp
        import optax
        from horovod_tpu.core import audit, faults
        from horovod_tpu.obs import metrics as obs_metrics

        hvt.init()
        r = hvt.rank()
        tx = hvt.DistributedOptimizer(optax.adam(0.1))
        params = {"w": jnp.ones((8,)), "b": jnp.zeros((2,))}
        st = tx.init(params)
        # step 1: healthy
        g = {"w": jnp.full((8,), float(r + 1)), "b": jnp.ones((2,))}
        upd, st = tx.update(g, st, params)
        params = optax.apply_updates(params, upd)
        # step 2: rank 1's gradient is NaN-poisoned
        g = {"w": jnp.full((8,), 1.0), "b": jnp.ones((2,))}
        if r == 1:
            g = {"w": g["w"].at[3].set(float("nan")), "b": g["b"]}
        upd, st = tx.update(g, st, params)
        assert np.all(np.asarray(upd["w"]) == 0.0), "step not skipped"
        params = optax.apply_updates(params, upd)
        skips = obs_metrics.counter(
            "hvtpu_optimizer_nonfinite_skips_total").value()
        assert skips == 1.0
        # replicas byte-identical after the coordinated skip
        report = audit.verify(
            {"params": params, "opt": st}, "post-skip")
        assert report["divergent"] == {}
        runs = obs_metrics.counter("hvtpu_audit_runs_total").value()
        assert runs >= 1.0
        # now manufacture REAL divergence: corrupt rank 1's allreduce
        # RESULT (collective.post) and prove the audit names rank 1
        faults.install("collective.post:corrupt@rank=1", rank=r)
        diverged = hvt.allreduce(jnp.ones((4,)), op=hvt.Sum)
        faults.uninstall()
        report = audit.verify({"x": diverged}, "post-corrupt",
                              action="warn")
        assert report["ranks"] == [1], report
        div = obs_metrics.counter(
            "hvtpu_audit_divergences_total").value()
        assert div == 1.0
        # abort action raises the typed error on every rank
        try:
            audit.verify({"x": diverged}, "post-corrupt-abort",
                         action="abort")
            raise AssertionError("abort action did not raise")
        except hvt.HvtpuDivergenceError as e:
            assert "divergent ranks [1]" in str(e)
        return r

    results = _run(body, timeout=300.0)
    assert sorted(results) == [0, 1]


@pytest.mark.multiprocess
def test_pre_corrupt_exercises_guard_end_to_end_2proc():
    """`collective.pre:corrupt@rank=0` (the fault-spec grammar, as a
    user would pass it) NaN-poisons rank 0's INPUT; the poison rides
    the wire, and BOTH ranks skip together."""

    def body():
        import numpy as np

        import horovod_tpu as hvt
        import jax.numpy as jnp
        import optax
        from horovod_tpu.obs import metrics as obs_metrics

        hvt.init()
        tx = hvt.DistributedOptimizer(optax.sgd(0.1))
        params = {"w": jnp.ones((4,))}
        st = tx.init(params)
        upd, st = tx.update({"w": jnp.full((4,), 2.0)}, st, params)
        assert np.all(np.asarray(upd["w"]) == 0.0)
        assert obs_metrics.counter(
            "hvtpu_optimizer_nonfinite_skips_total").value() == 1.0
        return hvt.rank()

    results = _run(body, env={
        "HVTPU_FAULT_SPEC": "collective.pre:corrupt@rank=0",
    }, timeout=300.0)
    assert sorted(results) == [0, 1]
