"""CLI: ``python -m tools.hvtpulint`` (or the ``hvtpulint`` script).

Exit codes: 0 clean, 1 findings, 2 usage/internal error.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from . import SUPPRESSION_FILE, Project, pass_names, run_passes


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="hvtpulint",
        description="static analysis for the hvtpu tree "
                    "(docs/static-analysis.md)")
    parser.add_argument("--root", type=Path, default=None,
                        help="repo root (default: auto-detected from "
                             "this file's location)")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument("--passes", default=None, metavar="P1,P2",
                        help="comma-separated subset of: "
                             + ",".join(pass_names()))
    parser.add_argument("--suppressions", type=Path, default=None,
                        help=f"suppression file (default: "
                             f"<root>/{SUPPRESSION_FILE})")
    parser.add_argument("--list-passes", action="store_true")
    parser.add_argument("--write-knobs", action="store_true",
                        help="regenerate docs/knobs.md from the "
                             "extracted knob set (preserves existing "
                             "descriptions), then exit")
    args = parser.parse_args(argv)

    if args.list_passes:
        for name in pass_names():
            print(name)
        return 0

    root = args.root
    if root is None:
        # tools/hvtpulint/__main__.py -> repo root two levels up
        root = Path(__file__).resolve().parent.parent.parent
    root = root.resolve()
    if not (root / "horovod_tpu").is_dir():
        print(f"hvtpulint: {root} does not look like the hvtpu repo "
              "(no horovod_tpu/); pass --root", file=sys.stderr)
        return 2

    if args.write_knobs:
        from . import knob_registry
        out = knob_registry.write_knobs_md(Project(root))
        print(f"hvtpulint: wrote {out}")
        return 0

    only = [p.strip() for p in args.passes.split(",")] if args.passes else None
    try:
        findings = run_passes(root, only=only,
                              suppress_path=args.suppressions)
    except ValueError as exc:
        print(f"hvtpulint: {exc}", file=sys.stderr)
        return 2

    if args.format == "json":
        print(json.dumps({"findings": [f.as_json() for f in findings],
                          "count": len(findings)}, indent=2))
    else:
        for f in findings:
            print(f.format_text())
        n = len(findings)
        ran = ", ".join(only) if only else "all passes"
        print(f"hvtpulint: {n} finding{'s' if n != 1 else ''} ({ran})")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
