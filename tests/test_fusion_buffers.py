"""Zero-copy fusion-buffer plane: packing-level contracts.

The controller-integration side (enqueue-time packing, the
{predicted, mispredicted} x {lockstep, streamed} fallback matrix,
quiesce hygiene, the non-steady enqueue overhead guard) lives in
tests/test_eager_controller.py; this file pins the pure
comm/packing.py pieces those paths are built from:

- aligned offset assignment (the satellite fixing unpack_bytes'
  silent unaligned-fallback copy),
- ExchangeBuffer write/complete/view semantics,
- FusionBufferPool reuse + LRU eviction bounds,
- the cached group-unpack program and its mispredict invalidation.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.comm import packing


MIXED = [((3,), np.dtype(np.float64), 24),
         ((5,), np.dtype(np.float32), 20),
         ((7,), np.dtype(np.int16), 14),
         ((2, 2), np.dtype(np.float64), 32)]


class TestAssignOffsets:
    def test_uniform_dtype_layout_is_contiguous(self):
        specs = [((4,), np.dtype(np.float32), 16),
                 ((2, 3), np.dtype(np.float32), 24)]
        offsets, total = packing.assign_offsets(specs)
        assert offsets == [0, 16]
        assert total == 40

    def test_mixed_dtype_offsets_are_dtype_aligned(self):
        offsets, total = packing.assign_offsets(MIXED)
        align = 8  # group max itemsize (float64)
        for off, (_s, dt, _n) in zip(offsets, MIXED):
            assert off % align == 0
            assert off % dt.itemsize == 0
        # padding only where needed: int16 block (14 bytes) pads the
        # following float64 up to the 8-byte boundary
        assert offsets == [0, 24, 48, 64]
        assert total == 96

    def test_explicit_align_override(self):
        offsets, total = packing.assign_offsets(
            [((3,), np.dtype(np.int8), 3), ((3,), np.dtype(np.int8), 3)],
            align=64)
        assert offsets == [0, 64]
        assert total == 128


class TestExchangeBuffer:
    def test_mixed_dtype_unpack_is_views_not_copies(self):
        """Satellite regression: the aligned layout keeps EVERY piece
        on unpack_bytes' view path — np.shares_memory with the backing
        buffer, no silent tobytes() copy for any dtype in the mix."""
        xb = packing.ExchangeBuffer(MIXED)
        for i, (shape, dt, _n) in enumerate(xb.specs):
            assert xb.write(i, np.arange(int(np.prod(shape)),
                                         dtype=dt).reshape(shape))
        assert xb.complete()
        views = xb.views()
        for i, (v, (shape, dt, _n)) in enumerate(zip(views, xb.specs)):
            assert v.shape == shape and v.dtype == dt
            assert np.shares_memory(v, xb.buf), f"piece {i} was copied"
            assert v.flags["ALIGNED"], f"piece {i} view is unaligned"
            np.testing.assert_array_equal(
                v, np.arange(int(np.prod(shape)), dtype=dt).reshape(shape))

    def test_contiguous_layout_of_same_specs_is_unaligned(self):
        """Contrast case proving what the aligned layout buys: the
        CONTIGUOUS layout of the same mix leaves the float64 after the
        int16 run on an odd offset — numpy hands back an ALIGNED=False
        view, which every downstream consumer (jnp.asarray, BLAS)
        silently copies before use."""
        specs = [((7,), np.dtype(np.int16), 14),
                 ((2,), np.dtype(np.float64), 16)]
        buf = np.zeros(30, np.uint8)
        pieces = packing.unpack_bytes(buf, specs)
        assert not pieces[1].flags["ALIGNED"]

    def test_write_rejects_mismatch_and_double_fill(self):
        xb = packing.ExchangeBuffer([((4,), np.dtype(np.float32), 16)])
        assert not xb.write(0, np.zeros(4, np.float64))  # dtype
        assert not xb.write(0, np.zeros(8, np.float32))  # nbytes
        assert not xb.complete()
        assert xb.write(0, np.ones(4, np.float32))
        assert not xb.write(0, np.ones(4, np.float32))   # stale plan
        assert xb.complete()
        xb.reset()
        assert not xb.complete()
        assert xb.write(0, np.ones(4, np.float32))

    def test_typed_view_requires_uniform_dtype(self):
        xb = packing.ExchangeBuffer(MIXED)
        with pytest.raises(ValueError):
            xb.typed_view()
        uni = packing.ExchangeBuffer(
            [((2,), np.dtype(np.float32), 8),
             ((3,), np.dtype(np.float32), 12)])
        uni.write(0, np.array([1, 2], np.float32))
        uni.write(1, np.array([3, 4, 5], np.float32))
        flat = uni.typed_view()
        assert flat.dtype == np.float32
        assert np.shares_memory(flat, uni.buf)
        np.testing.assert_array_equal(flat, [1, 2, 3, 4, 5])


class TestFusionBufferPool:
    SPECS = [((4,), np.dtype(np.float32), 16)]

    def test_release_then_acquire_reuses_the_buffer(self):
        pool = packing.FusionBufferPool(capacity=4)
        xb = pool.acquire(0, self.SPECS)
        xb.write(0, np.ones(4, np.float32))
        pool.release(0, xb)
        assert pool.stats()["pooled"] == 1
        again = pool.acquire(0, self.SPECS)
        assert again is xb
        assert not again.complete()  # release reset the fill set
        assert pool.stats()["pooled"] == 0

    def test_keying_isolates_process_sets_and_layouts(self):
        pool = packing.FusionBufferPool(capacity=4)
        xb = pool.acquire(0, self.SPECS)
        pool.release(0, xb)
        assert pool.acquire(1, self.SPECS) is not xb  # other set
        other = [((8,), np.dtype(np.float32), 32)]
        assert pool.acquire(0, other) is not xb       # other layout
        assert pool.acquire(0, self.SPECS) is xb

    def test_lru_eviction_bounds_the_pool(self):
        pool = packing.FusionBufferPool(capacity=2)
        layouts = [[((n,), np.dtype(np.float32), 4 * n)]
                   for n in (2, 3, 4)]
        bufs = [pool.acquire(0, sp) for sp in layouts]
        for sp, xb in zip(layouts, bufs):
            pool.release(0, xb)
        st = pool.stats()
        assert st["pooled"] == 2 and st["capacity"] == 2
        # the oldest layout was evicted; the two youngest survive
        assert pool.acquire(0, layouts[0]) is not bufs[0]
        assert pool.acquire(0, layouts[1]) is bufs[1]
        assert pool.acquire(0, layouts[2]) is bufs[2]

    def test_env_knob_and_clear(self, monkeypatch):
        monkeypatch.setenv(packing.POOL_KNOB, "3")
        pool = packing.FusionBufferPool()
        assert pool.capacity == 3
        pool.release(0, packing.ExchangeBuffer(self.SPECS))
        pool.clear()
        assert pool.stats() == {"pooled": 0, "capacity": 3, "layouts": 0}


class TestGroupUnpackProgram:
    def test_unpacks_like_unpack_flat(self):
        specs = [((2, 2), jnp.float32, 4), ((3,), jnp.float32, 3)]
        flat = jnp.arange(7.0, dtype=jnp.float32)
        fn = packing.group_unpack_program(specs)
        got = fn(flat)
        want = packing.unpack_flat(flat, specs)
        assert len(got) == 2
        for g, w in zip(got, want):
            np.testing.assert_array_equal(np.asarray(g), np.asarray(w))

    def test_program_is_cached_per_spec_key(self):
        specs = [((5,), jnp.float32, 5)]
        assert (packing.group_unpack_program(specs)
                is packing.group_unpack_program(list(specs)))
        packing.clear_unpack_cache()
        # cache dropped: a fresh jitted program is built
        info = packing._unpack_program.cache_info()
        assert info.currsize == 0

    def test_invalidate_routing_plans_drops_unpack_cache(self):
        """Mispredict invalidation rides the comm layer's plan drop:
        the memoized unpack programs are keyed by now-suspect
        groupings and must go with them."""
        from horovod_tpu.comm import eager as eager_comm

        packing.group_unpack_program([((2,), jnp.float32, 2)])
        assert packing._unpack_program.cache_info().currsize > 0
        eager_comm.invalidate_routing_plans()
        assert packing._unpack_program.cache_info().currsize == 0
