"""Elastic keras state (parity: ``horovod/keras/elastic.py``
``KerasState``): alias of the TF/Keras state object plus the shared
``run`` decorator."""

from ..elastic import run  # noqa: F401  (parity: hvd.elastic.run)
from ..tensorflow.elastic import TensorFlowKerasState

KerasState = TensorFlowKerasState
