#include "controller.h"

#include <algorithm>
#include <chrono>
#include <sstream>
#include <tuple>

namespace hvt {

double NowSeconds() {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// --------------------------------------------------------------------------
// TensorQueue
// --------------------------------------------------------------------------

bool TensorQueue::Add(Entry e) {
  std::lock_guard<std::mutex> g(mu_);
  // Parity: tensor_queue.cc AddToTensorQueue rejects duplicate names —
  // the same tensor cannot be pending twice.
  if (pending_names_.count(e.name) || in_flight_.count(e.name)) return false;
  pending_names_.insert(e.name);
  pending_.push_back(std::move(e));
  return true;
}

std::vector<Entry> TensorQueue::Drain(size_t limit) {
  std::lock_guard<std::mutex> g(mu_);
  size_t n = pending_.size();
  if (limit > 0 && limit < n) n = limit;
  std::vector<Entry> out(pending_.begin(), pending_.begin() + n);
  for (const Entry& e : out) {
    in_flight_.emplace(e.name, e);
    pending_names_.erase(e.name);
  }
  pending_.erase(pending_.begin(), pending_.begin() + n);
  return out;
}

std::vector<uint64_t> TensorQueue::Finish(
    const std::vector<std::string>& names) {
  std::lock_guard<std::mutex> g(mu_);
  std::vector<uint64_t> seqs;
  for (const std::string& n : names) {
    auto it = in_flight_.find(n);
    if (it != in_flight_.end()) {
      seqs.push_back(it->second.seq);
      in_flight_.erase(it);
    }
  }
  return seqs;
}

std::vector<Entry> TensorQueue::InFlightSnapshot() const {
  std::lock_guard<std::mutex> g(mu_);
  std::vector<Entry> out;
  out.reserve(in_flight_.size());
  for (const auto& kv : in_flight_) out.push_back(kv.second);
  return out;
}

int64_t TensorQueue::pending_count() const {
  std::lock_guard<std::mutex> g(mu_);
  return static_cast<int64_t>(pending_.size());
}

int64_t TensorQueue::pending_bytes() const {
  std::lock_guard<std::mutex> g(mu_);
  int64_t b = 0;
  for (const Entry& e : pending_) b += e.nbytes();
  return b;
}

// --------------------------------------------------------------------------
// ResponseCache
// --------------------------------------------------------------------------

std::string ResponseCache::Signature(const Entry& e) {
  // Parity: response_cache.cc keys on (name, op params, dtype, shape,
  // device); device is implicit here (one logical device per rank).
  std::ostringstream ss;
  ss << e.name << '|' << int(e.type) << '|' << int(e.red_op) << '|'
     << int(e.dtype) << '|' << e.process_set_id << '|' << e.root_rank << '|';
  for (int64_t d : e.shape) ss << d << ',';
  return ss.str();
}

int64_t ResponseCache::Lookup(const std::string& signature) const {
  auto it = by_sig_.find(signature);
  if (it == by_sig_.end()) return -1;
  return it->second->bit;
}

uint32_t ResponseCache::Put(const std::string& signature, const Entry& e) {
  auto it = by_sig_.find(signature);
  if (it != by_sig_.end()) {
    // Touch: move to front (most recently used).
    lru_.splice(lru_.begin(), lru_, it->second);
    return it->second->bit;
  }
  // Evict if at capacity (parity: response_cache.cc capacity_,
  // HOROVOD_CACHE_CAPACITY).
  if (lru_.size() >= capacity_ && !lru_.empty()) {
    const CacheItem& victim = lru_.back();
    free_bits_.insert(victim.bit);
    by_sig_.erase(victim.signature);
    by_bit_.erase(victim.bit);
    lru_.pop_back();
  }
  uint32_t bit;
  if (!free_bits_.empty()) {
    bit = *free_bits_.begin();
    free_bits_.erase(free_bits_.begin());
  } else {
    bit = next_bit_++;
  }
  lru_.push_front(CacheItem{signature, e, bit});
  by_sig_[signature] = lru_.begin();
  by_bit_[bit] = lru_.begin();
  return bit;
}

bool ResponseCache::GetEntryForBit(uint32_t bit, Entry* out) const {
  auto it = by_bit_.find(bit);
  if (it == by_bit_.end()) return false;
  *out = it->second->entry;
  return true;
}

// --------------------------------------------------------------------------
// Controller
// --------------------------------------------------------------------------

Controller::Controller(int32_t rank, int32_t size,
                       int64_t fusion_threshold_bytes, size_t cache_capacity,
                       double stall_warn_s, double stall_abort_s)
    : rank_(rank),
      size_(size),
      fusion_threshold_(fusion_threshold_bytes),
      stall_warn_s_(stall_warn_s),
      stall_abort_s_(stall_abort_s),
      cache_(cache_capacity) {
  // Global process set 0 = all ranks (parity: process_set.cc id 0).
  std::vector<int32_t> all(size);
  for (int32_t i = 0; i < size; ++i) all[i] = i;
  process_sets_[0] = std::move(all);
}

void Controller::RegisterProcessSet(int32_t psid, std::vector<int32_t> ranks) {
  std::lock_guard<std::mutex> g(mu_);
  std::sort(ranks.begin(), ranks.end());
  process_sets_[psid] = std::move(ranks);
}

int32_t Controller::RequiredRanks(int32_t psid) const {
  auto it = process_sets_.find(psid);
  return it == process_sets_.end() ? size_
                                   : static_cast<int32_t>(it->second.size());
}

std::vector<int32_t> Controller::ProcessSetRanks(int32_t psid) const {
  auto it = process_sets_.find(psid);
  if (it != process_sets_.end()) return it->second;
  std::vector<int32_t> all(size_);
  for (int32_t i = 0; i < size_; ++i) all[i] = i;
  return all;
}

uint64_t Controller::Enqueue(Entry e, Status* status) {
  static_cast<void>(rank_);
  e.enqueue_time_s = NowSeconds();
  uint64_t seq = e.seq;
  if (!queue_.Add(std::move(e))) {
    *status = Status::Error("duplicate tensor name in queue");
    return 0;
  }
  *status = Status::OK();
  return seq;
}

std::vector<uint8_t> Controller::DrainRequests(int64_t limit) {
  RequestList rl;
  rl.rank = rank_;
  rl.joined = joined_;
  rl.shutdown = shutdown_;
  bool resync_flush = resync_flush_;
  resync_flush_ = false;
  // In-flight ops BEFORE this drain: re-announced on a coordinator-
  // requested resync (their first announcement may have hit an
  // unexpandable cache bit at the coordinator).
  std::vector<Entry> prior_in_flight;
  if (resync_flush) {
    prior_in_flight = queue_.InFlightSnapshot();
    std::sort(prior_in_flight.begin(), prior_in_flight.end(),
              [](const Entry& a, const Entry& b) {
                return TableKey(a) < TableKey(b);
              });
  }
  std::vector<Entry> entries =
      queue_.Drain(limit > 0 ? static_cast<size_t>(limit) : 0);
  std::vector<int64_t> bits;
  bits.reserve(entries.size());
  bool all_hit = !entries.empty();
  for (const Entry& e : entries) {
    int64_t bit = cache_.Lookup(ResponseCache::Signature(e));
    bits.push_back(bit);
    if (bit < 0) all_hit = false;
  }
  // derive from the captured flags so the blob is internally
  // consistent even if SetJoined/SetShutdown race the drain
  bool membership = rl.joined || rl.shutdown;
  // Steady-state bypass: every drained op is a cache hit, no
  // membership change in flight, and the periodic full-resync cycle is
  // not due — the whole drain travels as one compact bit vector
  // (parity: the coordinated cache bitvector of
  // Controller::CoordinateCacheAndState).
  if (all_hit && !membership && !resync_flush && resync_every_ > 0 &&
      bypass_streak_ + 1 < resync_every_) {
    bypass_streak_++;
    rl.cache_bypass = true;
    rl.burst_id = ++burst_seq_;
    rl.burst_len = static_cast<uint32_t>(bits.size());
    std::vector<uint32_t> sorted_bits;
    sorted_bits.reserve(bits.size());
    for (int64_t b : bits) sorted_bits.push_back(static_cast<uint32_t>(b));
    std::sort(sorted_bits.begin(), sorted_bits.end());
    rl.cache_bits = PackBits(sorted_bits);
    return SerializeRequestList(rl);
  }
  bypass_streak_ = 0;
  // Periodic resync (streak exhausted) or coordinator-forced flush:
  // full entries keep the coordinator's message table and stall
  // inspector authoritative even if caches diverge.
  bool resync = resync_flush || (all_hit && !membership);
  rl.cache_resync = resync;
  if (!entries.empty()) {
    // Fresh entries form one atomic burst unit; resync re-announcements
    // (prior_in_flight) ride behind them, OUTSIDE the unit, and match
    // idempotently at ingest.
    rl.burst_id = ++burst_seq_;
    rl.burst_len = static_cast<uint32_t>(entries.size());
  }
  for (size_t i = 0; i < entries.size(); ++i) {
    Entry& e = entries[i];
    int64_t bit = bits[i];
    Request rq;
    rq.rank = rank_;
    if (bit >= 0) rl.cache_hits.push_back(static_cast<uint32_t>(bit));
    if (bit >= 0 && !resync) {
      // Mixed cycle: transmit the bit id + seq only; the coordinator
      // expands the bit via its own (identical) cache.
      rq.cached = true;
      rq.cache_bit = static_cast<uint32_t>(bit);
      rq.entry.seq = e.seq;
      rq.entry.name = e.name;  // kept for local Finish() + debuggability
    } else {
      rq.entry = std::move(e);
    }
    rl.requests.push_back(std::move(rq));
  }
  for (Entry& e : prior_in_flight) {
    Request rq;
    rq.rank = rank_;
    rq.entry = std::move(e);
    rl.requests.push_back(std::move(rq));
  }
  return SerializeRequestList(rl);
}

bool Controller::SameParams(const Entry& a, const Entry& b) {
  if (a.type != b.type || a.red_op != b.red_op || a.dtype != b.dtype ||
      a.root_rank != b.root_rank) {
    return false;
  }
  if (a.type == OpType::kAllgather || a.type == OpType::kAlltoall) {
    // Dim 0 is legitimately per-rank (ragged gathers, variable
    // splits); rank-count and trailing dims must still agree.
    if (a.shape.size() != b.shape.size()) return false;
    for (size_t i = 1; i < a.shape.size(); ++i) {
      if (a.shape[i] != b.shape[i]) return false;
    }
    return true;
  }
  return a.shape == b.shape;
}

std::string Controller::EntryDesc(const Entry& e) {
  std::ostringstream ss;
  ss << "op=" << int(e.type) << " red_op=" << int(e.red_op)
     << " dtype=" << int(e.dtype) << " shape=[";
  for (size_t i = 0; i < e.shape.size(); ++i) {
    if (i) ss << ',';
    ss << e.shape[i];
  }
  ss << "] root_rank=" << e.root_rank;
  return ss.str();
}

Controller::PendingCoordination* Controller::TableAdd(Entry e, int32_t rank,
                                                      double now,
                                                      bool occurrence,
                                                      std::string* out_key) {
  std::string key = TableKey(e);
  if (out_key) *out_key = key;
  std::deque<PendingCoordination>& q = message_table_[key];
  PendingCoordination* pc = nullptr;
  if (occurrence) {
    // Burst-unit announcement: a NEW occurrence relative to ones this
    // rank already announced, so back-to-back confirmed bursts of the
    // same tensor names queue instead of collapsing into one release.
    for (PendingCoordination& cand : q) {
      if (!cand.ranks.count(rank)) {
        pc = &cand;
        break;
      }
    }
  } else {
    // Legacy/idempotent matching (unit-less frames and resync
    // re-announcements): a re-announcing rank lands on the occurrence
    // it already joined, never opening a duplicate.
    for (PendingCoordination& cand : q) {
      if (cand.ranks.count(rank)) {
        pc = &cand;
        break;
      }
    }
    if (pc == nullptr && !q.empty()) pc = &q.front();
  }
  if (pc == nullptr) {
    // Parity: MessageTable insertion on first Request for a name.
    PendingCoordination fresh;
    fresh.entry = std::move(e);
    fresh.first_seen_s = now;
    fresh.first_rank = rank;
    fresh.ranks.insert(rank);
    fresh.seq = pc_seq_++;
    q.push_back(std::move(fresh));
    return &q.back();
  }
  pc->ranks.insert(rank);
  if (rank != pc->first_rank && !pc->mismatched.count(rank) &&
      !SameParams(e, pc->entry)) {
    pc->mismatched.emplace(rank, std::move(e));
  }
  return pc;
}

void Controller::ReleaseFront(const std::string& key,
                              const PendingCoordination& pc) {
  // Drop the key from every burst unit that referenced this occurrence
  // (so an error-released member doesn't deadlock the rest of its
  // unit), then pop the occurrence queue.
  for (const UnitRef& ref : pc.units) {
    auto it = units_.find(ref);
    if (it != units_.end()) {
      it->second.erase(key);
      if (it->second.empty()) units_.erase(it);
    }
  }
  auto qit = message_table_.find(key);
  if (qit != message_table_.end() && !qit->second.empty()) {
    qit->second.pop_front();
    if (qit->second.empty()) message_table_.erase(qit);
  }
}

std::string Controller::TableKey(const Entry& e) {
  // Coordination is scoped per process set: the same tensor name may be
  // pending simultaneously in disjoint sets (parity: each ProcessSet in
  // process_set.cc owns its own controller + MessageTable).  '\x01'
  // cannot appear in a psid decimal string, so keys are unambiguous,
  // and std::map's byte order matches Python's sorted() on the same
  // strings (UTF-8 byte order == code-point order).
  return std::to_string(e.process_set_id) + '\x01' + e.name;
}

void Controller::Ingest(const uint8_t* data, size_t len) {
  RequestList rl = ParseRequestList(data, len);
  std::lock_guard<std::mutex> g(mu_);
  double now = NowSeconds();
  if (rl.joined && joined_ranks_.insert(rl.rank).second) {
    // Track the temporally-last joiner (parity: hvd.join() returns the
    // last rank that joined, not the largest rank id).
    last_joined_rank_ = rl.rank;
  }
  if (rl.shutdown) shutdown_ranks_.insert(rl.rank);
  const bool has_unit = rl.burst_id > 0 && rl.burst_len > 0;
  const UnitRef ref{rl.rank, rl.burst_id};
  std::set<std::string> unit_keys;
  if (rl.cache_bypass) {
    // Expand the rank's cache-bit vector through the coordinator's own
    // (identical) cache.  An unknown bit means the caches diverged
    // (e.g. elastic generations mixing): request a full resync from
    // every rank via the next ResponseList.
    std::vector<uint32_t> bits = UnpackBits(rl.cache_bits);
    for (size_t idx = 0; idx < bits.size(); ++idx) {
      Entry cached;
      if (!cache_.GetEntryForBit(bits[idx], &cached)) {
        resync_needed_ = true;
        continue;
      }
      cached.seq = 0;
      bool in_unit = has_unit && idx < rl.burst_len;
      std::string key;
      PendingCoordination* pc =
          TableAdd(std::move(cached), rl.rank, now, in_unit, &key);
      if (in_unit) {
        pc->units.insert(ref);
        unit_keys.insert(key);
        if (rl.predicted) pc->predicted.insert(rl.rank);
      }
    }
    if (has_unit && !unit_keys.empty()) units_[ref] = std::move(unit_keys);
    return;
  }
  for (size_t idx = 0; idx < rl.requests.size(); ++idx) {
    const Request& rq = rl.requests[idx];
    Entry e = rq.entry;
    if (rq.cached) {
      // Expand the bit back into the full entry via the coordinator's
      // own (identical) cache.
      Entry cached;
      if (cache_.GetEntryForBit(rq.cache_bit, &cached)) {
        cached.seq = e.seq;
        e = cached;
      }
    }
    bool in_unit = has_unit && idx < rl.burst_len;
    std::string key;
    PendingCoordination* pc = TableAdd(std::move(e), rl.rank, now, in_unit, &key);
    if (in_unit) {
      pc->units.insert(ref);
      unit_keys.insert(key);
      if (rl.predicted) pc->predicted.insert(rl.rank);
    }
  }
  if (has_unit && !unit_keys.empty()) units_[ref] = std::move(unit_keys);
}

int32_t Controller::PresentCount(const PendingCoordination& pc) const {
  // Joined ranks count as implicitly ready for every pending tensor in
  // their process sets (parity: operations.cc EnqueueJoin / JoinOp —
  // a joined rank participates with a zero contribution, so remaining
  // ranks' collectives never stall on it).
  int32_t present = 0;
  for (int32_t r : ProcessSetRanks(pc.entry.process_set_id)) {
    if (pc.ranks.count(r) || joined_ranks_.count(r)) present++;
  }
  return present;
}

ResponseList Controller::BuildResponseList() {
  // Caller holds mu_.
  ResponseList out;
  out.tuned_fusion_threshold = tuned_threshold_;
  out.tuned_cycle_time_us = tuned_cycle_us_;
  out.cache_resync_needed = resync_needed_;
  resync_needed_ = false;

  // 1. collect globally-ready keys (every member rank reported, or is
  //    joined).  Only the FRONT occurrence of each key is eligible, so
  //    per-key release order always matches announcement order.
  //    message_table_ is a std::map → deterministic (process set,
  //    name) order, the analog of FuseResponses' stable ordering.
  std::map<std::string, PendingCoordination*> fronts;
  for (auto& kv : message_table_) {
    if (!kv.second.empty()) fronts[kv.first] = &kv.second.front();
  }
  std::vector<std::string> ready;
  for (auto& kv : fronts) {
    const PendingCoordination& pc = *kv.second;
    if (PresentCount(pc) >= RequiredRanks(pc.entry.process_set_id)) {
      ready.push_back(kv.first);
    }
  }

  // 2. group gating (parity: group_table.cc — a grouped tensor only
  //    executes when the whole group is ready).
  std::unordered_map<int64_t, int32_t> group_ready_counts;
  for (const std::string& n : ready) {
    const Entry& e = fronts[n]->entry;
    if (e.group_id >= 0) group_ready_counts[e.group_id]++;
  }
  std::map<std::string, PendingCoordination*> candidates;
  std::vector<std::string> mismatch_keys;
  for (const std::string& n : ready) {
    PendingCoordination* pc = fronts[n];
    const Entry& e = pc->entry;
    if (e.group_id >= 0) {
      int32_t want = group_table_.GroupSize(e.group_id);
      if (want > 0 && group_ready_counts[e.group_id] < want) continue;
    }
    if (!pc->mismatched.empty()) {
      mismatch_keys.push_back(n);
    } else {
      candidates[n] = pc;
    }
  }

  // 3. atomic-unit admission: a ready op releases only when every
  //    burst unit containing it is COMPLETELY ready, and the
  //    transitive closure over shared unit refs partitions the
  //    releasable work into connected components.  Fusion runs per
  //    component (fresh open-group state each time), so the
  //    coordinator can never form a fusion group across a burst
  //    boundary — a peer's split burst holds its whole component back
  //    instead of diverging the fused groupings that
  //    PredictResponses() reconstructed locally.
  struct Component {
    uint64_t seq;
    std::vector<std::string> keys;  // sorted
  };
  std::vector<Component> components;
  std::set<std::string> assigned;
  for (auto& kv : candidates) {
    const std::string& seed = kv.first;
    if (assigned.count(seed)) continue;
    std::set<std::string> comp;
    bool comp_ok = true;
    std::vector<std::string> stack{seed};
    while (!stack.empty() && comp_ok) {
      std::string k = stack.back();
      stack.pop_back();
      if (comp.count(k)) continue;
      auto cit = candidates.find(k);
      if (cit == candidates.end()) {
        comp_ok = false;
        break;
      }
      comp.insert(k);
      for (const UnitRef& ref : cit->second->units) {
        auto uit = units_.find(ref);
        if (uit == units_.end()) continue;
        for (const std::string& k2 : uit->second) {
          auto c2 = candidates.find(k2);
          if (c2 == candidates.end() || !c2->second->units.count(ref)) {
            comp_ok = false;
            break;
          }
          if (!comp.count(k2)) stack.push_back(k2);
        }
        if (!comp_ok) break;
      }
    }
    if (!comp_ok) continue;  // a unit is split-pending: hold the component
    uint64_t min_seq = UINT64_MAX;
    for (const std::string& k : comp) {
      min_seq = std::min(min_seq, candidates[k]->seq);
      assigned.insert(k);
    }
    components.push_back(
        Component{min_seq, std::vector<std::string>(comp.begin(), comp.end())});
  }
  // Mismatch errors bypass unit gating (fail fast; the forced resync
  // re-anchors the survivors) as singleton components.
  for (const std::string& key : mismatch_keys) {
    components.push_back(Component{fronts[key]->seq, {key}});
  }
  // Creation order == per-rank announcement order on every stream, so
  // component emission order matches every predictor's confirmation
  // FIFO.
  std::sort(components.begin(), components.end(),
            [](const Component& a, const Component& b) {
              return a.seq < b.seq;
            });

  // 4. one Response per tensor, fused PER COMPONENT.  Responses carry
  //    the BARE tensor name; the set scope travels in process_set_id.
  //    A component whose every member rank announced as a PREDICTED
  //    confirmation is suppressed down to a confirm hash.
  for (const Component& component : components) {
    std::vector<Response> comp_responses;
    bool suppress = true;
    for (const std::string& n : component.keys) {
      // Take the front occurrence off its queue; ReleaseFront below
      // needs the units copy after the pop.
      PendingCoordination pc = std::move(message_table_[n].front());
      const Entry& e = pc.entry;
      Response rs;
      rs.type = e.type;
      rs.red_op = e.red_op;
      rs.dtype = e.dtype;
      rs.process_set_id = e.process_set_id;
      rs.root_rank = e.root_rank;
      rs.tensor_names.push_back(e.name);
      rs.tensor_shapes.push_back(e.shape);
      rs.total_bytes = e.nbytes();
      if (!pc.mismatched.empty()) {
        // Cross-rank disagreement: fail LOUDLY on every member rank,
        // naming each offender and what it submitted (text must match
        // fallback.PyController byte-for-byte).  The error broadcast
        // also forces a full cache resync, re-anchoring the bypass
        // AND predict planes.
        std::ostringstream ss;
        ss << "cross-rank tensor mismatch for '" << e.name << "': rank "
           << pc.first_rank << " submitted " << EntryDesc(e);
        for (const auto& kv : pc.mismatched) {
          ss << "; rank " << kv.first << " submitted "
             << EntryDesc(kv.second);
        }
        rs.error = ss.str();
        out.cache_resync_needed = true;
        suppress = false;
        comp_responses.push_back(std::move(rs));
        ReleaseFront(n, pc);
        continue;
      }
      // Zero substitution from joined ranks is only sound for additive
      // semantics; reject ops it would silently corrupt (min/max/
      // product zeroed, adasum NaN from zero norms, broadcast root
      // with no data, int8 wire needing the two-phase quantized kernel
      // on every rank).
      bool used_joined = false;
      for (int32_t r : ProcessSetRanks(e.process_set_id)) {
        if (!pc.ranks.count(r) && joined_ranks_.count(r)) used_joined = true;
      }
      if (used_joined) {
        if (e.type == OpType::kBroadcast && e.root_rank >= 0 &&
            !pc.ranks.count(e.root_rank) && joined_ranks_.count(e.root_rank)) {
          rs.error = "broadcast root rank " + std::to_string(e.root_rank) +
                     " has joined";
        } else if ((e.type == OpType::kAllreduce ||
                    e.type == OpType::kReducescatter) &&
                   (e.red_op == RedOp::kMin || e.red_op == RedOp::kMax ||
                    e.red_op == RedOp::kProduct ||
                    e.red_op == RedOp::kAdasum)) {
          rs.error = "reduction op " +
                     std::to_string(static_cast<int>(e.red_op)) +
                     " does not support joined-rank zero contribution";
        } else if ((e.type == OpType::kAllreduce ||
                    e.type == OpType::kReducescatter) &&
                   e.dtype == DataType::kInt8) {
          rs.error =
              "int8 wire format does not support joined-rank zero "
              "contribution";
        }
      }
      std::vector<int32_t> mv = ProcessSetRanks(e.process_set_id);
      std::set<int32_t> members(mv.begin(), mv.end());
      if (!rs.error.empty() || used_joined || pc.predicted != members) {
        suppress = false;
      }
      comp_responses.push_back(std::move(rs));
      ReleaseFront(n, pc);
    }
    FuseResponses(&comp_responses);
    bool any_error = false;
    for (const Response& r : comp_responses) {
      if (!r.error.empty()) any_error = true;
    }
    if (suppress && !comp_responses.empty() && !any_error) {
      // Every member rank announced this whole component as a
      // PREDICTED confirmation: each already executed the identical
      // locally predicted schedule, so emit only the hash of the
      // would-be response bytes — the response-side half of killing
      // the round trip.
      ResponseList bare;
      bare.responses = std::move(comp_responses);
      std::vector<uint8_t> blob = SerializeResponseList(bare);
      out.confirm_hashes.push_back(Fnv1a64(blob.data(), blob.size()));
    } else {
      for (Response& r : comp_responses) {
        out.responses.push_back(std::move(r));
      }
    }
  }

  // 4b. pending tensors that can never complete because a REQUIRED
  //     rank announced shutdown fail promptly with an error response
  //     (parity: the reference's "Horovod has been shut down" error)
  //     instead of stalling the remaining ranks to the transport
  //     timeout.
  if (!shutdown_ranks_.empty()) {
    std::vector<std::string> keys;
    for (const auto& kv : message_table_) keys.push_back(kv.first);
    for (const std::string& key : keys) {
      auto qit = message_table_.find(key);
      if (qit == message_table_.end() || qit->second.empty()) continue;
      const PendingCoordination& front = qit->second.front();
      int32_t dead_rank = -1;
      for (int32_t r : ProcessSetRanks(front.entry.process_set_id)) {
        if (!front.ranks.count(r) && !joined_ranks_.count(r) &&
            shutdown_ranks_.count(r)) {
          dead_rank = r;
          break;
        }
      }
      if (dead_rank < 0) continue;
      PendingCoordination pc = std::move(qit->second.front());
      const Entry& e = pc.entry;
      Response rs;
      rs.type = e.type;
      rs.red_op = e.red_op;
      rs.dtype = e.dtype;
      rs.process_set_id = e.process_set_id;
      rs.root_rank = e.root_rank;
      rs.tensor_names.push_back(e.name);
      rs.tensor_shapes.push_back(e.shape);
      rs.error = "rank " + std::to_string(dead_rank) + " has shut down";
      out.responses.push_back(std::move(rs));
      ReleaseFront(key, pc);
    }
  }

  // 4. join: once every rank joined, emit the last joiner (parity:
  //    operations.cc join handling returns the last joined rank).
  if (static_cast<int32_t>(joined_ranks_.size()) >= size_ && size_ > 0) {
    out.join_last_rank = last_joined_rank_;
    joined_ranks_.clear();
    last_joined_rank_ = -1;
  }
  // Global quiesce only when EVERY rank announced shutdown (parity:
  // horovod_shutdown coordinating via DONE requests — a finishing
  // rank's controller keeps serving peers until all agree to stop).
  if (static_cast<int32_t>(shutdown_ranks_.size()) >= size_ && size_ > 0) {
    out.shutdown = true;
  }
  return out;
}

void Controller::FuseResponses(std::vector<Response>* responses) const {
  // Compatibility-GROUP fusion (parity: Controller::FuseResponses,
  // strengthened): every fusible response merges into the open group
  // for its (type, red_op, dtype, process set) key — not just
  // adjacent ones — so an unrelated response (another process set's
  // release landing in the same compute) cannot split an otherwise-
  // stable fusion group.  That order-independence is what makes
  // steady-state schedule prediction sound (see PredictResponses).
  // Output order is group-opening order; a group that would exceed
  // the fusion threshold closes and a new one opens at the end.
  // Allreduce/adasum only (allgather fusion needs size tables).
  std::vector<Response> fused;
  std::map<std::tuple<int, int, int, int32_t>, size_t> open_group;
  for (Response& r : *responses) {
    bool can_fuse =
        (r.type == OpType::kAllreduce || r.type == OpType::kAdasum) &&
        r.error.empty();
    if (can_fuse) {
      auto key = std::make_tuple(static_cast<int>(r.type),
                                 static_cast<int>(r.red_op),
                                 static_cast<int>(r.dtype),
                                 r.process_set_id);
      auto it = open_group.find(key);
      if (it != open_group.end() &&
          fused[it->second].total_bytes + r.total_bytes <=
              fusion_threshold_) {
        Response& g = fused[it->second];
        g.tensor_names.insert(g.tensor_names.end(),
                              r.tensor_names.begin(),
                              r.tensor_names.end());
        g.tensor_shapes.insert(g.tensor_shapes.end(),
                               r.tensor_shapes.begin(),
                               r.tensor_shapes.end());
        g.total_bytes += r.total_bytes;
        continue;
      }
      open_group[key] = fused.size();
    }
    fused.push_back(std::move(r));
  }
  *responses = std::move(fused);
}

std::vector<uint8_t> Controller::PredictResponses(
    const std::vector<uint32_t>& bits) {
  // The ResponseList the coordinator WILL emit for a pure bypass
  // cycle carrying exactly `bits` — a deterministic function of the
  // (replicated) response cache and the fusion threshold.  Empty
  // result = unknown bit (caller must not predict).  Only sound under
  // the Python controller's gating; see eager/controller.py.
  std::lock_guard<std::mutex> g(mu_);
  std::vector<Entry> entries;
  entries.reserve(bits.size());
  for (uint32_t b : bits) {
    Entry e;
    if (!cache_.GetEntryForBit(b, &e)) return {};
    entries.push_back(std::move(e));
  }
  std::sort(entries.begin(), entries.end(),
            [](const Entry& a, const Entry& b) {
              return TableKey(a) < TableKey(b);
            });
  ResponseList out;
  for (const Entry& e : entries) {
    Response rs;
    rs.type = e.type;
    rs.red_op = e.red_op;
    rs.dtype = e.dtype;
    rs.process_set_id = e.process_set_id;
    rs.root_rank = e.root_rank;
    rs.tensor_names.push_back(e.name);
    rs.tensor_shapes.push_back(e.shape);
    rs.total_bytes = e.nbytes();
    out.responses.push_back(std::move(rs));
  }
  FuseResponses(&out.responses);
  return SerializeResponseList(out);
}

std::vector<uint64_t> Controller::FinishNames(
    const std::vector<std::string>& names) {
  // Eagerly retire in-flight entries executed from a PREDICTED
  // schedule (duplicate-name guard would otherwise trip on the next
  // step's re-enqueue before the real response streams in).
  return queue_.Finish(names);
}

std::vector<uint8_t> Controller::ComputeResponses() {
  std::lock_guard<std::mutex> g(mu_);
  return SerializeResponseList(BuildResponseList());
}

ResponseList Controller::ApplyResponses(const uint8_t* data, size_t len,
                                        std::vector<uint64_t>* out_finished) {
  ResponseList rl = ParseResponseList(data, len);
  for (const Response& rs : rl.responses) {
    // Cache insertion in response order — identical on every rank, so
    // bit ids stay globally consistent (see header comment).  The entry
    // is rebuilt entirely from the response (incl. echoed shapes), so
    // the signature matches what Enqueue computes next cycle.
    for (size_t i = 0; i < rs.tensor_names.size(); ++i) {
      if (rs.type == OpType::kBarrier || rs.type == OpType::kJoin) continue;
      Entry e;
      e.name = rs.tensor_names[i];
      e.type = rs.type;
      e.red_op = rs.red_op;
      e.dtype = rs.dtype;
      if (i < rs.tensor_shapes.size()) e.shape = rs.tensor_shapes[i];
      e.process_set_id = rs.process_set_id;
      e.root_rank = rs.root_rank;
      cache_.Put(ResponseCache::Signature(e), e);
    }
    std::vector<uint64_t> seqs = queue_.Finish(rs.tensor_names);
    out_finished->insert(out_finished->end(), seqs.begin(), seqs.end());
  }
  if (rl.cache_resync_needed) {
    // Coordinator failed to expand a bypass bit: next drain is a full
    // resync re-announcing whatever is still outstanding (set AFTER
    // the Finish pops above, so completed ops are not re-announced).
    resync_flush_ = true;
  }
  if (rl.join_last_rank >= 0) joined_ = false;
  return rl;
}

std::vector<StallEntry> Controller::CheckStalls() const {
  std::lock_guard<std::mutex> g(mu_);
  std::vector<StallEntry> out;
  double now = NowSeconds();
  for (const auto& kv : message_table_) {
    if (kv.second.empty()) continue;
    const PendingCoordination& pc = kv.second.front();
    double waited = now - pc.first_seen_s;
    if (waited < stall_warn_s_) continue;
    StallEntry se;
    se.name = pc.entry.name;
    se.waiting_s = waited;
    for (int32_t r : ProcessSetRanks(pc.entry.process_set_id)) {
      // Joined ranks are implicitly present (they zero-contribute).
      if (pc.ranks.count(r) || joined_ranks_.count(r))
        se.present_ranks.push_back(r);
      else
        se.missing_ranks.push_back(r);
    }
    out.push_back(std::move(se));
  }
  return out;
}

}  // namespace hvt
