"""Spark integration surface, local-mode functional.

Parity surface: ``horovod.spark`` (horovod/spark/__init__.py /
runner.py + common/ + torch/ + keras/) — ``run(fn)`` executes one
Horovod rank per process and returns per-rank results, and the
Estimator surface (``TorchEstimator``/``KerasEstimator`` over a
``Store`` + ``Backend``) gives DataFrame-in → trained-Model-out.

TPU pods are launched by ``hvtpurun`` / the cluster scheduler, so a
Spark-executor *placement* backend is out of scope (SURVEY.md §7.3);
everything else is the same API executed in **local mode**: ranks are
launched as local worker processes through the hvtpurun machinery (the
reference itself runs its estimator CI on local-mode Spark — SURVEY
§4's localhost-as-cluster pattern), DataFrames are pandas/dict frames
(pyspark frames accepted when pyspark is importable), and Petastorm's
role is played by columnar npz materialization in the Store
(``common.data``).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from .common import (  # noqa: F401
    Backend,
    EstimatorParams,
    FilesystemStore,
    HorovodEstimator,
    HorovodModel,
    LocalBackend,
    LocalStore,
    SparkBackend,
    Store,
)
from .keras import KerasEstimator, KerasModel  # noqa: F401
from .torch import TorchEstimator, TorchModel  # noqa: F401


def run(
    fn: Callable,
    args: tuple = (),
    kwargs: Optional[Dict[str, Any]] = None,
    num_proc: Optional[int] = None,
    extra_mpi_args=None,        # accepted for source compat; unused
    env: Optional[Dict[str, str]] = None,
    start_timeout: Optional[float] = None,
    verbose: int = 0,
    cpu_devices: Optional[int] = 1,
) -> List[Any]:
    """Run ``fn`` on ``num_proc`` ranks and return per-rank results.

    Local-mode execution via the hvtpurun launcher: same signature
    shape and return convention as the reference's
    ``horovod.spark.run`` (fn rides pickle to each rank; results come
    back ordered by rank).  ``cpu_devices`` defaults to 1 XLA CPU
    device per rank — pass None to let workers see the real
    accelerator (single-host only).
    """
    from .. import runner

    return runner.run(
        fn, args=args, kwargs=kwargs, np=num_proc or 2,
        cpu_devices=cpu_devices, env=env, verbose=bool(verbose),
        start_timeout=start_timeout,
    )


def run_elastic(
    fn: Callable,
    args: tuple = (),
    kwargs: Optional[Dict[str, Any]] = None,
    num_proc: Optional[int] = None,
    min_np: Optional[int] = None,
    max_np: Optional[int] = None,
    env: Optional[Dict[str, str]] = None,
    start_timeout: Optional[float] = None,
    verbose: int = 0,
    cpu_devices: Optional[int] = 1,
) -> List[Any]:
    """Run ``fn`` under the elastic driver (parity:
    ``horovod.spark.run_elastic``): ``fn`` follows the elastic contract
    (``hvd.elastic.State`` + ``@hvd.elastic.run``), and membership
    changes restart it from the last commit.  Local-mode execution —
    Spark-executor *placement* stays out of scope (SURVEY.md §7.3),
    exactly as with :func:`run`."""
    from .. import runner

    return runner.run_elastic(
        fn, args=args, kwargs=kwargs, num_proc=num_proc or 2,
        min_np=min_np, max_np=max_np, cpu_devices=cpu_devices,
        env=env, start_timeout=start_timeout, verbose=bool(verbose),
    )
