// Minimal fixture twin of native/src/message.h (wire-twin clean case).
#pragma once
#include <cstdint>

namespace hvt {

constexpr uint32_t kRequestMagic = 0x52545648;
constexpr uint32_t kResponseMagic = 0x50545648;
constexpr uint32_t kWireVersion = 3;

}  // namespace hvt
