"""hvtpusim: run the hvtpu control plane at virtual scale.

``python -m tools.hvtpusim run <scenario> --ranks N --seed S`` executes
one named chaos scenario (see ``list``) on the deterministic fabric
simulator and prints per-phase virtual-time stats plus the event-log
digest; ``bench`` produces the measured control-plane scaling rows
(negotiation cycle / rendezvous / drain commit vs world size) recorded
in BENCH_SCALING.json.  docs/simulation.md documents the architecture
and the determinism/replay contract.
"""
