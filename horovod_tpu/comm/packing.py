"""Shared flat-buffer pack/unpack used by every fused collective path
(the memcpy-in/out of the reference's fusion buffer,
horovod/common/ops/collective_operations.cc MemcpyInFusionBuffer /
MemcpyOutFusionBuffer — here expressed as XLA concat/slice that fuse
into the surrounding program).

Zero-copy fusion-buffer plane (see docs/design.md "Zero-copy fusion
buffers"): :class:`ExchangeBuffer` is the persistent host exchange
buffer of the reference's FusionBufferManager, pooled per
(process-set, fused-spec) by :class:`FusionBufferPool` and filled at
*enqueue* time by the eager controller once a steady predicted
schedule fixes each op's offset before the burst drains.  Offsets are
dtype-aligned (:func:`assign_offsets`) so every unpack is a view —
never the silent ``tobytes()`` copy of :func:`unpack_bytes`'s
unaligned fallback — and the drain-time unpack is one cached jitted
program (:func:`group_unpack_program`) whose slice/reshape/cast fuse
into the consumer's own XLA program instead of running as an eager
per-tensor copy loop."""

from __future__ import annotations

import functools
import os
import threading
from collections import OrderedDict
from typing import Any, List, Optional, Sequence, Tuple

import jax.numpy as jnp


def pack_flat(tensors: Sequence[Any]):
    """Concatenate tensors into one flat buffer in the promoted dtype.

    Returns (flat, specs) where specs = [(shape, dtype, size), ...] in
    input order.
    """
    tensors = [jnp.asarray(t) for t in tensors]
    if not tensors:
        raise ValueError("pack_flat requires at least one tensor")
    compute_dtype = jnp.result_type(*[t.dtype for t in tensors])
    flat = jnp.concatenate([t.reshape(-1).astype(compute_dtype) for t in tensors])
    specs = [(tuple(t.shape), t.dtype, t.size) for t in tensors]
    return flat, specs


def unpack_flat(flat, specs) -> List[Any]:
    """Inverse of pack_flat: slice, reshape, and cast back."""
    outs, off = [], 0
    for shape, dtype, size in specs:
        outs.append(flat[off : off + size].reshape(shape).astype(dtype))
        off += size
    return outs


def pack_bytes(raws, parallel: bool = True):
    """Pack host arrays into ONE uint8 buffer, byte-exact per dtype
    (the native-thread-pool fused path of broadcast_parameters /
    broadcast_variables — unlike :func:`pack_flat` there is no dtype
    promotion: each leaf's bytes ride verbatim).

    ``raws``: numpy arrays (any dtype incl. ml_dtypes bf16).  Returns
    ``(buf, specs)`` with specs = [(shape, dtype, nbytes), ...].
    NOTE: shapes are recorded BEFORE ``ascontiguousarray``, which
    promotes 0-d arrays to 1-d — the bug this helper exists to fix
    exactly once.
    """
    import numpy as np

    shapes = [r.shape for r in raws]
    vals = [np.ascontiguousarray(r) for r in raws]
    views = [v.reshape(-1).view(np.uint8) for v in vals]
    buf = np.empty(sum(v.nbytes for v in views), np.uint8)
    if parallel:
        from ..native import core as native_core

        native_core.parallel_gather(
            memoryview(buf), [memoryview(v) for v in views]
        )
    else:  # pragma: no cover - used only where native core is absent
        off = 0
        for v in views:
            buf[off:off + v.nbytes] = v
            off += v.nbytes
    specs = [(s, v.dtype, v.nbytes)
             for s, v in zip(shapes, vals)]
    return buf, specs


def unpack_bytes(buf, specs, offsets: Optional[Sequence[int]] = None):
    """Inverse of :func:`pack_bytes` → list of numpy arrays (views
    where alignment allows, copies otherwise).  ``offsets`` overrides
    the contiguous layout with explicit byte offsets (the aligned
    layout of :func:`assign_offsets`, under which the view path
    always applies)."""
    import numpy as np

    out = []
    off = 0
    for i, (shape, dtype, nbytes) in enumerate(specs):
        if offsets is not None:
            off = offsets[i]
        chunk = buf[off:off + nbytes]
        try:
            piece = chunk.view(dtype).reshape(shape)
        except ValueError:  # unaligned offset for this dtype
            piece = np.frombuffer(
                chunk.tobytes(), dtype=dtype
            ).reshape(shape)
        out.append(piece)
        off += nbytes
    return out


# ---------------------------------------------------------------------------
# zero-copy fusion-buffer plane
# ---------------------------------------------------------------------------

#: Pool-capacity knob: how many idle exchange buffers FusionBufferPool
#: keeps across all layouts before evicting the least recently used.
POOL_KNOB = "HVTPU_FUSION_BUFFER_POOL"


def _byte_specs(specs):
    import numpy as np

    return [(tuple(shape), np.dtype(dtype), int(nbytes))
            for shape, dtype, nbytes in specs]


def assign_offsets(specs, align: Optional[int] = None
                   ) -> Tuple[List[int], int]:
    """Byte offsets for packing ``specs`` = [(shape, dtype, nbytes),
    ...] into one buffer, each offset padded up to the group's max
    itemsize (or ``align``) so ``unpack_bytes``'s view path always
    applies — the aligned-offset contract of the zero-copy plane.
    Returns ``(offsets, total_bytes)``; for a uniform-dtype group the
    padding is zero and the layout is exactly the contiguous one."""
    import numpy as np

    specs = _byte_specs(specs)
    if align is None:
        align = max((np.dtype(d).itemsize for _s, d, _n in specs),
                    default=1)
    align = max(1, int(align))
    offsets, off = [], 0
    for _shape, _dtype, nbytes in specs:
        off = -(-off // align) * align
        offsets.append(off)
        off += nbytes
    return offsets, -(-off // align) * align


class ExchangeBuffer:
    """One persistent host exchange buffer for a fused group (parity:
    the reference's FusionBufferManager buffer).  ``write(i, arr)`` is
    the group's entire MemcpyInFusionBuffer for op ``i`` — a single
    byte copy to a dtype-aligned offset assigned at construction, so
    the eager controller can pack payloads at *enqueue* time, before
    the burst drains.  ``typed_view()`` exposes the filled payload as
    one wire-dtype array for the fused collective (uniform-dtype
    groups, the only kind the controller fuses)."""

    __slots__ = ("specs", "offsets", "nbytes", "buf", "_filled")

    def __init__(self, specs):
        import numpy as np

        self.specs = _byte_specs(specs)
        self.offsets, self.nbytes = assign_offsets(self.specs)
        self.buf = np.empty(self.nbytes, np.uint8)
        self._filled: set = set()

    def layout_key(self):
        return tuple(self.specs)

    def reset(self):
        self._filled.clear()

    def write(self, i: int, arr) -> bool:
        """Pack op ``i``'s bytes at its assigned offset; False when the
        slot was already filled (a stale plan — caller falls back)."""
        import numpy as np

        if i in self._filled:
            return False
        shape, dtype, nbytes = self.specs[i]
        a = np.ascontiguousarray(arr)
        if a.dtype != dtype or a.nbytes != nbytes:
            return False
        off = self.offsets[i]
        self.buf[off:off + nbytes] = a.reshape(-1).view(np.uint8)
        self._filled.add(i)
        return True

    def complete(self) -> bool:
        return len(self._filled) == len(self.specs)

    def typed_view(self):
        """The whole payload as one 1-D wire-dtype array (requires the
        uniform-dtype layout the controller's fuser guarantees)."""
        dtype = self.specs[0][1]
        if any(d != dtype for _s, d, _n in self.specs):
            raise ValueError("typed_view requires a uniform-dtype group")
        return self.buf.view(dtype)

    def element_specs(self):
        """(shape, dtype, element-count) triples in pack_flat's spec
        form, for :func:`group_unpack_program`."""
        return [(shape, dtype, nbytes // dtype.itemsize)
                for shape, dtype, nbytes in self.specs]

    def views(self):
        """Host-side unpack: per-op numpy VIEWS of the buffer (the
        aligned offsets make the view path unconditional)."""
        return unpack_bytes(self.buf, self.specs, offsets=self.offsets)


class FusionBufferPool:
    """LRU pool of :class:`ExchangeBuffer`\\ s keyed per
    (process-set id, fused-spec layout) — the same keying as the
    memoized allreduce routing plans in comm/eager.py — bounded by the
    ``HVTPU_FUSION_BUFFER_POOL`` knob.  Thread-safe: the controller's
    enqueue thread acquires while the executor thread releases."""

    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            capacity = int(os.environ.get(POOL_KNOB, "16"))
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        # (psid, layout) -> stack of idle buffers; OrderedDict order is
        # the LRU order across keys.
        self._idle: "OrderedDict[tuple, list]" = OrderedDict()
        self._pooled = 0

    def acquire(self, psid: int, specs) -> ExchangeBuffer:
        key = (psid, tuple(_byte_specs(specs)))
        with self._lock:
            stack = self._idle.get(key)
            if stack:
                self._idle.move_to_end(key)
                self._pooled -= 1
                buf = stack.pop()
                if not stack:
                    del self._idle[key]
                buf.reset()
                return buf
        return ExchangeBuffer(specs)

    def release(self, psid: int, xb: ExchangeBuffer):
        key = (psid, xb.layout_key())
        xb.reset()
        with self._lock:
            self._idle.setdefault(key, []).append(xb)
            self._idle.move_to_end(key)
            self._pooled += 1
            while self._pooled > self.capacity:
                _k, stack = next(iter(self._idle.items()))
                stack.pop(0)
                self._pooled -= 1
                if not stack:
                    del self._idle[_k]

    def clear(self):
        with self._lock:
            self._idle.clear()
            self._pooled = 0

    def stats(self) -> dict:
        with self._lock:
            return {"pooled": self._pooled, "capacity": self.capacity,
                    "layouts": len(self._idle)}


@functools.lru_cache(maxsize=128)
def _unpack_program(specs_key):
    import jax

    def run(flat):
        outs, off = [], 0
        for shape, dtype, size in specs_key:
            outs.append(flat[off:off + size].reshape(shape).astype(dtype))
            off += size
        return tuple(outs)

    return jax.jit(run)


def group_unpack_program(specs):
    """ONE cached jitted program slicing/reshaping/casting every piece
    of a fused wire result — the deferred MemcpyOutFusionBuffer of the
    zero-copy plane.  Keyed by the (shape, dtype, size) spec tuple, so
    steady-state drains reuse the compiled artifact; the cache is
    dropped with the routing plans on mispredict
    (comm/eager.invalidate_routing_plans)."""
    key = tuple((tuple(s), jnp.dtype(d), int(n)) for s, d, n in specs)
    return _unpack_program(key)


def clear_unpack_cache() -> None:
    """Drop the memoized group-unpack programs (mispredict/membership
    invalidation rides comm/eager.invalidate_routing_plans)."""
    _unpack_program.cache_clear()
