"""``horovod_tpu.tensorflow.keras`` — the tf.keras frontend (parity:
``horovod/tensorflow/keras/__init__.py``).

The reference ships the keras surface twice — ``horovod.keras`` for
standalone keras and ``horovod.tensorflow.keras`` for ``tf.keras`` —
sharing one implementation under ``horovod/_keras/``.  Here the shared
implementation lives in ``horovod_tpu.keras`` (keras 3 serves both
roles); this package keeps the reference's canonical import path
working unchanged::

    import horovod_tpu.tensorflow.keras as hvd

    hvd.init()
    opt = hvd.DistributedOptimizer(keras.optimizers.SGD(0.01 * hvd.size()))
"""

from __future__ import annotations

from ...keras import *  # noqa: F401,F403
from ...keras import DistributedOptimizer  # noqa: F401
from . import callbacks  # noqa: F401  (pin the local shim module)
from . import elastic  # noqa: F401
