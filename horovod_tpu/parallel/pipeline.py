"""Pipeline parallelism: GPipe microbatch schedule over a mesh axis.

Out of the reference's scope (SURVEY.md §2.7: PP absent) but
first-class here.  SPMD formulation: every pp stage runs the *same*
compiled program (no per-stage programs, no send/recv runtime); stage
identity is ``lax.axis_index(pp)``, activations advance one stage per
schedule tick via ``lax.ppermute`` (neighbour ICI transfer), and the
tick loop is a ``lax.scan`` — so the whole pipeline, fill and drain
included, is one XLA computation that autodiff reverses into the
backward pipeline automatically.

Schedule: classic GPipe.  ``M`` microbatches over ``S`` stages take
``M + S - 1`` ticks; bubble fraction ``(S-1)/(M+S-1)``.  Stage 0 feeds
microbatch ``t`` at tick ``t``; the last stage emits microbatch
``t-(S-1)`` at tick ``t``; a final ``psum`` replicates the collected
outputs to every stage so loss/backward code is stage-oblivious.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], Any],
    stage_params: Any,
    microbatches: jax.Array,
    axis_name: str,
    *,
    with_aux: bool = False,
):
    """Run ``stage_fn`` as a GPipe pipeline over ``axis_name``.

    Args:
      stage_fn: ``(params, x) -> y`` (or ``(params, x) -> (y, aux)``
        with ``with_aux=True``, ``aux`` a scalar accumulated over all
        valid (non-bubble) stage executions and psum'd over the pp
        axis).  ``y`` must have the same shape/dtype as ``x`` (the
        usual transformer-block invariant).
      stage_params: THIS stage's parameters (pytree) — i.e. already
        sharded over the pp axis outside shard_map with the stage dim
        consumed.
      microbatches: ``[M, ...]`` input microbatches, replicated over the
        pp axis (only stage 0 reads them).
      axis_name: the pp mesh axis.

    Returns:
      ``[M, ...]`` stage-``S-1`` outputs, replicated to all stages
      (plus the accumulated aux scalar when ``with_aux``).
    """
    n_stages = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    n_micro = microbatches.shape[0]
    ticks = n_micro + n_stages - 1

    # Scan requires carry input/output types (incl. varying-axis sets)
    # to match.  Outputs vary over this pp axis (stage masks, ppermute)
    # plus every axis the microbatches or stage params vary over; build
    # a zero carrying exactly that union and fold it into the inits.
    zp = sum(
        ((leaf * 0).sum().astype(jnp.float32)
         for leaf in jax.tree_util.tree_leaves(stage_params)),
        start=jnp.zeros((), jnp.float32),
    )
    zero = (
        zp
        + (microbatches * 0).sum().astype(jnp.float32)
        + (lax.axis_index(axis_name) * 0).astype(jnp.float32)
    )
    x0 = jnp.zeros_like(microbatches[0]) + zero.astype(microbatches.dtype)
    out0 = jnp.zeros_like(microbatches) + zero.astype(microbatches.dtype)
    aux0 = zero

    fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(carry, t):
        x_in, outs, aux_acc = carry
        # Stage 0 sources microbatch t (clamped; masked past M).
        mb = lax.dynamic_index_in_dim(
            microbatches, jnp.clip(t, 0, n_micro - 1), axis=0, keepdims=False
        )
        feed = jnp.where(t < n_micro, mb, jnp.zeros_like(mb))
        x = jnp.where(stage == 0, feed, x_in)
        res = stage_fn(stage_params, x)
        y, aux = res if with_aux else (res, jnp.zeros((), jnp.float32))
        # Stage s does useful work for microbatch t-s at ticks
        # s <= t < s + M; bubble executions contribute nothing.
        useful = jnp.logical_and(t >= stage, t < stage + n_micro)
        aux_acc = aux_acc + jnp.where(useful, aux, 0.0)
        # Last stage writes microbatch t-(S-1) once the pipe is full.
        out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
        valid = jnp.logical_and(stage == n_stages - 1, t >= n_stages - 1)
        cur = lax.dynamic_index_in_dim(outs, out_idx, axis=0, keepdims=False)
        outs = lax.dynamic_update_index_in_dim(
            outs, jnp.where(valid, y, cur), out_idx, axis=0
        )
        # Advance the pipe: my output becomes stage+1's next input.
        x_next = lax.ppermute(y, axis_name, fwd_perm)
        return (x_next, outs, aux_acc), None

    (_, outs, aux_acc), _ = lax.scan(tick, (x0, out0, aux0),
                                     jnp.arange(ticks))
    # Replicate the last stage's collected outputs to every stage.
    outs = lax.psum(
        jnp.where(stage == n_stages - 1, outs, jnp.zeros_like(outs)),
        axis_name,
    )
    if with_aux:
        return outs, lax.psum(aux_acc, axis_name)
    return outs


def bubble_fraction(n_micro: int, n_stages: int) -> float:
    """GPipe bubble overhead for a given schedule size."""
    return (n_stages - 1) / (n_micro + n_stages - 1)
