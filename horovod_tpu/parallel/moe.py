"""Expert parallelism: Switch-style top-1 MoE with all_to_all dispatch.

Out of the reference's scope (SURVEY.md §2.7: EP absent; its
``hvd.alltoall`` is the primitive EP is built from).  TPU-first
formulation per GShard/Switch: routing is dense einsum algebra over
one-hot dispatch/combine tensors (MXU-friendly, static shapes,
capacity-bounded), and the only communication is a pair of
``lax.all_to_all``s over the ``ep`` axis — tokens travel to their
expert's device and back in two ICI hops.

Capacity discipline: each expert accepts at most
``C = ceil(tokens_per_device * capacity_factor / E)`` tokens from each
ep peer; overflow tokens fall through the residual connection (standard
Switch behaviour — keeps every shape static for XLA).
"""

from __future__ import annotations

import math
from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def switch_route(
    x: jax.Array,
    gate_w: jax.Array,
    num_experts: int,
    capacity: int,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Top-1 routing: returns (dispatch [N,E,C] bool-ish one-hot,
    combine [N,E,C] weights, aux load-balancing loss scalar)."""
    n = x.shape[0]
    logits = jnp.einsum("nd,de->ne", x.astype(jnp.float32), gate_w)
    probs = jax.nn.softmax(logits, axis=-1)  # [N, E]
    expert_idx = jnp.argmax(probs, axis=-1)  # [N]
    gate = jnp.take_along_axis(probs, expert_idx[:, None], axis=1)[:, 0]
    onehot = jax.nn.one_hot(expert_idx, num_experts, dtype=jnp.float32)
    # Position of each token within its expert's queue.
    pos = (jnp.cumsum(onehot, axis=0) - onehot) * onehot  # [N, E]
    pos_in_expert = pos.sum(axis=-1).astype(jnp.int32)  # [N]
    keep = pos_in_expert < capacity
    dispatch = (
        onehot
        * keep[:, None].astype(jnp.float32)
    )[..., None] * jax.nn.one_hot(
        pos_in_expert, capacity, dtype=jnp.float32
    )[:, None, :]  # [N, E, C]
    combine = dispatch * gate[:, None, None]
    # Switch aux loss: E * Σ_e fraction_tokens_e · mean_prob_e.
    frac = onehot.mean(axis=0)
    mean_prob = probs.mean(axis=0)
    aux = num_experts * jnp.sum(frac * mean_prob)
    return dispatch, combine, aux


def expert_parallel_moe(
    x: jax.Array,
    gate_w: jax.Array,
    expert_params: Any,
    expert_fn: Callable[[Any, jax.Array], jax.Array],
    axis_name: str,
    *,
    num_experts: int,
    capacity_factor: float = 1.25,
) -> Tuple[jax.Array, jax.Array]:
    """Switch-MoE layer over the ``ep`` mesh axis (inside shard_map).

    Args:
      x: local tokens ``[N, D]`` (flatten batch×seq before calling).
      gate_w: router weights ``[D, E]`` (replicated).
      expert_params: pytree stacked ``[E_local, ...]`` — this device's
        ``E_local = E/ep`` experts' params.
      expert_fn: ``(params_one_expert, tokens [C', D]) -> [C', D]``.
      axis_name: the ep mesh axis.
      num_experts: E, total experts across the ep group.

    Returns:
      (output ``[N, D]``, aux load-balancing loss scalar).
    """
    ep = lax.axis_size(axis_name)
    if num_experts % ep != 0:
        raise ValueError(f"E={num_experts} not divisible by ep={ep}")
    e_local = num_experts // ep
    n, d = x.shape
    capacity = max(1, math.ceil(n * capacity_factor / num_experts))

    dispatch, combine, aux = switch_route(x, gate_w, num_experts, capacity)
    # Gather each expert's token queue: [E, C, D].
    sent = jnp.einsum("nec,nd->ecd", dispatch, x.astype(jnp.float32))
    # ep-th of the E dim goes to each peer; received queues stack along
    # capacity: [E, C, D] -> [E_local, ep*C, D].
    recv = lax.all_to_all(
        sent, axis_name, split_axis=0, concat_axis=1, tiled=True
    )
    recv = recv.astype(x.dtype)
    # Run this device's experts over their queues.
    out = jax.vmap(expert_fn)(expert_params, recv)  # [E_local, ep*C, D]
    # Return trip + weighted combine back into token order.
    back = lax.all_to_all(
        out.astype(jnp.float32), axis_name, split_axis=1, concat_axis=0,
        tiled=True,
    )  # [E, C, D]
    y = jnp.einsum("nec,ecd->nd", combine, back)
    return y.astype(x.dtype), aux
