"""Correctness tests for horovod_tpu.parallel on an 8-device CPU mesh.

Pattern per SURVEY.md §4: SPMD test bodies, localhost-as-cluster (8
virtual XLA CPU devices).  Every sharded implementation is checked
against a dense single-device reference to tolerance.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu import parallel as par


def dense_attention(q, k, v, causal=False):
    # q,k,v: [B, H, T, D]
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        t = q.shape[2]
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


def ring_mesh(n=8):
    return Mesh(np.asarray(jax.devices()[:n], dtype=object), ("sp",))


# ---------------------------------------------------------------------------
# mesh layout
# ---------------------------------------------------------------------------

class TestMeshLayout:
    def test_make_layout_shapes(self):
        lay = par.make_layout(jax.devices(), dp=2, tp=2, pp=2)
        assert lay.mesh.shape == {"pp": 2, "dp": 2, "tp": 2}
        assert lay.axis("sp") == "tp"  # sp shares the tp group
        assert lay.axis("ep") == "dp"  # ep shares the dp group
        assert lay.axis_size("sp") == 2

    def test_dedicated_sp_axis(self):
        lay = par.make_layout(jax.devices(), dp=2, tp=2, sp=2)
        assert lay.axis("sp") == "sp"
        assert lay.mesh.shape["sp"] == 2

    def test_auto_layout_covers_all_devices(self):
        lay = par.auto_layout(jax.devices())
        assert int(np.prod(list(lay.mesh.shape.values()))) == 8

    def test_bad_factorization_raises(self):
        with pytest.raises(ValueError):
            par.make_layout(jax.devices(), dp=3, tp=2, pp=2)


# ---------------------------------------------------------------------------
# ring attention
# ---------------------------------------------------------------------------

class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, causal):
        mesh = ring_mesh()
        b, h, t, d = 2, 4, 64, 16
        rng = jax.random.PRNGKey(0)
        kq, kk, kv = jax.random.split(rng, 3)
        q = jax.random.normal(kq, (b, h, t, d), jnp.float32)
        k = jax.random.normal(kk, (b, h, t, d), jnp.float32)
        v = jax.random.normal(kv, (b, h, t, d), jnp.float32)

        ref = dense_attention(q, k, v, causal=causal)

        def body(q, k, v):
            return par.ring_attention(q, k, v, "sp", causal=causal)

        out = jax.jit(
            jax.shard_map(
                body, mesh=mesh,
                in_specs=(P(None, None, "sp"), P(None, None, "sp"),
                          P(None, None, "sp")),
                out_specs=P(None, None, "sp"),
            )
        )(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_grad_flows(self):
        mesh = ring_mesh()
        b, h, t, d = 1, 2, 32, 8
        q = jax.random.normal(jax.random.PRNGKey(1), (b, h, t, d))
        k = jax.random.normal(jax.random.PRNGKey(2), (b, h, t, d))
        v = jax.random.normal(jax.random.PRNGKey(3), (b, h, t, d))

        def loss_sharded(q, k, v):
            def body(q, k, v):
                o = par.ring_attention(q, k, v, "sp", causal=True)
                return lax.psum(jnp.sum(o ** 2), "sp")
            return jax.shard_map(
                body, mesh=mesh,
                in_specs=(P(None, None, "sp"),) * 3,
                out_specs=P(),
            )(q, k, v)

        def loss_dense(q, k, v):
            return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

        g_sharded = jax.jit(jax.grad(loss_sharded))(q, k, v)
        g_dense = jax.grad(loss_dense)(q, k, v)
        np.testing.assert_allclose(np.asarray(g_sharded),
                                   np.asarray(g_dense), rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# ulysses
# ---------------------------------------------------------------------------

class TestUlysses:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, causal):
        mesh = ring_mesh()
        b, t, h, d = 2, 64, 8, 16  # h divisible by sp=8
        rng = jax.random.split(jax.random.PRNGKey(7), 3)
        # activation layout [B, T, H, D]
        q = jax.random.normal(rng[0], (b, t, h, d))
        k = jax.random.normal(rng[1], (b, t, h, d))
        v = jax.random.normal(rng[2], (b, t, h, d))

        ref = dense_attention(
            q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3), causal=causal,
        ).transpose(0, 2, 1, 3)

        def body(q, k, v):
            return par.ulysses_attention(q, k, v, "sp", causal=causal)

        out = jax.jit(
            jax.shard_map(
                body, mesh=mesh,
                in_specs=(P(None, "sp"),) * 3,
                out_specs=P(None, "sp"),
            )
        )(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_head_divisibility_check(self):
        mesh = ring_mesh()
        q = jnp.ones((1, 8, 4, 4))  # 4 heads, sp=8 → error

        def body(q):
            return par.ulysses_attention(q, q, q, "sp")

        with pytest.raises(ValueError, match="not divisible"):
            jax.jit(
                jax.shard_map(body, mesh=mesh, in_specs=P(None, "sp"),
                              out_specs=P(None, "sp"))
            )(q)


# ---------------------------------------------------------------------------
# tensor parallel
# ---------------------------------------------------------------------------

class TestTensorParallel:
    def test_column_then_row_matches_dense(self):
        mesh = Mesh(np.asarray(jax.devices(), dtype=object).reshape(8),
                    ("tp",))
        bsz, f_in, f_hidden, f_out = 4, 16, 64, 16
        rng = jax.random.split(jax.random.PRNGKey(0), 4)
        x = jax.random.normal(rng[0], (bsz, f_in))
        w1 = jax.random.normal(rng[1], (f_in, f_hidden)) / np.sqrt(f_in)
        b1 = jax.random.normal(rng[2], (f_hidden,))
        w2 = jax.random.normal(rng[3], (f_hidden, f_out)) / np.sqrt(f_hidden)

        ref = jax.nn.gelu(x @ w1 + b1) @ w2

        def body(x, w1, b1, w2):
            h = jax.nn.gelu(par.column_parallel(x, w1, b1))
            return par.row_parallel(h, w2, "tp")

        out = jax.jit(
            jax.shard_map(
                body, mesh=mesh,
                in_specs=(P(), P(None, "tp"), P("tp"), P("tp", None)),
                out_specs=P(),
            )
        )(x, w1, b1, w2)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# pipeline
# ---------------------------------------------------------------------------

class TestPipeline:
    def test_matches_sequential(self):
        n_stages, n_micro = 4, 8
        mesh = Mesh(np.asarray(jax.devices()[:n_stages], dtype=object),
                    ("pp",))
        d = 16
        rng = jax.random.split(jax.random.PRNGKey(5), n_stages + 1)
        ws = jnp.stack([
            jax.random.normal(rng[i], (d, d)) / np.sqrt(d)
            for i in range(n_stages)
        ])  # [S, d, d]
        x = jax.random.normal(rng[-1], (n_micro, 4, d))  # [M, B_mb, d]

        def stage_fn(w, x):
            return jnp.tanh(x @ w)

        # dense reference: stages applied in order to each microbatch
        ref = x
        for i in range(n_stages):
            ref = stage_fn(ws[i], ref)

        def body(ws_local, mb):
            w = ws_local[0]  # [1, d, d] shard -> this stage's weights
            return par.pipeline_apply(stage_fn, w, mb, "pp")

        out = jax.jit(
            jax.shard_map(
                body, mesh=mesh,
                in_specs=(P("pp"), P()),
                out_specs=P(),
            )
        )(ws, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_grad_matches_sequential(self):
        n_stages, n_micro, d = 2, 4, 8
        mesh = Mesh(np.asarray(jax.devices()[:n_stages], dtype=object),
                    ("pp",))
        rng = jax.random.split(jax.random.PRNGKey(9), 3)
        ws = jnp.stack([jax.random.normal(rng[i], (d, d)) / np.sqrt(d)
                        for i in range(n_stages)])
        x = jax.random.normal(rng[2], (n_micro, 2, d))

        def stage_fn(w, x):
            return jnp.tanh(x @ w)

        def loss_pp(ws):
            def body(ws_local, mb):
                out = par.pipeline_apply(stage_fn, ws_local[0], mb, "pp")
                return jnp.sum(out ** 2)
            return jax.shard_map(
                body, mesh=mesh, in_specs=(P("pp"), P()), out_specs=P(),
            )(ws, x)

        def loss_seq(ws):
            y = x
            for i in range(n_stages):
                y = stage_fn(ws[i], y)
            return jnp.sum(y ** 2)

        g_pp = jax.jit(jax.grad(loss_pp))(ws)
        g_seq = jax.grad(loss_seq)(ws)
        np.testing.assert_allclose(np.asarray(g_pp), np.asarray(g_seq),
                                   rtol=1e-4, atol=1e-4)

    def test_bubble_fraction(self):
        assert par.bubble_fraction(8, 4) == pytest.approx(3 / 11)


# ---------------------------------------------------------------------------
# expert parallel MoE
# ---------------------------------------------------------------------------

class TestMoE:
    def test_routing_capacity_and_onehot(self):
        n, d, e, c = 16, 8, 4, 4
        x = jax.random.normal(jax.random.PRNGKey(0), (n, d))
        gw = jax.random.normal(jax.random.PRNGKey(1), (d, e))
        dispatch, combine, aux = par.switch_route(x, gw, e, c)
        assert dispatch.shape == (n, e, c)
        # each token dispatched at most once
        assert np.all(np.asarray(dispatch.sum(axis=(1, 2))) <= 1.0 + 1e-6)
        # each (expert, slot) holds at most one token
        assert np.all(np.asarray(dispatch.sum(axis=0)) <= 1.0 + 1e-6)
        assert float(aux) > 0

    def test_identity_experts_roundtrip(self):
        """With identity experts and ample capacity, MoE output ==
        gate_prob * x for every kept token — verifies the all_to_all
        dispatch/return plumbing exactly."""
        ep = 4
        mesh = Mesh(np.asarray(jax.devices()[:ep], dtype=object), ("ep",))
        n, d, e = 32, 8, 8
        x = jax.random.normal(jax.random.PRNGKey(3), (ep * n, d))
        gw = jax.random.normal(jax.random.PRNGKey(4), (d, e))
        # identity expert: params are [E_local] dummies
        params = jnp.zeros((e // ep * ep,))  # placeholder, resharded below
        params_local = jnp.zeros((e,))

        def expert_fn(p, tokens):
            del p
            return tokens

        def body(x_local):
            out, aux = par.expert_parallel_moe(
                x_local, gw, jnp.zeros((e // ep,)), expert_fn, "ep",
                num_experts=e, capacity_factor=4.0,
            )
            return out, lax.pmean(aux, "ep")

        out, aux = jax.jit(
            jax.shard_map(body, mesh=mesh, in_specs=P("ep"),
                          out_specs=(P("ep"), P()))
        )(x)
        # reference: per-shard routing with identity experts
        outs = []
        for s in range(ep):
            xs = x[s * n:(s + 1) * n]
            cap = max(1, int(np.ceil(n * 4.0 / e)))
            dispatch, combine, _ = par.switch_route(xs, gw, e, cap)
            outs.append(np.einsum("nec,ecd->nd",
                                  np.asarray(combine),
                                  np.einsum("nec,nd->ecd",
                                            np.asarray(dispatch),
                                            np.asarray(xs))))
        ref = np.concatenate(outs, axis=0)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4,
                                   atol=1e-5)
