"""Ray integration hook (out of scope for the TPU build; SURVEY.md
§7.3).  The reference's ``RayExecutor`` places ranks via Ray placement
groups; TPU jobs are launched by ``hvtpurun`` / GKE instead.  The API
hook is kept so code probing for it degrades clearly.
"""

from __future__ import annotations

_MSG = (
    "horovod_tpu does not ship a Ray integration: TPU workers are "
    "launched by hvtpurun (see horovod_tpu.runner) or your cluster "
    "scheduler. The horovod.ray surface is documented out of scope in "
    "SURVEY.md §7.3."
)


class RayExecutor:  # pragma: no cover - stub surface
    def __init__(self, *args, **kwargs):
        raise NotImplementedError(_MSG)


class ElasticRayExecutor:  # pragma: no cover - stub surface
    def __init__(self, *args, **kwargs):
        raise NotImplementedError(_MSG)
