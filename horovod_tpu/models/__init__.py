from .mlp import MLP
from .transformer import (
    TransformerConfig,
    init_params as transformer_init_params,
    make_loss_fn as transformer_loss_fn,
    make_train_step as transformer_train_step,
    param_specs as transformer_param_specs,
)
from .resnet import (
    ResNet,
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet101,
    ResNet152,
)
from .vgg import VGG, VGG16, VGG19
from .inception import InceptionV3

__all__ = [
    "MLP",
    "TransformerConfig",
    "transformer_init_params",
    "transformer_loss_fn",
    "transformer_train_step",
    "transformer_param_specs",
    "ResNet",
    "ResNet18",
    "ResNet34",
    "ResNet50",
    "ResNet101",
    "ResNet152",
    "VGG", "VGG16", "VGG19",
    "InceptionV3",
]
