"""metrics-catalog pass: registered metrics vs docs vs bench contract.

Three sources, checked in both directions:

  * registered: literal first arguments of counter()/gauge()/
    histogram() calls under horovod_tpu/, plus op_counter() — the one
    dynamic registration, `hvtpu_{kind}_total`, expanded over the
    collective kinds (the kind_to_type map in eager/controller.py
    plus literal op_counter call sites)
  * cataloged: every `hvtpu_*` token in docs/observability.md
  * required: bench.py REQUIRED_METRIC_KEYS (the bench-guard contract)

Findings: registered-but-uncataloged, cataloged-but-unregistered, and
required keys missing from either side.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Set, Tuple

from . import Finding, Project

PASS = "metrics-catalog"

SCAN_DIRS = ("horovod_tpu",)
OBS_MD = "docs/observability.md"
BENCH_PY = "bench.py"
CONTROLLER_PY = "horovod_tpu/eager/controller.py"

_REGISTER_FUNCS = {"counter", "gauge", "histogram"}
_METRIC_TOKEN_RE = re.compile(r"\bhvtpu_\w+\b")


def _func_name(func: ast.expr):
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _collective_kinds(project: Project) -> Set[str]:
    """Keys of the kind_to_type dict in eager/controller.py — the
    closed set of values op_counter() is called with dynamically."""
    kinds: Set[str] = set()
    tree = project.parse(CONTROLLER_PY)
    if tree is None:
        return kinds
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "kind_to_type"
                and isinstance(node.value, ast.Dict)):
            for key in node.value.keys:
                if isinstance(key, ast.Constant) and isinstance(key.value, str):
                    kinds.add(key.value)
    return kinds


def registered_metrics(project: Project) -> Dict[str, Tuple[str, int]]:
    """Metric name -> (file, line) of one registration site."""
    out: Dict[str, Tuple[str, int]] = {}
    kinds = _collective_kinds(project)
    for path in project.py_files(*SCAN_DIRS):
        tree = project.parse(path)
        if tree is None:
            continue
        rel = project.rel(path)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fname = _func_name(node.func)
            if fname in _REGISTER_FUNCS and node.args:
                arg = node.args[0]
                if (isinstance(arg, ast.Constant)
                        and isinstance(arg.value, str)
                        and arg.value.startswith("hvtpu_")):
                    out.setdefault(arg.value, (rel, node.lineno))
            elif fname == "op_counter" and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                    out.setdefault(f"hvtpu_{arg.value}_total",
                                   (rel, node.lineno))
                else:
                    # dynamic kind: expands over the collective kinds
                    for kind in kinds:
                        out.setdefault(f"hvtpu_{kind}_total",
                                       (rel, node.lineno))
    return out


def cataloged_metrics(text: str) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        for m in _METRIC_TOKEN_RE.finditer(line):
            out.setdefault(m.group(0), lineno)
    return out


def required_keys(project: Project) -> List[str]:
    tree = project.parse(BENCH_PY)
    if tree is None:
        return []
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "REQUIRED_METRIC_KEYS"):
            try:
                return [str(v) for v in ast.literal_eval(node.value)]
            except ValueError:
                return []
    return []


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    obs_text = project.read(OBS_MD)
    if obs_text is None:
        findings.append(project.missing(PASS, OBS_MD))
        return findings

    registered = registered_metrics(project)
    cataloged = cataloged_metrics(obs_text)
    required = required_keys(project)

    for name, (rel, line) in sorted(registered.items()):
        if name not in cataloged:
            findings.append(Finding(
                PASS, rel, line, name,
                f"metric {name} is registered but missing from {OBS_MD}"))
    for name, line in sorted(cataloged.items()):
        if name not in registered:
            findings.append(Finding(
                PASS, OBS_MD, line, name,
                f"metric {name} is cataloged but never registered — "
                "stale doc or a renamed registration"))
    if not required:
        findings.append(Finding(
            PASS, BENCH_PY, 0, "required-metric-keys",
            "REQUIRED_METRIC_KEYS not found in bench.py — the bench "
            "contract the metrics-catalog pass cross-checks is gone"))
    for name in required:
        if name not in registered:
            findings.append(Finding(
                PASS, BENCH_PY, 0, f"required:{name}",
                f"bench REQUIRED_METRIC_KEYS entry {name} is not a "
                "registered metric"))
        if name not in cataloged:
            findings.append(Finding(
                PASS, BENCH_PY, 0, f"required-doc:{name}",
                f"bench REQUIRED_METRIC_KEYS entry {name} is missing "
                f"from {OBS_MD}"))
    return findings
