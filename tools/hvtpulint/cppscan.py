"""Lightweight lexical C++ scanning for the wire-twin pass.

This is deliberately NOT a C++ parser.  The native sources follow the
project style guide (one constant per line, brace-on-same-line
function bodies, ``w.u32(...)`` writer calls), and the scanner leans
on that.  If the style drifts far enough that these regexes miss, the
wire-twin pass fails closed with a missing-surface finding rather
than silently passing.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

_COMMENT_RE = re.compile(r"//[^\n]*|/\*.*?\*/", re.S)
_CONST_RE = re.compile(
    r"constexpr\s+(?:uint32_t|uint64_t|int32_t|int64_t|int|unsigned)\s+"
    r"(k\w+)\s*=\s*(0x[0-9a-fA-F]+|\d+)\s*;")
_ENUM_RE = re.compile(
    r"enum\s+class\s+(\w+)\s*:\s*\w+\s*\{(.*?)\}\s*;", re.S)
_ENUM_MEMBER_RE = re.compile(r"k(\w+)\s*=\s*(\d+)")
# Writer calls: `w.u32(expr)` / `w.str(expr)` — the receiver is always
# a local named `w` in message.cc.
_WRITE_RE = re.compile(r"\bw\.(u8|u32|i32|i64|u64|f64|str)\s*\(")
_WRITE_ENTRY_RE = re.compile(r"\bWriteEntry\s*\(")


def strip_comments(src: str) -> str:
    return _COMMENT_RE.sub("", src)


def constants(src: str) -> Dict[str, int]:
    """All `constexpr <int-type> kFoo = <literal>;` declarations."""
    out: Dict[str, int] = {}
    for m in _CONST_RE.finditer(strip_comments(src)):
        out[m.group(1)] = int(m.group(2), 0)
    return out


def const_line(src: str, name: str) -> int:
    for i, line in enumerate(src.splitlines(), 1):
        if name in line:
            return i
    return 0


def enums(src: str) -> Dict[str, Dict[str, int]]:
    """`enum class Name : <type> { kA = 0, ... }` bodies.

    Members without an explicit `= value` take previous+1, mirroring
    C++ semantics, so the scan survives a style change even though the
    sources currently spell every value out.
    """
    out: Dict[str, Dict[str, int]] = {}
    for m in _ENUM_RE.finditer(strip_comments(src)):
        members: Dict[str, int] = {}
        next_val = 0
        for item in m.group(2).split(","):
            item = item.strip()
            if not item:
                continue
            em = _ENUM_MEMBER_RE.search(item)
            if em:
                members[em.group(1)] = int(em.group(2))
                next_val = int(em.group(2)) + 1
            else:
                nm = re.match(r"k(\w+)", item)
                if nm:
                    members[nm.group(1)] = next_val
                    next_val += 1
        out[m.group(1)] = members
    return out


def function_body(src: str, name: str) -> Optional[str]:
    """Extract the brace-balanced body of the first function whose
    signature line contains ``name(``."""
    clean = strip_comments(src)
    idx = clean.find(name + "(")
    while idx != -1:
        brace = clean.find("{", idx)
        semi = clean.find(";", idx)
        if brace == -1:
            return None
        if semi != -1 and semi < brace:
            # A declaration, not a definition — keep looking.
            idx = clean.find(name + "(", semi)
            continue
        depth = 0
        for i in range(brace, len(clean)):
            if clean[i] == "{":
                depth += 1
            elif clean[i] == "}":
                depth -= 1
                if depth == 0:
                    return clean[brace:i + 1]
        return None
    return None


def write_sequence(body: str) -> List[str]:
    """Ordered writer-op sequence of a serialize function body.

    Returns tokens like ``u32``/``i64``/``str`` plus ``entry`` for
    nested WriteEntry calls.  Loops collapse to their element ops —
    the twin check compares shapes of the write programs, and both
    sides express repetition the same way (count prefix + loop)."""
    events: List[Tuple[int, str]] = []
    for m in _WRITE_RE.finditer(body):
        events.append((m.start(), m.group(1)))
    for m in _WRITE_ENTRY_RE.finditer(body):
        events.append((m.start(), "entry"))
    events.sort()
    return [op for _, op in events]


def datatype_size_map(src: str) -> Tuple[Dict[str, int], Optional[int]]:
    """Parse the DataTypeSize() switch.

    Returns ({enum-member: size}, default-size-or-None); members
    covered by the ``default:`` label take the default size."""
    body = function_body(src, "DataTypeSize")
    if body is None:
        return {}, None
    out: Dict[str, int] = {}
    default: Optional[int] = None
    pending: List[str] = []
    saw_default = False
    for line in body.splitlines():
        for cm in re.finditer(r"case\s+DataType::k(\w+)\s*:", line):
            pending.append(cm.group(1))
        if re.search(r"\bdefault\s*:", line):
            saw_default = True
        rm = re.search(r"return\s+(\d+)\s*;", line)
        if rm:
            for name in pending:
                out[name] = int(rm.group(1))
            pending = []
            if saw_default:
                default = int(rm.group(1))
                saw_default = False
    return out, default
