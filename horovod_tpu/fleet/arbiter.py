"""The multi-job resource arbiter: one pool, N jobs, gang scheduling,
priority preemption via graceful drain, autoscaling hooks.

The arbiter promotes the one-job ElasticDriver into a fleet: it owns a
:class:`~horovod_tpu.elastic.discovery.HostManager` over the POOL's
discovery (the same cooldown-blacklist machinery the single-job driver
uses) and divides the discovered slots among jobs.  Everything is
driven by :meth:`tick` — a pure, lock-held scheduling pass over
arbiter state — so the production loop (:meth:`run`, real clock
thread), the CLI server, tier-1 fake-clock tests, and the fabric
simulator (a kernel task calling ``tick()`` on virtual time) all run
the SAME logic.

Scheduling policy (deterministic by construction):

- **Gang scheduling.**  A job launches only when its full ``min_np``
  allocation is free — never a partial gang.  Pending jobs are visited
  in (priority desc, submit order) order; a small job behind a starved
  big one may backfill (no slot is held idle waiting), because the big
  one acquires its gang through preemption, not accumulation.
- **Start-time expansion.**  When every pending job has been placed,
  freshly-started jobs widen toward ``max_np`` with the leftover slots
  (free — the job has not launched yet).  Already-RUNNING jobs never
  auto-expand; growth is the autoscaler's (or an operator's) call,
  because a grow costs the job a commit-boundary reset.
- **Priority preemption.**  A pending job that cannot fit may reclaim
  slots from strictly-lower-priority RUNNING jobs, shrinking each
  victim toward its ``min_np`` — never evicting below it.  Victim
  order is lowest priority first, and within a tier the YOUNGEST job
  (highest submit_seq) yields first; ``submit_seq`` is unique, so
  selection is a total order (the tie-break determinism tests pin
  this).  The shrink rides the planned-drain channel: per-rank
  ``core/preempt.py`` notice files → coordinated emergency commit →
  ``DRAIN_EXIT_CODE`` exits → a resize with zero lost steps and no
  restart-budget or blacklist strike.  If the drain grace expires, the
  arbiter escalates (SIGTERM) and the victim pays a charged restart.
- **Fail fast.**  A pending job whose ``min_np`` exceeds the pool's
  total discovered capacity can never run; it FAILs immediately with a
  diagnostic naming both numbers.

Thread safety: ``_lock`` guards all arbiter state; ``tick``/``submit``
/``cancel``/``debug_state`` take it.  Job handles have their own
internal locks and never call back into the arbiter.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
from typing import Callable, Dict, List, Optional

from ..core import clock
from ..elastic.discovery import HostManager
from ..obs import metrics as obs_metrics
from . import job as job_mod
from .autoscale import Autoscaler
from .job import (DONE, DRAINING, FAILED, FleetSpecError, Job, JobSpec,
                  PENDING, RESIZING, RUNNING, STATES)

__all__ = ["FleetArbiter"]

_M_JOBS = obs_metrics.gauge(
    "hvtpu_fleet_jobs",
    "Fleet jobs per lifecycle state (label: state).")
_M_SLOTS_TOTAL = obs_metrics.gauge(
    "hvtpu_fleet_pool_slots_total",
    "Schedulable slots in the fleet pool (discovered minus "
    "blacklist-cooldown hosts).")
_M_SLOTS_USED = obs_metrics.gauge(
    "hvtpu_fleet_pool_slots_used",
    "Pool slots currently allocated to live jobs.")
_M_PREEMPTIONS = obs_metrics.counter(
    "hvtpu_fleet_preemptions_total",
    "Planned shrinks the arbiter issued on lower-priority jobs "
    "(priority preemption + autoscale shrinks), via the graceful-"
    "drain channel.")
_M_QUEUE_WAIT = obs_metrics.histogram(
    "hvtpu_fleet_queue_wait_seconds",
    "Submit-to-launch wait per job: how long the gang waited for its "
    "full min-world allocation.")
_M_RESIZE_S = obs_metrics.histogram(
    "hvtpu_fleet_resize_seconds",
    "Arbiter-initiated resize latency: shrink request to the victim "
    "running again at its new size.")
_M_AUTOSCALE = obs_metrics.counter(
    "hvtpu_fleet_autoscale_events_total",
    "Autoscale decisions applied (label: direction = grow | shrink).")
_M_JOB_STEP_RATE = obs_metrics.gauge(
    "hvtpu_fleet_job_step_rate",
    "Per-job EWMA optimizer steps/second from the latest fleet health "
    "summary (label: job; 0 until the job publishes).")
_M_JOB_INCIDENTS = obs_metrics.gauge(
    "hvtpu_fleet_job_incidents",
    "Per-job total anomaly incidents from the latest fleet health "
    "summary (label: job).")
_M_JOB_STALL_AGE = obs_metrics.gauge(
    "hvtpu_fleet_job_stall_age_seconds",
    "Per-job stall age from the latest fleet health summary: seconds "
    "since the last completed step while a newer stall warning is "
    "outstanding; 0 when healthy (label: job).")


class FleetArbiter:
    """One shared pool serving N prioritised elastic jobs."""

    def __init__(self, discovery, *,
                 fleet_dir: Optional[str] = None,
                 tick_s: Optional[float] = None,
                 drain_grace_s: Optional[float] = None,
                 runner_factory: Optional[Callable[[Job], object]] = None,
                 event_fn: Optional[Callable[..., None]] = None,
                 blacklist_cooldown: Optional[float] = None,
                 verbose: bool = False,
                 register_debug: bool = True,
                 health_client=None):
        self.hosts = HostManager(discovery,
                                 cooldown_base_s=blacklist_cooldown)
        if fleet_dir is None:
            fleet_dir = os.environ.get("HVTPU_FLEET_DIR")
        self.fleet_dir = fleet_dir
        if tick_s is None:
            tick_s = float(
                os.environ.get("HVTPU_FLEET_TICK_SECONDS", "1") or 1)
        self.tick_s = tick_s
        if drain_grace_s is None:
            drain_grace_s = float(
                os.environ.get("HVTPU_FLEET_DRAIN_GRACE_SECONDS", "30")
                or 30)
        self.drain_grace_s = drain_grace_s
        self._event_fn = event_fn
        self.verbose = verbose
        if runner_factory is None:
            base = (os.path.join(fleet_dir, "jobs") if fleet_dir
                    else tempfile.mkdtemp(prefix="hvtpu_fleet_"))

            def runner_factory(j, _base=base):
                from .runner import ElasticJobRunner

                return ElasticJobRunner(j, _base, verbose=self.verbose)

        self._runner_factory = runner_factory
        # Optional KV client reaching the jobs' prefixed health keys
        # (fleet/health.py): each tick pulls fleet/<job>/health and
        # folds it into state.json + the per-job fleet gauges.
        self._health_client = health_client
        self._lock = threading.RLock()
        self.jobs: Dict[str, Job] = {}  # hvtpulint: guarded-by(_lock)
        self._autoscalers: Dict[str, Autoscaler] = {}  # hvtpulint: guarded-by(_lock)
        self._submit_seq = 0  # hvtpulint: guarded-by(_lock)
        self._pool_seen = False  # hvtpulint: guarded-by(_lock)
        self._stop = threading.Event()
        self._registered_debug = register_debug
        if register_debug:
            obs_metrics.register_debug_provider("fleet", self.debug_state)

    # -- events ---------------------------------------------------------
    def _event(self, kind: str, **fields) -> None:
        if self._event_fn is not None:
            self._event_fn(f"fleet.{kind}", **fields)
        if self.verbose:
            detail = " ".join(f"{k}={v}" for k, v in fields.items())
            print(f"hvtpu.fleet: {kind} {detail}", flush=True)

    # -- submit / cancel -------------------------------------------------
    def submit(self, spec: JobSpec) -> Job:
        """Queue a validated spec; duplicate live names are rejected
        (the name keys the state dir and KV prefix)."""
        with self._lock:
            return self._submit_locked(spec)

    def _submit_locked(self, spec: JobSpec) -> Job:  # hvtpulint: requires(_lock)
        existing = self.jobs.get(spec.name)
        if existing is not None and not existing.terminal:
            raise FleetSpecError(
                "name", f"job {spec.name!r} already exists "
                f"(state {existing.state})")
        self._submit_seq += 1
        job = Job(spec, self._submit_seq)
        self.jobs[spec.name] = job
        if spec.autoscale is not None:
            asc = Autoscaler.from_spec(spec.autoscale)
            if asc is not None:
                self._autoscalers[spec.name] = asc
            else:
                self._event("autoscale_unconfigured", job=spec.name)
        self._event("submit", job=spec.name, priority=spec.priority,
                    min_np=spec.min_np, max_np=spec.max_np)
        return job

    def attach_autoscaler(self, name: str, autoscaler: Autoscaler
                          ) -> None:
        with self._lock:
            if name not in self.jobs:
                raise KeyError(f"unknown job {name!r}")
            self._autoscalers[name] = autoscaler

    def cancel(self, name: str) -> bool:
        with self._lock:
            return self._cancel_locked(name)

    def _cancel_locked(self, name: str) -> bool:  # hvtpulint: requires(_lock)
        job = self.jobs.get(name)
        if job is None or job.terminal:
            return False
        job.cancelled = True
        if job.state == PENDING:
            job.to(FAILED, reason="cancelled")
        elif job.handle is not None:
            job.handle.stop()  # whole-job graceful drain
        self._event("cancel", job=name, state=job.state)
        return True

    # -- the scheduling pass ---------------------------------------------
    def tick(self) -> None:
        """One full arbiter pass: spool intake → pool refresh → reap →
        fail-fast → gang schedule (+preempt) → autoscale → publish."""
        with self._lock:
            self._intake_spool()
            self._refresh_pool()
            self._reap()
            self._fail_oversized()
            self._schedule()
            self._autoscale_tick()
            self._poll_health()
            self._publish()

    def _refresh_pool(self) -> None:  # hvtpulint: requires(_lock)
        try:
            self.hosts.refresh()
        except Exception as e:  # noqa: BLE001 — transient discovery failure
            self._event("discovery_error", error=str(e)[:200])
            return
        if self.hosts.last_found:
            self._pool_seen = True

    def _live_jobs(self) -> List[Job]:  # hvtpulint: requires(_lock)
        return [j for j in self.jobs.values() if not j.terminal]

    def _free_map(self) -> Dict[str, int]:  # hvtpulint: requires(_lock)
        """host → unallocated schedulable slots (negative clamped: a
        pool that shrank below its allocations frees nothing)."""
        free = dict(self.hosts.current)
        for j in self._live_jobs():
            for h, n in j.allocation.items():
                if h in free:
                    free[h] -= n
        return {h: n for h, n in free.items() if n > 0}

    @staticmethod
    def _take(free: Dict[str, int], n: int) -> Dict[str, int]:
        """Deterministically carve ``n`` slots out of ``free`` (hosts
        in sorted name order)."""
        out: Dict[str, int] = {}
        for h in sorted(free):
            if n <= 0:
                break
            got = min(free[h], n)
            if got > 0:
                out[h] = got
                free[h] -= got
                n -= got
        return out

    def _reap(self) -> None:  # hvtpulint: requires(_lock)
        """Adopt every handle's view: exits, phase changes, live
        allocations, charged restarts, drain-grace escalation."""
        now = clock.monotonic()
        for j in self._live_jobs():
            h = j.handle
            if h is None:
                continue
            j.charged_restarts = j.restarts_base + h.charged_restarts
            code = h.poll()
            if code is not None:
                j.exit_code = code
                j.allocation = {}
                if j.cancelled:
                    j.to(FAILED, reason="cancelled")
                elif code == 0:
                    j.to(DONE)
                else:
                    j.to(FAILED, reason=f"exit {code}")
                self._event("job_end", job=j.name, state=j.state,
                            code=code,
                            charged_restarts=j.charged_restarts)
                continue
            phase = h.phase()
            if j.state == DRAINING:
                if phase == "resizing":
                    j.to(RESIZING)
                elif phase == "running" and h.target_np() is None:
                    # drain landed and the relaunch won the race with
                    # this tick
                    self._finish_resize(j, now)
                elif (j.shrink_deadline is not None
                      and now >= j.shrink_deadline
                      and not j.shrink_escalated):
                    j.shrink_escalated = True
                    n = h.escalate()
                    self._event("drain_grace_expired", job=j.name,
                                signalled=n)
            elif j.state == RESIZING and phase == "running":
                self._finish_resize(j, now)
            elif j.state == RUNNING and phase == "resizing":
                # an external event (spot reclaim drain, crash) is
                # resizing the job without the arbiter asking
                j.to(RESIZING)
            j.allocation = h.allocation()

    def _finish_resize(self, j: Job, now: float) -> None:
        j.to(RUNNING)
        if j.shrink_started_t is not None:
            _M_RESIZE_S.observe(now - j.shrink_started_t)
            self._event("resized", job=j.name,
                        np=j.handle.current_np(),
                        resize_s=round(now - j.shrink_started_t, 6))
        j.shrink_started_t = None
        j.shrink_deadline = None
        j.shrink_escalated = False

    def _fail_oversized(self) -> None:  # hvtpulint: requires(_lock)
        """A gang that can NEVER fit (min_np > the pool's total
        discovered capacity) fails fast with both numbers named."""
        if not self._pool_seen:
            return
        capacity = sum(self.hosts.last_found.values())
        for j in self._live_jobs():
            if j.state == PENDING and j.spec.min_np > capacity:
                j.to(FAILED, reason=(
                    f"min_np={j.spec.min_np} can never fit: the pool "
                    f"has {capacity} total slots"))
                self._event("job_unschedulable_fatal", job=j.name,
                            min_np=j.spec.min_np, capacity=capacity)

    def _schedule(self) -> None:  # hvtpulint: requires(_lock)
        pending = sorted(
            (j for j in self.jobs.values() if j.state == PENDING),
            key=lambda j: (-j.spec.priority, j.submit_seq))
        started: List[Job] = []
        all_placed = True
        for j in pending:
            free = self._free_map()
            total = sum(free.values())
            if total >= j.spec.min_np:
                alloc = self._take(free, j.spec.min_np)
                self._start_job(j, alloc)
                started.append(j)
            else:
                all_placed = False
                self._maybe_preempt(j, total)
        # start-time expansion: only when nothing is left waiting
        if all_placed:
            for j in sorted(started,
                            key=lambda j: (-j.spec.priority,
                                           j.submit_seq)):
                self._expand_at_start(j)
        # launch AFTER expansion so each gang starts once, full-width
        for j in started:
            j.handle.start(j.allocation)
            self._event("job_start", job=j.name,
                        np=sum(j.allocation.values()),
                        queue_wait_s=round(j.queue_wait_s or 0.0, 6))

    def _start_job(self, j: Job, alloc: Dict[str, int]) -> None:
        j.allocation = alloc
        j.handle = self._runner_factory(j)
        j.to(RUNNING)
        if j.queue_wait_s is not None:
            _M_QUEUE_WAIT.observe(j.queue_wait_s)

    def _expand_at_start(self, j: Job) -> None:  # hvtpulint: requires(_lock)
        free = self._free_map()
        total = sum(free.values())
        cur = sum(j.allocation.values())
        cap = j.spec.max_np if j.spec.max_np is not None else cur + total
        extra = min(cap - cur, total)
        if extra <= 0:
            return
        more = self._take(free, extra)
        for h, n in more.items():
            j.allocation[h] = j.allocation.get(h, 0) + n

    def _maybe_preempt(self, j: Job, free_total: int) -> None:  # hvtpulint: requires(_lock)
        """Reclaim ``min_np - free`` slots from strictly-lower-priority
        RUNNING jobs, shrinking each toward its min.  Victim order:
        priority asc, then YOUNGEST first (submit_seq desc) — a unique
        total order."""
        need = j.spec.min_np - free_total
        victims = sorted(
            (v for v in self.jobs.values()
             if v.state == RUNNING and v.handle is not None
             and v.spec.priority < j.spec.priority),
            key=lambda v: (v.spec.priority, -v.submit_seq))
        plan = []
        for v in victims:
            if need <= 0:
                break
            cur = sum(v.allocation.values())
            reclaim = min(cur - v.spec.min_np, need)
            if reclaim > 0:
                plan.append((v, cur - reclaim))
                need -= reclaim
        if need > 0:
            if not j.unschedulable_reported:
                j.unschedulable_reported = True
                self._event("job_waiting", job=j.name,
                            min_np=j.spec.min_np, free=free_total,
                            missing=need)
            return
        j.unschedulable_reported = False
        for v, new_np in plan:
            self._start_shrink(v, new_np,
                               reason=f"preempted for {j.name}")

    def _start_shrink(self, v: Job, new_np: int, reason: str) -> None:
        if not v.handle.request_shrink(new_np):
            return  # between incarnations; retried next tick
        now = clock.monotonic()
        v.preemptions += 1
        v.shrink_started_t = now
        v.shrink_deadline = now + self.drain_grace_s
        v.shrink_escalated = False
        v.to(DRAINING, reason=reason)
        _M_PREEMPTIONS.inc()
        self._event("preempt", victim=v.name, to_np=new_np,
                    reason=reason)

    def _autoscale_tick(self) -> None:  # hvtpulint: requires(_lock)
        now = clock.monotonic()
        for name in sorted(self._autoscalers):
            asc = self._autoscalers[name]
            j = self.jobs.get(name)
            if j is None or j.state != RUNNING or j.handle is None:
                continue
            decision = asc.evaluate(now)
            if decision is None:
                continue
            direction, step = decision
            cur = sum(j.allocation.values())
            if direction == "grow":
                free = self._free_map()
                cap = (j.spec.max_np if j.spec.max_np is not None
                       else cur + sum(free.values()))
                extra = min(step, cap - cur, sum(free.values()))
                if extra <= 0:
                    continue
                more = self._take(free, extra)
                alloc = dict(j.allocation)
                for h, n in more.items():
                    alloc[h] = alloc.get(h, 0) + n
                j.allocation = alloc
                j.handle.update_allocation(alloc)
                _M_AUTOSCALE.inc(direction="grow")
                self._event("autoscale", job=name, direction="grow",
                            np=sum(alloc.values()),
                            signal=asc.last_signal)
            else:
                new_np = max(j.spec.min_np, cur - step)
                if new_np >= cur:
                    continue
                _M_AUTOSCALE.inc(direction="shrink")
                self._event("autoscale", job=name, direction="shrink",
                            np=new_np, signal=asc.last_signal)
                self._start_shrink(j, new_np, reason="autoscale")

    # -- crash recovery ---------------------------------------------------
    def recover(self) -> int:
        """Resume from a previous arbiter incarnation's ``state.json``:
        every non-terminal job is resubmitted as PENDING with its
        restart/preemption accounting restored.  Worker processes were
        children of the dead arbiter, so there is nothing to adopt —
        the next tick gang-launches each recovered job afresh and its
        elastic state dir (the durable commit plane) makes the resume
        exact.  Terminal jobs stay forgotten (their record lives in
        the event log).  Returns the number of jobs recovered; a
        missing or unreadable state.json recovers nothing."""
        d = self.fleet_dir
        if not d:
            return 0
        try:
            with open(os.path.join(d, "state.json")) as f:
                state = json.load(f)
        except (OSError, ValueError):
            return 0
        recovered = 0
        with self._lock:
            for row in state.get("jobs", []):
                if not isinstance(row, dict) or row.get("state") in (
                        DONE, FAILED):
                    continue
                spec_d = row.get("spec")
                if not isinstance(spec_d, dict):
                    # a pre-spec state.json (older arbiter): the job
                    # cannot be reconstructed — surface, don't guess
                    self._event("recover_skipped",
                                job=str(row.get("name")),
                                error="state.json row carries no spec")
                    continue
                try:
                    spec = JobSpec.from_dict(spec_d)
                except FleetSpecError as e:
                    self._event("recover_skipped",
                                job=str(row.get("name")),
                                error=str(e)[:300])
                    continue
                existing = self.jobs.get(spec.name)
                if existing is not None and not existing.terminal:
                    continue  # already resubmitted (idempotent recover)
                job = self._submit_locked(spec)
                try:
                    job.preemptions = int(row.get("preemptions") or 0)
                    job.restarts_base = int(
                        row.get("charged_restarts") or 0)
                    job.charged_restarts = job.restarts_base
                except (TypeError, ValueError):
                    pass
                recovered += 1
                self._event("recover", job=job.name,
                            prior_state=row.get("state"))
        return recovered

    # -- spool protocol (CLI ↔ arbiter) ----------------------------------
    def _intake_spool(self) -> None:  # hvtpulint: requires(_lock)
        d = self.fleet_dir
        if not d:
            return
        sub = os.path.join(d, "submit")
        if os.path.isdir(sub):
            for fn in sorted(os.listdir(sub)):
                if not fn.endswith(".json"):
                    continue
                path = os.path.join(sub, fn)
                try:
                    spec = JobSpec.load(path)
                except FleetSpecError as e:
                    self._reject(fn, str(e))
                else:
                    existing = self.jobs.get(spec.name)
                    if (existing is not None and not existing.terminal
                            and existing.spec.to_dict()
                            == spec.to_dict()):
                        # this exact submit already landed — an
                        # arbiter that crashed between intake and
                        # unlink (or recover() beat the spool to it).
                        # Consume the file instead of rejecting the
                        # live job's own spec as a duplicate.
                        self._event("spool_duplicate", spool=fn,
                                    job=spec.name)
                    else:
                        try:
                            self._submit_locked(spec)
                        except FleetSpecError as e:
                            self._reject(fn, str(e))
                try:
                    os.unlink(path)
                except OSError:
                    pass
        can = os.path.join(d, "cancel")
        if os.path.isdir(can):
            for fn in sorted(os.listdir(can)):
                self._cancel_locked(fn)
                try:
                    os.unlink(os.path.join(can, fn))
                except OSError:
                    pass

    def _reject(self, fn: str, message: str) -> None:
        self._event("submit_rejected", spool=fn, error=message[:300])
        d = os.path.join(self.fleet_dir, "rejected")
        try:
            os.makedirs(d, exist_ok=True)
            with open(os.path.join(d, fn + ".error"), "w") as f:
                f.write(message + "\n")
        except OSError:
            pass

    def _poll_health(self) -> None:  # hvtpulint: requires(_lock)
        """Pull each live job's health summary (fleet/health.py) off
        the shared KV when one exists (the fabric simulator), else off
        the per-job health-file channel the ElasticJobRunner handle
        exposes as ``health_dir``; a missing/None read keeps the
        previous summary so one flaky tick doesn't blank the rollup."""
        from . import health as health_mod

        for j in self._live_jobs():
            summary = None
            if self._health_client is not None:
                summary = health_mod.read(self._health_client, j.name)
            if summary is None:
                hd = getattr(j.handle, "health_dir", None)
                if hd:
                    summary = health_mod.read_file(hd)
            if summary is not None:
                j.health = summary

    def _publish(self) -> None:  # hvtpulint: requires(_lock)
        counts = {s: 0 for s in STATES}
        for j in self.jobs.values():
            counts[j.state] += 1
        for s, c in counts.items():
            _M_JOBS.set(c, state=s)
        total = sum(self.hosts.current.values())
        used = sum(n for j in self._live_jobs()
                   for n in j.allocation.values())
        _M_SLOTS_TOTAL.set(total)
        _M_SLOTS_USED.set(min(used, total) if total else used)
        for j in self._live_jobs():
            h = j.health
            if h:
                _M_JOB_STEP_RATE.set(
                    float(h.get("step_rate") or 0.0), job=j.name)
                _M_JOB_INCIDENTS.set(
                    float(h.get("incidents_total") or 0.0), job=j.name)
                _M_JOB_STALL_AGE.set(
                    float(h.get("stall_age_s") or 0.0), job=j.name)
        if self.fleet_dir:
            self._write_state_json()

    def _write_state_json(self) -> None:  # hvtpulint: requires(_lock)
        state = self.debug_state_locked()
        path = os.path.join(self.fleet_dir, "state.json")
        tmp = path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(state, f, sort_keys=True, indent=1)
            os.replace(tmp, path)
        except OSError:
            pass

    # -- read side -------------------------------------------------------
    def debug_state(self) -> dict:
        with self._lock:
            return self.debug_state_locked()

    def debug_state_locked(self) -> dict:  # hvtpulint: requires(_lock)
        free = self._free_map()
        out = {
            "t_wall": round(clock.wall(), 3),
            "pool": {
                "hosts": dict(self.hosts.current),
                "blacklisted": self.hosts.blacklisted_now(),
                "slots_total": sum(self.hosts.current.values()),
                "slots_free": sum(free.values()),
            },
            "jobs": [j.info()
                     for j in sorted(self.jobs.values(),
                                     key=lambda j: j.submit_seq)],
            "autoscalers": {n: a.debug_state()
                            for n, a in sorted(
                                self._autoscalers.items())},
        }
        return out

    def all_terminal(self) -> bool:
        with self._lock:
            return bool(self.jobs) and all(
                j.terminal for j in self.jobs.values())

    # -- loop ------------------------------------------------------------
    def run(self, until_idle: bool = False) -> None:
        """Tick on ``tick_s`` cadence (through the clock seam) until
        :meth:`stop` — or, with ``until_idle``, until every submitted
        job is terminal."""
        while not self._stop.is_set():
            self.tick()
            if until_idle and self.all_terminal():
                return
            clock.sleep(self.tick_s)

    def stop(self) -> None:
        self._stop.set()

    def close(self) -> None:
        self.stop()
        if self._registered_debug:
            try:
                obs_metrics.unregister_debug_provider("fleet")
            except Exception:  # noqa: BLE001 — already unregistered
                pass


# keep the job module import visible for re-exports (fleet/__init__)
_ = job_mod
