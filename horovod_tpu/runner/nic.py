"""Network-interface discovery for the launcher.

Parity surface: ``horovod/runner/driver/driver_service.py`` — before a
multi-host launch the reference starts a driver service, has every host
probe its NICs, and intersects the routable interface set so workers
get a rendezvous address they can actually reach
(``HorovodRunDriverService`` + ``network.get_local_host_addresses``).

TPU-native scope: the coordination service lives in rank 0's worker, so
only rank 0's host needs probing — workers just need ONE address of
that host which is routable from the others.  The probe prefers
globally-scoped, up, non-loopback IPv4 interfaces from ``ip -j addr``
(with a pure-socket fallback), and ``--network-interface`` accepts an
interface NAME (resolved here, as the reference's flag does) or a
literal address.
"""

from __future__ import annotations

import json
import socket
import subprocess
from typing import List, Tuple


def local_interfaces(usable_only: bool = False) -> List[Tuple[str, str]]:
    """``[(ifname, ipv4_addr), ...]`` for this host.  Uses
    ``ip -j addr``; falls back to resolving the hostname when ``ip`` is
    unavailable (containers, macOS).

    ``usable_only=True`` keeps only addresses a remote peer could
    plausibly reach: globally-scoped (drops loopback and 169.254/…
    link-local) on interfaces that are not operationally DOWN — the
    filter the coordinator probe needs so a docker bridge or dead NIC
    listed first in ifindex order cannot silently hang the rendezvous.
    """
    try:
        out = subprocess.run(
            ["ip", "-j", "addr"], capture_output=True, text=True,
            timeout=10, check=True,
        ).stdout
        result = []
        for iface in json.loads(out):
            if usable_only and iface.get("operstate") == "DOWN":
                continue
            for info in iface.get("addr_info", []):
                if info.get("family") != "inet":
                    continue
                if usable_only and info.get("scope") != "global":
                    continue
                result.append((iface["ifname"], info["local"]))
        if result or usable_only:
            return result
    except Exception:  # noqa: BLE001 — any failure falls through
        pass
    result = [] if usable_only else [("lo", "127.0.0.1")]
    try:
        for addr in socket.gethostbyname_ex(socket.gethostname())[2]:
            if not addr.startswith("127."):
                result.append(("host", addr))
    except OSError:
        pass
    return result


def resolve_interface(nic: str) -> str:
    """``--network-interface`` value → coordinator address.  Accepts an
    interface name (``eth0`` — resolved like the reference's flag) or a
    literal address/hostname.  A value that is neither a local
    interface nor resolvable as an address raises immediately (a typo
    must not become a silent rendezvous hang)."""
    ifaces = local_interfaces()
    for ifname, addr in ifaces:
        if nic == ifname:
            return addr
    try:
        socket.getaddrinfo(nic, None)
        return nic
    except OSError:
        names = ", ".join(sorted({n for n, _ in ifaces}))
        raise ValueError(
            f"--network-interface {nic!r} is neither a local interface "
            f"(have: {names}) nor a resolvable address"
        ) from None


def _egress_addr(probe_target: str) -> str | None:
    """The local address the kernel's routing table picks to reach
    ``probe_target`` — a connect() on a UDP socket does the route
    lookup without sending a packet.  Returns None when no route."""
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect((probe_target, 9))
            return s.getsockname()[0]
    except OSError:
        return None


def probe_coordinator_addr(remote_host: str | None = None) -> str:
    """A usable (global-scope, iface up) non-loopback IPv4 address of
    this host that remote workers can plausibly reach (the reference's
    NIC intersection degenerates to this when only rank 0's host serves
    the rendezvous).

    Preference order: the EGRESS address toward ``remote_host`` (or a
    public anchor when none is given) — i.e. the interface carrying the
    actual route — then the first usable interface.  Enumeration order
    alone is a trap: a docker/VM bridge (172.17.0.1 is global scope on
    an UP interface) can sort first and silently hang remote workers
    until the rendezvous timeout.  Raises with the
    ``--network-interface`` escape hatch when no address exists."""
    usable = [a for _, a in local_interfaces(usable_only=True)
              if not a.startswith("127.")]
    if not usable:
        raise ValueError(
            "no usable non-loopback interface found for the coordinator; "
            "pass --network-interface with an address remote hosts can "
            "reach"
        )
    for target in filter(None, (remote_host, "8.8.8.8")):
        egress = _egress_addr(target)
        if egress in usable:
            return egress
    return usable[0]
