"""Torch frontend tests (parity model: test/parallel/test_torch.py in
the reference, §4 of SURVEY.md — op × dtype matrix, in-place semantics,
optimizer behavior).

This sandbox is one process, so collectives degenerate to
identity/size-1 semantics; the multi-rank data path is exercised by the
engine's own tests and by runner integration tests.  What IS fully
tested here: the torch↔engine adapter boundary (dtype/shape/layout
round-trips, in-place contracts, handle lifecycle) and the
DistributedOptimizer's hook/synchronize machinery, which is identical
code at any world size.
"""

import numpy as np
import pytest
import torch

import horovod_tpu.torch as hvd


@pytest.fixture(autouse=True)
def _init():
    hvd.init()
    yield


DTYPES = [torch.float32, torch.float64, torch.int32, torch.int64,
          torch.float16, torch.bfloat16]


class TestOps:
    @pytest.mark.parametrize("dtype", DTYPES)
    def test_allreduce_roundtrip(self, dtype):
        t = torch.arange(17).reshape(17).to(dtype)
        out = hvd.allreduce(t, name=f"ar.{dtype}")
        assert out.dtype == dtype
        assert out.shape == t.shape
        torch.testing.assert_close(out, t)

    def test_fp64_precision_warning(self):
        import warnings as _w
        import horovod_tpu.torch.mpi_ops as mo
        import jax
        if jax.config.jax_enable_x64:
            pytest.skip("x64 enabled: no precision loss to warn about")
        mo._warned_fp64 = False
        with _w.catch_warnings(record=True) as rec:
            _w.simplefilter("always")
            hvd.allreduce(torch.ones(4, dtype=torch.float64), name="w64")
        assert any("float64" in str(r.message) for r in rec)
        # warn-once contract
        with _w.catch_warnings(record=True) as rec2:
            _w.simplefilter("always")
            hvd.allreduce(torch.ones(4, dtype=torch.float64), name="w64b")
        assert not any("float64" in str(r.message) for r in rec2)

    def test_allreduce_noncontiguous(self):
        t = torch.arange(12.0).reshape(3, 4).t()  # non-contiguous view
        out = hvd.allreduce(t, name="ar.nc")
        torch.testing.assert_close(out, t)

    def test_allreduce_inplace(self):
        t = torch.ones(5)
        r = hvd.allreduce_(t, name="ar.ip")
        assert r is t
        torch.testing.assert_close(t, torch.ones(5))

    def test_allreduce_prescale(self):
        t = torch.ones(4)
        out = hvd.allreduce(t, prescale_factor=2.0, name="ar.pre")
        torch.testing.assert_close(out, 2 * torch.ones(4))

    def test_allreduce_compression_fp16(self):
        t = torch.full((8,), 0.5)
        out = hvd.allreduce(t, compression=hvd.Compression.fp16,
                            name="ar.fp16")
        assert out.dtype == torch.float32
        torch.testing.assert_close(out, t)

    def test_grouped_allreduce(self):
        ts = [torch.ones(3), torch.arange(4.0)]
        outs = hvd.grouped_allreduce(ts, name="gar")
        for o, t in zip(outs, ts):
            torch.testing.assert_close(o, t)

    def test_allgather(self):
        t = torch.arange(6.0).reshape(2, 3)
        out = hvd.allgather(t)
        assert out.shape == (2 * hvd.size(), 3)

    def test_broadcast_inplace(self):
        t = torch.randn(4, 4)
        want = t.clone()
        r = hvd.broadcast_(t, root_rank=0)
        assert r is t
        torch.testing.assert_close(t, want)

    def test_alltoall(self):
        t = torch.arange(8.0)
        out = hvd.alltoall(t)
        torch.testing.assert_close(out, t)

    def test_alltoall_with_splits(self):
        t = torch.arange(6.0)
        out, rsplits = hvd.alltoall(t, splits=torch.tensor([6]))
        torch.testing.assert_close(out, t)
        assert int(rsplits.sum()) == 6

    def test_reducescatter(self):
        t = torch.arange(8.0)
        out = hvd.reducescatter(t)
        assert out.numel() == 8 // hvd.size()

    def test_async_handle_lifecycle(self):
        t = torch.ones(4)
        h = hvd.allreduce_async(t, name="as.1")
        out = hvd.synchronize(h)
        torch.testing.assert_close(out, t)

    def test_async_inplace(self):
        t = torch.full((3,), 2.0)
        h = hvd.allreduce_async_(t, name="as.2")
        r = hvd.synchronize(h)
        assert r is t
        torch.testing.assert_close(t, torch.full((3,), 2.0))

    def test_broadcast_object(self):
        obj = {"a": torch.ones(2), "b": [1, 2, 3]}
        out = hvd.broadcast_object(obj, root_rank=0)
        torch.testing.assert_close(out["a"], obj["a"])
        assert out["b"] == obj["b"]

    def test_broadcast_parameters(self):
        model = torch.nn.Linear(4, 2)
        hvd.broadcast_parameters(model.state_dict(), root_rank=0)


class TestRegisteredGradients:
    """torch.autograd.Function adjoints on the bare collectives
    (parity: the HorovodAllreduce/... Function wrappers in
    horovod/torch/mpi_ops.py).  Size-1 closed forms; cross-rank
    behavior mirrors the TF suite's multiprocess coverage."""

    def test_allreduce_grad_is_allreduce_of_grad(self, hvt):
        x = torch.tensor([1.0, 2.0, 3.0], requires_grad=True)
        hvd.allreduce(x * 2.0, op=hvd.Sum).sum().backward()
        assert x.grad.tolist() == [2.0, 2.0, 2.0]

    def test_allreduce_minmax_grad_rejected(self, hvt):
        x = torch.tensor([1.0], requires_grad=True)
        y = hvd.allreduce(x, op=hvd.Min)
        with pytest.raises(NotImplementedError, match="MIN"):
            y.backward()

    def test_allgather_grad_slices_own_rows(self, hvt):
        x = torch.ones((2, 1), requires_grad=True)
        (hvd.allgather(x)
         * torch.tensor([[2.0], [5.0]])).sum().backward()
        assert x.grad.ravel().tolist() == [2.0, 5.0]

    def test_broadcast_grad_reduces_to_root(self, hvt):
        x = torch.ones(2, requires_grad=True)
        (hvd.broadcast(x, root_rank=0) * 3.0).sum().backward()
        assert x.grad.tolist() == [3.0, 3.0]

    def test_reducescatter_grad_is_allgather(self, hvt):
        x = torch.ones((2, 1), requires_grad=True)
        (hvd.reducescatter(x, op=hvd.Sum) * 7.0).sum().backward()
        assert x.grad.ravel().tolist() == [7.0, 7.0]

    def test_alltoall_grad_routes_back(self, hvt):
        x = torch.arange(3.0, requires_grad=True)
        out, _ = hvd.alltoall(x, splits=[3])
        (out * 5.0).sum().backward()
        assert x.grad.tolist() == [5.0, 5.0, 5.0]
        x = torch.arange(2.0, requires_grad=True)
        (hvd.alltoall(x) * 2.0).sum().backward()
        assert x.grad.tolist() == [2.0, 2.0]

    def test_grouped_allreduce_grad(self, hvt):
        xs = [torch.ones(2, requires_grad=True),
              torch.ones(3, requires_grad=True)]
        outs = hvd.grouped_allreduce(xs, op=hvd.Sum)
        (outs[0] * 2.0).sum().add((outs[1] * 3.0).sum()).backward()
        assert xs[0].grad.tolist() == [2.0, 2.0]
        assert xs[1].grad.tolist() == [3.0, 3.0, 3.0]

    def test_grouped_allreduce_mixed_grad_list(self, hvt):
        # a grad-free tensor in the group must come back grad-free
        # (e.g. .numpy() on it keeps working) while its peer still
        # backprops
        p = torch.ones(2, requires_grad=True)
        d = torch.ones(2)
        outs = hvd.grouped_allreduce([p, d], op=hvd.Sum)
        assert not outs[1].requires_grad
        outs[1].numpy()  # must not raise
        (outs[0] * 4.0).sum().backward()
        assert p.grad.tolist() == [4.0, 4.0]
        assert d.grad is None

    def test_no_grad_path_unchanged(self, hvt):
        # detached inputs keep the plain zero-overhead route and
        # produce grad-free outputs
        x = torch.ones(3)
        out = hvd.allreduce(x, op=hvd.Sum)
        assert not out.requires_grad


class TestDistributedOptimizer:
    def _model_and_data(self):
        torch.manual_seed(0)
        model = torch.nn.Sequential(
            torch.nn.Linear(8, 16), torch.nn.ReLU(), torch.nn.Linear(16, 4)
        )
        x = torch.randn(32, 8)
        y = torch.randint(0, 4, (32,))
        return model, x, y

    def test_trains(self):
        model, x, y = self._model_and_data()
        opt = hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1, momentum=0.9),
            named_parameters=model.named_parameters(),
        )
        losses = []
        for _ in range(20):
            opt.zero_grad()
            loss = torch.nn.functional.cross_entropy(model(x), y)
            loss.backward()
            opt.step()
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.9

    def test_matches_plain_sgd_size1(self):
        """At size 1, DistributedOptimizer must be numerically identical
        to the wrapped optimizer."""
        model1, x, y = self._model_and_data()
        model2 = torch.nn.Sequential(
            torch.nn.Linear(8, 16), torch.nn.ReLU(), torch.nn.Linear(16, 4)
        )
        model2.load_state_dict(model1.state_dict())

        opt1 = hvd.DistributedOptimizer(
            torch.optim.SGD(model1.parameters(), lr=0.1, momentum=0.9),
            named_parameters=model1.named_parameters(),
        )
        opt2 = torch.optim.SGD(model2.parameters(), lr=0.1, momentum=0.9)
        for _ in range(3):
            for opt, model in ((opt1, model1), (opt2, model2)):
                opt.zero_grad()
                torch.nn.functional.cross_entropy(model(x), y).backward()
                opt.step()
        for p1, p2 in zip(model1.parameters(), model2.parameters()):
            torch.testing.assert_close(p1, p2)

    def test_backward_passes_per_step(self):
        model, x, y = self._model_and_data()
        opt = hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.05),
            named_parameters=model.named_parameters(),
            backward_passes_per_step=2,
        )
        opt.zero_grad()
        torch.nn.functional.cross_entropy(model(x[:16]), y[:16]).backward()
        torch.nn.functional.cross_entropy(model(x[16:]), y[16:]).backward()
        opt.step()  # accumulated 2 passes, then stepped

    def test_too_many_passes_raises(self):
        model, x, y = self._model_and_data()
        opt = hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.05),
            named_parameters=model.named_parameters(),
        )
        opt.zero_grad()
        torch.nn.functional.cross_entropy(model(x), y).backward()
        with pytest.raises(AssertionError, match="more than"):
            torch.nn.functional.cross_entropy(model(x), y).backward()
        # drain the first backward's pending handles so their names
        # don't race the next test's enqueues
        opt.synchronize()

    def test_zero_grad_mid_cycle_raises(self):
        model, x, y = self._model_and_data()
        opt = hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.05),
            named_parameters=model.named_parameters(),
        )
        opt.zero_grad()
        torch.nn.functional.cross_entropy(model(x), y).backward()
        with pytest.raises(AssertionError, match="zero_grad"):
            opt.zero_grad()
        opt.synchronize()  # clean up

    def test_predivide_requires_average(self):
        model, _, _ = self._model_and_data()
        with pytest.raises(ValueError, match="predivide"):
            hvd.DistributedOptimizer(
                torch.optim.SGD(model.parameters(), lr=0.1),
                named_parameters=model.named_parameters(),
                op=hvd.Sum, gradient_predivide_factor=2.0,
            )

    def test_predivide_postscale_uses_process_set_size(self, monkeypatch):
        # Average emulation must divide by the participating-rank count
        # (the process set's size), not the world size.
        model, _, _ = self._model_and_data()
        opt = hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters(),
            gradient_predivide_factor=2.0,
        )

        from horovod_tpu.core.process_set import ProcessSet
        ps = ProcessSet([0, 1])
        opt._process_set = ps

        import horovod_tpu.torch.optimizer as opt_mod
        monkeypatch.setattr(opt_mod._hvt, "size", lambda: 8)
        seen = {}

        def fake_async(grad, name, op, compression, prescale_factor,
                       postscale_factor, process_set):
            seen["post"] = postscale_factor
            return 0
        monkeypatch.setattr(opt_mod.mpi_ops, "allreduce_async_", fake_async)
        p = opt._requires_update[0]
        p.grad = torch.zeros_like(p)
        opt._allreduce_grad_async(p)
        assert seen["post"] == pytest.approx(2.0 / 2)

    def test_skip_synchronize(self):
        model, x, y = self._model_and_data()
        opt = hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters(),
        )
        opt.zero_grad()
        torch.nn.functional.cross_entropy(model(x), y).backward()
        opt.synchronize()
        with opt.skip_synchronize():
            opt.step()

    def test_isinstance_preserved(self):
        model, _, _ = self._model_and_data()
        opt = hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters(),
        )
        assert isinstance(opt, torch.optim.SGD)


class TestSyncBatchNorm:
    def test_matches_batchnorm_size1(self):
        torch.manual_seed(1)
        x = torch.randn(8, 3, 4, 4)
        bn = torch.nn.BatchNorm2d(3)
        sbn = hvd.SyncBatchNorm(3)
        sbn.load_state_dict(bn.state_dict())
        bn.train(), sbn.train()
        torch.testing.assert_close(sbn(x), bn(x))

    def test_eval_mode(self):
        sbn = hvd.SyncBatchNorm(3)
        sbn.eval()
        x = torch.randn(2, 3, 4)
        assert sbn(x).shape == x.shape

    def test_grad_flows(self):
        sbn = hvd.SyncBatchNorm(4)
        sbn.train()
        x = torch.randn(6, 4, requires_grad=True)
        sbn(x).sum().backward()
        assert x.grad is not None
        assert sbn.weight.grad is not None

    def test_affine_false_backward(self):
        # affine=False: forward's weight/bias are None — backward must
        # return None grads at those slots or autograd raises.
        from horovod_tpu.torch.sync_batch_norm import _SyncBatchNormFn
        x = torch.randn(6, 4, requires_grad=True)
        out = _SyncBatchNormFn.apply(
            x, None, None, None, None, 1e-5, 0.1, None)
        out.sum().backward()
        assert x.grad is not None

    def test_affine_false_module(self):
        sbn = hvd.SyncBatchNorm(4, affine=False)
        sbn.train()
        x = torch.randn(6, 4, requires_grad=True)
        sbn(x).sum().backward()
        assert x.grad is not None


class TestZeroCopyAdapter:
    """The DLPack adapter boundary (VERDICT round-1 task 5): contiguous
    fp32 tensors must cross torch->jax and jax->torch with NO host
    copy, asserted by buffer pointer identity."""

    def test_torch_to_jax_pointer_identity(self, hvt):
        from horovod_tpu.torch.mpi_ops import _to_jax

        t = torch.arange(16, dtype=torch.float32)
        j = _to_jax(t)
        assert t.data_ptr() == j.unsafe_buffer_pointer()

    def test_jax_to_torch_pointer_identity(self, hvt):
        import jax.numpy as jnp

        from horovod_tpu.torch.mpi_ops import _from_jax

        j = jnp.arange(8.0)
        t = _from_jax(j)
        assert t.data_ptr() == j.unsafe_buffer_pointer()

    def test_bf16_rides_dlpack(self, hvt):
        from horovod_tpu.torch.mpi_ops import _to_jax

        t = torch.ones(8, dtype=torch.bfloat16)
        j = _to_jax(t)
        assert str(j.dtype) == "bfloat16"
        assert t.data_ptr() == j.unsafe_buffer_pointer()
        out = hvd.allreduce(t, op=hvd.Sum, name="bf16zc")
        assert out.dtype == torch.bfloat16

    def test_noncontiguous_falls_back(self, hvt):
        t = torch.arange(16, dtype=torch.float32).reshape(4, 4).t()
        out = hvd.allreduce(t, op=hvd.Sum, name="nc")
        assert torch.allclose(out, t)


class TestSparseAllreduce:
    def test_sparse_allreduce_roundtrip(self, hvt):
        i = torch.tensor([[0, 2, 0]])
        v = torch.tensor([[1.0, 2.0], [3.0, 4.0], [10.0, 20.0]])
        sp = torch.sparse_coo_tensor(i, v, size=(4, 2))
        h = hvd.sparse_allreduce_async(sp, name="sp1", op=hvd.Sum)
        out = hvd.synchronize(h)
        assert out.is_sparse
        dense = out.to_dense()
        # duplicate index 0 coalesced: [11, 22]
        assert dense[0].tolist() == [11.0, 22.0]
        assert dense[2].tolist() == [3.0, 4.0]
        assert dense[1].tolist() == [0.0, 0.0]

    def test_sparse_average(self, hvt):
        i = torch.tensor([[1]])
        v = torch.tensor([[8.0]])
        sp = torch.sparse_coo_tensor(i, v, size=(3, 1))
        out = hvd.synchronize(
            hvd.sparse_allreduce_async(sp, name="sp2", op=hvd.Average)
        )
        assert out.to_dense()[1].item() == 8.0  # size-1 world

    def test_dense_tensor_rejected(self, hvt):
        with pytest.raises(ValueError, match="sparse"):
            hvd.sparse_allreduce_async(torch.ones(3), name="d")

    def test_embedding_sparse_grads_through_optimizer(self, hvt):
        emb = torch.nn.Embedding(10, 4, sparse=True)
        opt = torch.optim.SGD(emb.parameters(), lr=0.5)
        opt = hvd.DistributedOptimizer(
            opt, named_parameters=emb.named_parameters()
        )
        w0 = emb.weight.detach().clone()
        idx = torch.tensor([1, 3, 1])
        loss = emb(idx).sum()
        opt.zero_grad()
        loss.backward()
        assert emb.weight.grad.is_sparse
        opt.step()
        moved = (emb.weight.detach() - w0).abs().sum(dim=1)
        assert moved[1] > 0 and moved[3] > 0
        assert moved[0] == 0 and moved[2] == 0

    def test_embedding_sparse_as_dense(self, hvt):
        emb = torch.nn.Embedding(6, 2, sparse=True)
        opt = torch.optim.SGD(emb.parameters(), lr=0.5)
        opt = hvd.DistributedOptimizer(
            opt, named_parameters=emb.named_parameters(),
            sparse_as_dense=True,
        )
        loss = emb(torch.tensor([0, 5])).sum()
        opt.zero_grad()
        loss.backward()
        opt.step()
        assert not emb.weight.grad.is_sparse


class TestFusedBroadcastParameters:
    def test_mixed_dtype_state_dict(self, hvt):
        """The fused byte-buffer path must handle fp32 + bf16 + int64
        buffers in one broadcast and leave values intact (size-1
        world: identity)."""
        model = torch.nn.Sequential(
            torch.nn.Linear(4, 3), torch.nn.BatchNorm1d(3)
        )
        model = model.to(torch.float32)
        sd = model.state_dict()
        before = {k: v.clone() for k, v in sd.items()}
        hvd.broadcast_parameters(sd, root_rank=0)
        for k, v in sd.items():
            assert torch.equal(v, before[k]), k

    def test_single_tensor_falls_through(self, hvt):
        p = torch.nn.Parameter(torch.randn(5))
        before = p.detach().clone()
        hvd.broadcast_parameters([("w", p)], root_rank=0)
        assert torch.equal(p.detach(), before)


class TestEagerBenchRegression:
    """CI-side anchors for BENCH_EAGER.json (VERDICT round-2 task 3):
    the eager path's tracked properties fail a test here rather than
    only drifting in the recorded tables."""

    def test_sync_dispatch_overhead_bound(self, hvt):
        """Small-tensor sync allreduce dispatch must stay in the
        sub-10ms regime (recorded: ~0.5 ms for 256 KB at P=1); a
        regression to a pathological path (host copy of a large
        staging buffer, blocking re-trace per call) lands well above
        the generous 50 ms CI bound."""
        import time

        t = torch.ones(64 * 1024 // 4, dtype=torch.float32)
        for i in range(3):
            hvd.allreduce(t, op=hvd.Sum, name=f"bench_warm{i}")
        times = []
        for i in range(10):
            t0 = time.perf_counter()
            hvd.allreduce(t, op=hvd.Sum, name=f"bench_sync{i}")
            times.append(time.perf_counter() - t0)
        med = sorted(times)[len(times) // 2]
        assert med < 0.050, f"sync dispatch {med*1e3:.1f} ms"

    def test_async_fused_path_zero_copy(self, hvt, monkeypatch):
        """The async/fused path must keep the torch->jax hop
        zero-copy: every contiguous fp32 tensor that enters
        allreduce_async crosses the adapter with pointer identity
        (extends the sync-path data_ptr assertion to the fused path)."""
        from horovod_tpu.torch import mpi_ops as mo

        pairs = []
        real = mo._to_jax

        def spy(t):
            j = real(t)
            if (isinstance(t, torch.Tensor) and t.is_contiguous()
                    and t.dtype == torch.float32):
                pairs.append((t.data_ptr(), j.unsafe_buffer_pointer()))
            return j

        monkeypatch.setattr(mo, "_to_jax", spy)
        tensors = [torch.full((1024,), float(i)) for i in range(8)]
        handles = [mo.allreduce_async(t, op=hvd.Sum, name=f"zc{i}")
                   for i, t in enumerate(tensors)]
        outs = [hvd.synchronize(h) for h in handles]
        assert len(pairs) == 8
        for tp, jp in pairs:
            assert tp == jp, "async adapter hop made a host copy"
        for i, o in enumerate(outs):
            assert torch.allclose(o, torch.full((1024,), float(i)))
