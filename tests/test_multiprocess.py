"""REAL multi-process integration tests: N worker processes launched by
the runner, speaking through the actual JAX coordination service
(KVTransport) and the cross-process XLA CPU data plane (gloo-backed
collectives).

This is the analog of the reference's ``test/parallel/*`` suite running
under ``horovodrun -np N`` on localhost (SURVEY.md §4 patterns 1-2):
test bodies are SPMD — every rank runs the same function — and the
launcher is the real one, not a mock.  Each test bundles many asserts
into one launch because process spawn + rendezvous costs seconds.
"""

import os

import pytest

import horovod_tpu
from horovod_tpu.runner import RunError, run

pytestmark = pytest.mark.multiprocess

_REPO_ROOT = os.path.dirname(os.path.dirname(horovod_tpu.__file__))
_ENV = {"PYTHONPATH": _REPO_ROOT + os.pathsep
        + os.environ.get("PYTHONPATH", "")}


def _run(body, np=2, cpu_devices=1, **kw):
    return run(body, np=np, cpu_devices=cpu_devices, env=_ENV,
               start_timeout=300.0, **kw)


def test_sync_collectives_2proc():
    """The full sync eager op matrix across 2 real processes."""

    def body():
        import jax
        import jax.numpy as jnp
        import numpy as np

        import horovod_tpu as hvt

        hvt.init()
        r, s = hvt.rank(), hvt.size()
        assert s == 2
        out = {}

        # allreduce (sum + average + prescale)
        x = jnp.full((3,), float(r + 1))
        out["sum"] = np.asarray(hvt.allreduce(x, op=hvt.Sum)).tolist()
        out["avg"] = np.asarray(hvt.allreduce(x, op=hvt.Average)).tolist()
        out["pre"] = np.asarray(
            hvt.allreduce(x, op=hvt.Sum, prescale_factor=2.0)
        ).tolist()

        # ragged allgather: rank r contributes r+1 rows of value r
        g = hvt.allgather(jnp.full((r + 1, 2), float(r)))
        out["gather"] = np.asarray(g).tolist()

        # broadcast from rank 1
        b = hvt.broadcast(jnp.full((2,), float(r * 10)), root_rank=1)
        out["bcast"] = np.asarray(b).tolist()

        # alltoall with variable splits: rank 0 sends [1 row, 2 rows],
        # rank 1 sends [3 rows, 1 row]
        splits = [1, 2] if r == 0 else [3, 1]
        t = jnp.arange(sum(splits), dtype=jnp.float32) + 100 * r
        recv, rsplits = hvt.alltoall(t, splits=splits)
        out["a2a"] = np.asarray(recv).tolist()
        out["a2a_splits"] = np.asarray(rsplits).tolist()

        # reducescatter, uneven dim0 (5 rows over 2 ranks -> 3/2)
        rs = hvt.reducescatter(jnp.ones((5, 2)), op=hvt.Sum)
        out["rs_shape"] = list(rs.shape)

        # barrier
        hvt.barrier()
        return (r, out)

    results = _run(body, np=2)
    for r, out in results:
        assert out["sum"] == [3.0, 3.0, 3.0]
        assert out["avg"] == [1.5, 1.5, 1.5]
        assert out["pre"] == [6.0, 6.0, 6.0]
        assert out["gather"] == [[0.0, 0.0], [1.0, 1.0], [1.0, 1.0]]
        assert out["bcast"] == [10.0, 10.0]
        # rank 0 receives: its own first chunk [100*0+0], rank 1's first
        # chunk (3 rows). rank 1 receives rank 0's second chunk (2 rows)
        # + its own second chunk (1 row).
        if r == 0:
            assert out["a2a"] == [0.0, 100.0, 101.0, 102.0]
            assert out["a2a_splits"] == [1, 3]
        else:
            assert out["a2a"] == [1.0, 2.0, 103.0]
            assert out["a2a_splits"] == [2, 1]
        assert out["rs_shape"] == ([3, 2] if r == 0 else [2, 2])


def test_async_controller_negotiation_2proc():
    """Ranks enqueue async ops in DIFFERENT orders; the controller must
    negotiate one execution order through the real KVTransport (the
    core Horovod property — never before exercised across processes)."""

    def body():
        import jax.numpy as jnp
        import numpy as np

        import horovod_tpu as hvt

        hvt.init()
        r = hvt.rank()
        names = ["a", "b", "c", "d"] if r == 0 else ["d", "c", "b", "a"]
        handles = {
            n: hvt.allreduce_async(
                jnp.full((8,), float((r + 1) * (i + 1))), name=n,
                op=hvt.Sum,
            )
            for i, n in enumerate(names)
        }
        vals = {n: float(np.asarray(hvt.synchronize(h))[0])
                for n, h in handles.items()}

        # grouped allreduce: members only execute together
        g = hvt.grouped_allreduce_async(
            [jnp.full((2,), float(r)), jnp.full((3,), float(r + 1))],
            names=["g1", "g2"], op=hvt.Sum,
        )
        grouped = [np.asarray(hvt.synchronize(h)).tolist() for h in g]

        # async broadcast + ragged allgather through the controller
        hb = hvt.broadcast_async(jnp.full((2,), float(r)), root_rank=0,
                                 name="bc")
        hg = hvt.allgather_async(jnp.full((r + 2,), 1.0), name="ag")
        bcast = np.asarray(hvt.synchronize(hb)).tolist()
        gath = np.asarray(hvt.synchronize(hg)).tolist()
        return (r, vals, grouped, bcast, gath)

    results = _run(body, np=2)
    for r, vals, grouped, bcast, gath in results:
        # rank0 enqueued (i+1), rank1 enqueued 2(i+1) with names reversed:
        # a: r0 gives 1, r1 gives 2*4=8 -> 9 ... pair by NAME not order.
        assert vals == {"a": 1.0 + 8.0, "b": 2.0 + 6.0,
                        "c": 3.0 + 4.0, "d": 4.0 + 2.0}
        assert grouped == [[1.0, 1.0], [3.0, 3.0, 3.0]]
        assert bcast == [0.0, 0.0]
        assert gath == [1.0] * 5


def test_process_sets_and_fusion_4proc():
    """Process-set-scoped collectives + fused small tensors, 4 procs."""

    def body():
        import jax.numpy as jnp
        import numpy as np

        import horovod_tpu as hvt

        hvt.init()
        r, s = hvt.rank(), hvt.size()
        assert s == 4
        evens = hvt.add_process_set([0, 2])
        odds = hvt.add_process_set([1, 3])
        mine = evens if r % 2 == 0 else odds

        # sync collective scoped to the set
        v = float(np.asarray(
            hvt.allreduce(jnp.asarray([float(r)]), op=hvt.Sum,
                          process_set=mine)
        )[0])

        # async: many small tensors -> the controller fuses them into
        # one flat wire buffer per cycle (FusionBufferManager parity)
        handles = [
            hvt.allreduce_async(jnp.full((4,), float(r + i)),
                                name=f"t{i}", op=hvt.Sum)
            for i in range(6)
        ]
        fused = [float(np.asarray(hvt.synchronize(h))[0]) for h in handles]

        # set-scoped async allgather
        hg = hvt.allgather_async(jnp.asarray([float(r)]), name="ps_ag",
                                 process_set=mine)
        ps_gather = np.asarray(hvt.synchronize(hg)).tolist()
        return (r, v, fused, ps_gather)

    results = _run(body, np=4)
    for r, v, fused, ps_gather in results:
        expected_set = 0.0 + 2.0 if r % 2 == 0 else 1.0 + 3.0
        assert v == expected_set
        assert fused == [float(sum(rr + i for rr in range(4)))
                         for i in range(6)]
        assert ps_gather == ([0.0, 2.0] if r % 2 == 0 else [1.0, 3.0])


def test_torch_bare_collective_gradients_2proc():
    """autograd through BARE torch collectives across ranks (parity:
    the torch.autograd.Function registrations): grad of an averaged
    allreduce averages the rank-local upstream grads; allgather's
    grad sums-and-slices; broadcast's reduces to the root."""

    def body():
        import torch

        import horovod_tpu.torch as hvd

        hvd.init()
        r = hvd.rank()
        out = {}

        # replicated weight through a bare averaged allreduce with a
        # rank-local coefficient: grad = avg over ranks of the coeff
        w = torch.tensor([[2.0]], requires_grad=True)
        c = float(10 * (r + 1))
        (hvd.allreduce(w, op=hvd.Average) * c).sum().backward()
        out["bare"] = w.grad.ravel().tolist()

        # allgather grad: summed coeffs, sliced to this rank's rows
        x = torch.ones((r + 1, 2), requires_grad=True)
        coeff = torch.tensor([[1.0], [2.0], [3.0]])
        (hvd.allgather(x) * coeff).sum().backward()
        out["gather_grad"] = x.grad.tolist()

        # broadcast grad: reduce-to-root
        b = torch.tensor([float(r + 5)], requires_grad=True)
        (hvd.broadcast(b, root_rank=0) * float(r + 1)).sum().backward()
        out["bcast_grad"] = b.grad.tolist()

        # no-splits alltoall with DIFFERENT per-rank row counts: the
        # adjoint must route each grad row back via the RECEIVED
        # counts (rank0 sends 2 rows to each peer, rank1 sends 1)
        t = torch.arange(float((2 - r) * 2), requires_grad=True)
        recv = hvd.alltoall(t)
        (recv * float(r + 1)).sum().backward()
        out["a2a_grad"] = t.grad.tolist()
        return (r, out)

    results = _run(body, np=2)
    for r, out in results:
        assert out["bare"] == [15.0]  # avg(10, 20)
        if r == 0:
            assert out["gather_grad"] == [[2.0, 2.0]]
        else:
            assert out["gather_grad"] == [[4.0, 4.0], [6.0, 6.0]]
        assert out["bcast_grad"] == ([3.0] if r == 0 else [0.0])
        # rank0's rows 0-1 were received by rank0 (coeff 1), rows 2-3
        # by rank1 (coeff 2); rank1's row 0 by rank0, row 1 by rank1
        if r == 0:
            assert out["a2a_grad"] == [1.0, 1.0, 2.0, 2.0]
        else:
            assert out["a2a_grad"] == [1.0, 2.0]


def test_torch_optimizer_2proc():
    """The torch frontend end-to-end across processes: broadcast
    parameters, DistributedOptimizer averaging gradients."""

    def body():
        import numpy as np
        import torch

        import horovod_tpu.torch as hvd

        hvd.init()
        r = hvd.rank()
        torch.manual_seed(1234 + r)  # different init per rank
        model = torch.nn.Linear(4, 2)
        hvd.broadcast_parameters(model.state_dict(), root_rank=0)
        w0 = model.weight.detach().clone().numpy()

        opt = torch.optim.SGD(model.parameters(), lr=0.1)
        opt = hvd.DistributedOptimizer(
            opt, named_parameters=model.named_parameters()
        )
        torch.manual_seed(r)  # different data per rank
        x = torch.randn(8, 4)
        y = torch.randn(8, 2)
        for _ in range(2):
            opt.zero_grad()
            loss = torch.nn.functional.mse_loss(model(x), y)
            loss.backward()
            opt.step()
        return (r, w0.tolist(), model.weight.detach().numpy().tolist())

    results = _run(body, np=2)
    (r0, w0_init, w0_final), (r1, w1_init, w1_final) = results
    # broadcast made initial params identical; averaged grads keep them
    # identical through steps despite different per-rank data
    assert w0_init == w1_init
    assert w0_final == w1_final
    assert w0_final != w0_init  # training moved


def test_grouped_variants_and_compression_2proc():
    """Grouped allgather/reducescatter across real processes + fp16
    wire compression on the async allreduce path."""

    def body():
        import jax.numpy as jnp
        import numpy as np

        import horovod_tpu as hvt
        from horovod_tpu.comm.compression import Compression

        hvt.init()
        r = hvt.rank()
        out = {}

        hs = hvt.grouped_allgather_async(
            [jnp.full((r + 1, 2), float(r)), jnp.asarray([float(r)])],
            names=["g1", "g2"],
        )
        g1, g2 = [np.asarray(hvt.synchronize(h)) for h in hs]
        out["g1"] = g1.tolist()
        out["g2"] = g2.tolist()

        hs = hvt.grouped_reducescatter_async(
            [jnp.ones((4, 2)), jnp.full((2,), float(r + 1))],
            names=["r1", "r2"], op=hvt.Sum,
        )
        r1, r2 = [np.asarray(hvt.synchronize(h)) for h in hs]
        out["r1_shape"] = list(r1.shape)
        out["r2"] = r2.tolist()

        h = hvt.allreduce_async(
            jnp.full((8,), 1.5 + r), name="fp16c", op=hvt.Sum,
            compression=Compression.fp16,
        )
        out["fp16"] = float(np.asarray(hvt.synchronize(h))[0])

        # int8 (incl. the stochastic subclass) must stay OFF the fused
        # flat-buffer path: per-rank block scales don't survive a raw
        # summed wire (regression: the controller's unfusable check
        # matched Int8Compressor by identity, so the subclass fused and
        # produced garbage).  Two concurrent ops makes the controller
        # emit one fused response covering both.
        hs = [
            hvt.allreduce_async(
                jnp.full((16,), 2.0 + r), name="q8a", op=hvt.Sum,
                compression=Compression.int8_stochastic,
            ),
            hvt.allreduce_async(
                jnp.full((16,), 10.0 * (r + 1)), name="q8b", op=hvt.Sum,
                compression=Compression.int8_stochastic,
            ),
        ]
        q8a, q8b = [np.asarray(hvt.synchronize(h)) for h in hs]
        out["q8a"] = float(q8a[0])
        out["q8b"] = float(q8b[0])
        return (r, out)

    results = _run(body, np=2)
    for r, out in results:
        assert out["g1"] == [[0.0, 0.0], [1.0, 1.0], [1.0, 1.0]]
        assert out["g2"] == [0.0, 1.0]
        assert out["r1_shape"] == [2, 2]
        # reducescatter of (2,) over 2 ranks -> 1 element per rank
        assert out["r2"] == [3.0]
        assert out["fp16"] == 4.0  # 1.5 + 2.5, exact in fp16
        # 2+3=5 and 10+20=30, within one int8 quantization step
        assert abs(out["q8a"] - 5.0) <= 5.0 / 127 + 1e-6
        assert abs(out["q8b"] - 30.0) <= 30.0 / 127 + 1e-6


def test_join_uneven_batches_2proc():
    """JoinOp semantics across real processes: rank 1 exhausts its data
    after 1 batch and joins; rank 0 runs 2 more batches whose
    allreduces must complete with rank 1 contributing zeros (sum keeps
    only rank 0's grads; average still divides by world size —
    reference join semantics)."""

    def body():
        import jax.numpy as jnp
        import numpy as np

        import horovod_tpu as hvt

        hvt.init()
        r = hvt.rank()
        out = {}
        # batch 0: everyone participates
        h = hvt.allreduce_async(jnp.full((4,), float(r + 1)), name="b0",
                                op=hvt.Sum)
        out["b0"] = float(np.asarray(hvt.synchronize(h))[0])
        if r == 1:
            last = hvt.join()  # out of data
            out["join_last"] = last
        else:
            # two uneven extra batches
            h1 = hvt.allreduce_async(jnp.full((4,), 10.0), name="b1",
                                     op=hvt.Sum)
            out["b1"] = float(np.asarray(hvt.synchronize(h1))[0])
            h2 = hvt.allreduce_async(jnp.full((4,), 8.0), name="b2",
                                     op=hvt.Average)
            out["b2"] = float(np.asarray(hvt.synchronize(h2))[0])
            out["join_last"] = hvt.join()
        return (r, out)

    results = _run(body, np=2)
    for r, out in results:
        assert out["b0"] == 3.0
        # rank 1 joined first, rank 0 last -> join() returns 0 everywhere
        assert out["join_last"] == 0
        if r == 0:
            assert out["b1"] == 10.0  # rank 1 contributed zeros
            assert out["b2"] == 4.0   # (8 + 0) / 2: zeros count in avg


@pytest.mark.slow
def test_elastic_reset_callback_rebroadcast_2proc():
    """ADVICE r5 regression: a RANK-DEPENDENT reset callback in a
    relaunched incarnation runs after sync; without the wrapper's
    re-broadcast the tracked attributes silently diverge across
    ranks.  Both ranks must come out with rank 0's values."""

    def body():
        import os

        import horovod_tpu as hvt
        from horovod_tpu import elastic

        os.environ["HVTPU_ELASTIC_GENERATION"] = "1"
        hvt.init()
        state = elastic.ObjectState(lr=0.0, epoch=3)
        state.register_reset_callbacks(
            [lambda: setattr(state, "lr", 100.0 + hvt.rank())])

        @elastic.run
        def train(st):
            return (st.lr, st.epoch)

        return train(state)

    results = _run(body, np=2)
    assert results[0] == results[1] == (100.0, 3)


def test_hierarchical_allreduce_4proc():
    """HVTPU_HIERARCHICAL_ALLREDUCE over a 2-host x 2-slot layout
    (both 'hosts' are loopback names, so everything spawns locally but
    local/cross topology is real): the two-stage (ici then dcn) reduce
    must produce the same numbers as the flat path."""

    def body():
        import jax.numpy as jnp
        import numpy as np

        import horovod_tpu as hvt

        hvt.init()
        r = hvt.rank()
        assert hvt.local_size() == 2 and hvt.cross_size() == 2
        assert hvt.size() == 4
        s = np.asarray(hvt.allreduce(
            jnp.full((5,), float(r + 1)), op=hvt.Sum
        )).tolist()
        a = np.asarray(hvt.allreduce(
            jnp.full((3,), float(10 * (r + 1))), op=hvt.Average
        )).tolist()
        return (r, s, a)

    results = run(
        body, np=4, cpu_devices=1,
        hosts="localhost:2,127.0.0.1:2",
        env={**_ENV, "HVTPU_HIERARCHICAL_ALLREDUCE": "1"},
        start_timeout=300.0,
    )
    for r, s, a in results:
        assert s == [10.0] * 5          # 1+2+3+4
        assert a == [25.0] * 3          # avg(10,20,30,40)


def test_sparse_allreduce_2proc():
    """sparse_allreduce_async across real processes: overlapping and
    disjoint embedding rows from two ranks coalesce to the cross-rank
    sum (reference: entries+values allgather path)."""

    def body():
        import torch

        import horovod_tpu.torch as hvd

        hvd.init()
        r = hvd.rank()
        # rank 0 touches rows {0, 1}; rank 1 touches rows {1, 2}
        i = torch.tensor([[0 + r, 1 + r]])
        v = torch.tensor([[1.0 * (r + 1)], [10.0 * (r + 1)]])
        sp = torch.sparse_coo_tensor(i, v, size=(4, 1))
        out = hvd.synchronize(
            hvd.sparse_allreduce_async(sp, name="emb", op=hvd.Sum)
        )
        return (r, out.to_dense().squeeze(1).tolist())

    results = _run(body, np=2)
    for r, dense in results:
        # row0: rank0's 1.0; row1: rank0's 10.0 + rank1's 2.0; row2:
        # rank1's 20.0
        assert dense == [1.0, 12.0, 20.0, 0.0]


def test_early_exit_rank_does_not_hang_peers():
    """A worker that finishes and exits must not hang the remaining
    ranks' coordination: its shutdown farewell tells the coordinator to
    stop waiting for its cycle blobs (the controller cycle gathers from
    every rank otherwise)."""

    def body():
        import time

        import jax.numpy as jnp

        import horovod_tpu as hvt

        hvt.init()
        r = hvt.rank()
        solo = hvt.add_process_set([0])
        other = hvt.add_process_set([1])
        hvt.synchronize(
            hvt.allreduce_async(jnp.ones(2), name="warm", op=hvt.Sum)
        )
        if r == 1:
            return "bye"  # exits while rank 0 keeps coordinating
        mine = solo
        for i in range(20):
            hvt.synchronize(hvt.allreduce_async(
                jnp.ones(2), name=f"solo{i}", op=hvt.Sum,
                process_set=mine,
            ))
            time.sleep(0.05)
        return "done"

    results = _run(body, np=2)
    assert [x[1] if isinstance(x, tuple) else x for x in results] \
        == ["done", "bye"] or results == ["done", "bye"]


def test_worker_failure_propagates():
    """One rank raising must fail the job with that rank's traceback
    and terminate the peers (reference: launcher exit-code handling)."""

    def body():
        import horovod_tpu as hvt

        hvt.init()
        if hvt.rank() == 1:
            raise RuntimeError("deliberate-worker-crash")
        return hvt.rank()

    with pytest.raises(RunError) as err:
        _run(body, np=2)
    assert "deliberate-worker-crash" in str(err.value)


# ---------------------------------------------------------------------------
# Pod shape: P processes x D>1 local devices (the north star's topology —
# many hosts x several chips each, one jit program over the global mesh)
# ---------------------------------------------------------------------------


def _pod_train_body():
    """SPMD body: jit DistributedOptimizer training step over the GLOBAL
    8-device world mesh from each of 2 processes owning 4 devices
    (multi-controller JAX: same jit on every process, per-host
    addressable shards).  NOTE: shipped to workers by VALUE — the test
    registers this module for cloudpickle by-value pickling, since
    workers cannot import the test module."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    import horovod_tpu as hvt

    hvt.init()
    assert hvt.size() == 2, hvt.size()
    assert jax.local_device_count() == 4
    assert jax.device_count() == 8

    mesh = hvt.world_mesh()
    assert mesh.devices.size == 8

    rng = np.random.RandomState(0)
    W0 = (rng.randn(16, 4) * 0.1).astype(np.float32)
    X = rng.randn(64, 16).astype(np.float32)
    Y = rng.randn(64, 4).astype(np.float32)

    repl = NamedSharding(mesh, P())
    row = NamedSharding(mesh, P("world"))
    w = jax.make_array_from_callback((16, 4), repl, lambda i: W0[i])
    x = jax.make_array_from_callback((64, 16), row, lambda i: X[i])
    y = jax.make_array_from_callback((64, 4), row, lambda i: Y[i])

    opt = hvt.DistributedOptimizer(
        optax.sgd(0.1, momentum=0.9), axis_name="world"
    )

    def step(w, s, xs, ys):
        def loss_fn(w):
            return jnp.mean((xs @ w - ys) ** 2)

        l, g = jax.value_and_grad(loss_fn)(w)
        updates, s = opt.update(g, s, w)
        return optax.apply_updates(w, updates), s, jax.lax.pmean(l, "world")

    sstep = jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(), P(), P("world"), P("world")),
        out_specs=(P(), P(), P()), check_vma=False,
    ))
    s = jax.jit(
        opt.init, out_shardings=jax.tree_util.tree_map(lambda _: repl,
                                                       jax.eval_shape(opt.init, w))
    )(w)

    losses = []
    for _ in range(5):
        w, s, l = sstep(w, s, x, y)
        losses.append(float(np.asarray(l.addressable_data(0))))
    wout = np.asarray(w.addressable_data(0))
    return (hvt.rank(), losses, wout.tolist())


def test_pod_shape_jit_global_mesh_2proc_x_4dev():
    """The flagship jit path on a multi-process global mesh — 2 procs x
    4 CPU devices = 8-device world mesh, XLA compiling per-host programs
    (never previously exercised; every earlier multi-process test ran
    cpu_devices=1 and every 8-device test was single-process)."""
    import numpy as np
    import optax

    import sys

    import cloudpickle

    cloudpickle.register_pickle_by_value(sys.modules[__name__])
    try:
        results = _run(_pod_train_body, np=2, cpu_devices=4)
    finally:
        cloudpickle.unregister_pickle_by_value(sys.modules[__name__])

    # (a) lockstep across the two processes: identical loss trajectory
    # and identical final params
    (r0, losses0, w0), (r1, losses1, w1) = sorted(results)
    assert (r0, r1) == (0, 1)
    np.testing.assert_allclose(losses0, losses1, rtol=0, atol=0)
    np.testing.assert_allclose(w0, w1, rtol=0, atol=0)

    # (b) equivalence with the single-process full-batch reference:
    # grads averaged over the world axis == full-batch gradient
    rng = np.random.RandomState(0)
    W = (rng.randn(16, 4) * 0.1).astype(np.float32)
    X = rng.randn(64, 16).astype(np.float32)
    Y = rng.randn(64, 4).astype(np.float32)
    opt = optax.sgd(0.1, momentum=0.9)
    s = opt.init(W)
    import jax
    import jax.numpy as jnp

    def loss_fn(w):
        return jnp.mean((jnp.asarray(X) @ w - jnp.asarray(Y)) ** 2)

    w = jnp.asarray(W)
    ref_losses = []
    for _ in range(5):
        l, g = jax.value_and_grad(loss_fn)(w)
        upd, s = opt.update(g, s, w)
        w = optax.apply_updates(w, upd)
        ref_losses.append(float(l))
    np.testing.assert_allclose(losses0, ref_losses, rtol=2e-5)
    np.testing.assert_allclose(w0, np.asarray(w), rtol=1e-4, atol=1e-5)


def test_eager_engine_multidevice_2proc_x_2dev():
    """The eager engine's D>1-per-process story: eager collectives are
    PROCESS-granularity (one process = one Horovod rank, contribution
    rides the process's designated transport device); extra local
    devices belong to the jit/SPMD path.  hvt.size() must stay the
    process count and results must match the P=2 semantics exactly."""
    import numpy as np

    def body():
        import jax
        import jax.numpy as jnp
        import numpy as np

        import horovod_tpu as hvt

        hvt.init()
        assert hvt.size() == 2
        assert jax.local_device_count() == 2
        assert jax.device_count() == 4
        r = hvt.rank()
        out = {}
        out["sum"] = np.asarray(
            hvt.allreduce(jnp.full((3,), float(r + 1)), op=hvt.Sum)
        ).tolist()
        out["gather"] = np.asarray(
            hvt.allgather(jnp.full((1, 2), float(r)))
        ).tolist()
        h = hvt.allreduce_async(jnp.full((4,), float(r + 1)), name="pod",
                                op=hvt.Sum)
        out["async"] = np.asarray(hvt.synchronize(h)).tolist()
        out["bcast"] = np.asarray(
            hvt.broadcast(jnp.full((2,), float(r * 7)), root_rank=1)
        ).tolist()
        return (r, out)

    results = _run(body, np=2, cpu_devices=2)
    for _, out in sorted(results):
        assert out["sum"] == [3.0, 3.0, 3.0]
        assert out["gather"] == [[0.0, 0.0], [1.0, 1.0]]
        assert out["async"] == [3.0, 3.0, 3.0, 3.0]
        assert out["bcast"] == [7.0, 7.0]


def test_hierarchical_jit_mesh_2proc_x_4dev():
    """Multi-slice jit collectives on the (dcn, ici) hierarchical mesh
    in the pod shape: 2 processes (dcn axis) x 4 local devices (ici
    axis).  A two-stage allreduce (psum over ici, then dcn) must equal
    the flat world psum — the jit-path analog of the eager
    hierarchical path (comm/eager.py allreduce_hier), closing the loop
    between the pod-shape tests and HVTPU_HIERARCHICAL_ALLREDUCE."""
    import numpy as np

    def body():
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import NamedSharding, PartitionSpec as P

        import horovod_tpu as hvt
        from horovod_tpu.comm import spmd
        from horovod_tpu.comm.reduce_ops import ReduceOp

        hvt.init()
        assert hvt.size() == 2 and jax.local_device_count() == 4
        hier = hvt.hierarchical_mesh()
        assert hier.devices.shape == (2, 4)
        assert hier.axis_names == ("dcn", "ici")

        rng = np.random.RandomState(5)
        data = rng.randn(8, 512).astype(np.float32)
        shard = NamedSharding(hier, P(("dcn", "ici")))
        x = jax.make_array_from_callback((8, 512), shard,
                                         lambda i: data[i])

        def two_stage(xs):
            v = xs[0]
            v = spmd.allreduce(v, axis_name="ici", op=ReduceOp.SUM)
            v = spmd.allreduce(v, axis_name="dcn", op=ReduceOp.SUM)
            return v

        out = jax.jit(jax.shard_map(
            two_stage, mesh=hier,
            in_specs=(P(("dcn", "ici")),), out_specs=P(),
            check_vma=False,
        ))(x)
        got = np.asarray(out.addressable_data(0))
        want = data.sum(0)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
        return hvt.rank()

    results = _run(body, np=2, cpu_devices=4)
    assert sorted(results) == [0, 1]


def test_remote_path_executes_via_ssh_transport(tmp_path):
    """The remote-host launch path EXECUTED, not just string-compared
    (VERDICT round-2 task 5): a 2-rank job whose second host is
    non-local goes through build_ssh_command and a real transport exec
    (a local sh shim standing in for sshd — the sandbox has no ssh
    binary), covering env-export serialization, quoting, cwd, piping
    and exit propagation; the NIC probe supplies the coordinator
    address for the mixed local/remote spec."""
    import subprocess
    import sys

    script = tmp_path / "remote_worker.py"
    script.write_text(
        "import jax\n"
        "import horovod_tpu as hvt\n"
        "hvt.init()\n"
        "import jax.numpy as jnp\n"
        "out = hvt.allreduce(jnp.full((2,), float(hvt.rank() + 1)),"
        " op=hvt.Sum)\n"
        "print(f'REMOTE_OK rank={hvt.rank()} sum={float(out[0])}',"
        " flush=True)\n"
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["HVTPU_SSH_COMMAND"] = (
        f"{sys.executable} {os.path.join(_REPO_ROOT, 'tests', 'fake_ssh.py')}"
    )
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner",
         "-np", "2", "-H", "localhost:1,fakeremote.invalid:1",
         "--cpu-devices", "1",
         "--", sys.executable, str(script)],
        env=env, capture_output=True, text=True, timeout=300,
    )
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, out[-3000:]
    assert "FAKE_SSH host=fakeremote.invalid" in out, out[-3000:]
    assert "REMOTE_OK rank=0 sum=3.0" in out, out[-3000:]
    assert "REMOTE_OK rank=1 sum=3.0" in out, out[-3000:]


def test_remote_path_propagates_failure(tmp_path):
    """A remote worker's non-zero exit must terminate the job with a
    failing exit code through the same transport."""
    import subprocess
    import sys

    script = tmp_path / "remote_fail.py"
    script.write_text(
        "import os, sys\n"
        "rank = int(os.environ['HVTPU_RANK'])\n"
        "sys.exit(7 if rank == 1 else 0)\n"
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["HVTPU_SSH_COMMAND"] = (
        f"{sys.executable} {os.path.join(_REPO_ROOT, 'tests', 'fake_ssh.py')}"
    )
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner",
         "-np", "2", "-H", "localhost:1,fakeremote.invalid:1",
         "--", sys.executable, str(script)],
        env=env, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode != 0
    assert "FAKE_SSH" in (proc.stdout + proc.stderr)


def test_adasum_multiprocess_2_and_4proc():
    """Adasum across REAL processes (previously only verified single-
    process against numpy): P=2 and P=4 flat recursive doubling must
    match the numpy reference bit-for-tolerance on every rank."""
    import numpy as np

    from horovod_tpu.comm.adasum import adasum_reduce_reference

    def body():
        import jax.numpy as jnp
        import numpy as np

        import horovod_tpu as hvt

        hvt.init()
        r, s = hvt.rank(), hvt.size()
        rng = np.random.RandomState(40 + r)
        t = rng.randn(33).astype(np.float32)
        out = np.asarray(hvt.allreduce(jnp.asarray(t), op=hvt.Adasum))
        # async path through the controller too
        h = hvt.allreduce_async(jnp.asarray(t * 2.0), name="ad",
                                op=hvt.Adasum)
        out2 = np.asarray(hvt.synchronize(h))
        return (r, out.tolist(), out2.tolist())

    for np_procs in (2, 4):
        results = _run(body, np=np_procs)
        tensors = [
            np.random.RandomState(40 + r).randn(33).astype(np.float32)
            for r in range(np_procs)
        ]
        want = adasum_reduce_reference(tensors)
        want2 = adasum_reduce_reference([t * 2.0 for t in tensors])
        for r, out, out2 in results:
            np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-6)
            np.testing.assert_allclose(out2, want2, rtol=1e-5, atol=1e-6)


def test_hierarchical_adasum_4proc():
    """Hierarchical Adasum on the (dcn, ici) layout (parity:
    adasum_gpu_operations.cc — local SUM within the host, Adasum
    across hosts): 2 hosts x 2 slots must produce
    adasum(host0_sum, host1_sum) on every rank."""
    import numpy as np

    from horovod_tpu.comm.adasum import adasum_reduce_reference

    def body():
        import jax.numpy as jnp
        import numpy as np

        import horovod_tpu as hvt

        hvt.init()
        r = hvt.rank()
        assert hvt.local_size() == 2 and hvt.cross_size() == 2
        rng = np.random.RandomState(50 + r)
        t = rng.randn(17).astype(np.float32)
        out = np.asarray(hvt.allreduce(jnp.asarray(t), op=hvt.Adasum))
        return (r, out.tolist())

    results = run(
        body, np=4, cpu_devices=1,
        hosts="localhost:2,127.0.0.1:2",
        env={**_ENV, "HVTPU_HIERARCHICAL_ALLREDUCE": "1"},
        start_timeout=300.0,
    )
    tensors = [
        np.random.RandomState(50 + r).randn(17).astype(np.float32)
        for r in range(4)
    ]
    # hosts are assigned in sorted order (127.0.0.1 before localhost),
    # but host-sums are symmetric inputs to the pairwise combine, so
    # grouping (0,1) vs (2,3) matches either assignment
    h0 = tensors[0] + tensors[1]
    h1 = tensors[2] + tensors[3]
    want = adasum_reduce_reference([h0, h1])
    for r, out in results:
        np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-6)


def test_eager_multidevice_lanes_2proc_x_4dev():
    """Multi-lane eager allreduce at the pod shape: each process's
    payload is sharded across its 4 local devices (4 parallel
    reduction lanes) with numerics identical to the process-level
    contract, across ops/dtypes/odd sizes (the
    HVTPU_EAGER_MULTIDEVICE=0 fallback is the sibling optout test)."""
    import numpy as np

    def body():
        import os

        import jax
        import jax.numpy as jnp
        import numpy as np

        import horovod_tpu as hvt
        from jax.sharding import Mesh

        hvt.init()
        r = hvt.rank()
        assert hvt.size() == 2 and jax.local_device_count() == 4
        out = {}

        # >= _MULTIDEV_MIN_BYTES so the lane path engages
        x = jnp.arange(100000, dtype=jnp.float32) + 100000.0 * r
        out["sum_ok"] = bool(np.array_equal(
            np.asarray(hvt.allreduce(x, op=hvt.Sum)),
            np.arange(100000) * 2.0 + 100000.0,
        ))
        out["mx"] = np.asarray(
            hvt.allreduce(jnp.full((7,), float(r)), op=hvt.Max)
        ).tolist()
        out["bf16"] = np.asarray(hvt.allreduce(
            jnp.full((9,), 2.0, jnp.bfloat16), op=hvt.Average
        ).astype(jnp.float32)).tolist()
        out["int_avg"] = np.asarray(hvt.allreduce(
            jnp.full((3,), 3 + r, jnp.int32), op=hvt.Average
        )).tolist()

        # lane-parallel broadcast (the broadcast_parameters startup
        # wire): large byte buffer + odd length from a non-zero root
        bb = np.arange(130_001, dtype=np.uint8) + r  # wraps mod 256
        out["bcast_ok"] = bool(np.array_equal(
            np.asarray(hvt.broadcast(jnp.asarray(bb), root_rank=1)),
            (np.arange(130_001) + 1).astype(np.uint8),
        ))

        # the multi-lane mesh actually engaged (cached on the set)
        st = hvt.core.state.global_state()
        gset = st.process_set_table.global_process_set
        out["lanes"] = isinstance(
            getattr(gset, "_multidev_mesh", None), Mesh
        )

        # mid-run env flips must have NO effect: the flag is
        # snapshotted at init (divergent per-process settings would
        # compile mismatched collective programs and hang)
        os.environ["HVTPU_EAGER_MULTIDEVICE"] = "0"
        out["sum_after_flip_ok"] = bool(np.array_equal(
            np.asarray(hvt.allreduce(x, op=hvt.Sum, name="flip")),
            np.arange(100000) * 2.0 + 100000.0,
        ))
        out["lanes_after_flip"] = isinstance(
            getattr(gset, "_multidev_mesh", None), Mesh
        )
        os.environ.pop("HVTPU_EAGER_MULTIDEVICE")
        return (r, out)

    results = _run(body, np=2, cpu_devices=4)
    for _, out in sorted(results):
        assert out["sum_ok"] is True
        assert out["mx"] == [1.0] * 7
        assert out["bf16"] == [2.0] * 9
        assert out["int_avg"] == [3] * 3  # floor((3 + 4)/2)
        assert out["bcast_ok"] is True
        assert out["lanes"] is True
        assert out["sum_after_flip_ok"] is True
        assert out["lanes_after_flip"] is True


def test_eager_multilane_gather_scatter_alltoall_2proc_x_4dev():
    """Round-4: the lane path extended beyond allreduce/broadcast —
    allgather (incl. ragged), reducescatter (Sum + Average), and
    variable-split alltoall move big payloads over all 4 local lanes
    with results IDENTICAL to the small-payload (single-transport)
    path."""
    import numpy as np

    def body():
        import jax
        import jax.numpy as jnp
        import numpy as np

        import horovod_tpu as hvt

        hvt.init()
        r = hvt.rank()
        assert hvt.size() == 2 and jax.local_device_count() == 4
        out = {}

        # big ragged allgather: rank r contributes (r+1)*9000 rows of 3
        big = (jnp.arange((r + 1) * 9000 * 3, dtype=jnp.float32)
               .reshape(-1, 3) + 1e6 * r)
        g = np.asarray(hvt.allgather(big))
        expect = np.concatenate([
            np.arange(9000 * 3, dtype=np.float32).reshape(-1, 3),
            np.arange(2 * 9000 * 3, dtype=np.float32).reshape(-1, 3)
            + 1e6,
        ])
        out["gather_ok"] = bool(np.array_equal(g, expect))

        # big even reducescatter, Sum and Average, odd inner size
        x = (jnp.arange(40_000 * 3, dtype=jnp.float32)
             .reshape(-1, 3) * (r + 1))
        rs = np.asarray(hvt.reducescatter(x, op=hvt.Sum))
        full = (np.arange(40_000 * 3, dtype=np.float32)
                .reshape(-1, 3) * 3.0)
        out["rs_sum_ok"] = bool(np.allclose(
            rs, full[r * 20_000:(r + 1) * 20_000]))
        rsa = np.asarray(hvt.reducescatter(x, op=hvt.Average))
        out["rs_avg_ok"] = bool(np.allclose(
            rsa, full[r * 20_000:(r + 1) * 20_000] / 2.0))

        # big variable-split alltoall
        splits = [30_000, 10_000] if r == 0 else [5_000, 25_000]
        t = (jnp.arange(sum(splits) * 2, dtype=jnp.float32)
             .reshape(-1, 2) + 1e6 * r)
        recv, rsplits = hvt.alltoall(t, splits=splits)
        recv = np.asarray(recv)
        # build expectations from both ranks' send buffers
        t0 = (np.arange(40_000 * 2, dtype=np.float32).reshape(-1, 2))
        t1 = (np.arange(30_000 * 2, dtype=np.float32).reshape(-1, 2)
              + 1e6)
        if r == 0:
            want = np.concatenate([t0[:30_000], t1[:5_000]])
            want_splits = [30_000, 5_000]
        else:
            want = np.concatenate([t0[30_000:40_000], t1[5_000:30_000]])
            want_splits = [10_000, 25_000]
        out["a2a_ok"] = bool(np.array_equal(recv, want))
        out["a2a_splits"] = np.asarray(rsplits).tolist() == want_splits

        # identical numerics when the payload is SMALL (flat path):
        # same ops, sizes below the 64KB lane threshold
        g2 = np.asarray(hvt.allgather(
            jnp.full((r + 1, 2), float(r))))
        out["small_gather_ok"] = bool(np.array_equal(
            g2, np.asarray([[0, 0], [1, 1], [1, 1]], np.float32)))
        return (r, out)

    results = _run(body, np=2, cpu_devices=4)
    for _, out in sorted(results):
        assert out["gather_ok"] is True
        assert out["rs_sum_ok"] is True
        assert out["rs_avg_ok"] is True
        assert out["a2a_ok"] is True
        assert out["a2a_splits"] is True
        assert out["small_gather_ok"] is True


def test_eager_multidevice_optout_2proc_x_4dev():
    """HVTPU_EAGER_MULTIDEVICE=0 (launcher-distributed env):
    single-transport fallback with identical numbers."""
    def body_single():
        import jax.numpy as jnp
        import numpy as np

        import horovod_tpu as hvt

        hvt.init()
        r = hvt.rank()
        x = jnp.arange(100000, dtype=jnp.float32) + 100000.0 * r
        ok = bool(np.array_equal(
            np.asarray(hvt.allreduce(x, op=hvt.Sum)),
            np.arange(100000) * 2.0 + 100000.0,
        ))
        st = hvt.core.state.global_state()
        gset = st.process_set_table.global_process_set
        return (r, ok, getattr(gset, "_multidev_mesh", None) is None)

    results = run(body_single, np=2, cpu_devices=4,
                  env={**_ENV, "HVTPU_EAGER_MULTIDEVICE": "0"},
                  start_timeout=300.0)
    for _, ok, no_lanes in sorted(results):
        assert ok is True
        assert no_lanes


def _split_burst_body():
    """SPMD body for the split-burst divergence matrix: records the
    fused groupings each rank APPLIES (in order) while an injected
    mid-burst delay on rank 1 splits its drained bursts — the exact
    scenario that made v4 schedule prediction unsound.  With atomic
    burst units the coordinator never fuses across a burst boundary,
    so the applied groupings (predicted or negotiated) must stay
    byte-identical across ranks."""
    import jax.numpy as jnp
    import numpy as np

    import horovod_tpu as hvt
    from horovod_tpu.eager import get_controller
    from horovod_tpu.obs import metrics as obs_metrics

    hvt.init()
    r = hvt.rank()
    ctrl = get_controller()
    groupings = []
    orig = ctrl._execute_one

    def spy(rs, payloads):
        groupings.append(list(rs.tensor_names))
        return orig(rs, payloads)

    ctrl._execute_one = spy
    for step in range(14):
        hs = [hvt.allreduce_async(jnp.full((64,), float(step)),
                                  name=f"sb/{i}", op=hvt.Sum)
              for i in range(4)]
        for h in hs:
            out = hvt.synchronize(h)
            assert float(np.asarray(out)[0]) == 2.0 * step, (step, out)
    assert ctrl.quiesce(timeout=20)
    pred = obs_metrics.counter(
        "hvtpu_controller_predicted_cycles_total").value()
    misp = obs_metrics.counter(
        "hvtpu_controller_mispredicts_total").value()
    return (r, groupings, pred, misp, len(ctrl._predicted))


@pytest.mark.chaos
@pytest.mark.parametrize("force_py", ["0", "1"])
@pytest.mark.parametrize("stream", ["0", "1"])
def test_split_burst_groupings_identical_2proc(force_py, stream):
    """Split-burst divergence matrix over {native, py} × {lockstep,
    streamed} with prediction on by default: a 20ms delay injected on
    rank 1 mid-run splits its bursts across drain boundaries; fused
    groupings must stay identical on both ranks, every predicted cycle
    must be confirmed, and nothing may mispredict."""
    import sys

    import cloudpickle

    env = {
        **_ENV,
        "HVTPU_EAGER_STREAM": stream,
        "HVTPU_FAULT_SPEC": "collective.pre:delay(20)@rank=1,count=6,times=4",
    }
    if force_py == "1":
        env["HVTPU_FORCE_PY_CONTROLLER"] = "1"
    cloudpickle.register_pickle_by_value(sys.modules[__name__])
    try:
        results = run(_split_burst_body, np=2, cpu_devices=1, env=env,
                      start_timeout=300.0)
    finally:
        cloudpickle.unregister_pickle_by_value(sys.modules[__name__])
    (r0, g0, p0, m0, out0), (r1, g1, p1, m1, out1) = sorted(results)
    assert (r0, r1) == (0, 1)
    assert g0 == g1, (g0, g1)
    assert m0 == 0 and m1 == 0  # zero mispredicts, recovered or not
    assert out0 == 0 and out1 == 0  # every prediction confirmed
    # every tensor of every step was applied exactly once on each rank
    applied = sorted(n for grp in g0 for n in grp)
    assert applied == sorted([f"sb/{i}" for i in range(4)] * 14)


def test_eager_collectives_8proc():
    """World-size-8 smoke across REAL processes — the largest world
    this sandbox launches (multi-host shape at process granularity):
    sync + async-fused allreduce, ragged allgather, and a broadcast
    stay correct and the amortized stall watchdog stays transparent."""

    def body():
        import jax.numpy as jnp
        import numpy as np

        import horovod_tpu as hvt

        hvt.init()
        r, s = hvt.rank(), hvt.size()
        assert s == 8
        out = {}

        x = jnp.full((64,), float(r + 1))
        out["sum"] = float(np.asarray(
            hvt.allreduce(x, op=hvt.Sum))[0])  # 1+..+8 = 36
        out["avg"] = float(np.asarray(
            hvt.allreduce(x, op=hvt.Average))[0])  # 4.5

        # async fused burst through the controller
        hs = [hvt.allreduce_async(jnp.full((8,), float(r)),
                                  op=hvt.Sum, name=f"t{i}")
              for i in range(4)]
        outs = [float(np.asarray(hvt.synchronize(h))[0]) for h in hs]
        out["async"] = outs  # sum of ranks 0..7 = 28, every tensor

        g = hvt.allgather(jnp.full((r % 2 + 1, 2), float(r)))
        out["gather_rows"] = int(np.asarray(g).shape[0])  # 4*1+4*2=12

        b = hvt.broadcast(jnp.full((2,), float(r)), root_rank=5)
        out["bcast"] = float(np.asarray(b)[0])
        return (r, out)

    # np=8 on localhost occasionally trips a jaxlib/gloo teardown race
    # (one rank SIGSEGVs mid-collective, code -11, and the peers report
    # "Connection closed by peer").  That race is in the gloo transport,
    # not this engine — retry via the named gloo-teardown policy
    # (core/retry.py) so the semantic assertions below still gate every
    # op, but an infra crash alone doesn't flake CI.
    from horovod_tpu.core import retry as core_retry

    results = core_retry.call(core_retry.GLOO_TEARDOWN, _run, body, np=8)
    assert len(results) == 8
    for _, out in sorted(results):
        assert out["sum"] == 36.0
        assert out["avg"] == 4.5
        assert out["async"] == [28.0] * 4
        assert out["gather_rows"] == 12
        assert out["bcast"] == 5.0
