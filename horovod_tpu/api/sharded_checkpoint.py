"""Pod-scale sharded checkpointing: every process writes its shards.

TPU-native extension of SURVEY.md §5.4 (the reference's idiom is
rank-0-writes + broadcast fanout — fine for one host, quadratically
painful for a pod where rank 0 would have to gather TBs over DCN).
This is the orbax multi-host idiom expressed minimally: each process
serializes only its ADDRESSABLE shards of each global ``jax.Array``,
with a manifest describing which global slices each piece covers;
restore rebuilds global arrays on the CURRENT mesh — which may have a
different process count or sharding than the one that saved — by
assembling every requested device shard from the intersecting saved
pieces.

Layout of one step directory::

    step_000000000042/
      meta.json           # leaf paths, shapes, dtypes (rank 0)
      manifest_p{K}.json  # process K's pieces: leaf -> [(file, slices)]
      pieces/{leaf-hash}.p{K}.{i}.npy

Replicated (or partially replicated) arrays are written exactly once:
only shards with ``replica_id == 0`` are serialized.  Host-side leaves
(plain numpy/python scalars — not global ``jax.Array``s) take RANK 0's
value, written once.

The write is collective and ``meta.json`` is the COMMIT MARKER: rank 0
clears any stale content of the step dir first (a re-save of the same
step after an elastic resize must not leave orphan pieces from the
larger world), every rank then writes its pieces, and only after a
completion barrier does rank 0 write ``meta.json`` — so a step dir
without it (a rank crashed mid-save) is invisible to
``all_steps``/``latest_step`` and resume falls back to the last intact
step.  (Callers wanting the reference's rank-0 convention should use
``api.checkpoint`` instead.)
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import re
import shutil
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..core import durable as core_durable
from ..core import state as core_state
from .checkpoint import list_steps, step_dir_name


def _leaf_key(path_str: str) -> str:
    """Filesystem-safe stable name for a tree path."""
    h = hashlib.sha1(path_str.encode()).hexdigest()[:12]
    safe = re.sub(r"[^A-Za-z0-9_.-]", "_", path_str)[:48]
    return f"{safe}.{h}"


def _norm_slices(index: Tuple[slice, ...], shape: Tuple[int, ...]
                 ) -> List[List[int]]:
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


class ShardedCheckpointer:
    """Distributed save/restore of pytrees of global ``jax.Array``s."""

    def __init__(self, directory: str):
        self.directory = os.path.abspath(directory)

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, step_dir_name(step))

    @staticmethod
    def _barrier(st):
        if st.size > 1:
            from ..comm import eager as eager_comm

            eager_comm.barrier()

    # -- write side ----------------------------------------------------
    def save(self, step: int, tree) -> None:
        st = core_state.require_init("sharded checkpointing")
        pid = jax.process_index()
        target = self._step_dir(step)
        pieces_dir = os.path.join(target, "pieces")

        # 1. rank 0 clears any stale content (a re-save of this step by
        #    a SMALLER world must not leave the old world's orphan
        #    pieces to be blended in at restore), then everyone waits.
        if st.rank == 0:
            shutil.rmtree(target, ignore_errors=True)
            os.makedirs(pieces_dir, exist_ok=True)
        self._barrier(st)
        os.makedirs(pieces_dir, exist_ok=True)

        # 2. every rank writes its pieces + an atomically-renamed
        #    manifest.
        leaves = jax.tree_util.tree_leaves_with_path(tree)
        manifest: Dict[str, List[dict]] = {}
        meta = {"leaves": []}
        for path, leaf in leaves:
            path_str = jax.tree_util.keystr(path)
            key = _leaf_key(path_str)
            if isinstance(leaf, jax.Array):
                arr = leaf
                shards = [
                    (j, shard) for j, shard in
                    enumerate(arr.addressable_shards)
                    if shard.replica_id == 0  # replicas written once
                ]
                shape, dtype = arr.shape, arr.dtype
                pieces = [
                    (f"{key}.p{pid}.{j}.npy", np.asarray(s.data),
                     _norm_slices(s.index, shape))
                    for j, s in shards
                ]
            else:
                # host-side leaf: rank 0's value, written once (every
                # process writing its own full copy would make the
                # restored value depend on manifest merge order)
                val = np.asarray(leaf)
                shape, dtype = val.shape, val.dtype
                pieces = []
                if st.rank == 0:
                    pieces = [(f"{key}.host.npy", val,
                               _norm_slices((slice(None),) * val.ndim,
                                            shape))]
            meta["leaves"].append({
                "path": path_str, "key": key,
                "shape": list(shape), "dtype": str(dtype),
            })
            entries = []
            for fname, data, slices in pieces:
                # serialize first so the manifest records the INTENDED
                # hash/size — a torn/corrupt piece on disk then fails
                # verify_step instead of being silently assembled
                buf = io.BytesIO()
                np.save(buf, data)
                raw = buf.getvalue()
                core_durable.atomic_write(
                    os.path.join(pieces_dir, fname), raw,
                    detail=f"{fname}@step{step}")
                entries.append({
                    "file": fname, "slices": slices,
                    "sha256": hashlib.sha256(raw).hexdigest(),
                    "bytes": len(raw),
                })
            if entries:
                manifest[key] = entries
        mpath = os.path.join(target, f"manifest_p{pid}.json")
        core_durable.atomic_write(
            mpath, json.dumps(manifest).encode(),
            detail=f"manifest_p{pid}@step{step}")

        # 3. completion barrier, THEN the commit marker: a step dir
        #    without meta.json (some rank died mid-save) stays
        #    invisible to all_steps/latest_step.  meta.json rides the
        #    same fsync-then-rename discipline — it is the commit
        #    point, so a torn marker must be impossible, not merely
        #    detectable.
        self._barrier(st)
        if st.rank == 0:
            core_durable.atomic_write(
                os.path.join(target, "meta.json"),
                json.dumps(meta).encode(),
                detail=f"meta@step{step}")
        # and one more so no rank returns before the marker exists
        self._barrier(st)

    # -- read side -----------------------------------------------------
    def all_steps(self) -> List[int]:
        return list_steps(self.directory, require_file="meta.json")

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def verify_step(self, step: int) -> bool:
        """Integrity check of one step as THIS process sees it:
        ``meta.json`` parses, every per-process manifest parses, and
        every piece file matches its recorded sha256 + byte size.
        Entries written before hashes existed (no ``sha256`` key) only
        require the file to be present.  Failures count toward
        ``hvtpu_ckpt_verify_failures_total``."""
        target = self._step_dir(step)
        try:
            with open(os.path.join(target, "meta.json")) as f:
                json.load(f)
            names = os.listdir(target)
        except (OSError, ValueError):
            core_durable.note_verify_failure()
            return False
        for name in sorted(names):
            if not (name.startswith("manifest_")
                    and name.endswith(".json")):
                continue
            try:
                with open(os.path.join(target, name)) as f:
                    manifest = json.load(f)
            except (OSError, ValueError):
                core_durable.note_verify_failure()
                return False
            for entries in manifest.values():
                for e in entries:
                    p = os.path.join(target, "pieces", e["file"])
                    try:
                        with open(p, "rb") as f:
                            raw = f.read()
                    except OSError:
                        core_durable.note_verify_failure()
                        return False
                    if "sha256" in e and (
                            len(raw) != e.get("bytes")
                            or hashlib.sha256(raw).hexdigest()
                            != e["sha256"]):
                        core_durable.note_verify_failure()
                        return False
        return True

    def restore(self, template, *, step: Optional[int] = None):
        """Rebuild the saved tree onto ``template``'s shardings.

        ``template`` is a pytree matching the saved structure whose
        leaves are ``jax.Array``s / ``ShapeDtypeStruct``s carrying a
        ``.sharding`` — the CURRENT mesh's layout, which may differ
        from the saving job's (elastic resize, different slice shape):
        each requested device shard is assembled from the intersecting
        saved pieces.  ``step`` is keyword-only (the sibling
        ``Checkpointer.restore`` takes it positionally — keeping it
        positional here too would invite ``restore(11)`` misuse).
        """
        core_state.require_init("sharded checkpointing")
        if step is None:
            step = self.latest_step()
            if step is None:
                return None
        target = self._step_dir(step)
        with open(os.path.join(target, "meta.json")) as f:
            meta = json.load(f)
        by_path = {l["path"]: l for l in meta["leaves"]}

        pieces: Dict[str, List[dict]] = {}
        for name in os.listdir(target):
            if not name.startswith("manifest_"):
                continue
            with open(os.path.join(target, name)) as f:
                for key, entries in json.load(f).items():
                    pieces.setdefault(key, []).extend(entries)

        def _restore_leaf(path, like):
            # per-leaf piece cache: piece files are leaf-scoped, and a
            # restore-wide cache would hold the process's share of the
            # WHOLE checkpoint in host RAM at once
            cache: Dict[str, np.ndarray] = {}

            def _piece(fname: str) -> np.ndarray:
                if fname not in cache:
                    cache[fname] = np.load(
                        os.path.join(target, "pieces", fname))
                return cache[fname]

            path_str = jax.tree_util.keystr(path)
            info = by_path.get(path_str)
            if info is None:
                raise KeyError(
                    f"checkpoint step {step} has no leaf {path_str!r}"
                )
            shape = tuple(info["shape"])
            dtype = np.dtype(info["dtype"])
            entries = pieces.get(info["key"], [])

            def cb(index: Tuple[slice, ...]) -> np.ndarray:
                want = _norm_slices(index, shape)
                out = np.empty([b - a for a, b in want], dtype)
                filled = 0
                for e in entries:
                    have = e["slices"]
                    inter = [
                        [max(w[0], h[0]), min(w[1], h[1])]
                        for w, h in zip(want, have)
                    ]
                    if any(a >= b for a, b in inter):
                        continue
                    src = _piece(e["file"])[tuple(
                        slice(a - h[0], b - h[0])
                        for (a, b), h in zip(inter, have)
                    )]
                    out[tuple(
                        slice(a - w[0], b - w[0])
                        for (a, b), w in zip(inter, want)
                    )] = src
                    filled += src.size
                if filled < out.size:
                    raise ValueError(
                        f"saved pieces do not cover the requested "
                        f"region of {path_str!r} (have {filled} of "
                        f"{out.size} elements) — incomplete checkpoint?"
                    )
                return out
            sharding = getattr(like, "sharding", None)
            if sharding is None:
                # host-side template leaf (plain numpy / scalar):
                # assemble the full value on host
                return cb(tuple(slice(0, d) for d in shape))
            return jax.make_array_from_callback(shape, sharding, cb)

        return jax.tree_util.tree_map_with_path(_restore_leaf, template)
