"""Minimal fixture twin of native/fallback.py (wire-twin clean case)."""


def _table_key(e):
    return f"{e.process_set_id}\x01{e.name}"
