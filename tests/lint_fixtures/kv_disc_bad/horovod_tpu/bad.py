"""kv-discipline bad fixture: every raw-client leak shape."""

from jax._src import distributed as _jd


def leak_direct_calls():
    client = _jd.global_state.client
    client.key_value_set("hvt/k", "v")
    client.key_value_set("hvt/k2", "v2")  # occurrence-indexed keys
    client.blocking_key_value_get("hvt/k", 1000)
    return client


def leak_chained_call():
    # no binding at all: the call rides the singleton chain directly
    return _jd.global_state.client.key_value_dir_get("hvt/ns/")


def leak_via_alias():
    client = _jd.global_state.client
    kv = client  # alias keeps the raw taint
    kv.key_value_delete("hvt/k")


class Transport:
    def __init__(self):
        client = _jd.global_state.client
        self._kv = client  # escape: raw client stored on self
