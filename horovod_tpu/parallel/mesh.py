"""Mesh layouts for hybrid dp/tp/pp/sp/ep parallelism.

The reference is a pure data-parallel framework (SURVEY.md §2.7): its
only notion of topology is rank/local_rank/cross_rank
(horovod/common/basics.py) and named rank subsets
(horovod/common/process_set.cc ``ProcessSetTable``).  On TPU the
idiomatic generalization is a single ``jax.sharding.Mesh`` whose axes
carry all parallelism dimensions at once, with XLA lowering collectives
onto the ICI torus per axis.  This module owns the mapping from a flat
device list to that mesh, and from *logical* parallelism axes
(dp/tp/pp/sp/ep) to *physical* mesh axes.

Two logical axes may share one physical axis — the standard layouts:

* ``sp`` (sequence/context parallel) defaults to sharing the ``tp``
  group, as in Megatron-LM sequence parallelism: inside attention the
  sequence is resharded over the tensor-parallel group (Ulysses
  all-to-all or ring ppermute), so no extra devices are needed.
* ``ep`` (expert parallel) defaults to sharing the ``dp`` group, the
  usual Switch/GShard layout: experts are spread over data-parallel
  replicas and tokens reach them via all_to_all.

Dedicated ``sp``/``ep`` physical axes are supported when the device
count allows (pass explicit sizes).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

LOGICAL_AXES = ("dp", "pp", "tp", "sp", "ep")


@dataclasses.dataclass(frozen=True)
class MeshLayout:
    """A physical mesh plus the logical→physical axis mapping.

    ``axis("sp")`` returns the physical mesh-axis name to use in
    ``PartitionSpec``/collectives for sequence parallelism, which may be
    ``"tp"`` when sp shares the tensor-parallel group.
    """

    mesh: Mesh
    logical_to_physical: Dict[str, str]

    def axis(self, logical: str) -> str:
        if logical not in self.logical_to_physical:
            raise KeyError(
                f"unknown logical axis {logical!r}; have "
                f"{sorted(self.logical_to_physical)}"
            )
        return self.logical_to_physical[logical]

    def axis_size(self, logical: str) -> int:
        return self.mesh.shape[self.axis(logical)]

    @property
    def dp(self) -> str:
        return self.axis("dp")

    @property
    def tp(self) -> str:
        return self.axis("tp")

    @property
    def pp(self) -> str:
        return self.axis("pp")

    @property
    def sp(self) -> str:
        return self.axis("sp")

    @property
    def ep(self) -> str:
        return self.axis("ep")


def _factor_default(n: int) -> Dict[str, int]:
    """Balanced default factorization of ``n`` devices into pp×dp×tp.

    Heuristic order of preference mirrors how real TPU jobs are laid
    out: tp first (rides the fastest ICI links), then pp, then dp soaks
    up the rest.
    """
    tp = 1
    for cand in (2, 4, 8):
        if n % cand == 0 and cand <= n:
            tp = cand
        else:
            break
    tp = min(tp, 4) if n > 4 else tp
    rem = n // tp
    pp = 2 if rem % 2 == 0 and rem >= 2 else 1
    dp = rem // pp
    return {"pp": pp, "dp": dp, "tp": tp}


def make_layout(
    devices: Optional[Sequence[jax.Device]] = None,
    *,
    dp: Optional[int] = None,
    tp: int = 1,
    pp: int = 1,
    sp: Optional[int] = None,
    ep: Optional[int] = None,
) -> MeshLayout:
    """Build a :class:`MeshLayout` over ``devices``.

    ``dp=None`` means "whatever is left" after tp/pp (and dedicated
    sp/ep, if given).  ``sp``/``ep`` of ``None`` share tp/dp
    respectively; an explicit integer size allocates a dedicated
    physical axis.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)

    phys_sizes: Dict[str, int] = {}
    logical_to_physical = {"dp": "dp", "tp": "tp", "pp": "pp"}

    denom = tp * pp
    if sp is not None:
        phys_sizes["sp"] = sp
        logical_to_physical["sp"] = "sp"
        denom *= sp
    else:
        logical_to_physical["sp"] = "tp"
    if ep is not None:
        phys_sizes["ep"] = ep
        logical_to_physical["ep"] = "ep"
        denom *= ep
    else:
        logical_to_physical["ep"] = "dp"

    if dp is None:
        if n % denom != 0:
            raise ValueError(
                f"{n} devices not divisible by tp*pp(*sp*ep)={denom}"
            )
        dp = n // denom
    total = dp * denom
    if total != n:
        raise ValueError(
            f"mesh size {total} (dp={dp} tp={tp} pp={pp} sp={sp} ep={ep})"
            f" != {n} devices"
        )

    # Physical axis order: slowest-varying first.  pp stages talk only
    # to neighbours (cheap over any link); tp is innermost so its
    # all-reduces ride contiguous ICI; dedicated sp/ep sit between.
    order: Tuple[str, ...] = ("pp", "dp")
    shape = [pp, dp]
    if "ep" in phys_sizes:
        order = order + ("ep",)
        shape.append(phys_sizes["ep"])
    if "sp" in phys_sizes:
        order = order + ("sp",)
        shape.append(phys_sizes["sp"])
    order = order + ("tp",)
    shape.append(tp)

    dev_array = np.asarray(devices, dtype=object).reshape(shape)
    mesh = Mesh(dev_array, order)
    return MeshLayout(mesh=mesh, logical_to_physical=logical_to_physical)


def auto_layout(devices: Optional[Sequence[jax.Device]] = None) -> MeshLayout:
    """Default hybrid layout for ``len(devices)`` chips (pp×dp×tp, with
    sp sharing tp and ep sharing dp)."""
    if devices is None:
        devices = jax.devices()
    f = _factor_default(len(devices))
    return make_layout(devices, dp=f["dp"], tp=f["tp"], pp=f["pp"])
