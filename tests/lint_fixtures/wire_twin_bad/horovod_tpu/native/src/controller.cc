// Minimal fixture twin of native/src/controller.cc (wire-twin clean case).
#include "controller.h"

namespace hvt {

std::string ResponseCache::Signature(const Entry& e) {
  std::ostringstream ss;
  ss << e.name << '|' << int(e.dtype) << '|';
  for (int64_t d : e.shape) ss << d << ',';
  return ss.str();
}

static std::string TableKey(const Entry& e) {
  return std::to_string(e.process_set_id) + '|' + e.name;
}

}  // namespace hvt
