"""Shared flat-buffer pack/unpack used by every fused collective path
(the memcpy-in/out of the reference's fusion buffer,
horovod/common/ops/collective_operations.cc MemcpyInFusionBuffer /
MemcpyOutFusionBuffer — here expressed as XLA concat/slice that fuse
into the surrounding program)."""

from __future__ import annotations

from typing import Any, List, Sequence, Tuple

import jax.numpy as jnp


def pack_flat(tensors: Sequence[Any]):
    """Concatenate tensors into one flat buffer in the promoted dtype.

    Returns (flat, specs) where specs = [(shape, dtype, size), ...] in
    input order.
    """
    tensors = [jnp.asarray(t) for t in tensors]
    if not tensors:
        raise ValueError("pack_flat requires at least one tensor")
    compute_dtype = jnp.result_type(*[t.dtype for t in tensors])
    flat = jnp.concatenate([t.reshape(-1).astype(compute_dtype) for t in tensors])
    specs = [(tuple(t.shape), t.dtype, t.size) for t in tensors]
    return flat, specs


def unpack_flat(flat, specs) -> List[Any]:
    """Inverse of pack_flat: slice, reshape, and cast back."""
    outs, off = [], 0
    for shape, dtype, size in specs:
        outs.append(flat[off : off + size].reshape(shape).astype(dtype))
        off += size
    return outs
