"""CLI: ``python -m tools.hvtputrace {merge,report} <trace-dir>``."""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import (merge, overlap, postmortem_merge, render_overlap,
               render_postmortem, render_report, report)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="hvtputrace",
        description="Merge per-rank hvtpu traces (HVTPU_TRACE dirs) "
                    "into one Perfetto-loadable file and attribute "
                    "stragglers.")
    sub = p.add_subparsers(dest="cmd", required=True)

    pm = sub.add_parser(
        "merge", help="fuse rank*.trace.json into one Chrome-trace "
                      "JSON on rank 0's clock")
    pm.add_argument("trace_dir")
    pm.add_argument("-o", "--output", default=None,
                    help="output path (default: "
                         "<trace-dir>/merged.trace.json)")

    pr = sub.add_parser(
        "report", help="arrival-skew / wait-vs-compute / straggler "
                       "attribution analysis")
    pr.add_argument("trace_dir")
    pr.add_argument("--top", type=int, default=10,
                    help="straggler table size (default 10)")
    pr.add_argument("--json", action="store_true",
                    help="emit the raw report dict as JSON")

    po = sub.add_parser(
        "overlap", help="measured compute/comm overlap: six-way "
                        "per-rank step decomposition (optionally "
                        "joined against an XLA device profile) and a "
                        "top-N exposed-collective list")
    po.add_argument("trace_dir")
    po.add_argument("--xplane", default=None,
                    help="directory holding *.xplane.pb device "
                         "profiles (jax.profiler/obs.profile.trace "
                         "output); omit for host-only attribution")
    po.add_argument("--top", type=int, default=10,
                    help="exposed-collective table size (default 10)")
    po.add_argument("--json", action="store_true",
                    help="emit the raw overlap dict as JSON")

    pp = sub.add_parser(
        "postmortem", help="merge postmortem-<rank>-<gen>.json flight-"
                           "recorder dumps into one clock-corrected "
                           "causal timeline")
    pp.add_argument("dump_dir",
                    help="directory holding the dumps (HVTPU_FLIGHT_DIR)")
    pp.add_argument("--tail", type=int, default=0,
                    help="show only the last N timeline events "
                         "(default: all)")
    pp.add_argument("--json", action="store_true",
                    help="emit the merged dict as JSON")

    args = p.parse_args(argv)
    if args.cmd == "postmortem":
        rep = postmortem_merge(args.dump_dir)
        print(json.dumps(rep, indent=2, default=str) if args.json
              else render_postmortem(rep, tail=args.tail))
        return 0
    if args.cmd == "overlap":
        rep = overlap(args.trace_dir, xplane_dir=args.xplane,
                      top=args.top)
        print(json.dumps(rep, indent=2) if args.json
              else render_overlap(rep))
        return 0
    if args.cmd == "merge":
        events = merge(args.trace_dir)
        out = args.output or os.path.join(args.trace_dir,
                                          "merged.trace.json")
        with open(out, "w", encoding="utf-8") as f:
            json.dump(events, f)
        print(f"wrote {len(events)} events from "
              f"{len({e.get('pid') for e in events})} ranks to {out}")
        return 0
    rep = report(args.trace_dir, top=args.top)
    print(json.dumps(rep, indent=2) if args.json else render_report(rep))
    return 0


if __name__ == "__main__":
    sys.exit(main())
