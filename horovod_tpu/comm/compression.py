"""Gradient compression for the wire.

Parity surface: ``horovod/torch/compression.py`` /
``horovod/tensorflow/compression.py`` — the pluggable ``Compression``
namespace with ``none`` and ``fp16`` compressors exposing
``compress(tensor) -> (tensor, ctx)`` / ``decompress(tensor, ctx)``.

TPU-native notes: compressors are pure jax functions, so they fuse into
the surrounding XLA program (the cast rides the same HBM pass as the
bucket flatten).  ``bf16`` is added because bfloat16 is the TPU wire
format of choice (same 2× saving as fp16, no range loss), and ``int8``
implements EQuARX-style quantized allreduce (PAPERS.md) with per-chunk
scales.
"""

from __future__ import annotations

import jax.numpy as jnp


class Compressor:
    """Interface: compress before the collective, decompress after."""

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError

    @staticmethod
    def wire_dtype(dtype):
        """Dtype that actually crosses the wire for an input of `dtype`
        (the fusion/caching signature — fusion_buffer_manager.cc keys
        buffers on the buffer dtype, not the framework dtype)."""
        return dtype


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    """Cast float tensors to fp16 on the wire, back to original dtype after."""

    @staticmethod
    def compress(tensor):
        ctx = tensor.dtype
        if jnp.issubdtype(tensor.dtype, jnp.floating):
            tensor = tensor.astype(jnp.float16)
        return tensor, ctx

    @staticmethod
    def decompress(tensor, ctx):
        if ctx is not None and tensor.dtype != ctx:
            tensor = tensor.astype(ctx)
        return tensor

    @staticmethod
    def wire_dtype(dtype):
        return jnp.float16 if jnp.issubdtype(dtype, jnp.floating) else dtype


class BF16Compressor(Compressor):
    """bfloat16 wire format — the TPU-idiomatic 2× compression."""

    @staticmethod
    def compress(tensor):
        ctx = tensor.dtype
        if jnp.issubdtype(tensor.dtype, jnp.floating):
            tensor = tensor.astype(jnp.bfloat16)
        return tensor, ctx

    @staticmethod
    def decompress(tensor, ctx):
        if ctx is not None and tensor.dtype != ctx:
            tensor = tensor.astype(ctx)
        return tensor

    @staticmethod
    def wire_dtype(dtype):
        return jnp.bfloat16 if jnp.issubdtype(dtype, jnp.floating) else dtype


class Int8Compressor(Compressor):
    """Block-scaled int8 quantization (EQuARX-style, PAPERS.md).

    Tensors are quantized in chunks of ``BLOCK`` elements with a per-chunk
    absmax scale carried alongside in fp32.  4× wire saving for the
    payload; the scales add 4/BLOCK bytes/element.  Intended for the
    fused-bucket path where tensors are large and flat.
    """

    BLOCK = 1024

    @staticmethod
    def compress(tensor):
        if not jnp.issubdtype(tensor.dtype, jnp.floating):
            return tensor, None
        orig_dtype = tensor.dtype
        orig_shape = tensor.shape
        flat = tensor.reshape(-1)
        n = flat.shape[0]
        block = Int8Compressor.BLOCK
        pad = (-n) % block
        flat = jnp.pad(flat, (0, pad))
        chunks = flat.reshape(-1, block).astype(jnp.float32)
        scale = jnp.max(jnp.abs(chunks), axis=1, keepdims=True) / 127.0
        safe = jnp.where(scale == 0, 1.0, scale)
        q = jnp.clip(jnp.round(chunks / safe), -127, 127).astype(jnp.int8)
        return q, (orig_dtype, orig_shape, n, scale)

    @staticmethod
    def decompress(tensor, ctx):
        if ctx is None:
            return tensor
        orig_dtype, orig_shape, n, scale = ctx
        deq = tensor.astype(jnp.float32) * scale
        return deq.reshape(-1)[:n].reshape(orig_shape).astype(orig_dtype)

    @staticmethod
    def wire_dtype(dtype):
        return jnp.int8 if jnp.issubdtype(dtype, jnp.floating) else dtype


class Compression:
    """Namespace matching the reference API: ``Compression.none`` etc."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
    int8 = Int8Compressor

    @staticmethod
    def from_name(name: str):
        try:
            return {
                "none": NoneCompressor,
                "fp16": FP16Compressor,
                "bf16": BF16Compressor,
                "int8": Int8Compressor,
            }[name]
        except KeyError:
            raise ValueError(f"unknown compression {name!r}") from None
