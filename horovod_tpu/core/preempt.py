"""Graceful preemption: coordinated drain, emergency commit, and a
planned elastic departure.

TPU fleets lose workers to *planned* events (spot/preemptible reclaims,
maintenance windows) far more often than to crashes.  Without this
module a SIGTERM'd worker dies mid-collective: peers hit stall aborts,
the elastic driver burns a restart-budget strike and a blacklist strike
on a healthy host, and training rolls back to the last periodic commit.
With it, the preemption notice window is used proactively:

1. **Notice** — the departing rank learns it is going away, from any of
   three sources: the configured signal (``HVTPU_PREEMPT_SIGNAL``,
   default SIGTERM), a pollable notice file (``HVTPU_PREEMPT_NOTICE_FILE``,
   the TPU maintenance-event delivery style), or the fault-injection
   action ``preempt`` (core/faults.py), which makes the whole path
   deterministically testable.  The watcher publishes
   ``hvtdrain/<generation>/notice/<rank>`` through the coordination KV
   (ResilientKV) so every peer observes the pending departure within
   one poll.

2. **Drain commit** — at its next commit boundary the departing rank
   publishes ``plan/<rank> = commit_count + 1``: the commit count every
   rank must reach before draining.  Commit counts advance in lockstep
   (the elastic contract), so all ranks reach the agreed boundary
   together, making an unconditionally *durable* save safe even for
   collective savers (``ShardedJaxState``).  The one-step lookahead
   gives peers a full step to learn the plan through the watcher.

3. **Planned exit** — after the drain commit the departing rank exits
   with :data:`DRAIN_EXIT_CODE` (distinct from the crash and reset
   codes); peers raise :class:`~.exceptions.DrainInterrupt` so the
   committed state stands (no rollback).  The elastic driver classifies
   the exit as a planned departure: no restart-budget strike, no
   blacklist strike, immediate resize, and the next incarnation resumes
   from the drain commit — zero lost steps.

The whole exchange is bounded by ``HVTPU_DRAIN_GRACE_SECONDS``: if no
commit boundary arrives in time, the departing rank force-exits with
:data:`DRAIN_EXIT_CODE` anyway (the departure stays planned; progress
since the last durable commit is lost).  During the grace window the
stall inspectors (comm/stall.py) report "rank N draining" instead of
firing a heartbeat abort, and the eager controller drains its burst
gate immediately so in-flight collectives complete before the commit.

Hot-path cost when nothing is draining: one module attribute read
(:data:`PENDING`), the same idiom as ``faults.ACTIVE``.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import sys
import threading
from typing import Dict, Optional

from . import clock
from ..obs import flight
from ..obs import metrics as obs_metrics
from ..obs import tracing

logger = logging.getLogger("horovod_tpu")

#: Exit code the elastic driver classifies as a PLANNED departure (no
#: restart-budget strike, no blacklist strike).  Distinct from the
#: worker reset code (73), plain crashes, and signal deaths.
DRAIN_EXIT_CODE = 79

#: Module-level fast path: False means no drain is pending anywhere in
#: the world as seen by this process — commit boundaries and the eager
#: burst gate check this single attribute and skip everything else.
PENDING = False

# KV namespace for the drain protocol; namespaced by the ELASTIC
# generation (env HVTPU_ELASTIC_GENERATION — identical on every rank of
# one incarnation, unlike the per-process init counter) so a relaunched
# world can never read the previous incarnation's markers.
_NS = "hvtdrain"

# Watcher poll interval.  Deliberately a constant, not a knob: at 0.2s
# the notice→peer-visibility latency is far below any realistic grace
# window, and the KV load is one directory read per rank per poll.
_POLL_S = 0.2

_M_NOTICES = obs_metrics.counter(
    "hvtpu_preempt_notices_total",
    "Preemption notices accepted by this rank, by source "
    "(signal | file | fault | api).")
_M_DRAIN_COMMIT_S = obs_metrics.histogram(
    "hvtpu_drain_commit_seconds",
    "Notice-to-drain-commit latency: how much of the preemption grace "
    "window the coordinated emergency commit consumed.")

_coord: Optional["_DrainCoordinator"] = None
_module_lock = threading.Lock()

# Thread-local coordinator override: the fabric simulator installs one
# _DrainCoordinator per virtual-rank thread (see horovod_tpu/sim) so N
# ranks' drain protocols coexist in one process without sharing the
# module-global fast path.  Production never touches this.
_tls = threading.local()


def resolve_signal(name) -> Optional[signal.Signals]:
    """'SIGTERM' / 'TERM' / '15' -> signal.Signals, None if unknown."""
    s = str(name or "").strip()
    if not s:
        return None
    if s.isdigit():
        try:
            return signal.Signals(int(s))
        except ValueError:
            return None
    s = s.upper()
    if not s.startswith("SIG"):
        s = "SIG" + s
    got = getattr(signal, s, None)
    return got if isinstance(got, signal.Signals) else None


def configured_signal() -> signal.Signals:
    """The preemption-notice signal (HVTPU_PREEMPT_SIGNAL, default
    SIGTERM).  Shared with the elastic driver's drain forwarding so
    both sides always speak the same signal."""
    sig = resolve_signal(os.environ.get("HVTPU_PREEMPT_SIGNAL"))
    return sig if sig is not None else signal.SIGTERM


class _DrainCoordinator:
    """Per-process drain state: notice intake, the KV watcher thread,
    and the commit-boundary agreement protocol."""

    def __init__(self, rank: int, size: int, grace_s: float,
                 notice_file: Optional[str], generation: int,
                 client=None, *, start_watcher: bool = True,
                 shared_pending: bool = True, exit_fn=None):
        self._kv = client
        self.rank = rank
        self.size = size
        self.grace_s = max(0.5, float(grace_s))
        self.notice_file = notice_file
        self.gen = generation
        # shared_pending=False (sim): drain state stays per-instance so
        # N coordinators in one process never see each other's notices
        # through the module global.  exit_fn (sim) replaces os._exit.
        self._shared_pending = shared_pending
        self._exit_fn = exit_fn
        self._pending_local = False
        self._lock = threading.Lock()
        # Set from the signal handler WITHOUT the lock (a handler runs
        # on the main thread between bytecodes; taking a non-reentrant
        # lock the interrupted frame may hold would deadlock) — plain
        # attribute writes are atomic under the GIL, and every other
        # accessor tolerates reading them a poll late.
        self._departing = False
        self._reason = ""
        self._notice_t = 0.0
        # watcher-thread-only bookkeeping
        self._notice_posted = False
        # The notice KEY may be posted from either the watcher or the
        # commit thread (see drain_boundary) — separate flag, lock-
        # guarded; a benign double-post of the identical value is the
        # worst a race here can produce.
        self._notice_key_posted = False  # hvtpulint: guarded-by(_lock)
        self._grace_timer: Optional[clock.Timer] = None
        # rank -> first-seen monotonic time of a peer's drain notice
        self._peer_notices: Dict[int, float] = {}  # hvtpulint: guarded-by(_lock)
        self._plans: Dict[int, int] = {}  # hvtpulint: guarded-by(_lock)
        self._plan: Optional[int] = None  # hvtpulint: guarded-by(_lock)
        self._drained = False  # hvtpulint: guarded-by(_lock)
        self._wake = threading.Event()
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if start_watcher:
            self._thread = threading.Thread(
                target=self._watch_loop, name="hvtpu-preempt-watch",
                daemon=True)
            self._thread.start()

    # -- notice intake (signal-handler safe) ---------------------------
    def notice(self, source: str) -> None:
        """Accept a preemption notice for THIS rank.  Safe to call from
        a signal handler: flag writes and an Event set only — all KV,
        metrics, and tracing work happens on the watcher thread."""
        if self._departing:
            return
        self._reason = source
        self._notice_t = clock.monotonic()
        self._departing = True
        self._mark_pending()
        self._wake.set()

    @property
    def pending(self) -> bool:
        """Any drain pending anywhere in the world, as seen by this
        coordinator (instance state; never the module global)."""
        return self._pending_local

    def _mark_pending(self) -> None:
        self._pending_local = True
        if self._shared_pending:
            global PENDING
            PENDING = True

    # -- watcher -------------------------------------------------------
    def _watch_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                self._poll_once()
            except Exception:
                # the watcher must never take the job down on its own
                logger.debug("preempt watcher error", exc_info=True)
            self._wake.wait(_POLL_S)
            self._wake.clear()

    def _poll_once(self) -> None:
        # 1. pollable notice file (TPU maintenance-event delivery)
        if (not self._departing and self.notice_file
                and os.path.exists(self.notice_file)):
            self.notice("file")
        # 2. publish this rank's departure exactly once
        if self._departing and not self._notice_posted:
            self._notice_posted = True
            _M_NOTICES.inc(source=self._reason)
            logger.warning(
                "preemption notice (%s): rank %d draining; coordinating "
                "an emergency commit within %.0fs grace",
                self._reason, self.rank, self.grace_s)
            if tracing.ACTIVE:
                tracing.instant(
                    "drain_begin", rank=self.rank, source=self._reason,
                    grace_s=self.grace_s)
            if flight.ACTIVE:
                flight.note("drain_begin", rank=self.rank,
                            source=self._reason, grace_s=self.grace_s)
            self._arm_grace_timer()
            self._post_notice_key()
        # 3. observe peers' notices and drain plans
        if self._kv is None or self.size <= 1:
            return
        self._observe_peers()

    def _post_notice_key(self) -> None:
        """Publish this rank's notice marker exactly once (idempotent
        across the watcher and commit threads)."""
        with self._lock:
            if self._notice_key_posted:
                return
            self._notice_key_posted = True
        if self._kv is not None:
            self._kv.key_value_set(
                f"{_NS}/{self.gen}/notice/{self.rank}",
                json.dumps({"reason": self._reason,
                            "grace_s": self.grace_s}))

    def _observe_peers(self) -> None:
        entries = self._dir_entries()
        now = clock.monotonic()
        newly_seen = []
        any_peer = False
        with self._lock:
            for kind, r, v in entries:
                if r == self.rank:
                    continue
                if kind == "notice":
                    any_peer = True
                    if r not in self._peer_notices:
                        self._peer_notices[r] = now
                        newly_seen.append(r)
                elif kind == "plan":
                    any_peer = True
                    try:
                        self._plans[r] = int(v)
                    except (TypeError, ValueError):
                        pass
        for r in newly_seen:
            logger.warning(
                "rank %d draining (preemption notice); emergency "
                "commit at the next agreed step boundary", r)
        if any_peer:
            self._mark_pending()

    def _dir_entries(self):
        """[(kind, rank, value)] under this generation's namespace —
        one directory read when the client supports it, per-rank
        try_get fallback otherwise (test fakes, older clients)."""
        prefix = f"{_NS}/{self.gen}/"
        out = []
        dir_get = getattr(self._kv, "key_value_dir_get", None)
        if dir_get is not None:
            try:
                for k, v in dir_get(prefix):
                    parts = k.rsplit("/", 2)
                    if len(parts) < 2:
                        continue
                    kind, r = parts[-2], parts[-1]
                    try:
                        out.append((kind, int(r), v))
                    except ValueError:
                        continue
                return out
            except Exception:
                out = []
        for kind in ("notice", "plan"):
            for r in range(self.size):
                if r == self.rank:
                    continue
                try:
                    v = self._kv.key_value_try_get(f"{prefix}{kind}/{r}")
                except Exception:
                    v = None
                if v is not None:
                    out.append((kind, r, v))
        return out

    # -- grace bound ---------------------------------------------------
    def _arm_grace_timer(self) -> None:
        self._grace_timer = clock.call_later(
            self.grace_s, self._grace_expired)

    def _grace_expired(self) -> None:
        with self._lock:
            if self._drained:
                return
        # No commit boundary arrived inside the grace window (the loop
        # may be wedged, or the window was simply too short).  Exit
        # with the DRAIN code anyway: the departure stays planned (no
        # budget/blacklist strike), but progress since the last durable
        # commit is lost — the bounded-grace half of the contract.
        print(
            f"hvtpu.preempt: drain grace ({self.grace_s:.0f}s) expired "
            f"before a commit boundary; rank {self.rank} exiting "
            f"{DRAIN_EXIT_CODE} without a drain commit (planned "
            "departure; progress since the last durable commit is "
            "lost)", file=sys.stderr, flush=True)
        if tracing.ACTIVE:
            tracing.instant("drain_exit", rank=self.rank,
                            committed=False)
        if flight.ACTIVE:
            flight.note("drain_exit", rank=self.rank, committed=False,
                        grace_s=self.grace_s)
        # force-exit without a commit boundary is a fatal-path story
        # worth a black box: what was the loop doing all grace long?
        flight.dump_postmortem("drain_grace_expired",
                               grace_s=self.grace_s)
        self._planned_exit()

    def _planned_exit(self) -> None:
        """Leave the process with the planned-departure code.  The sim
        substitutes ``exit_fn`` (raising a virtual-exit control-flow
        exception) and skips the real-process teardown."""
        if self._exit_fn is not None:
            self._exit_fn(DRAIN_EXIT_CODE)
            return
        # Drain any queued background checkpoint writes first: the
        # drain commit may still be sitting in the durable writer's
        # queue, and os._exit skips atexit hooks.
        try:
            from . import durable as core_durable

            core_durable.quiesce_writers()
        except Exception:
            pass
        self._quiesce_data_loaders()
        try:
            from . import state as core_state

            core_state.shutdown()
        except Exception:
            pass
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(DRAIN_EXIT_CODE)

    # -- commit-boundary protocol --------------------------------------
    def drain_boundary(self, commit_count: int) -> bool:
        """Called by ``State.commit()`` (via :func:`drain_boundary`)
        once a drain is pending.  Returns True when THIS commit is the
        agreed drain commit: every published plan (commit-count target)
        has been reached.  The departing rank publishes
        ``commit_count + 1`` on its first boundary after the notice, so
        peers get one full step — including its collectives — to learn
        the plan before anyone drains."""
        post = None
        with self._lock:
            if self._drained:
                return False
            if self._departing and self._plan is None:
                self._plan = commit_count + 1
                post = self._plan
            plans = dict(self._plans)
            if self._plan is not None:
                plans[self.rank] = self._plan
        if post is not None:
            logger.warning(
                "rank %d drain plan: emergency commit at step boundary "
                "%d", self.rank, post)
            if self._kv is not None:
                try:
                    # Key-order invariant (found by the fabric
                    # simulator): a notice arriving within one watcher
                    # poll of a commit boundary would otherwise publish
                    # the PLAN before the NOTICE, and a peer scanning
                    # between the two reaches its drain commit with no
                    # notice recorded — DrainInterrupt then misattributes
                    # the departure (rank=-1).  Posting the notice here
                    # first guarantees every observer of a plan has also
                    # seen its notice.
                    self._post_notice_key()
                    self._kv.key_value_set(
                        f"{_NS}/{self.gen}/plan/{self.rank}", str(post))
                except Exception:
                    logger.warning(
                        "could not publish the drain plan; peers will "
                        "recover through the collective-failure path",
                        exc_info=True)
        if not plans or commit_count < min(plans.values()):
            return False
        # This is the drain commit: let in-flight eager collectives
        # finish before the durable save so no negotiation is abandoned
        # mid-burst (controller.quiesce is a no-op when idle).
        self._quiesce_controller()
        return True

    def _quiesce_data_loaders(self) -> None:
        """Stop input prefetch threads before the drain exit so none is
        mid-``device_put`` when the process leaves.  The drain commit
        already captured the delivered cursor, so parked batches are
        simply re-fetched by the next incarnation."""
        try:
            from ..data.loader import quiesce_all

            quiesce_all()
        except Exception:
            logger.debug("pre-drain data loader quiesce failed",
                         exc_info=True)

    def _quiesce_controller(self) -> None:
        try:
            from . import state as core_state

            c = core_state.global_state().controller
            if c is not None and hasattr(c, "quiesce"):
                c.quiesce(timeout=min(5.0, self.grace_s / 2))
        except Exception:
            logger.debug("pre-drain controller quiesce failed",
                         exc_info=True)

    def finish_drain(self, commit_count: int) -> None:
        """After the drain commit persisted: record telemetry, then
        either exit (departing rank) or raise DrainInterrupt (peers) so
        the committed state stands without a rollback."""
        with self._lock:
            if self._drained:
                return
            self._drained = True
            peer_ranks = sorted(self._peer_notices)
        departing = self._departing
        t0 = self._notice_t
        if not departing:
            # peers measure from their first observation of any notice
            with self._lock:
                t0 = min(self._peer_notices.values(), default=0.0)
        elapsed = (clock.monotonic() - t0) if t0 else 0.0
        _M_DRAIN_COMMIT_S.observe(elapsed)
        if tracing.ACTIVE:
            tracing.instant(
                "drain_commit", rank=self.rank, commit=commit_count,
                departing=departing, waited_s=round(elapsed, 3))
        if flight.ACTIVE:
            flight.note("drain_commit", rank=self.rank,
                        commit=commit_count, departing=departing,
                        waited_s=round(elapsed, 3))
        if self._grace_timer is not None:
            self._grace_timer.cancel()
        if departing:
            print(
                f"hvtpu.preempt: drain commit done at step boundary "
                f"{commit_count} ({elapsed:.1f}s after the notice); "
                f"rank {self.rank} exiting {DRAIN_EXIT_CODE} for a "
                "planned departure", file=sys.stderr, flush=True)
            if tracing.ACTIVE:
                tracing.instant("drain_exit", rank=self.rank,
                                committed=True)
            # production path posts the stall goodbye tombstone and
            # flushes traces before the coordination client goes away
            self._planned_exit()
            return
        from .exceptions import DrainInterrupt

        raise DrainInterrupt(
            rank=peer_ranks[0] if peer_ranks else -1)

    # -- read-side surface ---------------------------------------------
    def draining_ranks(self) -> Dict[int, float]:
        """rank -> grace seconds remaining, for every rank currently
        inside its drain window.  Peer windows are measured from OUR
        first observation of the notice (clock-skew-free, and slightly
        generous — the safe direction for holding a stall abort).
        Entries disappear when the window expires, so normal stall
        semantics resume if a drain wedges."""
        now = clock.monotonic()
        out: Dict[int, float] = {}
        if self._departing:
            rem = self.grace_s - (now - self._notice_t)
            if rem > 0:
                out[self.rank] = rem
        with self._lock:
            peers = dict(self._peer_notices)
        for r, t0 in peers.items():
            rem = self.grace_s - (now - t0)
            if rem > 0:
                out[r] = rem
        return out

    def debug_state(self) -> dict:
        draining = self.draining_ranks()
        with self._lock:
            plans = dict(self._plans)
            if self._plan is not None:
                plans[self.rank] = self._plan
            drained = self._drained
        return {
            "pending": self._pending_local,
            "departing": self._departing,
            "reason": self._reason or None,
            "drained": drained,
            "grace_s": self.grace_s,
            "notice_file": self.notice_file,
            "plans": {str(r): p for r, p in sorted(plans.items())},
            "draining_ranks": {str(r): round(rem, 1)
                               for r, rem in sorted(draining.items())},
        }

    def stop(self) -> None:
        self._stopped.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        if self._grace_timer is not None:
            self._grace_timer.cancel()


# -- module surface (what the rest of the framework calls) -------------

def use(coord: Optional["_DrainCoordinator"]) -> None:
    """Install ``coord`` as the CALLING THREAD's drain coordinator
    (None to uninstall).  The fabric simulator gives each virtual-rank
    thread its own coordinator this way; every module-level entry point
    below then routes to it instead of the process-wide one."""
    _tls.coord = coord


def _current() -> Optional["_DrainCoordinator"]:
    c = getattr(_tls, "coord", None)
    return c if c is not None else _coord


def pending() -> bool:
    """Is any drain pending, as seen by the calling thread?  The hot
    path: one thread-local read plus one attribute read.  Prefer this
    over reading :data:`PENDING` directly — the module global cannot be
    virtualised per rank."""
    c = getattr(_tls, "coord", None)
    if c is not None:
        return c.pending
    return PENDING


def install(cfg, rank: int, size: int, client=None) -> None:
    """Arm the drain coordinator (called by ``core.state.init`` for
    elastic jobs): start the watcher, install the preemption-signal
    handler, and remember the prior disposition for uninstall."""
    global _coord
    with _module_lock:
        if _coord is not None:
            _uninstall_locked()
        gen = int(os.environ.get("HVTPU_ELASTIC_GENERATION", "0") or 0)
        if hasattr(client, "add_journal_prefix"):
            # Drain accounting is durable history a coordinator-loss
            # relaunch must see: journal this rank's writes under the
            # drain namespace for replay (core/journal.py).
            client.add_journal_prefix(f"{_NS}/")
        _coord = _DrainCoordinator(
            rank=rank, size=size,
            grace_s=getattr(cfg, "drain_grace_seconds", 30.0),
            notice_file=getattr(cfg, "preempt_notice_file", None),
            generation=gen, client=client)
        obs_metrics.register_debug_provider("drain", debug_state)
        signame = getattr(cfg, "preempt_signal", "SIGTERM")
        sig = resolve_signal(signame) or signal.SIGTERM
        coord = _coord

        def handler(signum, frame):
            coord.notice("signal")

        try:
            _prev_handler[:] = [sig, signal.signal(sig, handler)]
        except ValueError:
            # non-main thread (tests importing under a runner thread):
            # signal delivery degrades to the notice file / fault
            # action — worth saying, since a real preemption would
            # then kill the process with the default disposition.
            _prev_handler[:] = []
            logger.warning(
                "could not install the %s preemption handler "
                "(signal.signal outside the main thread); preemption "
                "notices degrade to the notice file / fault action",
                sig.name)


_prev_handler: list = []


def _uninstall_locked() -> None:
    global _coord, PENDING
    if _coord is not None:
        _coord.stop()
        _coord = None
        try:
            obs_metrics.unregister_debug_provider("drain")
        except Exception:
            pass
    if _prev_handler:
        sig, prev = _prev_handler
        _prev_handler[:] = []
        try:
            signal.signal(sig, prev)
        except (ValueError, TypeError):
            pass
    PENDING = False


def uninstall() -> None:
    with _module_lock:
        _uninstall_locked()


def installed() -> bool:
    return _coord is not None


def notice(source: str = "api") -> None:
    """Deliver a preemption notice to this rank programmatically (the
    ``preempt`` fault action and tests use this)."""
    coord = _current()
    if coord is None:
        logger.warning(
            "preemption notice (%s) ignored: the drain coordinator is "
            "not installed (non-elastic job, or before init)", source)
        return
    coord.notice(source)


def drain_boundary(commit_count: int) -> bool:
    """True when this commit boundary is the agreed drain commit.
    Callers guard on :func:`pending` first (hot path)."""
    coord = _current()
    if coord is None:
        return False
    return coord.drain_boundary(commit_count)


def finish_drain(commit_count: int) -> None:
    """Complete the drain after the commit persisted: the departing
    rank exits :data:`DRAIN_EXIT_CODE`; peers raise DrainInterrupt."""
    coord = _current()
    if coord is not None:
        coord.finish_drain(commit_count)


def draining_ranks() -> Dict[int, float]:
    """rank -> remaining grace seconds for ranks currently draining
    (stall inspectors report these instead of blaming them)."""
    coord = _current()
    if coord is None:
        return {}
    return coord.draining_ranks()


def debug_state() -> dict:
    coord = _current()
    if coord is None:
        return {"pending": pending(), "installed": False}
    return coord.debug_state()
