// Chrome-trace timeline writer.
//
// Parity: horovod/common/timeline.cc (Timeline, TimelineController) —
// the HOROVOD_TIMELINE chrome://tracing JSON of per-tensor lifecycle
// phases (NEGOTIATE_* -> QUEUE -> fusion memcpy -> collective).  Here
// the phase vocabulary is the TPU pipeline (NEGOTIATE -> QUEUE ->
// PACK -> XLA_COLLECTIVE -> UNPACK); the file format is identical, so
// the same chrome://tracing / Perfetto UI reads both.
#pragma once

#include <cstdio>
#include <mutex>
#include <string>

namespace hvt {

class TimelineWriter {
 public:
  TimelineWriter(const std::string& path, int32_t rank);
  ~TimelineWriter();
  bool ok() const { return f_ != nullptr; }
  // ph: 'B' begin, 'E' end, 'X' complete (with dur_us), 'i' instant.
  void Event(const std::string& name, char ph, const std::string& category,
             double ts_us, double dur_us = 0);
  void MarkCycle(double ts_us);
  void Flush();

 private:
  std::mutex mu_;
  FILE* f_ = nullptr;
  int32_t rank_;
  bool first_ = true;
};

}  // namespace hvt
