"""Clean simulator module: virtual clock + seeded RNG streams only."""

import random

from horovod_tpu.core import clock


def wait_and_draw(kernel, seed):
    kernel.sleep(0.5)           # virtual sleep: fine
    now = clock.monotonic()     # seam read: fine
    rng = random.Random(seed)   # seeded generator instance: allowed
    return now + rng.random()
