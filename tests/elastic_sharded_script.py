"""Elastic training script holding GLOBAL sharded arrays
(ShardedJaxState) — used by the pod-resize fault-injection test: the
driver relaunches at a different world size and sync() must reshard
the committed params onto the new global mesh.

Each epoch adds +1 to every element of a world-sharded parameter
vector, so the committed value encodes exactly how many epochs ran —
replays or lost state are immediately visible.
"""

import os
import time


def main():
    import numpy as np

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    import horovod_tpu as hvt
    import horovod_tpu.elastic as elastic

    hvt.init()
    epochs = int(os.environ.get("ELASTIC_EPOCHS", "6"))
    sleep_s = float(os.environ.get("EPOCH_SLEEP", "0.3"))

    mesh = hvt.world_mesh()
    n_dev = mesh.devices.size
    init_w = np.zeros((24, 4), np.float32)  # divisible by 4 AND 6 devices
    state = elastic.ShardedJaxState(
        params=jax.make_array_from_callback(
            init_w.shape, NamedSharding(mesh, P("world")),
            lambda i: init_w[i]),
        epoch=0,
    )

    @elastic.run
    def train(state):
        import jax.numpy as jnp

        while state.epoch < epochs:
            # one "step": params += 1 everywhere (value == epochs run)
            state.params = jax.tree_util.tree_map(
                lambda a: a + jnp.ones_like(a), state.params
            )
            state.epoch += 1
            if hvt.rank() == 0:
                first = float(np.asarray(
                    state.params.addressable_data(0)).ravel()[0])
                print(
                    f"EPOCH epoch={state.epoch} size={hvt.size()} "
                    f"ndev={n_dev} w0={first}",
                    flush=True,
                )
            time.sleep(sleep_s)
            state.commit()

    train(state)
    if hvt.rank() == 0:
        final = float(np.asarray(
            state.params.addressable_data(0)).ravel()[0])
        print(f"DONE size={hvt.size()} epoch={state.epoch} w0={final}",
              flush=True)


if __name__ == "__main__":
    main()
