"""Black-box flight recorder: a bounded ring of structured events plus
crash postmortems.

Aviation flight recorders answer "what was the aircraft doing in the
last N minutes" after the fact; this module does the same for a
training job.  Every interesting seam the runtime already has —
step-boundary records (obs/stepprof), controller mispredicts/resyncs
(eager/controller), KV retries (core/retry), stall warnings
(comm/stall), drain transitions (core/preempt), elastic restarts,
audit verdicts (core/audit), durable-writer commits (core/durable),
anomaly incidents (obs/anomaly) — appends ONE cheap event to a
per-process ring (``deque(maxlen=HVTPU_FLIGHT_WINDOW)``).  The ring
costs a tuple and a deque append per event and is always on unless
``HVTPU_FLIGHT=0``.

When a job dies on a *fatal* path — stall abort,
``HvtpuMismatchError``/``HvtpuDivergenceError``, restart-budget
exhaustion, an unhandled worker exception, drain-grace force-exit —
or on demand via ``SIGUSR2``, :func:`dump_postmortem` writes
``postmortem-<rank>-<gen>.json`` into ``HVTPU_FLIGHT_DIR`` (default:
the trace dir, else CWD) containing the ring, every registered
``/debug`` provider snapshot, and a final metrics snapshot.
``python -m tools.hvtputrace postmortem <dir>`` merges the per-rank
dumps into one clock-corrected causal timeline.

Zero-cost-when-off contract (same as obs/tracing): hot seams guard
with ``if flight.ACTIVE: flight.note(...)`` — a single module
attribute test when disabled, timeit-enforced in tests/test_flight.py.

Event timestamps are read through the ``core/clock`` seam so the
fabric simulator records deterministic virtual-time rings.
"""

from __future__ import annotations

import collections
import json
import os
import signal
import threading
from typing import Any, Deque, Dict, List, Optional, Tuple

from ..core import clock as _clock
from . import metrics as _metrics

__all__ = [
    "ACTIVE",
    "FlightRecorder",
    "install",
    "uninstall",
    "note",
    "dump_postmortem",
    "get_recorder",
    "env_enabled",
    "env_window",
    "POSTMORTEM_SCHEMA",
]

POSTMORTEM_SCHEMA = "hvtpu-postmortem-v1"

_M_EVENTS = _metrics.counter(
    "hvtpu_flight_events_total",
    "Structured events appended to the flight-recorder ring.")
_M_POSTMORTEMS = _metrics.counter(
    "hvtpu_postmortems_total",
    "Postmortem dumps written, labeled by trigger reason.")


def env_enabled() -> bool:
    """``HVTPU_FLIGHT`` gate (default on — the recorder is the black
    box; opt *out*, not in)."""
    return os.environ.get("HVTPU_FLIGHT", "1").lower() not in (
        "0", "false", "off")


def env_window() -> int:
    """``HVTPU_FLIGHT_WINDOW``: ring capacity in events."""
    try:
        n = int(os.environ.get("HVTPU_FLIGHT_WINDOW", "2048"))
    except ValueError:
        return 2048
    return max(16, n)


def _env_dir() -> str:
    # explicit flight dir > trace dir > the job's durable state dir >
    # CWD as the last resort — a fleet/elastic job must never litter
    # the operator's working directory with postmortems
    return (os.environ.get("HVTPU_FLIGHT_DIR")
            or os.environ.get("HVTPU_TRACE")
            or os.environ.get("HVTPU_ELASTIC_STATE_DIR")
            or ".")


class FlightRecorder:
    """The per-process ring.  Appends store ``(t_mono, kind, fields)``
    tuples — no per-event dict churn; dicts materialize only at dump
    time.  Thread-safe: one lock around the deque."""

    def __init__(self, *, rank: Any = 0, size: int = 1,
                 generation: int = 0, out_dir: Optional[str] = None,
                 window: Optional[int] = None):
        self.rank = rank
        self.size = size
        self.generation = generation
        self.out_dir = out_dir or _env_dir()
        self._lock = threading.Lock()
        self._ring: Deque[Tuple[float, str, Optional[dict]]] = \
            collections.deque(maxlen=window or env_window())
        self._dropped = 0          # hvtpulint: guarded-by(_lock)
        self._appended = 0         # hvtpulint: guarded-by(_lock)
        self._last_t: Dict[str, float] = {}  # hvtpulint: guarded-by(_lock)
        self._reasons: List[str] = []
        # wall↔monotonic anchor pair: dump converts ring timestamps to
        # wall time as wall_anchor + (t - mono_anchor), and the merge
        # tool cross-corrects ranks from these plus the tracing offset.
        self.wall_anchor = _clock.wall()
        self.mono_anchor = _clock.monotonic()

    # -- hot path --------------------------------------------------------
    def note(self, kind: str, fields: Optional[dict] = None) -> None:
        t = _clock.monotonic()
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self._dropped += 1
            self._ring.append((t, kind, fields))
            self._appended += 1
            self._last_t[kind] = t
        _M_EVENTS.inc()

    # -- read side -------------------------------------------------------
    def last_event_t(self, kind: str) -> Optional[float]:
        """Monotonic timestamp of the newest event of ``kind`` (None if
        never seen) — the fleet health summary's stall-age input."""
        with self._lock:
            return self._last_t.get(kind)

    def events(self) -> List[dict]:
        """Ring contents as dicts with wall-clock timestamps (oldest
        first)."""
        with self._lock:
            ring = list(self._ring)
        base = self.wall_anchor - self.mono_anchor
        out = []
        for t, kind, fields in ring:
            ev = {"t_wall": round(t + base, 6), "kind": kind}
            if fields:
                ev.update(fields)
            out.append(ev)
        return out

    def debug_state(self) -> dict:
        with self._lock:
            n = len(self._ring)
            kinds: Dict[str, int] = {}
            for _, kind, _f in self._ring:
                kinds[kind] = kinds.get(kind, 0) + 1
            return {
                "active": True,
                "rank": self.rank,
                "generation": self.generation,
                "window": self._ring.maxlen,
                "events": n,
                "appended": self._appended,
                "dropped": self._dropped,
                "kinds": kinds,
                "reasons": list(self._reasons),
            }

    # -- postmortem ------------------------------------------------------
    def dump(self, reason: str, **fields) -> Optional[str]:
        """Write ``postmortem-<rank>-<gen>.json`` (atomic replace).
        Repeated dumps overwrite — the newest ring wins — with every
        trigger reason accumulated in ``reasons``.  Never raises: a
        postmortem failure must not mask the original fatal error."""
        try:
            with self._lock:
                if reason not in self._reasons:
                    self._reasons.append(reason)
                reasons = list(self._reasons)
            clock_meta: Dict[str, Any] = {
                "wall_anchor": self.wall_anchor,
                "mono_anchor": self.mono_anchor,
            }
            try:
                from . import tracing as _tracing
                tracer = _tracing.get_tracer()
                if tracer is not None:
                    clock_meta["offset_us"] = tracer.offset_us
                    clock_meta["error_bound_us"] = tracer.offset_error_us
            except Exception:
                pass
            doc = {
                "schema": POSTMORTEM_SCHEMA,
                "rank": self.rank,
                "size": self.size,
                "generation": self.generation,
                "reason": reason,
                "reasons": reasons,
                "t_wall": round(_clock.wall(), 6),
                "clock": clock_meta,
                "events": self.events(),
                "debug": _metrics.debug_snapshot(),
                "metrics": _metrics.snapshot(),
            }
            if fields:
                doc["detail"] = fields
            os.makedirs(self.out_dir, exist_ok=True)
            path = os.path.join(
                self.out_dir,
                f"postmortem-{self.rank}-{self.generation}.json")
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(doc, f, indent=1, sort_keys=True, default=str)
                f.write("\n")
            os.replace(tmp, path)
            _M_POSTMORTEMS.inc(reason=reason)
            return path
        except Exception:
            return None


# ---------------------------------------------------------------------------
# module plumbing (mirrors obs/tracing.py: ACTIVE flag + None-checked shims)
# ---------------------------------------------------------------------------

ACTIVE = False
_recorder: Optional[FlightRecorder] = None
_prev_sigusr2: Any = None
_install_lock = threading.Lock()


def install(*, rank: Any = 0, size: int = 1, generation: int = 0,
            out_dir: Optional[str] = None,
            window: Optional[int] = None,
            sigusr2: bool = True) -> Optional[FlightRecorder]:
    """Create the process recorder, flip :data:`ACTIVE`, register the
    ``flight`` /debug provider, and (main thread only) hook ``SIGUSR2``
    for on-demand postmortems.  No-op when ``HVTPU_FLIGHT=0`` or
    already installed."""
    global ACTIVE, _recorder, _prev_sigusr2
    if not env_enabled():
        return None
    with _install_lock:
        if _recorder is not None:
            return _recorder
        rec = FlightRecorder(rank=rank, size=size, generation=generation,
                             out_dir=out_dir, window=window)
        _recorder = rec
        ACTIVE = True
    _metrics.register_debug_provider("flight", rec.debug_state)
    if sigusr2:
        try:
            _prev_sigusr2 = signal.signal(signal.SIGUSR2, _on_sigusr2)
        except (ValueError, OSError, AttributeError):
            _prev_sigusr2 = None  # non-main thread or odd platform
    rec.note("flight_start",
             {"rank": rank, "size": size, "generation": generation})
    return rec


def uninstall() -> None:
    """Idempotent teardown: flips ACTIVE off first so racing hot-path
    callers see a plain ``False`` before the recorder goes away."""
    global ACTIVE, _recorder, _prev_sigusr2
    with _install_lock:
        ACTIVE = False
        rec, _recorder = _recorder, None
        prev, _prev_sigusr2 = _prev_sigusr2, None
    if rec is None:
        return
    try:
        _metrics.unregister_debug_provider("flight")
    except Exception:
        pass
    if prev is not None:
        try:
            signal.signal(signal.SIGUSR2, prev)
        except (ValueError, OSError):
            pass


def get_recorder() -> Optional[FlightRecorder]:
    return _recorder


def note(kind: str, **fields) -> None:
    """Append one event.  Callers guard with ``if flight.ACTIVE`` so
    the disabled path is a single attribute test."""
    r = _recorder
    if r is not None:
        r.note(kind, fields or None)


def dump_postmortem(reason: str, *, rank: Any = None,
                    **fields) -> Optional[str]:
    """Write a postmortem now.  Works with no recorder installed (e.g.
    the elastic *driver* on restart-budget exhaustion): a transient
    recorder captures the metrics/debug snapshots with an empty ring —
    but only when ``HVTPU_FLIGHT_DIR`` names a destination, so library
    code calling this on fatal paths never litters an unconfigured
    process's CWD.  Returns the file path, or None (disabled / no
    recorder and no dir / write failure)."""
    r = _recorder
    if r is None:
        if not env_enabled() or not os.environ.get("HVTPU_FLIGHT_DIR"):
            return None
        gen = int(os.environ.get("HVTPU_ELASTIC_GENERATION", "0") or 0)
        r = FlightRecorder(
            rank="driver" if rank is None else rank, generation=gen)
    return r.dump(reason, **fields)


def _on_sigusr2(signum, frame):  # pragma: no cover - signal path
    """On-demand black-box dump (documented beside SIGUSR1 in
    docs/robustness.md)."""
    try:
        if ACTIVE:
            note("sigusr2")
        dump_postmortem("sigusr2")
    except Exception:
        pass
    prev = _prev_sigusr2
    if callable(prev):
        try:
            prev(signum, frame)
        except Exception:
            pass
