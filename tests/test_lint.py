"""hvtpulint: fixture corpus + clean-tree gate.

Each pass gets at least one known-bad and one known-clean fixture tree
under tests/lint_fixtures/ (the trees replicate the repo-relative
layout the passes expect).  `test_repo_is_clean` is the tier-1 gate:
the shipped tree must lint clean, so ABI/knob/metric drift fails CI
before it fails a real job.
"""

import json
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

from tools.hvtpulint import (Project, load_suppressions, run_passes)
from tools.hvtpulint import (knob_registry, kv_discipline, metrics_catalog,
                             rank_divergence, sim_purity, thread_safety,
                             wire_twin)

REPO_ROOT = Path(__file__).resolve().parent.parent
FIXTURES = REPO_ROOT / "tests" / "lint_fixtures"


def run_pass(module, case: str):
    return module.run(Project(FIXTURES / case))


def keys(findings):
    return {f.key for f in findings}


# --------------------------------------------------------------------------
# wire-twin
# --------------------------------------------------------------------------

class TestWireTwin:
    def test_clean_twin_has_no_findings(self):
        assert run_pass(wire_twin, "wire_twin_clean") == []

    def test_bad_twin_flags_every_seeded_drift(self):
        findings = run_pass(wire_twin, "wire_twin_bad")
        assert keys(findings) == {
            "const:kWireVersion",
            "enum:OpType:Allreduce",
            "enum:OpType:Barrier",
            "order:SerializeResponseList",
            "table-key-separator",
            "burst-delimiter",
        }
        by_key = {f.key: f for f in findings}
        ver = by_key["const:kWireVersion"]
        assert ver.pass_name == "wire-twin"
        assert ver.path == "horovod_tpu/native/wire.py"
        assert ver.line == 5  # the WIRE_VERSION assignment
        assert "kWireVersion=0x4" in ver.message

    def test_bad_twin_burst_delimiter_fires_for_both_twins(self):
        # The bad fixture moves the burst_id/burst_len pair before the
        # flag bytes IDENTICALLY in both twins: the generic order
        # check is blind to it, so only the absolute-position check
        # stands between that edit and silent v5 framing drift.
        findings = run_pass(wire_twin, "wire_twin_bad")
        burst = [f for f in findings if f.key == "burst-delimiter"]
        assert {f.path for f in burst} == {
            wire_twin.MESSAGE_CC, wire_twin.WIRE_PY}
        assert all("burst-unit delimiter" in f.message for f in burst)

    def test_missing_surface_fails_closed(self, tmp_path):
        # An empty tree must produce missing-file findings, not a
        # silent pass.
        findings = wire_twin.run(Project(tmp_path))
        assert any(f.key.startswith("missing-file:") for f in findings)

    def test_real_tree_catches_bumped_wire_version(self, tmp_path):
        """Regression: copy the *real* native sources, bump
        kWireVersion, and the pass must name the drift."""
        for rel in (wire_twin.MESSAGE_H, wire_twin.COMMON_H,
                    wire_twin.MESSAGE_CC, wire_twin.CONTROLLER_CC,
                    wire_twin.WIRE_PY, wire_twin.FALLBACK_PY):
            src = REPO_ROOT / rel
            dst = tmp_path / rel
            dst.parent.mkdir(parents=True, exist_ok=True)
            shutil.copy(src, dst)

        clean = wire_twin.run(Project(tmp_path))
        assert clean == [], [f.format_text() for f in clean]

        hdr = tmp_path / wire_twin.MESSAGE_H
        text = hdr.read_text(encoding="utf-8")
        assert "kWireVersion = 5" in text
        hdr.write_text(text.replace("kWireVersion = 5", "kWireVersion = 6"),
                       encoding="utf-8")

        findings = wire_twin.run(Project(tmp_path))
        assert keys(findings) == {"const:kWireVersion"}
        f = findings[0]
        assert f.path == wire_twin.WIRE_PY and f.line > 0


# --------------------------------------------------------------------------
# rank-divergence
# --------------------------------------------------------------------------

class TestRankDivergence:
    def test_clean_patterns_are_silent(self):
        findings = run_pass(rank_divergence, "rank_div")
        assert not any("clean.py" in f.path for f in findings)

    def test_bad_patterns_all_flagged(self):
        findings = run_pass(rank_divergence, "rank_div")
        assert keys(findings) == {
            "examples/bad.py:direct_rank_test:broadcast",
            "examples/bad.py:tainted_local:allreduce",
            "examples/bad.py:else_arm:barrier",
            "examples/bad.py:ternary:allreduce",
        }
        for f in findings:
            assert f.pass_name == "rank-divergence"
            assert f.path == "examples/bad.py"
            assert f.line > 0


# --------------------------------------------------------------------------
# thread-safety
# --------------------------------------------------------------------------

class TestThreadSafety:
    def test_clean_discipline_is_silent(self):
        findings = run_pass(thread_safety, "thread_safety")
        assert not any("clean.py" in f.path for f in findings)

    def test_bad_discipline_flagged(self):
        findings = run_pass(thread_safety, "thread_safety")
        assert keys(findings) == {
            "BadWorker._loop:call:_drain",
            "BadWorker.submit:_queue",
            "BadWorker.submit:call:_drain",
            "BadWorker.bump:_depth",
        }
        by_key = {f.key: f for f in findings}
        # racy-read-ok permits the unlocked read in peek_depth but not
        # the write in bump.
        assert "write to self._depth" in by_key["BadWorker.bump:_depth"].message


# --------------------------------------------------------------------------
# knob-registry
# --------------------------------------------------------------------------

class TestKnobRegistry:
    def test_clean_docs_are_silent(self):
        assert run_pass(knob_registry, "knob_clean") == []

    def test_drift_in_every_direction(self):
        findings = run_pass(knob_registry, "knob_bad")
        assert keys(findings) == {
            "HVTPU_FIXTURE_UNDOC",       # read, undocumented
            "HVTPU_FIXTURE_DEAD",        # documented, never read
            "describe:HVTPU_FIXTURE_TODO",  # documented with TODO
        }


# --------------------------------------------------------------------------
# metrics-catalog
# --------------------------------------------------------------------------

class TestMetricsCatalog:
    def test_clean_catalog_is_silent(self):
        assert run_pass(metrics_catalog, "metrics_clean") == []

    def test_drift_in_every_direction(self):
        findings = run_pass(metrics_catalog, "metrics_bad")
        assert keys(findings) == {
            "hvtpu_fixture_undocumented_total",        # registered, uncataloged
            "hvtpu_fixture_stale",                     # cataloged, unregistered
            "required:hvtpu_fixture_missing_total",    # bench key unregistered
            "required-doc:hvtpu_fixture_missing_total",  # bench key uncataloged
        }


# --------------------------------------------------------------------------
# suppressions
# --------------------------------------------------------------------------

class TestSuppressions:
    def test_entry_without_justification_is_a_finding(self, tmp_path):
        sup = tmp_path / ".hvtpulint.suppress"
        sup.write_text("rank-divergence some:key\n", encoding="utf-8")
        entries, bad = load_suppressions(sup)
        assert entries == []
        assert len(bad) == 1 and bad[0].key == "malformed:1"

    def test_unused_entry_is_a_finding(self, tmp_path):
        (tmp_path / "horovod_tpu").mkdir()
        sup = tmp_path / ".hvtpulint.suppress"
        sup.write_text("rank-divergence no/such:key stale justification\n",
                       encoding="utf-8")
        findings = run_passes(tmp_path, only=["rank-divergence"],
                              suppress_path=sup)
        assert [f.key for f in findings] == \
            ["unused:rank-divergence:no/such:key"]

    def test_suppression_silences_matching_finding(self, tmp_path):
        case = FIXTURES / "rank_div"
        shutil.copytree(case / "examples", tmp_path / "examples")
        sup = tmp_path / ".hvtpulint.suppress"
        sup.write_text(
            "rank-divergence examples/bad.py:direct_rank_test:broadcast "
            "fixture: intentional root-rank broadcast\n", encoding="utf-8")
        findings = run_passes(tmp_path, only=["rank-divergence"],
                              suppress_path=sup)
        got = keys(findings)
        assert "examples/bad.py:direct_rank_test:broadcast" not in got
        assert "examples/bad.py:tainted_local:allreduce" in got

    def test_repo_suppression_file_is_well_formed(self):
        entries, bad = load_suppressions(REPO_ROOT / ".hvtpulint.suppress")
        assert bad == []
        for e in entries:
            assert e.justification  # every entry carries a written reason


# --------------------------------------------------------------------------
# CLI + tier-1 clean-tree gate
# --------------------------------------------------------------------------

class TestCli:
    def test_json_output_and_exit_code_on_fixture(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.hvtpulint",
             "--root", str(FIXTURES / "wire_twin_bad"),
             "--passes", "wire-twin", "--format", "json"],
            cwd=REPO_ROOT, capture_output=True, text=True)
        assert proc.returncode == 1
        payload = json.loads(proc.stdout)
        findings = payload["findings"]
        assert {f["pass_name"] for f in findings} == {"wire-twin"}
        assert any(f["key"] == "const:kWireVersion" for f in findings)

    def test_unknown_pass_is_a_usage_error(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.hvtpulint",
             "--passes", "no-such-pass"],
            cwd=REPO_ROOT, capture_output=True, text=True)
        assert proc.returncode == 2

    def test_list_passes(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.hvtpulint", "--list-passes"],
            cwd=REPO_ROOT, capture_output=True, text=True)
        assert proc.returncode == 0
        listed = set(proc.stdout.split())
        assert {"wire-twin", "rank-divergence", "thread-safety",
                "knob-registry", "metrics-catalog", "sim-purity",
                "kv-discipline"} <= listed


# --------------------------------------------------------------------------
# sim-purity
# --------------------------------------------------------------------------

class TestSimPurity:
    def test_clean_sim_tree_has_no_findings(self):
        assert run_pass(sim_purity, "sim_purity_clean") == []

    def test_bad_tree_flags_every_leak(self):
        findings = run_pass(sim_purity, "sim_purity_bad")
        assert keys(findings) == {
            "time.time:bad.py:1",
            "time.monotonic:bad.py:1",
            "time.sleep:bad.py:1",
            "time.sleep:bad.py:2",      # occurrence-indexed keys
            "time.sleep:bad.py:3",      # from-import alias
            "random.random:bad.py:1",
            "random.seed:bad.py:1",
            "random.randint:bad.py:1",  # from-import of a module fn
        }
        # random.Random(7) instantiation in the same fixture is allowed
        assert not any("random.Random" in k for k in keys(findings))

    def test_real_sim_package_is_pure(self):
        # the shipped simulator itself honours its own contract
        findings = sim_purity.run(Project(REPO_ROOT))
        assert findings == [], "\n".join(
            f.format_text() for f in findings)


# --------------------------------------------------------------------------
# kv-discipline
# --------------------------------------------------------------------------

class TestKvDiscipline:
    def test_clean_wrapper_patterns_are_silent(self):
        assert run_pass(kv_discipline, "kv_disc_clean") == []

    def test_bad_tree_flags_every_leak(self):
        findings = run_pass(kv_discipline, "kv_disc_bad")
        assert keys(findings) == {
            "call:key_value_set:bad.py:1",
            "call:key_value_set:bad.py:2",        # occurrence-indexed
            "call:blocking_key_value_get:bad.py:1",
            "call:key_value_dir_get:bad.py:1",    # chained, no binding
            "call:key_value_delete:bad.py:1",     # taint through alias
            "escape:_kv:bad.py:1",                # raw client on self
        }
        by_key = {f.key: f for f in findings}
        esc = by_key["escape:_kv:bad.py:1"]
        assert esc.pass_name == "kv-discipline"
        assert esc.path == "horovod_tpu/bad.py"
        assert "self._kv" in esc.message
        assert "FencedKV/ResilientKV" in esc.message

    def test_real_tree_has_only_the_transport_escape(self):
        # The eager KVTransport deliberately holds the raw client (see
        # the justified entry in .hvtpulint.suppress); everything else
        # in the shipped tree must go through core/retry.py wrappers.
        findings = kv_discipline.run(Project(REPO_ROOT))
        assert keys(findings) == {"escape:_kv:controller.py:1"}, \
            "\n".join(f.format_text() for f in findings)


def test_repo_is_clean():
    """Tier-1 gate: the shipped tree lints clean (with the checked-in
    suppression file).  A failure here IS the lint finding — run
    `python -m tools.hvtpulint` for the full text."""
    findings = run_passes(REPO_ROOT)
    assert findings == [], "\n" + "\n".join(f.format_text() for f in findings)


def test_knobs_md_regeneration_is_stable():
    """--write-knobs over the current tree must be a no-op: the checked
    in docs/knobs.md matches what the extractor produces."""
    project = Project(REPO_ROOT)
    regenerated = knob_registry.generate_knobs_md(project)
    on_disk = (REPO_ROOT / "docs" / "knobs.md").read_text(encoding="utf-8")
    assert regenerated == on_disk
