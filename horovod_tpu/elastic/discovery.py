"""Host discovery for elastic training.

Parity surface: ``horovod/runner/elastic/discovery.py``
(``HostDiscoveryScript``, ``HostManager``) — a user-provided executable
prints the currently-available ``host:slots`` lines; the driver polls it
on an interval and reacts to diffs, maintaining a blacklist of hosts
that failed.
"""

from __future__ import annotations

import subprocess
from typing import Dict, List, Optional, Set

from ..runner import hosts as hosts_mod


class HostDiscoveryScript:
    """Runs the user's discovery script and parses its output (parity:
    HostDiscoveryScript.find_available_hosts_and_slots)."""

    def __init__(self, script: str, timeout: float = 30.0):
        self.script = script
        self.timeout = timeout

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        out = subprocess.run(
            self.script, shell=True, capture_output=True, text=True,
            timeout=self.timeout,
        )
        if out.returncode != 0:
            raise RuntimeError(
                f"host discovery script failed ({out.returncode}): "
                f"{out.stderr.strip()[:500]}"
            )
        slots: Dict[str, int] = {}
        for line in out.stdout.splitlines():
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            hs = hosts_mod.parse_host_spec(line)
            for h in hs:
                slots[h.hostname] = slots.get(h.hostname, 0) + h.slots
        return slots


class HostManager:
    """Tracks current hosts, computes diffs, maintains the blacklist
    (parity: HostManager + the blacklist in
    horovod/runner/elastic/registration.py)."""

    def __init__(self, discovery: HostDiscoveryScript):
        self._discovery = discovery
        self.current: Dict[str, int] = {}
        self.last_found: Dict[str, int] = {}
        self.blacklist: Set[str] = set()

    def blacklist_host(self, hostname: str):
        self.blacklist.add(hostname)

    def refresh(self) -> bool:
        """Poll discovery; returns True if the effective host set
        changed (additions or removals, after blacklist filtering)."""
        found = self._discovery.find_available_hosts_and_slots()
        self.last_found = dict(found)
        effective = {
            h: s for h, s in found.items() if h not in self.blacklist
        }
        changed = effective != self.current
        self.current = effective
        return changed

    def exhausted(self, min_np: int) -> bool:
        """True when the last discovery succeeded yet EVERY discovered
        host is blacklisted — hosts never leave the blacklist, so
        unless discovery produces brand-new hosts the wait is hopeless
        and the driver should fail fast instead of burning the full
        elastic timeout."""
        del min_np  # reserved for smarter policies
        return (bool(self.last_found)
                and all(h in self.blacklist for h in self.last_found))

    def available_slots(self) -> int:
        return sum(self.current.values())

    def host_spec(self) -> str:
        return ",".join(
            f"{h}:{s}" for h, s in sorted(self.current.items())
        )
