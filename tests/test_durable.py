"""Unit tests for the durable state plane (core/durable.py).

Covers the PR 15 commit protocol in isolation: manifest-last
ordering, torn/bitflip detection, retention GC, the restore quorum
against a fake KV, and the background writer's error-surfacing
contract.  The end-to-end chaos runs (kill mid-commit under a real
2-proc elastic job) live in test_faults.py; the 256-1024 virtual-rank
storm lives in test_sim.py.
"""

import os
import pickle
import threading
import time

import pytest

from horovod_tpu.core import durable
from horovod_tpu.core import faults


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.uninstall()
    yield
    faults.uninstall()


def _files(n=1, size=256):
    return {f"f{i}.pkl": bytes([i]) * size for i in range(n)}


# ---------------------------------------------------------------------------
# atomic_write + commit protocol
# ---------------------------------------------------------------------------


class TestAtomicWrite:
    def test_roundtrip_leaves_no_tmp(self, tmp_path):
        p = str(tmp_path / "blob")
        n = durable.atomic_write(p, b"hello", fsync=False)
        assert n == 5
        assert open(p, "rb").read() == b"hello"
        assert sorted(os.listdir(tmp_path)) == ["blob"]

    def test_overwrite_is_atomic_replace(self, tmp_path):
        p = str(tmp_path / "blob")
        durable.atomic_write(p, b"one", fsync=False)
        durable.atomic_write(p, b"two", fsync=False)
        assert open(p, "rb").read() == b"two"


class TestCommitProtocol:
    def test_write_read_roundtrip(self, tmp_path):
        root = str(tmp_path)
        files = _files(3)
        d = durable.write_snapshot(root, 7, files, fsync=False)
        assert os.path.isdir(d)
        assert durable.latest_verified(root) == 7
        assert durable.read_snapshot(root, 7) == files

    def test_manifest_written_last_is_the_commit_point(self, tmp_path):
        # one payload file = ckpt.write invocation 1 is the payload,
        # invocation 2 the manifest.  Tear the manifest: the payload
        # is intact on disk yet the snapshot is NOT committed —
        # proving the manifest is the commit point.
        root = str(tmp_path)
        faults.install("ckpt.write:torn@count=2", rank=0)
        durable.write_snapshot(root, 1, _files(1), fsync=False)
        faults.uninstall()
        d = durable.snapshot_path(root, 1)
        assert os.path.exists(os.path.join(d, "f0.pkl"))
        assert durable._committed(d) is None
        assert durable.latest_verified(root) is None
        with pytest.raises(FileNotFoundError):
            durable.read_snapshot(root, 1)

    def test_torn_payload_rejected_by_verification(self, tmp_path):
        root = str(tmp_path)
        durable.write_snapshot(root, 1, _files(1), fsync=False)
        # invocation 1 = the payload of seq 2; its manifest (written
        # after the tear) records the INTENDED hash, so verification
        # catches the damage even though the commit "landed"
        faults.install("ckpt.write:torn@count=1,times=1", rank=0)
        durable.write_snapshot(root, 2, _files(1), fsync=False)
        faults.uninstall()
        d2 = durable.snapshot_path(root, 2)
        assert durable._committed(d2) is not None
        assert not durable.verify_snapshot(d2)
        # ...and restore walks down to the last good commit
        assert durable.latest_verified(root) == 1

    def test_bitflip_rejected_by_verification(self, tmp_path):
        root = str(tmp_path)
        durable.write_snapshot(root, 1, _files(1), fsync=False)
        faults.install("ckpt.write:bitflip@count=1,times=1", rank=0)
        durable.write_snapshot(root, 2, _files(1), fsync=False)
        faults.uninstall()
        d2 = durable.snapshot_path(root, 2)
        # a single flipped bit: sizes match, only the hash catches it
        assert durable._committed(d2) is not None
        assert not durable.verify_snapshot(d2)
        assert durable.latest_verified(root) == 1

    def test_elided_rename_leaves_uncommitted_tmp(self, tmp_path):
        root = str(tmp_path)
        faults.install("ckpt.rename:drop@count=2", rank=0)
        durable.write_snapshot(root, 3, _files(1), fsync=False)
        faults.uninstall()
        d = durable.snapshot_path(root, 3)
        assert os.path.exists(
            os.path.join(d, durable.MANIFEST + ".tmp"))
        assert durable._committed(d) is None

    def test_verify_failure_counts_metric(self, tmp_path):
        from horovod_tpu.obs import metrics as obs_metrics

        root = str(tmp_path)
        d = durable.write_snapshot(root, 1, _files(1), fsync=False)
        with open(os.path.join(d, "f0.pkl"), "ab") as f:
            f.write(b"x")
        def count():
            fam = obs_metrics.snapshot().get(
                "hvtpu_ckpt_verify_failures_total", {})
            return fam.get("values", {}).get("", 0.0)

        before = count()
        assert not durable.verify_snapshot(d)
        assert count() == before + 1

    def test_rewrite_of_same_seq_starts_clean(self, tmp_path):
        root = str(tmp_path)
        durable.write_snapshot(root, 1, _files(2), fsync=False)
        durable.write_snapshot(root, 1, {"only.pkl": b"z"}, fsync=False)
        assert durable.read_snapshot(root, 1) == {"only.pkl": b"z"}


class TestRetention:
    def test_gc_keeps_newest_k_commits(self, tmp_path):
        root = str(tmp_path)
        for seq in range(5):
            durable.write_snapshot(root, seq, _files(1), fsync=False,
                                   keep=2)
        assert durable.list_snapshots(root) == [3, 4]

    def test_gc_spares_inflight_newer_than_newest_commit(self, tmp_path):
        root = str(tmp_path)
        durable.write_snapshot(root, 1, _files(1), fsync=False, keep=1)
        # an in-flight (uncommitted) attempt newer than every commit
        os.makedirs(durable.snapshot_path(root, 9))
        durable.gc_snapshots(root, keep=1)
        assert durable.list_snapshots(root) == [1, 9]
        # once seq 10 commits, the dead seq-9 leftover is collected
        durable.write_snapshot(root, 10, _files(1), fsync=False, keep=1)
        assert durable.list_snapshots(root) == [10]

    def test_keep_knob_default(self, tmp_path, monkeypatch):
        monkeypatch.setenv("HVTPU_CKPT_KEEP", "3")
        root = str(tmp_path)
        for seq in range(6):
            durable.write_snapshot(root, seq, _files(1), fsync=False)
        assert durable.list_snapshots(root) == [3, 4, 5]


# ---------------------------------------------------------------------------
# restore quorum
# ---------------------------------------------------------------------------


class _FakeKV:
    """Pre-seeded coordination KV: peers' votes are already published."""

    def __init__(self, votes=None):
        self.store = dict(votes or {})
        self.sets = []

    def key_value_set(self, key, value):
        self.sets.append((key, value))
        self.store[key] = value

    def blocking_key_value_get(self, key, timeout_ms):
        if key not in self.store:
            raise TimeoutError(f"timed out waiting for {key}")
        return self.store[key]


class TestRestoreQuorum:
    NS = "hvtpu/ckpt/quorum/0/0"

    def _votes(self, *bests):
        return {f"{self.NS}/vote/{r}": str(v)
                for r, v in enumerate(bests)}

    def test_unanimous(self):
        kv = _FakeKV(self._votes(5, 5, 5))
        assert durable.restore_quorum(
            kv, rank=0, size=3, local_best=5, namespace=self.NS) == 5

    def test_straggler_lowers_the_pick_never_diverges_it(self):
        votes = self._votes(5, 3, 5)
        picks = {
            r: durable.restore_quorum(
                _FakeKV(votes), rank=r, size=3,
                local_best=[5, 3, 5][r], namespace=self.NS)
            for r in range(3)
        }
        assert set(picks.values()) == {3}

    def test_any_empty_rank_yields_none(self):
        kv = _FakeKV(self._votes(5, -1, 5))
        assert durable.restore_quorum(
            kv, rank=0, size=3, local_best=5, namespace=self.NS) is None

    def test_local_none_votes_minus_one(self):
        kv = _FakeKV()
        assert durable.restore_quorum(
            kv, rank=0, size=1, local_best=None,
            namespace=self.NS) is None
        assert kv.sets == [(f"{self.NS}/vote/0", "-1")]

    def test_timeout_propagates_to_caller(self):
        kv = _FakeKV(self._votes(5))  # peer 1 never votes
        with pytest.raises(TimeoutError):
            durable.restore_quorum(
                kv, rank=0, size=2, local_best=5, namespace=self.NS,
                timeout_s=0.01)


# ---------------------------------------------------------------------------
# background writer
# ---------------------------------------------------------------------------


class TestDurableWriter:
    def test_flush_waits_for_queued_writes(self, tmp_path):
        w = durable.DurableWriter(maxsize=4)
        done = []
        gate = threading.Event()

        def work():
            gate.wait(5)
            done.append(1)

        w.submit(work)
        gate.set()
        w.flush()
        assert done == [1]
        w.close()

    def test_error_surfaces_on_next_flush(self):
        w = durable.DurableWriter(maxsize=4)

        def boom():
            raise OSError("disk on fire")

        w.submit(boom)
        with pytest.raises(RuntimeError, match="background write"):
            w.flush()
        # the error is consumed: the writer is usable again
        w.flush()
        w.close()

    def test_error_surfaces_on_next_submit(self):
        w = durable.DurableWriter(maxsize=4)

        def boom():
            raise OSError("disk on fire")

        w.submit(boom)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            try:
                w.submit(lambda: None)
            except RuntimeError:
                break
            time.sleep(0.01)
        else:
            pytest.fail("queued error never surfaced on submit")
        w.close()

    def test_close_is_idempotent_and_rejects_submits(self):
        w = durable.DurableWriter(maxsize=4)
        w.submit(lambda: None)
        w.close()
        w.close()
        with pytest.raises(RuntimeError, match="closed"):
            w.submit(lambda: None)

    def test_shared_writer_recreated_after_quiesce(self):
        a = durable.shared_writer()
        assert durable.shared_writer() is a
        durable.quiesce_writers()
        b = durable.shared_writer()
        assert b is not a
        durable.quiesce_writers()

    def test_quiesce_never_raises(self):
        w = durable.shared_writer()

        def boom():
            raise OSError("late failure")

        w.submit(boom)
        durable.quiesce_writers()  # must swallow, not raise
