"""TF2-frontend synthetic benchmark — the horovod_tpu surface of the
reference's measurement tool (examples/tensorflow2/
tensorflow2_synthetic_benchmark.py): random data, timed training
iterations via ``DistributedGradientTape``, per-rank and aggregate
images/sec with the same log format.

Only the import line changes from the reference idiom
(``import horovod.tensorflow as hvd`` -> ``import
horovod_tpu.tensorflow as hvd``).  A small dense model keeps the
TF-eager data path (the system under test) tractable offline; peak TPU
numbers come from the jit-path benchmark at the repo root (bench.py).

Run:  hvtpurun -np 2 --cpu-devices 1 python \
          examples/tensorflow2_synthetic_benchmark.py --num-iters 3
"""

import argparse
import time

import numpy as np
import tensorflow as tf

import horovod_tpu.tensorflow as hvd


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--num-warmup-batches", type=int, default=2)
    p.add_argument("--num-batches-per-iter", type=int, default=5)
    p.add_argument("--num-iters", type=int, default=5)
    p.add_argument("--fp16-allreduce", action="store_true")
    args = p.parse_args()

    hvd.init()
    tf.random.set_seed(2 + hvd.rank())

    model = tf.keras.Sequential([
        tf.keras.layers.Dense(256, activation="relu"),
        tf.keras.layers.Dense(256, activation="relu"),
        tf.keras.layers.Dense(10),
    ])
    opt = tf.keras.optimizers.SGD(0.01)
    loss_fn = tf.keras.losses.SparseCategoricalCrossentropy(
        from_logits=True
    )
    compression = (hvd.Compression.fp16 if args.fp16_allreduce
                   else hvd.Compression.none)

    data = tf.random.normal((args.batch_size, 784))
    target = tf.random.uniform(
        (args.batch_size,), 0, 10, dtype=tf.int64
    )

    def benchmark_step(first_batch):
        with tf.GradientTape() as tape:
            loss = loss_fn(target, model(data, training=True))
        # Horovod idiom: wrap the tape; grads come back allreduced.
        tape = hvd.DistributedGradientTape(tape, compression=compression)
        grads = tape.gradient(loss, model.trainable_variables)
        opt.apply_gradients(zip(grads, model.trainable_variables))
        if first_batch:
            hvd.broadcast_variables(model.variables, root_rank=0)
            hvd.broadcast_variables(opt.variables, root_rank=0)

    def log(s):
        if hvd.rank() == 0:
            print(s, flush=True)

    log(f"Model: 3-layer MLP, Batch size: {args.batch_size}, "
        f"number of ranks: {hvd.size()}")

    benchmark_step(first_batch=True)
    for _ in range(args.num_warmup_batches - 1):
        benchmark_step(first_batch=False)

    img_secs = []
    for x in range(args.num_iters):
        t = time.perf_counter()
        for _ in range(args.num_batches_per_iter):
            benchmark_step(first_batch=False)
        dt = time.perf_counter() - t
        img_sec = args.batch_size * args.num_batches_per_iter / dt
        log(f"Iter #{x}: {img_sec:.1f} img/sec per rank")
        img_secs.append(img_sec)

    img_sec_mean = np.mean(img_secs)
    img_sec_conf = 1.96 * np.std(img_secs)
    log(f"Img/sec per rank: {img_sec_mean:.1f} +-{img_sec_conf:.1f}")
    log(f"Total img/sec on {hvd.size()} rank(s): "
        f"{hvd.size() * img_sec_mean:.1f} "
        f"+-{hvd.size() * img_sec_conf:.1f}")


if __name__ == "__main__":
    main()
