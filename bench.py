#!/usr/bin/env python
"""Headline benchmark: ResNet-50 synthetic-ImageNet throughput, the same
measurement the reference ships (examples/pytorch/pytorch_synthetic_benchmark.py
/ examples/tensorflow2/tensorflow2_synthetic_benchmark.py — random data,
timed training steps, images/sec).

Runs data-parallel over every available device through the framework's
own DistributedOptimizer path (bucketed fused allreduce inside the
jitted step).  Prints exactly ONE JSON line:

    {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": R}

vs_baseline compares against NCCL-on-A100 images/sec/chip for the same
model/precision (~2500 img/s at bf16/AMP per BASELINE.json's north-star
"images/sec/chip parity with NCCL-on-A100").
"""

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import horovod_tpu as hvt
from horovod_tpu.models import InceptionV3, ResNet50, ResNet101, VGG16
from horovod_tpu.obs import metrics as obs_metrics
from horovod_tpu.obs import stepprof as obs_stepprof

A100_BASELINE_IMG_PER_SEC_PER_CHIP = 2500.0

# The reference's README benchmark trio + the north-star model
# (docs/benchmarks.rst: Inception V3 / ResNet-101 / VGG-16; BASELINE
# north star: ResNet-50).
# (ctor, input_px, default_batch, takes_bn_axis, default_steps_per_call)
# vgg16's smaller defaults are the RECORDED config: the 32-step scan of
# the 138M-param model exceeds the tunneled chip's compile budget.
MODELS = {
    "resnet50": (ResNet50, 224, 256, True, 32),
    "resnet101": (ResNet101, 224, 128, True, 32),
    "inception3": (InceptionV3, 299, 128, True, 32),
    "vgg16": (VGG16, 224, 64, False, 8),
}
MODEL = os.environ.get("HVTPU_BENCH_MODEL", "resnet50")
if MODEL not in MODELS:
    raise SystemExit(
        f"HVTPU_BENCH_MODEL={MODEL!r} unknown; choose from "
        f"{sorted(MODELS)}"
    )

BATCH_PER_CHIP = int(os.environ.get("HVTPU_BENCH_BATCH", "0")) \
    or MODELS[MODEL][2]
WARMUP = int(os.environ.get("HVTPU_BENCH_WARMUP", "2"))
ITERS = int(os.environ.get("HVTPU_BENCH_ITERS", "6"))
# Training steps fused into one device dispatch via lax.scan — the
# standard TPU train-loop shape (amortizes host->device dispatch, which
# on a tunneled/remote chip costs tens of ms per call; real training
# loops batch steps exactly like this).
STEPS_PER_CALL = int(os.environ.get("HVTPU_BENCH_STEPS_PER_CALL", "0")) \
    or MODELS[MODEL][4]


def check_regression_floor(model: str, value: float,
                           repo_root: str) -> "str | None":
    """Round-over-round floor guard (VERDICT r4 #4): every benchmarked
    model's recorded img/s is a floor with a small tolerance — a
    silent regression in any model's path fails the bench run instead
    of drifting in the recorded tables.  Floors live in
    BENCH_MODELS.json's ``bar.floors`` (the ResNet-50 north star is
    additionally enforced against the A100 parity bar by the driver).
    Returns an error string on regression, else None."""
    path = os.path.join(repo_root, "BENCH_MODELS.json")
    try:
        with open(path) as f:
            bar = json.load(f).get("bar", {})
    except Exception:
        return None
    if not isinstance(bar, dict):
        return None
    floor = bar.get("floors", {}).get(model)
    if floor is None:
        return None
    tol = float(bar.get("tolerance", 0.02))
    if value < floor * (1.0 - tol):
        return (
            f"REGRESSION: {model} measured {value:.1f} img/s/chip, "
            f"below the recorded floor {floor:.1f} - {tol:.0%} "
            f"tolerance ({floor * (1 - tol):.1f}). A deliberate perf "
            "change must update BENCH_MODELS.json bar.floors in the "
            "same commit."
        )
    return None


# Families the embedded snapshot must always carry so BENCH_* rounds
# stay comparable (tests/test_bench_guard.py enforces the schema):
# step accounting from this host loop, the eager data plane's byte and
# op counters, and the controller cycle histogram.
REQUIRED_METRIC_KEYS = (
    "hvtpu_optimizer_steps_total",
    "hvtpu_examples_total",
    "hvtpu_allreduce_total",
    "hvtpu_tensor_bytes_total",
    "hvtpu_wire_bytes_total",
    "hvtpu_controller_cycles_total",
    "hvtpu_controller_cycle_seconds",
    # integrity layer (PR 4): cross-rank mismatch diagnostics, the
    # coordinated non-finite guard, and the divergence audit — all 0
    # on a healthy run, which is exactly what the trajectory proves.
    "hvtpu_controller_mismatch_errors_total",
    "hvtpu_optimizer_nonfinite_skips_total",
    "hvtpu_audit_runs_total",
    "hvtpu_audit_divergences_total",
    # observability layer (PR 7): arrival-skew histogram — the report's
    # straggler signal; {count, sum} gives mean skew per collective.
    "hvtpu_collective_arrival_skew_seconds",
    # graceful preemption (PR 8): notice/drain counters and the
    # drain-commit latency histogram — 0 on a healthy bench run, and a
    # nonzero count here flags that the round absorbed a preemption.
    "hvtpu_preempt_notices_total",
    "hvtpu_elastic_drains_total",
    "hvtpu_drain_commit_seconds",
    # input pipeline (PR 9): per-batch input wait and delivery counters
    # from data/loader.py — the data-stall half of the straggler
    # decomposition; the report derives data_stall.stall_fraction from
    # the wait histogram against wall time.
    "hvtpu_data_wait_seconds",
    "hvtpu_data_batches_delivered_total",
    "hvtpu_data_samples_delivered_total",
    # overlap profiler (PR 12, obs/stepprof.py): measured per-step
    # exposed-communication time, the device-joined overlap fraction
    # (0 until a profile join runs), and measured MFU (0 until the
    # host loop provides cost_analysis FLOPs).
    "hvtpu_step_exposed_comm_seconds",
    "hvtpu_step_overlap_fraction",
    "hvtpu_mfu",
    # durable state plane (PR 15, core/durable.py): commit latency and
    # bytes written by the crash-consistent checkpoint protocol,
    # manifest-verification rejections (0 on a healthy run — nonzero
    # means a torn/corrupt snapshot was caught and skipped), and
    # restore-quorum rounds (one per elastic sync that consulted
    # peers before picking a restore point).
    "hvtpu_ckpt_commit_seconds",
    "hvtpu_ckpt_bytes_written_total",
    "hvtpu_ckpt_verify_failures_total",
    "hvtpu_ckpt_restore_quorum_rounds_total",
    # flight recorder + anomaly detection (PR 16, obs/flight.py,
    # obs/anomaly.py, fleet/health.py): ring appends prove the black
    # box was recording; incident count is 0 on a healthy run — a
    # nonzero value names a round that tripped a detector.  The fleet
    # gauges stay 0 outside an arbiter-run fleet (no _seconds suffix:
    # condense_metrics zero-fills gauges as scalars).
    "hvtpu_flight_events_total",
    "hvtpu_incidents_total",
    "hvtpu_fleet_job_step_rate",
    "hvtpu_fleet_job_incidents",
    # coordination-plane fault tolerance (PR 17, core/retry.py,
    # comm/stall.py): fencing-token rejections and fence exits are 0
    # on a healthy run — nonzero names a round where a superseded or
    # lease-expired writer was stopped; the suspect histogram counts
    # seconds peers held stall blame for a silent-but-leased rank
    # instead of declaring it dead.
    "hvtpu_kv_fenced_writes_total",
    "hvtpu_fence_exits_total",
    "hvtpu_partition_suspect_seconds",
    # zero-copy fusion buffers (PR 18, comm/packing.py,
    # eager/controller.py): which fused-allreduce path ran.  A steady
    # run shows zero_copy climbing and staged flat after warmup;
    # staged rising mid-run means the pack plan kept falling back
    # (mispredicts, shape churn, compression).
    "hvtpu_fusion_zero_copy_ops_total",
    "hvtpu_fusion_staged_copies_total",
    # fleet front door (PR 19, fleet/{intake,admission,placement}.py):
    # queue depth by tier and journal intake lag show the backlog a
    # submission storm builds and how fast the bounded-budget intake
    # drains it; admission rejections are 0 unless a tenant blew a
    # quota (or a spec was malformed); fragmentation is the measured
    # contiguity of the pool's free capacity on the host torus.
    "hvtpu_fleet_queue_depth",
    "hvtpu_fleet_intake_lag",
    "hvtpu_fleet_admission_rejections_total",
    "hvtpu_fleet_fragmentation",
    # wire-plane fault tolerance (PR 20, comm/wirefault.py): retries
    # and the consensus histogram are 0 on a healthy run — a nonzero
    # count names a round where a collective attempt was agreed dead
    # and reissued instead of restarting the job; link_health is the
    # worst per-peer degradation score (0 = every link clean) and
    # reroutes counts ring permutations taken around a sick link.
    "hvtpu_collective_retries_total",
    "hvtpu_collective_abort_consensus_seconds",
    "hvtpu_link_health",
    "hvtpu_ring_reroutes_total",
)


def condense_metrics(snap=None) -> dict:
    """Registry snapshot -> the compact form embedded in the bench JSON
    line: counters/gauges collapse to a scalar total across label sets,
    histograms to {count, sum}.  Families in REQUIRED_METRIC_KEYS are
    always present (0 when never touched) so BENCH_* trajectories keep
    a stable schema across rounds."""
    if snap is None:
        snap = obs_metrics.snapshot()
    out = {}
    for name, fam in snap.items():
        if fam["type"] == "histogram":
            cells = fam["values"].values()
            out[name] = {
                "count": sum(c["count"] for c in cells),
                "sum": round(sum(c["sum"] for c in cells), 6),
            }
        else:
            out[name] = sum(fam["values"].values())
    for name in REQUIRED_METRIC_KEYS:
        if name not in out:
            out[name] = (
                {"count": 0, "sum": 0.0} if name.endswith("_seconds")
                else 0)
    return out


def build_report(**fields) -> dict:
    """Assemble the ONE-JSON-line bench report.  Every report embeds
    the condensed registry snapshot under ``metrics`` so BENCH_*
    trajectories capture wire-bytes and cycle stats alongside img/s
    (schema enforced by tests/test_bench_guard.py)."""
    report = dict(fields)
    report["metrics"] = condense_metrics()
    # Straggler headline: mean cross-rank arrival skew per collective
    # (rank 0 observes the skew histogram; 0 collectives -> 0.0 mean so
    # the row is schema-stable even on 1-proc runs).
    skew = report["metrics"]["hvtpu_collective_arrival_skew_seconds"]
    report["arrival_skew"] = {
        "collectives": skew["count"],
        "mean_seconds": round(skew["sum"] / skew["count"], 6)
        if skew["count"] else 0.0,
    }
    # Input-stall headline: time the host loop blocked on the data
    # pipeline vs wall time.  Near-0 stall_fraction with nonzero
    # batches is the prefetch-overlap proof; null when the caller
    # passed no elapsed_seconds (schema-stable either way).
    wait = report["metrics"]["hvtpu_data_wait_seconds"]
    elapsed = fields.get("elapsed_seconds")
    report["data_stall"] = {
        "batches": wait["count"],
        "wait_seconds": round(wait["sum"], 6),
        "stall_fraction": round(wait["sum"] / elapsed, 6)
        if elapsed else None,
    }
    # Overlap headline (PR 12): per-step exposed-comm time from the
    # stepprof collector plus the measured overlap/MFU gauges.  The
    # gauges default to 0 (never joined / no FLOPs provided) and are
    # reported as null then, so a recorded 0.31 means "measured 0.31",
    # never "not measured".
    exposed = report["metrics"]["hvtpu_step_exposed_comm_seconds"]
    report["overlap"] = {
        "steps": exposed["count"],
        "exposed_comm_seconds": round(exposed["sum"], 6),
        "overlap_fraction":
            report["metrics"]["hvtpu_step_overlap_fraction"] or None,
        "mfu": report["metrics"]["hvtpu_mfu"] or None,
    }
    return report


def main():
    hvt.init()
    mesh = hvt.world_mesh()
    n_dev = hvt.num_devices()
    global_batch = BATCH_PER_CHIP * n_dev

    # bn_axis_name keeps the replicated batch_stats actually consistent
    # across devices (sync BatchNorm over the dp axis).
    ctor, px, _, takes_bn, _steps = MODELS[MODEL]
    kwargs = dict(num_classes=1000, dtype=jnp.bfloat16)
    if takes_bn:
        kwargs["bn_axis_name"] = "world" if n_dev > 1 else None
    model = ctor(**kwargs)
    rng = jax.random.PRNGKey(0)
    images = jax.random.normal(
        rng, (global_batch, px, px, 3), jnp.bfloat16
    )
    labels = jax.random.randint(rng, (global_batch,), 0, 1000)

    variables = model.init(rng, images[:2], train=True)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})

    # VGG (no BatchNorm) diverges at the 0.1 default; the reference's
    # synthetic benchmark uses SGD lr=0.01 — LR does not affect img/s.
    lr = 0.01 if MODEL == "vgg16" else 0.1
    tx = hvt.DistributedOptimizer(
        optax.sgd(lr, momentum=0.9), axis_name="world"
    )
    opt_state = tx.init(params)

    def loss_fn(params, batch_stats, x, y):
        logits, mutated = model.apply(
            {"params": params, "batch_stats": batch_stats},
            x, train=True, mutable=["batch_stats"],
        )
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, y
        ).mean()
        return loss, mutated.get("batch_stats", {})

    def one_step(params, batch_stats, opt_state, x, y):
        (loss, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(params, batch_stats, x, y)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, new_stats, opt_state, jax.lax.pmean(loss, "world")

    def body(params, batch_stats, opt_state, x, y):
        # STEPS_PER_CALL optimizer steps in one dispatch (lax.scan keeps
        # it one compiled program; XLA reuses buffers across steps).
        def scan_step(carry, _):
            params, batch_stats, opt_state = carry
            params, batch_stats, opt_state, loss = one_step(
                params, batch_stats, opt_state, x, y
            )
            return (params, batch_stats, opt_state), loss

        (params, batch_stats, opt_state), losses = jax.lax.scan(
            scan_step, (params, batch_stats, opt_state), None,
            length=STEPS_PER_CALL,
        )
        return params, batch_stats, opt_state, losses[-1]

    step = jax.jit(
        jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(P(), P(), P(), P("world"), P("world")),
            out_specs=(P(), P(), P(), P()),
            check_vma=False,
        ),
        donate_argnums=(0, 1, 2),
    )

    def fence(loss):
        # Force a device->host readback as the timing fence.  On remote
        # TPU transports block_until_ready can report completion early;
        # a dependent scalar read cannot.
        return float(loss)

    # Feed dispatches through the elastic input pipeline so the bench
    # measures (and reports, via data_stall) the prefetch overlap: the
    # loader's thread places batch k+1 on the mesh while dispatch k
    # runs.  shuffle=False over exactly one global batch keeps the fed
    # tensors byte-identical to the direct arrays, so compute — and the
    # regression floors — are unaffected.  HVTPU_BENCH_DATA_LOADER=0
    # restores the direct path.
    loader = None
    if os.environ.get("HVTPU_BENCH_DATA_LOADER", "1") != "0" \
            and hvt.size() == 1:
        # single-controller path only: in a multi-process bench each
        # process already holds its own per-process global batch, which
        # the loader's world-sharding would re-split
        from jax.sharding import NamedSharding

        from horovod_tpu import data as hvt_data

        sharding = NamedSharding(mesh, P("world"))

        def place(batch):
            return {"x": jax.device_put(batch["x"], sharding),
                    "y": jax.device_put(batch["y"], sharding)}

        loader = hvt_data.ElasticDataLoader(
            hvt_data.ArraySource(
                {"x": np.asarray(images), "y": np.asarray(labels)}),
            batch_size=global_batch, shuffle=False, device_put=False,
            transform=place, name="bench")
        batches = loader.stream()

        def next_batch():
            b = next(batches)
            return b["x"], b["y"]
    else:
        def next_batch():
            return images, labels

    # Shape specs for the post-run AOT lowering (measured-MFU FLOPs):
    # captured before the loop because donated buffers are deleted by
    # then; lowering from ShapeDtypeStructs never touches data.
    aval_specs = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(jnp.shape(a), a.dtype),
        (params, batch_stats, opt_state, images, labels))

    loss = None
    for _ in range(WARMUP):
        x, y = next_batch()
        params, batch_stats, opt_state, loss = step(
            params, batch_stats, opt_state, x, y
        )
    if loss is not None:
        fence(loss)

    t0 = time.perf_counter()
    for _ in range(ITERS):
        x, y = next_batch()
        params, batch_stats, opt_state, loss = step(
            params, batch_stats, opt_state, x, y
        )
        # jit path: the traced update can't count itself, so the host
        # loop reports steps/examples per dispatch (obs/metrics.py).
        obs_metrics.note_step(examples=global_batch * STEPS_PER_CALL,
                              steps=STEPS_PER_CALL)
    final_loss = fence(loss)
    elapsed = time.perf_counter() - t0

    # Optional device-profile capture of one extra (untimed) dispatch:
    # joins the XLA op timeline against the collective windows and
    # publishes the measured overlap fraction (hvtpu_step_overlap_
    # fraction).  HVTPU_BENCH_PROFILE names the capture dir.
    overlap_fraction = None
    prof_dir = os.environ.get("HVTPU_BENCH_PROFILE", "")
    if prof_dir:
        with obs_stepprof.profile_window(prof_dir) as join:
            x, y = next_batch()
            params, batch_stats, opt_state, loss = step(
                params, batch_stats, opt_state, x, y
            )
            fence(loss)
        overlap_fraction = join.get("overlap_fraction")
    if loader is not None:
        loader.close()

    if not np.isfinite(final_loss):
        raise RuntimeError(f"non-finite loss {final_loss}; benchmark invalid")

    img_per_sec = global_batch * ITERS * STEPS_PER_CALL / elapsed
    img_per_sec_per_chip = img_per_sec / n_dev
    # MFU context: approx train FLOPs/image (fwd+bwd) per model against
    # v5e's 197 TFLOP/s bf16 peak (resnet50 figure from XLA cost
    # analysis: 6.08e12 flops at batch 256; others are standard
    # 3x-forward estimates).  The resnet50 step is HBM-bound, so MFU is
    # the honest context for the img/s number, not the target.
    flops_per_img = {"resnet50": 23.8e9, "resnet101": 47e9,
                     "inception3": 34e9, "vgg16": 93e9}[MODEL]
    mfu = img_per_sec_per_chip * flops_per_img / 197e12
    # Measured MFU (PR 12): the FLOPs numerator comes from the compiled
    # program's own cost model — jit(...).lower().compile().
    # cost_analysis() — instead of the hand table above; cost_analysis
    # counts the per-device program, so dividing by per-dispatch steps
    # and per-chip batch yields FLOPs/image/chip directly.  mfu_est is
    # retained for comparison; a backend without cost analysis reports
    # null rather than guessing.
    mfu_measured = None
    try:
        compiled = step.lower(*aval_specs).compile()
        flops_call = obs_stepprof.measured_flops(compiled)
    except Exception:
        flops_call = None
    if flops_call:
        flops_img = flops_call / (STEPS_PER_CALL * BATCH_PER_CHIP)
        mfu_measured = round(
            img_per_sec_per_chip * flops_img
            / obs_stepprof.peak_flops(), 4)
        obs_stepprof.set_step_flops(flops_call / STEPS_PER_CALL)

    exposed = condense_metrics()["hvtpu_step_exposed_comm_seconds"]
    exposed_comm_ms = (
        round(exposed["sum"] / exposed["count"] * 1e3, 3)
        if exposed["count"] else 0.0)
    # vs_baseline is defined against the north-star ResNet-50 A100
    # parity bar; other models report null (no published per-chip bar)
    vs_baseline = (
        round(img_per_sec_per_chip / A100_BASELINE_IMG_PER_SEC_PER_CHIP, 4)
        if MODEL == "resnet50" else None
    )
    regression = check_regression_floor(
        MODEL, img_per_sec_per_chip,
        os.path.dirname(os.path.abspath(__file__)))
    print(
        json.dumps(
            build_report(
                metric=(
                    f"{MODEL}_synthetic_bf16_images_per_sec_per_chip"
                ),
                value=round(img_per_sec_per_chip, 1),
                unit="images/sec/chip",
                vs_baseline=vs_baseline,
                model=MODEL,
                batch_per_chip=BATCH_PER_CHIP,
                mfu_est=round(mfu, 4),
                mfu_measured=mfu_measured,
                overlap_fraction=overlap_fraction,
                exposed_comm_ms=exposed_comm_ms,
                elapsed_seconds=round(elapsed, 3),
                notes=(
                    f"{STEPS_PER_CALL} steps/dispatch via lax.scan"
                ) if MODEL != "resnet50" else (
                    f"{STEPS_PER_CALL} steps/dispatch via lax.scan; "
                    "TPU-fast BatchNorm (flattened 2-D stats, bf16 "
                    "normalize pass). HBM-bandwidth-bound: profiled "
                    "step is 34% BN stats/grad column-reduces, 25% "
                    "BN/ReLU elementwise, 24% convs, 12% residual "
                    "adds, i.e. ~96% of the 77 GB/step roofline at "
                    "829 GB/s (2720 img/s ceiling). Round-3 kernel "
                    "audit (docs/benchmarks.md): Pallas data-plane "
                    "kernels measure 105-237 GB/s vs XLA's 829 on "
                    "this stack, MXU dot-stats ties the fused reduce "
                    "by construction — fused conv+BN byte removal is "
                    "the only lever left and sits inside XLA's conv. "
                    "Batch 512, remat, s2d stem, 64 steps/dispatch, "
                    "standalone Pallas BN all measured <=0 gain"
                ),
            )
        )
    )
    if regression is not None:
        print(regression, file=sys.stderr)
        sys.exit(2)


if __name__ == "__main__":
    main()
