"""Hand-rolled ICI ring collectives as Pallas TPU kernels.

The reference's data plane is NCCL's ring algorithms
(``horovod/common/ops/nccl_operations.cc`` — ``ncclAllReduce`` et al.
run ring reduce-scatter + ring all-gather over NVLink).  On TPU, XLA's
own collectives already lower to tuned ICI rings, so these kernels are
NOT the default data plane; they exist for the cases XLA cannot
express:

* ``ring_allreduce(..., quantized=True)`` — the true EQuARX design
  (PAPERS.md, arXiv:2506.17615): int8 codes + per-block scales cross
  the wire on EVERY hop.  Reduce-scatter hops dequantize → f32
  accumulate → requantize (values change per hop); all-gather hops
  relay each owner's codes VERBATIM (store-and-forward), so every
  rank dequantizes identical bytes and the output is bit-equal across
  ranks — the allreduce contract.  The XLA-level approximation in
  comm/quantized.py must round-trip through ``all_to_all``/
  ``all_gather``; here the quantize lives inside the transfer loop,
  which is the actual paper algorithm (1 B/elt wire on all 2(N-1)
  hops).
* A reference implementation of the ring protocol itself (double
  buffering, per-slot DMA semaphore accounting) that the multi-chip
  dry-run exercises in the Pallas TPU interpreter — the same role the
  Python controller twin plays for the C++ control plane.

Protocol (the standard bidirectional-capable ring, one direction):
each device holds a 2-slot VMEM comm buffer; step ``i`` RDMAs slot
``i%2`` to the right neighbor's slot ``(i+1)%2`` with per-slot send /
recv semaphores, so a slot is never written while its previous
transfer is in flight.  Reduce-scatter accumulates the received chunk
with the local contribution in place; after N-1 steps rank r owns the
fully-reduced chunk (r+1)%N, and a second N-1-step ring gathers them.

Shapes: kernels operate on f32 ``(N*CH, 128)`` buffers (CH rows per
rank); the public wrappers flatten/pad arbitrary tensors.  The whole
buffer lives in VMEM — callers should keep per-call payloads under a
few MB (the fused-bucket path already slices at the fusion threshold).

Testing: CPU runs execute the REAL kernel bodies under the Pallas TPU
interpreter (``pltpu.InterpretParams``), which simulates the remote
DMAs and semaphores across the shard_map devices (race detection
available); on a single real chip the ring degenerates to a copy and
runs compiled.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_ops import _LANES, _QROWS, _pallas_mode, block_scale_inv

# Per-rank chunk rows must be a multiple of the f32 tile height.
_CHUNK_ROW_QUANTUM = 8

def _CompilerParams(**kw):
    """Portable pltpu compiler params: jax < 0.5 names the dataclass
    TPUCompilerParams and lacks newer fields (has_side_effects), which
    are dropped there — the interpreter path those versions take does
    not consult them."""
    import dataclasses

    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    fields = {f.name for f in dataclasses.fields(cls)}
    return cls(**{k: v for k, v in kw.items() if k in fields})


def _interpret_arg():
    use, interp = _pallas_mode()
    if not use:
        return None  # caller must fall back
    if not interp:
        return False
    if not hasattr(pltpu, "InterpretParams"):
        # jax < 0.5: the legacy Pallas interpreter cannot simulate
        # remote DMA semaphores ("Remote signal not implemented"), so
        # the ring kernels are unrunnable on CPU there — fall back to
        # the XLA collectives the wrappers keep for exactly this case.
        return None
    return pltpu.InterpretParams()


# ----------------------------------------------------------------------
# ring all-gather
# ----------------------------------------------------------------------


def _allgather_kernel(local_ref, out_ref, comm_ref, send_sem, recv_sem,
                      ack_sem, *, axis_name):
    my_id = lax.axis_index(axis_name)
    n = lax.axis_size(axis_name)
    left = lax.rem(my_id - 1 + n, n)
    ch = local_ref.shape[0]
    out_ref[pl.ds(my_id * ch, ch), :] = local_ref[:]
    comm_ref[0] = local_ref[:]

    def step(i, _):
        send_slot = lax.rem(i, 2)
        recv_slot = lax.rem(i + 1, 2)
        dst = lax.rem(my_id + 1, n)
        src_dev = lax.rem(my_id - i - 1 + 2 * n, n)

        # Backpressure: my step-i RDMA writes the right neighbor's
        # comm[recv_slot], which was THEIR send buffer at step i-1 —
        # wait for their ACK that the slot is free.  Without this a
        # rank running ahead stomps a slower neighbor's unsent data
        # (ring skew is unbounded: each rank only waits on its own
        # semaphores).
        @pl.when(i >= 1)
        def _():
            pltpu.semaphore_wait(ack_sem.at[recv_slot], 1)

        rdma = pltpu.make_async_remote_copy(
            src_ref=comm_ref.at[send_slot],
            dst_ref=comm_ref.at[recv_slot],
            send_sem=send_sem.at[send_slot],
            recv_sem=recv_sem.at[recv_slot],
            device_id=dst,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        rdma.wait()
        out_ref[pl.ds(src_dev * ch, ch), :] = comm_ref[recv_slot]

        # my send buffer is dead -> tell the LEFT neighbor (who writes
        # it at their next step); skip after the last step that could
        # consume it, or the count leaks past kernel exit
        @pl.when(i < n - 2)
        def _():
            pltpu.semaphore_signal(
                ack_sem.at[send_slot], inc=1, device_id=left,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )

        return 0

    lax.fori_loop(0, n - 1, step, 0)


def ring_allgather_2d(local, *, axis_name: str):
    """All-gather a per-rank ``(CH, 128)`` f32 block into ``(N*CH, 128)``
    via the Pallas ring.  Must run inside shard_map over ``axis_name``."""
    n = lax.axis_size(axis_name)
    ch = local.shape[0]
    interp = _interpret_arg()
    if interp is None:
        return lax.all_gather(local, axis_name, tiled=True)
    return pl.pallas_call(
        functools.partial(_allgather_kernel, axis_name=axis_name),
        out_shape=jax.ShapeDtypeStruct((n * ch, _LANES), jnp.float32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, ch, _LANES), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR((2,)),
        ],
        # distinct collective_id per kernel entry point: concurrent
        # collective kernels sharing a barrier semaphore is documented
        # as a correctness hazard (allgather=0, allreduce=1, quant=2)
        compiler_params=_CompilerParams(
            has_side_effects=True, collective_id=0
        ),
        interpret=interp,
    )(local.astype(jnp.float32))


# ----------------------------------------------------------------------
# ring allreduce (reduce-scatter phase + all-gather phase)
# ----------------------------------------------------------------------


def _allreduce_kernel(x_ref, out_ref, comm_ref, acc_ref,
                      send_sem, recv_sem, ack_sem, *, axis_name):
    """x_ref: (N*CH, 128) local contributions; out_ref: (N*CH, 128)
    reduced result (same on every rank afterwards)."""
    my_id = lax.axis_index(axis_name)
    n = lax.axis_size(axis_name)
    left = lax.rem(my_id - 1 + n, n)
    ch = x_ref.shape[0] // n

    # ---- phase 1: ring reduce-scatter ------------------------------
    # comm starts with my contribution to chunk my_id's ring walk.
    comm_ref[0] = x_ref[pl.ds(my_id * ch, ch), :]

    def rs_step(i, _):
        send_slot = lax.rem(i, 2)
        recv_slot = lax.rem(i + 1, 2)
        dst = lax.rem(my_id + 1, n)
        chunk = lax.rem(my_id - i - 1 + 2 * n, n)  # chunk received now

        # backpressure (see _allgather_kernel): don't write the right
        # neighbor's slot until they've freed it
        @pl.when(i >= 1)
        def _():
            pltpu.semaphore_wait(ack_sem.at[recv_slot], 1)

        rdma = pltpu.make_async_remote_copy(
            src_ref=comm_ref.at[send_slot],
            dst_ref=comm_ref.at[recv_slot],
            send_sem=send_sem.at[send_slot],
            recv_sem=recv_sem.at[recv_slot],
            device_id=dst,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        rdma.wait()
        # accumulate my contribution in place; this slot is next step's
        # send buffer
        comm_ref[recv_slot] = (
            comm_ref[recv_slot] + x_ref[pl.ds(chunk * ch, ch), :]
        )

        @pl.when(i < n - 2)
        def _():
            pltpu.semaphore_signal(
                ack_sem.at[send_slot], inc=1, device_id=left,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )

        return 0

    lax.fori_loop(0, n - 1, rs_step, 0)

    # I now hold the fully-reduced chunk (my_id+1)%N in slot (n-1)%2.
    owned = lax.rem(my_id + 1, n)
    final_slot = lax.rem(n - 1, 2)
    acc_ref[:] = comm_ref[final_slot]
    out_ref[pl.ds(owned * ch, ch), :] = acc_ref[:]

    # ---- phase 2: ring all-gather of reduced chunks ----------------
    # DISJOINT slot pair (2,3) + matching semaphores: a rank ahead of
    # its neighbor may start phase 2 while the neighbor still waits on
    # its last phase-1 receive — sharing slots would let the phase-2
    # RDMA overwrite that in-flight phase-1 buffer.
    comm_ref[2] = acc_ref[:]

    def ag_step(i, _):
        send_slot = 2 + lax.rem(i, 2)
        recv_slot = 2 + lax.rem(i + 1, 2)
        dst = lax.rem(my_id + 1, n)
        src_dev = lax.rem(my_id - i - 1 + 2 * n, n)
        src_chunk = lax.rem(src_dev + 1, n)   # chunk owned by src_dev

        @pl.when(i >= 1)
        def _():
            pltpu.semaphore_wait(ack_sem.at[recv_slot], 1)

        rdma = pltpu.make_async_remote_copy(
            src_ref=comm_ref.at[send_slot],
            dst_ref=comm_ref.at[recv_slot],
            send_sem=send_sem.at[send_slot],
            recv_sem=recv_sem.at[recv_slot],
            device_id=dst,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        rdma.wait()
        out_ref[pl.ds(src_chunk * ch, ch), :] = comm_ref[recv_slot]

        @pl.when(i < n - 2)
        def _():
            pltpu.semaphore_signal(
                ack_sem.at[send_slot], inc=1, device_id=left,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )

        return 0

    lax.fori_loop(0, n - 1, ag_step, 0)


def _quantize_block(x):
    """(CH, 128) f32 -> int8 codes (CH,128) + scales (CH/8, 1); shares
    the exact scale formula with pallas_ops (block_scale_inv)."""
    g = x.shape[0] // _QROWS
    xg = x.reshape(g, _QROWS * _LANES)
    scale, inv = block_scale_inv(xg)
    q = jnp.clip(jnp.round(xg * inv), -127, 127).astype(jnp.int8)
    return q.reshape(x.shape), scale


def _dequantize_block(q, scale):
    g = q.shape[0] // _QROWS
    deq = q.astype(jnp.float32).reshape(g, _QROWS * _LANES) * scale
    return deq.reshape(q.shape)


def _quantized_allreduce_kernel(x_ref, out_ref, qcomm_ref, scomm_ref,
                                acc_ref, send_sem, recv_sem,
                                ssend_sem, srecv_sem, ack_sem,
                                *, axis_name):
    """Per-hop requantizing ring allreduce: EVERY transfer carries int8
    codes + f32 per-1024-block scales; accumulation stays f32."""
    my_id = lax.axis_index(axis_name)
    n = lax.axis_size(axis_name)
    left = lax.rem(my_id - 1 + n, n)
    ch = x_ref.shape[0] // n

    def transfer_hop(i, base):
        """One double-buffered ring hop of the codes+scales pair that
        currently sit in slot ``base + i%2``: ACK-backpressured dual
        RDMA to the right neighbor's slot ``base + (i+1)%2``, then the
        freed-slot ACK to the left.  The semaphore protocol lives ONLY
        here — both phases (and any future one) share it.  ``base``
        selects the phase's disjoint slot pair (see _allreduce_kernel:
        phases must not share in-flight buffers/semaphores).  Returns
        the recv slot index."""
        send_slot = base + lax.rem(i, 2)
        recv_slot = base + lax.rem(i + 1, 2)
        dst = lax.rem(my_id + 1, n)

        # backpressure (one ACK covers the lockstep codes+scales pair)
        @pl.when(i >= 1)
        def _():
            pltpu.semaphore_wait(ack_sem.at[recv_slot], 1)

        rdma_q = pltpu.make_async_remote_copy(
            src_ref=qcomm_ref.at[send_slot],
            dst_ref=qcomm_ref.at[recv_slot],
            send_sem=send_sem.at[send_slot],
            recv_sem=recv_sem.at[recv_slot],
            device_id=dst,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma_s = pltpu.make_async_remote_copy(
            src_ref=scomm_ref.at[send_slot],
            dst_ref=scomm_ref.at[recv_slot],
            send_sem=ssend_sem.at[send_slot],
            recv_sem=srecv_sem.at[recv_slot],
            device_id=dst,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma_q.start()
        rdma_s.start()
        rdma_q.wait()
        rdma_s.wait()

        @pl.when(i < n - 2)
        def _():
            pltpu.semaphore_signal(
                ack_sem.at[send_slot], inc=1, device_id=left,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )

        return recv_slot

    def send_hop(i, value, base):
        """Quantize ``value`` into the send slot, run a transfer hop,
        return the dequantized incoming block."""
        send_slot = base + lax.rem(i, 2)
        q, s = _quantize_block(value)
        qcomm_ref[send_slot] = q
        scomm_ref[send_slot] = s
        recv_slot = transfer_hop(i, base)
        return _dequantize_block(qcomm_ref[recv_slot], scomm_ref[recv_slot])

    # ---- phase 1: reduce-scatter with per-hop requantization -------
    acc_ref[:] = x_ref[pl.ds(my_id * ch, ch), :]

    def rs_step(i, _):
        chunk = lax.rem(my_id - i - 1 + 2 * n, n)
        incoming = send_hop(i, acc_ref[:], 0)
        acc_ref[:] = incoming + x_ref[pl.ds(chunk * ch, ch), :]
        return 0

    lax.fori_loop(0, n - 1, rs_step, 0)

    # ---- phase 2: all-gather, store-and-forward --------------------
    # The reduced chunk values do NOT change in this phase, so each
    # chunk is quantized exactly ONCE (by its owner) and the int8
    # codes + scales are relayed VERBATIM around the ring.  Every rank
    # therefore dequantizes identical bytes — the output is bit-equal
    # on all ranks (the allreduce contract) and the quantization error
    # does not grow with ring distance.  The owner likewise keeps the
    # dequantized form of the codes it put on the wire, not its raw
    # f32 accumulator.
    owned = lax.rem(my_id + 1, n)
    q0, s0 = _quantize_block(acc_ref[:])
    qcomm_ref[2] = q0
    scomm_ref[2] = s0
    out_ref[pl.ds(owned * ch, ch), :] = _dequantize_block(q0, s0)

    def ag_step(i, _):
        src_dev = lax.rem(my_id - i - 1 + 2 * n, n)
        src_chunk = lax.rem(src_dev + 1, n)
        # relay only — no quantize: received codes land in recv_slot
        # == next step's send_slot, so they are forwarded verbatim
        recv_slot = transfer_hop(i, 2)
        out_ref[pl.ds(src_chunk * ch, ch), :] = _dequantize_block(
            qcomm_ref[recv_slot], scomm_ref[recv_slot]
        )
        return 0

    lax.fori_loop(0, n - 1, ag_step, 0)


def _ring_allreduce_2d(x2, *, axis_name: str, quantized: bool):
    n = lax.axis_size(axis_name)
    rows = x2.shape[0]
    ch = rows // n
    interp = _interpret_arg()
    assert interp is not None
    if quantized:
        kernel = functools.partial(
            _quantized_allreduce_kernel, axis_name=axis_name
        )
        scratch = [
            pltpu.VMEM((4, ch, _LANES), jnp.int8),
            pltpu.VMEM((4, ch // _QROWS, 1), jnp.float32),
            pltpu.VMEM((ch, _LANES), jnp.float32),
            pltpu.SemaphoreType.DMA((4,)),
            pltpu.SemaphoreType.DMA((4,)),
            pltpu.SemaphoreType.DMA((4,)),
            pltpu.SemaphoreType.DMA((4,)),
            pltpu.SemaphoreType.REGULAR((4,)),
        ]
    else:
        kernel = functools.partial(_allreduce_kernel, axis_name=axis_name)
        scratch = [
            pltpu.VMEM((4, ch, _LANES), jnp.float32),
            pltpu.VMEM((ch, _LANES), jnp.float32),
            pltpu.SemaphoreType.DMA((4,)),
            pltpu.SemaphoreType.DMA((4,)),
            pltpu.SemaphoreType.REGULAR((4,)),
        ]
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((rows, _LANES), jnp.float32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=scratch,
        compiler_params=_CompilerParams(
            has_side_effects=True, collective_id=2 if quantized else 1
        ),
        interpret=interp,
    )(x2)


def ring_allreduce(tensor, *, axis_name: str, average: bool = False,
                   quantized: bool = False):
    """Ring allreduce of an arbitrary float tensor inside shard_map.

    ``quantized=True`` sends int8 codes + per-1024-element scales on
    every hop (per-hop requantization — the EQuARX algorithm proper).
    Falls back to ``psum`` / the XLA-level quantized path when Pallas
    is unavailable.

    The per-rank chunk must fit VMEM; callers on the hot path slice at
    the fusion threshold first.
    """
    n = lax.axis_size(axis_name)
    orig_shape = tensor.shape
    orig_dtype = tensor.dtype

    if not jnp.issubdtype(orig_dtype, jnp.floating):
        # integers always take the exact psum path (the f32 ring would
        # silently lose precision past 2^24 and the result dtype would
        # depend on which backend is active); average uses floor
        # division like spmd.allreduce's integer convention.
        out = lax.psum(tensor, axis_name)
        if average:
            out = out // n
        return out

    flat = tensor.reshape(-1).astype(jnp.float32)
    size = flat.shape[0]

    if _interpret_arg() is None or n == 1:
        if quantized and n > 1:
            from ..comm.quantized import quantized_allreduce

            return quantized_allreduce(
                tensor, axis_name=axis_name, average=average
            )
        out = lax.psum(tensor.astype(jnp.float32), axis_name)
        if average:
            out = out / n
        return out.astype(orig_dtype)

    # pad so every rank owns an equal (CH, 128) block with CH a
    # multiple of the tile/scale quantum
    quantum = n * _CHUNK_ROW_QUANTUM * _LANES
    padded = ((size + quantum - 1) // quantum) * quantum
    if padded != size:
        flat = jnp.pad(flat, (0, padded - size))
    x2 = flat.reshape(padded // _LANES, _LANES)

    red = _ring_allreduce_2d(x2, axis_name=axis_name, quantized=quantized)
    out = red.reshape(-1)[:size]
    if average:
        out = out / n
    return out.reshape(orig_shape).astype(orig_dtype)
