"""Ring attention: exact long-context attention over a sequence-sharded
mesh axis.

The reference has **no** sequence/context parallelism (SURVEY.md §5.7 —
its nearest primitive is ``hvd.alltoall``).  This module is the
TPU-first answer to the same scaling problem: each device holds a
``T/S`` slice of the sequence; K/V blocks rotate around the ``sp`` ring
via ``lax.ppermute`` (lowered to ICI neighbour transfers) while each
device folds every block into a numerically-stable online-softmax
accumulator (the log-sum-exp recurrence of blockwise/flash attention).
Compute on block ``s`` overlaps the transfer of block ``s+1`` because
XLA schedules the ppermute asynchronously.

Memory per device is O(T/S · d) for K/V and O((T/S)²) only transiently
per block-pair — sequence length scales linearly with ring size.

Call inside ``jax.shard_map`` with the sequence dimension sharded over
``axis_name``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30


def _block_scores(q, k, scale):
    # q: [B, H, Tq, D]  k: [B, H, Tk, D]  -> [B, H, Tq, Tk]
    return jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    """Exact attention over a sequence sharded along ``axis_name``.

    Args:
      q, k, v: local shards ``[B, H, T_local, D]`` (sequence dim 2).
      axis_name: mesh axis the sequence is sharded over (ring).
      causal: apply a causal mask in *global* sequence positions.
      scale: score scale; default ``1/sqrt(D)``.

    Returns:
      Local attention output ``[B, H, T_local, D]``.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    ring_size = lax.axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    b, h, t_local, d = q.shape

    q32 = q.astype(jnp.float32)

    q_gpos = my_idx * t_local + jnp.arange(t_local)  # [Tq] global positions

    def fold(carry, s):
        k_cur, v_cur, m, l, acc = carry
        # After s forward rotations, we hold the block originally owned
        # by ring position (my_idx - s) mod S.
        src = (my_idx - s) % ring_size
        scores = _block_scores(q32, k_cur.astype(jnp.float32), scale)
        if causal:
            k_gpos = src * t_local + jnp.arange(t_local)
            mask = q_gpos[:, None] >= k_gpos[None, :]  # [Tq, Tk]
            scores = jnp.where(mask[None, None], scores, _NEG_INF)
        m_new = jnp.maximum(m, scores.max(axis=-1))  # [B, H, Tq]
        # Guard fully-masked rows: keep m finite so exp() stays 0, not nan.
        m_safe = jnp.maximum(m_new, _NEG_INF / 2)
        p = jnp.exp(scores - m_safe[..., None])  # [B, H, Tq, Tk]
        correction = jnp.exp(m - m_safe)  # [B, H, Tq]
        l = l * correction + p.sum(axis=-1)
        acc = acc * correction[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_cur.astype(jnp.float32)
        )
        k_nxt, v_nxt = _rotate(k_cur, v_cur, axis_name, ring_size)
        return (k_nxt, v_nxt, m_new, l, acc), None

    # Scan requires carry input/output types (incl. varying-axis sets)
    # to match.  The loop makes every carry vary over this ring axis
    # (ppermute) and over whatever axes q/k/v already vary over; build
    # a zero that carries exactly that union and fold it into the inits.
    zero = (
        (q32 * 0).sum()
        + (k.astype(jnp.float32) * 0).sum()
        + (v.astype(jnp.float32) * 0).sum()
        + (lax.axis_index(axis_name) * 0).astype(jnp.float32)
    )
    k0 = k + zero.astype(k.dtype)
    v0 = v + zero.astype(v.dtype)
    m0 = jnp.full((b, h, t_local), _NEG_INF, jnp.float32) + zero
    l0 = jnp.zeros((b, h, t_local), jnp.float32) + zero
    acc0 = jnp.zeros((b, h, t_local, d), jnp.float32) + zero
    (_, _, _, l, acc), _ = _scan_fold(fold, (k0, v0, m0, l0, acc0),
                                      ring_size)
    out = acc / jnp.maximum(l[..., None], 1e-30)
    return out.astype(q.dtype)


def _rotate(k, v, axis_name, ring_size):
    perm = [(i, (i + 1) % ring_size) for i in range(ring_size)]
    return (
        lax.ppermute(k, axis_name, perm),
        lax.ppermute(v, axis_name, perm),
    )


def _scan_fold(fold, init, steps):
    return lax.scan(fold, init, jnp.arange(steps))
