"""In-memory coordination-KV fabric with per-link latency models.

The real control plane talks to the JAX coordination service through a
tiny client surface — ``key_value_set`` / ``blocking_key_value_get`` /
``key_value_try_get`` / ``key_value_delete``, plus the optional
directory and raw-bytes extensions newer jaxlibs add.  Every framework
component already routes through that surface (KVTransport,
ResilientKV, the stall inspector, the drain coordinator, the audit
exchange), so substituting it is enough to host the WHOLE plane on the
simulator: no framework code changes, no mocks of framework logic.

:class:`SimFabric` is the central store; :meth:`SimFabric.client`
returns a per-rank client facade whose every operation

1. parks the calling task for the rank's *request* link delay
   (latency + payload/bandwidth + seeded jitter),
2. applies the operation to the store at the virtual arrival instant
   (writes wake parked blocking gets immediately — the coordination
   service's watch semantics), and
3. parks again for the *response* leg before returning.

Timeout semantics match the production client: a blocking get that
expires raises ``TimeoutError`` with a ``DEADLINE_EXCEEDED`` marker
(what ``core/retry.py`` classifies as retryable), a ``try_get`` miss
raises ``KeyError`` with ``NOT_FOUND`` (an *answer*, not a transient),
and a delete with a trailing ``/`` clears the whole prefix (the
directory-GC idiom KVTransport uses between lockstep cycles).

Capability tiers mirror the client zoo the framework already handles:
``caps="str"`` is the minimal legacy surface, ``"dir"`` adds
``key_value_dir_get`` (what unlocks the amortized stall inspector and
single-RPC request gathers), ``"bytes"`` adds the raw-bytes triple
(what KVTransport's base64-free fast path detects).  Scenarios pick a
tier to run the same protocol over each capability level.

Chaos does NOT live here: ``kv.get`` / ``kv.put`` fault clauses fire
at the REAL injection sites inside ResilientKV and KVTransport, so an
injected brownout exercises the production retry/backoff code, not a
simulator re-implementation.  The fabric's own knobs
(``HVTPU_SIM_LATENCY_US``, ``HVTPU_SIM_BANDWIDTH_GBPS``,
``HVTPU_SIM_JITTER_FRAC``) shape the *healthy* network instead.
"""

from __future__ import annotations

import collections
import os
from typing import Dict, List, Optional, Tuple

from .kernel import SimKernel, WaitToken

__all__ = ["LinkModel", "EdgeModel", "SimFabric"]


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    try:
        return float(raw)
    except ValueError:
        raise ValueError(
            f"{name} must be a number, got {raw!r}") from None


class LinkModel:
    """One rank's link to the coordination service: fixed latency plus
    payload serialisation time plus seeded jitter."""

    __slots__ = ("latency_s", "bandwidth_bps", "jitter_frac", "_rng")

    def __init__(self, latency_s: float, bandwidth_bps: float,
                 jitter_frac: float, rng):
        self.latency_s = max(0.0, float(latency_s))
        self.bandwidth_bps = max(1.0, float(bandwidth_bps))
        self.jitter_frac = max(0.0, float(jitter_frac))
        self._rng = rng

    def delay(self, nbytes: int) -> float:
        base = self.latency_s + nbytes / self.bandwidth_bps
        if not self.jitter_frac:
            return base
        return base * (1.0 + self.jitter_frac * self._rng.random())


class EdgeModel:
    """One directed DATA-plane edge ``src → dst`` between ranks.

    Where :class:`LinkModel` shapes a rank's path to the coordination
    service, an edge shapes the peer-to-peer wire the ring collectives
    ride (the ``lossy-link`` scenario's hop model).  On top of the
    latency/bandwidth/jitter triple it carries a seeded per-send loss
    probability and an optional periodic FLAP window during which the
    edge drops everything — the two failure shapes "Demystifying NCCL"
    reports from production fabrics."""

    __slots__ = ("latency_s", "bandwidth_bps", "jitter_frac",
                 "loss_prob", "flap_period_s", "flap_down_s",
                 "flap_start_s", "_rng")

    def __init__(self, latency_s: float, bandwidth_bps: float,
                 jitter_frac: float, rng, loss_prob: float = 0.0,
                 flap_period_s: float = 0.0, flap_down_s: float = 0.0,
                 flap_start_s: float = 0.0):
        self.latency_s = max(0.0, float(latency_s))
        self.bandwidth_bps = max(1.0, float(bandwidth_bps))
        self.jitter_frac = max(0.0, float(jitter_frac))
        self.loss_prob = min(1.0, max(0.0, float(loss_prob)))
        self.flap_period_s = max(0.0, float(flap_period_s))
        self.flap_down_s = max(0.0, float(flap_down_s))
        self.flap_start_s = max(0.0, float(flap_start_s))
        self._rng = rng

    def delay(self, nbytes: int) -> float:
        base = self.latency_s + nbytes / self.bandwidth_bps
        if not self.jitter_frac:
            return base
        return base * (1.0 + self.jitter_frac * self._rng.random())

    def up(self, now: float) -> bool:
        """False while inside a flap's down window (the first
        ``flap_down_s`` of each period, starting at ``flap_start_s``)."""
        if self.flap_period_s <= 0.0 or now < self.flap_start_s:
            return True
        phase = (now - self.flap_start_s) % self.flap_period_s
        return phase >= self.flap_down_s

    def lost(self, now: float) -> bool:
        """One send's fate at virtual instant ``now``: dropped by the
        flap window, or by the seeded per-send loss draw."""
        if not self.up(now):
            return True
        return bool(self.loss_prob
                    and self._rng.random() < self.loss_prob)


class SimFabric:
    """The simulated coordination service: one store, per-rank links,
    park-and-notify blocking gets, and operation counters."""

    def __init__(self, kernel: SimKernel, *,
                 latency_us: Optional[float] = None,
                 bandwidth_gbps: Optional[float] = None,
                 jitter_frac: Optional[float] = None):
        self.kernel = kernel
        if latency_us is None:
            latency_us = _env_float("HVTPU_SIM_LATENCY_US", 50.0)
        if bandwidth_gbps is None:
            bandwidth_gbps = _env_float("HVTPU_SIM_BANDWIDTH_GBPS", 1.0)
        if jitter_frac is None:
            jitter_frac = _env_float("HVTPU_SIM_JITTER_FRAC", 0.1)
        self._latency_s = latency_us / 1e6
        self._bandwidth_bps = bandwidth_gbps * 1e9 / 8.0
        self._jitter_frac = jitter_frac
        self._store: Dict[str, object] = {}
        self._waiters: Dict[str, List[WaitToken]] = {}
        self._links: Dict[int, LinkModel] = {}
        self._edges: Dict[Tuple[int, int], EdgeModel] = {}
        self._down = False
        self.ops = collections.Counter()

    # -- outage ---------------------------------------------------------
    def set_down(self, down: bool = True) -> None:
        """Coordinator outage switch: while down, every server-side
        operation spends its request-leg delay and then raises an
        ``UNAVAILABLE``-marked error (what ``core/retry.py`` classifies
        as retryable) instead of touching the store.  Models the
        coordinator HOST dying — clients keep timing out until the
        driver relaunches against a fresh fabric."""
        self._down = bool(down)

    @property
    def down(self) -> bool:
        return self._down

    def _check_up(self, key: str) -> None:
        if self._down:
            self.ops["unavailable"] += 1
            raise ConnectionError(
                f"UNAVAILABLE: coordination service unreachable "
                f"({key!r})")

    # -- links ----------------------------------------------------------
    def link(self, rank: int) -> LinkModel:
        model = self._links.get(rank)
        if model is None:
            model = LinkModel(
                self._latency_s, self._bandwidth_bps, self._jitter_frac,
                self.kernel.rng(f"link/{rank}"))
            self._links[rank] = model
        return model

    def set_link(self, rank: int, *, latency_s: Optional[float] = None,
                 bandwidth_bps: Optional[float] = None,
                 jitter_frac: Optional[float] = None) -> LinkModel:
        """Override one rank's link (straggler / brownout shaping)."""
        base = self.link(rank)
        self._links[rank] = LinkModel(
            base.latency_s if latency_s is None else latency_s,
            base.bandwidth_bps if bandwidth_bps is None else bandwidth_bps,
            base.jitter_frac if jitter_frac is None else jitter_frac,
            self.kernel.rng(f"link/{rank}"))
        return self._links[rank]

    # -- data-plane edges ----------------------------------------------
    def edge(self, src: int, dst: int) -> EdgeModel:
        model = self._edges.get((src, dst))
        if model is None:
            model = EdgeModel(
                self._latency_s, self._bandwidth_bps, self._jitter_frac,
                self.kernel.rng(f"edge/{src}/{dst}"))
            self._edges[(src, dst)] = model
        return model

    def set_edge(self, src: int, dst: int, *,
                 latency_s: Optional[float] = None,
                 bandwidth_bps: Optional[float] = None,
                 jitter_frac: Optional[float] = None,
                 loss_prob: Optional[float] = None) -> EdgeModel:
        """Override one directed edge (sick-link shaping); unset
        fields keep the edge's current values."""
        base = self.edge(src, dst)
        model = EdgeModel(
            base.latency_s if latency_s is None else latency_s,
            base.bandwidth_bps if bandwidth_bps is None
            else bandwidth_bps,
            base.jitter_frac if jitter_frac is None else jitter_frac,
            self.kernel.rng(f"edge/{src}/{dst}"),
            loss_prob=base.loss_prob if loss_prob is None else loss_prob,
            flap_period_s=base.flap_period_s,
            flap_down_s=base.flap_down_s,
            flap_start_s=base.flap_start_s)
        self._edges[(src, dst)] = model
        return model

    def flap(self, src: int, dst: int, *, period_s: float,
             down_s: float, start_s: float = 0.0) -> EdgeModel:
        """Make the edge flap: down for the first ``down_s`` of every
        ``period_s`` window, beginning at virtual time ``start_s``."""
        base = self.edge(src, dst)
        base.flap_period_s = max(0.0, float(period_s))
        base.flap_down_s = max(0.0, float(down_s))
        base.flap_start_s = max(0.0, float(start_s))
        return base

    def edge_up(self, src: int, dst: int) -> bool:
        return self.edge(src, dst).up(self.kernel.now)

    def edge_lost(self, src: int, dst: int) -> bool:
        """Decide one send's fate on the edge NOW (counts toward the
        fabric's op counters so scenarios can audit loss volume)."""
        lost = self.edge(src, dst).lost(self.kernel.now)
        if lost:
            self.ops["edge_lost"] += 1
        else:
            self.ops["edge_send"] += 1
        return lost

    def edge_delay(self, src: int, dst: int, nbytes: int) -> float:
        return self.edge(src, dst).delay(nbytes)

    # -- client facades -------------------------------------------------
    def client(self, rank: int, caps: str = "bytes"):
        """A per-rank client at capability tier ``caps`` ∈ {"str",
        "dir", "bytes"}."""
        if caps == "str":
            return _StrKV(self, rank)
        if caps == "dir":
            return _DirKV(self, rank)
        if caps == "bytes":
            return _BytesKV(self, rank)
        raise ValueError(
            f"caps must be 'str' | 'dir' | 'bytes', got {caps!r}")

    # -- server-side operations (called from facades) -------------------
    @staticmethod
    def _nbytes(value) -> int:
        return len(value) if isinstance(value, (bytes, bytearray, str)) \
            else 64

    def _put(self, rank: int, key: str, value) -> None:
        link = self.link(rank)
        self.kernel.sleep(link.delay(self._nbytes(value)))
        self._check_up(key)
        self.ops["put"] += 1
        self._store[key] = value
        for token in self._waiters.pop(key, []):
            # capture the value at notification time: the key may be
            # deleted again before the waiter's resume event fires
            self.kernel.notify(token, value=value)
        self.kernel.sleep(link.delay(1))

    def _delete(self, rank: int, key: str) -> None:
        link = self.link(rank)
        self.kernel.sleep(link.delay(len(key)))
        self._check_up(key)
        self.ops["delete"] += 1
        if key.endswith("/"):
            for k in [k for k in self._store if k.startswith(key)]:
                del self._store[k]
        else:
            self._store.pop(key, None)
        self.kernel.sleep(link.delay(1))

    def _try_get(self, rank: int, key: str):
        link = self.link(rank)
        self.kernel.sleep(link.delay(len(key)))
        self._check_up(key)
        self.ops["get"] += 1
        if key not in self._store:
            self.kernel.sleep(link.delay(1))
            raise KeyError(f"NOT_FOUND: {key}")
        value = self._store[key]
        self.kernel.sleep(link.delay(self._nbytes(value)))
        return value

    def _blocking_get(self, rank: int, key: str, timeout_ms: int):
        link = self.link(rank)
        self.kernel.sleep(link.delay(len(key)))
        self._check_up(key)
        self.ops["get"] += 1
        if key in self._store:
            value = self._store[key]
        else:
            token = WaitToken()
            self._waiters.setdefault(key, []).append(token)
            ok = self.kernel.block(
                token, max(0.0, timeout_ms) / 1000.0,
                f"kv.blocking_get({key})")
            if not ok:
                waiting = self._waiters.get(key)
                if waiting is not None:
                    try:
                        waiting.remove(token)
                    except ValueError:
                        pass
                    if not waiting:
                        del self._waiters[key]
                self.ops["get_timeout"] += 1
                raise TimeoutError(
                    f"DEADLINE_EXCEEDED: key {key!r} not posted within "
                    f"{timeout_ms}ms")
            value = token.value
        self.kernel.sleep(link.delay(self._nbytes(value)))
        return value

    def _dir_get(self, rank: int, prefix: str) -> List[Tuple[str, object]]:
        link = self.link(rank)
        self.kernel.sleep(link.delay(len(prefix)))
        self._check_up(prefix)
        self.ops["dir_get"] += 1
        items = [(k, self._store[k])
                 for k in sorted(self._store) if k.startswith(prefix)]
        payload = sum(self._nbytes(v) for _k, v in items) or 1
        self.kernel.sleep(link.delay(payload))
        return items


class _StrKV:
    """Minimal legacy client surface (string values only)."""

    def __init__(self, fabric: SimFabric, rank: int):
        self._fabric = fabric
        self.rank = rank

    def key_value_set(self, key: str, value: str) -> None:
        self._fabric._put(self.rank, key, value)

    def blocking_key_value_get(self, key: str, timeout_ms: int):
        return self._fabric._blocking_get(self.rank, key, timeout_ms)

    def key_value_try_get(self, key: str):
        return self._fabric._try_get(self.rank, key)

    def key_value_delete(self, key: str) -> None:
        self._fabric._delete(self.rank, key)


class _DirKV(_StrKV):
    """Adds the directory read (amortized stall inspector, single-RPC
    request gathers, drain-notice scans)."""

    def key_value_dir_get(self, prefix: str):
        return self._fabric._dir_get(self.rank, prefix)


class _BytesKV(_DirKV):
    """Adds the raw-bytes triple (KVTransport's base64-free path)."""

    def key_value_set_bytes(self, key: str, value: bytes) -> None:
        self._fabric._put(self.rank, key, bytes(value))

    def blocking_key_value_get_bytes(self, key: str, timeout_ms: int):
        return self._fabric._blocking_get(self.rank, key, timeout_ms)

    def key_value_dir_get_bytes(self, prefix: str):
        return self._fabric._dir_get(self.rank, prefix)
