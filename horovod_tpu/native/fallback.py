"""Pure-Python controller — the executable spec of the native core.

Implements exactly the protocol of native/src/controller.cc (same wire
bytes via :mod:`horovod_tpu.native.wire`, same ordering, fusion, cache
and stall semantics) for environments without a C++ toolchain, and as a
cross-check in tests (test_native.py runs both and asserts byte-level
agreement).  Parity anchors as in controller.h.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Dict, List, Optional, Sequence, Set, Tuple

from . import wire


class _ResponseCache:
    """LRU keyed by signature; mutation only in apply order (see the
    consistency argument in native/src/controller.h)."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        self._lru: "collections.OrderedDict[str, Tuple[int, wire.Entry]]" = (
            collections.OrderedDict()
        )  # sig -> (bit, entry); last = most recent
        self._by_bit: Dict[int, str] = {}
        self._free_bits: List[int] = []
        self._next_bit = 0

    def lookup(self, sig: str) -> int:
        item = self._lru.get(sig)
        return -1 if item is None else item[0]

    def put(self, sig: str, entry: wire.Entry) -> int:
        if sig in self._lru:
            bit = self._lru[sig][0]
            self._lru.move_to_end(sig)
            return bit
        if len(self._lru) >= self.capacity and self._lru:
            victim_sig, (victim_bit, _) = next(iter(self._lru.items()))
            del self._lru[victim_sig]
            del self._by_bit[victim_bit]
            # Match C++: freed bits are reused smallest-first.
            self._free_bits.append(victim_bit)
            self._free_bits.sort()
        if self._free_bits:
            bit = self._free_bits.pop(0)
        else:
            bit = self._next_bit
            self._next_bit += 1
        self._lru[sig] = (bit, entry)
        self._by_bit[bit] = sig
        return bit

    def entry_for_bit(self, bit: int) -> Optional[wire.Entry]:
        sig = self._by_bit.get(bit)
        return None if sig is None else self._lru[sig][1]

    def __len__(self):
        return len(self._lru)


class PyController:
    """Python twin of native Controller (controller.cc)."""

    def __init__(self, rank: int, size: int, fusion_threshold: int,
                 cache_capacity: int = 1024, stall_warn_s: float = 60.0,
                 stall_abort_s: float = 0.0, resync_every: int = 64):
        self.rank = rank
        self.size = size
        self.fusion_threshold = fusion_threshold
        self.stall_warn_s = stall_warn_s
        self.stall_abort_s = stall_abort_s
        self.resync_every = resync_every
        self._lock = threading.Lock()
        self._pending: List[wire.Entry] = []
        self._pending_names: Set[str] = set()
        self._in_flight: Dict[str, wire.Entry] = {}
        self._cache = _ResponseCache(cache_capacity)
        self._groups: Dict[int, int] = {}
        self._joined = False
        self._shutdown = False
        # steady-state bypass bookkeeping (see drain_requests)
        self._bypass_streak = 0
        self._resync_flush = False
        # per-rank monotonic burst-unit counter (drain side)
        self._burst_seq = 0
        # coordinator state.  Each key holds an OCCURRENCE QUEUE of
        # pending coordinations (front = oldest): with prediction on, a
        # rank's fire-and-forget confirmations can announce the same
        # tensor names for several bursts before the coordinator
        # catches up, so one-slot-per-key would collapse distinct
        # bursts into one release.
        self._message_table: Dict[str, List[dict]] = {}
        # (rank, burst_id) -> set of table keys forming that rank's
        # atomic burst unit; a ready op releases only when every unit
        # containing it is completely ready, and fusion runs per
        # connected unit component — never across a burst boundary.
        self._units: Dict[Tuple[int, int], Set[str]] = {}
        # monotonic creation index for deterministic component ordering
        self._pc_seq = 0
        self._joined_ranks: Set[int] = set()
        self._last_joined_rank = -1
        self._tuned_threshold = -1
        self._tuned_cycle_us = -1
        self._shutdown_ranks: Set[int] = set()
        self._resync_needed = False
        self._process_sets: Dict[int, List[int]] = {0: list(range(size))}
        # (name, skew_s, last_rank) per released op, drained by the
        # eager controller into the arrival-skew metrics (bounded:
        # oldest entries drop if nobody drains, e.g. native twin hosts
        # or manual tests).
        self._skew_events: List[Tuple[str, float, int]] = []

    # ---- rank-local side ----
    def enqueue(self, seq: int, name: str, op_type: int, red_op: int,
                dtype: int, shape: Sequence[int], process_set_id: int = 0,
                group_id: int = -1, root_rank: int = -1) -> bool:
        with self._lock:
            if name in self._pending_names or name in self._in_flight:
                return False
            e = wire.Entry(
                seq=seq, name=name, type=op_type, red_op=red_op,
                dtype=dtype, shape=tuple(shape),
                process_set_id=process_set_id, group_id=group_id,
                root_rank=root_rank,
            )
            e._enqueue_time = time.monotonic()  # type: ignore[attr-defined]
            self._pending.append(e)
            self._pending_names.add(name)
            return True

    def declare_group(self, group_id: int, size: int):
        self._groups[group_id] = size

    def register_process_set(self, psid: int, ranks: Sequence[int]):
        with self._lock:
            self._process_sets[psid] = sorted(ranks)

    def set_joined(self):
        self._joined = True

    def set_tuned(self, fusion_threshold: int, cycle_time_us: int):
        """Publish autotuned params in subsequent ResponseLists
        (coordinator only; parity: ParameterManager broadcast)."""
        with self._lock:
            self._tuned_threshold = int(fusion_threshold)
            self._tuned_cycle_us = int(cycle_time_us)

    def set_shutdown(self):
        """Announce this rank wants to shut down (next drain_requests)."""
        self._shutdown = True

    def set_resync_every(self, n: int):
        self.resync_every = int(n)

    def force_resync(self):
        """Rank-side re-anchor (mispredict recovery / quiesce rollback):
        the next drain_requests emits a full-entry resync frame —
        re-announcing in-flight ops — exactly as if the coordinator had
        requested cache_resync_needed."""
        with self._lock:
            self._resync_flush = True
            self._bypass_streak = 0

    def drain_requests(self, limit: int = 0) -> bytes:
        with self._lock:
            rl = wire.RequestList(rank=self.rank, joined=self._joined,
                                  shutdown=self._shutdown)
            resync_flush = self._resync_flush
            self._resync_flush = False
            # In-flight ops BEFORE this drain: re-announced on a
            # coordinator-requested resync (their first announcement
            # may have hit an unexpandable cache bit there).
            prior_in_flight = (
                sorted(self._in_flight.values(),
                       key=lambda e: self._table_key(e))
                if resync_flush else [])
            if limit > 0 and len(self._pending) > limit:
                # Atomic-burst cap: a caller that knows the steady burst
                # size drains exactly one burst even when the next one
                # already started queueing, so each wire unit maps to
                # exactly one application burst.
                entries = self._pending[:limit]
                del self._pending[:limit]
            else:
                entries = list(self._pending)
                self._pending.clear()
            bits: List[int] = []
            for e in entries:
                self._in_flight[e.name] = e
                self._pending_names.discard(e.name)
                bits.append(self._cache.lookup(e.signature()))
            all_hit = bool(entries) and all(b >= 0 for b in bits)
            # derive from the captured flags so the blob is internally
            # consistent even if set_joined/set_shutdown race the drain
            membership = rl.joined or rl.shutdown
            # Steady-state bypass: every drained op is a cache hit, no
            # membership change in flight, and the periodic full-resync
            # cycle is not due — the whole drain travels as one compact
            # bit vector (parity: the coordinated cache bitvector of
            # Controller::CoordinateCacheAndState).
            if (all_hit and not membership and not resync_flush
                    and self.resync_every > 0
                    and self._bypass_streak + 1 < self.resync_every):
                self._bypass_streak += 1
                rl.cache_bypass = True
                self._burst_seq += 1
                rl.burst_id = self._burst_seq
                rl.burst_len = len(bits)
                rl.cache_bits = wire.bits_to_words(sorted(bits))
                return wire.serialize_request_list(rl)
            self._bypass_streak = 0
            # Periodic resync (streak exhausted) or coordinator-forced
            # flush: full entries keep the coordinator's message table
            # and stall inspector authoritative even if caches diverge.
            resync = resync_flush or (all_hit and not membership)
            rl.cache_resync = resync
            if entries:
                # Fresh entries form one atomic burst unit; resync
                # re-announcements (prior_in_flight) ride behind them,
                # OUTSIDE the unit, and match idempotently at ingest.
                self._burst_seq += 1
                rl.burst_id = self._burst_seq
                rl.burst_len = len(entries)
            for e, bit in zip(entries, bits):
                rq = wire.Request(rank=self.rank)
                if bit >= 0:
                    rl.cache_hits.append(bit)
                if bit >= 0 and not resync:
                    rq.cached = True
                    rq.cache_bit = bit
                    rq.entry = wire.Entry(seq=e.seq, name=e.name)
                else:
                    rq.entry = e
                rl.requests.append(rq)
            for e in prior_in_flight:
                rl.requests.append(wire.Request(rank=self.rank, entry=e))
            return wire.serialize_request_list(rl)

    def apply_responses(self, blob: bytes) -> List[int]:
        rl = wire.parse_response_list(blob)
        finished: List[int] = []
        with self._lock:
            for rs in rl.responses:
                if rs.type not in (wire.BARRIER, wire.JOIN):
                    for i, name in enumerate(rs.tensor_names):
                        shape = (rs.tensor_shapes[i]
                                 if i < len(rs.tensor_shapes) else ())
                        e = wire.Entry(
                            name=name, type=rs.type, red_op=rs.red_op,
                            dtype=rs.dtype, shape=tuple(shape),
                            process_set_id=rs.process_set_id,
                            root_rank=rs.root_rank,
                        )
                        self._cache.put(e.signature(), e)
                for name in rs.tensor_names:
                    e = self._in_flight.pop(name, None)
                    if e is not None:
                        finished.append(e.seq)
            if rl.cache_resync_needed:
                # Coordinator failed to expand a bypass bit: next drain
                # is a full resync re-announcing whatever is still
                # outstanding (set AFTER the pops above, so completed
                # ops are not re-announced).
                self._resync_flush = True
            if rl.join_last_rank >= 0:
                self._joined = False
        return finished

    # ---- coordinator side ----
    @staticmethod
    def _table_key(e: wire.Entry) -> str:
        """Coordination scoped per process set (same tensor name may be
        pending in disjoint sets); must match Controller::TableKey —
        sorted() on these strings == std::map byte order."""
        return f"{e.process_set_id}\x01{e.name}"

    @staticmethod
    def _same_params(a: wire.Entry, b: wire.Entry) -> bool:
        """The cross-rank agreement surface: every member rank must
        submit identical (type, red_op, dtype, shape, root) or the
        collective would mis-fuse / corrupt data.  Exclusions, which
        must match Controller::SameParams exactly: group_id (rank-local
        bookkeeping; ranks may number groups differently) and DIM 0
        for allgather/alltoall (ragged gathers and variable splits are
        legitimately per-rank; trailing dims and rank-count must still
        agree — reference parity: controller.cc only checks
        non-first dimensions for allgather)."""
        if (a.type != b.type or a.red_op != b.red_op
                or a.dtype != b.dtype or a.root_rank != b.root_rank):
            return False
        sa, sb = tuple(a.shape), tuple(b.shape)
        if a.type in (wire.ALLGATHER, wire.ALLTOALL):
            return len(sa) == len(sb) and sa[1:] == sb[1:]
        return sa == sb

    @staticmethod
    def _entry_desc(e: wire.Entry) -> str:
        """Human-readable submission summary for mismatch diagnostics;
        must match Controller::EntryDesc byte-for-byte."""
        dims = ",".join(str(int(d)) for d in e.shape)
        return (f"op={e.type} red_op={e.red_op} dtype={e.dtype} "
                f"shape=[{dims}] root_rank={e.root_rank}")

    def _table_add(self, e: wire.Entry, rank: int, now: float,
                   occurrence: bool = False) -> Tuple[str, dict]:
        """Record one rank's announcement in the message table,
        tracking conflicting submissions per rank (must match
        Controller::TableAdd).

        ``occurrence=True`` (burst-unit announcements) treats the
        announcement as a NEW occurrence relative to any this rank
        already announced, so back-to-back confirmed bursts of the same
        tensor names queue instead of collapsing into one release.
        ``occurrence=False`` (unit-less frames and resync
        re-announcements past ``burst_len``) matches idempotently: a
        rank re-announcing an in-flight op lands on the occurrence it
        already joined, never opening a duplicate."""
        key = self._table_key(e)
        q = self._message_table.get(key)
        if q is None:
            q = self._message_table[key] = []
        pc: Optional[dict] = None
        if occurrence:
            for cand in q:
                if rank not in cand["ranks"]:
                    pc = cand
                    break
        else:
            for cand in q:
                if rank in cand["ranks"]:
                    pc = cand
                    break
            if pc is None and q:
                pc = q[0]
        if pc is None:
            # "arrived" (first announcement time per rank) is local
            # bookkeeping for arrival-skew attribution — not part of
            # the C++ parity surface.
            pc = {
                "entry": e, "ranks": {rank}, "first_seen": now,
                "first_rank": rank, "mismatch": {},
                "arrived": {rank: now},
                "units": set(), "predicted": set(),
                "seq": self._pc_seq,
            }
            self._pc_seq += 1
            q.append(pc)
            return key, pc
        pc["ranks"].add(rank)
        pc["arrived"].setdefault(rank, now)
        if (rank != pc["first_rank"] and rank not in pc["mismatch"]
                and not self._same_params(e, pc["entry"])):
            pc["mismatch"][rank] = e
        return key, pc

    def ingest(self, blob: bytes):
        rl = wire.parse_request_list(blob)
        now = time.monotonic()
        with self._lock:
            if rl.joined and rl.rank not in self._joined_ranks:
                # Temporally-last joiner (parity: hvd.join() return value).
                self._joined_ranks.add(rl.rank)
                self._last_joined_rank = rl.rank
            if rl.shutdown:
                self._shutdown_ranks.add(rl.rank)
            ref = ((rl.rank, rl.burst_id)
                   if rl.burst_id > 0 and rl.burst_len > 0 else None)
            unit_keys: Set[str] = set()
            if rl.cache_bypass:
                # Expand the rank's cache-bit vector through the
                # coordinator's own (identical) cache.  An unknown bit
                # means the caches diverged (e.g. elastic generations
                # mixing): request a full resync from every rank.
                for idx, bit in enumerate(wire.words_to_bits(rl.cache_bits)):
                    cached = self._cache.entry_for_bit(bit)
                    if cached is None:
                        self._resync_needed = True
                        continue
                    e = wire.Entry(**{**cached.__dict__, "seq": 0})
                    in_unit = ref is not None and idx < rl.burst_len
                    key, pc = self._table_add(e, rl.rank, now,
                                              occurrence=in_unit)
                    if in_unit:
                        pc["units"].add(ref)
                        unit_keys.add(key)
                        if rl.predicted:
                            pc["predicted"].add(rl.rank)
                if ref is not None and unit_keys:
                    self._units[ref] = unit_keys
                return
            for idx, rq in enumerate(rl.requests):
                e = rq.entry
                if rq.cached:
                    cached = self._cache.entry_for_bit(rq.cache_bit)
                    if cached is not None:
                        e = wire.Entry(**{**cached.__dict__, "seq": rq.entry.seq})
                in_unit = ref is not None and idx < rl.burst_len
                key, pc = self._table_add(e, rl.rank, now,
                                          occurrence=in_unit)
                if in_unit:
                    pc["units"].add(ref)
                    unit_keys.add(key)
                    if rl.predicted:
                        pc["predicted"].add(rl.rank)
            if ref is not None and unit_keys:
                self._units[ref] = unit_keys

    def _required_ranks(self, psid: int) -> int:
        ranks = self._process_sets.get(psid)
        return self.size if ranks is None else len(ranks)

    def _member_ranks(self, psid: int) -> List[int]:
        return self._process_sets.get(psid, list(range(self.size)))

    def _present_count(self, pc: dict) -> int:
        """Joined ranks count as implicitly ready (parity: EnqueueJoin /
        JoinOp — joined ranks zero-contribute, so the rest never stall)."""
        return sum(
            1 for r in self._member_ranks(pc["entry"].process_set_id)
            if r in pc["ranks"] or r in self._joined_ranks
        )

    def _release_front(self, key: str, pc: dict):
        """Pop a released coordination off its occurrence queue and drop
        its key from every burst unit that referenced it (so an
        error-released member doesn't deadlock the rest of its unit)."""
        q = self._message_table.get(key)
        if q and q[0] is pc:
            q.pop(0)
            if not q:
                del self._message_table[key]
        for ref in pc["units"]:
            s = self._units.get(ref)
            if s is not None:
                s.discard(key)
                if not s:
                    del self._units[ref]

    def compute_responses(self) -> bytes:
        with self._lock:
            out = wire.ResponseList(
                tuned_fusion_threshold=self._tuned_threshold,
                tuned_cycle_time_us=self._tuned_cycle_us,
            )
            out.cache_resync_needed = self._resync_needed
            self._resync_needed = False
            # deterministic (psid, name) order == std::map iteration;
            # only the FRONT occurrence of each key is eligible, so
            # per-key release order always matches announcement order.
            fronts = {key: q[0]
                      for key, q in self._message_table.items() if q}
            ready = [
                key for key in sorted(fronts)
                if self._present_count(fronts[key])
                >= self._required_ranks(fronts[key]["entry"].process_set_id)
            ]
            group_counts: Dict[int, int] = collections.Counter(
                fronts[n]["entry"].group_id
                for n in ready
                if fronts[n]["entry"].group_id >= 0
            )
            candidates: Dict[str, dict] = {}
            mismatch_keys: List[str] = []
            for key in ready:
                pc = fronts[key]
                e = pc["entry"]
                if e.group_id >= 0:
                    want = self._groups.get(e.group_id, -1)
                    if want > 0 and group_counts[e.group_id] < want:
                        continue
                if pc["mismatch"]:
                    mismatch_keys.append(key)
                else:
                    candidates[key] = pc
            # Atomic-unit admission: a ready op releases only when every
            # burst unit containing it is COMPLETELY ready, and the
            # transitive closure over shared unit refs partitions the
            # releasable work into connected components.  Fusion runs
            # per component (fresh open-group state each time), so the
            # coordinator can never form a fusion group across a burst
            # boundary — a peer's split burst holds its whole component
            # back instead of diverging the fused groupings that
            # predict_responses() reconstructed locally.
            components: List[Tuple[int, List[str]]] = []
            assigned: Set[str] = set()
            for key in sorted(candidates):
                if key in assigned:
                    continue
                comp: Set[str] = set()
                ok = True
                stack = [key]
                while stack:
                    k = stack.pop()
                    if k in comp:
                        continue
                    pc = candidates.get(k)
                    if pc is None:
                        ok = False
                        break
                    comp.add(k)
                    for ref in pc["units"]:
                        for k2 in self._units.get(ref, ()):
                            if (k2 not in candidates
                                    or ref not in candidates[k2]["units"]):
                                ok = False
                                break
                            if k2 not in comp:
                                stack.append(k2)
                        if not ok:
                            break
                    if not ok:
                        break
                if not ok:
                    continue  # a unit is split-pending: hold the component
                assigned |= comp
                components.append(
                    (min(candidates[k]["seq"] for k in comp), sorted(comp)))
            # Mismatch errors bypass unit gating (fail fast; the forced
            # resync re-anchors the survivors) as singleton components.
            for key in mismatch_keys:
                components.append((fronts[key]["seq"], [key]))
            # Creation order == per-rank announcement order on every
            # stream, so component emission order matches every
            # predictor's confirmation FIFO.
            components.sort()
            emitted: List[wire.Response] = []
            for _, comp_keys in components:
                responses: List[wire.Response] = []
                suppress = True
                for key in comp_keys:
                    pc = fronts[key]
                    e = pc["entry"]
                    rs = wire.Response(
                        type=e.type, red_op=e.red_op, dtype=e.dtype,
                        process_set_id=e.process_set_id,
                        root_rank=e.root_rank,
                        tensor_names=[e.name],
                        tensor_shapes=[tuple(e.shape)],
                        total_bytes=e.nbytes,
                    )
                    if pc["mismatch"]:
                        # Cross-rank disagreement: fail LOUDLY on every
                        # member rank, naming each offender and what it
                        # submitted (parity: the reference controller's
                        # "Mismatched ..." error responses; text must
                        # match Controller::BuildResponseList
                        # byte-for-byte).  The error broadcast also
                        # forces a full cache resync, re-anchoring the
                        # bypass AND predict planes.
                        parts = [f"rank {pc['first_rank']} submitted "
                                 f"{self._entry_desc(e)}"]
                        for r in sorted(pc["mismatch"]):
                            parts.append(
                                f"rank {r} submitted "
                                f"{self._entry_desc(pc['mismatch'][r])}")
                        rs.error = (f"cross-rank tensor mismatch for "
                                    f"'{e.name}': " + "; ".join(parts))
                        out.cache_resync_needed = True
                        suppress = False
                        responses.append(rs)
                        self._release_front(key, pc)
                        continue
                    # Zero substitution from joined ranks is only sound
                    # for additive semantics (must match Controller's
                    # C++ texts byte-for-byte for the cross-check tests).
                    used_joined = any(
                        r not in pc["ranks"] and r in self._joined_ranks
                        for r in self._member_ranks(e.process_set_id)
                    )
                    if used_joined:
                        if (e.type == wire.BROADCAST and e.root_rank >= 0
                                and e.root_rank not in pc["ranks"]
                                and e.root_rank in self._joined_ranks):
                            rs.error = (f"broadcast root rank "
                                        f"{e.root_rank} has joined")
                        elif (e.type in (wire.ALLREDUCE, wire.REDUCESCATTER)
                              and e.red_op in (wire.RED_MIN, wire.RED_MAX,
                                               wire.RED_PRODUCT,
                                               wire.RED_ADASUM)):
                            rs.error = (f"reduction op {e.red_op} does "
                                        "not support joined-rank zero "
                                        "contribution")
                        elif (e.type in (wire.ALLREDUCE, wire.REDUCESCATTER)
                              and e.dtype == wire.DTYPE_IDS["int8"]):
                            rs.error = ("int8 wire format does not support "
                                        "joined-rank zero contribution")
                    arrived = pc.get("arrived") or {}
                    if len(arrived) >= 2:
                        last_rank = max(arrived, key=arrived.get)
                        skew = max(arrived.values()) - min(arrived.values())
                        self._skew_events.append((e.name, skew, last_rank))
                        if len(self._skew_events) > 1024:
                            del self._skew_events[:-1024]
                    members = self._member_ranks(e.process_set_id)
                    if (rs.error or used_joined
                            or pc["predicted"] != set(members)):
                        suppress = False
                    responses.append(rs)
                    self._release_front(key, pc)
                fused = self._fuse(responses)
                if suppress and fused and not any(r.error for r in fused):
                    # Every member rank announced this whole component
                    # as a PREDICTED confirmation: each already executed
                    # the identical locally predicted schedule, so emit
                    # only the hash of the would-be response bytes —
                    # the response-side half of killing the round trip.
                    blob = wire.serialize_response_list(
                        wire.ResponseList(responses=fused))
                    out.confirm_hashes.append(wire.fnv1a64(blob))
                else:
                    emitted.extend(fused)
            out.responses = emitted
            # pending tensors that can never complete because a REQUIRED
            # rank announced shutdown fail promptly (must match
            # Controller::BuildResponseList step 3b byte-for-byte)
            if self._shutdown_ranks:
                for key in sorted(self._message_table):
                    q = self._message_table.get(key)
                    if not q:
                        continue
                    pc = q[0]
                    e = pc["entry"]
                    dead_rank = -1
                    for r in self._member_ranks(e.process_set_id):
                        if (r not in pc["ranks"]
                                and r not in self._joined_ranks
                                and r in self._shutdown_ranks):
                            dead_rank = r
                            break
                    if dead_rank < 0:
                        continue
                    out.responses.append(wire.Response(
                        type=e.type, red_op=e.red_op, dtype=e.dtype,
                        process_set_id=e.process_set_id,
                        root_rank=e.root_rank,
                        tensor_names=[e.name],
                        tensor_shapes=[tuple(e.shape)],
                        error=f"rank {dead_rank} has shut down",
                    ))
                    self._release_front(key, pc)
            if len(self._joined_ranks) >= self.size and self.size > 0:
                out.join_last_rank = self._last_joined_rank
                self._joined_ranks.clear()
                self._last_joined_rank = -1
            # global quiesce only when EVERY rank announced shutdown
            # (must match Controller::BuildResponseList)
            if len(self._shutdown_ranks) >= self.size and self.size > 0:
                out.shutdown = True
            return wire.serialize_response_list(out)

    def _fuse(self, responses: List[wire.Response]) -> List[wire.Response]:
        """Compatibility-GROUP fusion: every fusible response merges
        into the open group for its (type, red_op, dtype, process set)
        key — not just adjacent ones — so an unrelated response
        (another process set's release landing in the same compute)
        cannot split an otherwise-stable fusion group.  That
        order-independence is what makes steady-state schedule
        prediction sound (see predict_responses).  Output order is
        group-opening order; a group that would exceed the fusion
        threshold closes and a new one opens at the end."""
        fused: List[wire.Response] = []
        open_group: Dict[Tuple[int, int, int, int], int] = {}
        for r in responses:
            can_fuse = r.type in (wire.ALLREDUCE, wire.ADASUM) and not r.error
            if can_fuse:
                key = (r.type, r.red_op, r.dtype, r.process_set_id)
                gi = open_group.get(key)
                if (gi is not None
                        and fused[gi].total_bytes + r.total_bytes
                        <= self.fusion_threshold):
                    g = fused[gi]
                    g.tensor_names.extend(r.tensor_names)
                    g.tensor_shapes.extend(r.tensor_shapes)
                    g.total_bytes += r.total_bytes
                    continue
                open_group[key] = len(fused)
            fused.append(r)
        return fused

    # ---- steady-state schedule prediction ----
    def predict_responses(self, bits: Sequence[int]) -> Optional[bytes]:
        """The ResponseList the coordinator WILL emit for a pure
        bypass cycle carrying exactly ``bits`` — a deterministic
        function of the (replicated) response cache and the fusion
        threshold, so a rank in steady state can execute without
        waiting for the round trip.  Returns None when any bit is
        unknown.  Only sound under the caller's gating (never-tuned
        threshold, no interleaved unscheduled work, additive ops);
        see eager/controller.py."""
        with self._lock:
            entries = []
            for b in bits:
                e = self._cache.entry_for_bit(b)
                if e is None:
                    return None
                entries.append(e)
            entries.sort(key=self._table_key)
            out = wire.ResponseList()
            out.responses = self._fuse([
                wire.Response(
                    type=e.type, red_op=e.red_op, dtype=e.dtype,
                    process_set_id=e.process_set_id,
                    root_rank=e.root_rank,
                    tensor_names=[e.name],
                    tensor_shapes=[tuple(e.shape)],
                    total_bytes=e.nbytes,
                ) for e in entries
            ])
            return wire.serialize_response_list(out)

    def finish(self, names: Sequence[str]) -> List[int]:
        """Eagerly retire in-flight entries executed from a PREDICTED
        schedule, so re-enqueues of the same tensor name don't trip
        the duplicate-name guard before the real (matching) response
        streams in."""
        with self._lock:
            out = []
            for n in names:
                e = self._in_flight.pop(n, None)
                if e is not None:
                    out.append(e.seq)
            return out

    # ---- introspection ----
    @property
    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)

    @property
    def pending_bytes(self) -> int:
        with self._lock:
            return sum(e.nbytes for e in self._pending)

    @property
    def cache_size(self) -> int:
        return len(self._cache)

    def set_fusion_threshold(self, nbytes: int):
        self.fusion_threshold = nbytes

    def take_arrival_skew(self) -> List[Tuple[str, float, int]]:
        """Drain (name, skew_s, last_rank) events recorded when ops
        released from the message table (coordinator side only; the
        eager controller feeds them into the arrival-skew metrics).
        The native twin has no equivalent — callers getattr-guard."""
        with self._lock:
            out, self._skew_events = self._skew_events, []
            return out

    def pending_summary(self, limit: int = 32) -> List[dict]:
        """Coordinator's pending-coordination table for the /debug
        endpoint: which ops are waiting and on whom."""
        now = time.monotonic()
        out: List[dict] = []
        with self._lock:
            for key in sorted(self._message_table):
                if len(out) >= limit:
                    break
                q = self._message_table[key]
                if not q:
                    continue
                pc = q[0]
                members = self._member_ranks(pc["entry"].process_set_id)
                present = [r for r in members
                           if r in pc["ranks"] or r in self._joined_ranks]
                out.append({
                    "name": pc["entry"].name,
                    "process_set_id": pc["entry"].process_set_id,
                    "waiting_s": round(now - pc["first_seen"], 6),
                    "ranks_present": present,
                    "ranks_missing": [r for r in members
                                      if r not in present],
                })
        return out

    def check_stalls(self) -> List[dict]:
        now = time.monotonic()
        out = []
        with self._lock:
            for key in sorted(self._message_table):
                q = self._message_table[key]
                if not q:
                    continue
                pc = q[0]
                waited = now - pc["first_seen"]
                if waited < self.stall_warn_s:
                    continue
                members = self._member_ranks(pc["entry"].process_set_id)
                present = [r for r in members
                           if r in pc["ranks"] or r in self._joined_ranks]
                out.append({
                    "name": pc["entry"].name,
                    "waiting_s": waited,
                    "present": present,
                    "missing": [r for r in members if r not in present],
                })
        return out

    def close(self):
        pass
