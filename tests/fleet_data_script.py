"""Fleet variant of elastic_data_script: same exactly-once training
loop, but DELIVER lines are appended to the per-job file named by
``FLEET_DELIVER_LOG`` instead of stdout — two jobs sharing one arbiter
(and one test process's stdout) must not interleave their accounting.
Single short O_APPEND writes keep concurrent ranks line-atomic.
"""

import os
import time

import numpy as np

import horovod_tpu as hvt
import horovod_tpu.elastic as elastic
from horovod_tpu.data import ArraySource, ElasticDataLoader


def main():
    hvt.init()
    epochs = int(os.environ.get("ELASTIC_EPOCHS", "2"))
    sleep_s = float(os.environ.get("EPOCH_SLEEP", "0.3"))
    n = int(os.environ.get("DATA_SAMPLES", "48"))
    batch = int(os.environ.get("DATA_BATCH", "4"))
    log_path = os.environ["FLEET_DELIVER_LOG"]
    x = np.arange(n, dtype=np.float32).reshape(n, 1)
    loader = ElasticDataLoader(
        ArraySource({"x": x}), batch_size=batch, seed=7,
        device_put=False)
    state = elastic.ObjectState(data=loader.state, total=0.0)

    def deliver(line):
        with open(log_path, "a") as f:
            f.write(line + "\n")

    @elastic.run
    def train(state):
        import jax.numpy as jnp

        gen = os.environ.get("HVTPU_ELASTIC_GENERATION", "0")
        while loader.state.epoch < epochs:
            epoch = loader.state.epoch
            for b in loader:
                idx = sorted(int(v) for v in np.asarray(b["x"]).ravel())
                out = hvt.allreduce(jnp.ones(2), op=hvt.Sum)
                state.total += float(out[0])
                deliver(
                    f"DELIVER rank={hvt.rank()} size={hvt.size()} "
                    f"gen={gen} epoch={epoch} idx={idx}")
                time.sleep(sleep_s)
                state.commit()
        if hvt.rank() == 0:
            deliver(f"DONE size={hvt.size()} epoch={loader.state.epoch}")

    train(state)
    loader.close()


if __name__ == "__main__":
    main()
