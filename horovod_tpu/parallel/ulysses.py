"""Ulysses (DeepSpeed-style) sequence parallelism via all-to-all.

Absent from the reference (SURVEY.md §5.7), whose ``hvd.alltoall``
(horovod/common/operations.cc ``EnqueueTensorAlltoall``) is exactly the
primitive Ulysses is built from — here expressed as ``lax.all_to_all``
inside shard_map, which XLA lowers to a single ICI all-to-all.

Layout transform: activations arrive sequence-sharded
``[B, T/S, H, D]``; the first all-to-all reshards to head-sharded
``[B, T, H/S, D]`` so each device runs *full-sequence* attention over
its head subset (any kernel — including flash/splash — works
unchanged); the second all-to-all reshards back.  Exact attention, two
collectives, no per-block recurrence — the right trade when
``H >= ring size`` and sequence blocks are small enough to gather.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax


def seq_to_heads(x: jax.Array, axis_name: str) -> jax.Array:
    """[B, T/S, H, D] -> [B, T, H/S, D] (inside shard_map)."""
    # all_to_all: split the head axis (2) across the group, concat the
    # sequence axis (1) in peer (= sequence-block) order.
    return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)


def heads_to_seq(x: jax.Array, axis_name: str) -> jax.Array:
    """[B, T, H/S, D] -> [B, T/S, H, D] (inverse of seq_to_heads)."""
    return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def _default_attention(q, k, v, *, causal, scale):
    # q,k,v: [B, T, h, D] -> [B, T, h, D]; fp32 softmax accumulation.
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        t = q.shape[1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        scores = jnp.where(mask[None, None], scores, -1e30)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    attn_fn: Optional[Callable] = None,
) -> jax.Array:
    """Sequence-parallel exact attention via two all-to-alls.

    Args:
      q, k, v: local shards ``[B, T_local, H, D]`` — note layout
        (sequence dim 1, heads dim 2), matching transformer activation
        layout.  ``H`` must be divisible by the axis size.
      axis_name: mesh axis carrying the sequence shards.
      attn_fn: optional full-sequence attention kernel
        ``(q, k, v, causal=..., scale=...) -> out`` with ``[B, T, h, D]``
        layout; defaults to a fused-softmax reference implementation
        (swap in a Pallas flash kernel on TPU).

    Returns:
      Local output ``[B, T_local, H, D]``.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if attn_fn is None:
        attn_fn = _default_attention
    s = lax.axis_size(axis_name)
    if q.shape[2] % s != 0:
        raise ValueError(
            f"num heads {q.shape[2]} not divisible by axis {axis_name!r}"
            f" size {s}"
        )
    qh = seq_to_heads(q, axis_name)
    kh = seq_to_heads(k, axis_name)
    vh = seq_to_heads(v, axis_name)
    out = attn_fn(qh, kh, vh, causal=causal, scale=scale)
    return heads_to_seq(out, axis_name)
