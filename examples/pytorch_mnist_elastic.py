"""Elastic torch training example — the horovod_tpu analog of the
reference's examples/elastic/pytorch/pytorch_mnist_elastic.py:
``hvd.elastic.run`` with ``TorchState`` (model + optimizer); commits
survive worker loss and world resizes.

Input rides the framework-agnostic :class:`ElasticDataLoader` instead
of the reference's ``ElasticSampler`` + ``record_batch`` bookkeeping:
the loader's ``(epoch, cursor, seed)`` state registers with
``TorchState`` like any other ``state_dict`` handle, so a resize
re-splits the unconsumed epoch remainder across the new world and a
preemption resumes from the drain-committed cursor — no samples
repeated or dropped, and no per-batch ``record_batch`` calls in the
loop.

Run:
  hvtpurun --host-discovery-script ./discover.sh --min-np 2 \
      --cpu-devices 1 python examples/pytorch_mnist_elastic.py
where discover.sh prints e.g. "localhost:4".
"""

import numpy as np
import torch
import torch.nn as nn
import torch.nn.functional as F

import horovod_tpu.torch as hvd
from horovod_tpu.data import ArraySource, ElasticDataLoader


class Net(nn.Module):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(784, 128)
        self.fc2 = nn.Linear(128, 10)

    def forward(self, x):
        x = F.relu(self.fc1(x))
        return F.log_softmax(self.fc2(x), dim=1)


def main():
    hvd.init()
    torch.manual_seed(42)

    rng = np.random.RandomState(0)
    x = rng.rand(1024, 784).astype(np.float32)
    w = rng.randn(784, 10).astype(np.float32)
    y = (x @ w).argmax(axis=1).astype(np.int64)

    model = Net()
    # elastic: lr scales with the CURRENT size; rebuilt on reset
    opt = torch.optim.SGD(model.parameters(), lr=0.05 * hvd.size())
    opt = hvd.DistributedOptimizer(
        opt, named_parameters=model.named_parameters())

    # device_put=False: torch consumes host numpy batches directly
    loader = ElasticDataLoader(
        ArraySource({"x": x, "y": y}), batch_size=64, seed=42,
        device_put=False)
    state = hvd.elastic.TorchState(
        model=model, optimizer=opt, data=loader.state)

    def on_reset():
        for g in opt.param_groups:
            g["lr"] = 0.05 * hvd.size()

    state.register_reset_callbacks([on_reset])
    epochs = 6

    @hvd.elastic.run
    def train(state):
        while loader.state.epoch < epochs:
            epoch = loader.state.epoch
            total, steps = 0.0, 0
            for batch in loader:  # resumes mid-epoch after a resize
                bx = torch.from_numpy(np.ascontiguousarray(batch["x"]))
                by = torch.from_numpy(np.ascontiguousarray(batch["y"]))
                opt.zero_grad()
                loss = F.nll_loss(model(bx), by)
                loss.backward()
                opt.step()
                total += float(loss)
                steps += 1
            avg = hvd.allreduce(
                torch.tensor(total / max(steps, 1)), op=hvd.Average)
            if hvd.rank() == 0:
                print(f"epoch {epoch}: loss={float(avg):.4f} "
                      f"(world size {hvd.size()})", flush=True)
            state.commit()

    train(state)
    loader.close()
    if hvd.rank() == 0:
        print(f"done; ranks consistent ({hvd.size()} ranks)",
              flush=True)


if __name__ == "__main__":
    main()
