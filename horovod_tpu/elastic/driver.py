"""Launcher-side elastic driver.

Parity surface: ``horovod/runner/elastic/driver.py`` (``ElasticDriver``)
+ ``horovod/runner/launch.py`` (``_run_elastic``): poll a host-discovery
script on an interval, keep min_np ≤ world ≤ max_np workers running,
notify workers on membership change, blacklist repeatedly-failing
hosts, and restart the job from committed state.

TPU-native mapping (restart-based elasticity, see elastic/state.py):
instead of the reference's in-process Gloo re-rendezvous, the driver
relaunches the whole worker set on a fresh coordination-service port;
workers resume from the durable commit (``HVTPU_ELASTIC_STATE_DIR``).
Driver→worker "hosts updated" notification is SIGUSR1 (the analog of
``WorkerNotificationClient``); workers exit with ``RESET_EXIT_CODE`` at
the next commit boundary and the driver rebuilds the world.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import tempfile
from typing import Dict, List, Optional

from ..obs import flight
from ..obs import metrics as obs_metrics
from ..runner import hosts as hosts_mod
from ..runner import safe_shell_exec
from ..runner.launch import (
    _default_coordinator_addr,
    build_ssh_command,
    build_worker_env,
    find_free_port,
    ssh_options_from_args,
    uniform_local_size,
)
from ..core import clock
from ..core.preempt import DRAIN_EXIT_CODE, configured_signal
from .discovery import HostDiscoveryScript, HostManager
from .worker import FENCE_EXIT_CODE, RESET_EXIT_CODE

# A host is blacklisted after this many consecutive crashed (not
# reset-requested) workers (parity: registration.py blacklist policy).
# Blacklisting is a COOLDOWN, not a life sentence: see
# discovery.HostManager (exponential re-admission) — upstream Horovod
# never re-admits a blacklisted host; we probe it again after the
# cooldown and decay strikes on successful incarnations.
BLACKLIST_THRESHOLD = 3

# Driver-side telemetry (obs/metrics.py): the driver process keeps its
# own registry — workers each publish theirs (HVTPU_METRICS_PORT; the
# driver deliberately does not bind a port, it would collide with the
# rank-0 worker on the same host).
_M_WORKERS = obs_metrics.gauge(
    "hvtpu_elastic_workers",
    "Live worker (rank) count of this incarnation's world as seen by "
    "this rank.")
_M_RESTARTS = obs_metrics.counter(
    "hvtpu_elastic_restarts_total",
    "Worker-set relaunches performed by the elastic driver.")
_M_RENDEZVOUS_S = obs_metrics.histogram(
    "hvtpu_elastic_rendezvous_seconds",
    "Driver-side rendezvous: discovery reaching min_np through a "
    "launched worker set, per incarnation.")
_M_BLACKLISTED = obs_metrics.gauge(
    "hvtpu_elastic_blacklisted_hosts",
    "Hosts currently sidelined by the cooldown blacklist.")
_M_BUDGET_LEFT = obs_metrics.gauge(
    "hvtpu_elastic_restart_budget_remaining",
    "Relaunches left before the driver declares the workload "
    "crash-looping and fails fast (-1 = unlimited).")
_M_DRAINS = obs_metrics.counter(
    "hvtpu_elastic_drains_total",
    "Planned departures (DRAIN_EXIT_CODE exits after a graceful drain, "
    "core/preempt.py) the driver resized around WITHOUT charging the "
    "restart budget or a blacklist strike.")

_TERM_CODES = (-signal.SIGTERM, 128 + signal.SIGTERM)
# SIGUSR1 arriving before the worker installed its handler kills the
# process with the default disposition; classify that as a reset
# request, not a crash, so healthy hosts don't collect strikes.
_USR1_CODES = (-signal.SIGUSR1, 128 + signal.SIGUSR1)


class ElasticDriver:
    """One elastic job: discovery loop + worker lifecycle + restarts."""

    def __init__(
        self,
        command: List[str],
        discovery: HostDiscoveryScript,
        min_np: int,
        max_np: Optional[int] = None,
        discovery_interval: float = 1.0,
        elastic_timeout: float = 600.0,
        args: Optional[argparse.Namespace] = None,
        state_dir: Optional[str] = None,
        verbose: bool = False,
        max_restarts: int = -1,
        restart_window: float = 0.0,
        blacklist_cooldown: Optional[float] = None,
        drain_grace: Optional[float] = None,
        notice_dir: Optional[str] = None,
        extra_env: Optional[Dict[str, str]] = None,
    ):
        self.command = command
        # per-job environment overlay (fleet: the JobSpec's env block)
        self.extra_env = dict(extra_env or {})
        self.hosts = HostManager(discovery,
                                 cooldown_base_s=blacklist_cooldown)
        self.min_np = min_np
        self.max_np = max_np
        self.interval = discovery_interval
        self.elastic_timeout = elastic_timeout
        self.args = args
        # restart budget: total relaunches allowed (-1 = unlimited);
        # with restart_window > 0 only relaunches inside the trailing
        # window count, so a long job survives occasional preemptions
        # while a tight crash loop still trips the budget.
        self.max_restarts = max_restarts
        self.restart_window = restart_window
        self._restart_times: List[float] = []
        self._last_crash_summary = ""
        # drain grace: how long workers get to reach the coordinated
        # drain commit after the driver forwards a preemption notice
        # (SIGTERM to hvtpurun) — always applied BEFORE terminate()'s
        # SIGTERM/SIGKILL escalation, so the kill grace can never
        # undercut the drain grace.
        if drain_grace is None:
            drain_grace = float(
                os.environ.get("HVTPU_DRAIN_GRACE_SECONDS", "30")
                or 30)
        self.drain_grace = drain_grace
        self._drain_requested = False
        self._drain_forwarded = False
        # durable-commit location: explicit arg > caller's env (a user
        # pointing commits at a persistent/shared filesystem) > fresh
        # temp dir owned — and cleaned up on success — by this driver
        env_dir = os.environ.get("HVTPU_ELASTIC_STATE_DIR")
        self.state_dir = state_dir or env_dir or tempfile.mkdtemp(
            prefix="hvtpu_elastic_"
        )
        self._owns_state_dir = state_dir is None and env_dir is None
        self.verbose = verbose
        self._crash_counts: Dict[str, int] = {}
        # blacklist hints survive a driver restart (and therefore a
        # coordinator-loss relaunch cycle) via the elastic state dir —
        # without them a relaunched driver would happily re-elect the
        # host it just struck out as the new coordinator.
        self._hints_path = os.path.join(self.state_dir,
                                        "host_hints.json")
        hinted = self.hosts.load_hints(self._hints_path)
        if hinted and verbose:
            print(f"hvtpu.elastic.driver: restored blacklist hints "
                  f"for {hinted} host(s) from {self._hints_path}",
                  file=sys.stderr, flush=True)
        # coordinator address of the previous incarnation: a change
        # across relaunches IS a coordinator re-election.
        self._last_coordinator_addr: Optional[str] = None
        # world size of the last-launched incarnation; after a clean
        # run() this is the FINAL world (result collection filters
        # stale rank files from larger earlier incarnations with it)
        self.final_world_size: Optional[int] = None
        # incarnation counter: 0 for the first launch, +1 per
        # relaunch; workers use it to run reset callbacks after a
        # world reconfiguration (HVTPU_ELASTIC_GENERATION)
        self._generation = 0
        # fleet seams (horovod_tpu/fleet): notice_dir gives every rank
        # its own pollable preemption-notice file
        # (<notice_dir>/rank<N>), so an arbiter can drain a SUBSET of
        # ranks through the planned core/preempt.py path; listener is
        # an optional callable(event, info) told about launches and
        # incarnation ends SYNCHRONOUSLY on the driver thread — a
        # fleet runner flips the job's allocation view there, before
        # the next discovery poll can race it.
        self.notice_dir = notice_dir
        self.listener = None
        self.current_slots: List[hosts_mod.SlotInfo] = []
        self._workers: List[safe_shell_exec.WorkerProcess] = []

    def _log(self, msg: str):
        if self.verbose:
            print(f"hvtpu.elastic.driver: {msg}", file=sys.stderr,
                  flush=True)

    def _refresh_hosts(self) -> bool:
        """Poll discovery, swallowing transient script failures (a slow
        or briefly-failing discovery script must not kill a healthy
        job — the whole point of elasticity)."""
        try:
            return self.hosts.refresh()
        except Exception as e:  # noqa: BLE001 — includes TimeoutExpired
            self._log(f"discovery error (ignored): {e}")
            return False

    def _wait_for_min_hosts(self) -> bool:
        deadline = clock.monotonic() + self.elastic_timeout
        while clock.monotonic() < deadline:
            self._refresh_hosts()
            _M_BLACKLISTED.set(len(self.hosts.blacklisted_now()))
            if self.hosts.available_slots() >= self.min_np:
                return True
            if self.hosts.exhausted(self.min_np):
                # every discovered host is cooling down; wait out the
                # soonest re-admission when it fits the deadline,
                # otherwise fail fast instead of burning the timeout
                readmit = self.hosts.next_readmission_s()
                remaining = deadline - clock.monotonic()
                if readmit is None:
                    pass  # raced with an expiry: re-poll immediately
                elif readmit >= remaining:
                    self._log(
                        "all discovered hosts blacklisted and the "
                        f"soonest re-admission is {readmit:.0f}s away "
                        f"(> {remaining:.0f}s left); giving up")
                    return False
                else:
                    self._log(
                        "all discovered hosts blacklisted; probing "
                        f"again in {readmit:.0f}s")
                    clock.sleep(min(readmit + 0.05, remaining))
                continue
            clock.sleep(self.interval)
        return False

    def _elect_coordinator(self, slots: List[hosts_mod.SlotInfo]) -> str:
        """One coordinator address for the whole world (rank 0's host),
        exactly like the static launch path.  host_spec() already
        excludes cooling (blacklisted) hosts, so when the previous
        coordinator's host struck out, slots[0] — and therefore this
        address — lands on a SURVIVING host: that is the re-election."""
        coordinator_addr = _default_coordinator_addr(slots)
        if (self._last_coordinator_addr is not None
                and coordinator_addr != self._last_coordinator_addr):
            self._log(
                f"coordinator re-elected: {self._last_coordinator_addr}"
                f" -> {coordinator_addr} (generation "
                f"{self._generation - 1})")
            flight.note("coordinator_reelected",
                        old=self._last_coordinator_addr,
                        new=coordinator_addr,
                        generation=self._generation - 1)
        self._last_coordinator_addr = coordinator_addr
        return coordinator_addr

    def _spawn(self, slots: List[hosts_mod.SlotInfo], port: int
               ) -> List[safe_shell_exec.WorkerProcess]:
        base_env = dict(os.environ)
        base_env.update(self.extra_env)
        base_env["HVTPU_ELASTIC"] = "1"
        base_env["HVTPU_ELASTIC_STATE_DIR"] = self.state_dir
        base_env["HVTPU_ELASTIC_GENERATION"] = str(self._generation)
        self._generation += 1
        coordinator_addr = self._elect_coordinator(slots)
        workers = []
        import threading

        lock = threading.Lock()
        uniform = uniform_local_size(slots)
        for slot in slots:
            env = build_worker_env(
                base_env, slot, coordinator_addr, port, self.args,
                uniform_local=uniform,
            )
            if self.notice_dir:
                # per-rank notice file: the fleet arbiter touches
                # <notice_dir>/rank<N> to drain exactly rank N (a
                # job-wide --preempt-notice-file would drain everyone)
                env["HVTPU_PREEMPT_NOTICE_FILE"] = os.path.join(
                    self.notice_dir, f"rank{slot.rank}")
            if hosts_mod.is_local_host(slot.hostname):
                cmd = list(self.command)
            else:
                cmd = build_ssh_command(
                    slot.hostname, self.command, env, cwd=os.getcwd(),
                    **ssh_options_from_args(self.args),
                )
            workers.append(
                safe_shell_exec.WorkerProcess(
                    slot.rank, cmd, env, stdout_lock=lock
                )
            )
        return workers

    def _notify_listener(self, event: str, **info):
        """Tell the fleet listener (if any) about a lifecycle event,
        synchronously on the driver thread; listener errors are logged,
        never fatal to the job."""
        fn = self.listener
        if fn is None:
            return
        try:
            fn(event, info)
        except Exception as e:  # noqa: BLE001 — listener must not kill the job
            self._log(f"listener error on {event} (ignored): {e}")

    def signal_ranks(self, ranks, sig=signal.SIGTERM) -> int:
        """Send ``sig`` to the live workers of the CURRENT incarnation
        whose global rank is in ``ranks``; returns how many were
        signalled.  The fleet arbiter's drain-grace escalation uses
        this: a SIGTERM outside a forwarded drain is classified as a
        crash, so the expiry is charged to the restart budget — exactly
        the documented escalation semantics."""
        wanted = set(ranks)
        sent = 0
        for w in list(self._workers):
            if w.rank in wanted and w.poll() is None:
                try:
                    os.kill(w.proc.pid, sig)
                    sent += 1
                except ProcessLookupError:
                    pass
        return sent

    def _notify_hosts_updated(self, workers):
        self._log("hosts updated; signalling workers (SIGUSR1)")
        for w in workers:
            if w.poll() is None:
                try:
                    os.kill(w.proc.pid, signal.SIGUSR1)
                except ProcessLookupError:
                    pass

    def run(self) -> int:
        """Main loop (parity: ElasticDriver.start + _run_elastic)."""
        # Driver-level preemption: a SIGTERM to hvtpurun itself means
        # the WHOLE job is being reclaimed — flag it and let
        # _supervise forward a drain to the workers first (handler is
        # flag-only: no locks, no I/O).
        prev_term = None

        def _term_handler(signum, frame):
            self._drain_requested = True

        try:
            prev_term = signal.signal(signal.SIGTERM, _term_handler)
        except ValueError:
            pass  # non-main thread (tests): no driver-side drain
        try:
            return self._run_loop()
        finally:
            if prev_term is not None:
                try:
                    signal.signal(signal.SIGTERM, prev_term)
                except ValueError:
                    pass

    def _run_loop(self) -> int:
        _M_BUDGET_LEFT.set(self.max_restarts
                           if self.max_restarts >= 0 else -1)
        while True:
            t_rdv = clock.monotonic()
            if not self._wait_for_min_hosts():
                print(
                    f"hvtpu.elastic: fewer than min_np={self.min_np} "
                    f"slots available for {self.elastic_timeout}s; "
                    "giving up",
                    file=sys.stderr,
                )
                return 1
            np_now = self.hosts.available_slots()
            if self.max_np is not None:
                np_now = min(np_now, self.max_np)
            spec = self.hosts.host_spec()
            slots = hosts_mod.get_host_assignments(
                hosts_mod.parse_host_spec(spec), np_now
            )
            port = find_free_port()
            self._log(
                f"launching {np_now} workers on {spec} (port {port})"
            )
            self.final_world_size = np_now
            workers = self._spawn(slots, port)
            self.current_slots = slots
            self._workers = workers
            self._notify_listener(
                "launch", generation=self._generation - 1, size=np_now)
            _M_RENDEZVOUS_S.observe(clock.monotonic() - t_rdv)
            _M_WORKERS.set(np_now)
            outcome = self._supervise(workers, slots)
            self._notify_listener(
                "incarnation_end", generation=self._generation - 1,
                size=np_now, outcome=outcome)
            _M_WORKERS.set(0)
            if outcome == "done":
                if self._owns_state_dir:
                    import shutil

                    shutil.rmtree(self.state_dir, ignore_errors=True)
                return 0
            if outcome == "failed":
                return 1
            if outcome == "term":
                # whole-job preemption (driver got SIGTERM): workers
                # drained; propagate the conventional signal code
                return 128 + int(signal.SIGTERM)
            if outcome == "drain":
                # planned departure: resize immediately with NO
                # restart-budget charge — that budget exists to catch
                # crash loops, and a graceful drain is the opposite of
                # a crash.
                _M_DRAINS.inc()
                continue
            # outcome == "restart": loop around, re-discover, relaunch
            # — unless the restart budget says this workload is
            # crash-looping and relaunching forever helps nobody.
            _M_RESTARTS.inc()
            if flight.ACTIVE:
                flight.note("elastic_restart",
                            generation=self._generation - 1,
                            size=np_now)
            if not self._restart_budget_ok():
                # The job is dead for good: flush a driver-side black
                # box (ring may be empty — the snapshots matter here;
                # per-rank rings live in the workers' own postmortems).
                flight.dump_postmortem(
                    "restart_budget_exhausted",
                    generation=self._generation - 1,
                    crashes=self._last_crash_summary or "")
                return 1

    def _restart_budget_ok(self) -> bool:
        """Charge one relaunch against the budget; False (with a
        diagnostic) when it is exhausted."""
        now = clock.monotonic()
        self._restart_times.append(now)
        if self.restart_window > 0:
            self._restart_times = [
                t for t in self._restart_times
                if now - t <= self.restart_window]
        used = len(self._restart_times)
        if self.max_restarts < 0:
            _M_BUDGET_LEFT.set(-1)
            return True
        remaining = self.max_restarts - used
        _M_BUDGET_LEFT.set(max(remaining, 0))
        if remaining >= 0:
            return True
        window = (f" within {self.restart_window:.0f}s"
                  if self.restart_window > 0 else "")
        crashes = self._last_crash_summary or "no crash details recorded"
        print(
            f"hvtpu.elastic: restart budget exhausted — {used} "
            f"relaunches{window} > --max-restarts={self.max_restarts}; "
            "the workload is crash-looping, not recovering. "
            f"Last incarnation: {crashes}. Fix the failing rank (or "
            "raise --max-restarts / HVTPU_MAX_RESTARTS) and relaunch.",
            file=sys.stderr, flush=True,
        )
        return False

    def _forward_drain(self, workers):
        """Forward the preemption notice to every live worker (pid,
        not pgid: the worker's own handler starts the drain; its
        children follow at terminate())."""
        sig = configured_signal()
        self._log(
            f"driver preempted (SIGTERM); forwarding {sig.name} drain "
            f"to workers with {self.drain_grace:.0f}s grace before "
            "terminate escalation")
        for w in workers:
            if w.poll() is None:
                try:
                    os.kill(w.proc.pid, sig)
                except ProcessLookupError:
                    pass

    def _supervise(self, workers, slots) -> str:
        """Watch one incarnation.
        Returns 'done' | 'restart' | 'drain' | 'term' | 'failed'."""
        notified = False
        drain_deadline = None
        while True:
            clock.sleep(self.interval)
            # 0. driver-level preemption: forward the drain FIRST and
            # give workers the full drain grace to reach the commit;
            # only then escalate through terminate()'s SIGTERM/SIGKILL
            # — the kill grace can never undercut the drain grace.
            if self._drain_requested and not self._drain_forwarded:
                self._drain_forwarded = True
                drain_deadline = clock.monotonic() + self.drain_grace
                self._forward_drain(workers)
            # 1. check worker exits
            running, done_ok, reset_req, crashed, drained = \
                [], [], [], [], []
            fenced = []
            for w in workers:
                code = w.poll()
                if code is None:
                    running.append(w)
                elif code == 0:
                    done_ok.append(w)
                elif code == DRAIN_EXIT_CODE:
                    # graceful drain after a preemption notice: a
                    # PLANNED departure, never a crash
                    drained.append(w)
                elif code == FENCE_EXIT_CODE:
                    # self-fenced (generation superseded / KV lease
                    # expired): the rank PROTECTED the job by dying —
                    # rebuild the world, but never charge its host a
                    # blacklist strike (core/retry.py FencedKV)
                    fenced.append(w)
                elif code == RESET_EXIT_CODE or code in _USR1_CODES:
                    reset_req.append(w)
                elif code in _TERM_CODES and (notified
                                              or self._drain_forwarded):
                    reset_req.append(w)
                else:
                    crashed.append((w, code))
            if fenced:
                for w in fenced:
                    self._log(f"rank {w.rank} self-fenced "
                              f"(exit {FENCE_EXIT_CODE}); relaunching "
                              "without a blacklist strike")
                flight.note("worker_fenced",
                            ranks=sorted(w.rank for w in fenced),
                            generation=self._generation - 1)
                reset_req.extend(fenced)
            _M_WORKERS.set(len(running))
            if self._drain_forwarded:
                # whole-job preemption: wait out the drain, then stop
                if not running:
                    return "term"
                if clock.monotonic() >= drain_deadline:
                    for w in workers:
                        w.terminate()
                    for w in workers:
                        try:
                            w.wait(timeout=10)
                        except Exception:
                            pass
                    return "term"
                continue
            if not running:
                if crashed or reset_req or drained:
                    return self._finish_incarnation(workers, slots, crashed)
                return "done"
            if crashed or reset_req or drained:
                # A peer is gone: remaining workers would stall in
                # collectives. Tell them to reset at the commit
                # boundary, then escalate to SIGTERM.
                return self._finish_incarnation(workers, slots, crashed)
            # 2. poll discovery for membership changes.  Compare the
            # EFFECTIVE world (capped at max_np) to the running one —
            # comparing raw discovered slots would restart-thrash
            # forever when discovery grows past --max-np.
            if self._refresh_hosts() and not notified:
                cur = self.hosts.available_slots()
                if self.max_np is not None:
                    cur = min(cur, self.max_np)
                if cur != len(slots) and cur >= 1:
                    self._notify_hosts_updated(workers)
                    notified = True

    def _finish_incarnation(self, workers, slots, crashed) -> str:
        by_rank_host = {s.rank: s.hostname for s in slots}
        self._last_crash_summary = "; ".join(
            f"rank {w.rank} on {by_rank_host.get(w.rank, '?')} exited "
            f"{code}" for w, code in crashed) or "no crashes (reset)"
        crashed_hosts = {by_rank_host.get(w.rank, "?")
                         for w, _code in crashed}
        for w, code in crashed:
            host = by_rank_host.get(w.rank, "?")
            self._crash_counts[host] = self._crash_counts.get(host, 0) + 1
            self._log(
                f"rank {w.rank} on {host} crashed with {code} "
                f"({self._crash_counts[host]} strikes)"
            )
            if self._crash_counts[host] >= BLACKLIST_THRESHOLD:
                cooldown = self.hosts.blacklist_host(host)
                self._log(
                    f"blacklisting {host} for {cooldown:.0f}s "
                    f"(strike {self.hosts.strikes(host)})")
                # a fresh threshold applies after re-admission; the
                # cooldown's own strike count carries the history
                self._crash_counts[host] = 0
        # decay: hosts whose workers all exited cleanly this
        # incarnation earn back a crash count and a blacklist strike —
        # a recovered host must not stay one crash from the blacklist
        # forever.
        for host in {s.hostname for s in slots} - crashed_hosts:
            if self._crash_counts.get(host, 0) > 0:
                self._crash_counts[host] -= 1
            self.hosts.record_success(host)
        _M_BLACKLISTED.set(len(self.hosts.blacklisted_now()))
        self.hosts.save_hints(self._hints_path)
        # grace period for the rest to exit at a commit boundary
        self._notify_hosts_updated(workers)
        deadline = clock.monotonic() + 30.0
        while clock.monotonic() < deadline:
            if all(w.poll() is not None for w in workers):
                break
            clock.sleep(0.2)
        for w in workers:
            w.terminate()
        for w in workers:
            try:
                w.wait(timeout=10)
            except Exception:
                pass
        # Classify AFTER the grace wait: the drain exit (the departing
        # rank's DRAIN_EXIT_CODE) often lands a poll tick after its
        # peers' reset exits, and a poll-time snapshot would misfile
        # the planned departure as a budget-charged restart.
        fenced = [w for w in workers if w.poll() == FENCE_EXIT_CODE]
        if fenced:
            print(
                f"hvtpu.elastic: rank(s) "
                f"{sorted(w.rank for w in fenced)} self-fenced (exit "
                f"{FENCE_EXIT_CODE}); relaunching without a blacklist "
                "strike", file=sys.stderr, flush=True)
        drained = [w for w in workers if w.poll() == DRAIN_EXIT_CODE]
        if drained and not crashed:
            ranks = sorted(w.rank for w in drained)
            print(
                f"hvtpu.elastic: planned departure: rank(s) {ranks} "
                f"drained (exit {DRAIN_EXIT_CODE}); resizing without "
                "a restart-budget or blacklist strike",
                file=sys.stderr, flush=True)
            return "drain"
        return "restart"


def run_elastic_driver(args: argparse.Namespace
                       ) -> "tuple[int, ElasticDriver]":
    """Build + run the elastic driver, returning (exit_code, driver) —
    callers needing post-run facts (final world size for result
    collection) use this; the CLI wrapper below keeps the int
    contract."""
    discovery = HostDiscoveryScript(args.host_discovery_script)
    max_restarts = getattr(args, "max_restarts", None)
    if max_restarts is None:
        max_restarts = int(os.environ.get("HVTPU_MAX_RESTARTS", "-1"))
    restart_window = getattr(args, "restart_window", None)
    if restart_window is None:
        restart_window = float(
            os.environ.get("HVTPU_RESTART_WINDOW_SECONDS", "0"))
    blacklist_cooldown = getattr(args, "blacklist_cooldown", None)
    drain_grace = getattr(args, "drain_grace", None)
    driver = ElasticDriver(
        command=args.command,
        discovery=discovery,
        min_np=args.min_np or args.np or 1,
        max_np=args.max_np,
        discovery_interval=(
            float(os.environ.get("HVTPU_ELASTIC_DISCOVERY_INTERVAL", 0)
                  or 1.0)
        ),
        elastic_timeout=args.elastic_timeout or 600.0,
        args=args,
        verbose=args.verbose,
        max_restarts=max_restarts,
        restart_window=restart_window,
        blacklist_cooldown=blacklist_cooldown,
        drain_grace=drain_grace,
    )
    return driver.run(), driver


def run_elastic(args: argparse.Namespace) -> int:
    """Entry from ``hvtpurun --host-discovery-script ...`` (parity:
    launch.py _run_elastic)."""
    return run_elastic_driver(args)[0]
