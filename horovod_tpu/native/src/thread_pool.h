// Worker pool for parallel host-memory packing.
//
// Parity: horovod/common/thread_pool.cc (used there to parallelize
// MemcpyInFusionBuffer on CPU).  Here it parallelizes gather/scatter of
// many eager tensors (e.g. torch grads) into/out of one flat fusion
// staging buffer before/after a fused XLA collective.
#pragma once

#include <condition_variable>
#include <functional>
#include <mutex>
#include <queue>
#include <thread>
#include <vector>

namespace hvt {

class ThreadPool {
 public:
  explicit ThreadPool(int num_threads);
  ~ThreadPool();
  // Run fn(i) for i in [0, n) across the pool; blocks until done.
  void ParallelFor(int64_t n, const std::function<void(int64_t)>& fn);
  int num_threads() const { return static_cast<int>(workers_.size()); }

 private:
  void Loop();
  std::vector<std::thread> workers_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::condition_variable done_cv_;
  std::queue<std::function<void()>> tasks_;
  int64_t outstanding_ = 0;
  bool stop_ = false;
};

// Process-wide pool, lazily constructed.
ThreadPool& GlobalPool();

}  // namespace hvt
