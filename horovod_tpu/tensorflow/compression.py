"""Gradient compression intents for the TF frontend (parity:
horovod/tensorflow/compression.py).  Like the torch frontend, the
actual wire codec runs inside the engine; these classes express user
intent and are mapped onto the engine codec at the op boundary."""

from __future__ import annotations


class Compressor:
    """Interface parity: compress/decompress are identity at the TF
    layer — the engine compresses on the wire."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class NoneCompressor(Compressor):
    pass


class FP16Compressor(Compressor):
    pass


class BF16Compressor(Compressor):
    """TPU-native extension: bfloat16 wire format."""


class Compression:
    """Parity: hvd.Compression.{none,fp16} (+ TPU-native bf16)."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
