"""metrics-catalog fixture (clean): registry, docs, and bench agree."""

from .registry import REGISTRY, counter, gauge

STEPS = counter("hvtpu_fixture_steps_total", "Completed steps.")
DEPTH = gauge("hvtpu_fixture_queue_depth", "Pending items.")

# Registry-attribute registration with buckets and a multi-line help
# string — the obs/stepprof.py shape (PR 12).
EXPOSED = REGISTRY.histogram(
    "hvtpu_fixture_exposed_seconds",
    "Exposed (not overlapped) time per step; "
    "host upper bound until a device join runs.",
    buckets=[0.1, 1.0])
FRACTION = REGISTRY.gauge(
    "hvtpu_fixture_overlap_fraction",
    "Measured overlap fraction from the most recent join.")
