"""Fleet health rollup: job-side publish, arbiter-side read.

The arbiter (fleet/arbiter.py) schedules jobs it otherwise cannot see
inside: a RUNNING row says nothing about whether the job is making
steps, throwing incidents, or wedged in a stall.  This module closes
the loop with one compact JSON summary per job:

- **Job side** — rank 0 runs a :class:`HealthReporter` (installed by
  ``core/state.init`` when ``HVTPU_FLEET_JOB`` names the owning fleet
  job) that every ``HVTPU_HEALTH_INTERVAL_S`` seconds summarizes the
  process's own telemetry (optimizer steps + EWMA step rate from the
  metrics registry, per-kind incident counts from obs/anomaly, the
  elastic generation, and a stall age derived from the flight ring's
  ``step`` vs ``stall_warning`` recency) and writes it at key
  ``health`` under the job's prefixed KV namespace
  (``fleet/<job>/health`` — see fleet/job.py's ``prefixed_client``).
- **Arbiter side** — :func:`read` fetches a job's summary; the arbiter
  attaches it to the job row in ``state.json`` each tick and exports
  the fleet gauges, and ``hvtpufleet top`` renders the table.

In a real deployment each job's coordination KV is private to its own
world — the arbiter process is not a member and cannot read it.  The
reporter therefore also mirrors every summary to an atomic file in
``HVTPU_FLEET_HEALTH_DIR`` (a job-scoped directory the fleet runner
injects alongside ``HVTPU_FLEET_JOB``), and the arbiter falls back to
:func:`read_file` when it has no shared KV client.  The KV channel
remains primary where one exists (the fabric simulator).

Time flows through ``core/clock`` so the rollup behaves identically
under the fabric simulator's virtual clock.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from typing import Any, Dict, Optional

from ..core import clock
from ..core.retry import unstamp
from ..obs import metrics as obs_metrics
from .job import prefixed_client

__all__ = ["HEALTH_KEY", "HEALTH_FILE", "summarize", "HealthReporter",
           "read", "read_file", "health_interval_s"]

logger = logging.getLogger("horovod_tpu")

#: key under the job's prefixed namespace (full: ``fleet/<job>/health``)
HEALTH_KEY = "health"

#: filename inside ``HVTPU_FLEET_HEALTH_DIR`` (the file channel)
HEALTH_FILE = "health.json"

# A summary whose publish wall-clock age exceeds this many intervals is
# reported with "stale": true by read() so `hvtpufleet top` can flag a
# job that stopped publishing (wedged or dead) without guessing.
STALE_INTERVALS = 3.0


def health_interval_s() -> float:
    """``HVTPU_HEALTH_INTERVAL_S``: publish cadence (seconds)."""
    try:
        return max(1.0, float(
            os.environ.get("HVTPU_HEALTH_INTERVAL_S", "15")))
    except ValueError:
        return 15.0


def summarize(*, rank: int = 0, generation: Optional[int] = None
              ) -> Dict[str, Any]:
    """One compact health summary from this process's own telemetry.
    Pure read: registry counters/gauges, the anomaly engine's incident
    counts, and the flight ring's event recency."""
    reg = obs_metrics.REGISTRY
    steps = reg.counter("hvtpu_optimizer_steps_total").value()
    rate = reg.gauge("hvtpu_steps_per_second").value()
    if generation is None:
        generation = int(reg.gauge("hvtpu_elastic_generation").value())
    out: Dict[str, Any] = {
        "t_wall": round(clock.wall(), 3),
        "rank": rank,
        "generation": generation,
        "restarts": generation,
        "steps": steps,
        "step_rate": round(rate, 4),
        "incidents": {},
        "incidents_total": 0,
        "stall_age_s": 0.0,
        "interval_s": health_interval_s(),
    }
    try:
        from ..obs import anomaly as _anomaly
        eng = _anomaly.get_engine()
        if eng is not None:
            counts = eng.counts()
            out["incidents"] = counts
            out["incidents_total"] = sum(counts.values())
    except Exception:
        pass
    try:
        from ..obs import flight as _flight
        rec = _flight.get_recorder()
        if rec is not None:
            warn_t = rec.last_event_t("stall_warning")
            step_t = rec.last_event_t("step")
            if warn_t is not None and (step_t is None or warn_t > step_t):
                # a stall warning newer than the last completed step:
                # the job is (still) stalled; age from the last step
                # it did finish, else from the warning itself.
                now = clock.monotonic()
                out["stall_age_s"] = round(
                    now - (step_t if step_t is not None else warn_t), 3)
    except Exception:
        pass
    return out


class HealthReporter:
    """Rank 0's background publisher.  ``client`` is the coordination
    KV (already-resilient) — None for file-only publishing;
    ``job_name`` selects the prefixed namespace.  ``file_dir``
    (default: ``HVTPU_FLEET_HEALTH_DIR``) additionally mirrors each
    summary to an atomic file the arbiter can read without being a
    member of the job's coordination world.  ``start()`` spawns a
    daemon loop on the publish cadence; :meth:`publish_once` is the
    synchronous unit (the sim and tests drive it directly)."""

    def __init__(self, client, job_name: str, *, rank: int = 0,
                 interval_s: Optional[float] = None,
                 file_dir: Optional[str] = None):
        self.job_name = job_name
        self.rank = rank
        self.interval_s = (health_interval_s()
                           if interval_s is None else interval_s)
        self._kv = (prefixed_client(client, job_name)
                    if client is not None else None)
        self.file_dir = (os.environ.get("HVTPU_FLEET_HEALTH_DIR")
                         if file_dir is None else file_dir)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def publish_once(self) -> Optional[Dict[str, Any]]:
        """Summarize and write; never raises (health must not take the
        job down).  Returns the published summary, or None when every
        channel failed."""
        try:
            summary = summarize(rank=self.rank)
            summary["job"] = self.job_name
            payload = json.dumps(summary, sort_keys=True)
        except Exception:
            logger.debug("fleet health summarize failed", exc_info=True)
            return None
        ok = False
        if self._kv is not None:
            try:
                self._kv.key_value_set(HEALTH_KEY, payload)
                ok = True
            except Exception:
                logger.debug("fleet health KV publish failed",
                             exc_info=True)
        if self.file_dir:
            try:
                os.makedirs(self.file_dir, exist_ok=True)
                tmp = os.path.join(
                    self.file_dir, f".{HEALTH_FILE}.{os.getpid()}.part")
                with open(tmp, "w", encoding="utf-8") as f:
                    f.write(payload)
                os.replace(tmp, os.path.join(self.file_dir, HEALTH_FILE))
                ok = True
            except OSError:
                logger.debug("fleet health file publish failed",
                             exc_info=True)
        return summary if ok else None

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.publish_once()
            clock.sleep(self.interval_s)

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="hvtpu-health", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        # no join: the loop may be inside clock.sleep; daemon threads
        # die with the process and publish_once is crash-safe.
        self._thread = None


def _parse(raw, now_wall: Optional[float]) -> Optional[Dict[str, Any]]:
    # The reporter side writes through a fenced client (core/retry.py
    # FencedKV), so the summary may carry a generation-fencing stamp;
    # the arbiter reads with its own raw client and must stay
    # stamp-tolerant.  unstamp() is a no-op on unstamped payloads.
    if isinstance(raw, bytes):
        try:
            raw = raw.decode("utf-8")
        except UnicodeDecodeError:
            return None
    _tok, raw = unstamp(raw)
    try:
        summary = json.loads(raw)
    except (TypeError, ValueError):
        return None
    if not isinstance(summary, dict):
        return None
    t = summary.get("t_wall")
    interval = summary.get("interval_s") or health_interval_s()
    if isinstance(t, (int, float)):
        now = clock.wall() if now_wall is None else now_wall
        summary["stale"] = bool(
            now - t > STALE_INTERVALS * float(interval))
    return summary


def read(client, job_name: str,
         *, now_wall: Optional[float] = None) -> Optional[Dict[str, Any]]:
    """Arbiter-side fetch of a job's latest summary (None when the job
    never published or the read failed).  Adds ``"stale": true`` when
    the summary's publish time is older than ``STALE_INTERVALS`` times
    its own cadence."""
    try:
        kv = prefixed_client(client, job_name)
        raw = kv.key_value_try_get(HEALTH_KEY)
    except Exception:
        return None
    if raw is None:
        return None
    return _parse(raw, now_wall)


def read_file(file_dir: str,
              *, now_wall: Optional[float] = None
              ) -> Optional[Dict[str, Any]]:
    """File-channel twin of :func:`read`: load the summary the
    reporter mirrored into ``file_dir`` (None when the job never
    published there or the file is unreadable/torn)."""
    try:
        with open(os.path.join(file_dir, HEALTH_FILE),
                  encoding="utf-8") as f:
            raw = f.read()
    except OSError:
        return None
    return _parse(raw, now_wall)
