"""Torch-frontend elastic state.

Parity surface: ``horovod/torch/elastic/state.py`` (``TorchState``) and
``horovod/torch/elastic/sampler.py`` (``ElasticSampler``): capture
``nn.Module`` / optimizer state_dicts for commit/rollback, broadcast
them on sync, and reshard the sampler when the world changes.
"""

from __future__ import annotations

import copy
from typing import Any, Dict

import torch

from ..elastic import run  # noqa: F401  (parity: hvd.elastic.run)
from ..elastic.state import ObjectState


class TorchState(ObjectState):
    """Elastic state tracking torch modules/optimizers plus plain
    attributes (parity: TorchState(model=..., optimizer=..., epoch=0)).

    Modules and optimizers are captured via ``state_dict()`` /
    ``load_state_dict()``; everything else behaves like ObjectState.
    """

    def __init__(self, model=None, optimizer=None, **kwargs):
        self._handles: Dict[str, Any] = {}
        if model is not None:
            self._handles["model"] = model
        if optimizer is not None:
            self._handles["optimizer"] = optimizer
        # also accept arbitrary named modules/optimizers in kwargs
        plain = {}
        for k, v in list(kwargs.items()):
            if isinstance(v, torch.nn.Module) or hasattr(v, "state_dict"):
                self._handles[k] = v
            else:
                plain[k] = v
        super().__init__(**plain)
        for k, v in self._handles.items():
            setattr(self, k, v)
        self.save_to_memory()

    # -- payload capture over state_dicts --
    def _capture(self) -> Dict[str, Any]:
        payload = {
            k: copy.deepcopy(getattr(self, k)) for k in self._tracked
        }
        for k, h in self._handles.items():
            payload["__sd__" + k] = copy.deepcopy(h.state_dict())
        return payload

    def _apply(self, payload: Dict[str, Any]):
        for k, v in payload.items():
            if k.startswith("__sd__"):
                self._handles[k[len("__sd__"):]].load_state_dict(v)
            else:
                setattr(self, k, v)

    def sync(self):
        """Broadcast rank 0's committed state (model/optimizer via the
        torch broadcast helpers for exactness, scalars via objects)."""
        super().sync()


class ElasticSampler(torch.utils.data.Sampler):
    """Distributed sampler that reshards on world changes and skips
    already-processed indices after a restore (parity: ElasticSampler).
    """

    def __init__(self, dataset, shuffle: bool = True, seed: int = 0):
        from . import rank as hvd_rank
        from . import size as hvd_size

        self.dataset = dataset
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        self.processed_indices: set = set()
        self.rank = hvd_rank()
        self.num_replicas = hvd_size()
        self._reshard()

    def set_epoch(self, epoch: int):
        self.epoch = epoch
        self.processed_indices = set()
        self._reshard()

    def record_batch(self, batch_idx: int, batch_size: int):
        """Mark ``batch_size`` samples starting at ``batch_idx`` as
        processed so a restore doesn't revisit them."""
        lo = batch_idx * batch_size
        self.processed_indices.update(self.indices[lo:lo + batch_size])

    def load_state_dict(self, sd: Dict[str, Any]):
        self.epoch = sd["epoch"]
        self.processed_indices = set(sd["processed_indices"])
        self._reshard()

    def state_dict(self) -> Dict[str, Any]:
        return {
            "epoch": self.epoch,
            "processed_indices": sorted(self.processed_indices),
        }

    def _reshard(self):
        from . import rank as hvd_rank
        from . import size as hvd_size

        self.rank = hvd_rank()
        self.num_replicas = hvd_size()
        remaining = [
            i for i in range(len(self.dataset))
            if i not in self.processed_indices
        ]
        if self.shuffle:
            g = torch.Generator()
            g.manual_seed(self.seed + self.epoch)
            perm = torch.randperm(len(remaining), generator=g).tolist()
            remaining = [remaining[i] for i in perm]
        # drop the tail so every replica sees the same count
        per = len(remaining) // self.num_replicas
        self.indices = remaining[
            self.rank * per:(self.rank + 1) * per
        ]

    def __iter__(self):
        return iter(self.indices)

    def __len__(self):
        return len(self.indices)
