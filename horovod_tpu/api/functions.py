"""State-distribution helpers.

Parity surface: ``horovod/torch/functions.py`` —
``broadcast_parameters``, ``broadcast_optimizer_state``,
``broadcast_object`` — plus ``allgather_object``, the utilities every
Horovod training script calls once at startup to fan rank 0's state out
to the world (SURVEY.md §5.4).
"""

from __future__ import annotations

import io
import pickle
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..comm import eager
from ..core import state as core_state


def broadcast_parameters(params, root_rank: int = 0, process_set=None):
    """Broadcast a pytree of arrays from ``root_rank`` to all ranks.

    Returns the broadcast tree (functional, unlike the reference's
    in-place torch version — JAX arrays are immutable).
    """
    core_state.require_init("broadcast_parameters")
    return jax.tree_util.tree_map(
        lambda t: eager.broadcast(
            jnp.asarray(t), root_rank=root_rank, process_set=process_set
        ),
        params,
    )


def broadcast_optimizer_state(opt_state, root_rank: int = 0, process_set=None):
    """Broadcast optimizer state (any pytree; non-array leaves go via
    ``broadcast_object``)."""
    core_state.require_init("broadcast_optimizer_state")

    def bcast_leaf(t):
        if isinstance(t, (jax.Array, np.ndarray)) or jnp.isscalar(t):
            return eager.broadcast(
                jnp.asarray(t), root_rank=root_rank, process_set=process_set
            )
        return broadcast_object(t, root_rank=root_rank, process_set=process_set)

    return jax.tree_util.tree_map(bcast_leaf, opt_state)


def broadcast_object(obj: Any, root_rank: int = 0, process_set=None) -> Any:
    """Pickle on root, broadcast size then payload, unpickle everywhere.

    Parity: ``horovod/torch/functions.py broadcast_object`` (same
    two-phase size/payload wire protocol).
    """
    core_state.require_init("broadcast_object")
    st = core_state.global_state()
    if st.size == 1:
        return obj

    buf = io.BytesIO()
    pickle.dump(obj, buf, protocol=pickle.HIGHEST_PROTOCOL)
    payload = np.frombuffer(buf.getvalue(), dtype=np.uint8)

    # uint32 size header: stays exact without jax_enable_x64 (bounds
    # one pickled object at 4 GiB, same as the reference's int wire).
    size = eager.broadcast(
        jnp.asarray(payload.size, jnp.uint32),
        root_rank=root_rank,
        process_set=process_set,
    )
    n = int(size)
    local = payload if st.rank == root_rank else np.zeros((n,), np.uint8)
    wire = eager.broadcast(
        jnp.asarray(local[:n]), root_rank=root_rank, process_set=process_set
    )
    return pickle.loads(np.asarray(wire).tobytes())


def allgather_object(obj: Any, process_set=None):
    """Gather a picklable object from every rank; returns a list ordered
    by rank (parity: hvd.allgather_object)."""
    core_state.require_init("allgather_object")
    st = core_state.global_state()
    if st.size == 1:
        return [obj]

    buf = io.BytesIO()
    pickle.dump(obj, buf, protocol=pickle.HIGHEST_PROTOCOL)
    payload = np.frombuffer(buf.getvalue(), dtype=np.uint8)
    gathered_sizes = np.asarray(
        eager.allgather(
            jnp.asarray([payload.size], jnp.uint32), process_set=process_set
        )
    )
    blob = np.asarray(
        eager.allgather(jnp.asarray(payload), process_set=process_set)
    ).tobytes()
    out, off = [], 0
    for s in gathered_sizes:
        out.append(pickle.loads(blob[off : off + int(s)]))
        off += int(s)
    return out
