"""Flight recorder + online anomaly detection (obs/flight, obs/anomaly,
fleet/health, hvtputrace postmortem).

Acceptance shape (ISSUE PR 16):

- a 2-process chaos job with ``collective.pre:delay`` on rank 1 must
  raise a ``straggler`` incident that *names rank 1*, and the same job
  on a clean control run must raise zero incidents;
- a ``worker.step:kill`` chaos job must leave merged postmortems from
  both ranks that ``hvtputrace postmortem`` fuses into one timeline;
- with the planes disabled the hot-path hook must be a single
  module-attribute test (same contract as tracing.ACTIVE), timeit-
  enforced below;
- the detector itself must pass a synthetic-series matrix: silent
  through warmup, fires on a step change and a single spike, stays
  silent through slow drift.
"""

import json
import os
import signal
import time

import pytest

import horovod_tpu
from horovod_tpu.fleet import health
from horovod_tpu.obs import anomaly, flight
from horovod_tpu.obs import metrics as obs_metrics
from horovod_tpu.runner import RunError, run
import tools.hvtputrace as hvtputrace

_REPO_ROOT = os.path.dirname(os.path.dirname(horovod_tpu.__file__))
_ENV = {"PYTHONPATH": _REPO_ROOT + os.pathsep
        + os.environ.get("PYTHONPATH", "")}


def _read_json(path):
    with open(path, encoding="utf-8") as f:
        return json.load(f)


@pytest.fixture(autouse=True)
def _clean_planes():
    """Unit tests here install/uninstall the process singletons; make
    sure no test leaks an installed recorder/engine into the next."""
    flight.uninstall()
    anomaly.uninstall()
    yield
    flight.uninstall()
    anomaly.uninstall()


# --------------------------------------------------------------------------
# FlightRecorder unit tests
# --------------------------------------------------------------------------

class TestFlightRecorder:
    def test_ring_is_bounded_and_counts_drops(self, tmp_path):
        rec = flight.FlightRecorder(out_dir=str(tmp_path), window=16)
        for i in range(20):
            rec.note("tick", {"i": i})
        evs = rec.events()
        assert len(evs) == 16
        # oldest events fell off the front
        assert [e["i"] for e in evs] == list(range(4, 20))
        st = rec.debug_state()
        assert st["appended"] == 20
        assert st["dropped"] == 4
        assert st["window"] == 16
        assert st["kinds"] == {"tick": 16}

    def test_events_carry_wall_timestamps(self, tmp_path):
        rec = flight.FlightRecorder(out_dir=str(tmp_path), window=32)
        rec.note("a")
        rec.note("b", {"x": 1})
        evs = rec.events()
        assert [e["kind"] for e in evs] == ["a", "b"]
        assert evs[0]["t_wall"] <= evs[1]["t_wall"]
        # wall-converted: near the recorder's own anchor, not monotonic
        assert abs(evs[0]["t_wall"] - rec.wall_anchor) < 60.0
        assert evs[1]["x"] == 1

    def test_last_event_t(self, tmp_path):
        rec = flight.FlightRecorder(out_dir=str(tmp_path), window=32)
        assert rec.last_event_t("step") is None
        rec.note("step")
        t1 = rec.last_event_t("step")
        rec.note("step")
        assert rec.last_event_t("step") >= t1

    def test_dump_schema_and_reason_accumulation(self, tmp_path):
        rec = flight.FlightRecorder(rank=3, size=8, generation=2,
                                    out_dir=str(tmp_path), window=32)
        rec.note("step", {"n": 1})
        path = rec.dump("stall_abort", tensor="grad.0")
        assert path == str(tmp_path / "postmortem-3-2.json")
        doc = _read_json(path)
        assert doc["schema"] == flight.POSTMORTEM_SCHEMA
        assert doc["rank"] == 3 and doc["size"] == 8
        assert doc["generation"] == 2
        assert doc["reason"] == "stall_abort"
        assert doc["reasons"] == ["stall_abort"]
        assert doc["detail"] == {"tensor": "grad.0"}
        assert "wall_anchor" in doc["clock"]
        assert "mono_anchor" in doc["clock"]
        assert any(e["kind"] == "step" for e in doc["events"])
        assert isinstance(doc["debug"], dict)
        assert isinstance(doc["metrics"], dict)
        # a second trigger overwrites the file but accumulates reasons
        path2 = rec.dump("sigusr2")
        assert path2 == path
        doc2 = _read_json(path)
        assert doc2["reason"] == "sigusr2"
        assert doc2["reasons"] == ["stall_abort", "sigusr2"]

    def test_dump_never_raises(self, tmp_path):
        blocker = tmp_path / "not-a-dir"
        blocker.write_text("x")
        rec = flight.FlightRecorder(out_dir=str(blocker / "sub"))
        assert rec.dump("boom") is None  # swallowed, not raised


# --------------------------------------------------------------------------
# module plumbing: install/uninstall, env gates, SIGUSR2
# --------------------------------------------------------------------------

class TestFlightModule:
    def test_install_uninstall_flip_active(self, tmp_path):
        assert flight.ACTIVE is False
        rec = flight.install(rank=1, size=2, out_dir=str(tmp_path),
                             sigusr2=False)
        assert rec is not None and flight.ACTIVE is True
        assert flight.get_recorder() is rec
        assert flight.install(out_dir=str(tmp_path)) is rec  # idempotent
        flight.note("hello", a=1)
        assert any(e["kind"] == "hello" for e in rec.events())
        assert obs_metrics.debug_snapshot()["flight"]["active"] is True
        flight.uninstall()
        assert flight.ACTIVE is False
        assert flight.get_recorder() is None
        assert "flight" not in obs_metrics.debug_snapshot()
        flight.uninstall()  # double-uninstall is a no-op

    def test_env_opt_out(self, monkeypatch, tmp_path):
        monkeypatch.setenv("HVTPU_FLIGHT", "0")
        assert flight.env_enabled() is False
        assert flight.install(out_dir=str(tmp_path)) is None
        assert flight.ACTIVE is False

    def test_env_window_parsing(self, monkeypatch):
        monkeypatch.delenv("HVTPU_FLIGHT_WINDOW", raising=False)
        assert flight.env_window() == 2048
        monkeypatch.setenv("HVTPU_FLIGHT_WINDOW", "100")
        assert flight.env_window() == 100
        monkeypatch.setenv("HVTPU_FLIGHT_WINDOW", "4")
        assert flight.env_window() == 16  # floor
        monkeypatch.setenv("HVTPU_FLIGHT_WINDOW", "junk")
        assert flight.env_window() == 2048

    def test_dump_postmortem_without_recorder_needs_dir(
            self, monkeypatch, tmp_path):
        assert flight.get_recorder() is None
        monkeypatch.delenv("HVTPU_FLIGHT_DIR", raising=False)
        # no recorder + no destination: never litters the CWD
        assert flight.dump_postmortem("restart_budget_exhausted") is None
        monkeypatch.setenv("HVTPU_FLIGHT_DIR", str(tmp_path))
        path = flight.dump_postmortem("restart_budget_exhausted")
        assert path == str(tmp_path / "postmortem-driver-0.json")
        doc = _read_json(path)
        assert doc["rank"] == "driver"
        assert doc["events"] == []  # transient recorder: empty ring

    def test_sigusr2_dumps_on_demand(self, tmp_path):
        rec = flight.install(rank=0, size=1, out_dir=str(tmp_path))
        assert rec is not None
        os.kill(os.getpid(), signal.SIGUSR2)
        path = tmp_path / "postmortem-0-0.json"
        deadline = time.monotonic() + 10.0
        while not path.exists() and time.monotonic() < deadline:
            time.sleep(0.05)
        doc = _read_json(path)
        assert doc["reason"] == "sigusr2"
        assert any(e["kind"] == "sigusr2" for e in doc["events"])


# --------------------------------------------------------------------------
# disabled-path overhead: one module-attribute test, nothing more
# --------------------------------------------------------------------------

def test_disabled_hooks_are_one_attribute_check():
    """Acceptance: with the planes off, the seam guards
    ``if flight.ACTIVE: ...`` / ``if anomaly.ACTIVE: ...`` cost one
    module-attribute read — the same budget tracing.ACTIVE holds."""
    import timeit

    assert flight.ACTIVE is False
    assert anomaly.ACTIVE is False
    n = 100_000
    t = timeit.timeit(
        lambda: flight.ACTIVE and flight.note("x", a=1), number=n)
    assert t / n < 5e-6, f"flight: {t / n * 1e9:.0f} ns/op"
    t = timeit.timeit(
        lambda: anomaly.ACTIVE and anomaly.on_step({}), number=n)
    assert t / n < 5e-6, f"anomaly: {t / n * 1e9:.0f} ns/op"


# --------------------------------------------------------------------------
# detector unit matrix: warmup / step change / spike / drift
# --------------------------------------------------------------------------

def _cfg(**kw):
    base = dict(window=16, warmup=8, threshold=6.0, ewma_alpha=0.15,
                min_rel=0.25, cooldown_s=0.0)
    base.update(kw)
    return anomaly.AnomalyConfig(**base)


class TestRobustDetector:
    def test_silent_through_warmup(self):
        det = anomaly.RobustDetector(_cfg())
        # even wild values produce no verdicts before warmup samples
        for v in [1.0, 50.0, 1.0, 80.0, 2.0, 1.0, 99.0, 1.0]:
            assert det.update(v) is None
        assert det.samples == 8

    def test_step_change_fires(self):
        det = anomaly.RobustDetector(_cfg())
        for i in range(20):
            assert det.update(1.0 + (i % 3) * 0.01) is None
        v = det.update(10.0)
        assert v is not None
        assert v["zscore"] >= 6.0
        assert 0.9 < v["baseline"] < 1.1
        assert v["value"] == 10.0

    def test_single_spike_fires_once_without_shifting_baseline(self):
        det = anomaly.RobustDetector(_cfg())
        for i in range(20):
            det.update(1.0 + (i % 3) * 0.01)
        assert det.update(10.0) is not None  # the spike
        # the spike entered the window but median/MAD shrug it off:
        # healthy samples afterwards stay silent
        for i in range(10):
            assert det.update(1.0 + (i % 3) * 0.01) is None

    def test_slow_drift_does_not_fire(self):
        det = anomaly.RobustDetector(_cfg())
        v = 1.0
        for _ in range(60):
            assert det.update(v) is None
            v *= 1.01  # +1%/sample: EWMA and the window track it
        assert det.ewma == pytest.approx(v, rel=0.2)

    def test_low_side_never_fires(self):
        det = anomaly.RobustDetector(_cfg())
        for i in range(20):
            det.update(1.0 + (i % 3) * 0.01)
        assert det.update(0.001) is None  # faster is never an incident


class TestAnomalyEngine:
    def test_straggler_incident_names_the_rank(self):
        eng = anomaly.AnomalyEngine(
            rank=0, size=8,
            config=_cfg(window=8, warmup=4, min_rel=1.0))
        for i in range(8):
            assert eng.on_arrival_skew(f"g.{i}", 0.001 + (i % 2) * 1e-4,
                                       last_rank=i % 8) == []
        fired = eng.on_arrival_skew("g.slow", 0.5, last_rank=5)
        assert len(fired) == 1
        inc = fired[0]
        assert inc["kind"] == "straggler"
        assert inc["ranks"] == [5]
        assert inc["detail"]["tensor"] == "g.slow"
        assert eng.counts() == {"straggler": 1}
        assert eng.incidents()[-1]["kind"] == "straggler"
        assert eng.debug_state()["recent"][-1]["kind"] == "straggler"

    def test_cooldown_rate_limits(self):
        eng = anomaly.AnomalyEngine(
            rank=0, size=2,
            config=_cfg(window=8, warmup=4, cooldown_s=3600.0))
        for i in range(8):
            eng.on_arrival_skew("g", 0.001, last_rank=i % 2)
        eng.on_arrival_skew("g", 0.5, last_rank=1)
        eng.on_arrival_skew("g", 0.6, last_rank=1)
        assert eng.counts() == {"straggler": 1}

    def test_on_step_fires_step_time(self):
        eng = anomaly.AnomalyEngine(
            rank=2, size=4, config=_cfg(window=8, warmup=4))
        for _ in range(10):
            assert eng.on_step({"step_wall_s": 0.1, "steps": 1}) == []
        fired = eng.on_step({"step_wall_s": 5.0, "steps": 1})
        kinds = {i["kind"] for i in fired}
        assert "step_time" in kinds
        # process-local signal: blames this rank
        inc = next(i for i in fired if i["kind"] == "step_time")
        assert inc["ranks"] == [2]

    def test_engine_install_respects_env_gate(self, monkeypatch):
        monkeypatch.setenv("HVTPU_ANOMALY", "off")
        assert anomaly.install() is None
        assert anomaly.ACTIVE is False

    def test_config_from_env(self, monkeypatch):
        monkeypatch.setenv("HVTPU_ANOMALY_WINDOW", "12")
        monkeypatch.setenv("HVTPU_ANOMALY_WARMUP", "2")  # floored to 4
        monkeypatch.setenv("HVTPU_ANOMALY_THRESHOLD", "5.5")
        monkeypatch.setenv("HVTPU_ANOMALY_MIN_REL", "0.5")
        monkeypatch.setenv("HVTPU_ANOMALY_COOLDOWN_S", "2")
        cfg = anomaly.AnomalyConfig.from_env()
        assert cfg.window == 12
        assert cfg.warmup == 4
        assert cfg.threshold == 5.5
        assert cfg.min_rel == 0.5
        assert cfg.cooldown_s == 2.0


# --------------------------------------------------------------------------
# postmortem merge unit tests (tools/hvtputrace)
# --------------------------------------------------------------------------

def _fake_dump(dirpath, rank, events, *, offset_us=None, gen=0,
               reason="stall_abort"):
    clock = {"wall_anchor": 100.0, "mono_anchor": 0.0}
    if offset_us is not None:
        clock["offset_us"] = offset_us
    doc = {
        "schema": flight.POSTMORTEM_SCHEMA,
        "rank": rank, "size": 2, "generation": gen,
        "reason": reason, "reasons": [reason],
        "t_wall": 110.0, "clock": clock,
        "events": events, "debug": {}, "metrics": {},
    }
    path = os.path.join(str(dirpath), f"postmortem-{rank}-{gen}.json")
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    return path


class TestPostmortemMerge:
    def test_merge_corrects_clocks_and_sorts(self, tmp_path):
        _fake_dump(tmp_path, 0,
                   [{"t_wall": 100.0, "kind": "a"},
                    {"t_wall": 101.0, "kind": "b"}],
                   offset_us=0.0)
        # rank 1's clock runs 0.5 s fast; offset_us corrects it back
        _fake_dump(tmp_path, 1,
                   [{"t_wall": 100.9, "kind": "c", "x": 7}],
                   offset_us=-500_000.0)
        rep = hvtputrace.postmortem_merge(str(tmp_path))
        assert rep["ranks"] == [0, 1]
        assert [e["kind"] for e in rep["timeline"]] == ["a", "c", "b"]
        c = rep["timeline"][1]
        assert c["t"] == pytest.approx(100.4)
        assert c["rank"] == 1 and c["x"] == 7
        assert all(p["clock_corrected"] for p in rep["per_rank"])

    def test_uncorrected_rank_is_flagged(self, tmp_path):
        _fake_dump(tmp_path, 0, [{"t_wall": 1.0, "kind": "a"}],
                   offset_us=0.0)
        _fake_dump(tmp_path, 1, [{"t_wall": 2.0, "kind": "b"}])
        rep = hvtputrace.postmortem_merge(str(tmp_path))
        flags = {p["rank"]: p["clock_corrected"] for p in rep["per_rank"]}
        assert flags == {0: True, 1: False}
        text = hvtputrace.render_postmortem(rep)
        assert "UNCORRECTED" in text
        assert "[rank 1] b" in text

    def test_render_tail_limits_timeline(self, tmp_path):
        _fake_dump(tmp_path, 0,
                   [{"t_wall": float(i), "kind": f"k{i}"}
                    for i in range(10)], offset_us=0.0)
        text = hvtputrace.render_postmortem(
            hvtputrace.postmortem_merge(str(tmp_path)), tail=3)
        assert "3 of 10 events" in text
        assert "k9" in text and "k0" not in text

    def test_empty_dir_raises_with_guidance(self, tmp_path):
        with pytest.raises(FileNotFoundError, match="HVTPU_FLIGHT_DIR"):
            hvtputrace.load_postmortems(str(tmp_path))

    def test_cli_postmortem_subcommand(self, tmp_path, capsys):
        from tools.hvtputrace.__main__ import main
        _fake_dump(tmp_path, 0, [{"t_wall": 1.0, "kind": "a"}],
                   offset_us=0.0)
        assert main(["postmortem", str(tmp_path)]) == 0
        assert "hvtputrace postmortem" in capsys.readouterr().out


# --------------------------------------------------------------------------
# fleet health rollup unit tests
# --------------------------------------------------------------------------

class _FakeKV:
    def __init__(self):
        self.store = {}

    def key_value_set(self, key, value):
        self.store[key] = value

    def key_value_try_get(self, key):
        return self.store.get(key)


class TestFleetHealth:
    def test_summarize_shape(self):
        s = health.summarize(rank=0, generation=1)
        for k in ("t_wall", "steps", "step_rate", "incidents",
                  "incidents_total", "stall_age_s", "restarts",
                  "interval_s"):
            assert k in s
        assert s["generation"] == 1 and s["restarts"] == 1

    def test_summarize_reports_stall_age(self, tmp_path):
        rec = flight.install(out_dir=str(tmp_path), sigusr2=False)
        rec.note("step")
        time.sleep(0.02)
        rec.note("stall_warning", {"tensor": "g"})
        s = health.summarize(rank=0, generation=0)
        assert s["stall_age_s"] > 0.0
        # a newer step clears the stall age
        rec.note("step")
        assert health.summarize(rank=0, generation=0)["stall_age_s"] == 0.0

    def test_publish_read_round_trip_and_staleness(self):
        kv = _FakeKV()
        rep = health.HealthReporter(kv, "trainer", rank=0, interval_s=5.0)
        published = rep.publish_once()
        assert published is not None
        assert "fleet/trainer/health" in kv.store
        got = health.read(kv, "trainer")
        assert got["job"] == "trainer"
        assert got["stale"] is False
        # an old summary reads back stale after STALE_INTERVALS cadences
        later = published["t_wall"] + published["interval_s"] * 10
        assert health.read(kv, "trainer", now_wall=later)["stale"] is True
        assert health.read(kv, "missing-job") is None

    def test_publish_once_never_raises(self):
        class _Broken:
            def key_value_set(self, key, value):
                raise RuntimeError("kv down")

            def key_value_try_get(self, key):
                raise RuntimeError("kv down")

        rep = health.HealthReporter(_Broken(), "j", rank=0, interval_s=5)
        assert rep.publish_once() is None
        assert health.read(_Broken(), "j") is None

    def test_file_channel_round_trip_and_staleness(self, tmp_path):
        # no KV at all: a real deployment's arbiter is not a member of
        # the job's coordination world, so the file channel carries it
        rep = health.HealthReporter(None, "trainer", rank=0,
                                    interval_s=5.0,
                                    file_dir=str(tmp_path))
        published = rep.publish_once()
        assert published is not None
        assert (tmp_path / health.HEALTH_FILE).is_file()
        got = health.read_file(str(tmp_path))
        assert got["job"] == "trainer"
        assert got["stale"] is False
        later = published["t_wall"] + published["interval_s"] * 10
        assert health.read_file(
            str(tmp_path), now_wall=later)["stale"] is True
        assert health.read_file(str(tmp_path / "missing")) is None

    def test_file_channel_tolerates_torn_file_and_broken_kv(
            self, tmp_path):
        (tmp_path / health.HEALTH_FILE).write_text('{"t_wall": 1,')
        assert health.read_file(str(tmp_path)) is None

        class _Broken:
            def key_value_set(self, key, value):
                raise RuntimeError("kv down")

        # KV down but the file channel still lands the summary
        rep = health.HealthReporter(_Broken(), "j", rank=0,
                                    interval_s=5.0,
                                    file_dir=str(tmp_path))
        assert rep.publish_once() is not None
        assert health.read_file(str(tmp_path))["job"] == "j"


# --------------------------------------------------------------------------
# 2-process chaos acceptance
# --------------------------------------------------------------------------

_ANOMALY_ENV = {
    # small window/warmup so the job fires within a short run; a high
    # relative floor (value must exceed 21x the median skew) keeps the
    # clean control run silent under CPU scheduler jitter while a
    # 300 ms injected delay clears it by orders of magnitude.
    "HVTPU_ANOMALY_WINDOW": "8",
    "HVTPU_ANOMALY_WARMUP": "6",
    "HVTPU_ANOMALY_THRESHOLD": "8",
    "HVTPU_ANOMALY_MIN_REL": "20",
    "HVTPU_ANOMALY_COOLDOWN_S": "0",
    # arrival-skew drain lives in the Python controller twin
    "HVTPU_FORCE_PY_CONTROLLER": "1",
}


def _make_anomaly_body():
    # defined inside a factory so cloudpickle ships it by value (the
    # worker can't import the tests package)
    def _anomaly_body():
        import jax.numpy as jnp

        import horovod_tpu as hvt
        from horovod_tpu.obs import anomaly as _anomaly

        hvt.init()
        assert _anomaly.ACTIVE is True
        # async ops: issuance goes through the eager controller, whose
        # coordinator records per-op arrival skew (the straggler feed);
        # sync collectives never announce and leave no skew trail
        for i in range(24):
            h = hvt.allreduce_async(jnp.ones((256,), jnp.float32),
                                    name=f"g{i}")
            hvt.synchronize(h)
        eng = _anomaly.get_engine()
        counts = eng.counts() if eng else {}
        blamed = sorted({r for i in (eng.incidents() if eng else [])
                         if i["kind"] == "straggler" for r in i["ranks"]})
        hvt.shutdown()
        return (counts, blamed)

    return _anomaly_body


@pytest.mark.multiprocess
def test_straggler_incident_names_rank_2proc():
    """Chaos: a 300 ms pre-collective (issuance-boundary) delay on
    rank 1, after 9 healthy collectives establish the baseline, raises
    a straggler incident blaming exactly rank 1 on the coordinator."""
    env = dict(
        _ENV, **_ANOMALY_ENV,
        HVTPU_FAULT_SPEC="collective.pre:delay(300)@rank=1,count=10",
    )
    results = run(_make_anomaly_body(), np=2, cpu_devices=1, env=env,
                  start_timeout=300.0)
    counts0, blamed0 = results[0]
    assert counts0.get("straggler", 0) >= 1, counts0
    assert blamed0 == [1], blamed0


@pytest.mark.multiprocess
def test_clean_control_run_raises_zero_incidents_2proc():
    """Control: the same job with no fault injected must stay silent —
    the detector's floor absorbs healthy loopback jitter."""
    env = dict(_ENV, **_ANOMALY_ENV)
    results = run(_make_anomaly_body(), np=2, cpu_devices=1, env=env,
                  start_timeout=300.0)
    for counts, blamed in results:
        assert counts == {}, counts
        assert blamed == []


@pytest.mark.multiprocess
def test_worker_kill_leaves_postmortems_both_ranks_2proc(tmp_path):
    """Chaos: both workers die at their 2nd step; each flight recorder
    dumps a fault_kill postmortem on the way down, and hvtputrace
    fuses them into one two-rank timeline."""

    def body():
        import jax.numpy as jnp

        import horovod_tpu as hvt
        from horovod_tpu.elastic import worker as _worker

        hvt.init()
        for i in range(4):
            # lockstep barrier: without it a lagging rank can still be
            # steps behind when the other rank's kill tears down the
            # coordination service, dying collaterally BEFORE its own
            # kill site dumps the postmortem this test asserts on
            hvt.allreduce(jnp.ones(()), name=f"step-{i}")
            _worker.note_step()
        hvt.shutdown()
        return "survived"  # unreachable: the kill fires at step 2

    env = dict(
        _ENV,
        HVTPU_FLIGHT_DIR=str(tmp_path),
        HVTPU_FAULT_SPEC="worker.step:kill@rank=0|1,count=2",
    )
    with pytest.raises(RunError):
        run(body, np=2, cpu_devices=1, env=env, start_timeout=300.0)

    for rank in (0, 1):
        doc = _read_json(tmp_path / f"postmortem-{rank}-0.json")
        assert doc["schema"] == flight.POSTMORTEM_SCHEMA
        assert doc["reason"] == "fault_kill"
        assert doc["rank"] == rank
        assert doc["detail"]["site"] == "worker.step"
        assert any(e["kind"] == "flight_start" for e in doc["events"])

    from tools.hvtputrace.__main__ import main
    assert main(["postmortem", str(tmp_path)]) == 0
    rep = hvtputrace.postmortem_merge(str(tmp_path))
    assert rep["ranks"] == [0, 1]
    assert {e["rank"] for e in rep["timeline"]} == {0, 1}
