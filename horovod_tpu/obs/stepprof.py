"""Step-level compute/communication overlap profiler.

Every scaling verdict in this repo used to lean on an *assumed*
overlap budget (BENCH_SCALING's 0.5, bench.py's hand-tabulated FLOPs).
This module measures instead of modeling, by joining the two timelines
the repo already produces but never correlated:

  * the **XLA device profile** — ``obs/profile.load_profile`` parses
    ``*.xplane.pb`` into timestamped per-op intervals, with wire
    collectives flagged (``is_comm_op``); this is device truth for
    what the chip was doing and when, and
  * the **hvtpu distributed trace** — per-collective EXEC spans and
    DATA_WAIT spans from ``obs/tracing.py`` plus the step-boundary
    instants this module emits through ``metrics.note_step``.

Per step window it computes a six-way wall decomposition by interval
algebra (:func:`decompose`)::

    pure compute | overlapped comm | EXPOSED comm | data wait
                 | host/controller gap | idle

whose parts sum to the step wall time by construction.  The measured
overlap fraction is ``overlapped / (overlapped + exposed)`` and the
measured MFU numerator comes from the compiled program's own
``cost_analysis()`` FLOPs (:func:`measured_flops`), not a per-model
constant.

Two consumers:

  * **runtime collector** (this module, always-on unless
    ``HVTPU_STEPPROF=0``): collective dispatch windows and data-pipeline
    waits feed per-step metrics ``hvtpu_step_exposed_comm_seconds``,
    ``hvtpu_step_overlap_fraction``, ``hvtpu_mfu`` and a ``stepprof``
    /debug provider.  Without a device profile the host cannot see
    overlap, so the per-step comm time is reported as exposed (an
    upper bound — exact for the sync data plane, which blocks the
    host); :func:`join_device_profile` upgrades it to device truth
    after a ``profile.trace`` capture.
  * **offline analysis** — ``python -m tools.hvtputrace overlap``
    performs the same join over merged rank traces + an optional
    xplane dir, rendering per-rank decomposition tables.

Hot call sites guard with ``if stepprof.ACTIVE:`` (one module
attribute read, same contract as ``tracing.ACTIVE``/``faults.ACTIVE``).
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from collections import deque
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from . import metrics as obs_metrics
from . import profile as obs_profile
from . import tracing

Interval = Tuple[float, float]

# ---------------------------------------------------------------------------
# knobs
# ---------------------------------------------------------------------------

# HVTPU_STEPPROF=0 disables the runtime collector entirely (call sites
# fall back to one attribute read).
ACTIVE = os.environ.get("HVTPU_STEPPROF", "1").lower() not in (
    "0", "false", "off")

# HVTPU_STEPPROF_PEAK_TFLOPS: per-chip peak for the MFU denominator
# (default: v5e bf16 197 TFLOP/s).
PEAK_TFLOPS = float(os.environ.get("HVTPU_STEPPROF_PEAK_TFLOPS", "197"))

# HVTPU_STEPPROF_WINDOW: max collective/data windows retained between
# step boundaries (bounds collector memory on pathological loops).
_WINDOW = int(os.environ.get("HVTPU_STEPPROF_WINDOW", "4096"))


def peak_flops() -> float:
    """Per-chip peak FLOP/s used as the MFU denominator."""
    return PEAK_TFLOPS * 1e12


# ---------------------------------------------------------------------------
# interval algebra (timestamps are floats; unit is the caller's — the
# runtime collector uses wall seconds, hvtputrace uses trace µs)
# ---------------------------------------------------------------------------


def union(ivs: Iterable[Interval]) -> List[Interval]:
    """Merge intervals into a sorted, disjoint cover."""
    out: List[Interval] = []
    for t0, t1 in sorted((a, b) for a, b in ivs if b > a):
        if out and t0 <= out[-1][1]:
            if t1 > out[-1][1]:
                out[-1] = (out[-1][0], t1)
        else:
            out.append((t0, t1))
    return out


def intersect(a: Sequence[Interval], b: Sequence[Interval]
              ) -> List[Interval]:
    """Intersection of two disjoint sorted interval lists."""
    out: List[Interval] = []
    i = j = 0
    while i < len(a) and j < len(b):
        t0 = max(a[i][0], b[j][0])
        t1 = min(a[i][1], b[j][1])
        if t1 > t0:
            out.append((t0, t1))
        if a[i][1] < b[j][1]:
            i += 1
        else:
            j += 1
    return out


def subtract(a: Sequence[Interval], b: Sequence[Interval]
             ) -> List[Interval]:
    """``a − b`` over disjoint sorted interval lists."""
    out: List[Interval] = []
    j = 0
    for t0, t1 in a:
        cur = t0
        while j < len(b) and b[j][1] <= cur:
            j += 1
        k = j
        while k < len(b) and b[k][0] < t1:
            if b[k][0] > cur:
                out.append((cur, b[k][0]))
            cur = max(cur, b[k][1])
            k += 1
        if cur < t1:
            out.append((cur, t1))
    return out


def total(ivs: Iterable[Interval]) -> float:
    return sum(t1 - t0 for t0, t1 in ivs)


def clip(ivs: Iterable[Interval], t0: float, t1: float) -> List[Interval]:
    return intersect(union(ivs), [(t0, t1)])


def decompose(t0: float, t1: float, *,
              compute: Iterable[Interval] = (),
              comm: Iterable[Interval] = (),
              data: Iterable[Interval] = (),
              host: Iterable[Interval] = ()) -> dict:
    """Six-way wall decomposition of the step window ``[t0, t1)``.

    Priority order resolves multi-bucket instants: comm∩compute is
    *overlapped* comm; comm alone is *exposed*; data and host windows
    only count where neither device timeline is busy; the remainder is
    idle.  Invariant (pinned by tests/test_stepprof.py)::

        compute + overlapped + exposed + data_wait + host + idle
            == step_wall
    """
    if t1 < t0:
        t0, t1 = t1, t0
    window = [(t0, t1)]
    comp_u = intersect(union(compute), window)
    comm_u = intersect(union(comm), window)
    overlapped = intersect(comp_u, comm_u)
    pure = subtract(comp_u, comm_u)
    exposed = subtract(comm_u, comp_u)
    busy = union(list(comp_u) + list(comm_u))
    data_w = subtract(intersect(union(data), window), busy)
    not_attributed = union(list(busy) + list(data_w))
    host_w = subtract(intersect(union(host), window), not_attributed)
    wall = t1 - t0
    parts = {
        "compute": total(pure),
        "overlapped_comm": total(overlapped),
        "exposed_comm": total(exposed),
        "data_wait": total(data_w),
        "host": total(host_w),
    }
    parts["idle"] = max(wall - sum(parts.values()), 0.0)
    comm_total = parts["overlapped_comm"] + parts["exposed_comm"]
    parts["step_wall"] = wall
    parts["overlap_fraction"] = (
        parts["overlapped_comm"] / comm_total if comm_total > 0 else None)
    return parts


def exposed_span(span: Interval, compute_u: Sequence[Interval]) -> float:
    """Exposed (non-compute-overlapped) time of one comm span — the
    per-collective blame number behind the overlap report's top-N."""
    return total(subtract(union([span]), compute_u))


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------

EXPOSED_COMM = obs_metrics.REGISTRY.histogram(
    "hvtpu_step_exposed_comm_seconds",
    "Per-step exposed (not compute-overlapped) communication time. "
    "Host-side collection reports the union of collective dispatch "
    "windows per step (an upper bound; exact for the blocking sync "
    "plane); a device-profile join (stepprof.join_device_profile / "
    "hvtputrace overlap) measures it against the XLA op timeline.",
    buckets=obs_metrics.DEFAULT_TIME_BUCKETS)
OVERLAP_FRACTION = obs_metrics.REGISTRY.gauge(
    "hvtpu_step_overlap_fraction",
    "Measured comm/compute overlap fraction "
    "(overlapped / (overlapped + exposed)) from the most recent "
    "device-profile join; 0 until a join has run.")
MFU = obs_metrics.REGISTRY.gauge(
    "hvtpu_mfu",
    "Measured model FLOPs utilization: cost_analysis() FLOPs per step "
    "/ (step wall time x HVTPU_STEPPROF_PEAK_TFLOPS peak). 0 until "
    "the host loop provides step FLOPs (stepprof.set_step_flops).")


# ---------------------------------------------------------------------------
# runtime collector
# ---------------------------------------------------------------------------


class _Collector:
    """Per-process overlap collector.

    Fed from three places: ``comm/eager.py`` (collective dispatch
    windows, executor and sync threads), ``data/loader.py`` (input
    waits, loader threads), and ``metrics.note_step`` (step boundaries,
    host loop) — hence the lock.
    """

    def __init__(self):
        self._lock = threading.Lock()
        # hvtpulint: guarded-by(_lock)
        self._comm: deque = deque(maxlen=_WINDOW)
        # hvtpulint: guarded-by(_lock)
        self._data: deque = deque(maxlen=_WINDOW)
        self._step_t: Optional[float] = None  # hvtpulint: guarded-by(_lock)
        self._steps = 0  # hvtpulint: guarded-by(_lock)
        self._flops_per_step: Optional[float] = None
        self._last: dict = {}  # hvtpulint: guarded-by(_lock)

    def note_comm(self, name: str, t0: float, t1: float, nbytes: int = 0):
        with self._lock:
            self._comm.append((t0, t1, name, nbytes))

    def note_data_wait(self, t0: float, t1: float):
        with self._lock:
            self._data.append((t0, t1))

    def set_step_flops(self, flops: Optional[float]):
        with self._lock:
            self._flops_per_step = flops

    @staticmethod
    def _windows_since(ring, prev: float, now: float) -> list:
        """Windows of ``ring`` overlapping ``(prev, now)``, scanning
        newest-first and stopping once the ring is clearly older than
        the step.  Entries append in ~completion order, so instead of
        filtering all ``maxlen`` (4096) entries every step we bail
        after a slack run of pre-``prev`` windows; the slack absorbs
        mild cross-thread append reordering."""
        out = []
        stale = 0
        for item in reversed(ring):
            t0, t1 = item[0], item[1]
            if t1 > prev:
                stale = 0
                if t0 < now:
                    out.append((t0, t1))
            else:
                stale += 1
                if stale >= 64:
                    break
        out.reverse()
        return out

    def note_step_boundary(self, steps: float = 1.0) -> Optional[dict]:
        """Close the step window ending now; emit per-step metrics.

        Called (via ``metrics.note_step``) once per host-loop dispatch;
        ``steps`` is the optimizer steps folded into the dispatch
        (lax.scan loops).  Without a device profile the comm union is
        reported as exposed — the host-side upper bound.  Returns the
        step record (also kept as ``last_step`` in the /debug state) —
        the feed for the anomaly detectors and the flight ring — or
        None on the first/degenerate boundary.
        """
        now = time.time()
        if tracing.ACTIVE:
            # Every boundary is marked — including the first, which
            # opens the first step window for hvtputrace overlap.
            tracing.step_boundary(wall_us=now * 1e6, steps=steps)
        with self._lock:
            prev = self._step_t
            self._step_t = now
            self._steps += steps
            if prev is None or now <= prev:
                return None
            # Windows stay in the ring (join_device_profile reads them
            # across step boundaries); the step only counts overlap
            # with its own window, so stale entries age out via maxlen
            # without double counting.
            comm = self._windows_since(self._comm, prev, now)
            data = self._windows_since(self._data, prev, now)
            flops = self._flops_per_step
        parts = decompose(prev, now, comm=comm, data=data)
        EXPOSED_COMM.observe(parts["exposed_comm"])
        wall = now - prev
        if flops:
            MFU.set(flops * steps / (wall * peak_flops()))
        rec = {
            "step_wall_s": round(wall, 6),
            "steps": steps,
            "exposed_comm_s": round(parts["exposed_comm"], 6),
            "data_wait_s": round(parts["data_wait"], 6),
            "collectives": len(comm),
        }
        with self._lock:
            self._last = rec
        return rec

    def debug_state(self) -> dict:
        with self._lock:
            return {
                "active": ACTIVE,
                "steps": self._steps,
                "flops_per_step": self._flops_per_step,
                "peak_tflops": PEAK_TFLOPS,
                "overlap_fraction": OVERLAP_FRACTION.value(),
                "mfu": MFU.value(),
                "last_step": dict(self._last),
                "pending_comm_windows": len(self._comm),
            }


_collector = _Collector()


def note_comm(name: str, t0: float, t1: float, nbytes: int = 0):
    """Record one collective's wall-clock dispatch window (seconds)."""
    _collector.note_comm(name, t0, t1, nbytes)


def note_data_wait(t0: float, t1: float):
    """Record one input-pipeline wait window (wall seconds)."""
    _collector.note_data_wait(t0, t1)


def note_step_boundary(steps: float = 1.0) -> Optional[dict]:
    return _collector.note_step_boundary(steps)


def set_step_flops(flops: Optional[float]):
    """Provide the per-step per-chip FLOPs numerator for the live
    ``hvtpu_mfu`` gauge (from :func:`measured_flops`)."""
    _collector.set_step_flops(flops)


def get_collector() -> _Collector:
    return _collector


def install():
    """Register the /debug provider (idempotent; core/state.init)."""
    obs_metrics.register_debug_provider(
        "stepprof", lambda: _collector.debug_state())


def uninstall():
    obs_metrics.unregister_debug_provider("stepprof")


def reset():
    """Fresh collector (tests / re-init)."""
    global _collector
    _collector = _Collector()


# ---------------------------------------------------------------------------
# measured MFU: FLOPs from the compiled program itself
# ---------------------------------------------------------------------------


def measured_flops(compiled) -> Optional[float]:
    """Total FLOPs of one execution of a compiled jax program, read
    from XLA's own cost model: ``jit(f).lower(...).compile()`` →
    ``cost_analysis()``.  Returns None when the backend exposes no
    cost analysis (some plugin runtimes) — callers fall back to their
    analytic estimate, never crash.
    """
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    # jax has returned both a per-device list of dicts and a bare dict
    # across versions.
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    flops = ca.get("flops")
    try:
        flops = float(flops)
    except (TypeError, ValueError):
        return None
    return flops if flops > 0 else None


def mfu(flops_per_step: Optional[float], step_seconds: float,
        peak: Optional[float] = None) -> Optional[float]:
    """MFU from measured FLOPs and measured step time."""
    if not flops_per_step or step_seconds <= 0:
        return None
    return flops_per_step / (step_seconds * (peak or peak_flops()))


# ---------------------------------------------------------------------------
# device-profile join
# ---------------------------------------------------------------------------

# Device timestamps are joined on the wall clock when they look like
# epoch time; profilers that emit boot-relative or trace-relative
# timestamps are re-anchored onto the observed comm windows instead.
_CLOCK_SANITY_US = 86400e6  # 1 day


def align_device_intervals(intervals: List[dict],
                           anchor_us: float) -> Tuple[List[dict], float]:
    """Map device-profile intervals onto the caller's timebase.

    If the device timestamps are within a day of ``anchor_us`` they are
    already wall-clock and pass through; otherwise the whole device
    timeline is shifted so its first event lands on the anchor.
    Returns (intervals, shift_us).
    """
    if not intervals:
        return intervals, 0.0
    first = min(iv["t0_us"] for iv in intervals)
    if abs(first - anchor_us) <= _CLOCK_SANITY_US:
        return intervals, 0.0
    shift = anchor_us - first
    return [dict(iv, t0_us=iv["t0_us"] + shift,
                 t1_us=iv["t1_us"] + shift)
            for iv in intervals], shift


@contextlib.contextmanager
def profile_window(logdir: str):
    """Capture an XLA device profile around the body, then join it
    against the collector's recorded comm windows: yields a dict that
    is filled with the join summary on exit."""
    result: dict = {}
    with obs_profile.trace(logdir):
        t0 = time.time()
        yield result
        t1 = time.time()
    result.update(join_device_profile(logdir, window=(t0, t1)))


def join_device_profile(logdir: str,
                        window: Optional[Interval] = None) -> dict:
    """Join a captured xplane against the collector's comm windows and
    publish the measured overlap fraction.

    Returns ``{"status", "overlap_fraction", "exposed_comm_s",
    "overlapped_comm_s", "compute_s", "device_planes"}``; status is
    passed through from :func:`obs_profile.load_profile` (never
    raises — "no-profile"/"empty"/"truncated" leave the gauges alone).
    """
    prof = obs_profile.load_profile(logdir)
    if prof["status"] != "ok":
        return {"status": prof["status"], "reason": prof["reason"],
                "overlap_fraction": None}
    with _collector._lock:
        host_comm_us = [(t0 * 1e6, t1 * 1e6)
                        for t0, t1, _n, _b in _collector._comm]
    compute_us: List[Interval] = []
    comm_us: List[Interval] = []
    anchor = (window[0] * 1e6 if window
              else (host_comm_us[0][0] if host_comm_us else None))
    for _pname, ivs in sorted(prof["planes"].items()):
        if anchor is not None:
            ivs, _shift = align_device_intervals(ivs, anchor)
        for iv in ivs:
            (comm_us if iv["comm"] else compute_us).append(
                (iv["t0_us"], iv["t1_us"]))
    if not comm_us:
        # the device saw no collectives: fall back to host windows so
        # single-plane captures still yield an overlap number
        comm_us = host_comm_us
    comp_u = union(compute_us)
    comm_u = union(comm_us)
    if window is not None:
        w0, w1 = window[0] * 1e6, window[1] * 1e6
        comp_u = clip(comp_u, w0, w1)
        comm_u = clip(comm_u, w0, w1)
    overlapped = total(intersect(comp_u, comm_u))
    exposed = total(subtract(comm_u, comp_u))
    frac = (overlapped / (overlapped + exposed)
            if (overlapped + exposed) > 0 else None)
    if frac is not None:
        OVERLAP_FRACTION.set(frac)
    return {
        "status": "ok",
        "overlap_fraction": frac,
        "overlapped_comm_s": overlapped / 1e6,
        "exposed_comm_s": exposed / 1e6,
        "compute_s": total(comp_u) / 1e6,
        "device_planes": sorted(prof["planes"]),
    }
