"""Admission control above the priority tiers: per-tenant quotas,
weighted fair-share deficit accounting, and the starvation guard.

Tenancy config lives in ``<fleet_dir>/tenants.json`` and is
hot-reloaded by mtime every arbiter tick::

    {
      "acme":  {"weight": 2.0, "max_ranks": 64, "max_queued": 8},
      "guest": {"weight": 0.5, "max_ranks": 8},
      "*":     {"weight": 1.0}
    }

- ``weight`` (number > 0, default 1): the tenant's fair share of the
  pool is ``weight / sum(weights of tenants with live jobs)``.
- ``max_ranks`` (int >= 0): cap on the tenant's CONCURRENT allocated
  ranks.  Enforced at job-start time only — hot-reloading a quota
  below a tenant's current usage never kills running jobs, it just
  gates new starts until usage drains below the cap.
- ``max_queued`` (int >= 0): cap on the tenant's queued (PENDING)
  jobs, enforced at intake; over-quota submissions are rejected with
  the tenant and quota named.
- ``"*"`` is the default row for tenants without an explicit entry
  (absent: unlimited, weight 1).

Malformed config is rejected field-by-field à la
:class:`~.job.FleetSpecError` — a broken reload keeps the previous
table in force (the arbiter surfaces the error) rather than dropping
all quotas on the floor.

Fair share: among same-priority pending jobs the arbiter schedules the
tenant FURTHEST BELOW its share first (largest deficit =
``share - used_ranks``), so a burst from one tenant cannot lock out
the others within a tier.

Starvation guard: a pending job older than
``HVTPU_FLEET_STARVATION_SECONDS`` is *aged* — it sorts ahead of every
un-aged tier and may preempt as if it outranked all running jobs — so
a min-priority tenant's queue wait under sustained higher-tier load is
bounded by the threshold plus one drain-grace + relaunch cycle.

Thread safety: instances are owned by the arbiter and only touched
under its ``_lock``.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional

from ..obs import metrics as obs_metrics

__all__ = ["AdmissionController", "TenantConfigError", "TenantPolicy",
           "starvation_s", "M_REJECTS"]

M_REJECTS = obs_metrics.counter(
    "hvtpu_fleet_admission_rejections_total",
    "Submissions refused by the fleet front door (label: reason = "
    "queue_full | tenant_queued_quota | spec_invalid | "
    "duplicate_name | corrupt_record).")

_TENANT_FIELDS = ("weight", "max_ranks", "max_queued")
DEFAULT_TENANT = "default"


def starvation_s() -> float:
    """Age at which a pending job is boosted past every tier (0
    disables the guard)."""
    try:
        v = float(os.environ.get("HVTPU_FLEET_STARVATION_SECONDS",
                                 "900") or 900)
    except ValueError:
        v = 900.0
    return max(0.0, v)


class TenantConfigError(ValueError):
    """One tenants.json field is malformed; names tenant and field."""

    def __init__(self, tenant: str, field: str, message: str):
        self.tenant = tenant
        self.field = field
        super().__init__(f"tenant {tenant!r}: field {field!r}: "
                         f"{message}")


class TenantPolicy:
    """One tenant's validated quota row."""

    def __init__(self, name: str, *, weight: float = 1.0,
                 max_ranks: Optional[int] = None,
                 max_queued: Optional[int] = None):
        self.name = name
        self.weight = weight
        self.max_ranks = max_ranks
        self.max_queued = max_queued

    @classmethod
    def from_dict(cls, name: str, d: dict) -> "TenantPolicy":
        if not isinstance(d, dict):
            raise TenantConfigError(name, "row",
                                    "must be an object of quota fields")
        unknown = sorted(set(d) - set(_TENANT_FIELDS))
        if unknown:
            raise TenantConfigError(
                name, unknown[0],
                f"unknown field (known: {', '.join(_TENANT_FIELDS)})")
        weight = d.get("weight", 1.0)
        if not isinstance(weight, (int, float)) or isinstance(
                weight, bool) or not weight > 0:
            raise TenantConfigError(name, "weight",
                                    f"must be a number > 0, got "
                                    f"{weight!r}")
        out = {"weight": float(weight)}
        for field in ("max_ranks", "max_queued"):
            v = d.get(field)
            if v is None:
                continue
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                raise TenantConfigError(
                    field=field, tenant=name,
                    message=f"must be an integer >= 0, got {v!r}")
            out[field] = v
        return cls(name, **out)

    def to_dict(self) -> dict:
        d = {"weight": self.weight}
        if self.max_ranks is not None:
            d["max_ranks"] = self.max_ranks
        if self.max_queued is not None:
            d["max_queued"] = self.max_queued
        return d


def _parse(raw: dict) -> Dict[str, TenantPolicy]:
    if not isinstance(raw, dict):
        raise TenantConfigError("*", "root",
                                "tenants.json must be an object of "
                                "tenant rows")
    return {name: TenantPolicy.from_dict(name, row)
            for name, row in sorted(raw.items())}


class AdmissionController:
    """Hot-reloaded tenant table + quota/fair-share arithmetic."""

    def __init__(self, fleet_dir: Optional[str] = None):
        self.path = (os.path.join(fleet_dir, "tenants.json")
                     if fleet_dir else None)
        self._table: Dict[str, TenantPolicy] = {}
        self._mtime: Optional[float] = None
        self.last_error: Optional[str] = None

    # -- config ----------------------------------------------------------
    def maybe_reload(self) -> Optional[str]:
        """Reload tenants.json when its mtime changed.  Returns
        "reloaded" / an error string / None (unchanged).  A broken
        file keeps the previous table in force."""
        if not self.path:
            return None
        try:
            mtime = os.stat(self.path).st_mtime
        except OSError:
            if self._table or self._mtime is not None:
                self._table, self._mtime = {}, None
                return "reloaded"
            return None
        if mtime == self._mtime:
            return None
        self._mtime = mtime
        try:
            with open(self.path) as f:
                raw = json.load(f)
            self._table = _parse(raw)
        except (OSError, ValueError) as e:
            self.last_error = str(e)
            return f"tenants.json rejected (previous table kept): {e}"
        self.last_error = None
        return "reloaded"

    def load_dict(self, raw: dict) -> None:
        """Install a table directly (tests, sim) — same validation."""
        self._table = _parse(raw)

    def policy(self, tenant: str) -> TenantPolicy:
        p = self._table.get(tenant) or self._table.get("*")
        return p if p is not None else TenantPolicy(tenant)

    # -- quota checks ----------------------------------------------------
    def check_queued(self, tenant: str, queued_now: int
                     ) -> Optional[str]:
        """None when admissible; else a rejection naming tenant and
        quota.  ``queued_now`` counts the tenant's PENDING jobs before
        this submission."""
        p = self.policy(tenant)
        if p.max_queued is not None and queued_now >= p.max_queued:
            return (f"tenant {tenant!r} over quota: {queued_now} jobs "
                    f"already queued (max_queued={p.max_queued})")
        return None

    def check_start(self, tenant: str, used_ranks: int,
                    want_ranks: int) -> Optional[str]:
        """Gate a job start against the concurrent-ranks quota; never
        applied to already-running jobs (shrinking a quota below
        current usage only blocks NEW starts)."""
        p = self.policy(tenant)
        if (p.max_ranks is not None
                and used_ranks + want_ranks > p.max_ranks):
            return (f"tenant {tenant!r} over quota: {used_ranks} ranks "
                    f"in use + {want_ranks} wanted > "
                    f"max_ranks={p.max_ranks}")
        return None

    # -- fair share ------------------------------------------------------
    def deficits(self, used_by_tenant: Dict[str, int],
                 slots_total: int) -> Dict[str, float]:
        """Per-tenant ``share - used``: positive means the tenant is
        below its weighted share of the pool.  Tenants are the keys of
        ``used_by_tenant`` (every tenant with a live job, at 0 use)."""
        if not used_by_tenant:
            return {}
        total_w = sum(self.policy(t).weight for t in used_by_tenant)
        if total_w <= 0:
            return {t: 0.0 for t in used_by_tenant}
        return {t: (self.policy(t).weight / total_w) * slots_total
                   - used
                for t, used in used_by_tenant.items()}

    def debug_state(self) -> dict:
        return {"tenants": {n: p.to_dict()
                            for n, p in sorted(self._table.items())},
                "error": self.last_error}
