"""Async handles for eager collectives.

Parity surface: the handle table of the reference torch binding
(``horovod/torch/handle_manager.cc`` + ``synchronize``/``poll`` in
horovod/torch/mpi_ops.py).

On TPU the XLA runtime is already asynchronous: every jax op returns a
future-like ``jax.Array`` immediately and blocks only when the host
reads it.  So an async handle is just the undelivered array plus a
completion probe, and ``synchronize`` is ``block_until_ready`` — the
background-thread machinery of the reference collapses into the runtime.
The mini-controller (horovod_tpu.eager) plugs in here when cross-process
enqueue-order negotiation is enabled.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional

import jax


class HandleManager:
    def __init__(self):
        self._lock = threading.Lock()
        self._next = 0
        self._results: Dict[int, Any] = {}

    def allocate(self, value) -> int:
        with self._lock:
            h = self._next
            self._next += 1
            self._results[h] = value
            return h

    def synchronize(self, handle: int):
        with self._lock:
            if handle not in self._results:
                raise ValueError(f"unknown or already-synchronized handle {handle}")
            value = self._results.pop(handle)
        if hasattr(value, "result") and hasattr(value, "done"):
            # Controller future (horovod_tpu.eager.OpFuture, duck-typed
            # to avoid the import cycle): block on negotiation+execution.
            value = value.result()
        elif callable(value):
            value = value()
        return jax.block_until_ready(value)

    def poll(self, handle: int) -> bool:
        with self._lock:
            value = self._results.get(handle)
        if value is None:
            return True  # unknown / already-synchronized handles are done
        if hasattr(value, "result") and hasattr(value, "done"):
            return bool(value.done())
        if callable(value):
            return False
        # value may be a pytree (e.g. alltoall's (tensor, splits) pair):
        # done only when every array leaf has landed.
        for leaf in jax.tree_util.tree_leaves(value):
            is_ready = getattr(leaf, "is_ready", None)
            if is_ready is not None and not is_ready():
                return False
        return True


_manager = HandleManager()


def manager() -> HandleManager:
    return _manager
