"""horovod_tpu — a TPU-native data-parallel training framework with the
capabilities of Horovod (reference: sj6077/horovod), rebuilt on
JAX/XLA/Pallas.

Public surface parity (reference: horovod/torch/__init__.py,
horovod/common/basics.py ``HorovodBasics``): ``init``, ``shutdown``,
``rank``/``size``/``local_rank``/..., eager collectives
(``allreduce``/``allgather``/``broadcast``/``alltoall``/
``reducescatter`` + async/grouped variants), ``DistributedOptimizer``,
``Compression``, ``ProcessSet``, elastic training, plus the SPMD layer
(``horovod_tpu.spmd``) that is the TPU-idiomatic hot path inside
jit/shard_map.

Typical JAX use::

    import horovod_tpu as hvt
    hvt.init()
    mesh = hvt.world_mesh()
    tx = hvt.DistributedOptimizer(optax.sgd(0.1), axis_name="world")
    # ... jit a shard_map train step over `mesh`; gradients are
    # bucket-fused and psum'd over ICI inside the compiled program.
"""

from __future__ import annotations

from typing import Optional

import jax

if not hasattr(jax, "shard_map"):
    # jax < 0.5 only ships shard_map under experimental, with the
    # replication check spelled check_rep instead of check_vma; alias
    # the modern surface so every call site (and user code written
    # against it) runs on both.
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def _shard_map_compat(f=None, /, **kw):
        if "check_vma" in kw:
            kw["check_rep"] = kw.pop("check_vma")
        if f is None:
            return lambda g: _exp_shard_map(g, **kw)
        return _exp_shard_map(f, **kw)

    jax.shard_map = _shard_map_compat

if not hasattr(jax.lax, "axis_size"):
    # jax < 0.5 has no lax.axis_size; core.axis_frame(name) resolves
    # the bound size of a mesh axis at trace time there.
    jax.lax.axis_size = lambda axis_name: jax.core.axis_frame(axis_name)

from . import comm, core
from . import data  # noqa: F401  (elastic-aware input pipeline)
from . import elastic  # noqa: F401  (hvt.elastic.State/run parity surface)
from .api import functions as _functions
from .api import optimizer as _optimizer
from .api.handles import manager as _handle_manager
from .comm import eager as _eager
from .comm import spmd
from .comm.compression import Compression
from .comm.stall import stall_guard  # noqa: F401  (jit-plane watchdog)
from .comm.reduce_ops import Adasum, Average, Max, Min, Product, ReduceOp, Sum
from .core import (
    Config,
    HorovodInternalError,
    HorovodTpuError,
    HostsUpdatedInterrupt,
    HvtpuDivergenceError,
    HvtpuMismatchError,
    ProcessSet,
    add_process_set,
    remove_process_set,
)
from .core import state as _state
from .version import __version__

# ---------------------------------------------------------------------------
# lifecycle (parity: horovod_init / horovod_shutdown / HorovodBasics)
# ---------------------------------------------------------------------------

def init(config: Optional[Config] = None):
    """Initialize horovod_tpu (idempotent).

    Rank ↔ process ↔ device model (pod shape):

    * One **Horovod rank = one process**: ``rank()``/``size()`` count
      processes, exactly like the reference (``hvd.rank/size``).  A
      process may own **several accelerator devices** (the usual TPU
      pod shape: P hosts × D chips each).
    * The **jit/SPMD path** (``world_mesh()`` + ``shard_map`` +
      ``DistributedOptimizer(axis_name=...)``) spans ALL
      ``jax.device_count()`` devices — the same jitted program runs on
      every process and XLA executes per-host partitions over the
      global mesh.  This is the flagship path and uses every chip.
    * The **eager path** (``allreduce``/``allgather``/... on concrete
      arrays) is PROCESS-granularity: each process contributes one
      tensor, carried on its designated transport device (the first
      local device).  With D>1 local devices the other devices are
      simply not participants of eager collectives — they are the
      jit path's compute surface, not extra eager ranks.  ``init()``
      logs this at INFO when it detects D>1.
    """
    return _state.init(config)


def shutdown():
    _state.shutdown()


def is_initialized() -> bool:
    return _state.initialized()


def rank() -> int:
    return _state.require_init("rank()").rank


def size() -> int:
    return _state.require_init("size()").size


def local_rank() -> int:
    return _state.require_init("local_rank()").local_rank


def local_size() -> int:
    return _state.require_init("local_size()").local_size


def cross_rank() -> int:
    return _state.require_init("cross_rank()").cross_rank


def cross_size() -> int:
    return _state.require_init("cross_size()").cross_size


def is_homogeneous() -> bool:
    """True when every host runs the same number of ranks (parity:
    ``hvd.is_homogeneous``).  Upstream allgathers local sizes; here a
    single-host world (``cross_size == 1``) is provably homogeneous
    from held state, and multi-host worlds rely on the launcher's
    uniformity certificate (``HVTPU_UNIFORM_LOCAL_SIZE``)."""
    st = _state.require_init("is_homogeneous()")
    if st.size == 1 or st.cross_size == 1:
        return True
    return bool(st.config and st.config.uniform_local_size > 0)


def __getattr__(name: str):
    # PEP 562: `hvt.global_process_set` mirrors the reference's
    # module-level attribute (horovod/common/process_sets.py) while
    # resolving to the LIVE table entry, which only exists after init.
    # Must raise AttributeError (never NotInitializedError) so
    # hasattr/getattr-with-default probes keep their contract.
    if name == "global_process_set":
        if not _state.initialized():
            raise AttributeError(
                "global_process_set is available after hvt.init()"
            )
        return _state.global_state().process_set_table.global_process_set
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def num_devices() -> int:
    """Total accelerator devices in the job (devices ≠ ranks on TPU:
    one process drives many chips)."""
    return _state.require_init("num_devices()").topology.num_devices


def local_devices():
    return jax.local_devices()


def world_mesh():
    """The flat 1-D device mesh (axis ``world``) for SPMD programs."""
    return _state.require_init("world_mesh()").topology.world_mesh()


def hierarchical_mesh():
    """(dcn, ici) mesh separating cross-host from intra-slice links."""
    return _state.require_init("hierarchical_mesh()").topology.hierarchical_mesh()


def mesh(axis_names, shape):
    """Arbitrary N-D mesh, e.g. ``hvt.mesh(("dp","tp"), (4, 2))``."""
    return _state.require_init("mesh()").topology.nd_mesh(
        tuple(axis_names), tuple(shape)
    )


# ---------------------------------------------------------------------------
# build/runtime feature probes (parity: basics.py mpi_built/nccl_built/...)
# ---------------------------------------------------------------------------

def mpi_enabled() -> bool:
    return False


def mpi_built() -> bool:
    return False


def mpi_threads_supported() -> bool:
    return False


def gloo_enabled() -> bool:
    return False


def gloo_built() -> bool:
    return False


def nccl_built() -> int:
    return 0


def ddl_built() -> bool:
    return False


def ccl_built() -> bool:
    return False


def cuda_built() -> bool:
    return False


def rocm_built() -> bool:
    return False


def xla_built() -> bool:
    """This framework *is* the XLA backend."""
    return True


def ici_built() -> bool:
    """True when a TPU (ICI-connected) backend is present."""
    try:
        return any(d.platform == "tpu" for d in jax.devices())
    except Exception:
        return False


# ---------------------------------------------------------------------------
# eager collectives (parity: horovod/torch/mpi_ops.py surface)
# ---------------------------------------------------------------------------

def allreduce(
    tensor,
    *,
    op=None,
    average=None,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
    compression=Compression.none,
    process_set=None,
    name: Optional[str] = None,
):
    _state.require_init("allreduce")
    return _eager.allreduce(
        tensor,
        op=op,
        average=average,
        prescale_factor=prescale_factor,
        postscale_factor=postscale_factor,
        compression=compression,
        process_set=process_set,
        name=name,
    )


def grouped_allreduce(tensors, *, op=None, average=None,
                      compression=Compression.none, process_set=None,
                      prescale_factor: float = 1.0,
                      postscale_factor: float = 1.0, name=None):
    """Reduce a list of tensors as one fused unit (parity:
    hvd.grouped_allreduce / group_table.cc).

    Sum/Average fuse into one flat wire buffer; Min/Max/Product/Adasum
    keep per-tensor semantics (matching spmd.grouped_allreduce).
    """
    _state.require_init("grouped_allreduce")
    return _eager.grouped_allreduce(
        tensors, op=op, average=average,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        compression=compression, process_set=process_set,
    )


def allgather(tensor, *, process_set=None, name: Optional[str] = None):
    _state.require_init("allgather")
    return _eager.allgather(tensor, process_set=process_set, name=name)


def broadcast(tensor, root_rank: int = 0, *, process_set=None,
              name: Optional[str] = None):
    _state.require_init("broadcast")
    return _eager.broadcast(tensor, root_rank=root_rank,
                           process_set=process_set, name=name)


def alltoall(tensor, splits=None, *, process_set=None,
             name: Optional[str] = None):
    _state.require_init("alltoall")
    return _eager.alltoall(tensor, splits, process_set=process_set,
                           name=name)


def reducescatter(tensor, *, op=None, process_set=None,
                  name: Optional[str] = None):
    _state.require_init("reducescatter")
    return _eager.reducescatter(tensor, op=op, process_set=process_set,
                                name=name)


def barrier(*, process_set=None):
    _state.require_init("barrier")
    return _eager.barrier(process_set=process_set)


# --- async variants (parity: *_async + synchronize/poll in
# horovod/torch/mpi_ops.py).  Async ops go through the eager
# mini-controller (horovod_tpu.eager): ranks may enqueue in ANY order —
# the controller negotiates an agreed, fused execution schedule each
# cycle, exactly the reference's background-thread semantics.  Sync ops
# (above) bypass it and require identical issuance order across ranks,
# like any SPMD program. ---

def _controller():
    from .eager import get_controller

    return get_controller()


def allreduce_async(tensor, *, op=None, average=None, name=None,
                    compression=Compression.none, process_set=None,
                    prescale_factor: float = 1.0,
                    postscale_factor: float = 1.0):
    _state.require_init("allreduce_async")
    from .comm.reduce_ops import normalize_op

    fut = _controller().enqueue(
        "allreduce", tensor, name=name, op=normalize_op(op, average),
        compression=compression, process_set=process_set,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
    )
    return _handle_manager().allocate(fut)


def grouped_allreduce_async(tensors, *, op=None, average=None, names=None,
                            compression=Compression.none, process_set=None):
    """Async grouped allreduce: the set executes only when every member
    is ready on every rank (parity: group_table.cc)."""
    _state.require_init("grouped_allreduce_async")
    from .comm.reduce_ops import normalize_op

    futs = _controller().grouped_enqueue(
        "allreduce", list(tensors), names=names,
        op=normalize_op(op, average), compression=compression,
        process_set=process_set,
    )
    return [_handle_manager().allocate(f) for f in futs]


def grouped_allgather(tensors, *, process_set=None):
    """Allgather a list of tensors (parity: hvd.grouped_allgather —
    newer-upstream surface; sync form gathers each in order)."""
    _state.require_init("grouped_allgather")
    return [_eager.allgather(t, process_set=process_set) for t in tensors]


def grouped_allgather_async(tensors, *, names=None, process_set=None):
    """Async grouped allgather: executes only when every member is
    ready on every rank (parity: hvd.grouped_allgather_async)."""
    _state.require_init("grouped_allgather_async")
    futs = _controller().grouped_enqueue(
        "allgather", list(tensors), names=names, process_set=process_set,
    )
    return [_handle_manager().allocate(f) for f in futs]


def grouped_reducescatter(tensors, *, op=None, process_set=None):
    """Reducescatter a list of tensors (parity:
    hvd.grouped_reducescatter)."""
    _state.require_init("grouped_reducescatter")
    return [
        _eager.reducescatter(t, op=op, process_set=process_set)
        for t in tensors
    ]


def grouped_reducescatter_async(tensors, *, op=None, names=None,
                                process_set=None):
    """Async grouped reducescatter (parity:
    hvd.grouped_reducescatter_async)."""
    _state.require_init("grouped_reducescatter_async")
    from .comm.reduce_ops import normalize_op

    futs = _controller().grouped_enqueue(
        "reducescatter", list(tensors), names=names,
        op=normalize_op(op, None), process_set=process_set,
    )
    return [_handle_manager().allocate(f) for f in futs]


def allgather_async(tensor, *, name=None, process_set=None):
    _state.require_init("allgather_async")
    fut = _controller().enqueue(
        "allgather", tensor, name=name, process_set=process_set
    )
    return _handle_manager().allocate(fut)


def broadcast_async(tensor, root_rank: int = 0, *, name=None,
                    process_set=None):
    _state.require_init("broadcast_async")
    fut = _controller().enqueue(
        "broadcast", tensor, name=name, root_rank=root_rank,
        process_set=process_set,
    )
    return _handle_manager().allocate(fut)


def alltoall_async(tensor, splits=None, *, name=None, process_set=None):
    _state.require_init("alltoall_async")
    fut = _controller().enqueue(
        "alltoall", tensor, name=name, splits=splits,
        process_set=process_set,
    )
    return _handle_manager().allocate(fut)


def reducescatter_async(tensor, *, op=None, name=None, process_set=None):
    _state.require_init("reducescatter_async")
    from .comm.reduce_ops import normalize_op

    fut = _controller().enqueue(
        "reducescatter", tensor, name=name,
        op=normalize_op(op, None), process_set=process_set,
    )
    return _handle_manager().allocate(fut)


def synchronize(handle: int):
    """Block until an async op completes and return its result."""
    return _handle_manager().synchronize(handle)


def poll(handle: int) -> bool:
    return _handle_manager().poll(handle)


def start_timeline(filename: str, mark_cycles: bool = False):
    """Begin writing a Chrome-trace timeline (parity: hvd.start_timeline)."""
    st = _state.require_init("start_timeline")
    from .obs.timeline import Timeline

    old = st.timeline
    new_tl = Timeline(filename, st.rank, mark_cycles=mark_cycles)
    if old is not None:
        # carry in-flight spans over so their 'E' events land in the
        # new file instead of silently vanishing; close() below writes
        # matching 'E's into the old file
        for name, phase in list(old._open_spans.items()):
            new_tl.begin(name, phase)
    st.timeline = new_tl
    if st.controller is not None:
        # a live eager controller captured the previous timeline (or
        # None) at construction; hand it the new one
        st.controller._timeline = new_tl
    if old is not None:
        old.close()
    return new_tl


def stop_timeline():
    """Stop and flush the timeline (parity: hvd.stop_timeline)."""
    st = _state.require_init("stop_timeline")
    if st.timeline is not None:
        st.timeline.close()
        st.timeline = None
    if st.controller is not None:
        st.controller._timeline = None


def join(device=None) -> int:
    """Signal this rank has no more work this epoch (uneven final
    batches; parity: hvd.join / EnqueueJoin + JoinOp).

    While joined, this rank's controller keeps cycling and contributes
    ZEROS to collectives the remaining ranks run (allreduce: zero
    tensor; allgather/alltoall: zero rows), so their training steps
    complete without stalling.  All ranks must eventually call
    ``join``; it returns the rank that joined last, on every rank.
    """
    st = _state.require_init("join")
    if st.size == 1:
        return 0
    # Dynamic form through the mini-controller: ranks may keep issuing
    # async collectives; join resolves once every rank has joined.
    return int(_controller().join().result())


# ---------------------------------------------------------------------------
# higher-level API
# ---------------------------------------------------------------------------

DistributedOptimizer = _optimizer.DistributedOptimizer
ShardedDistributedOptimizer = _optimizer.ShardedDistributedOptimizer
allreduce_gradients = _optimizer.allreduce_gradients
broadcast_parameters = _functions.broadcast_parameters
broadcast_optimizer_state = _functions.broadcast_optimizer_state
broadcast_object = _functions.broadcast_object
allgather_object = _functions.allgather_object

from .api.checkpoint import (  # noqa: E402
    Checkpointer,
    restore_checkpoint,
    save_checkpoint,
)
from .api.sharded_checkpoint import ShardedCheckpointer  # noqa: E402

__all__ = [
    "__version__",
    "init", "shutdown", "is_initialized",
    "rank", "size", "local_rank", "local_size", "cross_rank", "cross_size",
    "num_devices", "local_devices", "world_mesh", "hierarchical_mesh", "mesh",
    "allreduce", "grouped_allreduce", "allgather", "broadcast", "alltoall",
    "reducescatter", "barrier", "join",
    "grouped_allgather", "grouped_allgather_async",
    "grouped_reducescatter", "grouped_reducescatter_async",
    "allreduce_async", "grouped_allreduce_async", "allgather_async",
    "broadcast_async", "alltoall_async",
    "reducescatter_async", "synchronize", "poll",
    "start_timeline", "stop_timeline",
    "DistributedOptimizer", "ShardedDistributedOptimizer",
    "allreduce_gradients",
    "broadcast_parameters", "broadcast_optimizer_state", "broadcast_object",
    "allgather_object",
    "Checkpointer", "save_checkpoint", "restore_checkpoint",
    "is_homogeneous",
    "ShardedCheckpointer",
    "Compression", "ReduceOp", "Average", "Sum", "Adasum", "Min", "Max",
    "Product",
    "ProcessSet", "add_process_set", "remove_process_set",
    "Config", "HorovodTpuError", "HorovodInternalError",
    "HostsUpdatedInterrupt", "HvtpuMismatchError", "HvtpuDivergenceError",
    "spmd", "comm", "core", "data",
    "mpi_enabled", "mpi_built", "mpi_threads_supported", "gloo_enabled",
    "gloo_built", "nccl_built", "ddl_built", "ccl_built", "cuda_built",
    "rocm_built", "xla_built", "ici_built",
]
