"""Indexed submit intake: an append-only journal with a persisted
cursor — the fleet's overload-safe front door.

The PR 14 spool protocol (one file per submission, ``listdir`` every
tick) is O(files-per-tick): a burst of a few thousand queued
submissions makes EVERY subsequent tick pay for the whole backlog.
The journal replaces that with an indexed intake:

- **Append-only journal** (``<fleet_dir>/journal.jsonl``): every
  ``submit`` / ``cancel`` is one JSON line appended under an exclusive
  ``flock``, so concurrent CLI clients serialize and records carry a
  strictly increasing ``seq``.  A crash mid-append leaves at most one
  torn tail line: readers detect it (no trailing newline at EOF) and
  skip it, and the NEXT writer repairs it under the lock — it
  terminates the partial line with a newline before appending, so the
  dead writer's fragment surfaces as one corrupt record instead of
  silently merging with (and destroying) the new submission.
- **Persisted cursor** (``<fleet_dir>/journal.cursor``): the arbiter
  remembers ``(offset, seq)`` of the last applied record, written
  crash-atomically through :func:`core.durable.atomic_write` AFTER the
  batch is applied and the admitted jobs are durable in ``state.json``
  (commit-last ordering: a crash anywhere before the commit replays
  the batch; committing first would instead LOSE acknowledged
  submissions whose records the advanced cursor skips).  Each tick
  seeks straight to the first new record and reads at most ``budget``
  lines: per-tick cost is O(new-entries), never O(queue).  A replayed
  batch is deduped by the arbiter (same live name + same spec →
  consume silently), which makes intake exactly-once at the job
  level.
- **Backpressure**: the cursor also publishes the arbiter's drain rate
  (``budget`` records per ``tick_s``).  When the un-applied backlog
  reaches ``HVTPU_FLEET_QUEUE_LIMIT``, :meth:`SubmitJournal.append_submit`
  refuses with :class:`QueueFullError` carrying a truthful
  ``retry_after_s`` — the seconds until the arbiter will have drained
  back below the limit at its published rate — instead of silently
  piling the queue higher.

Cancel ordering: because clients append through the same lock, a
cancel for a spooled-but-not-yet-intaken job always lands AFTER its
submit record, so the arbiter (which applies records in ``seq`` order
within one tick batch) tombstones the job before it can ever reach
PENDING-then-scheduled.

Thread safety: a :class:`SubmitJournal` instance is confined to its
owner (one CLI process, or the arbiter under its ``_lock``); cross-
process safety comes from ``flock`` + atomic cursor replace, not
instance locks.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional

from ..core import durable
from ..obs import metrics as obs_metrics

__all__ = ["SubmitJournal", "QueueFullError", "intake_budget",
           "queue_limit"]

_M_INTAKE_LAG = obs_metrics.gauge(
    "hvtpu_fleet_intake_lag",
    "Submit-journal records appended but not yet applied by the "
    "arbiter (backlog behind the persisted cursor).")

_JOURNAL = "journal.jsonl"
_CURSOR = "journal.cursor"


def intake_budget() -> int:
    """Max journal records the arbiter applies per tick."""
    try:
        n = int(os.environ.get("HVTPU_FLEET_INTAKE_BUDGET", "256")
                or 256)
    except ValueError:
        n = 256
    return max(1, n)


def queue_limit() -> int:
    """Un-applied journal backlog at which new submits are refused."""
    try:
        n = int(os.environ.get("HVTPU_FLEET_QUEUE_LIMIT", "4096")
                or 4096)
    except ValueError:
        n = 4096
    return max(1, n)


class QueueFullError(RuntimeError):
    """The journal backlog is at the queue limit; retry later.

    ``retry_after_s`` is truthful: backlog-over-limit divided by the
    arbiter's published drain rate (budget records per tick)."""

    def __init__(self, depth: int, limit: int, retry_after_s: float):
        self.depth = depth
        self.limit = limit
        self.retry_after_s = retry_after_s
        super().__init__(
            f"queue full: {depth} submissions queued (limit {limit}); "
            f"retry after {retry_after_s:.1f}s")


def _flock(f):
    try:
        import fcntl

        fcntl.flock(f.fileno(), fcntl.LOCK_EX)
    except (ImportError, OSError):
        pass  # single-writer platforms still get O_APPEND atomicity


class SubmitJournal:
    """One fleet dir's journal + cursor.  Writers append; the arbiter
    reads from the cursor and commits it after applying."""

    def __init__(self, fleet_dir: str):
        self.fleet_dir = fleet_dir
        self.path = os.path.join(fleet_dir, _JOURNAL)
        self.cursor_path = os.path.join(fleet_dir, _CURSOR)
        # reader-side tentative position (set by read_batch, persisted
        # by commit); owner-confined, see module docstring
        self._pending_offset: Optional[int] = None
        self._pending_seq: Optional[int] = None

    # -- cursor ----------------------------------------------------------
    def read_cursor(self) -> dict:
        try:
            with open(self.cursor_path) as f:
                cur = json.load(f)
            if not isinstance(cur, dict):
                raise ValueError("cursor is not an object")
            return cur
        except (OSError, ValueError):
            return {"offset": 0, "seq": 0}

    def commit(self, *, budget: Optional[int] = None,
               tick_s: Optional[float] = None) -> None:
        """Persist the post-batch cursor crash-atomically.  Also
        publishes the arbiter's drain rate so clients can compute a
        truthful retry-after."""
        if self._pending_offset is None:
            return
        cur = {"offset": self._pending_offset,
               "seq": self._pending_seq or 0}
        if budget is not None:
            cur["budget"] = int(budget)
        if tick_s is not None:
            cur["tick_s"] = float(tick_s)
        durable.atomic_write(
            self.cursor_path,
            json.dumps(cur, sort_keys=True).encode() + b"\n",
            detail="journal.cursor")
        self._pending_offset = None
        self._pending_seq = None

    # -- write side (CLI clients) ----------------------------------------
    def _tail_seq(self) -> int:
        """Seq of the last COMPLETE record (newline-terminated and
        parseable).  Scans backwards from EOF in 64KB windows,
        widening until a parseable line is found, so one oversized
        record longer than a window cannot hide the tail and restart
        seq numbering (duplicate seqs would break depth() and
        cursor-based dedup).  O(tail) in the common case."""
        try:
            with open(self.path, "rb") as f:
                f.seek(0, os.SEEK_END)
                pos = f.tell()
                buf = b""
                while pos > 0:
                    back = min(pos, 65536)
                    pos -= back
                    f.seek(pos)
                    buf = f.read(back) + buf
                    lines = buf.split(b"\n")
                    # unless the window reached BOF, the first element
                    # is a mid-line fragment — keep it for the next
                    # widening pass instead of parsing it
                    for line in reversed(lines[0 if pos == 0 else 1:]):
                        if not line.strip():
                            continue
                        try:
                            rec = json.loads(line)
                            if isinstance(rec, dict):
                                return int(rec.get("seq", 0) or 0)
                        except (ValueError, TypeError):
                            pass  # torn tail or corrupt line
        except OSError:
            return 0
        return 0

    def depth(self) -> int:
        """Appended-but-unapplied records (journal tail vs cursor)."""
        return max(0, self._tail_seq() - int(
            self.read_cursor().get("seq", 0) or 0))

    def _append(self, rec: dict) -> int:
        os.makedirs(self.fleet_dir, exist_ok=True)
        with open(self.path, "a+b") as f:
            _flock(f)  # released on close
            # repair a torn tail left by a CRASHED writer: terminate
            # the partial line so this record cannot merge into it
            # (the fragment then surfaces as one corrupt record)
            f.seek(0, os.SEEK_END)
            end = f.tell()
            if end:
                f.seek(end - 1)
                if f.read(1) != b"\n":
                    f.seek(0, os.SEEK_END)
                    f.write(b"\n")
            f.seek(0, os.SEEK_END)
            seq = self._tail_seq() + 1
            rec = dict(rec, seq=seq)
            f.write(json.dumps(rec, sort_keys=True).encode() + b"\n")
            f.flush()
            try:
                os.fsync(f.fileno())
            except OSError:
                pass
        return seq

    def _check_backpressure(self) -> None:
        limit = queue_limit()
        cur = self.read_cursor()
        depth = max(0, self._tail_seq() - int(cur.get("seq", 0) or 0))
        if depth < limit:
            return
        budget = max(1, int(cur.get("budget", intake_budget()) or 1))
        tick_s = float(cur.get("tick_s", 1.0) or 1.0)
        over = depth - limit + 1
        ticks = (over + budget - 1) // budget
        raise QueueFullError(depth, limit, ticks * tick_s)

    def append_submit(self, spec_dict: dict) -> int:
        """Append a submit record; raises :class:`QueueFullError` when
        the backlog is at the queue limit."""
        self._check_backpressure()
        return self._append({"op": "submit", "spec": spec_dict})

    def append_cancel(self, name: str) -> int:
        """Append a cancel record (never backpressured: cancels only
        shrink the fleet's work)."""
        return self._append({"op": "cancel", "name": name})

    # -- read side (the arbiter) -----------------------------------------
    def read_batch(self, budget: int) -> List[dict]:
        """Read up to ``budget`` complete records past the cursor.
        Remembers the post-batch position for :meth:`commit`; malformed
        newline-terminated lines are skipped as ``{"op": "corrupt"}``
        records so the caller can surface them, while a torn tail
        (no trailing newline) is left for the next tick."""
        cur = self.read_cursor()
        start = int(cur.get("offset", 0) or 0)
        offset = start
        seq = int(cur.get("seq", 0) or 0)
        out: List[dict] = []
        try:
            f = open(self.path, "rb")
        except OSError:
            self._pending_offset = None
            self._pending_seq = None
            return out
        with f:
            f.seek(offset)
            while len(out) < budget:
                line = f.readline()
                if not line or not line.endswith(b"\n"):
                    break  # EOF or torn tail: retry next tick
                offset += len(line)
                if not line.strip():
                    continue
                try:
                    rec = json.loads(line)
                    if not isinstance(rec, dict):
                        raise ValueError("record is not an object")
                except ValueError:
                    out.append({"op": "corrupt", "seq": seq + 1})
                    seq += 1
                    continue
                seq = int(rec.get("seq", seq + 1) or seq + 1)
                out.append(rec)
        if offset != start:
            self._pending_offset = offset
            self._pending_seq = seq
        else:
            # nothing consumed: leave no pending state so an idle
            # tick's commit() is a no-op instead of an fsync'd
            # rewrite of an unchanged cursor
            self._pending_offset = None
            self._pending_seq = None
        _M_INTAKE_LAG.set(max(0, self._tail_seq() - seq))
        return out
