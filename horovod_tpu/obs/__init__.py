from .autotune import Autotuner
from .timeline import Timeline, start_jax_profiler, stop_jax_profiler

__all__ = ["Autotuner", "Timeline", "start_jax_profiler", "stop_jax_profiler"]
