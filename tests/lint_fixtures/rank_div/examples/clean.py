"""rank-divergence fixture: rank-uniform patterns the pass must accept."""

import horovod_tpu as hvt


def uniform_collectives(grads):
    # Fine: every rank issues the same collectives unconditionally.
    grads = hvt.allreduce(grads)
    hvt.barrier()
    return grads


def rank_only_logging(loss):
    # Fine: rank-dependent branch contains no collective.
    loss = hvt.allreduce(loss)
    if hvt.rank() == 0:
        print("loss", loss)
    return loss


def helper_defined_under_rank_branch():
    # Fine: a def nested under a rank test is not *executed* there.
    if hvt.rank() == 0:
        def save_hook(grads):
            return hvt.allreduce(grads)
        return save_hook
    return None


def thread_join(worker):
    # Fine: Thread.join is not the collective join.
    if hvt.rank() == 0:
        worker.join()
